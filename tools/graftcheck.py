#!/usr/bin/env python
"""graftcheck: static audit of every bundled config without touching a TPU.

Abstractly traces the train / eval / decode steps of each config on CPU
(ShapeDtypeStruct parameters — no FLOPs, no XLA compile) and runs graph rule
passes over the jaxprs (collective census vs goldens, dtype promotion,
donation, sharding specs, constant bloat), plus AST lint of the source tree
(axis-literal registry, .x escape ratchet, traced RNG/time, PartitionSpec
axes, host-sync ratchet, obs-in-trace ratchet).  See docs/static_analysis.md
for the rule catalogue, golden update workflow, and suppression syntax.

Usage:
  python tools/graftcheck.py --all-configs            # the CI gate
  python tools/graftcheck.py --config configs/x.json  # one config
  python tools/graftcheck.py --ast-only               # source lint only
  python tools/graftcheck.py --all-configs --update-goldens
Exit code: 1 if any ERROR finding (or any WARNING under --strict), else 0.
"""
import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU + 8 virtual devices BEFORE jax import: the census goldens are defined
# on the same virtual mesh the test suite uses (tests/conftest.py)
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--all-configs", action="store_true",
                   help="audit every configs/*.json plus the AST rules")
    p.add_argument("--config", action="append", default=[],
                   help="audit one config JSON (repeatable)")
    p.add_argument("--ast-only", action="store_true",
                   help="run only the source-tree AST rules")
    p.add_argument("--graph-only", action="store_true",
                   help="skip the AST rules")
    p.add_argument("--steps", default="train,decode",
                   help="comma list of steps to trace (train,eval,decode)")
    p.add_argument("--rules", default=None,
                   help="comma list restricting which rules run")
    p.add_argument("--update-goldens", action="store_true",
                   help="re-record census + ratchet goldens from this tree")
    p.add_argument("--strict", action="store_true",
                   help="warnings also fail the run")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--list-rules", action="store_true")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    from homebrewnlp_tpu import analysis
    if args.list_rules:
        for r in analysis.GRAPH_RULES:
            print(f"graph  {r}")
        for r in analysis.AST_RULES:
            print(f"ast    {r}")
        return 0
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = sorted(set(rules) - set(analysis.ALL_RULES))
        if unknown:
            print(f"unknown rule(s) {', '.join(unknown)}; valid: "
                  f"{', '.join(analysis.ALL_RULES)}", file=sys.stderr)
            return 2
    steps = tuple(s.strip() for s in args.steps.split(",") if s.strip())
    unknown_steps = sorted(set(steps) - {"train", "eval", "decode"})
    if unknown_steps:
        print(f"unknown step(s) {', '.join(unknown_steps)}; valid: "
              f"train, eval, decode", file=sys.stderr)
        return 2
    config_paths = list(args.config)
    if args.all_configs:
        config_paths += sorted(glob.glob(os.path.join(REPO, "configs", "*.json")))
    if not config_paths and not args.ast_only:
        print("nothing to do: pass --all-configs, --config, or --ast-only",
              file=sys.stderr)
        return 2

    findings = []
    t0 = time.time()
    if not args.ast_only:
        import jax  # noqa: F401  (env is pinned above)
        from homebrewnlp_tpu.config import Config
        for path in config_paths:
            name = os.path.splitext(os.path.basename(path))[0]
            with open(path) as f:
                raw = json.load(f)
            raw.pop("_comment", None)
            t1 = time.time()
            try:
                cfg = Config(raw)
            except Exception as e:
                findings.append(analysis.Finding(
                    "config", "error", path,
                    f"config failed to load: {type(e).__name__}: {e}"))
                continue
            traces = analysis.trace_config(cfg, name, steps=steps)
            findings.extend(analysis.run_graph_rules(
                traces, update_goldens=args.update_goldens, rules=rules))
            if not args.as_json:
                print(f"[graftcheck] {name}: "
                      f"{', '.join(sorted(traces.steps)) or 'no steps'} "
                      f"({time.time() - t1:.1f}s)", file=sys.stderr)
    if not args.graph_only:
        # the AST ratchet golden is tree-wide: only re-record it on a
        # tree-wide run (--all-configs / --ast-only), never as a side effect
        # of updating one config's census budget
        ast_update = args.update_goldens and (args.all_configs or args.ast_only)
        findings.extend(analysis.run_ast_rules(
            REPO, update_goldens=ast_update, rules=rules))

    print(analysis.render_report(findings, as_json=args.as_json))
    if not args.as_json:
        print(f"[graftcheck] total {time.time() - t0:.1f}s", file=sys.stderr)
    worst = analysis.worst_severity(findings)
    if worst == "error" or (args.strict and worst == "warning"):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
