#!/usr/bin/env python
"""graftcheck: static audit of every bundled config without touching a TPU.

Abstractly traces the train / eval / decode steps of each config on CPU
(ShapeDtypeStruct parameters — no FLOPs, no XLA compile) and runs graph rule
passes over the jaxprs (collective census vs goldens, dtype promotion,
donation, sharding specs, constant bloat), plus AST lint of the source tree
(axis-literal registry, .x escape ratchet, traced RNG/time, PartitionSpec
axes, host-sync ratchet, obs-in-trace ratchet).  See docs/static_analysis.md
for the rule catalogue, golden update workflow, and suppression syntax.

Usage:
  python tools/graftcheck.py --all-configs            # the CI gate
  python tools/graftcheck.py --config configs/x.json  # one config
  python tools/graftcheck.py --ast-only               # source lint only
  python tools/graftcheck.py --all-configs --update-goldens
Exit code: 1 if any ERROR finding (or any WARNING under --strict), 0 on
warnings-only/clean runs, 2 on usage errors; a findings-by-severity summary
line always prints to stderr.
"""
import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU + 8 virtual devices BEFORE jax import: the census goldens are defined
# on the same virtual mesh the test suite uses (tests/conftest.py)
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--all-configs", action="store_true",
                   help="audit every configs/*.json plus the AST rules")
    p.add_argument("--config", action="append", default=[],
                   help="audit one config JSON (repeatable)")
    p.add_argument("--ast-only", action="store_true",
                   help="run only the source-tree AST rules")
    p.add_argument("--graph-only", action="store_true",
                   help="skip the AST rules")
    p.add_argument("--steps", default="train,decode",
                   help="comma list of steps to trace "
                        "(train,eval,decode,prefill)")
    p.add_argument("--rules", default=None,
                   help="comma list restricting which rules run")
    p.add_argument("--update-goldens", action="store_true",
                   help="re-record census + ratchet goldens from this tree")
    p.add_argument("--strict", action="store_true",
                   help="warnings also fail the run")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--list-rules", action="store_true")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    from homebrewnlp_tpu import analysis
    if args.list_rules:
        for r in analysis.GRAPH_RULES:
            print(f"graph  {r}")
        for r in analysis.AST_RULES:
            print(f"ast    {r}")
        for r in analysis.TREE_RULES:
            print(f"tree   {r}")
        return 0
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = sorted(set(rules) - set(analysis.ALL_RULES))
        if unknown:
            print(f"unknown rule(s) {', '.join(unknown)}; valid: "
                  f"{', '.join(analysis.ALL_RULES)}", file=sys.stderr)
            return 2
    if rules is not None and "golden-coverage" in rules \
            and not args.all_configs:
        # tree-wide rule: without --all-configs it would silently not run
        # and report a clean exit — refuse instead of false-passing
        print("golden-coverage is a tree-wide rule; it requires "
              "--all-configs", file=sys.stderr)
        return 2
    steps = tuple(s.strip() for s in args.steps.split(",") if s.strip())
    unknown_steps = sorted(set(steps) - {"train", "eval", "decode",
                                         "prefill", "prefill_chunk"})
    if unknown_steps:
        print(f"unknown step(s) {', '.join(unknown_steps)}; valid: "
              f"train, eval, decode, prefill", file=sys.stderr)
        return 2
    config_paths = list(args.config)
    if args.all_configs:
        config_paths += sorted(glob.glob(os.path.join(REPO, "configs", "*.json")))
    if not config_paths and not args.ast_only:
        print("nothing to do: pass --all-configs, --config, or --ast-only",
              file=sys.stderr)
        return 2

    findings = []
    t0 = time.time()
    if not args.ast_only:
        import jax  # noqa: F401  (env is pinned above)
        from homebrewnlp_tpu.config import Config
        for path in config_paths:
            name = os.path.splitext(os.path.basename(path))[0]
            with open(path) as f:
                raw = json.load(f)
            raw.pop("_comment", None)
            t1 = time.time()
            try:
                cfg = Config(raw)
            except Exception as e:
                findings.append(analysis.Finding(
                    "config", "error", path,
                    f"config failed to load: {type(e).__name__}: {e}"))
                continue
            traces = analysis.trace_config(cfg, name, steps=steps)
            findings.extend(analysis.run_graph_rules(
                traces, update_goldens=args.update_goldens, rules=rules))
            if not args.as_json:
                print(f"[graftcheck] {name}: "
                      f"{', '.join(sorted(traces.steps)) or 'no steps'} "
                      f"({time.time() - t1:.1f}s)", file=sys.stderr)
    if args.all_configs and (rules is None or "golden-coverage" in rules):
        # tree-wide coverage gate: every bundled config must carry both a
        # census and a resources golden (a new config silently skipping
        # its budgets was satellite bug #1), and no golden may outlive its
        # config.  Needs no tracing, so it runs under --ast-only too; on
        # graph runs it runs AFTER --update-goldens wrote files.
        names = [os.path.splitext(os.path.basename(p))[0]
                 for p in sorted(glob.glob(
                     os.path.join(REPO, "configs", "*.json")))]
        findings.extend(analysis.check_golden_coverage(names))
    if not args.graph_only:
        # the AST ratchet golden is tree-wide: only re-record it on a
        # tree-wide run (--all-configs / --ast-only), never as a side effect
        # of updating one config's census budget
        ast_update = args.update_goldens and (args.all_configs or args.ast_only)
        findings.extend(analysis.run_ast_rules(
            REPO, update_goldens=ast_update, rules=rules))

    print(analysis.render_report(findings, as_json=args.as_json))
    if not args.as_json:
        print(f"[graftcheck] total {time.time() - t0:.1f}s", file=sys.stderr)
    # exit status by explicit severity COUNTS, not worst_severity string
    # compare: errors -> 1, warnings-only -> 0 (1 only under --strict),
    # clean/info -> 0.  The findings-by-severity summary prints to stderr in
    # every mode (the JSON report on stdout stays machine-parseable).
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = sum(1 for f in findings if f.severity == "warning")
    n_info = len(findings) - n_err - n_warn
    rc = 1 if n_err or (args.strict and n_warn) else 0
    print(f"[graftcheck] findings: {n_err} error(s), {n_warn} warning(s), "
          f"{n_info} info -> exit {rc}"
          + (" (--strict promotes warnings)" if args.strict and not n_err
             and n_warn else ""), file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
