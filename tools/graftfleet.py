#!/usr/bin/env python
"""graftfleet CLI: fleet-level observability over a shared fleet dir.

Renders the cross-rank view of a multi-host run from the ``<fleet_dir>/obs``
postings (docs/observability.md "Fleet observability"): which ranks are
reporting, the federated metrics summary, per-step dispatch skew, the EMA
straggler score per rank, and the barrier-wait decomposition — plus the
merged multi-lane Chrome trace with clock offsets estimated from
``dist/barrier`` span pairs.

Sources:

- a fleet directory (the ``--fleet-dir`` the per-host supervisors share);
- two committed ``MULTICHIP_r*.json`` rounds via ``--compare`` — the
  ``fleet_obs`` row (emitted by ``__graft_entry__.py``'s two-process drill)
  diffs round over round the same way ``graftprof --compare`` diffs
  profile captures.

Examples::

    python tools/graftfleet.py /shared/fleet
    python tools/graftfleet.py /shared/fleet --check
    python tools/graftfleet.py /shared/fleet --merged-trace merged.json
    python tools/graftfleet.py --compare MULTICHIP_r05.json MULTICHIP_r06.json

Exit codes: 0 ok; 1 a ``--check`` gate failed; 2 usage / unreadable source.

Like tools/supervise.py, this never imports the ``homebrewnlp_tpu`` package
(which pulls jax): fleet visibility must work on a host whose accelerator
toolchain is exactly what broke.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import typing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_light(name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


fleet = _load_light("hbnlp_obs_fleet_cli", "homebrewnlp_tpu/obs/fleet.py")

FLEET_OBS_MARKER = "fleet_obs: "


def load_fleet_obs_row(path: str) -> dict:
    """The ``fleet_obs`` row of a committed MULTICHIP round: the JSON
    payload after the ``fleet_obs: `` marker in the round's ``tail``."""
    with open(path) as f:
        doc = json.load(f)
    tail = doc.get("tail", "") if isinstance(doc, dict) else ""
    for line in tail.splitlines():
        if FLEET_OBS_MARKER in line:
            return json.loads(line.split(FLEET_OBS_MARKER, 1)[1])
    raise ValueError(f"{path}: no '{FLEET_OBS_MARKER}' row in its tail — "
                     "this round predates the fleet_obs drill")


def fleet_summary(fleet_dir: str) -> typing.Tuple[dict, str, dict]:
    """One read of the fleet dir for every output path: the summary doc,
    the federated text, and the raw traces (report / --check /
    --merged-trace / --federated all reuse them — a network-mounted fleet
    dir must not be re-parsed per flag, nor may two reads disagree)."""
    posts = fleet.read_step_posts(fleet_dir)
    report = fleet.straggler_report(posts)
    errors: typing.List[str] = []
    federation = fleet.FleetFederation(fleet_dir)
    texts = federation.rank_texts()
    # same composition as FleetFederation.render(): the --federated dump
    # must carry the hbnlp_fleet_* attribution gauges the live supervisor
    # endpoint serves, not a stripped-down exposition
    federated = fleet.federate(texts, errors=errors) \
        + federation.fleet_series(
            report, n_reporting=len(set(texts) | set(posts)))
    traces = fleet.read_traces(fleet_dir)
    offsets = fleet.estimate_offsets(traces)
    summary = {"fleet_dir": os.path.abspath(fleet_dir),
               "metrics_ranks": sorted(texts),
               "federated_series": sum(
                   1 for line in federated.splitlines()
                   if line and not line.startswith("#")),
               "merge_errors": errors,
               "trace_ranks": sorted(traces),
               "clock_offsets": offsets,
               "straggler": report}
    return summary, federated, traces


def render_report(s: dict) -> str:
    rep = s["straggler"]
    lines = [f"fleet dir: {s['fleet_dir']}",
             f"metrics snapshots: ranks {s['metrics_ranks']} "
             f"({s['federated_series']} federated series"
             + (f", {len(s['merge_errors'])} merge error(s)"
                if s["merge_errors"] else "") + ")",
             f"traces: ranks {s['trace_ranks']}"]
    off = s["clock_offsets"]
    if off["n_pairs"] and off["bound_s"] is not None:
        pretty = {r: f"{v * 1e3:+.3f}ms"
                  for r, v in off["offsets_s"].items()}
        lines.append(f"clock offsets vs rank {off['base_rank']}: {pretty} "
                     f"(bound {off['bound_s'] * 1e3:.3f}ms over "
                     f"{off['n_pairs']} barrier pair(s))")
    elif off["n_pairs"]:
        lines.append(f"clock offsets: rank(s) "
                     f"{off['ranks_without_pairs']} recorded no matched "
                     "dist/barrier spans — their lanes align on raw wall "
                     "clocks, so NO alignment bound holds")
    elif s["trace_ranks"]:
        lines.append("clock offsets: no matched dist/barrier span pairs — "
                     "lanes align on raw wall clocks (no bound)")
    if rep["ranks"]:
        lines.append("")
        lines.append(f"{'rank':>4} {'steps':>6} {'last':>6} "
                     f"{'step ms':>9} {'straggle ms':>12} "
                     f"{'barrier-wait s':>15}")
        for r, row in sorted(rep["ranks"].items(), key=lambda kv:
                             int(kv[0])):
            mean_ms = ("-" if row["mean_step_s"] is None
                       else f"{row['mean_step_s'] * 1e3:.3f}")
            lines.append(f"{r:>4} {row['steps']:>6} {row['last_step']:>6} "
                         f"{mean_ms:>9} {row['straggler_score_ms']:>12.3f} "
                         f"{row['barrier_wait_s']:>15.6f}")
    skew = rep.get("skew_ms")
    if skew:
        lines.append("")
        lines.append(
            f"step skew ms over {rep['n_common_steps']} common step(s): "
            f"mean {skew['mean']:.3f}  p95 {skew['p95']:.3f}  "
            f"max {skew['max']:.3f}  last {skew['last']:.3f}")
        lines.append(
            f"straggler rank: {rep['straggler_rank']}  "
            f"fleet barrier-wait total: {rep['barrier_wait_total_s']:.6f}s")
    for e in s["merge_errors"]:
        lines.append(f"MERGE ERROR: {e}")
    return "\n".join(lines)


def run_check(s: dict) -> typing.List[str]:
    """The CI gate: a fleet dir that claims to host a fleet must actually
    show one — >= 2 ranks' metrics, a populated skew report, traces that
    merge, and no federation merge errors.

    Alignment is gated only where it is CLAIMED: a fleet with no
    ``dist/barrier`` spans at all (supervision-only drills never barrier)
    merges on raw wall clocks, says so in the report, and passes — but a
    MIXED fleet (some lanes with pairs, some without) fails, because the
    merged file would silently carry one unaligned lane next to aligned
    ones."""
    failed = []
    if len(s["metrics_ranks"]) < 2:
        failed.append(f"only {len(s['metrics_ranks'])} rank(s) posted a "
                      "metrics snapshot (need >= 2)")
    if s["merge_errors"]:
        failed.append(f"{len(s['merge_errors'])} federation merge error(s)")
    if s["straggler"]["n_common_steps"] < 1:
        failed.append("skew report empty: no step dispatched by every "
                      "posting rank")
    if len(s["trace_ranks"]) >= 2:
        off = s["clock_offsets"]
        spanless = sorted(set(s["trace_ranks"])
                          - set(off.get("ranks_with_spans", [])))
        if off.get("ranks_with_spans") and spanless:
            failed.append(
                f"rank(s) {spanless} recorded no dist/barrier spans while "
                f"rank(s) {off['ranks_with_spans']} did — their merged "
                "lanes are NOT aligned")
        elif off["n_pairs"] and off["ranks_without_pairs"]:
            failed.append(
                f"rank(s) {off['ranks_without_pairs']} recorded no "
                "matched dist/barrier spans while others did — their "
                "merged lanes are NOT aligned")
        elif off["n_pairs"] and off["bound_s"] > 1.0:
            failed.append(f"clock-offset residual {off['bound_s']:.3f}s "
                          "exceeds 1s — barrier ends disagree; traces "
                          "cannot be trusted as aligned")
    return failed


def render_compare(a: dict, b: dict) -> str:
    """Round-over-round fleet drift, graftprof --compare shape: a -> b
    (+delta) for skew, barrier-wait, and per-rank step time."""
    lines = []

    def _num(doc, *path):
        cur: typing.Any = doc
        for k in path:
            if not isinstance(cur, dict) or k not in cur:
                return None
            cur = cur[k]
        return float(cur) if isinstance(cur, (int, float)) else None

    for label, path in (("skew mean ms", ("skew_ms", "mean")),
                        ("skew p95 ms", ("skew_ms", "p95")),
                        ("skew max ms", ("skew_ms", "max")),
                        ("barrier-wait total s",
                         ("barrier_wait_total_s",))):
        va, vb = _num(a, *path), _num(b, *path)
        if va is None or vb is None:
            lines.append(f"{label}: (absent in one round)")
        else:
            lines.append(f"{label}: {va:.3f} -> {vb:.3f} ({vb - va:+.3f})")
    sa, sb = _num(a, "straggler_rank"), _num(b, "straggler_rank")
    lines.append(f"straggler rank: {None if sa is None else int(sa)} -> "
                 f"{None if sb is None else int(sb)}")
    ranks = sorted(set(a.get("ranks", {})) | set(b.get("ranks", {})),
                   key=int)
    if ranks:
        lines.append("")
        lines.append(f"{'rank':>4} {'a step ms':>10} {'b step ms':>10} "
                     f"{'delta':>9} {'a wait s':>9} {'b wait s':>9}")
        for r in ranks:
            ra = a.get("ranks", {}).get(r, {})
            rb = b.get("ranks", {}).get(r, {})
            ma = ra.get("mean_step_s")
            mb = rb.get("mean_step_s")
            d = ("-" if ma is None or mb is None
                 else f"{(mb - ma) * 1e3:+.3f}")
            lines.append(
                f"{r:>4} "
                f"{'-' if ma is None else format(ma * 1e3, '.3f'):>10} "
                f"{'-' if mb is None else format(mb * 1e3, '.3f'):>10} "
                f"{d:>9} "
                f"{ra.get('barrier_wait_s', 0.0):>9.4f} "
                f"{rb.get('barrier_wait_s', 0.0):>9.4f}")
    return "\n".join(lines)


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="fleet observability over a shared fleet dir")
    p.add_argument("fleet_dir", nargs="?", default="",
                   help="the --fleet-dir the per-host supervisors share")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless the dir shows a healthy fleet "
                        "(>= 2 ranks, populated skew report, merged "
                        "traces, no federation errors)")
    p.add_argument("--merged-trace", default="",
                   help="write the merged multi-lane Chrome trace here")
    p.add_argument("--federated", default="",
                   help="write the federated Prometheus text here")
    p.add_argument("--compare", nargs=2, metavar=("A", "B"),
                   help="two MULTICHIP_r*.json rounds: print the "
                        "fleet_obs row drift (b - a)")
    args = p.parse_args(argv)

    if args.compare:
        try:
            a, b = (load_fleet_obs_row(x) for x in args.compare)
        except (OSError, ValueError) as e:
            print(f"graftfleet: {e}", file=sys.stderr)
            return 2
        print(json.dumps({"a": a, "b": b}, indent=1, sort_keys=True)
              if args.as_json else render_compare(a, b))
        return 0

    if not args.fleet_dir:
        p.error("fleet_dir required (or use --compare A B)")
    if not os.path.isdir(args.fleet_dir):
        print(f"graftfleet: {args.fleet_dir} is not a directory",
              file=sys.stderr)
        return 2
    s, federated, traces = fleet_summary(args.fleet_dir)
    if args.merged_trace:
        merged = fleet.merge_traces(traces, s["clock_offsets"])
        with open(args.merged_trace, "w") as f:
            json.dump(merged, f)
        print(f"merged trace ({len(traces)} lane(s)) -> "
              f"{args.merged_trace}", file=sys.stderr)
    if args.federated:
        with open(args.federated, "w") as f:
            f.write(federated)
        print(f"federated metrics -> {args.federated}", file=sys.stderr)
    print(json.dumps(s, indent=1, sort_keys=True) if args.as_json
          else render_report(s))
    if args.check:
        failed = run_check(s)
        for msg in failed:
            print(f"graftfleet: CHECK FAILED: {msg}", file=sys.stderr)
        return 1 if failed else 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
