"""Hyperparameter grid-sweep launcher.

Port of /root/reference/scripts/run_experiments.py: meshgrid over list-valued
config entries (:62-75), one JSON config + run name per grid point (:78-93),
then launch each run (:99-125).  The reference hardcodes preemptible-TPU
creation through ``gcloud compute tpus create`` inside ``screen``; here the
launch command is a template (``--launch-cmd``) so the same sweep runs
locally, under tmux, or against any cloud CLI — the gcloud/screen recipe is
the documented default template.

Usage:
  python tools/run_experiments.py --base configs/32ctx_mixer.json \
      --grid learning_rate=0.01,0.003 --grid depth=8,16 \
      --out-dir sweeps/lr_depth [--execute] \
      [--launch-cmd 'python main.py --model {config} --run_mode train']
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import subprocess

GCLOUD_TEMPLATE = (
    "gcloud compute tpus create {name} --zone europe-west4-a --range {cidr} "
    "--accelerator-type v3-8 --version tpu-vm-tf-2.x --preemptible && "
    "python3 main.py --model {config} --tpu {name} --run_mode train; "
    "gcloud compute tpus delete {name} --zone europe-west4-a --quiet"
)


def parse_value(v: str):
    try:
        return json.loads(v)
    except json.JSONDecodeError:
        return v


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--base", required=True, help="base JSON config")
    ap.add_argument("--grid", action="append", default=[],
                    help="key=v1,v2,... (repeatable); meshgrid over all")
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--launch-cmd",
                    default="python3 main.py --model {config} --run_mode train",
                    help="command per run; {config}/{name}/{cidr} substituted."
                         f" gcloud recipe: {GCLOUD_TEMPLATE!r}")
    ap.add_argument("--cidr-base", default="10.48", help="first two CIDR "
                    "octets for TPU ranges (reference :78-93)")
    ap.add_argument("--execute", action="store_true",
                    help="actually launch (default: just write configs)")
    args = ap.parse_args()

    with open(args.base) as f:
        base = json.load(f)
    keys, value_lists = [], []
    for g in args.grid:
        key, vals = g.split("=", 1)
        keys.append(key)
        value_lists.append([parse_value(v) for v in vals.split(",")])

    os.makedirs(args.out_dir, exist_ok=True)
    procs = []
    for run_idx, combo in enumerate(itertools.product(*value_lists)):
        cfg = dict(base)
        name_parts = []
        for k, v in zip(keys, combo):
            cfg[k] = v
            name_parts.append(f"{k}={v}")
        name = "-".join(name_parts).replace("/", "_") or f"run{run_idx}"
        cfg["model_path"] = os.path.join(args.out_dir, name)
        cfg_path = os.path.join(args.out_dir, f"{name}.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f, indent=2)
        cidr = f"{args.cidr_base}.{run_idx}.0/29"
        cmd = args.launch_cmd.format(config=cfg_path, name=f"sweep-{run_idx}",
                                     cidr=cidr)
        print(("LAUNCH " if args.execute else "would launch ") + cmd)
        if args.execute:
            procs.append(subprocess.Popen(cmd, shell=True))
    for p in procs:
        p.wait()


if __name__ == "__main__":
    main()
