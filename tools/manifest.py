"""Video-manifest work-list sharding: chunk + split.

Port of the reference's fleet tooling tail (/root/reference/scripts/
chunk_video_json.py:1-86, split_video_json.py:1-89): a manifest is a JSON
``{"id": [...], "duration": [...]}`` of video ids and durations (seconds);
``chunk`` groups shuffled videos into chunks of at least ``--min-duration``
seconds; ``split`` balances manifests (or chunks) across N workers by total
duration (greedy lightest-bucket, the same ``split_equal`` the downloader
uses for its per-worker balance, video2tfrecord.py).

Usage:
  python tools/manifest.py chunk  MANIFEST_OR_DIR --min-duration 3600 \
      [--prefix out/] [--seed 0]
  python tools/manifest.py split  MANIFEST_OR_DIR --splits 8 [--prefix out/]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import typing

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.video2tfrecord import split_equal  # noqa: E402


def load_manifests(path: str) -> typing.Tuple[list, list]:
    """One file or every file of a directory -> concatenated (ids, durations).
    Entries may be scalars (one video) or lists (a chunk)."""
    paths = ([os.path.join(path, p) for p in sorted(os.listdir(path))]
             if os.path.isdir(path) else [path])
    ids: list = []
    durations: list = []
    for p in paths:
        with open(p) as f:
            data = json.load(f)
        ids.extend(data["id"])
        durations.extend(data["duration"])
    if len(ids) != len(durations):
        raise ValueError(f"id/duration length mismatch in {path}")
    return ids, durations


def chunk(ids: list, durations: list, min_duration: float,
          seed: typing.Optional[int] = None
          ) -> typing.Tuple[list, list]:
    """Shuffle, then greedily close a chunk once it reaches min_duration
    (reference chunk_video_json.py:44-65)."""
    videos = list(zip(ids, durations))
    rng = random.Random(seed)
    rng.shuffle(videos)
    chunk_ids: list = []
    chunk_durations: list = []
    cur_i: list = []
    cur_d: list = []
    total = 0.0
    for i, d in videos:
        cur_i.append(i)
        cur_d.append(d)
        total += d
        if total >= min_duration:
            chunk_ids.append(cur_i)
            chunk_durations.append(cur_d)
            cur_i, cur_d, total = [], [], 0.0
    if cur_i:  # trailing partial chunk (reference keeps it too)
        chunk_ids.append(cur_i)
        chunk_durations.append(cur_d)
    return chunk_ids, chunk_durations


def split(ids: list, durations: list, n: int) -> typing.List[dict]:
    """Balance entries over n workers by total duration."""
    totals = [sum(d) if isinstance(d, list) else float(d) for d in durations]
    buckets = split_equal(totals, n)
    return [{"id": [ids[i] for i in b],
             "duration": [durations[i] for i in b]} for b in buckets]


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> None:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("chunk")
    c.add_argument("load_path")
    c.add_argument("--min-duration", type=float, required=True)
    c.add_argument("--prefix", default="")
    c.add_argument("--seed", type=int, default=None)
    s = sub.add_parser("split")
    s.add_argument("load_path")
    s.add_argument("--splits", type=int, required=True)
    s.add_argument("--prefix", default="")
    args = ap.parse_args(argv)

    ids, durations = load_manifests(args.load_path)
    if args.cmd == "chunk":
        cids, cdur = chunk(ids, durations, args.min_duration, args.seed)
        for i, d in enumerate(cdur):
            print(f"chunk: {i} videos: {len(d)} duration: {sum(d)}")
        print(f"total num of videos: {sum(len(d) for d in cdur)} "
              f"total video duration: {sum(sum(d) for d in cdur)}")
        out = f"{args.prefix}work_chunks.json"
        with open(out, "w") as f:
            json.dump({"id": cids, "duration": cdur}, f)
        print(out)
        return
    parts = split(ids, durations, args.splits)
    for i, part in enumerate(parts):
        total = sum(sum(d) if isinstance(d, list) else d
                    for d in part["duration"])
        print(f"split: {i} entries: {len(part['id'])} duration: {total}")
        out = f"{args.prefix}work_split_{i}.json"
        with open(out, "w") as f:
            json.dump(part, f)
        print(out)


if __name__ == "__main__":
    main()
