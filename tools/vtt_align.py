"""Word-level subtitle timing + BPE token alignment.

Covers the subtitle half of the reference's YouTube caption pipeline
(/root/reference/scripts/video2tfrecord.py:186-360, ``decode_vtt`` +
``bpe_with_word_split``): YouTube auto-caption VTTs carry per-word
"karaoke" timing via inline ``<HH:MM:SS.mmm><c> word</c>`` tags and repeat
each caption line in a rolling two-line window, while plain VTT/SRT cues
only carry per-cue spans, so word times are interpolated across the cue.
The scrape/proxy downloader itself stays a documented template
(tools/video2tfrecord.py module docstring) — this image has zero egress —
but the parsing is pure and offline-testable.

Design differences from the reference (intentional, not drift):
- karaoke extraction is regex-anchored on the ``<t><c>...</c>`` pair
  instead of fixed ``[-12:]`` string slicing, so HTML tags, missing
  trailing tags, and >99h timestamps don't corrupt words;
- rolling-caption repeats are dropped by comparing a line's untagged lead
  text against the previously emitted word (the reference concatenates the
  repeat into the neighboring word);
- token alignment walks CHARACTER OFFSETS of the exact decoded pieces
  instead of substring matching, so repeated words and subword overlaps
  cannot desynchronize the assignment.
"""
from __future__ import annotations

import re
import typing

TimedWord = typing.NamedTuple("TimedWord", (("time", float), ("word", str)))

_CUE_RE = re.compile(
    r"(\d+):(\d\d):(\d\d)[.,](\d+)\s*-->\s*(\d+):(\d\d):(\d\d)[.,](\d+)")
_KARAOKE_RE = re.compile(r"<(\d+):(\d\d):(\d\d)\.(\d+)><c>(.*?)</c>")
_INLINE_TS_RE = re.compile(r"<\d+:\d\d:\d\d\.\d+>")
_TAG_RE = re.compile(r"<[^>]*>")


def _seconds(h: str, m: str, s: str, frac: str) -> float:
    return int(h) * 3600 + int(m) * 60 + int(s) + float(f"0.{frac}")


def parse_timed_words(content: str) -> typing.List[TimedWord]:
    """VTT/SRT text -> one ``TimedWord`` per word, times in seconds.

    Karaoke VTTs (``<c>`` present) yield true per-word times; plain cue
    files interpolate the cue span evenly over its words (the reference's
    ``time_snip`` rule)."""
    if "<c>" in content:
        return _parse_karaoke(content)
    return _parse_cues(content)


def _parse_karaoke(content: str) -> typing.List[TimedWord]:
    out: typing.List[TimedWord] = []
    cue_start: typing.Optional[float] = None
    cue_end: typing.Optional[float] = None
    prev_cue_end: typing.Optional[float] = None
    emitted_from_prev_cue = False  # out[-1] came from the preceding cue
    cur_emitted = False
    for raw in content.split("\n"):
        m = _CUE_RE.search(raw)
        if m:
            prev_cue_end = cue_end
            emitted_from_prev_cue = cur_emitted
            cur_emitted = False
            cue_start = _seconds(*m.groups()[:4])
            cue_end = _seconds(*m.groups()[4:])
            continue
        if "<c>" not in raw:
            # rolling-window repeat of the previous line (or header/blank)
            continue
        # lead text before the first inline timestamp: the cue's first word
        # when fresh, or a rolling repeat of the last emitted word (YouTube's
        # tagged line restates the previous line's final word as its lead).
        # The discriminator is equality with the previous word PLUS cue
        # adjacency: the restate only happens when the previous cue emitted
        # that word and this cue's window abuts it in time.  A genuine
        # duplicate after a silence gap ("yeah <pause> yeah right") is
        # therefore kept; only an immediate duplicate across a CONTIGUOUS
        # boundary still collapses — preferred over the rolling repeat
        # duplicating a word at every cue boundary (the reference instead
        # concatenates repeats into the neighboring word,
        # video2tfrecord.py:218-241, which double-counts them)
        lead = _TAG_RE.sub("", _INLINE_TS_RE.split(raw, 1)[0]).strip()
        contiguous = (prev_cue_end is not None and cue_start is not None
                      and abs(cue_start - prev_cue_end) <= 0.101)
        rolling = (out and out[-1].word == lead
                   and emitted_from_prev_cue and contiguous)
        if lead and not rolling:
            out.append(TimedWord(cue_start if cue_start is not None else 0.0,
                                 lead))
            cur_emitted = True
        for h, mi, s, frac, word in _KARAOKE_RE.findall(raw):
            word = _TAG_RE.sub("", word).strip()
            if word:
                out.append(TimedWord(_seconds(h, mi, s, frac), word))
                cur_emitted = True
    return out


def _parse_cues(content: str) -> typing.List[TimedWord]:
    out: typing.List[TimedWord] = []
    span: typing.Optional[typing.Tuple[float, float]] = None
    lines: typing.List[str] = []

    def flush():
        if span is None or not lines:
            return
        words = " ".join(lines).split()
        if not words:
            return
        start, end = span
        step = (end - start) / len(words)
        out.extend(TimedWord(start + i * step, w)
                   for i, w in enumerate(words))

    for raw in content.split("\n"):
        m = _CUE_RE.search(raw)
        if m:
            flush()
            span = (_seconds(*m.groups()[:4]), _seconds(*m.groups()[4:]))
            lines = []
            continue
        text = _TAG_RE.sub("", raw).strip()
        if (text and span is not None and not text.isdigit()
                and "WEBVTT" not in text):
            lines.append(text)
    flush()
    return out


def align_tokens(encode: typing.Callable[[str], typing.Sequence[int]],
                 words: typing.Sequence[str],
                 token_bytes: typing.Optional[
                     typing.Callable[[int], int]] = None
                 ) -> typing.List[typing.List[int]]:
    """Tokenize the words' joined text ONCE and split the token stream back
    into one token list per word (the reference's ``bpe_with_word_split``).

    Tokenizing per word would produce different tokens than tokenizing the
    running text (BPE merges across word boundaries with the leading-space
    convention), so the stream is cut by BYTE offset: each token goes to the
    word whose UTF-8 span contains the token's first byte.  Byte space, not
    character space, because a token covering part of a multi-byte character
    has no well-defined character length (decoding it yields a replacement
    char and desynchronizes the walk on any non-ASCII caption).

    ``token_bytes(tok)`` -> decoded byte length of one token; the default is
    raw byte-level tokens (1 byte per id, the production tokenizer here).
    For a BPE vocabulary pass the piece's byte length from the merges
    table."""
    text = "".join(" " + w for w in words)
    tokens = list(encode(text))
    if token_bytes is None:
        token_bytes = lambda _tok: 1  # noqa: E731 — byte-level ids
    bounds = []
    pos = 0
    for w in words:
        pos += len((" " + w).encode("utf-8"))
        bounds.append(pos)
    out: typing.List[typing.List[int]] = [[] for _ in words]
    byte = 0
    wi = 0
    for tok in tokens:
        while wi + 1 < len(words) and byte >= bounds[wi]:
            wi += 1
        out[wi].append(int(tok))
        byte += token_bytes(tok)
    return out


def bpe_token_bytes(merges: typing.Sequence[typing.Sequence[int]],
                    first_new_id: int = 256
                    ) -> typing.Callable[[int], int]:
    """``token_bytes`` for :func:`align_tokens` over a
    ``tools/train_tokenizer.py`` vocabulary: ids < ``first_new_id`` are raw
    bytes (length 1); merge id ``first_new_id + i`` covers the combined byte
    length of the pair it merges."""
    lens: typing.List[int] = [1] * first_new_id
    for left, right in merges:
        lens.append(lens[int(left)] + lens[int(right)])
    return lambda tok: lens[int(tok)]


def byte_encode(text: str) -> typing.List[int]:
    return list(text.encode("utf-8", errors="replace"))


def byte_decode(ids: typing.Sequence[int]) -> str:
    return bytes(int(i) & 0xFF for i in ids).decode("utf-8", errors="replace")


def tokens_per_frame(timed: typing.Sequence[TimedWord],
                     token_lists: typing.Sequence[typing.Sequence[int]],
                     frame_time: float, frame_step: float
                     ) -> typing.List[int]:
    """Tokens of every word whose timestamp falls inside the frame's window
    ``[frame_time, frame_time + frame_step)`` — the per-frame assignment the
    TFRecord builder writes next to each frame."""
    out: typing.List[int] = []
    for tw, toks in zip(timed, token_lists):
        if frame_time <= tw.time < frame_time + frame_step:
            out.extend(toks)
    return out
