#!/usr/bin/env python
"""graftcost: static resource sheet for a config — no TPU, no XLA compile.

Prints, per config x traced step (train / decode / prefill), the cost
model's predictions (analysis/cost_model.py): per-device peak HBM broken
into params / optimizer slots / batch / KV cache / activation live-set,
collective payload bytes per mesh axis with an alpha-beta time estimate,
the static matmul flop count, and the roofline verdict (MXU- vs HBM- vs
ICI-bound) — then whether the config fits each device kind's HBM.

``--sweep`` answers the long-context / serving planning questions without
re-tracing: one traced anchor is classified into batch/sequence scaling
components (analysis/memory.py), so sweeping context 1k -> 128k is
arithmetic and the whole run takes seconds on a laptop CPU.

Usage:
  python tools/graftcost.py --config configs/32ctx_mixer.json
  python tools/graftcost.py --all-configs
  python tools/graftcost.py --config configs/32ctx_mixer.json \
      --sweep context=1024..131072
  python tools/graftcost.py --config configs/32big_mixer.json \
      --sweep batch=8..1024 --devices v5e,v4,v5p
  python tools/graftcost.py --config configs/x.json --json

Exit code: 0 (informational; the enforcing gate is graftcheck's
resource-budget rule), 2 on usage errors.
"""
import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# same virtual mesh as graftcheck/tests so predictions are reproducible
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--config", action="append", default=[],
                   help="config JSON to price (repeatable)")
    p.add_argument("--all-configs", action="store_true")
    p.add_argument("--steps", default="train,decode,prefill",
                   help="comma list of steps (train,eval,decode,prefill)")
    p.add_argument("--devices", default="v5e,v4,v5p",
                   help="comma list of device kinds for fit checks / sweeps")
    p.add_argument("--sweep", default="",
                   help="'context=LO..HI' or 'batch=LO..HI' — geometric x2 "
                        "sweep from one traced anchor")
    p.add_argument("--sweep-step", default="",
                   help="restrict the sweep to one step (default: all)")
    p.add_argument("--json", action="store_true", dest="as_json")
    return p.parse_args(argv)


def fmt_bytes(b: float) -> str:
    from homebrewnlp_tpu.analysis.cost_model import format_bytes
    return format_bytes(b, width=7)


def parse_sweep(spec: str):
    """'context=1024..131072' -> ('context', [1024, 2048, ..., 131072])."""
    key, _, rng = spec.partition("=")
    key = key.strip()
    if key not in ("context", "batch") or ".." not in rng:
        raise ValueError(
            f"bad --sweep {spec!r}; expected context=LO..HI or batch=LO..HI")
    lo_s, _, hi_s = rng.partition("..")
    lo, hi = int(lo_s), int(hi_s)
    if lo <= 0 or hi < lo:
        raise ValueError(f"bad --sweep range {rng!r}")
    points, v = [], lo
    while v < hi:
        points.append(v)
        v *= 2
    points.append(hi)
    return key, points


def sheet(traces, devices, as_json: bool):
    """One config's resource sheet (returns the JSON-able dict)."""
    from homebrewnlp_tpu.analysis import cost_model
    from homebrewnlp_tpu.devices import resolve_device
    res = cost_model.config_resources(traces)
    imesh = cost_model._imesh_shape(traces)
    out = {"config": traces.config_name, "intended_mesh": imesh,
           "target_device": getattr(traces.cfg, "target_device", ""),
           "steps": {}, "fits": {}, "errors": dict(traces.errors)}
    for step, r in res.items():
        row = r.as_golden()
        row["hbm_traffic_bytes"] = r.hbm_traffic_bytes
        row["verdict_device"] = r.verdict_device
        spec = resolve_device(r.verdict_device)
        if spec is not None:
            row["ici_time_s_per_axis"] = {
                k: round(v, 6)
                for k, v in r.comm.times(imesh, spec).items()}
        out["steps"][step] = row
    for kind in devices:
        spec = resolve_device(kind)
        if spec is None:
            continue
        out["fits"][kind] = {
            step: bool(r.hbm["peak"] <= spec.hbm_bytes)
            for step, r in res.items()}
    if not as_json:
        mesh_s = " ".join(f"{k}{v}" for k, v in imesh.items() if v > 1) or "1chip"
        print(f"\n== {traces.config_name}  (intended mesh: {mesh_s})"
              + (f"  target={out['target_device']}" if out["target_device"]
                 else ""))
        for step, r in res.items():
            h = r.hbm
            print(f"  {step:8s} peak {fmt_bytes(h['peak'])}/dev  = params "
                  f"{fmt_bytes(h['params'])} + slots "
                  f"{fmt_bytes(h.get('opt_slots', 0))} + batch "
                  f"{fmt_bytes(h.get('batch', 0))} + kv "
                  f"{fmt_bytes(h['kv_cache'])} + act "
                  f"{fmt_bytes(h['activation_peak'])}   "
                  f"[{r.verdict}-bound on {r.verdict_device}]")
            if r.comm.bytes_per_axis:
                axes = ", ".join(
                    f"{ax}: {fmt_bytes(b).strip()}"
                    for ax, b in sorted(r.comm.bytes_per_axis.items()))
                print(f"           collectives/axis: {axes}")
            if r.implicit_comm.bytes_per_axis:
                axes = ", ".join(
                    f"{ax}: {fmt_bytes(b).strip()}"
                    for ax, b in sorted(r.implicit_comm.bytes_per_axis.items()))
                print(f"           implicit (GSPMD)/axis: {axes}"
                      f"   [tools/graftspmd.py for the full census]")
        for kind, fits in out["fits"].items():
            verdict = " ".join(f"{s}:{'fits' if ok else 'OOM'}"
                               for s, ok in fits.items())
            print(f"           {kind:5s} -> {verdict}")
        for step, err in traces.errors.items():
            print(f"  {step:8s} trace failed: {err}")
    return out


def sweep(traces, devices, key, points, only_step, as_json: bool):
    from homebrewnlp_tpu.analysis import cost_model
    from homebrewnlp_tpu.devices import resolve_device
    model = cost_model.build_sweep_model(traces)
    out = {"config": traces.config_name, "sweep": key, "points": points,
           "anchor": {"batch": model.anchor_batch,
                      "context": model.anchor_seq},
           "ambiguous_anchor": model.ambiguous, "steps": {}}
    steps = [only_step] if only_step else sorted(model.steps)
    for step in steps:
        if step not in model.steps:
            # a valid-but-untraced step (e.g. decode on a video config)
            # must say so, not vanish into an empty sweep
            print(f"[graftcost] {traces.config_name}: step {step!r} not "
                  f"traced"
                  + (f" ({traces.errors[step]})" if step in traces.errors
                     else "") + " — no sweep rows", file=sys.stderr)
            continue
        rows = {}
        for v in points:
            kw = {"context": v} if key == "context" else {"batch": v}
            rows[v] = model.peak_at(step, **kw)
        srow = {"peaks": {v: int(r["peak"]) for v, r in rows.items()},
                "first_exceeding": {}}
        for kind in devices:
            spec = resolve_device(kind)
            if spec is None:
                continue
            srow["first_exceeding"][kind] = cost_model.first_exceeding(
                model, step, spec, points, key)
        out["steps"][step] = srow
        if not as_json:
            print(f"\n-- {traces.config_name} [{step}] sweep {key} "
                  f"(anchor batch={model.anchor_batch} "
                  f"context={model.anchor_seq}"
                  + (", AMBIGUOUS anchor: batch == context" if model.ambiguous
                     else "") + ")")
            for v in points:
                r = rows[v]
                print(f"  {key}={v:<8d} peak {fmt_bytes(r['peak'])}/dev  "
                      f"(kv {fmt_bytes(r.get('kv_cache', 0))}, act "
                      f"{fmt_bytes(r.get('activation_peak', 0))})")
            for kind, first in srow["first_exceeding"].items():
                spec = resolve_device(kind)
                print(f"  {kind:5s} ({fmt_bytes(spec.hbm_bytes).strip()}): "
                      + (f"first {key} exceeding HBM = {first}" if first
                         else f"fits at every swept {key}"))
    return out


def main(argv=None) -> int:
    args = parse_args(argv)
    config_paths = list(args.config)
    if args.all_configs:
        config_paths += sorted(glob.glob(os.path.join(REPO, "configs",
                                                      "*.json")))
    if not config_paths:
        print("nothing to do: pass --config or --all-configs",
              file=sys.stderr)
        return 2
    try:
        sweep_spec = parse_sweep(args.sweep) if args.sweep else None
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    devices = [d.strip() for d in args.devices.split(",") if d.strip()]
    steps = tuple(s.strip() for s in args.steps.split(",") if s.strip())
    valid_steps = {"train", "eval", "decode", "prefill", "prefill_chunk"}
    unknown = sorted(set(steps) - valid_steps)
    if args.sweep_step and args.sweep_step not in valid_steps:
        unknown.append(args.sweep_step)
    if unknown:
        # a typoed step would otherwise trace nothing and print an empty
        # sheet with exit 0 — same validation contract as graftcheck
        print(f"unknown step(s) {', '.join(unknown)}; valid: "
              f"{', '.join(sorted(valid_steps))}", file=sys.stderr)
        return 2

    import contextlib

    from homebrewnlp_tpu.config import Config
    from homebrewnlp_tpu.analysis import trace_config
    results = []
    t0 = time.time()
    # under --json, config/mesh WARNING prints must not corrupt the
    # machine-readable stdout stream
    quiet = (contextlib.redirect_stdout(sys.stderr) if args.as_json
             else contextlib.nullcontext())
    for path in config_paths:
        name = os.path.splitext(os.path.basename(path))[0]
        with open(path) as f:
            raw = json.load(f)
        raw.pop("_comment", None)
        with quiet:
            try:
                cfg = Config(raw)
            except Exception as e:
                results.append({"config": name,
                                "error": f"{type(e).__name__}: {e}"})
                continue
            traces = trace_config(cfg, name, steps=steps)
            if sweep_spec is not None:
                results.append(sweep(traces, devices, sweep_spec[0],
                                     sweep_spec[1], args.sweep_step,
                                     args.as_json))
            else:
                results.append(sheet(traces, devices, args.as_json))
    if args.as_json:
        print(json.dumps(results, indent=2))
    else:
        print(f"\n[graftcost] total {time.time() - t0:.1f}s", file=sys.stderr)
    if args.sweep_step and not any(r.get("steps") for r in results):
        # an explicitly requested sweep step that no config traced is an
        # empty answer, not a clean one
        print(f"[graftcost] --sweep-step {args.sweep_step}: no config "
              f"traced that step", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
