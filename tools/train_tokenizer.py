"""BPE tokenizer training with the C++ hot loop.

Port of /root/reference/scripts/train_tokenizer.pyx (+ run/compile scripts):
that pipeline streams The Pile's ``.jsonl.zst`` shards through parallel
wget/zstd, ftfy-fixes the text, and feeds HuggingFace's BpeTrainer with a
regex pre-split and a 256-byte special-token alphabet.  Here: local/stdin
corpus (the image has no egress — downloading is the operator's problem, and
`--download-cmd` documents the reference's wget|zstd recipe), C++ streaming
cleaner + greedy BPE core (native/hbnlp_native.cc), whitespace pre-split
boundaries, JSON vocab artifact.

Usage:
  python tools/train_tokenizer.py --input corpus1.txt corpus2.jsonl \
      --vocab-size 65536 --output tokenizer.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import typing

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.native import available, bpe_train_words, clean_text  # noqa: E402


def _chunks(path: str, limit: int) -> typing.Iterator[bytes]:
    """Yield text chunks; JSONL files are iterated line-by-line so records
    never straddle a read boundary (arbitrary-size documents parse whole)."""
    import io
    opener = open
    if path.endswith(".zst"):
        import zstandard  # optional; Pile shards

        def opener(p, mode="rb"):
            # ZstdDecompressionReader has no readline; buffer it for line
            # iteration
            return io.BufferedReader(zstandard.open(p, mode))
    is_jsonl = path.endswith((".jsonl", ".jsonl.zst"))
    with opener(path, "rb") as f:
        if is_jsonl:
            for line in f:
                if limit <= 0:
                    return
                try:
                    text = json.loads(line).get("text", "").encode()
                except Exception:
                    print(f"WARNING: unparseable JSONL line in {path}",
                          file=sys.stderr)
                    continue
                limit -= len(text)
                yield text
        else:
            while limit > 0:
                chunk = f.read(1 << 20)
                if not chunk:
                    return
                limit -= len(chunk)
                yield chunk


def corpus_word_counts(paths: typing.Sequence[str], limit_bytes: int
                       ) -> typing.Dict[bytes, int]:
    """Deduplicated {word-as-int32-token-bytes: count} — the HF-BpeTrainer
    structure the native trainer consumes; whole corpus never materializes
    as one token stream."""
    from collections import Counter
    counter: typing.Counter[bytes] = Counter()
    total = 0
    for path in paths:
        for chunk in _chunks(path, limit_bytes - total):
            chunk = clean_text(chunk)
            total += len(chunk)
            counter.update(chunk.split())  # whitespace-run word split
            if total >= limit_bytes:
                break
    if not counter:
        raise SystemExit("empty corpus")
    return {np.frombuffer(word, np.uint8).astype(np.int32).tobytes(): c
            for word, c in counter.items()}


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--input", nargs="+", required=True)
    p.add_argument("--vocab-size", type=int, default=65536)
    p.add_argument("--output", default="tokenizer.json")
    p.add_argument("--limit-mb", type=int, default=256,
                   help="max corpus bytes to train on")
    p.add_argument("--download-cmd", action="store_true",
                   help="print the reference's Pile download recipe and exit")
    args = p.parse_args()
    if args.download_cmd:
        print("for i in $(seq -w 0 29); do wget -q "
              "https://the-eye.eu/public/AI/pile/train/$i.jsonl.zst & done; "
              "wait  # (reference train_tokenizer.pyx:31-43)")
        return

    print(f"native library: {'yes' if available() else 'no (python fallback)'}")
    words = corpus_word_counts(args.input, args.limit_mb << 20)
    n_merges = args.vocab_size - 256
    n_tokens = sum(len(w) // 4 * c for w, c in words.items())
    print(f"training {n_merges} merges over {len(words)} unique words "
          f"({n_tokens} tokens)")
    pairs = bpe_train_words(words, n_merges, first_new_id=256)
    vocab = {"type": "bpe", "byte_fallback": True, "first_new_id": 256,
             "merges": pairs.tolist()}
    with open(args.output, "w") as f:
        json.dump(vocab, f)
    print(f"wrote {args.output}: {len(pairs)} merges "
          f"(vocab {256 + len(pairs)})")


if __name__ == "__main__":
    main()
