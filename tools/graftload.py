#!/usr/bin/env python
"""graftload: serving load generator + client-vs-server SLO reconciliation.

Drives the REST server (``serve/rest.py``) with a fixed-seed prompt corpus
in open- or closed-loop mode, logs every request (JSONL/CSV), and computes
a report from CLIENT-side wall-clock timestamps — e2e latency percentiles,
goodput tok/s, error/shed rates, an in-flight-depth trace — then reconciles
it against the SERVER's own ``/metrics`` histograms (TTFT, queue wait,
engine busy, e2e), the same predict-vs-measure discipline graftprof applies
to device time (docs/observability.md "Serving SLOs").

Modes:
  closed  N worker threads, each holding at most one request in flight
          (concurrency = offered load; ``--ramp-s`` staggers worker starts
          so the queue-depth trace shows the knee)
  open    requests fire on a fixed schedule (``--rate`` req/s) regardless
          of completions — the arrival process a public endpoint actually
          sees; latency under overload grows without the closed loop's
          self-throttling

Percentiles: client-side numbers use the exact order-statistic estimator,
server-side numbers the bucket-interpolated estimator — BOTH from
``obs/registry.py`` (``sample_quantile`` / ``bucket_quantile``), the one
shared percentile implementation.  Reconciliation tolerance (documented):

    tol = bucket_width_at(server_p50) + max(0.05, 0.25 * server_p50)

i.e. one histogram bucket (the estimator's resolution floor) plus a 25%
margin for client-stack overhead — a disagreement inside it is not
measurable by the histogram.

Usage:
  python tools/graftload.py --url http://127.0.0.1:8000 \
      --metrics-url http://127.0.0.1:9090 --requests 50 --concurrency 4 \
      --log load.jsonl --json
  python tools/graftload.py --url ... --mode open --rate 10 --check

Exit codes: 0 ok; 1 when ``--check`` and the reconciliation disagrees or
the error rate exceeds ``--max-error-rate``; 2 usage/connection errors.
"""
from __future__ import annotations

import argparse
import csv
import json
import math
import os
import random
import sys
import threading
import time
import typing
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from homebrewnlp_tpu.obs.registry import (bucket_quantile,  # noqa: E402
                                          bucket_width_at, sample_quantile)

#: client-side percentile keys every report section carries
QUANTILES = ((0.5, "p50"), (0.95, "p95"), (0.99, "p99"))

#: server histogram series -> report keys (serve/slo.py owns the series);
#: batch_size is per-DECODE-STEP lane occupancy (continuous batching) —
#: absent under the serialized engine, p50 > 1 when requests actually
#: share decode steps; itl_s/decode_step_s are the token-level series
#: (absent until an engine emits token-by-token)
SERVER_SERIES = (("e2e_s", "hbnlp_serve_request_seconds"),
                 ("ttft_s", "hbnlp_serve_ttft_seconds"),
                 ("queue_wait_s", "hbnlp_serve_queue_wait_seconds"),
                 ("engine_s", "hbnlp_serve_engine_seconds"),
                 ("decode_tokens_per_sec",
                  "hbnlp_serve_decode_tokens_per_sec"),
                 ("batch_size", "hbnlp_serve_batch_size"),
                 ("itl_s", "hbnlp_serve_itl_seconds"),
                 ("decode_step_s", "hbnlp_serve_decode_step_seconds"))


def make_corpus(seed: int, n: int, vocab: int = 256, min_len: int = 4,
                max_len: int = 24, long_frac: float = 0.0,
                long_len: int = 0) -> typing.List[typing.List[int]]:
    """Deterministic token-id prompt corpus: same (seed, n, vocab, bounds)
    -> byte-identical prompts on every machine, so two graftload runs (or a
    run and the bench serving row) drive the exact same work.

    ``long_frac``/``long_len`` mix in LONG prompts (exactly ``long_len``
    tokens, chosen per-request from the same seeded stream) — the
    mixed-length corpus that reproduces the admission-prefill stall a long
    prompt inflicts on decoding lanes (docs/observability.md; the scenario
    ``serve_prefill_chunk_tokens`` exists to fix).  The defaults draw no
    extra randomness, so pre-existing fixed-seed corpora are unchanged."""
    rng = random.Random(seed)
    lo, hi = max(1, int(min_len)), max(1, int(max_len))
    if hi < lo:
        lo, hi = hi, lo
    mix_long = float(long_frac) > 0.0 and int(long_len) > 0
    out = []
    for _ in range(max(1, n)):
        if mix_long and rng.random() < float(long_frac):
            n_tok = max(1, int(long_len))
        else:
            n_tok = rng.randint(lo, hi)
        out.append([rng.randrange(1, max(2, vocab)) for _ in range(n_tok)])
    return out


def _post(url: str, body: dict, timeout_s: float,
          headers: typing.Optional[dict] = None
          ) -> typing.Tuple[int, dict, typing.Any, float]:
    """POST JSON; returns ``(status, payload, response headers, wall clock
    at header arrival)`` — the response headers echo the server's
    correlation id + wall stamps (``X-Server-Recv-S``/``X-Server-Send-S``),
    the raw material of the client/server clock-offset estimate."""
    data = json.dumps(body).encode()
    hdr = {"Content-Type": "application/json"}
    hdr.update(headers or {})
    req = urllib.request.Request(url, data=data, headers=hdr)
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        hdr_wall = time.time()
        return r.status, json.loads(r.read() or b"{}"), r.headers, hdr_wall


def read_sse(fp) -> typing.Iterator[typing.Tuple[float, dict]]:
    """Yield ``(arrival perf_counter, event)`` per SSE ``data:`` line from
    a binary file-like (the serving layer frames one JSON document per
    event, serve/rest.py).  Factored so tests can drive it with a
    BytesIO."""
    for line in fp:
        if line.startswith(b"data: "):
            yield time.perf_counter(), json.loads(line[6:])


def _post_stream(url: str, body: dict, timeout_s: float,
                 headers: typing.Optional[dict] = None
                 ) -> typing.Tuple[int, dict, typing.List[float],
                                   typing.Any, float]:
    """POST with ``stream: true`` and drain the SSE response.  Returns
    ``(status, final event, chunk arrival times, response headers, wall
    clock at header arrival)`` — the final event carries the
    buffered-equivalent ``completion``; the arrival times (token-chunk
    events only, the final event excluded) are the client arm of the ITL
    reconciliation.  The header-arrival wall stamp (NOT stream-drain
    completion) pairs with the server's ``X-Server-Send-S`` header
    emission in the clock-offset estimate."""
    data = json.dumps(dict(body, stream=True)).encode()
    hdr = {"Content-Type": "application/json"}
    hdr.update(headers or {})
    req = urllib.request.Request(url, data=data, headers=hdr)
    final: dict = {}
    times: typing.List[float] = []
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        status = r.status
        hdrs = r.headers
        hdr_wall = time.time()
        ctype = r.headers.get("Content-Type", "")
        if not ctype.startswith("text/event-stream"):
            # a serve_stream=false (or pre-streaming) server answers
            # buffered JSON; treating that as an empty stream would let
            # --stream --check pass while measuring nothing
            raise RuntimeError(
                f"server did not stream (Content-Type {ctype!r}); "
                "is serve_stream enabled?")
        for t, event in read_sse(r):
            if event.get("done"):
                final = event
            elif "error" in event:
                raise RuntimeError(f"mid-stream error: {event['error']}")
            else:
                times.append(t)
    return status, final, times, hdrs, hdr_wall


def run_load(url: str, corpus: typing.Sequence[typing.Sequence[int]],
             n_requests: int, concurrency: int = 4, mode: str = "closed",
             rate: typing.Optional[float] = None, ramp_s: float = 0.0,
             response_len: int = 16, temperature: float = 1.0,
             timeout_s: float = 300.0, trace_interval_s: float = 0.05,
             stream: bool = False, xid_prefix: str = "gl",
             targets: typing.Optional[typing.Sequence[str]] = None,
             tenants: int = 0
             ) -> typing.Tuple[typing.List[dict], typing.List[list], float,
                               bool]:
    """Fire ``n_requests`` at ``url``/token_completion; returns
    ``(records, inflight_trace, duration_s, truncated)``.  Every request
    yields one record (id, prompt/response sizes, client timestamps,
    status, e2e); the trace is ``[t_s, inflight]`` samples at
    ``trace_interval_s``.  ``truncated`` is True when a worker outlived
    the join budget (per-worker request share x ``timeout_s``) — the
    records then cover only part of the run and must not be treated as a
    complete measurement (drive/check/bench all refuse to).

    ``targets`` overrides ``url`` with several base URLs round-robined by
    request index — either the replica set itself or (the common case) a
    single router URL (``serve/router.py``).  Each record carries the
    target it was sent to and a ``replica`` attribution: the ``X-Replica``
    response header when the target sets one (the router names the replica
    that actually COMMITTED the response, surviving transparent failover),
    else the target URL itself.

    ``stream=True`` sends ``stream: true`` and drains each response as
    SSE: records gain ``ttft_s`` (first chunk arrival, the client's own
    clock) and ``itl_gaps`` (deltas between consecutive chunk arrivals) —
    the client arm of the token-level reconciliation.

    Every request carries a deterministic ``X-Request-Id``
    (``<xid_prefix>-<i>``) the server echoes and threads through its log
    lines, span trails, and flight bundles; records keep the id plus the
    client/server wall stamps (``c_send_wall_s``/``c_hdr_wall_s`` and the
    echoed ``s_recv_wall_s``/``s_send_wall_s``) that
    :func:`estimate_offset` turns into one merged-trace timebase.

    ``tenants=N`` assigns each request a deterministic tenant identity
    ``t<i mod N>`` by REQUEST INDEX (no extra randomness — the seeded
    prompt stream, and therefore every pre-existing fixed-seed corpus,
    stays byte-identical) and sends it as ``X-Tenant``; records carry the
    assignment, the client arm of the usage-metering reconciliation
    (``obs/usage.py``).  0 = no header, the pre-tenancy wire format."""
    bases = [u.rstrip("/") for u in (targets if targets else (url,))]
    lock = threading.Lock()
    records: typing.List[dict] = []
    inflight = [0]
    trace: typing.List[list] = []
    done = threading.Event()
    t_start = time.perf_counter()

    def sample_trace():
        while not done.wait(trace_interval_s):
            with lock:
                trace.append([round(time.perf_counter() - t_start, 4),
                              inflight[0]])

    def _server_stamps(rec: dict, hdrs) -> None:
        rep = hdrs.get("X-Replica")
        if rep:  # router attribution: the replica that committed the bytes
            rec["replica"] = rep
        for key, hname in (("s_recv_wall_s", "X-Server-Recv-S"),
                           ("s_send_wall_s", "X-Server-Send-S")):
            v = hdrs.get(hname)
            if v is not None:
                try:
                    rec[key] = float(v)
                except ValueError:
                    pass

    def one(i: int) -> None:
        prompt = list(corpus[i % len(corpus)])
        xid = f"{xid_prefix}-{i:04d}"
        base = bases[i % len(bases)]
        endpoint = base + "/token_completion"
        rec = {"id": i, "xid": xid, "prompt_len": len(prompt),
               "t_send_s": round(time.perf_counter() - t_start, 6),
               "status": 0, "tokens_generated": 0,
               "target": base, "replica": base}
        if tenants > 0:
            rec["tenant"] = f"t{i % tenants}"
        with lock:
            inflight[0] += 1
        rec["c_send_wall_s"] = time.time()
        t0 = time.perf_counter()
        try:
            body = {"prompt": prompt, "temperature": temperature,
                    "response_len": response_len}
            req_hdrs = {"X-Request-Id": xid}
            if tenants > 0:
                req_hdrs["X-Tenant"] = rec["tenant"]
            if stream:
                status, out, chunk_ts, hdrs, hdr_wall = _post_stream(
                    endpoint, body, timeout_s, headers=req_hdrs)
                if chunk_ts:
                    rec["ttft_s"] = round(chunk_ts[0] - t0, 6)
                    rec["itl_gaps"] = [
                        round(chunk_ts[i] - chunk_ts[i - 1], 6)
                        for i in range(1, len(chunk_ts))]
            else:
                status, out, hdrs, hdr_wall = _post(endpoint, body,
                                                    timeout_s,
                                                    headers=req_hdrs)
            rec["c_hdr_wall_s"] = hdr_wall
            _server_stamps(rec, hdrs)
            rec["status"] = status
            comp = out.get("completion")
            if isinstance(comp, list):
                rec["tokens_generated"] = max(0, len(comp) - len(prompt))
        except urllib.error.HTTPError as e:
            rec["status"] = e.code
            # a rejection still echoes the correlation headers — its
            # clock pair is as good as a 200's
            rec["c_hdr_wall_s"] = time.time()
            _server_stamps(rec, e.headers)
            retry = e.headers.get("Retry-After")
            if retry is not None:
                rec["retry_after_s"] = float(retry)
            e.read()  # drain so the connection can be reused/closed cleanly
        except Exception as e:  # noqa: BLE001 - timeouts/conn errors -> record
            rec["error"] = f"{type(e).__name__}: {e}"[:200]
        finally:
            rec["e2e_s"] = round(time.perf_counter() - t0, 6)
            rec["c_done_wall_s"] = time.time()
            with lock:
                inflight[0] -= 1
                records.append(rec)

    tracer = threading.Thread(target=sample_trace, daemon=True)
    tracer.start()
    threads: typing.List[threading.Thread] = []
    if mode == "closed":
        counter = [0]

        def worker(k: int) -> None:
            if ramp_s and concurrency > 1:
                # stagger starts across the ramp so the in-flight trace
                # records the latency knee, not just the plateau
                time.sleep(ramp_s * k / (concurrency - 1))
            while True:
                with lock:
                    i = counter[0]
                    if i >= n_requests:
                        return
                    counter[0] += 1
                one(i)

        threads = [threading.Thread(target=worker, args=(k,), daemon=True)
                   for k in range(max(1, concurrency))]
        for t in threads:
            t.start()
    elif mode == "open":
        if not rate or rate <= 0:
            raise ValueError("open-loop mode needs --rate > 0 (req/s)")
        for i in range(n_requests):
            when = t_start + i / rate
            delay = when - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=one, args=(i,), daemon=True)
            t.start()
            threads.append(t)
    else:
        raise ValueError(f"unknown mode {mode!r} (closed|open)")
    # join budget scales with each worker's request share: a closed-loop
    # worker serves ~n/concurrency requests SEQUENTIALLY, each bounded by
    # its own HTTP timeout_s — a flat timeout would abandon slow-but-alive
    # runs and report partial records as if they were the whole run
    share = (-(-n_requests // max(1, concurrency)) if mode == "closed" else 1)
    deadline = time.monotonic() + share * timeout_s + ramp_s + 60.0
    truncated = False
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        truncated = truncated or t.is_alive()
    done.set()
    tracer.join(timeout=5.0)
    with lock:  # snapshot: a truncated run's workers may still append
        records = list(records)
    return records, trace, time.perf_counter() - t_start, truncated


def _pcts(samples: typing.Sequence[float]) -> typing.Optional[dict]:
    if not samples:
        return None
    out = {key: round(sample_quantile(samples, q), 6) for q, key in QUANTILES}
    out["mean"] = round(sum(samples) / len(samples), 6)
    out["max"] = round(max(samples), 6)
    return out


def client_report(records: typing.Sequence[dict],
                  trace: typing.Sequence[list], duration_s: float,
                  truncated: bool = False) -> dict:
    """Client-side arm of the reconciliation: exact percentiles over this
    process's own wall-clock measurements.  ``truncated`` (run_load gave
    up on a live worker) marks the whole report partial."""
    ok = [r for r in records if r.get("status") == 200]
    tokens = sum(int(r.get("tokens_generated") or 0) for r in ok)
    n = len(records)
    per_replica: typing.Dict[str, dict] = {}
    for r in records:
        row = per_replica.setdefault(str(r.get("replica") or
                                         r.get("target") or "?"),
                                     {"requests": 0, "ok": 0})
        row["requests"] += 1
        row["ok"] += int(r.get("status") == 200)
    per_tenant: typing.Dict[str, dict] = {}
    for r in records:
        tenant = r.get("tenant")
        if not tenant:
            continue
        row = per_tenant.setdefault(str(tenant),
                                    {"requests": 0, "ok": 0,
                                     "prompt_tokens": 0,
                                     "generated_tokens": 0, "_e2e": []})
        row["requests"] += 1
        if r.get("status") == 200:
            # token counts over 200s only — the server's billing rule
            # (obs/usage.py) and therefore the reconcilable quantity
            row["ok"] += 1
            row["prompt_tokens"] += int(r.get("prompt_len") or 0)
            row["generated_tokens"] += int(r.get("tokens_generated") or 0)
            if r.get("e2e_s") is not None:
                row["_e2e"].append(r["e2e_s"])
    for row in per_tenant.values():
        row["e2e_s"] = _pcts(row.pop("_e2e"))
    thin = max(1, len(trace) // 200)  # bound the trace the report embeds
    ttfts = [r["ttft_s"] for r in ok if r.get("ttft_s") is not None]
    gaps = [g for r in ok for g in (r.get("itl_gaps") or ())]
    stream_extra = {}
    if ttfts:
        stream_extra["ttft_s"] = _pcts(ttfts)
    if gaps:
        stream_extra["itl_s"] = _pcts(gaps)
    return {
        **stream_extra,
        "truncated": bool(truncated),
        "n_requests": n,
        "n_ok": len(ok),
        "n_rejected": sum(1 for r in records if r.get("status") == 503),
        "error_rate": (round(sum(1 for r in records
                                 if r.get("status") != 200) / n, 4)
                       if n else None),
        "duration_s": round(duration_s, 3),
        "requests_per_s": round(n / duration_s, 3) if duration_s > 0 else None,
        "goodput_tok_s": (round(tokens / duration_s, 2)
                          if duration_s > 0 else None),
        "e2e_s": _pcts([r["e2e_s"] for r in ok]),
        "per_replica": per_replica,
        **({"per_tenant": per_tenant} if per_tenant else {}),
        # peak concurrent in-flight over the run — the chaos-tolerance
        # budget: killing a replica can cost at most the requests that
        # were in flight at the kill (check_ok chaos_tolerant=True)
        "peak_inflight": max((int(p[1]) for p in trace), default=0),
        "inflight_trace": [list(p) for p in trace[::thin]],
    }


# -- Prometheus text parsing (the client's view of the server's histograms) --


def parse_prom(text: str) -> typing.Dict[str, typing.List[tuple]]:
    """{metric sample name: [(labels dict, float value), ...]} from
    Prometheus text exposition (0.0.4).

    Thin raw-sample view over the ONE prom-text parser the repo maintains
    (``obs.fleet.parse_prom_text`` — the fleet federation's): histogram
    families flatten back to their ``_bucket``/``_sum``/``_count`` raw
    names with cumulative bucket values, which is the shape
    ``histogram_snapshot`` below has always consumed."""
    from homebrewnlp_tpu.obs import fleet as fleet_obs
    out: typing.Dict[str, typing.List[tuple]] = {}
    for name, fam in fleet_obs.parse_prom_text(text).items():
        if fam.samples:
            out.setdefault(name, []).extend(fam.samples)
        for slot in fam.hist.values():
            labels = slot["labels"]
            for le, cum in sorted(slot["le"].items()):
                le_s = "+Inf" if le == math.inf else fleet_obs._fmt(le)
                out.setdefault(name + "_bucket", []).append(
                    (dict(labels, le=le_s), cum))
            out.setdefault(name + "_sum", []).append(
                (dict(labels), slot["sum"]))
            out.setdefault(name + "_count", []).append(
                (dict(labels), slot["count"]))
    return out


def histogram_snapshot(metrics: typing.Dict[str, typing.List[tuple]],
                       name: str,
                       match: typing.Optional[dict] = None
                       ) -> typing.Optional[dict]:
    """{"buckets", "counts" (NON-cumulative, +Inf last), "sum", "count"}
    for one histogram, summed across label children that contain ``match``;
    None when the series is absent or empty."""
    match = match or {}

    def keep(labels: dict) -> bool:
        return all(labels.get(k) == v for k, v in match.items())

    by_le: typing.Dict[float, float] = {}
    for labels, value in metrics.get(name + "_bucket", []):
        if "le" not in labels or not keep(labels):
            continue
        le = math.inf if labels["le"] == "+Inf" else float(labels["le"])
        by_le[le] = by_le.get(le, 0.0) + value
    if not by_le:
        return None
    edges = sorted(by_le)
    cum = [by_le[e] for e in edges]
    counts = [cum[0]] + [cum[i] - cum[i - 1] for i in range(1, len(cum))]
    total = sum(v for labels, v in metrics.get(name + "_count", [])
                if keep(labels))
    ssum = sum(v for labels, v in metrics.get(name + "_sum", [])
               if keep(labels))
    if total <= 0:
        return None
    buckets = [e for e in edges if e != math.inf]
    if len(counts) == len(buckets):  # renderer always emits +Inf, but be safe
        counts.append(0.0)
    return {"buckets": buckets, "counts": counts, "sum": ssum,
            "count": int(total)}


def server_report(metrics_text: str) -> dict:
    """Server-side arm: bucket-interpolated percentiles over the /metrics
    histograms serve/slo.py records (the completion path only — the e2e
    series is filtered to /token_completion, what graftload drives)."""
    metrics = parse_prom(metrics_text)
    out: dict = {}
    for key, name in SERVER_SERIES:
        match = ({"path": "/token_completion"}
                 if name == "hbnlp_serve_request_seconds" else None)
        snap = histogram_snapshot(metrics, name, match)
        if snap is None:
            continue
        row = {k: round(bucket_quantile(snap["buckets"], snap["counts"], q), 6)
               for q, k in QUANTILES}
        row["mean"] = round(snap["sum"] / snap["count"], 6)
        row["count"] = snap["count"]
        out[key] = row
    for gauge in ("hbnlp_serve_inflight", "hbnlp_serve_queue_depth",
                  "hbnlp_serve_kv_blocks_free", "hbnlp_serve_lane_occupancy"):
        for _, value in metrics.get(gauge, []):
            out[gauge.replace("hbnlp_serve_", "")] = value
    # decode-loop attribution counters (batch engine only): total loop
    # wall, the slice stalled on admission prefill, and their ratio — the
    # number that justifies lifting prefill off the decode critical path
    loop = sum(v for _, v in metrics.get("hbnlp_serve_decode_loop_seconds",
                                         []))
    stall = sum(v for _, v in metrics.get(
        "hbnlp_serve_prefill_stall_seconds", []))
    if loop > 0:
        out["decode_loop_s"] = round(loop, 6)
        out["prefill_stall_s"] = round(stall, 6)
        out["prefill_stall_fraction"] = round(stall / loop, 6)
    return out


def _router_counters(metrics_text: str) -> typing.Tuple[
        float, typing.Dict[typing.Tuple[str, str], float],
        typing.Optional[float]]:
    """(failovers_total, {(replica, outcome): count}, replicas_healthy)
    from a router /metrics scrape (``serve/router.py`` owns the series)."""
    metrics = parse_prom(metrics_text)
    failovers = sum(v for _, v in
                    metrics.get("hbnlp_router_failovers_total", []))
    requests: typing.Dict[typing.Tuple[str, str], float] = {}
    for labels, v in metrics.get("hbnlp_router_requests_total", []):
        key = (labels.get("replica", "?"), labels.get("outcome", "?"))
        requests[key] = requests.get(key, 0.0) + v
    healthy = None
    for _, v in metrics.get("hbnlp_router_replicas_healthy", []):
        healthy = v
    return failovers, requests, healthy


def router_report(before_text: str, after_text: str,
                  client_per_replica: typing.Optional[dict] = None) -> dict:
    """Router-side arm of the fleet reconciliation: per-replica attempt
    counts by outcome (ok / failover / truncated / error) as RUN DELTAS
    between two /metrics scrapes bracketing the load, so a long-lived
    router's prior traffic cannot pollute the comparison.

    The ``failover`` column is reconciled against
    ``hbnlp_router_failovers_total`` (the two are incremented on the same
    code path — disagreement means a counter bug), and when the client's
    own per-replica attribution (``X-Replica`` headers) is supplied, its
    200-count per replica is checked against the router's ``ok`` outcome
    for the same replica: the router only stamps the header on the attempt
    that committed, so the two views must agree exactly on clean AND
    chaotic runs alike."""
    f0, r0, _ = _router_counters(before_text)
    f1, r1, healthy = _router_counters(after_text)
    per_replica: typing.Dict[str, dict] = {}
    for (replica, outcome), v in r1.items():
        d = v - r0.get((replica, outcome), 0.0)
        if d:
            per_replica.setdefault(replica, {})[outcome] = int(d)
    failovers = int(f1 - f0)
    column_sum = sum(row.get("failover", 0) for row in per_replica.values())
    out: dict = {"failovers": failovers,
                 "per_replica": per_replica,
                 "failover_column_consistent": column_sum == failovers}
    if healthy is not None:
        out["replicas_healthy"] = healthy
    if client_per_replica is not None:
        mismatches = {}
        names = set(per_replica) | {k for k, v in client_per_replica.items()
                                    if v.get("ok")}
        for name in sorted(names):
            c_ok = int((client_per_replica.get(name) or {}).get("ok", 0))
            s_ok = int((per_replica.get(name) or {}).get("ok", 0))
            if c_ok != s_ok:
                mismatches[name] = {"client_ok": c_ok, "router_ok": s_ok}
        out["client_ok_matches_router"] = not mismatches
        if mismatches:
            out["mismatches"] = mismatches
    return out


def reconcile_report(client: dict, metrics_text: str) -> dict:
    """Client p50 e2e vs the server's own e2e histogram, inside the
    documented tolerance (module docstring), plus the serialization
    overhead the batching PR will be judged against:
    ``client p50 e2e − server p50 engine-busy`` = everything that is NOT
    the model (parse + queue wait + respond + client stack).

    Defined over CLEAN runs only: the server's e2e histogram has no status
    label, so fast 503 rejections would sit in the server arm while the
    client arm filters to 200s — under shedding the comparison would flag
    two perfectly healthy clocks.  Any client-side error/rejection skips
    the reconciliation with a reason instead."""
    err = client.get("error_rate")
    if err:
        return {"skipped": f"client error_rate={err}: non-200 responses "
                           "share the server histogram (no status label); "
                           "reconciliation is defined over clean runs"}
    metrics = parse_prom(metrics_text)
    snap = histogram_snapshot(metrics, "hbnlp_serve_request_seconds",
                              {"path": "/token_completion"})
    c = (client.get("e2e_s") or {}).get("p50")
    if snap is None or c is None:
        return {"skipped": "client or server p50 unavailable"}
    s = bucket_quantile(snap["buckets"], snap["counts"], 0.5)
    width = bucket_width_at(snap["buckets"], s)
    tol = (width if width != math.inf else 0.0) + max(0.05, 0.25 * s)
    out = {"client_p50_e2e_s": round(c, 6),
           "server_p50_e2e_s": round(s, 6),
           "abs_diff_s": round(abs(c - s), 6),
           "tolerance_s": round(tol, 6),
           "within_tolerance": bool(abs(c - s) <= tol)}
    eng = histogram_snapshot(metrics, "hbnlp_serve_engine_seconds")
    if eng is not None:
        e50 = bucket_quantile(eng["buckets"], eng["counts"], 0.5)
        out["server_p50_engine_s"] = round(e50, 6)
        out["serialization_overhead_s"] = round(max(0.0, c - e50), 6)
    # token-level arms (a --stream run): the client's own chunk-arrival
    # percentiles against the server's ITL/TTFT histograms, same tolerance
    # formula per series — one bucket width (the estimator's resolution
    # floor) + the 25% client-stack margin
    for key, series in (("itl", "hbnlp_serve_itl_seconds"),
                        ("ttft", "hbnlp_serve_ttft_seconds")):
        cp = (client.get(f"{key}_s") or {}).get("p50")
        snap = histogram_snapshot(metrics, series)
        if cp is None or snap is None:
            continue
        sp = bucket_quantile(snap["buckets"], snap["counts"], 0.5)
        width = bucket_width_at(snap["buckets"], sp)
        ktol = (width if width != math.inf else 0.0) + max(0.05, 0.25 * sp)
        out[key] = {"client_p50_s": round(cp, 6),
                    "server_p50_s": round(sp, 6),
                    "abs_diff_s": round(abs(cp - sp), 6),
                    "tolerance_s": round(ktol, 6),
                    "within_tolerance": bool(abs(cp - sp) <= ktol)}
    return out


def tenant_token_deltas(before_text: str, after_text: str
                        ) -> typing.Dict[tuple, float]:
    """``{(tenant, kind): delta}`` of ``hbnlp_serve_tokens_total`` between
    two /metrics scrapes bracketing a run — the server arm of the usage
    reconciliation, as run deltas so a long-lived server's prior traffic
    cannot pollute the comparison.  A tenant evicted from the top-K sketch
    restarts its series at 0 (obs/usage.py fold semantics), so a negative
    per-row delta is possible in principle; it is NOT clamped here — exact
    reconciliation must see it and fail, not paper over it."""
    out: typing.Dict[tuple, float] = {}
    for sign, text in ((-1.0, before_text), (1.0, after_text)):
        for labels, v in parse_prom(text).get("hbnlp_serve_tokens_total",
                                              []):
            key = (labels.get("tenant", "?"), labels.get("kind", "?"))
            out[key] = out.get(key, 0.0) + sign * v
    return out


def usage_reconcile_report(client_per_tenant: typing.Optional[dict],
                           deltas: typing.Dict[tuple, float]) -> dict:
    """Usage-metering reconciliation: the client's own per-tenant token
    counts (200s only — the server's billing rule, obs/usage.py) against
    the server's metered ``hbnlp_serve_tokens_total{tenant,kind}`` run
    deltas.  Tolerance is EXACT — both sides count the same tokens, not
    clocks, so any disagreement is a metering bug, not measurement noise.
    Defined over a DEDICATED run: foreign traffic, or a top-K fold moving
    one of our tenants into ``tenant="other"``, surfaces as extra server
    rows and fails the match rather than being absorbed."""
    if not client_per_tenant:
        return {"skipped": "no client tenant assignments (--tenants 0)"}
    rows: typing.Dict[str, dict] = {}
    mismatches: typing.Dict[str, dict] = {}
    for tenant, crow in sorted(client_per_tenant.items()):
        row: dict = {}
        for kind, field in (("prompt", "prompt_tokens"),
                            ("generated", "generated_tokens")):
            c = int(crow.get(field) or 0)
            s = int(round(deltas.get((tenant, kind), 0.0)))
            row[kind] = {"client": c, "server": s}
            if c != s:
                mismatches.setdefault(tenant, {})[kind] = row[kind]
        rows[tenant] = row
    extra = {f"{tenant}/{kind}": int(round(v))
             for (tenant, kind), v in sorted(deltas.items())
             if tenant not in client_per_tenant and v}
    c_total = sum(int(r.get("prompt_tokens") or 0)
                  + int(r.get("generated_tokens") or 0)
                  for r in client_per_tenant.values())
    s_total = int(round(sum(deltas.values())))
    out = {"client_tokens_total": c_total,
           "server_tokens_total": s_total,
           "per_tenant": rows,
           "tokens_match": (not mismatches and not extra
                            and c_total == s_total)}
    if mismatches:
        out["mismatches"] = mismatches
    if extra:
        out["server_extra_rows"] = extra
    return out


def check_ok(report: dict, max_error_rate: float = 0.0,
             chaos_tolerant: bool = False) -> bool:
    """The ``--check`` verdict as a pure function: the error rate must be
    within ``max_error_rate``, and the reconciliation must either agree
    within tolerance or have been skipped *because of* that tolerated
    non-zero error rate (reconcile_report is defined over clean runs only).
    Any other skip — no metrics URL, missing p50 — still fails, as does a
    truncated run (run_load abandoned a live worker: partial records).

    ``chaos_tolerant=True`` is the verdict for a CHAOS drill (a replica
    killed mid-run behind the router): instead of an error-RATE bound it
    accepts an error COUNT of at most the peak concurrent in-flight depth
    (``client.peak_inflight``) — killing a replica can cost at most the
    requests that were in flight at the kill (pre-first-byte ones fail
    over transparently; committed ones are at-most-once and may truncate)
    — and requires at least one success (the fleet recovered).  The
    latency reconciliation is not consulted: it is defined over clean
    runs, and a chaos run is by design not one.  Truncation still fails —
    a partial measurement proves nothing about recovery."""
    rec = report.get("reconcile", {})
    client = report.get("client") or {}
    if client.get("truncated"):
        return False
    # usage-metering arm (a --tenants run with a metrics URL): the token
    # counters must reconcile EXACTLY on chaos and clean runs alike —
    # failover must not double- or zero-bill a request
    usage = report.get("usage_reconcile")
    if isinstance(usage, dict) and "skipped" not in usage \
            and not usage.get("tokens_match", False):
        return False
    if chaos_tolerant:
        n = int(client.get("n_requests") or 0)
        n_ok = int(client.get("n_ok") or 0)
        peak = int(client.get("peak_inflight") or 0)
        return n > 0 and n_ok >= 1 and (n - n_ok) <= peak
    err = client.get("error_rate")
    err_ok = err is not None and err <= max_error_rate
    rec_ok = (rec.get("within_tolerance", False)
              or ("skipped" in rec and bool(err)))
    # token-level arms (streaming runs): when present they must agree too
    for key in ("itl", "ttft"):
        sub = rec.get(key)
        if isinstance(sub, dict):
            rec_ok = rec_ok and sub.get("within_tolerance", False)
    return err_ok and rec_ok


# -- per-request log ----------------------------------------------------------

LOG_FIELDS = ("id", "xid", "tenant", "replica", "t_send_s", "e2e_s",
              "ttft_s", "status", "prompt_len", "tokens_generated",
              "retry_after_s", "error")


def write_log(records: typing.Sequence[dict], path: str,
              fmt: typing.Optional[str] = None) -> str:
    """JSONL (default) or CSV per-request log; format inferred from the
    extension when ``fmt`` is None."""
    fmt = fmt or ("csv" if path.endswith(".csv") else "jsonl")
    if fmt == "csv":
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=LOG_FIELDS, extrasaction="ignore")
            w.writeheader()
            for r in records:
                w.writerow(r)
    else:
        with open(path, "w") as f:
            for r in records:
                f.write(json.dumps(r, sort_keys=True) + "\n")
    return path


def fetch_metrics(metrics_url: str, timeout_s: float = 10.0) -> str:
    url = metrics_url.rstrip("/")
    if not url.endswith("/metrics"):
        url += "/metrics"
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return r.read().decode()


# -- merged client/server tracing ---------------------------------------------


def estimate_offset(records: typing.Sequence[dict]
                    ) -> typing.Optional[dict]:
    """Client/server clock offset from the per-request echoed wall stamps,
    the NTP idea applied to request/response pairs (same barrier-matching
    estimator shape as ``obs.fleet.estimate_offsets``):

    per request, ``off = ((s_recv - c_send) + (s_send - c_hdr)) / 2``
    where ``c_hdr`` is the client's header-arrival stamp — the client-side
    event that pairs with the server's ``X-Server-Send-S`` emission.

    Returns ``{"offset_s", "bound_s", "n_pairs"}`` with ``server_wall =
    client_wall + offset_s``.  ``bound_s`` is an honest error bar: the
    worst residual across requests plus the worst half round-trip
    asymmetry ``((c_hdr - c_send) - (s_send - s_recv)) / 2`` — the offset
    cannot be pinned tighter than the network legs it rode on.  None when
    no request carried a complete stamp quad."""
    offs, halves = [], []
    for r in records:
        stamps = [r.get(k) for k in ("c_send_wall_s", "s_recv_wall_s",
                                     "s_send_wall_s", "c_hdr_wall_s")]
        if any(s is None for s in stamps):
            continue
        c0, s0, s1, c1 = stamps
        offs.append(((s0 - c0) + (s1 - c1)) / 2.0)
        halves.append(max(0.0, ((c1 - c0) - (s1 - s0)) / 2.0))
    if not offs:
        return None
    mean = sum(offs) / len(offs)
    bound = max(abs(o - mean) for o in offs) + max(halves)
    return {"offset_s": round(mean, 6), "bound_s": round(bound, 6),
            "n_pairs": len(offs)}


def fetch_trace(url: str, timeout_s: float = 10.0) -> dict:
    """GET the server's live Chrome-trace document (``/debugz/trace`` on
    the REST port — serve/rest.py exposes the engine's span ring)."""
    with urllib.request.urlopen(url.rstrip("/") + "/debugz/trace",
                                timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def merge_traces(records: typing.Sequence[dict],
                 server_doc: typing.Optional[dict] = None) -> dict:
    """One Chrome/Perfetto document holding both arms of each request:
    the client's send->done span (pid 0) and the server's queue/prefill/
    decode/emit spans (pid 1+) on a single timebase.  A single-process
    server doc lands on pid 1 exactly as before; a multi-process doc (the
    router's merged router+replicas trace, ``serve/router.py``) keeps its
    processes distinct, shifted up so pid 0 stays the client.

    Server events keep their relative ``ts`` but the whole process is
    shifted onto the client's wall clock via :func:`estimate_offset`; the
    applied offset and its error bound land in ``otherData`` so a reader
    knows how far to trust cross-process edge alignment."""
    clock = estimate_offset(records)
    off = clock["offset_s"] if clock else 0.0
    sent = [r for r in records if r.get("c_send_wall_s") is not None]
    origin = min((r["c_send_wall_s"] for r in sent), default=0.0)
    events: typing.List[dict] = [
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "graftload client"}},
        {"name": "thread_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "requests"}},
    ]
    for r in sent:
        c0, c_done = r["c_send_wall_s"], r.get("c_done_wall_s")
        if c_done is None:
            c_done = c0 + float(r.get("e2e_s") or 0.0)
        args = {"xid": r.get("xid", ""), "status": r.get("status")}
        if r.get("error"):
            args["error"] = r["error"]
        events.append({"name": "client/request", "ph": "X", "pid": 0,
                       "tid": 0, "ts": (c0 - origin) * 1e6,
                       "dur": max(0.0, c_done - c0) * 1e6, "args": args})
        if r.get("ttft_s") is not None:
            events.append({"name": "client/ttft", "ph": "X", "pid": 0,
                           "tid": 0, "ts": (c0 - origin) * 1e6,
                           "dur": float(r["ttft_s"]) * 1e6,
                           "args": {"xid": r.get("xid", "")}})
    n_server = 0
    if server_doc:
        s_epoch = float((server_doc.get("otherData") or {})
                        .get("wall_epoch", 0.0))
        # server ts are relative to its own epoch; correct the epoch onto
        # the client clock, then rebase onto this doc's origin
        shift = (s_epoch - off - origin) * 1e6
        s_pids = sorted({int(ev.get("pid", 0))
                         for ev in server_doc.get("traceEvents", ())})
        remap = {p: i + 1 for i, p in enumerate(s_pids)}
        for ev in server_doc.get("traceEvents", ()):
            ev = dict(ev, pid=remap[int(ev.get("pid", 0))])
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift
            events.append(ev)
            n_server += 1
    other = {"origin_wall_s": round(origin, 6),
             "n_client_requests": len(sent), "n_server_events": n_server}
    if clock:
        other["clock_offset"] = clock
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def drive(url: str, metrics_url: typing.Optional[str] = None,
          n_requests: int = 20, concurrency: int = 4, mode: str = "closed",
          rate: typing.Optional[float] = None, ramp_s: float = 0.0,
          seed: int = 0, vocab: int = 256, min_prompt: int = 4,
          max_prompt: int = 24, response_len: int = 16,
          temperature: float = 1.0, timeout_s: float = 300.0,
          log_path: typing.Optional[str] = None,
          log_format: typing.Optional[str] = None,
          stream: bool = False, long_frac: float = 0.0,
          long_len: int = 0,
          trace_out: typing.Optional[str] = None,
          targets: typing.Optional[typing.Sequence[str]] = None,
          router_metrics_url: typing.Optional[str] = None,
          tenants: int = 0) -> dict:
    """One full run: corpus -> load -> client report -> server scrape ->
    reconciliation.  The importable entry bench.py and the tests share.
    ``long_frac``/``long_len`` thread through to :func:`make_corpus` (the
    mixed prompt-length stall scenario).  ``trace_out`` fetches the
    server's span ring after the run and writes the merged client+server
    Chrome trace there (see :func:`merge_traces`).  ``targets`` round-
    robins requests over several base URLs (or a router, see
    :func:`run_load`); ``router_metrics_url`` brackets the run with two
    router /metrics scrapes and adds the :func:`router_report` fleet arm
    (per-replica outcome deltas + failover-column reconciliation).
    ``tenants=N`` assigns deterministic tenant identities (run_load) and —
    when a ``metrics_url`` is given — brackets the run with two server
    scrapes for the EXACT token reconciliation arm
    (:func:`usage_reconcile_report`)."""
    corpus = make_corpus(seed, max(8, n_requests), vocab, min_prompt,
                         max_prompt, long_frac=long_frac, long_len=long_len)
    router_before, router_err = None, ""
    if router_metrics_url:
        try:
            router_before = fetch_metrics(router_metrics_url)
        except Exception as e:  # noqa: BLE001 - scrape is best-effort
            router_before = None
            router_err = f"{type(e).__name__}: {e}"[:200]
    usage_before, usage_err = None, ""
    if tenants > 0 and metrics_url:
        try:
            usage_before = fetch_metrics(metrics_url)
        except Exception as e:  # noqa: BLE001 - scrape is best-effort
            usage_before = None
            usage_err = f"{type(e).__name__}: {e}"[:200]
    records, trace, duration, truncated = run_load(
        url, corpus, n_requests, concurrency=concurrency, mode=mode,
        rate=rate, ramp_s=ramp_s, response_len=response_len,
        temperature=temperature, timeout_s=timeout_s, stream=stream,
        xid_prefix=f"gl{seed:x}", targets=targets, tenants=tenants)
    report = {"url": url, "mode": mode, "concurrency": concurrency,
              "rate": rate, "seed": seed, "response_len": response_len,
              "stream": bool(stream),
              "long_frac": float(long_frac), "long_len": int(long_len),
              "client": client_report(records, trace, duration,
                                      truncated=truncated)}
    if targets:
        report["targets"] = [u.rstrip("/") for u in targets]
    if router_metrics_url:
        try:
            router_after = fetch_metrics(router_metrics_url)
            if router_before is None:
                raise RuntimeError(f"pre-run scrape failed: {router_err}")
            report["router"] = router_report(
                router_before, router_after,
                client_per_replica=report["client"].get("per_replica"))
        except Exception as e:  # noqa: BLE001 - scrape is best-effort
            report["router"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    if log_path:
        report["log_path"] = write_log(records, log_path, log_format)
    if metrics_url:
        try:
            text = fetch_metrics(metrics_url)
            report["server"] = server_report(text)
            report["reconcile"] = reconcile_report(report["client"], text)
            if tenants > 0:
                if usage_before is None:
                    report["usage_reconcile"] = {
                        "error": f"pre-run scrape failed: {usage_err}"}
                else:
                    report["usage_reconcile"] = usage_reconcile_report(
                        report["client"].get("per_tenant"),
                        tenant_token_deltas(usage_before, text))
        except Exception as e:  # noqa: BLE001 - scrape is best-effort
            report["server"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    if trace_out:
        server_doc = None
        try:
            server_doc = fetch_trace(url)
        except Exception as e:  # noqa: BLE001 - a server without a span
            # ring (flight_buffer_spans=0, no serve_trace_path) 404s here;
            # the client-only trace is still worth writing
            report["trace_error"] = f"{type(e).__name__}: {e}"[:200]
        merged = merge_traces(records, server_doc)
        with open(trace_out, "w") as f:
            json.dump(merged, f)
        report["trace"] = {"path": trace_out,
                           **{k: v for k, v in merged["otherData"].items()
                              if k != "origin_wall_s"}}
    return report


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--url", default="", help="REST server base URL")
    ap.add_argument("--target", action="append", default=None,
                    help="base URL to drive; repeatable — several replica "
                         "URLs round-robin by request index, one router "
                         "URL (tools/graftserve.py front door) exercises "
                         "health-gated routing + failover.  Replaces "
                         "--url when given")
    ap.add_argument("--metrics-url", default="",
                    help="obs exporter base URL (enables the server report "
                         "+ reconciliation)")
    ap.add_argument("--router-metrics-url", default="",
                    help="router base URL to scrape /metrics from before "
                         "and after the run (per-replica outcome deltas + "
                         "failover-column reconciliation); defaults to the "
                         "single --target when one is given")
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--requests", type=int, default=20)
    ap.add_argument("--concurrency", type=int, default=4,
                    help="closed-loop worker threads")
    ap.add_argument("--rate", type=float, default=None,
                    help="open-loop arrival rate (req/s)")
    ap.add_argument("--ramp-s", type=float, default=0.0,
                    help="closed-loop: stagger worker starts across this "
                         "many seconds")
    ap.add_argument("--seed", type=int, default=0,
                    help="prompt-corpus seed (fixed seed = fixed prompts)")
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--min-prompt", type=int, default=4)
    ap.add_argument("--max-prompt", type=int, default=24)
    ap.add_argument("--long-frac", type=float, default=0.0,
                    help="fraction of prompts drawn LONG (--long-len "
                         "tokens) — the fixed-seed mixed-length corpus that "
                         "reproduces the admission-prefill stall; 0 = off")
    ap.add_argument("--long-len", type=int, default=0,
                    help="token length of the long prompts --long-frac "
                         "mixes in")
    ap.add_argument("--tenants", type=int, default=0,
                    help="assign each request a deterministic tenant "
                         "identity t<i mod N> (X-Tenant header) and add "
                         "the per-tenant client report + the EXACT token "
                         "reconciliation arm against the server's usage "
                         "meter; 0 = no tenancy (default, wire-identical "
                         "to earlier releases)")
    ap.add_argument("--response-len", type=int, default=16)
    ap.add_argument("--temperature", type=float, default=1.0)
    ap.add_argument("--timeout-s", type=float, default=300.0)
    ap.add_argument("--stream", action="store_true",
                    help="drive SSE streaming requests and measure "
                         "client-side TTFT + inter-token latency (adds the "
                         "itl/ttft reconciliation arms)")
    ap.add_argument("--log", default="", help="per-request log (.jsonl/.csv)")
    ap.add_argument("--trace-out", default="",
                    help="write a merged client+server Chrome trace here "
                         "(fetches the server's /debugz/trace span ring and "
                         "rebases it onto the client clock)")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as one JSON document")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless reconciliation agrees and the error "
                         "rate is within --max-error-rate")
    ap.add_argument("--max-error-rate", type=float, default=0.0)
    ap.add_argument("--chaos-tolerant", action="store_true",
                    help="chaos-drill --check verdict: accept an error "
                         "COUNT up to the peak in-flight depth (what a "
                         "replica kill can cost) instead of the clean-run "
                         "error-rate/reconciliation gates")
    args = ap.parse_args(argv)
    targets = [u for u in (args.target or []) if u]
    if not args.url and not targets:
        print("graftload: one of --url / --target is required",
              file=sys.stderr)
        return 2
    url = args.url or targets[0]
    router_metrics = args.router_metrics_url or (
        targets[0] if len(targets) == 1 else "")
    try:
        report = drive(url, metrics_url=args.metrics_url or None,
                       n_requests=args.requests,
                       concurrency=args.concurrency, mode=args.mode,
                       rate=args.rate, ramp_s=args.ramp_s, seed=args.seed,
                       vocab=args.vocab, min_prompt=args.min_prompt,
                       max_prompt=args.max_prompt,
                       response_len=args.response_len,
                       temperature=args.temperature,
                       timeout_s=args.timeout_s, log_path=args.log or None,
                       stream=args.stream, long_frac=args.long_frac,
                       long_len=args.long_len,
                       trace_out=args.trace_out or None,
                       targets=targets or None,
                       router_metrics_url=router_metrics or None,
                       tenants=max(0, args.tenants))
    except (OSError, ValueError) as e:
        print(f"graftload: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        c = report["client"]
        print(f"{c['n_ok']}/{c['n_requests']} ok "
              f"({c['n_rejected']} rejected) in {c['duration_s']}s — "
              f"{c['goodput_tok_s']} tok/s goodput")
        if c.get("e2e_s"):
            print("client e2e_s: " + json.dumps(c["e2e_s"]))
        for key in ("ttft_s", "itl_s"):
            if c.get(key):
                print(f"client {key}: " + json.dumps(c[key]))
        for key in ("ttft_s", "itl_s", "queue_wait_s", "engine_s", "e2e_s"):
            row = report.get("server", {}).get(key)
            if row:
                print(f"server {key}: " + json.dumps(row))
        stall_frac = report.get("server", {}).get("prefill_stall_fraction")
        if stall_frac is not None:
            print(f"prefill_stall_fraction: {stall_frac} "
                  "(decode-loop wall lost to blocking admission prefill)")
        per_replica = c.get("per_replica") or {}
        if len(per_replica) > 1 or "router" in report:
            router_rows = (report.get("router") or {}).get("per_replica", {})
            print("replica        requests  ok  failover")
            for name in sorted(set(per_replica) | set(router_rows)):
                crow = per_replica.get(name) or {}
                fo = (router_rows.get(name) or {}).get("failover", 0)
                print(f"{name:<14} {crow.get('requests', 0):>8}  "
                      f"{crow.get('ok', 0):>2}  {fo:>8}")
        if "router" in report:
            print("router: " + json.dumps(
                {k: v for k, v in report["router"].items()
                 if k != "per_replica"}))
        per_tenant = c.get("per_tenant") or {}
        if per_tenant:
            print("tenant         requests  ok  prompt_tok  gen_tok")
            for name in sorted(per_tenant):
                row = per_tenant[name]
                print(f"{name:<14} {row['requests']:>8}  {row['ok']:>2}  "
                      f"{row['prompt_tokens']:>10}  "
                      f"{row['generated_tokens']:>7}")
        if "usage_reconcile" in report:
            print("usage_reconcile: " + json.dumps(
                {k: v for k, v in report["usage_reconcile"].items()
                 if k != "per_tenant"}))
        if "reconcile" in report:
            print("reconcile: " + json.dumps(report["reconcile"]))
        if "trace" in report:
            print("trace: " + json.dumps(report["trace"]))
    if args.check:
        return 0 if check_ok(report, args.max_error_rate,
                             chaos_tolerant=args.chaos_tolerant) else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
