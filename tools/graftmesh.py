#!/usr/bin/env python
"""graftmesh: topology-aware mesh auto-search — no TPU, no XLA compile.

Enumerates the DP/SP/PP/TP factorizations of a config's slice topology
(``parallel/mesh.py::mesh_factorizations``), scores every candidate with the
static cost model's ``static_step_times`` (manual collectives plus the
GSPMD-implicit ones the sharding propagation predicts, analysis/spmd.py)
against the config's ``target_device``, gates each candidate on that
device's HBM capacity
(OOM-before-compile), and prints the ranked sheet with the committed
hand-written mesh marked.  By default the sequence/pipeline axes stay pinned
to the config's declared structure (one abstract trace prices every
candidate); ``--free-axes sequence_parallel,pipeline`` widens the search and
re-traces per structure (seconds each).  See docs/static_analysis.md
"Mesh search".

Usage:
  python tools/graftmesh.py --config configs/8dev_composed_dryrun.json
  python tools/graftmesh.py --config configs/32big_mixer.json --device v4
  python tools/graftmesh.py --config configs/x.json --world 4     # degraded
  python tools/graftmesh.py --config configs/x.json \
      --free-axes sequence_parallel,pipeline
  python tools/graftmesh.py --all-configs --check --json
  python tools/graftmesh.py --config configs/x.json --emit out/   # goldens

Exit code: 0 ok; 1 when --check fails (a hand-written mesh ranks below its
config's mesh_search_top_k — with --strict-check, below the searcher's own
top pick) or when any config fails to load/trace; 2 on usage errors.
"""
import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# same virtual mesh as graftcheck/graftcost so traces are reproducible
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--config", action="append", default=[],
                   help="config JSON to search (repeatable)")
    p.add_argument("--all-configs", action="store_true")
    p.add_argument("--world", type=int, default=0,
                   help="device count to factor (default: the config's "
                        "tpu_size) — the degraded-resume question")
    p.add_argument("--device", default="",
                   help="device kind to score on (default: the config's "
                        "target_device, else the default verdict device)")
    p.add_argument("--free-axes", default="",
                   help="comma list of structural axes to unlock "
                        "(sequence_parallel,pipeline); each distinct "
                        "structure re-traces")
    p.add_argument("--top-k", type=int, default=0,
                   help="override the config's mesh_search_top_k for "
                        "--check")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless every hand-written mesh ranks "
                        "within top-k")
    p.add_argument("--strict-check", action="store_true",
                   help="with --check: the hand mesh must rank at or above "
                        "the searcher's own top pick (rank 1, ties count)")
    p.add_argument("--emit", default="",
                   help="directory to write the winning mesh's ranked "
                        "sheet + resources/census golden-style JSON into")
    p.add_argument("--json", action="store_true", dest="as_json")
    return p.parse_args(argv)


def _sheet_text(result) -> str:
    from homebrewnlp_tpu.analysis.cost_model import format_bytes
    lines = [f"\n== {result.config_name}  ({result.n_devices} devices, "
             f"scored on {result.device_kind}"
             + (f", free axes: {','.join(result.free_axes)}"
                if result.free_axes else "") + ")"]
    for c in result.candidates:
        mark = "  <- hand-written" if c.is_hand else ""
        fit = "" if c.fits else "  [OOM]"
        unpriced = ("  [IMPLICIT UNPRICED: " + c.spmd_error + "]"
                    if c.spmd_error else "")
        lines.append(
            f"  #{c.rank:<2d} {c.describe():28s} "
            f"step {c.step_s * 1e3:9.4f} ms  (ici "
            f"{c.predicted.get('ici_s', 0.0) * 1e3:8.4f} ms, peak "
            f"{format_bytes(c.hbm_peak, width=7)}/dev)"
            f"{fit}{mark}{unpriced}")
    for c in result.skipped:
        lines.append(f"  --  {c.axes}: skipped ({c.error})")
    lines.append(f"  hand-written mesh rank: #{result.hand_rank} of "
                 f"{len(result.candidates)}")
    return "\n".join(lines)


def _emit(result, traces, raw, out_dir: str) -> None:
    """Write the ranked sheet plus golden-style resources/census JSON for
    the winning mesh (what committing the searched layout would pin)."""
    from homebrewnlp_tpu.analysis import trace_config
    from homebrewnlp_tpu.analysis.cost_model import step_resources
    from homebrewnlp_tpu.analysis.graph_rules import _IntendedMesh, census_of
    from homebrewnlp_tpu.config import Config
    os.makedirs(out_dir, exist_ok=True)
    base = os.path.join(out_dir, result.config_name)
    with open(base + "_mesh.json", "w") as f:
        json.dump(result.as_json(), f, indent=2, sort_keys=True)
        f.write("\n")
    top = result.top
    if top.retraced:
        # a free-axes winner runs a DIFFERENT program than the declared
        # structure — its goldens must come from a trace of that program,
        # not from the anchor trace search() only kept the scores of
        win_raw = dict(raw)
        win_raw.pop("_comment", None)
        win_raw["sequence_parallel"] = top.axes["sequence_parallel"]
        win_raw["pipeline_parallel"] = top.axes["pipeline"]
        traces = trace_config(Config(win_raw), result.config_name,
                              steps=tuple(traces.steps) or ("train",))
    imesh = _IntendedMesh(dict(top.axes))
    steps = {}
    for name, st in sorted(traces.steps.items()):
        steps[name] = step_resources(traces, name, st, imesh,
                                     result.device_kind).as_golden()
    with open(base + "_resources.json", "w") as f:
        json.dump({"config": result.config_name,
                   "mesh": {k: int(v) for k, v in sorted(top.axes.items())},
                   "steps": steps}, f, indent=2, sort_keys=True)
        f.write("\n")
    with open(base + "_census.json", "w") as f:
        json.dump({"config": result.config_name,
                   "mesh": {k: int(v) for k, v in sorted(top.axes.items())},
                   "steps": {name: census_of(st) for name, st
                             in sorted(traces.steps.items())}},
                  f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"[graftmesh] wrote {base}_mesh.json + winner resources/census "
          f"goldens", file=sys.stderr)


def main(argv=None) -> int:
    args = parse_args(argv)
    config_paths = list(args.config)
    if args.all_configs:
        config_paths += sorted(glob.glob(os.path.join(REPO, "configs",
                                                      "*.json")))
    if not config_paths:
        print("nothing to do: pass --config or --all-configs",
              file=sys.stderr)
        return 2
    free_axes = tuple(a.strip() for a in args.free_axes.split(",")
                      if a.strip())
    unknown = sorted(set(free_axes) - {"sequence_parallel", "pipeline"})
    if unknown:
        print(f"unknown --free-axes {', '.join(unknown)}; valid: "
              f"sequence_parallel, pipeline", file=sys.stderr)
        return 2

    import contextlib

    from homebrewnlp_tpu.analysis import mesh_search, trace_config
    from homebrewnlp_tpu.config import Config
    results = []
    failed = []
    t0 = time.time()
    quiet = (contextlib.redirect_stdout(sys.stderr) if args.as_json
             else contextlib.nullcontext())
    for path in config_paths:
        name = os.path.splitext(os.path.basename(path))[0]
        with open(path) as f:
            raw = json.load(f)
        raw.pop("_comment", None)
        with quiet:
            try:
                cfg = Config(dict(raw))
            except Exception as e:
                print(f"[graftmesh] {name}: config failed to load "
                      f"({type(e).__name__}: {e})", file=sys.stderr)
                failed.append(name)
                continue
            if max(cfg.tpu_size, 1) <= 1 and not args.world:
                print(f"[graftmesh] {name}: tpu_size=1 — nothing to "
                      f"factor (pass --world N to search anyway)",
                      file=sys.stderr)
                continue
            # quiet: a config whose heads cannot factor the local virtual
            # mesh would otherwise print the very fold warning this tool
            # supersedes into its own ranked sheet
            traces = trace_config(cfg, name, steps=("train",), quiet=True)
            if "train" not in traces.steps:
                print(f"[graftmesh] {name}: train step failed to trace "
                      f"({traces.errors.get('train', '?')})",
                      file=sys.stderr)
                failed.append(name)
                continue
            try:
                result = mesh_search.search(
                    cfg, name, n_devices=args.world or None,
                    device_kind=args.device, traces=traces, raw=raw,
                    free_axes=free_axes)
            except ValueError as e:
                print(f"[graftmesh] {name}: {e}", file=sys.stderr)
                return 2
        results.append(result.as_json())
        if not args.as_json:
            print(_sheet_text(result))
        if args.emit:
            with quiet:
                _emit(result, traces, raw, args.emit)
        top_k = args.top_k or cfg.mesh_search_top_k
        bar = 1 if args.strict_check else top_k
        if args.check and result.hand_rank > bar:
            failed.append(name)
            print(f"[graftmesh] CHECK FAILED: {name} hand-written mesh "
                  f"ranks #{result.hand_rank} (> {bar}); searcher prefers "
                  f"{{{result.top.describe()}}}", file=sys.stderr)
    if args.as_json:
        print(json.dumps(results, indent=2))
    else:
        print(f"\n[graftmesh] total {time.time() - t0:.1f}s", file=sys.stderr)
    if failed:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
