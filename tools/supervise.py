#!/usr/bin/env python
"""Auto-resume supervisor: relaunch training across preemptions and crashes.

The reference assumed an operator (or a cron'd shell loop) would restart a
preempted TPU run; the framework's own resume machinery (verified
checkpoints + data-cursor sidecars, train/checkpoint.py) makes the restart
itself safe, so this closes the loop:

- **preemption** (exit ``EXIT_PREEMPTED`` = 83: SIGTERM/SIGINT handled, a
  grace checkpoint was cut) -> relaunch immediately, no backoff — spot
  reclamation is not a bug;
- **crash** (any other nonzero exit) -> relaunch with exponential backoff;
- **crash loop** (K consecutive exits with NO step progress, measured from
  ``metrics.jsonl`` and the verified-checkpoint manifests — never from the
  child's own claims) -> abort with ``EXIT_CRASH_LOOP`` = 85 so the
  orchestrator above sees a real failure instead of an infinite restart;
- progress resets both the failure count and the backoff.

Counters flow through the obs registry
(``hbnlp_supervisor_exits_total{outcome}``) along with cross-relaunch
goodput (``hbnlp_supervisor_goodput`` = productive seconds / wall seconds,
where only launch segments that advanced on-disk progress count as
productive), rendered to ``<model_path>/supervisor_metrics.prom`` after
every child exit and served live on ``--obs-port`` if given — so restarts
land in the same dashboard as the child's MFU.  Exit-code contract + drill
walkthrough: docs/reliability.md.

Usage:
  python tools/supervise.py --model-path runs/flagship -- \\
      python main.py --model configs/32big_mixer.json --run_mode train
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import logging
import os
import subprocess
import sys
import time
import typing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_light(name: str, relpath: str):
    """Load a stdlib-only module by FILE PATH, bypassing the
    ``homebrewnlp_tpu`` package __init__ (which imports jax via config.py).
    The supervisor must survive exactly the failures that kill the child —
    a broken jax/libtpu install must not take the relauncher down with it."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_registry = _load_light("hbnlp_obs_registry",
                        "homebrewnlp_tpu/obs/registry.py")
MetricsRegistry = _registry.MetricsRegistry
REGISTRY = _registry.REGISTRY

# the exit-code contract with homebrewnlp_tpu.reliability (which cannot be
# imported here without dragging in jax); pinned by a reliability_test
# assertion so the two definitions cannot drift
EXIT_PREEMPTED = 83
EXIT_GRACE_TIMEOUT = 84
EXIT_CRASH_LOOP = 85
# device telemetry halted on non-finite gradients (anomaly_policy="halt",
# docs/observability.md): crash semantics — relaunch with backoff so the
# child resumes from its last good checkpoint, but a distinct outcome label
EXIT_ANOMALY_HALT = 86

LOG = logging.getLogger("homebrewnlp_tpu.supervise")


def last_step_progress(model_path: str) -> int:
    """Newest training progress visible ON DISK: max of the last
    ``metrics.jsonl`` step and the newest checkpoint-manifest step.  -1
    before any progress.  Reads only JSON/dirnames — no jax, no orbax."""
    best = -1
    mpath = os.path.join(model_path, "metrics.jsonl")
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                for line in f:
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue  # torn tail line of a crashed writer
                    if "loss" in row and "step" in row:
                        best = max(best, int(row["step"]))
        except OSError as e:
            LOG.warning("cannot read %s: %r", mpath, e)
    ckpt = os.path.join(model_path, "ckpt")
    if os.path.isdir(ckpt):
        for fn in os.listdir(ckpt):
            if fn.startswith("manifest_") and fn.endswith(".json"):
                try:
                    best = max(best, int(fn[len("manifest_"):-len(".json")]))
                except ValueError:
                    continue
    return best


class Supervisor:
    """Relaunch policy around an injectable ``launch`` callable (a
    subprocess in production, an in-process train call in tests).

    ``progress`` is polled after every exit; only on-disk progress counts —
    a child that crashes before flushing anything reads as 'no progress'."""

    def __init__(self, launch: typing.Callable[[], int],
                 progress: typing.Callable[[], int], *,
                 max_failures_no_progress: int = 3,
                 backoff_base_s: float = 1.0, backoff_max_s: float = 60.0,
                 max_restarts: int = 0,
                 sleep: typing.Callable[[float], None] = time.sleep,
                 registry: typing.Optional[MetricsRegistry] = None,
                 metrics_path: typing.Optional[str] = None,
                 clock: typing.Callable[[], float] = time.monotonic):
        self.launch = launch
        self.progress = progress
        self.max_failures_no_progress = int(max_failures_no_progress)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.max_restarts = int(max_restarts)  # 0 = unlimited
        self.sleep = sleep
        self.registry = registry if registry is not None else REGISTRY
        self.metrics_path = metrics_path
        self.clock = clock
        self._exits = self.registry.counter(
            "hbnlp_supervisor_exits_total",
            "child exits seen by the supervisor, by outcome",
            labelnames=("outcome",))
        # goodput across relaunches (the in-run figure lives on the child's
        # own /metrics): wall covers backoff sleeps and dead children;
        # productive covers only launch segments that ADVANCED on-disk
        # progress — a restart loop reads as goodput -> 0 on the same
        # dashboard that shows the child's MFU
        self._t0 = self.clock()
        self._productive_s = 0.0
        self.registry.gauge(
            "hbnlp_supervisor_wall_seconds",
            "wall seconds since the supervisor started",
            fn=lambda: self.clock() - self._t0)
        self.registry.gauge(
            "hbnlp_supervisor_productive_seconds",
            "wall seconds inside launch segments that advanced on-disk "
            "progress", fn=lambda: self._productive_s)
        self.registry.gauge(
            "hbnlp_supervisor_goodput",
            "productive seconds / wall seconds across all relaunches",
            fn=self.goodput)
        self.restarts = 0

    def goodput(self) -> float:
        wall = self.clock() - self._t0
        return self._productive_s / wall if wall > 0 else 0.0

    def write_metrics(self) -> None:
        """Render the supervisor's registry to ``metrics_path`` (after every
        child exit and on return): restarts and goodput stay visible in the
        same dashboard as the child's MFU even between scrapes."""
        if not self.metrics_path:
            return
        try:
            os.makedirs(os.path.dirname(self.metrics_path) or ".",
                        exist_ok=True)
            with open(self.metrics_path, "w") as f:
                f.write(self.registry.render())
        except OSError as e:
            LOG.warning("could not persist supervisor metrics: %r", e)

    def run(self) -> int:
        failures_no_progress = 0
        backoff = self.backoff_base_s
        last = self.progress()
        while True:
            t_launch = self.clock()
            rc = self.launch()
            segment_s = self.clock() - t_launch
            now = self.progress()
            advanced = now > last
            last = max(last, now)
            if advanced:
                self._productive_s += segment_s
            if rc == 0:
                LOG.info("training completed cleanly at step %d "
                         "(%d restart(s), goodput %.3f)", last,
                         self.restarts, self.goodput())
                self._exits.labels(outcome="clean").inc()
                self.write_metrics()
                return 0
            preempted = rc == EXIT_PREEMPTED
            outcome = ("preemption" if preempted else
                       "anomaly_halt" if rc == EXIT_ANOMALY_HALT else
                       "crash")
            self._exits.labels(outcome=outcome).inc()
            # render AFTER the outcome counter: the on-disk file must show
            # this exit during the (possibly long) next child lifetime
            self.write_metrics()
            if advanced:
                failures_no_progress = 0
                backoff = self.backoff_base_s
            else:
                failures_no_progress += 1
                if failures_no_progress >= self.max_failures_no_progress:
                    LOG.error(
                        "crash loop: %d consecutive exits with no step "
                        "progress (stuck at step %d, last exit code %d); "
                        "aborting with %d", failures_no_progress, last, rc,
                        EXIT_CRASH_LOOP)
                    self._exits.labels(outcome="crash_loop_abort").inc()
                    self.write_metrics()
                    return EXIT_CRASH_LOOP
            self.restarts += 1
            if self.max_restarts and self.restarts > self.max_restarts:
                LOG.error("restart budget (%d) exhausted; passing through "
                          "exit code %d", self.max_restarts, rc)
                return rc
            if preempted:
                LOG.warning("preemption exit (%d): grace checkpoint cut at "
                            "step %d; relaunching (restart %d)", rc, last,
                            self.restarts)
            else:
                LOG.warning("crash exit %d at step %d; relaunching in %.1fs "
                            "(restart %d, %d/%d failures without progress)",
                            rc, last, backoff, self.restarts,
                            failures_no_progress,
                            self.max_failures_no_progress)
                self.sleep(backoff)
                backoff = min(backoff * 2.0, self.backoff_max_s)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        usage="supervise.py --model-path DIR [options] -- command ...")
    p.add_argument("--model-path", required=True,
                   help="the run's cfg.model_path (progress is read from "
                        "its metrics.jsonl + checkpoint manifests)")
    p.add_argument("--max-failures-no-progress", type=int, default=3,
                   help="K consecutive no-progress exits before the crash-"
                        "loop abort (exit %d)" % EXIT_CRASH_LOOP)
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="seconds before the first crash relaunch (doubles "
                        "up to --backoff-max; preemptions skip backoff)")
    p.add_argument("--backoff-max", type=float, default=60.0)
    p.add_argument("--max-restarts", type=int, default=0,
                   help="total relaunch budget (0 = unlimited)")
    p.add_argument("--obs-port", type=int, default=0,
                   help=">0: serve the supervisor's /metrics on "
                        "127.0.0.1:<port>")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command after '--'")
    args = p.parse_args(argv)
    cmd = list(args.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no training command given (append it after '--')")
    args.command = cmd
    return args


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s supervise %(levelname)s %(message)s")
    args = parse_args(argv)
    sup = Supervisor(
        lambda: subprocess.call(args.command),
        lambda: last_step_progress(args.model_path),
        max_failures_no_progress=args.max_failures_no_progress,
        backoff_base_s=args.backoff_base, backoff_max_s=args.backoff_max,
        max_restarts=args.max_restarts,
        metrics_path=os.path.join(args.model_path,
                                  "supervisor_metrics.prom"))
    server = None
    if args.obs_port:
        # the exporter import pulls the full package (and jax); degrade to
        # no endpoint rather than dying — supervision is the job here
        try:
            from homebrewnlp_tpu.obs.exporter import start_server
            server = start_server(args.obs_port, registry=sup.registry)
        except Exception as e:
            LOG.warning("--obs-port unavailable (%r); supervising without "
                        "a metrics endpoint", e)
    try:
        return sup.run()
    finally:
        sup.write_metrics()  # final render incl. the last exit's counters
        if server is not None:
            from homebrewnlp_tpu.obs.exporter import stop_server
            stop_server(server)


if __name__ == "__main__":
    sys.exit(main())
