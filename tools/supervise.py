#!/usr/bin/env python
"""Auto-resume supervisor: relaunch training across preemptions and crashes.

The reference assumed an operator (or a cron'd shell loop) would restart a
preempted TPU run; the framework's own resume machinery (verified
checkpoints + data-cursor sidecars, train/checkpoint.py) makes the restart
itself safe, so this closes the loop:

- **preemption** (exit ``EXIT_PREEMPTED`` = 83: SIGTERM/SIGINT handled, a
  grace checkpoint was cut) -> relaunch immediately, no backoff — spot
  reclamation is not a bug;
- **crash** (any other nonzero exit) -> relaunch with exponential backoff;
- **crash loop** (K consecutive exits with NO progress, measured from
  ``metrics.jsonl``, the verified-checkpoint manifests AND the
  reshard-restore marker — never from the child's own claims) -> abort with
  ``EXIT_CRASH_LOOP`` = 85 so the orchestrator above sees a real failure
  instead of an infinite restart;
- **peer lost** (exit ``EXIT_PEER_LOST`` = 87: the child observed a peer
  host's death or lost the coordinator, cut a checkpoint of its own healthy
  state, and exited) -> the per-host supervisors relaunch the **fleet in
  lockstep** through a shared ``--fleet-dir``: each supervisor that sees a
  peer's exit posted for the current generation SIGTERMs its own child
  (grace checkpoint, exit 83), every supervisor posts its child's exit and
  waits for the rest, then all relaunch together — no host spins alone
  against a dead collective;
- progress resets both the failure count and the backoff;
- crash backoff carries **jitter** (``--backoff-jitter``) so a fleet of
  per-host supervisors does not thundering-herd the coordinator after a
  shared outage.

Counters flow through the obs registry
(``hbnlp_supervisor_exits_total{outcome,rank}`` — every supervisor series
carries this host's rank, so fleets federate without collisions) along
with cross-relaunch goodput (``hbnlp_supervisor_goodput`` = productive
seconds / wall seconds, where only launch segments that advanced on-disk
progress count as productive), rendered to
``<model_path>/supervisor_metrics.prom`` (and, in a fleet,
``<fleet_dir>/obs/supervisor_r<rank>.prom``) after every child exit and
served live on ``--obs-port`` if given — in fleet mode that port serves
the FEDERATED ``/metrics`` + fleet ``/healthz`` built from every rank's
postings (docs/observability.md "Fleet observability") — so restarts land
in the same dashboard as the child's MFU.  Exit-code contract + drill
walkthrough: docs/reliability.md.

Usage:
  python tools/supervise.py --model-path runs/flagship -- \\
      python main.py --model configs/32big_mixer.json --run_mode train
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import logging
import os
import random
import re
import signal
import subprocess
import sys
import threading
import time
import typing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_light(name: str, relpath: str):
    """Load a stdlib-only module by FILE PATH, bypassing the
    ``homebrewnlp_tpu`` package __init__ (which imports jax via config.py).
    The supervisor must survive exactly the failures that kill the child —
    a broken jax/libtpu install must not take the relauncher down with it."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# declared-lock factories (stdlib-only by contract): loaded FIRST and
# published under the light alias so the standalone registry/fleet loads
# below can find the recorder through sys.modules — the supervisor's own
# locks then show up in HBNLP_SYNC_RECORD runs like everyone else's
_sync = _load_light("hbnlp_sync", "homebrewnlp_tpu/sync.py")
sys.modules.setdefault("hbnlp_sync", _sync)
make_lock = _sync.make_lock

_registry = _load_light("hbnlp_obs_registry",
                        "homebrewnlp_tpu/obs/registry.py")
MetricsRegistry = _registry.MetricsRegistry
REGISTRY = _registry.REGISTRY
# fleet observability (stdlib-only by contract, docs/observability.md
# "Fleet observability"): federated /metrics + fleet /healthz over the
# shared fleet dir, served by the SUPERVISOR so fleet visibility survives
# exactly the child failures being supervised
fleet_obs = _load_light("hbnlp_obs_fleet", "homebrewnlp_tpu/obs/fleet.py")

# the exit-code contract with homebrewnlp_tpu.reliability (which cannot be
# imported here without dragging in jax); pinned by a reliability_test
# assertion so the two definitions cannot drift
EXIT_PREEMPTED = 83
EXIT_GRACE_TIMEOUT = 84
EXIT_CRASH_LOOP = 85
# device telemetry halted on non-finite gradients (anomaly_policy="halt",
# docs/observability.md): crash semantics — relaunch with backoff so the
# child resumes from its last good checkpoint, but a distinct outcome label
EXIT_ANOMALY_HALT = 86
# the child observed a distributed failure (peer death, coordinator loss —
# reliability/dist.py), checkpointed its healthy state and exited: relaunch
# the FLEET in lockstep (no backoff; the fleet barrier is the pacing)
EXIT_PEER_LOST = 87

LOG = logging.getLogger("homebrewnlp_tpu.supervise")


def last_step_progress(model_path: str) -> int:
    """Newest training progress visible ON DISK: max of the last
    ``metrics.jsonl`` step and the newest checkpoint-manifest step.  -1
    before any progress.  Reads only JSON/dirnames — no jax, no orbax."""
    best = -1
    mpath = os.path.join(model_path, "metrics.jsonl")
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                for line in f:
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue  # torn tail line of a crashed writer
                    if "loss" in row and "step" in row:
                        best = max(best, int(row["step"]))
        except OSError as e:
            LOG.warning("cannot read %s: %r", mpath, e)
    ckpt = os.path.join(model_path, "ckpt")
    if os.path.isdir(ckpt):
        for fn in os.listdir(ckpt):
            if fn.startswith("manifest_") and fn.endswith(".json"):
                try:
                    best = max(best, int(fn[len("manifest_"):-len(".json")]))
                except ValueError:
                    continue
    return best


def reshard_restore_count(model_path: str) -> int:
    """Successful reshard restores recorded by train/checkpoint.py in
    ``ckpt/restore_marker*.json`` (monotonic count; multi-process children
    write per-rank ``_p<r>`` markers — take the max).  0 when absent."""
    ckpt = os.path.join(model_path, "ckpt")
    best = 0
    try:
        names = os.listdir(ckpt)
    except OSError:
        return 0
    for fn in names:
        if not (fn.startswith("restore_marker") and fn.endswith(".json")):
            continue
        try:
            with open(os.path.join(ckpt, fn)) as f:
                best = max(best, int(json.load(f).get("count", 0)))
        except (OSError, ValueError):
            continue
    return best


def progress_signature(model_path: str) -> typing.Tuple[int, int]:
    """On-disk progress as a comparable tuple: (last step, reshard-restore
    count).  A relaunch that advanced NO steps but successfully restored a
    checkpoint onto a new mesh shape still did real recovery work — without
    the second component, a restore-heavy elastic relaunch (each restore
    slower than the crash cadence) reads as 'no progress' and is
    misclassified as a crash loop (EXIT_CRASH_LOOP)."""
    return (last_step_progress(model_path), reshard_restore_count(model_path))


_EXIT_FILE_RE = re.compile(r"^exit_r(\d+)_g(\d+)\.json$")
_READY_FILE_RE = re.compile(r"^ready_r(\d+)_g(\d+)\.json$")


class FleetCoordinator:
    """Lockstep relaunch for N per-host supervisors over a shared directory.

    The shared filesystem is the one channel that still exists when the
    jax.distributed coordinator itself is the casualty.  Protocol, per
    launch *generation* g:

    1. while the child runs, a watcher thread polls for any PEER exit file
       ``exit_r<rank>_g>=g.json``; seeing one means that host's child is
       down for this generation — the watcher SIGTERMs our own child so it
       cuts a grace checkpoint instead of hanging in a dead collective;
    2. when our child exits, :meth:`post_exit` publishes its code;
    3. :meth:`await_peers` blocks (bounded by ``peer_timeout_s``) until
       every rank has posted for generation g — the relaunch barrier.  A
       supervisor that never posts (host gone entirely) is logged and
       skipped: the survivors relaunch DEGRADED rather than deadlock, and
       checkpoint resharding lets the smaller fleet actually resume.

    The starting generation is recovered from the HIGHEST generation any
    rank ever posted in the directory (plus one): a restarted supervisor
    rejoins the fleet at the right point, and a fresh run pointed at a
    stale ``--fleet-dir`` starts PAST the leftover postings instead of
    reading an old crash as a live peer failure and SIGTERMing its own
    healthy child."""

    def __init__(self, fleet_dir: str, rank: int, world_size: int, *,
                 peer_timeout_s: float = 300.0, poll_s: float = 0.2):
        self.dir = os.path.abspath(fleet_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.peer_timeout_s = float(peer_timeout_s)
        self.poll_s = float(poll_s)
        all_gens = [g for gens in self._scan().values() for g in gens]
        all_gens += [g for gens in self._scan(re_=_READY_FILE_RE).values()
                     for g in gens]
        # the generation counter is read by the FleetWatcher thread (its
        # peer_down polls) and the federation's /healthz callback while the
        # main loop advances it — all cross-thread reads go through
        # current_generation()
        self._lock = make_lock("tools.supervise.FleetCoordinator._lock")
        self.generation = (max(all_gens) + 1) if all_gens else 0
        #: ranks that missed a barrier entirely (no posting, no tombstone —
        #: host vanished): later barriers skip them until they post again,
        #: so one dead machine does not tax EVERY relaunch with the full
        #: peer timeout
        self._absent: typing.Set[int] = set()
        # we are alive: any tombstone bearing OUR rank is stale (a previous
        # run, or this supervisor's earlier life) — peers must resume
        # waiting for us at their barriers
        try:
            os.remove(os.path.join(self.dir, f"final_r{self.rank}.json"))
        except OSError:
            pass

    def _scan(self, min_gen: int = 0, re_: typing.Pattern = _EXIT_FILE_RE
              ) -> typing.Dict[int, typing.Dict[int, int]]:
        """{rank: {generation: exit_code}} from the shared dir for one
        posting kind (exit or ready).  Files below ``min_gen`` are filtered
        BY FILENAME before any open — peer_down/await poll this several
        times a second over what may be a network mount, and history can
        never match ``g >= generation``."""
        out: typing.Dict[int, typing.Dict[int, int]] = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for fn in names:
            m = re_.match(fn)
            if not m:
                continue
            r, g = int(m.group(1)), int(m.group(2))
            if g < min_gen:
                continue
            try:
                with open(os.path.join(self.dir, fn)) as f:
                    rc = int(json.load(f).get("rc", -1))
            except (OSError, ValueError):
                continue  # torn write: the poster retries/next poll sees it
            out.setdefault(r, {})[g] = rc
        return out

    def current_generation(self) -> int:
        with self._lock:
            return self.generation

    def peer_down(self) -> typing.Optional[int]:
        """Rank of a peer whose FAILED exit is posted for the current
        generation (its child is down while ours still runs), else None.
        Clean exits (rc 0) never trigger termination: a rank finishing the
        run slightly earlier than us must not cut our final steps short."""
        for r, gens in self._scan(self.current_generation()).items():
            if r == self.rank:
                continue
            if any(rc != 0 for rc in gens.values()):
                return r
        return None

    def watch_peers(self, on_peer_down: typing.Callable[[int], None]
                    ) -> "FleetWatcher":
        return FleetWatcher(self, on_peer_down)

    def _write_json(self, name: str, doc: dict) -> None:
        """Atomic posting, best-effort with a short retry: the fleet dir
        may be a network mount and every read path already tolerates
        OSError — a transient write hiccup must degrade to a logged miss
        (peers time out and skip us), never kill the supervisor, which is
        the one component built to survive exactly this weather."""
        path = os.path.join(self.dir, name)
        tmp = f"{path}.tmp.{os.getpid()}"
        for attempt in range(3):
            try:
                with open(tmp, "w") as f:
                    json.dump(doc, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                return
            except OSError as e:
                if attempt == 2:
                    LOG.error("could not post %s to the fleet dir (%r); "
                              "peers will treat this rank as silent until "
                              "the next posting succeeds", name, e)
                    return
                time.sleep(0.2 * (attempt + 1))

    def post_exit(self, rc: int) -> None:
        self._write_json(f"exit_r{self.rank}_g{self.current_generation()}"
                         f".json",
                         {"rc": int(rc), "wall_time": time.time()})

    def post_ready(self, rc: int) -> None:
        """Posted AFTER any backoff sleep, right before the barrier wait:
        the barrier keys on readiness-to-relaunch, not on death (exits post
        immediately so watchers react, but a rank sleeping a long crash
        backoff must keep holding its peers — releasing them early would
        burn their dist-init deadlines against an absent coordinator)."""
        self._write_json(f"ready_r{self.rank}_g{self.current_generation()}"
                         f".json",
                         {"rc": int(rc), "wall_time": time.time()})

    def post_final(self, rc: int) -> None:
        """Tombstone: this supervisor is exiting for good (clean completion,
        crash-loop abort, restart-budget exhaustion).  Surviving peers stop
        holding fleet barriers for this rank — without it, every later
        relaunch would pay the full peer timeout waiting for a rank whose
        supervisor no longer exists."""
        self._write_json(f"final_r{self.rank}.json",
                         {"rc": int(rc),
                          "generation": self.current_generation(),
                          "wall_time": time.time()})

    def _final_ranks(self) -> typing.Dict[int, int]:
        """{rank: final_rc} of supervisors that tombstoned themselves.
        Honored unconditionally: a rank that comes back to life deletes its
        own tombstone the moment its coordinator starts, so a standing one
        means that supervisor really is gone."""
        out: typing.Dict[int, int] = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for fn in names:
            m = re.match(r"^final_r(\d+)\.json$", fn)
            if not m:
                continue
            try:
                with open(os.path.join(self.dir, fn)) as f:
                    out[int(m.group(1))] = int(json.load(f).get("rc", -1))
            except (OSError, ValueError):
                continue
        return out

    def await_peers(self) -> typing.Dict[int, int]:
        """Block until every rank posted READY for this generation (or
        ``peer_timeout_s``); returns {rank: exit_code} for the ranks that
        did.  THE lockstep barrier: every supervisor leaves it only when
        the whole fleet finished its backoff sleeps, so the relaunched
        children meet a coordinator whose peers are all coming up too.
        Ranks that previously missed a barrier entirely (vanished host, no
        tombstone) are skipped until they post again — one dead machine
        must not tax every later relaunch with the full timeout."""
        deadline = time.monotonic() + self.peer_timeout_s
        want = set(range(self.world_size))
        gen = self.current_generation()
        while True:
            for r, rc in self._final_ranks().items():
                if r in want and r != self.rank:
                    # tombstoned: that supervisor exited for good (clean
                    # completion / crash-loop abort / budget exhaustion)
                    # and will never post again — do not hold the barrier
                    LOG.info("rank %d left the fleet permanently (final "
                             "rc %d); not holding the barrier for it", r, rc)
                    want.discard(r)
            seen: typing.Dict[int, int] = {}
            for r, gens in self._scan(gen, re_=_READY_FILE_RE).items():
                seen[r] = gens[max(gens)]
            self._absent -= set(seen)  # a vanished rank posting is back
            if want - self._absent <= set(seen):
                return seen
            if time.monotonic() >= deadline:
                missing = sorted(want - self._absent - set(seen))
                self._absent |= set(missing)
                LOG.error(
                    "fleet barrier (generation %d) expired after %.0fs; "
                    "rank(s) %s never posted readiness — relaunching "
                    "DEGRADED without them, and skipping them at later "
                    "barriers until they post again (supervision-only "
                    "fleets resume via checkpoint resharding; coordinator-"
                    "mode fleets need a restart with the new --world-size "
                    "— docs/reliability.md)",
                    gen, self.peer_timeout_s, missing)
                return seen
            time.sleep(self.poll_s)

    def advance(self) -> None:
        with self._lock:
            self.generation += 1
            gen = self.generation
        # prune OUR superseded postings (keep the previous generation —
        # peers may still be reading it): bounds the directory listing the
        # watcher polls several times a second for the run's whole lifetime
        for g in range(max(0, gen - 8), gen - 1):
            for fn in (f"exit_r{self.rank}_g{g}.json",
                       f"ready_r{self.rank}_g{g}.json"):
                try:
                    os.remove(os.path.join(self.dir, fn))
                except OSError:
                    pass


class FleetWatcher:
    """Background poll for peer exits during one child lifetime."""

    def __init__(self, fleet: FleetCoordinator,
                 on_peer_down: typing.Callable[[int], None]):
        self.fleet = fleet
        self.on_peer_down = on_peer_down
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-watch")
        self._thread.start()

    def _run(self) -> None:
        fired = False
        while not self._stop.wait(self.fleet.poll_s):
            r = self.fleet.peer_down()
            if r is None:
                continue
            if not fired:
                LOG.warning(
                    "peer rank %d posted an exit for generation %d while "
                    "our child still runs; terminating the child for the "
                    "lockstep fleet relaunch", r,
                    self.fleet.current_generation())
                fired = True
            # retry ONLY until one signal is delivered to a live child:
            # the first poll can race the launcher (Popen not started yet
            # -> nothing to signal), but repeating SIGTERM against a live
            # child would trip its GraceController's second-signal
            # escalation (forced exit 84, NO grace checkpoint) — exactly
            # the data loss the lockstep protocol exists to avoid
            try:
                delivered = self.on_peer_down(r)
            except Exception as e:  # pragma: no cover - defensive
                LOG.error("peer-down callback failed: %r", e)
                delivered = False
            if delivered:
                return  # the child's grace path owns the exit from here

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


class SubprocessLauncher:
    """The production ``launch`` callable: a subprocess the fleet watcher
    can terminate (SIGTERM -> the child's grace checkpoint -> exit 83)."""

    def __init__(self, cmd: typing.Sequence[str],
                 env: typing.Optional[dict] = None):
        self.cmd = list(cmd)
        self.env = env
        # the launcher runs on the supervisor's thread; terminate() is
        # called from the fleet watcher — the Popen handle crosses threads
        self._lock = make_lock("tools.supervise.SubprocessLauncher._lock")
        self._proc: typing.Optional[subprocess.Popen] = None

    def __call__(self, extra_env: typing.Optional[dict] = None) -> int:
        """``extra_env``: per-launch additions (the fleet generation) —
        an explicit parameter, so the caller never depends on mutating
        the exact dict instance the constructor captured."""
        env = self.env
        if extra_env:
            env = dict(env if env is not None else os.environ, **extra_env)
        proc = subprocess.Popen(self.cmd, env=env)
        with self._lock:
            self._proc = proc
        try:
            return proc.wait()
        finally:
            with self._lock:
                self._proc = None

    def terminate(self) -> bool:
        """SIGTERM the child if it is running; True when the signal was
        actually delivered (the watcher retries until then, and must stop
        after — a second SIGTERM escalates the child's grace shutdown to
        the forced no-checkpoint exit)."""
        with self._lock:
            p = self._proc
        if p is not None and p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
                return True
            except OSError:
                pass  # exited between poll and signal
        return False


class Supervisor:
    """Relaunch policy around an injectable ``launch`` callable (a
    subprocess in production, an in-process train call in tests).

    ``progress`` is polled after every exit; only on-disk progress counts —
    a child that crashes before flushing anything reads as 'no progress'."""

    def __init__(self, launch: typing.Callable[[], int],
                 progress: typing.Callable[[], typing.Any], *,
                 max_failures_no_progress: int = 3,
                 backoff_base_s: float = 1.0, backoff_max_s: float = 60.0,
                 backoff_jitter: float = 0.25,
                 max_restarts: int = 0,
                 sleep: typing.Callable[[float], None] = time.sleep,
                 registry: typing.Optional[MetricsRegistry] = None,
                 metrics_path: typing.Optional[str] = None,
                 clock: typing.Callable[[], float] = time.monotonic,
                 rng: typing.Callable[[], float] = random.random,
                 fleet: typing.Optional[FleetCoordinator] = None,
                 terminate: typing.Optional[
                     typing.Callable[[], None]] = None,
                 rank: int = 0,
                 suggest_mesh: typing.Optional[
                     typing.Callable[[int], typing.Any]] = None):
        self.launch = launch
        self.progress = progress
        # called with the surviving rank count when a fleet barrier expires
        # and the relaunch proceeds DEGRADED — wired to the mesh searcher
        # (mesh_suggestion below) so the log carries a searched layout for
        # the shrunken world instead of only the old fold warning
        self.suggest_mesh = suggest_mesh
        # every supervisor series carries this host's rank: N supervisors
        # sharing one fleet (or registry, or scrape target) must render N
        # distinguishable series, not N colliding unlabeled ones
        self.rank = int(rank)
        self.max_failures_no_progress = int(max_failures_no_progress)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        # +/- fraction applied to every crash backoff: a FLEET of per-host
        # supervisors sleeping the identical deterministic schedule after a
        # shared outage would reconnect to the coordinator in one synchronized
        # wave (satellite: thundering-herd hygiene, mirroring retry.py)
        self.backoff_jitter = float(backoff_jitter)
        self.max_restarts = int(max_restarts)  # 0 = unlimited
        self.sleep = sleep
        self.registry = registry if registry is not None else REGISTRY
        self.metrics_path = metrics_path
        self.clock = clock
        self.rng = rng
        self.fleet = fleet
        self.terminate = terminate
        self._exits = self.registry.counter(
            "hbnlp_supervisor_exits_total",
            "child exits seen by the supervisor, by outcome",
            labelnames=("outcome", "rank"))
        # goodput across relaunches (the in-run figure lives on the child's
        # own /metrics): wall covers backoff sleeps and dead children;
        # productive covers only launch segments that ADVANCED on-disk
        # progress — a restart loop reads as goodput -> 0 on the same
        # dashboard that shows the child's MFU
        self._t0 = self.clock()
        # written by run() on the supervisor thread, read by the metrics
        # server's scrape thread through the gauge callables below
        self._lock = make_lock("tools.supervise.Supervisor._lock")
        self._productive_s = 0.0
        self.registry.gauge(
            "hbnlp_supervisor_wall_seconds",
            "wall seconds since the supervisor started",
            labelnames=("rank",)).labels(rank=self.rank).set_function(
            lambda: self.clock() - self._t0)
        self.registry.gauge(
            "hbnlp_supervisor_productive_seconds",
            "wall seconds inside launch segments that advanced on-disk "
            "progress", labelnames=("rank",)).labels(
            rank=self.rank).set_function(self.productive_seconds)
        self.registry.gauge(
            "hbnlp_supervisor_goodput",
            "productive seconds / wall seconds across all relaunches",
            labelnames=("rank",)).labels(rank=self.rank).set_function(
            self.goodput)
        self.restarts = 0

    def productive_seconds(self) -> float:
        with self._lock:
            return self._productive_s

    def goodput(self) -> float:
        wall = self.clock() - self._t0
        return self.productive_seconds() / wall if wall > 0 else 0.0

    def write_metrics(self) -> None:
        """Render the supervisor's registry to ``metrics_path`` (after every
        child exit and on return): restarts and goodput stay visible in the
        same dashboard as the child's MFU even between scrapes.  In a fleet,
        the same render also lands at
        ``<fleet_dir>/obs/supervisor_r<rank>.prom`` — every series already
        carries this host's ``rank`` label, so N supervisors sharing the
        fleet dir render N distinct per-rank files that federate cleanly
        instead of N colliding unlabeled ones."""
        text = None
        if self.metrics_path:
            try:
                os.makedirs(os.path.dirname(self.metrics_path) or ".",
                            exist_ok=True)
                text = self.registry.render()
                with open(self.metrics_path, "w") as f:
                    f.write(text)
            except OSError as e:
                LOG.warning("could not persist supervisor metrics: %r", e)
        if self.fleet is not None:
            try:
                d = fleet_obs.obs_dir(self.fleet.dir)
                os.makedirs(d, exist_ok=True)
                path = os.path.join(d, f"supervisor_r{self.rank}.prom")
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    f.write(text if text is not None
                            else self.registry.render())
                os.replace(tmp, path)
            except OSError as e:
                LOG.warning("could not post supervisor metrics to the "
                            "fleet dir: %r", e)

    def _on_peer_down(self, peer_rank: int) -> bool:
        """Returns True once a termination signal reached the live child
        (the fleet watcher stops retrying at that point)."""
        if self.terminate is not None:
            return bool(self.terminate())
        return False

    def _fleet_barrier(self, rc: int) -> None:
        """Hold at the fleet barrier until every rank posted READINESS (or
        timed out) — the lockstep relaunch point.  The exit itself was
        posted the moment the child died (peers' watchers key off it);
        readiness posts here, after any backoff sleep, so the whole fleet
        leaves the barrier together."""
        self.fleet.post_ready(rc)
        peers = self.fleet.await_peers()
        others = {r: c for r, c in peers.items() if r != self.fleet.rank}
        LOG.info("fleet generation %d complete: own exit %d, peers %s",
                 self.fleet.current_generation(), rc,
                 others or "(none posted)")
        if len(peers) < self.fleet.world_size and self.suggest_mesh is not None:
            # DEGRADED relaunch: some rank never posted readiness — consult
            # the mesh searcher for the shrunken world before relaunching,
            # best-effort (the suggestion is a log line, never a blocker)
            try:
                self.suggest_mesh(len(peers))
            except Exception as e:
                LOG.warning("degraded-resume mesh suggestion failed: %r", e)
        self.fleet.advance()

    def run(self) -> int:
        failures_no_progress = 0
        backoff = self.backoff_base_s
        last = self.progress()
        while True:
            watcher = (self.fleet.watch_peers(self._on_peer_down)
                       if self.fleet is not None else None)
            t_launch = self.clock()
            try:
                rc = self.launch()
            finally:
                if watcher is not None:
                    watcher.stop()
            segment_s = self.clock() - t_launch
            now = self.progress()
            advanced = now > last
            last = max(last, now)
            if advanced:
                with self._lock:
                    self._productive_s += segment_s
            if rc == 0:
                LOG.info("training completed cleanly at %s "
                         "(%d restart(s), goodput %.3f)", last,
                         self.restarts, self.goodput())
                self._exits.labels(outcome="clean", rank=self.rank).inc()
                self.write_metrics()
                if self.fleet is not None:
                    # post so peers never block on us, but do NOT hold the
                    # barrier ourselves — there is nothing left to relaunch
                    self.fleet.post_exit(rc)
                    self.fleet.post_final(rc)
                return 0
            if self.fleet is not None:
                # publish the death IMMEDIATELY: peers' watchers key off it
                # to stop their own children instead of hanging in a dead
                # collective (the barrier wait comes later, after backoff)
                self.fleet.post_exit(rc)
            preempted = rc == EXIT_PREEMPTED
            peer_lost = rc == EXIT_PEER_LOST
            outcome = ("preemption" if preempted else
                       "peer_lost" if peer_lost else
                       "anomaly_halt" if rc == EXIT_ANOMALY_HALT else
                       "crash")
            self._exits.labels(outcome=outcome, rank=self.rank).inc()
            # render AFTER the outcome counter: the on-disk file must show
            # this exit during the (possibly long) next child lifetime
            self.write_metrics()
            if advanced:
                failures_no_progress = 0
                backoff = self.backoff_base_s
            else:
                failures_no_progress += 1
                if failures_no_progress >= self.max_failures_no_progress:
                    LOG.error(
                        "crash loop: %d consecutive exits with no "
                        "progress (stuck at %s, last exit code %d); "
                        "aborting with %d", failures_no_progress, last, rc,
                        EXIT_CRASH_LOOP)
                    self._exits.labels(outcome="crash_loop_abort",
                                       rank=self.rank).inc()
                    self.write_metrics()
                    if self.fleet is not None:
                        # exit already posted above; the tombstone tells
                        # peers we are gone for good
                        self.fleet.post_final(EXIT_CRASH_LOOP)
                    return EXIT_CRASH_LOOP
            self.restarts += 1
            if self.max_restarts and self.restarts > self.max_restarts:
                LOG.error("restart budget (%d) exhausted; passing through "
                          "exit code %d", self.max_restarts, rc)
                if self.fleet is not None:
                    self.fleet.post_final(rc)  # exit already posted above
                return rc
            if preempted or peer_lost:
                LOG.warning("%s exit (%d): checkpoint cut at %s; "
                            "relaunching%s (restart %d)",
                            "preemption" if preempted else "peer-lost", rc,
                            last,
                            " the fleet in lockstep" if peer_lost else "",
                            self.restarts)
            else:
                d = backoff
                if self.backoff_jitter:
                    d *= 1.0 + self.backoff_jitter * (2.0 * self.rng() - 1.0)
                LOG.warning("crash exit %d at %s; relaunching in %.1fs "
                            "(restart %d, %d/%d failures without progress)",
                            rc, last, d, self.restarts,
                            failures_no_progress,
                            self.max_failures_no_progress)
                self.sleep(max(0.0, d))
                backoff = min(backoff * 2.0, self.backoff_max_s)
            if self.fleet is not None:
                # the barrier is the LAST thing before relaunch — backoff
                # sleeps happen before it, so one host's long crash backoff
                # cannot make peers leave early and burn their dist-init
                # deadline against a coordinator that is still asleep
                self._fleet_barrier(rc)


def mesh_suggestion(config_path: str, world_devices: int, *,
                    run: typing.Callable = subprocess.run,
                    timeout_s: float = 180.0) -> typing.Optional[dict]:
    """Ask the mesh searcher for the degraded world's layout — in a
    SUBPROCESS (tools/graftmesh.py), because the supervisor must stay
    loadable on a broken jax install.  Best-effort: logs the searcher's
    top pick + the hand mesh's rank and returns the parsed sheet, or None
    (with a warning) on any failure."""
    cmd = [sys.executable,
           os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "graftmesh.py"),
           "--config", config_path, "--world", str(int(world_devices)),
           "--json"]
    try:
        r = run(cmd, capture_output=True, text=True, timeout=timeout_s)
        docs = json.loads(r.stdout) if (r.stdout or "").strip() else []
        if r.returncode != 0 or not docs:
            raise RuntimeError(f"rc={r.returncode}: "
                               f"{(r.stderr or '')[-500:]}")
        doc = docs[0]
        top = doc["candidates"][0]
        LOG.warning(
            "fleet degraded to %d device(s): mesh search suggests %s "
            "(predicted %.3f ms/step on %s; hand-written mesh ranks #%d) "
            "— %s", world_devices, top["axes"],
            top["step_time_s"] * 1e3, doc["device"], doc["hand_rank"],
            config_path)
        return doc
    except Exception as e:
        LOG.warning("degraded-resume mesh suggestion unavailable "
                    "(%s: %s); the child will fold axes as before",
                    type(e).__name__, e)
        return None


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        usage="supervise.py --model-path DIR [options] -- command ...")
    p.add_argument("--model-path", required=True,
                   help="the run's cfg.model_path (progress is read from "
                        "its metrics.jsonl + checkpoint manifests)")
    p.add_argument("--max-failures-no-progress", type=int, default=3,
                   help="K consecutive no-progress exits before the crash-"
                        "loop abort (exit %d)" % EXIT_CRASH_LOOP)
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="seconds before the first crash relaunch (doubles "
                        "up to --backoff-max; preemptions skip backoff)")
    p.add_argument("--backoff-max", type=float, default=60.0)
    p.add_argument("--backoff-jitter", type=float, default=0.25,
                   help="+/- fraction of jitter on every crash backoff so "
                        "a fleet of supervisors does not thundering-herd "
                        "the coordinator after a shared outage (0 = exact "
                        "exponential)")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="total relaunch budget (0 = unlimited)")
    p.add_argument("--obs-port", type=int, default=0,
                   help=">0: serve the supervisor's /metrics on "
                        "127.0.0.1:<port>")
    p.add_argument("--rank", type=int, default=0,
                   help="this host's rank; exported to the child as "
                        "HBNLP_DIST_PROCESS_ID (reliability/dist.py)")
    p.add_argument("--world-size", type=int, default=1,
                   help="fleet size; >1 enables lockstep fleet relaunch "
                        "(requires --fleet-dir) and exports "
                        "HBNLP_DIST_NUM_PROCESSES to the child")
    p.add_argument("--coordinator", type=str, default="",
                   help="host:port of the jax.distributed coordinator "
                        "(rank 0's address); exported to the child as "
                        "HBNLP_DIST_COORDINATOR")
    p.add_argument("--fleet-dir", type=str, default="",
                   help="SHARED directory the per-host supervisors "
                        "coordinate lockstep relaunches through "
                        "(exit-code postings + relaunch barrier)")
    p.add_argument("--peer-timeout", type=float, default=300.0,
                   help="seconds to hold the fleet relaunch barrier for a "
                        "peer supervisor's exit posting before relaunching "
                        "degraded without it")
    p.add_argument("--suggest-mesh-config", type=str, default="",
                   help="config JSON to run the mesh searcher on when a "
                        "fleet relaunch proceeds DEGRADED (tools/"
                        "graftmesh.py in a subprocess; logs the searched "
                        "layout for the shrunken world)")
    p.add_argument("--devices-per-host", type=int, default=1,
                   help="accelerator devices each rank contributes — "
                        "scales the surviving rank count into the device "
                        "world the mesh searcher factors")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command after '--'")
    args = p.parse_args(argv)
    if args.world_size > 1 and not args.fleet_dir:
        p.error("--world-size > 1 requires --fleet-dir (a directory shared "
                "by every host's supervisor)")
    if not 0 <= args.rank < max(1, args.world_size):
        p.error(f"--rank {args.rank} out of range for --world-size "
                f"{args.world_size}")
    cmd = list(args.command)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no training command given (append it after '--')")
    args.command = cmd
    return args


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s supervise %(levelname)s %(message)s")
    args = parse_args(argv)
    env = dict(os.environ)
    fleet = None
    if args.world_size > 1:
        if args.coordinator:
            # per-host rank/coordinator plumbing: the child's
            # reliability.dist reads these env vars, so ONE config file
            # serves every host.  Without --coordinator the fleet is
            # supervision-only (lockstep relaunch, no jax.distributed) —
            # the chaos-multihost drill mode.
            env["HBNLP_DIST_PROCESS_ID"] = str(args.rank)
            env["HBNLP_DIST_NUM_PROCESSES"] = str(args.world_size)
            env["HBNLP_DIST_COORDINATOR"] = args.coordinator
        fleet = FleetCoordinator(args.fleet_dir, args.rank, args.world_size,
                                 peer_timeout_s=args.peer_timeout)
        # fleet-obs identity plumbing (docs/observability.md "Fleet
        # observability"): the child posts step timestamps / metrics
        # snapshots / traces under <fleet_dir>/obs as this rank — injected
        # even for supervision-only fleets, where HBNLP_DIST_* stays unset
        env[fleet_obs.ENV_FLEET_DIR] = fleet.dir
        env[fleet_obs.ENV_FLEET_RANK] = str(args.rank)
        env[fleet_obs.ENV_FLEET_WORLD] = str(args.world_size)

    def launch() -> int:
        if fleet is None:
            return launcher()
        # per-launch: the child's /healthz identity block, run-start
        # marker, and step posts name the generation that launched it
        return launcher(extra_env={
            fleet_obs.ENV_FLEET_GENERATION: str(fleet.current_generation())})

    launcher = SubprocessLauncher(args.command, env=env)
    sup = Supervisor(
        launch,
        lambda: progress_signature(args.model_path),
        max_failures_no_progress=args.max_failures_no_progress,
        backoff_base_s=args.backoff_base, backoff_max_s=args.backoff_max,
        backoff_jitter=args.backoff_jitter,
        max_restarts=args.max_restarts,
        metrics_path=os.path.join(args.model_path,
                                  "supervisor_metrics.prom"),
        fleet=fleet, terminate=launcher.terminate, rank=args.rank,
        suggest_mesh=(
            (lambda ranks: mesh_suggestion(
                args.suggest_mesh_config,
                ranks * max(1, args.devices_per_host)))
            if args.suggest_mesh_config else None))
    server = None
    if args.obs_port and fleet is not None:
        # fleet mode: serve the FEDERATED view — per-rank child +
        # supervisor series (rank-labeled) with fleet aggregates, plus the
        # skew/straggler gauges, and a fleet /healthz.  Stdlib-only
        # (obs/fleet.py), so a broken jax install cannot take it down.
        federation = fleet_obs.FleetFederation(
            args.fleet_dir, own_registry=sup.registry, own_rank=args.rank,
            world_size=args.world_size,
            identity_doc={"rank": args.rank,
                          "world_size": args.world_size,
                          "coordinator": args.coordinator},
            generation=fleet.current_generation)
        try:
            server = fleet_obs.serve_federation(args.obs_port, federation)
        except OSError as e:
            LOG.warning("--obs-port unavailable (%r); supervising without "
                        "a federated endpoint", e)
    elif args.obs_port:
        # single-host: the exporter import pulls the full package (and
        # jax); degrade to no endpoint rather than dying — supervision is
        # the job here
        try:
            from homebrewnlp_tpu.obs.exporter import start_server
            server = start_server(args.obs_port, registry=sup.registry)
        except Exception as e:
            LOG.warning("--obs-port unavailable (%r); supervising without "
                        "a metrics endpoint", e)
    try:
        return sup.run()
    finally:
        sup.write_metrics()  # final render incl. the last exit's counters
        if server is not None:
            if fleet is not None:
                fleet_obs.stop_federation(server)
            else:
                from homebrewnlp_tpu.obs.exporter import stop_server
                stop_server(server)


if __name__ == "__main__":
    sys.exit(main())
