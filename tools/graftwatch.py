#!/usr/bin/env python
"""graftwatch: live serving-health watcher — SLO burn rates, alert state,
flight-recorder bundles — over a running server's observability surface.

Tails the obs exporter (``/healthz`` + ``/metrics``) and the REST debug
endpoints (``/debugz/flight``, ``POST /debugz/dump``) that serve/rest.py
exposes, and renders the operator's one-glance view: per-objective SLO
burn rates across the fast/slow windows (obs/slo_alerts.py), which alerts
are FIRING, request/error throughput deltas between scrapes, and the
flight recorder's ring occupancy.  The on-call loop in one command
instead of four curls.

Modes:
  one-shot   scrape once, print the table (default); ``--json`` emits the
             raw snapshot document instead
  --watch    rescrape every ``--interval`` seconds; rates (req/s, err/s)
             come from counter DELTAS between consecutive scrapes, so the
             numbers are the live rate, not the lifetime average
  --check    CI/probe gate: exit 1 when any SLO alert is firing or the
             server reports itself stalled, 0 when healthy
  --dump     ask the server for a flight bundle (``POST /debugz/dump``),
             validate it against the bundle schema
             (obs/flight.py ``validate_bundle``), and write it to the
             given local path — incident capture from the operator's seat

Usage:
  python tools/graftwatch.py --metrics-url http://127.0.0.1:9090
  python tools/graftwatch.py --metrics-url ... --url http://127.0.0.1:8000 \
      --watch --interval 5
  python tools/graftwatch.py --metrics-url ... --check
  python tools/graftwatch.py --url ... --dump incident.json

Exit codes: 0 ok; 1 when ``--check`` finds a firing alert / stall, or a
``--dump`` bundle fails validation; 2 usage/connection errors.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import typing
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from homebrewnlp_tpu.obs.flight import validate_bundle  # noqa: E402


def _get_json(url: str, timeout_s: float = 10.0) -> dict:
    req = urllib.request.Request(url)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        # /healthz answers 503 WITH a body when stalled — that body is the
        # signal, not a transport failure
        body = e.read().decode()
        try:
            return json.loads(body)
        except ValueError:
            raise e


def _get_text(url: str, timeout_s: float = 10.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return r.read().decode()


def parse_counters(metrics_text: str
                   ) -> typing.Dict[str, typing.List[tuple]]:
    """{sample name: [(labels, value), ...]} via the repo's one prom-text
    parser (graftload re-exports the same view)."""
    import graftload
    return graftload.parse_prom(metrics_text)


def scrape(metrics_url: typing.Optional[str],
           rest_url: typing.Optional[str],
           timeout_s: float = 10.0) -> dict:
    """One snapshot: healthz (status + alerts block), burn-rate gauges +
    request counters from /metrics, and the flight recorder's own status
    (``/debugz/flight`` on the REST port).  Every section is best-effort
    except the first URL that was explicitly given — a watcher that can't
    reach anything it was pointed at should fail loudly, not render an
    empty table."""
    snap: dict = {"wall_time_s": time.time()}
    if metrics_url:
        base = metrics_url.rstrip("/")
        snap["healthz"] = _get_json(base + "/healthz", timeout_s)
        metrics = parse_counters(_get_text(base + "/metrics", timeout_s))
        snap["burn_rates"] = [
            {"objective": labels.get("objective", "?"),
             "window": labels.get("window", "?"), "rate": value}
            for labels, value in metrics.get("hbnlp_slo_burn_rate", [])]
        snap["requests_total"] = sum(
            v for _, v in metrics.get("hbnlp_serve_requests_total", []))
        snap["errors_total"] = sum(
            v for labels, v in metrics.get("hbnlp_serve_requests_total", [])
            if labels.get("status", "").startswith("5"))
        for labels, v in metrics.get("hbnlp_serve_inflight", []):
            snap["inflight"] = v
        # per-tenant usage families (obs/usage.py collector; absent when
        # usage_top_k=0) — the raw material of the --watch usage pane
        tenant_tokens: typing.Dict[str, float] = {}
        for labels, v in metrics.get("hbnlp_serve_tokens_total", []):
            name = labels.get("tenant", "?")
            tenant_tokens[name] = tenant_tokens.get(name, 0.0) + v
        if tenant_tokens:
            snap["tenant_tokens"] = tenant_tokens
        tenant_errors: typing.Dict[str, float] = {}
        for labels, v in metrics.get("hbnlp_serve_tenant_errors_total", []):
            name = labels.get("tenant", "?")
            tenant_errors[name] = tenant_errors.get(name, 0.0) + v
        if tenant_errors:
            snap["tenant_errors"] = tenant_errors
    if rest_url:
        try:
            snap["flight"] = _get_json(
                rest_url.rstrip("/") + "/debugz/flight", timeout_s)
        except Exception as e:  # noqa: BLE001 - recorder may be off
            snap["flight"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    return snap


def deltas(prev: dict, cur: dict) -> dict:
    """Scrape-to-scrape rates: req/s and err/s from counter deltas.  The
    honest live rate — lifetime counters average away the incident."""
    dt = cur["wall_time_s"] - prev["wall_time_s"]
    if dt <= 0:
        return {}
    out = {}
    for key, name in (("requests_total", "req_per_s"),
                      ("errors_total", "err_per_s")):
        a, b = prev.get(key), cur.get(key)
        if a is not None and b is not None:
            out[name] = round(max(0.0, b - a) / dt, 3)
    # per-tenant pane: live tokens/s plus each tenant's share of the
    # error-budget burn this window (who is eating the SLO).  Negative
    # deltas — a tenant re-admitted after a top-K fold restarts its
    # series at 0 (obs/usage.py) — clamp to 0: this is a live view, not
    # the reconciliation arm
    a_tok = prev.get("tenant_tokens") or {}
    b_tok = cur.get("tenant_tokens") or {}
    a_err = prev.get("tenant_errors") or {}
    b_err = cur.get("tenant_errors") or {}
    err_total = sum(max(0.0, b_err.get(n, 0.0) - a_err.get(n, 0.0))
                    for n in set(a_err) | set(b_err))
    tenants = {}
    for name in set(a_tok) | set(b_tok) | set(a_err) | set(b_err):
        d_tok = max(0.0, b_tok.get(name, 0.0) - a_tok.get(name, 0.0))
        row = {"tok_per_s": round(d_tok / dt, 3)}
        if err_total > 0:
            d_err = max(0.0, b_err.get(name, 0.0) - a_err.get(name, 0.0))
            row["burn_share"] = round(d_err / err_total, 3)
        tenants[name] = row
    if tenants:
        out["tenants"] = tenants
    return out


def verdict(snap: dict) -> typing.Tuple[bool, typing.List[str]]:
    """The ``--check`` gate as a pure function: (ok, reasons).  Fails on
    any firing SLO alert or a stalled server; a missing alerts block
    (no objectives configured) is healthy, not unknown."""
    reasons = []
    hz = snap.get("healthz") or {}
    if hz.get("status") == "stalled":
        reasons.append("server reports status=stalled")
    alerts = hz.get("alerts") or {}
    for key in alerts.get("firing", ()):
        reasons.append(f"SLO alert firing: {key}")
    return not reasons, reasons


def fetch_dump(rest_url: str, out_path: str, timeout_s: float = 30.0
               ) -> typing.Tuple[dict, typing.List[str]]:
    """POST /debugz/dump, validate the returned bundle, write it locally.
    Returns ``(response document, validation problems)``."""
    req = urllib.request.Request(
        rest_url.rstrip("/") + "/debugz/dump", data=b"{}",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        doc = json.loads(r.read().decode())
    bundle = doc.get("bundle") or {}
    problems = list(doc.get("problems") or ()) or validate_bundle(bundle)
    with open(out_path, "w") as f:
        json.dump(bundle, f, sort_keys=True)
    return doc, problems


def render(snap: dict, rates: typing.Optional[dict] = None) -> str:
    """Human one-glance block: status line, burn-rate table, flight ring."""
    lines = []
    hz = snap.get("healthz") or {}
    status = hz.get("status", "?")
    head = f"status={status}"
    if snap.get("inflight") is not None:
        head += f" inflight={int(snap['inflight'])}"
    if snap.get("requests_total") is not None:
        head += (f" requests={int(snap['requests_total'])}"
                 f" errors={int(snap.get('errors_total') or 0)}")
    if rates:
        head += "".join(f" {k}={v}" for k, v in sorted(rates.items()))
    lines.append(head)
    alerts = hz.get("alerts") or {}
    for row in alerts.get("alerts", ()):
        burns = " ".join(f"{w}={r}" for w, r in
                         sorted((row.get("burn_rates") or {}).items()))
        state = "FIRING" if row.get("firing") else "ok"
        lines.append(f"  slo {row['objective']:<16} {state:<6} {burns}")
    if not alerts.get("alerts"):
        for row in snap.get("burn_rates", ()):
            lines.append(f"  burn {row['objective']}/{row['window']}: "
                         f"{row['rate']}")
    tenants = (rates or {}).get("tenants") or {}
    if tenants:  # top tenants by live tokens/s + their burn contribution
        ranked = sorted(tenants.items(),
                        key=lambda kv: (-kv[1].get("tok_per_s", 0.0),
                                        kv[0]))[:5]
        for name, row in ranked:
            line = f"  tenant {name:<16} tok/s={row.get('tok_per_s', 0.0)}"
            if row.get("burn_share") is not None:
                line += f" burn_share={row['burn_share']}"
            lines.append(line)
    fl = snap.get("flight")
    if isinstance(fl, dict) and "error" not in fl:
        lines.append(f"  flight: spans={fl.get('n_spans')} "
                     f"requests={fl.get('n_requests')} "
                     f"dumps={len(fl.get('dumps') or ())}")
    return "\n".join(lines)


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--metrics-url", default="",
                    help="obs exporter base URL (/healthz + /metrics)")
    ap.add_argument("--url", default="",
                    help="REST server base URL (/debugz/flight, --dump)")
    ap.add_argument("--watch", action="store_true",
                    help="rescrape every --interval seconds until ^C")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--count", type=int, default=0,
                    help="with --watch: stop after N scrapes (0 = forever)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when any SLO alert fires or the server "
                         "is stalled")
    ap.add_argument("--dump", default="",
                    help="fetch + validate a flight bundle, write it here "
                         "(needs --url)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw snapshot as one JSON document")
    args = ap.parse_args(argv)
    if not args.metrics_url and not args.url:
        print("graftwatch: need --metrics-url and/or --url",
              file=sys.stderr)
        return 2
    if args.dump and not args.url:
        print("graftwatch: --dump needs --url", file=sys.stderr)
        return 2
    try:
        if args.dump:
            doc, problems = fetch_dump(args.url, args.dump)
            print(f"bundle -> {args.dump} (server path: "
                  f"{doc.get('path')})")
            for p in problems:
                print(f"  INVALID: {p}", file=sys.stderr)
            if problems:
                return 1
        prev = None
        n = 0
        while True:
            snap = scrape(args.metrics_url or None, args.url or None)
            rates = deltas(prev, snap) if prev else None
            if args.json:
                print(json.dumps(dict(snap, rates=rates or {}),
                                 sort_keys=True))
            else:
                print(render(snap, rates))
            n += 1
            if not args.watch or (args.count and n >= args.count):
                break
            prev = snap
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0
    except (OSError, ValueError) as e:
        print(f"graftwatch: {e}", file=sys.stderr)
        return 2
    if args.check:
        ok, reasons = verdict(snap)
        for r in reasons:
            print(f"CHECK FAILED: {r}", file=sys.stderr)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
