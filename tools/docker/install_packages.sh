#!/bin/bash
set -euvo pipefail
export DEBIAN_FRONTEND=noninteractive
apt-get update
apt-get install -y python3 python3-pip ffmpeg libgl-dev git
python3 -m pip install -U pip
pip3 install -r requirements.txt
apt-get clean
rm -rf /var/lib/apt/lists/*
