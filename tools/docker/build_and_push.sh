#!/bin/bash
# Build the video-worker image from the repo root and push it; pass the image
# tag as $1 (reference scripts/build_and_push.sh).
set -ex
TAG="${1:?usage: build_and_push.sh <registry/image:tag>}"
ROOT="$(dirname "$0")/../.."
cp "$ROOT"/tools/video2tfrecord.py "$ROOT"/tools/manifest.py "$(dirname "$0")/"
cp -r "$ROOT"/homebrewnlp_tpu "$(dirname "$0")/homebrewnlp_tpu"
docker build -t "$TAG" "$(dirname "$0")"
docker push "$TAG"
