#!/usr/bin/env python
"""Compile-time ratchet: fail on a >20% compile_and_warmup_s regression.

The r04 -> r05 bench round slid compile+warmup from 79 s to 135 s with
nothing guarding it (ROADMAP "Raw speed").  bench.py now records
``compile_and_warmup_s`` per workload and evaluates it against the
committed per-device budgets in ``bench_compile_baseline.json``; this tool
re-runs the exact same evaluation (``bench.evaluate_compile_budget``) over
a recorded bench line so CI can reject a regressing BENCH_r*.json — the
slide cannot land silently again.

Usage:
  python tools/compile_ratchet.py                  # newest BENCH_r*.json
  python tools/compile_ratchet.py --bench FILE     # a specific bench line
  python tools/compile_ratchet.py --max-ratio 1.5  # override the tolerance

Exit code 1 when any workload exceeds its budget, 0 otherwise (including
when no bench line or no budget for the line's device kind exists — absence
is not a regression; the budget self-records on first contact with a new
device kind, see bench.main).
"""
import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def newest_bench_file() -> str:
    """The highest-numbered committed BENCH_r*.json (the driver's record of
    the latest bench round)."""
    def round_no(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1
    files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")),
                   key=round_no)
    return files[-1] if files else ""


def extract_record(path: str) -> dict:
    """The bench JSON line from either a raw line file or the driver's
    BENCH_r*.json wrapper ({"parsed": {...}} / {"tail": "...{line}\\n"})."""
    with open(path) as f:
        doc = json.load(f)
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        return doc["parsed"]
    if "workloads" in doc:
        return doc
    tail = doc.get("tail", "")
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "workloads" in rec:
                return rec
    return {}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--bench", default="",
                   help="bench JSON (line or BENCH_r*.json); default: the "
                        "newest committed BENCH_r*.json")
    p.add_argument("--baseline", default=os.path.join(
        REPO, "bench_compile_baseline.json"))
    p.add_argument("--max-ratio", type=float, default=None,
                   help="tolerated compile_and_warmup_s ratio "
                        "(default: bench.COMPILE_BUDGET_RATIO = 1.2)")
    args = p.parse_args(argv)

    from bench import COMPILE_BUDGET_RATIO, evaluate_compile_budget
    max_ratio = args.max_ratio or COMPILE_BUDGET_RATIO

    path = args.bench or newest_bench_file()
    if not path or not os.path.exists(path):
        print("compile-ratchet: no bench record found; nothing to check")
        return 0
    record = extract_record(path)
    workloads = record.get("workloads") or {}
    device = record.get("device", "")
    if not workloads:
        print(f"compile-ratchet: no workload rows in {path}; nothing to "
              "check")
        return 0
    with open(args.baseline) as f:
        budgets = json.load(f).get(device, {})
    if not budgets:
        print(f"compile-ratchet: no committed budget for device "
              f"{device!r}; record one in {os.path.basename(args.baseline)}")
        return 0

    rows, ok = evaluate_compile_budget(workloads, budgets, max_ratio)
    for nm, b in rows.items():
        mark = "ok  " if b["pass"] else "FAIL"
        print(f"{mark} {nm}: compile_and_warmup "
              f"{workloads[nm].get('compile_and_warmup_s')}s vs budget "
              f"{b['baseline_s']}s (ratio {b['ratio']}, max {max_ratio})")
    if not rows:
        print("compile-ratchet: no comparable rows (missing "
              "compile_and_warmup_s or budgets)")
    if not ok:
        print(f"compile-ratchet: REGRESSION — compile+warmup exceeded "
              f"{max_ratio}x its committed budget ({path}).  If the "
              f"regression is intended, update bench_compile_baseline.json "
              f"with the new figure and justify it in docs/performance.md")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
