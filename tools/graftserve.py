#!/usr/bin/env python
"""Serving fleet supervisor: N engine replicas behind the health-aware router.

The serving twin of ``tools/supervise.py`` (docs/reliability.md "Serving
resilience"): spawn N ``main.py --run_mode web_api`` replicas on adjacent
ports, run the replica router (serve/router.py) in-process in front of
them, and keep the set alive:

- **spawn** — replica i serves on ``--base-port + i`` with its /healthz
  exporter on ``--base-obs-port + i``; the router health-gates on the
  latter.  ``--fault-plan i:PLAN`` injects a chaos plan
  (``HBNLP_FAULT_PLAN``, reliability/faults.py) into exactly one replica —
  how the chaos-serve drill kills replica 0 mid-run.
- **health-watch + relaunch** — a dead replica (child exit) relaunches
  with exponential backoff (reliability/retry.py's RetryPolicy supplies
  the schedule); a shared ``serve_aot_cache_dir`` in the config makes the
  relaunch warm (AOT deserialization instead of recompilation).
  Optionally (``--unhealthy-restart-s``) a replica whose healthz stays
  unreachable that long is SIGTERMed so the same relaunch path recovers a
  wedged-but-alive process.
- **postings** — each replica slot posts exits/readiness/tombstones into
  ``--fleet-dir`` through supervise.py's FleetCoordinator scheme, so fleet
  tooling sees serving replicas exactly like training ranks.
- **drain** — SIGTERM drains the router (stop admitting, finish in-flight
  bounded by ``--grace-deadline-s``), then SIGTERMs every replica (their
  own grace drain), bounded-waits, SIGKILLs stragglers, tombstones, exits.

Stdlib-only, loadable on a broken jax install (the children pay for jax;
the supervisor must outlive exactly their failures).

Usage:
  python tools/graftserve.py --model configs/serve.json --replicas 2 \\
      --router-port 8080
"""
from __future__ import annotations

import argparse
import importlib.util
import logging
import os
import signal
import sys
import threading
import time
import typing
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_light(name: str, relpath: str):
    """Load a stdlib-only module by FILE PATH, bypassing the package
    __init__ (which imports jax via config.py) — supervise.py house
    style."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    # registered BEFORE exec: dataclass-bearing modules (retry.py) look
    # themselves up through sys.modules while their class bodies execute
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


# load order matters: sync first (the lock recorder), then the registry,
# then the modules that find both through sys.modules
_sync = _load_light("hbnlp_sync", "homebrewnlp_tpu/sync.py")
sys.modules.setdefault("hbnlp_sync", _sync)
make_lock = _sync.make_lock

_registry = _load_light("hbnlp_obs_registry",
                        "homebrewnlp_tpu/obs/registry.py")
sys.modules.setdefault("hbnlp_obs_registry", _registry)
REGISTRY = _registry.REGISTRY

_supervise = _load_light("hbnlp_supervise", "tools/supervise.py")
FleetCoordinator = _supervise.FleetCoordinator
SubprocessLauncher = _supervise.SubprocessLauncher

_retry = _load_light("hbnlp_retry",
                     "homebrewnlp_tpu/reliability/retry.py")
RetryPolicy = _retry.RetryPolicy

# the usage meter before the router: router.status() federates the
# replicas' per-tenant usage blocks through obs/usage.py::merge_usage and
# finds the module through sys.modules when loaded by file path
_usage = _load_light("hbnlp_obs_usage", "homebrewnlp_tpu/obs/usage.py")

router_mod = _load_light("hbnlp_router", "homebrewnlp_tpu/serve/router.py")

LOG = logging.getLogger("homebrewnlp_tpu.graftserve")


class ReplicaSupervisor:
    """One replica slot: spawn, watch, relaunch with backoff, drain.

    Runs on its own thread; ``stop()`` (the drain path) SIGTERMs the child
    — the replica's web_api handler turns that into its own graceful
    drain — and ends the relaunch loop."""

    def __init__(self, index: int, cmd: typing.Sequence[str],
                 env: dict, obs_url: str,
                 fleet: typing.Optional[FleetCoordinator] = None,
                 policy: typing.Optional[RetryPolicy] = None,
                 unhealthy_restart_s: float = 0.0,
                 registry=None):
        self.index = index
        self.obs_url = obs_url.rstrip("/")
        self.launcher = SubprocessLauncher(list(cmd), env=dict(env))
        self.fleet = fleet
        self.policy = policy if policy is not None else RetryPolicy(
            max_attempts=1_000_000, base_delay_s=0.5, max_delay_s=30.0)
        self.unhealthy_restart_s = float(unhealthy_restart_s)
        reg = registry if registry is not None else REGISTRY
        self._relaunches = reg.counter(
            "hbnlp_graftserve_relaunches_total",
            "replica relaunches by slot", labelnames=("replica",))
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name=f"replica-sup-{index}")

    def start(self) -> None:
        self.thread.start()

    def stop(self) -> None:
        """Begin the slot's shutdown: no more relaunches, SIGTERM the
        child (its own grace drain runs).  Join with :meth:`wait`."""
        self._stop.set()
        self.launcher.terminate()

    def kill(self) -> None:
        """Straggler escalation after the drain window: SIGKILL."""
        with self.launcher._lock:
            p = self.launcher._proc
        if p is not None and p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass

    def wait(self, timeout_s: float) -> bool:
        self.thread.join(timeout=timeout_s)
        return not self.thread.is_alive()

    def _watch_health(self, stop: threading.Event) -> None:
        """Wedged-process recovery: when healthz (including a 503 from a
        stalled decode loop, or a wedged snapshot's timeout) has answered
        nothing but errors for ``unhealthy_restart_s`` straight, SIGTERM
        the child so the relaunch loop recovers it."""
        last_ok = time.monotonic()
        while not stop.wait(1.0):
            try:
                with urllib.request.urlopen(self.obs_url + "/healthz",
                                            timeout=2.0):
                    last_ok = time.monotonic()
                    continue
            except Exception:  # noqa: BLE001 - any failure counts
                pass
            if time.monotonic() - last_ok >= self.unhealthy_restart_s:
                LOG.warning("replica %d healthz dead for %.0fs; SIGTERM "
                            "for relaunch", self.index,
                            self.unhealthy_restart_s)
                self.launcher.terminate()
                return

    def _run(self) -> None:
        attempt = 0
        while not self._stop.is_set():
            t0 = time.monotonic()
            if self.fleet is not None:
                self.fleet.post_ready(0)
            hstop = threading.Event()
            hthread = None
            if self.unhealthy_restart_s:
                hthread = threading.Thread(
                    target=self._watch_health, args=(hstop,), daemon=True,
                    name=f"replica-health-{self.index}")
                hthread.start()
            rc = self.launcher()
            hstop.set()
            if self.fleet is not None:
                self.fleet.post_exit(rc)
                self.fleet.advance()
            if self._stop.is_set():
                LOG.info("replica %d exited rc=%d during drain", self.index,
                         rc)
                return
            # long-lived children reset the backoff schedule: only rapid
            # death loops climb the exponential
            if time.monotonic() - t0 > 60.0:
                attempt = 0
            delay = self.policy.delay(attempt)
            attempt += 1
            self._relaunches.labels(replica=f"replica{self.index}").inc()
            LOG.warning("replica %d died rc=%d; relaunching in %.1fs "
                        "(warm via the shared AOT cache)", self.index, rc,
                        delay)
            if self._stop.wait(delay):
                return


def build_replica_cmd(cfg_path: str, port: int, obs_port: int
                      ) -> typing.List[str]:
    return [sys.executable, os.path.join(REPO, "main.py"),
            "--model", cfg_path, "--run_mode", "web_api",
            "--port", str(port), "--obs_port", str(obs_port)]


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        usage="graftserve.py --model CFG [options]")
    p.add_argument("--model", required=True, help="JSON config path")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--base-port", type=int, default=8100,
                   help="replica i serves on base-port + i")
    p.add_argument("--base-obs-port", type=int, default=9100,
                   help="replica i's /healthz exporter on base-obs-port + i")
    p.add_argument("--router-port", type=int, default=8080)
    p.add_argument("--router-host", type=str, default="127.0.0.1")
    p.add_argument("--health-interval-s", type=float, default=0.5)
    p.add_argument("--health-timeout-s", type=float, default=2.0)
    p.add_argument("--failover-retries", type=int, default=1)
    p.add_argument("--grace-deadline-s", type=float, default=30.0)
    p.add_argument("--backoff-base", type=float, default=0.5,
                   help="seconds before the first relaunch (doubles up to "
                        "--backoff-max; long-lived children reset it)")
    p.add_argument("--backoff-max", type=float, default=30.0)
    p.add_argument("--unhealthy-restart-s", type=float, default=0.0,
                   help=">0: SIGTERM a replica whose healthz has been "
                        "unreachable this long (wedged-process recovery); "
                        "0 disables")
    p.add_argument("--fleet-dir", type=str, default="",
                   help="shared dir for FleetCoordinator postings (exit/"
                        "ready/tombstone per replica slot); empty disables")
    p.add_argument("--fault-plan", action="append", default=[],
                   metavar="INDEX:PLAN",
                   help="inject a chaos plan (HBNLP_FAULT_PLAN) into one "
                        "replica, e.g. '0:replica:die@req5'; repeatable")
    return p.parse_args(argv)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s graftserve %(levelname)s %(message)s")
    args = parse_args(argv)
    plans: typing.Dict[int, str] = {}
    for spec in args.fault_plan:
        idx, _, plan = spec.partition(":")
        plans[int(idx)] = plan
    policy = RetryPolicy(max_attempts=1_000_000,
                         base_delay_s=args.backoff_base,
                         max_delay_s=args.backoff_max)
    replicas = []
    sups: typing.List[ReplicaSupervisor] = []
    for i in range(args.replicas):
        port = args.base_port + i
        obs_port = args.base_obs_port + i
        url = f"http://127.0.0.1:{port}"
        obs_url = f"http://127.0.0.1:{obs_port}"
        replicas.append(router_mod.Replica(url, obs_url,
                                           name=f"replica{i}"))
        env = dict(os.environ)
        if i in plans:
            env["HBNLP_FAULT_PLAN"] = plans[i]
        fleet = (FleetCoordinator(args.fleet_dir, rank=i,
                                  world_size=args.replicas)
                 if args.fleet_dir else None)
        sups.append(ReplicaSupervisor(
            i, build_replica_cmd(args.model, port, obs_port), env, obs_url,
            fleet=fleet, policy=policy,
            unhealthy_restart_s=args.unhealthy_restart_s))
    router = router_mod.Router(
        replicas, health_interval_s=args.health_interval_s,
        health_timeout_s=args.health_timeout_s,
        failover_retries=args.failover_retries)
    server = router_mod.serve_router(router, host=args.router_host,
                                     port=args.router_port, background=True)
    LOG.info("router on %s:%d over %d replica(s); replica ports %d..%d "
             "(obs %d..%d)", args.router_host, server.server_address[1],
             args.replicas, args.base_port,
             args.base_port + args.replicas - 1, args.base_obs_port,
             args.base_obs_port + args.replicas - 1)
    for sup in sups:
        sup.start()
    done = threading.Event()

    def _drain_all():
        # drain order matters: router first (stop admitting, finish
        # relaying in-flight), THEN the replicas' own grace drains — the
        # reverse would 503 streams the router still carries
        LOG.info("drain: router stops admitting (grace %.0fs)",
                 args.grace_deadline_s)
        server.drain(args.grace_deadline_s)
        for sup in sups:
            sup.stop()
        deadline = time.monotonic() + args.grace_deadline_s
        for sup in sups:
            sup.wait(max(0.1, deadline - time.monotonic()))
        for sup in sups:
            if not sup.wait(0.0):
                LOG.warning("replica %d ignored SIGTERM; SIGKILL",
                            sup.index)
                sup.kill()
                sup.wait(5.0)
            if sup.fleet is not None:
                sup.fleet.post_final(0)
        done.set()

    def _on_signal(signum, frame):
        threading.Thread(target=_drain_all, daemon=True,
                         name="graftserve-drain").start()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    while not done.wait(timeout=1.0):
        pass
    server.server_close()
    LOG.info("graftserve: drained and stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
