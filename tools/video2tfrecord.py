"""Video -> TFRecord shard builder.

Port of /root/reference/scripts/video2tfrecord.py (922 LoC): that pipeline
scrapes YouTube through proxies, parses VTT subtitles with per-timestamp BPE
alignment, extracts frames via ffmpeg/cv2 workers, and balances work by
duration.  The zero-egress port keeps everything after the download: local
video files -> cv2 frame extraction at a target fps, resize to the config's
frame geometry, per-word subtitle timing with token alignment per frame
(tools/vtt_align.py — karaoke/rolling-caption VTTs and plain SRT/VTT cues),
``concat``/``skip_frame`` flags between videos, multiprocess workers balanced
by duration (the reference's ``split_equal``, :168-183).

The proxied YouTube downloader (reference :57-129) is deliberately NOT run
or ported as executable code — this image has no egress.  Template for a
deployment that has it: enumerate video ids, fetch with a rate-limited
worker pool through rotating proxies, download the ``.vtt`` auto-caption
track alongside each video, then feed the (video, vtt) pairs to this tool.

Usage:
  python tools/video2tfrecord.py --model configs/video.json \
      --input a.mp4 b.mp4 [--subs a.vtt b.vtt] --output-dir datasets/video \
      [--fps 1] [--procs 4]
"""
from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import sys
import typing

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from homebrewnlp_tpu.config import Config  # noqa: E402
from homebrewnlp_tpu.data.tfrecord import encode_example  # noqa: E402
from homebrewnlp_tpu.native import write_records  # noqa: E402

def split_equal(durations: typing.Sequence[float], n: int
                ) -> typing.List[typing.List[int]]:
    """Balance items over n workers by duration (reference :168-183 —
    greedy into the lightest bucket)."""
    buckets: typing.List[typing.List[int]] = [[] for _ in range(n)]
    loads = [0.0] * n
    for idx in sorted(range(len(durations)), key=lambda i: -durations[i]):
        tgt = loads.index(min(loads))
        buckets[tgt].append(idx)
        loads[tgt] += durations[idx]
    return [b for b in buckets if b]


def video_frames(path: str, fps: float, width: int, height: int):
    """Yields (ts, next_ts, rgb_frame).  ``next_ts`` is the ACTUAL time of
    the next emitted frame (step/native_fps spacing — not 1/fps, which
    leaves gaps or overlaps whenever native_fps/fps is fractional), so
    [ts, next_ts) windows tile the subtitle timeline exactly."""
    import cv2
    cap = cv2.VideoCapture(path)
    native_fps = cap.get(cv2.CAP_PROP_FPS) or 30.0
    step = max(1, round(native_fps / fps))
    spacing = step / native_fps
    i = 0
    while True:
        ok, frame = cap.read()
        if not ok:
            break
        if i % step == 0:
            frame = cv2.resize(frame, (width, height))
            ts = i / native_fps
            yield ts, ts + spacing, cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
        i += 1
    cap.release()


def _encode_video(job) -> str:
    (worker_idx, video_paths, sub_paths, out_dir, cfg_path, fps) = job
    import cv2

    from vtt_align import (align_tokens, byte_encode, parse_timed_words,
                           tokens_per_frame)
    cfg = Config.from_json(cfg_path) if cfg_path else None
    width = cfg.frame_width if cfg else 320
    height = cfg.frame_height if cfg else 176
    ltpf = cfg.language_token_per_frame if cfg else 0
    payloads = []
    for vid_idx, path in enumerate(video_paths):
        timed, token_lists = [], []
        if sub_paths:
            with open(sub_paths[vid_idx], encoding="utf-8",
                      errors="replace") as f:
                timed = parse_timed_words(f.read())
            token_lists = align_tokens(byte_encode,
                                       [w.word for w in timed])
        first = True
        for ts, next_ts, frame in video_frames(path, fps, width, height):
            ok, jpg = cv2.imencode(".jpg", cv2.cvtColor(frame,
                                                        cv2.COLOR_RGB2BGR))
            assert ok
            feats: typing.Dict[str, typing.Any] = {
                "frame": jpg.tobytes(),
                "concat": [int(first)],
                "skip_frame": [0],
            }
            if ltpf:
                toks = tokens_per_frame(timed, token_lists, ts, next_ts - ts)
                toks = toks[:ltpf]
                feats["tokens"] = toks + [0] * (ltpf - len(toks))
                feats["mask"] = [len(toks)]
            payloads.append(encode_example(feats))
            first = False
    out = os.path.join(out_dir, f"video{worker_idx:05d}_{len(payloads)}.tfrecord")
    write_records(out, payloads)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", nargs="+", required=True, help="video files")
    ap.add_argument("--subs", nargs="*", default=None,
                    help="subtitle files (parallel to --input)")
    ap.add_argument("--model", default="", help="config JSON for frame "
                    "geometry / language_token_per_frame")
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--fps", type=float, default=1.0)
    ap.add_argument("--procs", type=int, default=os.cpu_count())
    args = ap.parse_args()
    os.makedirs(args.output_dir, exist_ok=True)

    import cv2
    durations = []
    for p in args.input:
        cap = cv2.VideoCapture(p)
        n = cap.get(cv2.CAP_PROP_FRAME_COUNT) or 0
        f = cap.get(cv2.CAP_PROP_FPS) or 30.0
        durations.append(n / f)
        cap.release()

    buckets = split_equal(durations, max(1, args.procs))
    jobs = []
    for w, bucket in enumerate(buckets):
        jobs.append((w, [args.input[i] for i in bucket],
                     [args.subs[i] for i in bucket] if args.subs else None,
                     args.output_dir, args.model, args.fps))
    with multiprocessing.Pool(len(jobs)) as pool:
        for out in pool.imap_unordered(_encode_video, jobs):
            print(out, flush=True)


if __name__ == "__main__":
    main()
