"""Video -> TFRecord shard builder.

Port of /root/reference/scripts/video2tfrecord.py (922 LoC): that pipeline
scrapes YouTube through proxies, parses VTT subtitles with per-timestamp BPE
alignment, extracts frames via ffmpeg/cv2 workers, and balances work by
duration.  The zero-egress port keeps everything after the download: local
video files -> cv2 frame extraction at a target fps, resize to the config's
frame geometry, per-word subtitle timing with token alignment per frame
(tools/vtt_align.py — karaoke/rolling-caption VTTs and plain SRT/VTT cues),
``concat``/``skip_frame`` flags between videos, multiprocess workers balanced
by duration (the reference's ``split_equal``, :168-183).

The proxied YouTube download fleet (reference :57-129 downloader/proxies,
:373-760 worker loop, :760-922 orchestration) lives in tools/fetch.py with
every network call behind an injected transport: ``download_and_encode``
below is the executable per-worker path (fetch videos + vtt tracks for each
chunk, then encode the chunk to one shard), unit-tested against mocked
transports (tests/tools_test.py) since this image has no egress; a
deployment with egress gets the real callables via ``--manifest`` mode.

Usage (local files):
  python tools/video2tfrecord.py --model configs/video.json \
      --input a.mp4 b.mp4 [--subs a.vtt b.vtt] --output-dir datasets/video \
      [--fps 1] [--procs 4]
Usage (download fleet, needs egress + youtube_dl):
  python tools/video2tfrecord.py --model configs/video.json \
      --manifest manifest.json --output-dir datasets/video \
      --buffer-dir /dev/shm/dl [--workers 4] [--webshare-key KEY]
"""
from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import sys
import typing

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from homebrewnlp_tpu.config import Config  # noqa: E402
from homebrewnlp_tpu.data.tfrecord import encode_example  # noqa: E402
from homebrewnlp_tpu.native import write_records  # noqa: E402

def split_equal(durations: typing.Sequence[float], n: int
                ) -> typing.List[typing.List[int]]:
    """Balance items over n workers by duration (reference :168-183 —
    greedy into the lightest bucket)."""
    buckets: typing.List[typing.List[int]] = [[] for _ in range(n)]
    loads = [0.0] * n
    for idx in sorted(range(len(durations)), key=lambda i: -durations[i]):
        tgt = loads.index(min(loads))
        buckets[tgt].append(idx)
        loads[tgt] += durations[idx]
    return [b for b in buckets if b]


def video_frames(path: str, fps: float, width: int, height: int):
    """Yields (ts, next_ts, rgb_frame).  ``next_ts`` is the ACTUAL time of
    the next emitted frame (step/native_fps spacing — not 1/fps, which
    leaves gaps or overlaps whenever native_fps/fps is fractional), so
    [ts, next_ts) windows tile the subtitle timeline exactly."""
    import cv2
    cap = cv2.VideoCapture(path)
    native_fps = cap.get(cv2.CAP_PROP_FPS) or 30.0
    step = max(1, round(native_fps / fps))
    spacing = step / native_fps
    i = 0
    while True:
        ok, frame = cap.read()
        if not ok:
            break
        if i % step == 0:
            frame = cv2.resize(frame, (width, height))
            ts = i / native_fps
            yield ts, ts + spacing, cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
        i += 1
    cap.release()


def _encode_video(job) -> str:
    (worker_idx, video_paths, sub_paths, out_dir, cfg_path, fps) = job
    import cv2

    from vtt_align import (align_tokens, byte_encode, parse_timed_words,
                           tokens_per_frame)
    cfg = Config.from_json(cfg_path) if cfg_path else None
    width = cfg.frame_width if cfg else 320
    height = cfg.frame_height if cfg else 176
    ltpf = cfg.language_token_per_frame if cfg else 0
    payloads = []
    for vid_idx, path in enumerate(video_paths):
        timed, token_lists = [], []
        # per-video None entries: a fleet worker whose vtt fetch failed
        # (skip_if_no_subtitles=False keeps the video, reference :690-693)
        if sub_paths and sub_paths[vid_idx] is not None:
            with open(sub_paths[vid_idx], encoding="utf-8",
                      errors="replace") as f:
                timed = parse_timed_words(f.read())
            token_lists = align_tokens(byte_encode,
                                       [w.word for w in timed])
        first = True
        for ts, next_ts, frame in video_frames(path, fps, width, height):
            ok, jpg = cv2.imencode(".jpg", cv2.cvtColor(frame,
                                                        cv2.COLOR_RGB2BGR))
            assert ok
            feats: typing.Dict[str, typing.Any] = {
                "frame": jpg.tobytes(),
                "concat": [int(first)],
                "skip_frame": [0],
            }
            if ltpf:
                toks = tokens_per_frame(timed, token_lists, ts, next_ts - ts)
                toks = toks[:ltpf]
                feats["tokens"] = toks + [0] * (ltpf - len(toks))
                feats["mask"] = [len(toks)]
            payloads.append(encode_example(feats))
            first = False
    out = os.path.join(out_dir, f"video{worker_idx:05d}_{len(payloads)}.tfrecord")
    write_records(out, payloads)
    return out


def download_and_encode(chunks: typing.Sequence[typing.Sequence[str]],
                        worker_idx: int, out_dir: str, buffer_dir: str,
                        cfg_path: str, fps: float,
                        info_extractor, downloader,
                        convert=None, validate=None,
                        want_subtitles: bool = True,
                        skip_if_no_subtitles: bool = True,
                        keep_buffer: bool = False) -> typing.List[str]:
    """One fleet worker (reference worker loop :373-760): for each chunk of
    video ids, fetch every video (+ vtt auto-caption track) through the
    injected ``info_extractor``/``downloader`` (tools/fetch.py), encode the
    chunk's successful fetches into one TFRecord shard, then drop the
    download buffer unless ``keep_buffer``.  Videos whose fetch fails are
    skipped (never crash the worker); with ``skip_if_no_subtitles`` a video
    without a vtt is skipped too (reference :690-693)."""
    import fetch

    os.makedirs(buffer_dir, exist_ok=True)
    resolution = _cfg_resolution(cfg_path)
    outs: typing.List[str] = []
    for chunk_idx, chunk in enumerate(chunks):
        vids: typing.List[str] = []
        subs: typing.List[typing.Optional[str]] = []
        fetched: typing.List[str] = []
        for video_id in chunk:
            v, s = fetch.fetch_video(
                video_id, buffer_dir, info_extractor, downloader,
                target_resolution=resolution,
                want_subtitles=want_subtitles, convert=convert,
                validate=validate)
            if v is None:
                continue
            fetched.append(v)
            if s is not None:
                fetched.append(s)
            if want_subtitles and s is None and skip_if_no_subtitles:
                continue
            vids.append(v)
            subs.append(s)
        if vids:
            out = _encode_video((worker_idx * 10000 + chunk_idx, vids,
                                 subs if want_subtitles else None,
                                 out_dir, cfg_path, fps))
            outs.append(out)
            print(out, flush=True)
        if not keep_buffer:
            for p in fetched:
                if os.path.exists(p):
                    os.remove(p)
    return outs


def _cfg_resolution(cfg_path: str) -> typing.Tuple[int, int]:
    if not cfg_path:
        return (320, 176)
    cfg = Config.from_json(cfg_path)
    return (cfg.frame_width, cfg.frame_height)


def _fleet_worker(job) -> typing.List[str]:
    (chunks, worker_idx, out_dir, buffer_dir, cfg_path, fps, webshare_key,
     want_subtitles, skip_if_no_subtitles, keep_buffer, rate_interval) = job
    import fetch

    rotator = fetch.ProxyRotator(fetch.requests_json_fetcher(), webshare_key)
    downloader = fetch.Downloader(
        fetch.requests_transport(), rotator,
        rate_limiter=fetch.RateLimiter(rate_interval))
    return download_and_encode(
        chunks, worker_idx, out_dir, buffer_dir, cfg_path, fps,
        fetch.youtube_info_extractor(), downloader,
        convert=fetch.ffmpeg_convert, validate=fetch.cv2_validate,
        want_subtitles=want_subtitles,
        skip_if_no_subtitles=skip_if_no_subtitles, keep_buffer=keep_buffer)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", nargs="*", default=None, help="video files")
    ap.add_argument("--subs", nargs="*", default=None,
                    help="subtitle files (parallel to --input)")
    ap.add_argument("--model", default="", help="config JSON for frame "
                    "geometry / language_token_per_frame")
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--fps", type=float, default=1.0)
    ap.add_argument("--procs", type=int, default=os.cpu_count())
    ap.add_argument("--manifest", nargs="*", default=None,
                    help="download-fleet mode: JSON manifests with "
                         "id/duration lists (reference manifest format)")
    ap.add_argument("--buffer-dir", default="",
                    help="download buffer (RAM disk recommended)")
    ap.add_argument("--workers", type=int, default=1,
                    help="fleet workers (--manifest mode)")
    ap.add_argument("--webshare-key", default=None,
                    help="webshare.io API key for proxy rotation")
    ap.add_argument("--min-duration", type=float, default=256.0,
                    help="skip chunks at or below this many seconds")
    ap.add_argument("--no-subtitles", action="store_true")
    ap.add_argument("--keep-without-subtitles", action="store_true")
    ap.add_argument("--keep-buffer", action="store_true")
    ap.add_argument("--rate-interval", type=float, default=1.0,
                    help="min seconds between fleet download requests")
    args = ap.parse_args()
    os.makedirs(args.output_dir, exist_ok=True)

    if args.manifest:
        import fetch
        ids, durations = fetch.load_manifest(args.manifest)
        shards, loads = fetch.plan_worker_shards(
            ids, durations, args.workers, args.min_duration)
        for w, (shard, load) in enumerate(zip(shards, loads)):
            print(f"worker {w}: {len(shard)} chunks, "
                  f"{sum(len(c) for c in shard)} videos, {load:.0f}s")
        jobs = [(shard, w, args.output_dir,
                 args.buffer_dir or os.path.join(args.output_dir, "buffer"),
                 args.model, args.fps, args.webshare_key,
                 not args.no_subtitles, not args.keep_without_subtitles,
                 args.keep_buffer, args.rate_interval)
                for w, shard in enumerate(shards) if shard]
        if not jobs:
            print("no chunks above --min-duration "
                  f"{args.min_duration}s; nothing to download")
            return
        with multiprocessing.Pool(len(jobs)) as pool:
            for outs in pool.imap_unordered(_fleet_worker, jobs):
                for out in outs:
                    print(out, flush=True)
        return

    if not args.input:
        ap.error("--input is required without --manifest")
    import cv2
    durations = []
    for p in args.input:
        cap = cv2.VideoCapture(p)
        n = cap.get(cv2.CAP_PROP_FRAME_COUNT) or 0
        f = cap.get(cv2.CAP_PROP_FPS) or 30.0
        durations.append(n / f)
        cap.release()

    buckets = split_equal(durations, max(1, args.procs))
    jobs = []
    for w, bucket in enumerate(buckets):
        jobs.append((w, [args.input[i] for i in bucket],
                     [args.subs[i] for i in bucket] if args.subs else None,
                     args.output_dir, args.model, args.fps))
    with multiprocessing.Pool(len(jobs)) as pool:
        for out in pool.imap_unordered(_encode_video, jobs):
            print(out, flush=True)


if __name__ == "__main__":
    main()
