"""Download front end for the ingest tools — executable, transport-injected.

The reference scrapes YouTube through rotating webshare.io proxies with a
rate-limited worker fleet (/root/reference/scripts/video2tfrecord.py:57-129
``Downloader``/``update_proxy``, :483-560 format selection + download loop,
:760-922 fleet orchestration) and streams Pile ``.jsonl.zst`` shards over
HTTP (/root/reference/scripts/text2tfrecord.py:35-54).  This image has no
egress, so every network touch point here is an INJECTED callable: the
logic — proxy-list pagination and rotation, bounded retry with partial-file
cleanup, resolution-targeted format selection with webm demotion, English
auto-caption vtt track selection, worker sharding by duration, shard-strided
Pile streaming — runs and is unit-tested against mocked transports
(tests/tools_test.py), and a deployment with egress passes the real
``requests``/``youtube_dl`` callables (see ``requests_transport`` /
``youtube_info_extractor`` at the bottom).
"""
from __future__ import annotations

import io
import json
import os
import typing


# -- rate limiting -----------------------------------------------------------

class RateLimiter:
    """Minimum-interval limiter (the reference rate-limits scraping with a
    shared multiprocessing lock + start_delay staggering,
    video2tfrecord.py:482-486,919; a min-interval token is the
    single-process equivalent).  ``clock``/``sleep`` injectable for tests."""

    def __init__(self, min_interval: float,
                 clock: typing.Callable[[], float] = None,
                 sleep: typing.Callable[[float], None] = None):
        import time
        self.min_interval = min_interval
        self._clock = clock or time.monotonic
        self._sleep = sleep or time.sleep
        self._last: typing.Optional[float] = None

    def wait(self) -> None:
        now = self._clock()
        if self._last is not None:
            remaining = self.min_interval - (now - self._last)
            if remaining > 0:
                self._sleep(remaining)
                now = self._clock()
        self._last = now


# -- proxy rotation ----------------------------------------------------------

class ProxyRotator:
    """webshare.io-style proxy pool (reference video2tfrecord.py:95-129):
    page through ``/api/proxy/list/`` following ``next`` links, keep the
    ``valid`` entries, shuffle, and expose one proxy mapping at a time;
    ``rotate()`` re-fetches (the reference calls ``update_proxy`` after
    every proxied failure).

    ``fetch_json(url, headers) -> dict`` is the injected transport; without
    an ``api_key`` the rotator is a no-proxy stub (reference behavior when
    ``webshare_io_key`` is None)."""

    LIST_URL = "https://proxy.webshare.io"

    def __init__(self, fetch_json: typing.Callable[[str, dict], dict],
                 api_key: typing.Optional[str] = None, rng=None):
        import random
        self._fetch = fetch_json
        self._key = api_key
        self._rng = rng or random.Random()
        self.proxies: typing.Optional[typing.Dict[str, str]] = None
        self.rotate()

    def rotate(self) -> typing.Optional[typing.Dict[str, str]]:
        if self._key is None:
            self.proxies = None
            return None
        pool: typing.List[dict] = []
        nxt: typing.Optional[str] = "/api/proxy/list/?page=1"
        while nxt is not None:
            page = self._fetch(self.LIST_URL + nxt,
                               {"Authorization": f"Token {self._key}"})
            nxt = None
            if page:
                nxt = page.get("next")
                pool += [p for p in page.get("results", ()) if p.get("valid")]
        self._rng.shuffle(pool)
        if not pool:
            self.proxies = None
            return None
        p = pool[0]
        url = (f"http://{p['username']}:{p['password']}"
               f"@{p['proxy_address']}:{p['ports']['http']}")
        self.proxies = {"http": url, "https": url}
        return self.proxies


# -- bounded-retry download --------------------------------------------------

class Downloader:
    """Stream a URL to a file with bounded retries (reference
    video2tfrecord.py:62-93): on a proxied failure rotate the proxy before
    the next try; after ``max_try`` failures delete the partial file and
    return False.

    ``transport(url, proxies) -> iterable of byte chunks`` is the injected
    network call (``requests.get(stream=True)`` in a real deployment)."""

    def __init__(self, transport: typing.Callable[
                     [str, typing.Optional[dict]], typing.Iterable[bytes]],
                 rotator: typing.Optional[ProxyRotator] = None,
                 max_try: int = 3,
                 rate_limiter: typing.Optional[RateLimiter] = None):
        self.transport = transport
        self.rotator = rotator
        self.max_try = max_try
        self.rate_limiter = rate_limiter

    def download(self, url: str, filename: str, use_proxy: bool) -> bool:
        proxies = self.rotator.proxies if (use_proxy and self.rotator) else None
        for _ in range(self.max_try):
            if self.rate_limiter is not None:
                self.rate_limiter.wait()
            try:
                with open(filename, "wb") as f:
                    for chunk in self.transport(url, proxies):
                        f.write(chunk)
                return True
            except Exception:  # noqa: BLE001 - network errors vary by transport
                if use_proxy and self.rotator is not None:
                    proxies = self.rotator.rotate()
        if os.path.exists(filename):
            os.remove(filename)
        return False


# -- format / caption selection ----------------------------------------------

def select_video_format(formats: typing.Sequence[dict],
                        target_resolution: typing.Tuple[int, int]
                        ) -> typing.List[dict]:
    """Pick the SMALLEST resolution strictly above the target, returning all
    candidate urls at that resolution with ``.webm`` demoted to the end
    (mp4 avoids the ffmpeg convert) — reference video2tfrecord.py:483-505
    (selection) and :536-540 (webm-last swap).  Entries must carry
    width/height/ext/url; 'tiny' (audio-only) format notes are skipped."""
    best: typing.Tuple[int, int] = (1 << 30, 1 << 30)
    out: typing.List[dict] = []
    for f in formats:
        if f.get("format_note") == "tiny":
            continue
        w, h = f.get("width"), f.get("height")
        if w is None or h is None or "url" not in f or "ext" not in f:
            continue
        if w > target_resolution[0] and h > target_resolution[1]:
            if (w, h) < best:
                best = (w, h)
                out = []
            if (w, h) == best:
                out.append({"width": w, "height": h,
                            "ext": f["ext"], "url": f["url"]})
    return ([f for f in out if f["ext"] != "webm"]
            + [f for f in out if f["ext"] == "webm"])


def select_caption_track(info: dict, lang: str = "en", ext: str = "vtt"
                         ) -> typing.Optional[str]:
    """First auto-caption track URL for ``lang`` with the requested ext
    (reference video2tfrecord.py:507-519)."""
    for track in info.get("automatic_captions", {}).get(lang, ()):
        if track.get("ext") == ext and "url" in track:
            return track["url"]
    return None


# -- one video: info -> select -> download -> validate -----------------------

def fetch_video(video_id: str, buffer_dir: str,
                info_extractor: typing.Callable[[str], dict],
                downloader: Downloader,
                target_resolution: typing.Tuple[int, int],
                want_subtitles: bool = False,
                convert: typing.Optional[
                    typing.Callable[[str, str], None]] = None,
                validate: typing.Optional[
                    typing.Callable[[str], bool]] = None,
                youtube_base: str = "https://www.youtube.com/watch?v=",
                ) -> typing.Tuple[typing.Optional[str],
                                  typing.Optional[str]]:
    """Fetch one video (+ optional vtt): extract info, select formats, walk
    the candidate list downloading until one validates (reference worker
    loop video2tfrecord.py:475-590).  Non-mp4 downloads go through
    ``convert(src, dst_mp4)`` (ffmpeg in the reference, :556-565); failed
    candidates are removed and the next tried.  Returns
    ``(video_path | None, vtt_path | None)``."""
    try:
        info = info_extractor(youtube_base + video_id)
    except Exception:  # noqa: BLE001 - scrape errors must not kill the worker
        return None, None
    candidates = select_video_format(info.get("formats", ()),
                                     target_resolution)
    video_path = None
    for cand in candidates:
        path = os.path.join(buffer_dir, f"{video_id}.{cand['ext']}")
        if not downloader.download(cand["url"], path, use_proxy=False):
            continue
        if cand["ext"] != "mp4" and convert is not None:
            mp4 = os.path.join(buffer_dir, f"{video_id}.mp4")
            convert(path, mp4)
            if os.path.exists(path):
                os.remove(path)
            path = mp4
        if validate is not None and not validate(path):
            if os.path.exists(path):
                os.remove(path)
            continue
        video_path = path
        break
    vtt_path = None
    if want_subtitles and video_path is not None:
        url = select_caption_track(info)
        if url is not None:
            cand_vtt = os.path.join(buffer_dir, f"{video_id}.vtt")
            # the reference downloads caption tracks THROUGH the proxy
            # (video2tfrecord.py:608-611) — the vtt endpoint is the
            # rate-limited one
            if downloader.download(url, cand_vtt, use_proxy=True):
                vtt_path = cand_vtt
    return video_path, vtt_path


# -- fleet sharding ----------------------------------------------------------

def plan_worker_shards(ids: typing.Sequence[typing.Sequence[str]],
                       durations: typing.Sequence[float], num_workers: int,
                       min_duration: float = 256.0
                       ) -> typing.Tuple[typing.List[typing.List[
                           typing.Sequence[str]]], typing.List[float]]:
    """Duration-balanced worker shards (reference ``split_equal``
    video2tfrecord.py:170-186): drop chunks at or below ``min_duration``
    seconds (<=0 disables), then greedy longest-first into the lightest
    worker.  Returns (per-worker chunk lists, per-worker total seconds)."""
    order = sorted(range(len(ids)), key=lambda i: -durations[i])
    shards: typing.List[typing.List[typing.Sequence[str]]] = [
        [] for _ in range(num_workers)]
    loads = [0.0] * num_workers
    for i in order:
        if min_duration > 0 and durations[i] <= min_duration:
            continue
        tgt = loads.index(min(loads))
        shards[tgt].append(ids[i])
        loads[tgt] += durations[i]
    return shards, loads


def load_manifest(paths: typing.Sequence[str]
                  ) -> typing.Tuple[typing.List[typing.List[str]],
                                    typing.List[float]]:
    """Reference manifest format (video2tfrecord.py:846-860): JSON files
    with ``id`` / ``duration`` lists; scalar ids become single-video chunks,
    list-of-list ids sum their durations."""
    ids: typing.List = []
    durations: typing.List = []
    for p in paths:
        with open(p) as f:
            m = json.load(f)
        ids += list(m["id"])
        durations += list(m["duration"])
    if ids and not isinstance(ids[0], list):
        return [[i] for i in ids], [float(d) for d in durations]
    return ([list(c) for c in ids],
            [float(sum(d)) if isinstance(d, (list, tuple)) else float(d)
             for d in durations])


# -- Pile shard streaming ----------------------------------------------------

PILE_URL_TEMPLATE = "http://eaidata.bmk.sh/data/pile/train/{shard:02d}.jsonl.zst"
PILE_SPLITS = 30


def pile_worker_shards(pid: int, procs: int, splits: int = PILE_SPLITS
                       ) -> typing.List[int]:
    """Shard-strided split of the Pile over workers (reference
    text2tfrecord.py:44: ``range(pid, splits, procs)``)."""
    return list(range(pid, splits, procs))


def stream_pile_documents(shards: typing.Sequence[int],
                          transport: typing.Callable[
                              [str, typing.Optional[dict]],
                              typing.Iterable[bytes]],
                          url_template: str = PILE_URL_TEMPLATE,
                          separator: int = 4
                          ) -> typing.Iterator[str]:
    """Stream documents out of Pile ``.jsonl.zst`` shards fetched over HTTP
    (reference text2tfrecord.py:35-54): zstd-decompress the byte stream
    incrementally, parse jsonlines, yield each document's text (dict
    entries yield ``item['text']``; list entries join on
    ``chr(separator)``).  ``transport(url, None) -> iterable of byte
    chunks`` is the same injected shape ``Downloader`` uses, so one real
    requests-backed callable serves both front ends."""
    import zstandard

    for shard in shards:
        url = url_template.format(shard=shard)
        chunks = transport(url, None)
        raw = _IterStream(iter(chunks))
        reader = io.BufferedReader(
            zstandard.ZstdDecompressor().stream_reader(raw))
        for line in io.TextIOWrapper(reader, encoding="utf-8",
                                     errors="replace"):
            line = line.strip()
            if not line:
                continue
            item = json.loads(line)
            if isinstance(item, dict):
                item = item["text"]
            if isinstance(item, list):
                item = chr(separator).join(item)
            yield item


class _IterStream(io.RawIOBase):
    """File-like view over an iterator of byte chunks (keeps the zstd
    decompressor streaming instead of buffering the whole shard the way the
    reference's ``r.raw.read()`` does — text2tfrecord.py:45-46)."""

    def __init__(self, chunks: typing.Iterator[bytes]):
        self._chunks = chunks
        self._buf = b""

    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        while not self._buf:
            try:
                self._buf = next(self._chunks)
            except StopIteration:
                return 0
        n = min(len(b), len(self._buf))
        b[:n] = self._buf[:n]
        self._buf = self._buf[n:]
        return n


# -- real transports (egress deployments only) -------------------------------

def requests_transport(chunk_size: int = 1 << 20):
    """``transport(url, proxies)`` backed by requests (reference
    video2tfrecord.py:70-77).  Import deferred: this module stays testable
    in zero-egress images."""
    import requests

    def transport(url: str, proxies: typing.Optional[dict]
                  ) -> typing.Iterable[bytes]:
        with requests.get(url, stream=True, proxies=proxies,
                          timeout=600) as r:
            r.raise_for_status()
            yield from r.iter_content(chunk_size)

    return transport


def requests_json_fetcher():
    """``fetch_json(url, headers)`` for ProxyRotator (reference
    video2tfrecord.py:99-104)."""
    import requests

    def fetch(url: str, headers: dict) -> dict:
        return requests.get(url, headers=headers, timeout=60).json()

    return fetch


def youtube_info_extractor():
    """``info_extractor(url)`` backed by youtube_dl (reference
    video2tfrecord.py:440-444,487-490).  The caller serializes info
    extraction across workers (the reference holds a multiprocessing lock)."""
    import youtube_dl
    getter = youtube_dl.YoutubeDL({"writeautomaticsub": True,
                                   "ignore-errors": True,
                                   "socket-timeout": 600})
    getter.add_default_info_extractors()

    def extract(url: str) -> dict:
        return getter.extract_info(url, download=False)

    return extract


def ffmpeg_convert(src: str, dst: str) -> None:
    """Container remux to mp4 (reference video2tfrecord.py:556-565)."""
    import subprocess
    subprocess.run(["ffmpeg", "-i", src, "-c", "copy", dst, "-y"],
                   stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
                   check=False)


def cv2_validate(path: str) -> bool:
    """A download only counts if cv2 can read a frame (reference
    video2tfrecord.py:569-585)."""
    try:
        import cv2
        cap = cv2.VideoCapture(path)
        ok, _ = cap.read()
        cap.release()
        return bool(ok)
    except Exception:  # noqa: BLE001
        return False
