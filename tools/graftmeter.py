#!/usr/bin/env python
"""graftmeter: per-tenant usage sheet + exact token reconciliation gate.

Tails the obs exporter's usage surface (the ``/healthz`` ``usage`` block
and the ``hbnlp_serve_*`` per-tenant counter families that
``obs/usage.py`` renders through the registry collector hook) and prints
the accountant's one-glance view: metered tokens/flops/KV block-seconds
by tenant, each tenant's DRF dominant-resource share and mean queue wait
(noisy-neighbor cause and symptom side by side), and the replica's — or,
pointed at the router, the FLEET's federated — capacity utilization
against the cost-model ceiling.

Modes:
  one-shot     scrape once, print the sheet (default); ``--json`` emits
               the raw snapshot document instead
  --window S   scrape twice S seconds apart and rank tenants by LIVE
               tokens/s from counter deltas (negative deltas — a tenant
               re-admitted after a top-K fold restarts its series at 0 —
               clamp to 0 in rates; lifetime columns stay absolute)
  --top N      show only the N busiest tenant rows (by tokens, or by
               tokens/s under --window); the fold row ``other`` always
               prints when present
  --check      CI gate, exit 1 unless the meter's books balance:
               (a) the row-sum invariant — token/request counters summed
               over every tenant row (``other`` included) equal the
               overall totals EXACTLY, and (b) with ``--load-report`` (a
               ``graftload --tenants N --json`` document) the client's
               own per-tenant token counts equal the server's metered
               totals EXACTLY — counters count tokens, not clocks, so
               the tolerance is zero.

Usage:
  python tools/graftmeter.py --metrics-url http://127.0.0.1:9090
  python tools/graftmeter.py --metrics-url ... --window 5 --top 10
  python tools/graftmeter.py --metrics-url ... --check \
      --load-report load_report.json

Exit codes: 0 ok; 1 when ``--check`` finds the books out of balance;
2 usage/connection errors.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import typing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from homebrewnlp_tpu.obs.usage import _ACC_FIELDS, OTHER  # noqa: E402

#: integer counter fields the row-sum invariant holds EXACTLY over (python
#: ints sum associatively); float accumulators get a relative tolerance
#: for summation-order drift
_INT_FIELDS = ("requests", "errors", "prompt_tokens", "generated_tokens")
_FLOAT_TOL = 1e-6


def _get_json(url: str, timeout_s: float = 10.0) -> dict:
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        # /healthz answers 503 WITH a body when stalled — the usage block
        # is still in it and still worth metering
        body = e.read().decode()
        try:
            return json.loads(body)
        except ValueError:
            raise e


def _get_text(url: str, timeout_s: float = 10.0) -> str:
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return r.read().decode()


def scrape(metrics_url: str, timeout_s: float = 10.0) -> dict:
    """One snapshot: the ``/healthz`` ``usage`` block (totals, rates,
    capacity, per-tenant attribution) plus the raw per-tenant token
    counters from ``/metrics`` (the series scrape deltas are taken
    over)."""
    import graftload
    base = metrics_url.rstrip("/")
    snap: dict = {"wall_time_s": time.time()}
    hz = _get_json(base + "/healthz", timeout_s)
    snap["status"] = hz.get("status")
    snap["usage"] = hz.get("usage")
    metrics = graftload.parse_prom(_get_text(base + "/metrics", timeout_s))
    tokens: typing.Dict[str, typing.Dict[str, float]] = {}
    for labels, v in metrics.get("hbnlp_serve_tokens_total", []):
        row = tokens.setdefault(labels.get("tenant", "?"), {})
        kind = labels.get("kind", "?")
        row[kind] = row.get(kind, 0.0) + v
    snap["tokens"] = tokens
    return snap


def deltas(prev: dict, cur: dict) -> dict:
    """Scrape-to-scrape per-tenant token rates.  Negative deltas (a fold
    restarted a re-admitted tenant's series at 0) clamp to 0 — rates are
    a live view, not the reconciliation arm, which must NOT clamp
    (graftload.tenant_token_deltas)."""
    dt = cur["wall_time_s"] - prev["wall_time_s"]
    if dt <= 0:
        return {}
    out: typing.Dict[str, dict] = {}
    names = set(prev.get("tokens") or {}) | set(cur.get("tokens") or {})
    for name in names:
        a = (prev.get("tokens") or {}).get(name) or {}
        b = (cur.get("tokens") or {}).get(name) or {}
        tok = sum(max(0.0, b.get(k, 0.0) - a.get(k, 0.0))
                  for k in set(a) | set(b))
        out[name] = {"tokens_per_s": round(tok / dt, 3)}
    return {"window_s": round(dt, 3), "per_tenant": out}


def row_sum_problems(usage: typing.Optional[dict]) -> typing.List[str]:
    """The meter's own books: every counter summed over the tenant rows
    (``other`` included) must equal the overall totals — integer fields
    exactly, float accumulators within summation-order drift.  Any
    violation is a metering bug (a record landed in a row but not the
    total, or vice versa)."""
    if not isinstance(usage, dict) or not isinstance(usage.get("totals"),
                                                     dict):
        return ["no usage block on /healthz (usage_top_k=0?)"]
    totals = usage["totals"]
    rows = (usage.get("per_tenant") or {}).values()
    problems = []
    for field in _ACC_FIELDS:
        total = totals.get(field, 0)
        summed = sum(r.get(field, 0) for r in rows)
        if field in _INT_FIELDS:
            ok = int(summed) == int(total)
        else:
            ok = abs(summed - total) <= _FLOAT_TOL * max(1.0, abs(total))
        if not ok:
            problems.append(f"row sum != total for {field}: "
                            f"{summed} != {total}")
    return problems


def reconcile(load_report: dict, usage: typing.Optional[dict]
              ) -> typing.Tuple[bool, typing.List[str]]:
    """The graftload-vs-meter gate as a pure function: ``(ok, reasons)``.

    Prefers the report's own ``usage_reconcile`` arm (run DELTAS bracketing
    the load — immune to prior traffic); falls back to comparing the
    client's per-tenant counts against the meter's ABSOLUTE totals, which
    is exact only on a server that served nothing else — the fallback says
    so when it fails."""
    arm = load_report.get("usage_reconcile")
    if isinstance(arm, dict) and "skipped" not in arm and "error" not in arm:
        if arm.get("tokens_match", False):
            return True, []
        reasons = [f"graftload usage_reconcile mismatch: "
                   f"client={arm.get('client_tokens_total')} "
                   f"server={arm.get('server_tokens_total')}"]
        for tenant, kinds in (arm.get("mismatches") or {}).items():
            reasons.append(f"  tenant {tenant}: {json.dumps(kinds)}")
        for key, v in (arm.get("server_extra_rows") or {}).items():
            reasons.append(f"  unexpected server row {key}: {v}")
        return False, reasons
    client = (load_report.get("client") or {}).get("per_tenant")
    if not client:
        return False, ["load report has no per-tenant data "
                       "(run graftload with --tenants N --json)"]
    if not isinstance(usage, dict):
        return False, ["no usage block on /healthz to reconcile against"]
    rows = usage.get("per_tenant") or {}
    reasons = []
    for tenant, crow in sorted(client.items()):
        srow = rows.get(tenant) or {}
        for field in ("prompt_tokens", "generated_tokens"):
            c, s = int(crow.get(field) or 0), int(srow.get(field) or 0)
            if c != s:
                reasons.append(
                    f"tenant {tenant} {field}: client={c} server={s} "
                    "(absolute comparison — exact only on a dedicated "
                    "server; prefer a report with its usage_reconcile arm)")
    return not reasons, reasons


def _fmtn(v: typing.Optional[float]) -> str:
    if v is None:
        return "-"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    if abs(f) >= 1e6:
        return f"{f:.3e}"
    return f"{f:.3f}"


def render(snap: dict, rates: typing.Optional[dict] = None,
           top: int = 0) -> str:
    """The usage sheet: capacity header, then one row per tenant."""
    lines = []
    usage = snap.get("usage")
    if not isinstance(usage, dict):
        return (f"status={snap.get('status', '?')} — no usage block "
                "(usage metering off: usage_top_k=0?)")
    totals = usage.get("totals") or {}
    all_tokens = ((totals.get("prompt_tokens") or 0)
                  + (totals.get("generated_tokens") or 0))
    head = (f"status={snap.get('status', '?')} "
            f"tenants={usage.get('tracked_tenants')} "
            f"folds={usage.get('folds')} "
            f"requests={_fmtn(totals.get('requests'))} "
            f"tokens={_fmtn(all_tokens)}")
    if usage.get("replicas") is not None:  # a router's federated block
        head += f" replicas={usage['replicas']}"
    lines.append(head)
    r = usage.get("rates") or {}
    cap = usage.get("capacity") or {}
    if r or cap:
        util = cap.get("capacity_utilization")
        sat = cap.get("projected_saturation_concurrency")
        lines.append(
            f"  capacity: tokens/s={_fmtn(r.get('tokens_per_s'))} "
            f"flops/s={_fmtn(r.get('flops_per_s'))} "
            f"peak={_fmtn(cap.get('peak_flops_per_s'))} "
            f"util={'-' if util is None else f'{util:.4f}'} "
            f"saturation_conc={'-' if sat is None else f'{sat:.1f}'}")
    per = usage.get("per_tenant") or {}
    rate_rows = (rates or {}).get("per_tenant") or {}

    def tokens_of(name: str) -> float:
        if rate_rows:
            return rate_rows.get(name, {}).get("tokens_per_s", 0.0)
        row = per.get(name) or {}
        return ((row.get("prompt_tokens") or 0)
                + (row.get("generated_tokens") or 0))

    names = sorted((n for n in per if n != OTHER),
                   key=lambda n: (-tokens_of(n), n))
    if top > 0:
        names = names[:top]
    if OTHER in per:  # the fold row always prints: it is the tail's account
        names.append(OTHER)
    if names:
        lines.append("  tenant           req  err  prompt_tok  gen_tok"
                     "    tok/s  kv_blk_s     flops  share  q_wait_s")
        for name in names:
            row = per.get(name) or {}
            rps = rate_rows.get(name, {}).get("tokens_per_s")
            qw = row.get("queue_wait_mean_s")
            share = row.get("dominant_share")
            lines.append(
                f"  {name:<15} {_fmtn(row.get('requests')):>4} "
                f"{_fmtn(row.get('errors')):>4} "
                f"{_fmtn(row.get('prompt_tokens')):>10} "
                f"{_fmtn(row.get('generated_tokens')):>8} "
                f"{_fmtn(rps):>8} "
                f"{_fmtn(row.get('kv_block_seconds')):>9} "
                f"{_fmtn(row.get('flops')):>9} "
                f"{'-' if share is None else f'{share:.3f}':>6} "
                f"{_fmtn(qw):>9}")
    return "\n".join(lines)


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--metrics-url", default="",
                    help="obs exporter (or router) base URL "
                         "(/healthz + /metrics)")
    ap.add_argument("--top", type=int, default=0,
                    help="show only the N busiest tenants (0 = all)")
    ap.add_argument("--window", type=float, default=0.0,
                    help="scrape twice this many seconds apart and rank "
                         "by live tokens/s from counter deltas")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw snapshot as one JSON document")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless the row-sum invariant holds and "
                         "(with --load-report) client/server token "
                         "counts reconcile EXACTLY")
    ap.add_argument("--load-report", default="",
                    help="graftload --tenants N --json report to "
                         "reconcile against (--check)")
    args = ap.parse_args(argv)
    if not args.metrics_url:
        print("graftmeter: --metrics-url is required", file=sys.stderr)
        return 2
    try:
        snap = scrape(args.metrics_url)
        rates = None
        if args.window > 0:
            time.sleep(args.window)
            cur = scrape(args.metrics_url)
            rates = deltas(snap, cur)
            snap = cur
        if args.json:
            print(json.dumps(dict(snap, rates=rates or {}),
                             sort_keys=True))
        else:
            print(render(snap, rates, top=max(0, args.top)))
    except (OSError, ValueError) as e:
        print(f"graftmeter: {e}", file=sys.stderr)
        return 2
    if args.check:
        problems = row_sum_problems(snap.get("usage"))
        if args.load_report:
            try:
                with open(args.load_report) as f:
                    report = json.load(f)
            except (OSError, ValueError) as e:
                print(f"graftmeter: {e}", file=sys.stderr)
                return 2
            ok, reasons = reconcile(report, snap.get("usage"))
            if not ok:
                problems.extend(reasons)
        for p in problems:
            print(f"CHECK FAILED: {p}", file=sys.stderr)
        return 1 if problems else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
