"""Per-component HBM byte budget for a workload config (VERDICT r4 item 4).

The 32mixer_group roofline (docs/perf/README.md) proves the step is
bandwidth-bound; this tool breaks the bytes down so the remaining GB are
attributable.  It cost-analyzes, via XLA on the live backend:

- the FULL train step (default knobs, remat off, fused-mixer on/off),
- each layer family standalone (one fwd+bwd call at the workload's
  activation shape): norm, masked-map attention, the gelu glue, the whole
  5-layer mixer block unfused vs fused (ops/pallas_mixer.py), and the
  bottleneck-group-linear block,
- the optimizer update alone (grads -> new params/slots),

and prints a JSON table plus derived "per step" extrapolations (calls per
step x per-call bytes).  NOTE pallas kernels are opaque to XLA cost
analysis (their in-kernel flops/bytes are not counted); the fused rows'
"bytes" are therefore the true HBM traffic at the pallas_call boundary
(exactly what the lever claims to cut) while their "flops" UNDERCOUNT —
wall-clock and the unfused flop count are the honest comparators.

Usage:
  python tools/byte_budget.py [--config configs/32mixer_group.json]
      [--batch 64] [--steps-probe]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def cost_of(fn, *args):
    c = jax.jit(fn).lower(*args).compile().cost_analysis()
    if isinstance(c, (list, tuple)):
        c = c[0] if c else {}
    c = dict(c or {})
    return {"flops": float(c.get("flops", 0.0)),
            "bytes": float(c.get("bytes accessed", 0.0))}


def layer_rows(cfg, shape, cfg_fused=None) -> dict:
    """Standalone fwd+bwd cost per layer family at the workload shape."""
    from homebrewnlp_tpu.models.ctx import Args, Ctx
    from homebrewnlp_tpu.models.registry import _get_block_part
    from homebrewnlp_tpu.config import BlockConfig
    from homebrewnlp_tpu.models import init_params
    from homebrewnlp_tpu.nd import NT

    names = ("batch", "sequence", "heads", "features_per_head")
    x = jax.random.normal(jax.random.key(0), shape).astype(
        cfg.calculation_dtype)

    chains = {
        "norm": ["norm-shift-scale-features-group"],
        "map_attention": [
            "attention-biased_attention_map-absolute-input_as_value-shared"],
        "gelu": ["activation-gelu"],
        "mixer_block_unfused": None,   # filled from the config
        "group_linear_block": None,
    }
    from homebrewnlp_tpu.models.layers import MIXER_FUSED_PATTERN
    chains["mixer_block_unfused"] = list(MIXER_FUSED_PATTERN)
    chains["group_linear_block"] = list(cfg.block_config[0]["layer"]
                                        if isinstance(cfg.block_config[0], dict)
                                        else cfg.block_config[0].layer)

    rows = {}
    for label, layer_list in chains.items():
        conf = BlockConfig(layer=layer_list, skip=False,
                           memory_reduction_strategy="none")

        def init_chain():
            ctx = Ctx(cfg, params=None, train=True)
            ctx._scope = ["probe"]
            _get_block_part(conf, ctx, NT(x, names))
            return ctx.collected

        params = jax.jit(init_chain)()

        def fwd_bwd(p, t):
            def f(p, t):
                ctx = Ctx(cfg, params=p, train=True)
                ctx._scope = ["probe"]
                out = _get_block_part(conf, ctx, NT(t, names))
                return jnp.sum(out.x.astype(jnp.float32))
            g = jax.grad(f, argnums=(0, 1))(p, t)
            return g

        rows[label] = cost_of(fwd_bwd, dict(params), x)
        if label == "mixer_block_unfused" and cfg_fused is not None:
            def fwd_bwd_fused(p, t):
                def f(p, t):
                    ctx = Ctx(cfg_fused, params=p, train=True)
                    ctx._scope = ["probe"]
                    out = _get_block_part(conf, ctx, NT(t, names))
                    return jnp.sum(out.x.astype(jnp.float32))
                return jax.grad(f, argnums=(0, 1))(p, t)
            rows["mixer_block_fused"] = cost_of(fwd_bwd_fused,
                                                dict(params), x)
    return rows


def main() -> None:
    from homebrewnlp_tpu.utils import (enable_compilation_cache, load_config,
                                       random_text_batch)
    from homebrewnlp_tpu.train import Trainer

    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="configs/32mixer_group.json")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--skip-step", action="store_true",
                    help="layer rows only (no full-step compiles)")
    args = ap.parse_args()

    common = dict(train_batch_size=args.batch, use_checkpointing=False,
                  calc_accuracy=False, tpu_size=1, slice_dtype="bfloat16")
    cfg = load_config(args.config, **common)
    enable_compilation_cache(cfg.compilation_cache_dir)

    out = {"config": args.config, "batch": args.batch,
           "device": jax.devices()[0].device_kind}

    shape = (cfg.train_batch_size, cfg.sequence_length, cfg.heads,
             cfg.features_per_head)
    out["activation_shape"] = list(shape)
    cfg_fused = load_config(args.config, **common, fused_mixer_block=True)
    out["layers"] = layer_rows(cfg, shape, cfg_fused)

    if not args.skip_step:
        variants = {
            "step_remat_off": dict(reversible_remat_blocks=False),
            "step_remat_on": dict(reversible_remat_blocks=True),
            "step_fused_mixer": dict(reversible_remat_blocks=False,
                                     fused_mixer_block=True),
        }
        out["step"] = {}
        for label, over in variants.items():
            c = load_config(args.config, **common, **over)
            tr = Trainer(c)
            batch = random_text_batch(c)
            state = tr.init(batch)
            cost = tr.step_cost_analysis(state, batch)
            out["step"][label] = {
                "flops": float(cost.get("flops", 0.0)),
                "bytes": float(cost.get("bytes accessed", 0.0))}

        # parameter/optimizer-state footprint (bf16 resident)
        n_params = sum(int(v.size) for v in state.params.values())
        n_slots = sum(int(x.size) for x in jax.tree_util.tree_leaves(
            state.opt_state))
        out["param_count"] = n_params
        out["opt_slot_count"] = n_slots
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
