"""Text -> TFRecord shard builder with C++ hot paths.

Port of /root/reference/scripts/text2tfrecord.py + local_text2tfrecord.pyx:
multiprocess encoding of text files into TFRecord shards, byte-level or BPE
(a tools/train_tokenizer.py artifact), with the token count embedded in the
filename (``..._<n>.tfrecord``) the way the run-log replay resume expects
(src/inputs.py:34).  A remote ``--output-dir`` (gs://...) uploads each shard
with bounded-retry backoff (reference scripts/text2tfrecord.py:61-89) via
data/fs.py; ``--post-cmd`` remains as a hook.  Framing + CRC go through
native/hbnlp_native.cc.

Usage:
  python tools/text2tfrecord.py --input *.txt --output-dir datasets/pile \
      [--tokenizer tokenizer.json] [--procs 8]
"""
from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import subprocess
import sys
import typing

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.data.tfrecord import encode_example  # noqa: E402
from homebrewnlp_tpu.native import bpe_encode, clean_text, write_records  # noqa: E402


def encode_file(path: str, merges: typing.Optional[np.ndarray]
                ) -> typing.Tuple[bytes, int]:
    with open(path, "rb") as f:
        raw = clean_text(f.read())
    return encode_payload(raw, merges)


def encode_payload(raw: bytes, merges: typing.Optional[np.ndarray]
                   ) -> typing.Tuple[bytes, int]:
    if merges is None:
        return encode_example({"text": raw}), len(raw)
    toks = np.frombuffer(raw, np.uint8).astype(np.int32)
    toks = bpe_encode(toks, merges)
    return encode_example({"text": [int(t) for t in toks]}), len(toks)


def iter_jsonl_zst(path: str) -> typing.Iterator[str]:
    """Stream documents out of a Pile-style ``.jsonl.zst`` shard — local path
    or URL (http/gs via data/fs.py), mirroring the reference's streaming
    downloader (scripts/text2tfrecord.py:35-54)."""
    import io

    import zstandard

    from homebrewnlp_tpu.data import fs
    with fs.open_stream(path, "rb") as raw:
        reader = zstandard.ZstdDecompressor().stream_reader(raw)
        for line in io.TextIOWrapper(reader, encoding="utf-8",
                                     errors="replace"):
            line = line.strip()
            if not line:
                continue
            yield json.loads(line).get("text", "")


def iter_pile_http(shards: typing.Sequence[int], url_template: str
                   ) -> typing.Iterator[str]:
    """Stream Pile documents over HTTP (reference text2tfrecord.py:35-54)
    through tools/fetch.py's injectable reader with the real requests
    transport."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import fetch
    return fetch.stream_pile_documents(shards, fetch.requests_transport(),
                                       url_template=url_template)


def _work(job) -> str:
    (shard_idx, paths, out_dir, tokenizer_path, jsonl_zst,
     url_template) = (job + ("",))[:6]
    merges = None
    suffix = "bytes"
    if tokenizer_path:
        with open(tokenizer_path) as f:
            merges = np.asarray(json.load(f)["merges"], np.int32)
        suffix = "int64"
    import tempfile

    from homebrewnlp_tpu.data import fs
    from homebrewnlp_tpu.data.tfrecord import RecordWriter

    remote = fs.is_remote(out_dir)
    # the token total goes in the FILENAME (run-log replay convention), so
    # records stream to a temp file that is renamed/uploaded once known —
    # a Pile shard decompresses to GBs and must not be buffered in RAM
    tmpdir = tempfile.mkdtemp(prefix="t2t_")
    tmp = os.path.join(tmpdir, f"shard{shard_idx:05d}.part")
    total = 0
    try:
        with RecordWriter(tmp) as w:
            if jsonl_zst == "pile":
                # paths are Pile shard numbers, streamed over HTTP
                for doc in iter_pile_http([int(p) for p in paths],
                                          url_template):
                    payload, n = encode_payload(clean_text(doc.encode()),
                                                merges)
                    w.write(payload)
                    total += n
            else:
                for p in paths:
                    if jsonl_zst:
                        # one TFRecord record per document (documents never
                        # cross records — the pipeline's windowing
                        # assumption)
                        for doc in iter_jsonl_zst(p):
                            payload, n = encode_payload(
                                clean_text(doc.encode()), merges)
                            w.write(payload)
                            total += n
                    else:
                        payload, n = encode_file(p, merges)
                        w.write(payload)
                        total += n
        name = f"shard{suffix}{shard_idx:05d}_{total}.tfrecord"
        if remote:
            # upload with bounded-retry backoff (the reference's GCS loop,
            # scripts/text2tfrecord.py:61-89)
            out = out_dir.rstrip("/") + "/" + name
            fs.put_with_retry(tmp, out)
        else:
            out = os.path.join(out_dir, name)
            os.replace(tmp, out)
        return out
    finally:
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", nargs="*", default=None)
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--tokenizer", default="",
                    help="tokenizer.json from tools/train_tokenizer.py "
                         "(omit for byte-level)")
    ap.add_argument("--files-per-shard", type=int, default=16)
    ap.add_argument("--jsonl-zst", action="store_true",
                    help="inputs are Pile-style .jsonl.zst shards (local or "
                         "URL), streamed document-by-document")
    ap.add_argument("--procs", type=int, default=os.cpu_count())
    ap.add_argument("--post-cmd", default="",
                    help="shell command run per finished shard, {} = path "
                         "(e.g. 'gsutil cp {} gs://bucket/')")
    ap.add_argument("--pile-stream", type=int, default=0, metavar="SPLITS",
                    help="stream this many Pile .jsonl.zst shards over HTTP "
                         "instead of reading --input (reference "
                         "text2tfrecord.py:35-54; needs egress)")
    ap.add_argument("--pile-url-template", default="",
                    help="override the shard URL template "
                         "({shard:02d} placeholder)")
    args = ap.parse_args()
    from homebrewnlp_tpu.data import fs
    if not fs.is_remote(args.output_dir):
        os.makedirs(args.output_dir, exist_ok=True)

    jobs = []
    if args.pile_stream:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import fetch
        template = args.pile_url_template or fetch.PILE_URL_TEMPLATE
        # shard-strided worker split, one job per worker (reference :44)
        for pid in range(min(args.procs, args.pile_stream)):
            shards = fetch.pile_worker_shards(
                pid, min(args.procs, args.pile_stream), args.pile_stream)
            jobs.append((pid, shards, args.output_dir, args.tokenizer,
                         "pile", template))
    else:
        if not args.input:
            ap.error("--input is required without --pile-stream")
        per = 1 if args.jsonl_zst else args.files_per_shard
        for i in range(0, len(args.input), per):
            jobs.append((len(jobs), args.input[i:i + per],
                         args.output_dir, args.tokenizer, args.jsonl_zst))
    with multiprocessing.Pool(min(args.procs, len(jobs))) as pool:
        for out in pool.imap_unordered(_work, jobs):
            print(out, flush=True)
            if args.post_cmd:
                subprocess.run(args.post_cmd.replace("{}", out), shell=True,
                               check=False)


if __name__ == "__main__":
    main()
