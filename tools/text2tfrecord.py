"""Text -> TFRecord shard builder with C++ hot paths.

Port of /root/reference/scripts/text2tfrecord.py + local_text2tfrecord.pyx:
multiprocess encoding of text files into TFRecord shards, byte-level or BPE
(a tools/train_tokenizer.py artifact), with the token count embedded in the
filename (``..._<n>.tfrecord``) the way the run-log replay resume expects
(src/inputs.py:34).  A remote ``--output-dir`` (gs://...) uploads each shard
with bounded-retry backoff (reference scripts/text2tfrecord.py:61-89) via
data/fs.py; ``--post-cmd`` remains as a hook.  Framing + CRC go through
native/hbnlp_native.cc.

Usage:
  python tools/text2tfrecord.py --input *.txt --output-dir datasets/pile \
      [--tokenizer tokenizer.json] [--procs 8]
"""
from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import subprocess
import sys
import typing

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.data.tfrecord import encode_example  # noqa: E402
from homebrewnlp_tpu.native import bpe_encode, clean_text, write_records  # noqa: E402


def encode_file(path: str, merges: typing.Optional[np.ndarray]
                ) -> typing.Tuple[bytes, int]:
    with open(path, "rb") as f:
        raw = clean_text(f.read())
    if merges is None:
        return encode_example({"text": raw}), len(raw)
    toks = np.frombuffer(raw, np.uint8).astype(np.int32)
    toks = bpe_encode(toks, merges)
    return encode_example({"text": [int(t) for t in toks]}), len(toks)


def _work(job) -> str:
    shard_idx, paths, out_dir, tokenizer_path = job
    merges = None
    suffix = "bytes"
    if tokenizer_path:
        with open(tokenizer_path) as f:
            merges = np.asarray(json.load(f)["merges"], np.int32)
        suffix = "int64"
    payloads, total = [], 0
    for p in paths:
        payload, n = encode_file(p, merges)
        payloads.append(payload)
        total += n
    name = f"shard{suffix}{shard_idx:05d}_{total}.tfrecord"
    from homebrewnlp_tpu.data import fs
    if fs.is_remote(out_dir):
        # write locally, then upload with bounded-retry backoff (the
        # reference's GCS loop, scripts/text2tfrecord.py:61-89)
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            local = os.path.join(td, name)
            write_records(local, payloads)
            out = out_dir.rstrip("/") + "/" + name
            fs.put_with_retry(local, out)
        return out
    out = os.path.join(out_dir, name)
    write_records(out, payloads)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", nargs="+", required=True)
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--tokenizer", default="",
                    help="tokenizer.json from tools/train_tokenizer.py "
                         "(omit for byte-level)")
    ap.add_argument("--files-per-shard", type=int, default=16)
    ap.add_argument("--procs", type=int, default=os.cpu_count())
    ap.add_argument("--post-cmd", default="",
                    help="shell command run per finished shard, {} = path "
                         "(e.g. 'gsutil cp {} gs://bucket/')")
    args = ap.parse_args()
    from homebrewnlp_tpu.data import fs
    if not fs.is_remote(args.output_dir):
        os.makedirs(args.output_dir, exist_ok=True)

    jobs = []
    for i in range(0, len(args.input), args.files_per_shard):
        jobs.append((len(jobs), args.input[i:i + args.files_per_shard],
                     args.output_dir, args.tokenizer))
    with multiprocessing.Pool(min(args.procs, len(jobs))) as pool:
        for out in pool.imap_unordered(_work, jobs):
            print(out, flush=True)
            if args.post_cmd:
                subprocess.run(args.post_cmd.replace("{}", out), shell=True,
                               check=False)


if __name__ == "__main__":
    main()
