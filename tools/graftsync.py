#!/usr/bin/env python
"""graftsync: the concurrency sheet — shared state and lock order, statically.

Builds the declared-lock model of the threaded host layer
(``analysis/concurrency.py``): every lock created through the
``homebrewnlp_tpu.sync`` factories under its ``<module>.<Class>.<attr>``
name, every attribute reachable from more than one thread identity, and the
lock-acquisition-order graph (nested ``with`` scopes plus calls into
lock-taking methods while holding).  Unguarded multi-thread writes are
ratcheted findings (``analysis/goldens/sync/shared_state.json`` — the count
may only go down); the order graph is pinned edge-for-edge
(``lock_order.json``) and cycle-checked.

``--validate`` is the honesty check: the serving/observability/data test
suites run in subprocesses with ``HBNLP_SYNC_RECORD=1``, which swaps every
declared lock for a recording proxy logging real ``held -> acquired`` edges
and held-while-blocking/joining events; every recorded edge must appear in
the static graph, or the model under-approximates reality.

Usage:
  python tools/graftsync.py                       # sheet
  python tools/graftsync.py --check               # CI gate (ratchet+golden)
  python tools/graftsync.py --update-goldens
  python tools/graftsync.py --validate            # runtime honesty check
  python tools/graftsync.py --json

Exit code: 0 ok; 1 when --check/--validate find errors; 2 on usage errors.
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the analyzer is pure-AST, but the recorded suites need the same pinned
# host platform as every other graft* tool so they run device-free
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()

#: suites whose threads exercise the declared locks (engine scheduler +
#: streams, SLO probes, exporter/watchdog, fleet reporter, feeder)
VALIDATE_SUITES = ("serve_engine_test.py", "serve_chunk_test.py",
                   "serve_slo_test.py", "serve_stream_test.py",
                   "serve_router_test.py", "serve_usage_test.py",
                   "obs_test.py", "fleet_obs_test.py", "data_test.py",
                   "flight_test.py")


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--check", action="store_true",
                   help="run the ratcheted shared-state rule and the pinned "
                        "lock-order golden; exit 1 on errors")
    p.add_argument("--update-goldens", action="store_true",
                   help="re-record analysis/goldens/sync/*.json")
    p.add_argument("--validate", action="store_true",
                   help="run the serving/obs/data suites under "
                        "HBNLP_SYNC_RECORD=1 and assert every recorded "
                        "lock-order edge appears in the static graph")
    p.add_argument("--suite", action="append", default=[],
                   help="override the --validate suite list (repeatable, "
                        "paths relative to tests/)")
    p.add_argument("--json", action="store_true", dest="as_json")
    return p.parse_args(argv)


def sheet(model, as_json: bool) -> dict:
    from homebrewnlp_tpu.analysis import concurrency as cc
    report = cc.shared_state_report(model)
    edges = {f"{a} -> {b}": sorted(locs)
             for (a, b), locs in model.edges.items()}
    cycles = cc._find_cycles(model.edges)
    out = {"locks": {lid: lk.kind for lid, lk in sorted(model.locks.items())},
           "edges": sorted(edges),
           "cycles": [list(c) for c in cycles],
           "unguarded": report,
           "warnings": [f.message for f in model.warnings]}
    if not as_json:
        print(f"\n== declared locks ({len(model.locks)})")
        for lid, kind in sorted(out["locks"].items()):
            print(f"  {kind:9s} {lid}")
        print(f"\n== lock-order edges ({len(edges)})")
        for e in sorted(edges):
            print(f"  {e}   [{edges[e][0]}]")
        if not edges:
            print("  (no nested acquisitions)")
        for cyc in cycles:
            print(f"  CYCLE: {' -> '.join(cyc)} -> {cyc[0]}")
        print(f"\n== unguarded multi-thread state ({len(report)})")
        for r in report:
            sites = ", ".join(f"{s['file']}:{s['line']}" for s in r["sites"])
            print(f"  {r['class']}.{r['attr']} (lock {r['lock'] or 'NONE'}) "
                  f"at {sites}")
        if not report:
            print("  (every shared attribute is guarded)")
        for w in out["warnings"]:
            print(f"  WARN {w}")
    return out


def run_validate(suites, as_json: bool):
    """Drive the threaded suites with the recording shim armed, then pin
    the recorded edges against the static graph."""
    from homebrewnlp_tpu.analysis import concurrency as cc
    from homebrewnlp_tpu.sync import load_records
    fd, record_file = tempfile.mkstemp(prefix="graftsync_", suffix=".jsonl")
    os.close(fd)
    suite_results = []
    try:
        for suite in suites:
            path = os.path.join(REPO, "tests", suite)
            if not os.path.exists(path):
                suite_results.append({"suite": suite, "rc": None,
                                      "error": "missing"})
                continue
            env = dict(os.environ, HBNLP_SYNC_RECORD="1",
                       HBNLP_SYNC_RECORD_FILE=record_file)
            t1 = time.time()
            r = subprocess.run(
                [sys.executable, "-m", "pytest", path, "-q", "-x",
                 "-m", "not slow", "-p", "no:cacheprovider",
                 "-p", "no:xdist", "-p", "no:randomly"],
                cwd=REPO, env=env, capture_output=True, text=True)
            suite_results.append({"suite": suite, "rc": r.returncode,
                                  "seconds": round(time.time() - t1, 1),
                                  "tail": r.stdout.strip().splitlines()[-1:]})
            if not as_json:
                tail = (r.stdout.strip().splitlines() or ["(no output)"])[-1]
                print(f"[graftsync] {suite}: rc={r.returncode} "
                      f"({time.time() - t1:.1f}s) {tail}", file=sys.stderr)
        records = load_records(record_file)
    finally:
        try:
            os.unlink(record_file)
        except OSError:
            pass
    findings = cc.validate_recorded(REPO, records)
    return findings, suite_results, records


def main(argv=None) -> int:
    args = parse_args(argv)
    from homebrewnlp_tpu.analysis import concurrency as cc
    rc = 0
    t0 = time.time()
    model = cc.build_model(REPO)
    out = sheet(model, args.as_json)
    findings = []
    if args.check or args.update_goldens:
        findings += cc.run_sync_rules(REPO,
                                      update_goldens=args.update_goldens)
    if args.validate:
        vfind, suite_results, records = run_validate(
            args.suite or VALIDATE_SUITES, args.as_json)
        findings += vfind
        out["validate"] = {
            "suites": suite_results,
            "recorded_events": len(records)}
        for s in suite_results:
            if s["rc"] not in (0,):  # a failing suite means nothing ran
                rc = max(rc, 1)
    out["findings"] = [{"rule": f.rule, "severity": f.severity,
                        "location": f.location, "message": f.message}
                       for f in findings]
    if any(f.severity == "error" for f in findings):
        rc = max(rc, 1)
    if args.as_json:
        print(json.dumps(out, indent=2))
    else:
        for f in findings:
            print(f"  {f.severity.upper():7s} [{f.rule}] {f.message}")
        print(f"\n[graftsync] total {time.time() - t0:.1f}s -> exit {rc}",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
