#!/usr/bin/env python
"""graftspmd: the implicit-collective sheet — what GSPMD will insert, statically.

Seeds every traced step input with its intended-mesh PartitionSpec and
propagates shardings through the jaxpr (analysis/spmd.py): contractions and
reductions over sharded dimensions surface as the implicit all-reduces /
all-gathers the partitioner will add at compile time, per mesh axis, with
payload bytes and an alpha-beta time estimate — the collectives the manual
census (graftcheck) cannot see.  Conflicting operand shardings (the
accidental-full-replication lint) are listed per equation.

``--validate-hlo`` is the honesty check: on CPU-compilable configs the real
train step is lowered + compiled under its real shardings and the predicted
census is compared against the collectives actually present in the
partitioned HLO text, within the documented tolerance
(analysis/spmd.py::HLO_TOLERANCE).

Usage:
  python tools/graftspmd.py --config configs/32big_mixer.json      # sheet
  python tools/graftspmd.py --all-configs --check                  # CI gate
  python tools/graftspmd.py --all-configs --update-goldens
  python tools/graftspmd.py --config configs/bpe65k_1chip.json \
      --world 2 --validate-hlo                                     # honesty
  python tools/graftspmd.py --config configs/x.json --json

Exit code: 0 ok; 1 when --check finds errors or a non-skipped
--validate-hlo comparison is out of tolerance; 2 on usage errors.
"""
import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# same virtual mesh as graftcheck/graftcost so predictions are reproducible
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--config", action="append", default=[],
                   help="config JSON to audit (repeatable)")
    p.add_argument("--all-configs", action="store_true")
    p.add_argument("--steps", default="train,decode",
                   help="comma list of steps (train,eval,decode,prefill)")
    p.add_argument("--world", type=int, default=0,
                   help="override tpu_size (e.g. --world 2 to validate a "
                        "1-chip config's sharded lowering on CPU devices)")
    p.add_argument("--check", action="store_true",
                   help="run the ratcheted implicit-collective rule "
                        "against the committed spmd goldens; exit 1 on "
                        "errors")
    p.add_argument("--update-goldens", action="store_true",
                   help="re-record analysis/goldens/spmd/<config>.json")
    p.add_argument("--validate-hlo", action="store_true",
                   help="lower+compile the train step and compare the "
                        "predicted census against the HLO collectives "
                        "(CPU-compilable configs; others report skipped)")
    p.add_argument("--json", action="store_true", dest="as_json")
    return p.parse_args(argv)


def _fmt(b) -> str:
    from homebrewnlp_tpu.analysis.cost_model import format_bytes
    return format_bytes(b).strip()


def sheet(traces, as_json: bool) -> dict:
    from homebrewnlp_tpu.analysis import spmd
    from homebrewnlp_tpu.analysis.cost_model import DEFAULT_VERDICT_DEVICE
    from homebrewnlp_tpu.analysis.graph_rules import intended_mesh
    from homebrewnlp_tpu.devices import resolve_device
    imesh = intended_mesh(traces.cfg)
    kind = (str(getattr(traces.cfg, "target_device", "") or "")
            or DEFAULT_VERDICT_DEVICE)
    spec = resolve_device(kind)
    out = {"config": traces.config_name,
           "intended_mesh": {k: int(v) for k, v in imesh.shape.items()},
           "device": kind, "steps": {}, "errors": dict(traces.errors)}
    for step, st in sorted(traces.steps.items()):
        r = spmd.propagate(st, imesh)
        row = {"seeded": bool(r.seeded), "error": r.error,
               "implicit": spmd.census(r, imesh),
               "conflicts": [{"location": c.location, "prim": c.prim,
                              "detail": c.detail} for c in r.conflicts]}
        if r.seeded and not r.error and spec is not None:
            comm = spmd.implicit_comm(r, imesh)
            row["ici_time_s_per_axis"] = {
                k: round(v, 6)
                for k, v in comm.times(dict(imesh.shape), spec).items()}
        out["steps"][step] = row
    if not as_json:
        mesh_s = " ".join(f"{k}{v}" for k, v in sorted(imesh.shape.items())
                          if v > 1) or "1chip"
        print(f"\n== {traces.config_name}  (intended mesh: {mesh_s}, "
              f"priced on {kind})")
        for step, row in out["steps"].items():
            if not row["seeded"] or row["error"]:
                print(f"  {step:8s} unaudited "
                      f"({row['error'] or 'no sharding seeds'})")
                continue
            if not row["implicit"]:
                print(f"  {step:8s} no implicit collectives "
                      f"(every contraction stays local)")
            for fam, axes in sorted(row["implicit"].items()):
                for ax, slot in sorted(axes.items()):
                    t = row.get("ici_time_s_per_axis", {}).get(ax)
                    # census rows are the as-LOWERED form (what the HLO
                    # validation pins); the axis time is priced at the
                    # tuned-lowering bound (best strategy + combiner) —
                    # the spread between them is the optimization headroom
                    print(f"  {step:8s} {fam:10s} x{slot['count']:<5d} over "
                          f"{ax:18s} payload {_fmt(slot['payload_bytes']):>11s}"
                          f"  moved {_fmt(slot['bytes']):>11s}"
                          + (f"  (axis priced ~{t * 1e3:.3f} ms at the "
                             f"tuned-lowering bound)"
                             if t is not None else ""))
            for c in row["conflicts"]:
                print(f"  {step:8s} CONFLICT {c['prim']} at {c['location']}: "
                      f"{c['detail']}")
        for step, err in traces.errors.items():
            print(f"  {step:8s} trace failed: {err}")
    return out


def validate(traces, as_json: bool) -> dict:
    from homebrewnlp_tpu.analysis import spmd
    v = spmd.validate_hlo(traces)
    if not as_json:
        if "skipped" in v:
            print(f"[graftspmd] {traces.config_name}: HLO validation "
                  f"skipped ({v['skipped']})", file=sys.stderr)
        else:
            p, h = v["predicted"], v["hlo"]
            verdict = "OK" if v["ok"] else "OUT OF TOLERANCE"
            ops = ", ".join("{} x{}".format(k, s["count"])
                            for k, s in sorted(h["ops"].items())) or "none"
            print(f"\n-- {traces.config_name} HLO cross-validation: {verdict}"
                  f"\n   predicted {p['count']} implicit collective(s), "
                  f"{_fmt(p['payload_bytes'])} payload"
                  f"\n   lowered   {h['count']} collective op(s), "
                  f"{_fmt(h['bytes'])} in partitioned HLO ({ops})")
            for r in v.get("reasons", []):
                print(f"   !! {r}")
    return v


def main(argv=None) -> int:
    args = parse_args(argv)
    config_paths = list(args.config)
    if args.all_configs:
        config_paths += sorted(glob.glob(os.path.join(REPO, "configs",
                                                      "*.json")))
    if not config_paths:
        print("nothing to do: pass --config or --all-configs",
              file=sys.stderr)
        return 2
    steps = tuple(s.strip() for s in args.steps.split(",") if s.strip())
    unknown = sorted(set(steps) - {"train", "eval", "decode", "prefill",
                                   "prefill_chunk"})
    if unknown:
        print(f"unknown step(s) {', '.join(unknown)}; valid: "
              f"train, eval, decode, prefill", file=sys.stderr)
        return 2
    if args.validate_hlo and "train" not in steps:
        print("--validate-hlo compiles the train step; include train in "
              "--steps", file=sys.stderr)
        return 2
    if args.world and (args.check or args.update_goldens):
        print("--check/--update-goldens pin the committed topology and "
              "cannot combine with --world", file=sys.stderr)
        return 2

    import contextlib

    from homebrewnlp_tpu.analysis import trace_config
    from homebrewnlp_tpu.analysis.spmd import check_implicit_collectives
    from homebrewnlp_tpu.config import Config
    results = []
    rc = 0
    t0 = time.time()
    quiet = (contextlib.redirect_stdout(sys.stderr) if args.as_json
             else contextlib.nullcontext())
    for path in config_paths:
        name = os.path.splitext(os.path.basename(path))[0]
        with open(path) as f:
            raw = json.load(f)
        raw.pop("_comment", None)
        if args.world:
            raw["tpu_size"] = int(args.world)
            name = f"{name}@world{args.world}"
        with quiet:
            try:
                cfg = Config(raw)
            except Exception as e:
                results.append({"config": name,
                                "error": f"{type(e).__name__}: {e}"})
                rc = max(rc, 1)
                continue
            traces = trace_config(cfg, name, steps=steps)
            row = sheet(traces, args.as_json)
            if args.check or args.update_goldens:
                findings = check_implicit_collectives(
                    traces, update_goldens=args.update_goldens)
                row["findings"] = [
                    {"severity": f.severity, "message": f.message}
                    for f in findings]
                n_err = sum(1 for f in findings if f.severity == "error")
                if n_err:
                    rc = max(rc, 1)
                if not args.as_json:
                    for f in findings:
                        print(f"  {f.severity.upper():7s} {f.message}")
            if args.validate_hlo:
                row["hlo_validation"] = validate(traces, args.as_json)
                if ("skipped" not in row["hlo_validation"]
                        and not row["hlo_validation"]["ok"]):
                    rc = max(rc, 1)
            results.append(row)
    if args.as_json:
        print(json.dumps(results, indent=2))
    else:
        print(f"\n[graftspmd] total {time.time() - t0:.1f}s -> exit {rc}",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
