"""Reproduce the in-image real-text corpus used by the 32ctx acceptance run
(docs/perf/32ctx_real_run.md): walks deterministic source/doc roots inside
the image (natural-language-rich .py/.rst/.md/.txt), concatenates up to a
byte budget, splits into N parts, and shards them with text2tfrecord.

Usage:
  python tools/build_corpus.py --out-dir datasets [--limit-mb 80] [--parts 8]

Produces datasets/corpus/part_* and datasets/corpus_tf/shardbytes*.tfrecord.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

ROOTS = ["/usr/lib/python3.11", "/opt/venv/lib/python3.12/site-packages"]
EXTS = (".py", ".rst", ".md", ".txt")
SKIP_DIRS = {"__pycache__", "tests", "test"}


def assemble(out_path: str, limit: int) -> int:
    roots = [r for r in ROOTS if os.path.isdir(r)]
    if not roots:
        raise SystemExit(f"none of the corpus roots exist: {ROOTS}")
    n = 0
    with open(out_path, "w", encoding="utf-8", errors="replace") as out:
        for root in roots:
            # lazy walk: sorting IN PLACE keeps the dirs[:] pruning effective
            # (sorted(os.walk(...)) would drain the generator before pruning)
            # and makes the traversal order machine-independent
            for dirpath, dirs, files in os.walk(root):
                dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
                for f in sorted(files):
                    if not f.endswith(EXTS):
                        continue
                    try:
                        text = open(os.path.join(dirpath, f), encoding="utf-8",
                                    errors="replace").read()
                    except OSError:
                        continue
                    out.write(text + "\n\n")
                    n += len(text)
                    if n > limit:
                        return n
    if n == 0:
        raise SystemExit("corpus roots contained no matching text files")
    return n


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="datasets")
    ap.add_argument("--limit-mb", type=int, default=80)
    ap.add_argument("--parts", type=int, default=8)
    args = ap.parse_args()
    corpus_dir = os.path.join(args.out_dir, "corpus")
    os.makedirs(corpus_dir, exist_ok=True)
    corpus = os.path.join(corpus_dir, "corpus.txt")
    n = assemble(corpus, args.limit_mb * 1024 * 1024)
    print(f"assembled {n} bytes -> {corpus}")
    for p in os.listdir(corpus_dir):  # stale parts from a previous --parts
        if p.startswith("part_"):
            os.remove(os.path.join(corpus_dir, p))
    subprocess.run(["split", "-n", str(args.parts), corpus,
                    os.path.join(corpus_dir, "part_")], check=True)
    parts = sorted(os.path.join(corpus_dir, p) for p in os.listdir(corpus_dir)
                   if p.startswith("part_"))
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "text2tfrecord.py")
    subprocess.run([sys.executable, tool, "--input", *parts, "--output-dir",
                    os.path.join(args.out_dir, "corpus_tf"),
                    "--files-per-shard", "1", "--procs", str(args.parts)],
                   check=True)


if __name__ == "__main__":
    main()
