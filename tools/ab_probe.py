"""On-chip A/B probe for config knobs: run a workload config with knob
overrides through the same harness as bench.bench_workload (median-of-5x10
step windows, host-pull timing) and print one JSON line per variant.

Usage:
  python tools/ab_probe.py --config 32mixer_group --batch 64 \
      --variant fused_group_linear=true --variant fused_group_linear=false
  python tools/ab_probe.py --config 32ctx_mixer --batch 8 \
      --variant blocked_causal_map=0 --variant blocked_causal_map=2

Each --variant is a comma-separated knob list (name=value; values parse as
JSON, falling back to string).  This is the single probe harness — the
round-5 fused-group and blocked-map measurements in docs/perf/README.md
used its per-knob predecessors with identical timing methodology.
"""
import argparse
import json
import sys
import time

import jax

sys.path.insert(0, ".")


def _parse_variant(spec: str) -> dict:
    knobs = {}
    for part in spec.split(","):
        name, _, value = part.partition("=")
        try:
            knobs[name] = json.loads(value)
        except json.JSONDecodeError:
            knobs[name] = value
    return knobs


def run(config: str, batch: int, knobs: dict) -> dict:
    from homebrewnlp_tpu.train import Trainer
    from homebrewnlp_tpu.utils import load_config, random_text_batch

    cfg = load_config(f"configs/{config}.json", use_checkpointing=False,
                      calc_accuracy=False, tpu_size=1,
                      slice_dtype="bfloat16", train_batch_size=batch,
                      **knobs)
    trainer = Trainer(cfg)
    batch_d = random_text_batch(cfg)
    state = trainer.init(batch_d)
    rng = jax.random.key(1)
    step_i = 0

    def run_steps(n, state):
        nonlocal step_i
        metrics = None
        for _ in range(n):
            state, metrics = trainer.step(state, batch_d,
                                          jax.random.fold_in(rng, step_i))
            step_i += 1
        return state, metrics

    state, metrics = run_steps(3, state)
    loss3 = float(metrics["loss"])
    windows = []
    for _ in range(5):
        t0 = time.perf_counter()
        state, metrics = run_steps(10, state)
        float(metrics["loss"])
        windows.append(time.perf_counter() - t0)
    dt = sorted(windows)[2]
    tokens = cfg.train_batch_size * cfg.sequence_length * 10
    return {"config": config, **knobs,
            "ms_per_step": round(dt / 10 * 1e3, 1),
            "tok_s": round(tokens / dt, 0), "loss_after_3": round(loss3, 4),
            "loss_after_53": round(float(metrics["loss"]), 4),
            "windows_step_ms": [round(w / 10 * 1e3, 1) for w in windows]}


def main() -> None:
    from homebrewnlp_tpu.utils import enable_compilation_cache

    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--batch", type=int, required=True)
    ap.add_argument("--variant", action="append", required=True,
                    help="comma-separated knob=value list; one run each")
    args = ap.parse_args()
    enable_compilation_cache(None)
    for spec in args.variant:
        print(json.dumps(run(args.config, args.batch, _parse_variant(spec))),
              flush=True)


if __name__ == "__main__":
    main()
