"""On-chip A/B probe: 32mixer_group with/without the fused group-linear
kernel pair (ops/pallas_group.py).  Same harness as bench.bench_workload
(median-of-5x10 windows, host-pull timing)."""
import json
import sys
import time

import jax

sys.path.insert(0, ".")


def run(fused: bool) -> dict:
    from homebrewnlp_tpu.train import Trainer
    from homebrewnlp_tpu.utils import load_config, random_text_batch

    cfg = load_config("configs/32mixer_group.json", use_checkpointing=False,
                      calc_accuracy=False, tpu_size=1,
                      slice_dtype="bfloat16", train_batch_size=64,
                      fused_group_linear=fused)
    trainer = Trainer(cfg)
    batch = random_text_batch(cfg)
    state = trainer.init(batch)
    rng = jax.random.key(1)
    step_i = 0

    def run_steps(n, state):
        nonlocal step_i
        metrics = None
        for _ in range(n):
            state, metrics = trainer.step(state, batch,
                                          jax.random.fold_in(rng, step_i))
            step_i += 1
        return state, metrics

    state, metrics = run_steps(3, state)
    loss3 = float(metrics["loss"])
    windows = []
    for _ in range(5):
        t0 = time.perf_counter()
        state, metrics = run_steps(10, state)
        float(metrics["loss"])
        windows.append(time.perf_counter() - t0)
    dt = sorted(windows)[2]
    tokens = cfg.train_batch_size * cfg.sequence_length * 10
    return {"fused_group": fused, "ms_per_step": round(dt / 10 * 1e3, 1),
            "tok_s": round(tokens / dt, 0), "loss_after_3": round(loss3, 4),
            "loss_after_53": round(float(metrics["loss"]), 4),
            "windows_step_ms": [round(w / 10 * 1e3, 1) for w in windows]}


if __name__ == "__main__":
    from homebrewnlp_tpu.utils import enable_compilation_cache
    enable_compilation_cache(None)
    for fused in (sys.argv[1:] or ["true", "false"]):
        print(json.dumps(run(fused == "true")), flush=True)
