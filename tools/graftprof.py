#!/usr/bin/env python
"""graftprof CLI: device-time attribution from jax.profiler traces.

Renders the per-scope / per-category attribution of a profiler capture
(docs/observability.md "Profile attribution"), exports flamegraph
collapsed stacks, diffs two captures (``--compare``), and reconciles the
measured decomposition against graftcost's static estimate (``--config``).

Sources (positional argument, auto-detected):

- a profiler output directory (``--profile`` dir / bench tempdir) — the
  newest ``plugins/profile/<session>/*.trace.json.gz`` is parsed, joined
  with the ``graftprof_op_map.json`` sidecar when present;
- a ``*.trace.json[.gz]`` file directly;
- a saved ``profile_summary.json`` (main.py writes one per ``--profile``
  run);
- a committed ``BENCH_r*.json`` line — the per-workload ``profile``
  sub-dict is adapted (pick the row with ``--workload``), so two BENCH
  rounds diff directly: ``graftprof.py BENCH_r06.json --compare
  BENCH_r07.json``.

Examples::

    python tools/graftprof.py /tmp/run/prof --steps 3
    python tools/graftprof.py /tmp/run/prof --flame /tmp/flame.txt
    python tools/graftprof.py BENCH_r06.json --compare BENCH_r07.json
    python tools/graftprof.py /tmp/run/prof --config configs/32big_mixer.json \
        --device v5e

Exit codes: 0 ok; 1 an ``--min-*`` attribution gate failed; 2 usage /
unreadable source.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import typing

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.obs import profile as P  # noqa: E402


def _summary_from_bench_row(row: dict, workload: str) -> P.ProfileSummary:
    """Adapt a BENCH workload ``profile`` sub-dict to a ProfileSummary —
    enough shape for tables and ``--compare`` (bench rows carry per-step
    figures; scopes re-inflate to window seconds)."""
    if not isinstance(row, dict) or "fractions" not in row:
        raise ValueError(
            f"workload {workload!r} carries no usable profile sub-dict "
            f"(got {sorted(row) if isinstance(row, dict) else type(row)})")
    steps = int(row.get("n_steps") or 1)
    decomp = dict(row.get("ms_per_step", {}))
    wall_ms = decomp.get("total", 0.0) * steps
    idle_ms = decomp.get("idle", 0.0) * steps
    return P.ProfileSummary(
        wall_s=wall_ms / 1e3,
        busy_s=(wall_ms - idle_ms) / 1e3,
        n_events=0, n_malformed=0, n_lanes=0, n_steps=steps,
        categories_s={}, collectives_s=dict(row.get("collectives_s", {})),
        scopes_s={k: v * steps / 1e3
                  for k, v in row.get("scopes_ms", {}).items()},
        top_ops=list(row.get("top_ops", [])),
        attributed_category_frac=row.get("attributed_category_frac", 0.0),
        attributed_scope_frac=row.get("attributed_scope_frac", 0.0),
        decomposition_ms_per_step=decomp,
        fractions=dict(row.get("fractions", {})))


def load_source(path: str, steps: typing.Optional[int],
                workload: str) -> P.ProfileSummary:
    """Resolve any supported source to a ProfileSummary (module doc)."""
    if os.path.isdir(path):
        s = P.capture_summary(path, n_steps=steps)
        if s is None:
            raise FileNotFoundError(
                f"no *.trace.json(.gz) under {path} (profiler plugin "
                f"directory absent)")
        return s
    if path.endswith((".trace.json", ".trace.json.gz", ".gz")):
        return P.summarize_trace(path, op_map=P.sidecar_op_map(path),
                                 n_steps=steps)
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "workloads" in doc:  # a BENCH line
        return _summary_from_bench_row(
            doc["workloads"].get(workload, {}).get("profile", {}), workload)
    if isinstance(doc, dict) and "traceEvents" in doc or isinstance(doc, list):
        events = doc if isinstance(doc, list) else doc["traceEvents"]
        return P.summarize_events(events, op_map=P.sidecar_op_map(path),
                                  n_steps=steps)
    if isinstance(doc, dict) and "wall_s" in doc:  # profile_summary.json
        return P.ProfileSummary.from_json(doc)
    raise ValueError(f"unrecognized source format: {path}")


def _collapse_depth(scopes_s: typing.Dict[str, float], depth: int
                    ) -> typing.Dict[str, float]:
    if depth <= 0:
        return dict(scopes_s)
    out: typing.Dict[str, float] = {}
    for k, v in scopes_s.items():
        key = "/".join(k.split("/")[:depth])
        out[key] = out.get(key, 0.0) + v
    return out


def render_summary(s: P.ProfileSummary, top: int, depth: int) -> str:
    lines = []
    steps = max(1, s.n_steps or 1)
    d = s.decomposition_ms_per_step
    lines.append(
        f"device window: {s.wall_s * 1e3:.3f} ms over {steps} step(s), "
        f"{s.n_events} events on {s.n_lanes} lane(s)"
        + (f", {s.n_malformed} malformed skipped" if s.n_malformed else ""))
    lines.append(
        f"ms/step: {d.get('total', 0.0):9.3f} = "
        f"mxu {d.get('mxu', 0.0):.3f} + hbm {d.get('hbm', 0.0):.3f} + "
        f"comm {d.get('comm', 0.0):.3f} + idle {d.get('idle', 0.0):.3f}")
    lines.append(
        f"attributed: category {s.attributed_category_frac:6.1%}   "
        f"scope {s.attributed_scope_frac:6.1%}")
    if s.categories_s:
        lines.append("")
        # lane-ms: SELF-time summed across concurrent device lanes
        # (thread-time), so totals can exceed the wall-clock ms/step above
        lines.append(f"{'category':<12} {'lane-ms/step':>12} {'share':>7}")
        busy = sum(s.categories_s.values()) or 1.0
        for cat, v in sorted(s.categories_s.items(), key=lambda kv: -kv[1]):
            lines.append(f"{cat:<12} {v * 1e3 / steps:>12.3f} "
                         f"{v / busy:>7.1%}")
    if s.collectives_s:
        lines.append("")
        lines.append(f"{'collective':<20} {'lane-ms/step':>12}")
        for kind, v in sorted(s.collectives_s.items(), key=lambda kv: -kv[1]):
            lines.append(f"{kind:<20} {v * 1e3 / steps:>12.3f}")
    scopes = _collapse_depth(s.scopes_s, depth)
    if scopes:
        total = sum(scopes.values()) or 1.0
        lines.append("")
        lines.append(f"{'scope':<56} {'lane-ms/step':>12} {'share':>7}")
        for k, v in sorted(scopes.items(), key=lambda kv: -kv[1])[:top]:
            lines.append(f"{k[:56]:<56} {v * 1e3 / steps:>12.3f} "
                         f"{v / total:>7.1%}")
    if s.top_ops:
        lines.append("")
        lines.append(f"{'op':<28} {'category':<11} "
                     f"{'scope':<40} {'lane-ms':>9}")
        for r in s.top_ops[:top]:
            lines.append(f"{r['op'][:28]:<28} {r['category']:<11} "
                         f"{r['scope'][:40]:<40} "
                         f"{r['self_s'] * 1e3 / steps:>9.3f}")
    return "\n".join(lines)


def render_diff(diff: dict, top: int) -> str:
    lines = []
    ms = diff["ms_per_step"]
    lines.append(f"ms/step: {ms['a']:.3f} -> {ms['b']:.3f} "
                 f"({ms['delta']:+.3f})")
    fd = diff["fractions_delta"]
    lines.append("fraction drift: " + "  ".join(
        f"{k} {fd[k]:+.3f}" for k in ("mxu", "hbm", "comm", "idle")))
    lines.append(f"scope coverage drift: "
                 f"{diff['attributed_scope_frac_delta']:+.3f}")
    rows = sorted(diff["scopes_ms"].items(),
                  key=lambda kv: -abs(kv[1]["delta_ms"]))[:top]
    if rows:
        lines.append("")
        lines.append(f"{'scope':<56} {'a ms':>9} {'b ms':>9} {'delta':>9}")
        for k, r in rows:
            lines.append(f"{k[:56]:<56} {r['a_ms']:>9.3f} {r['b_ms']:>9.3f} "
                         f"{r['delta_ms']:>+9.3f}")
    return "\n".join(lines)


def _reconcile_for_config(summary: P.ProfileSummary, config_path: str,
                          device: str) -> dict:
    from homebrewnlp_tpu.analysis import cost_model, trace_config
    from homebrewnlp_tpu.analysis.graph_rules import intended_mesh
    from homebrewnlp_tpu.utils import load_config
    cfg = load_config(config_path)
    name = os.path.splitext(os.path.basename(config_path))[0]
    traces = trace_config(cfg, name, steps=("train",))
    if "train" in traces.errors:
        raise RuntimeError(f"trace failed: {traces.errors['train']}")
    res = cost_model.config_resources(traces)["train"]
    kind = device or cfg.target_device or cost_model.DEFAULT_VERDICT_DEVICE
    pred = cost_model.step_static_times(res, dict(intended_mesh(cfg).shape),
                                        kind)
    out = P.reconcile(summary, pred)
    return {"device": kind, "verdict": res.verdict, "components": out}


def render_reconcile(rec: dict) -> str:
    lines = [f"graftcost reconciliation on {rec['device']} "
             f"(static verdict: {rec['verdict']})",
             f"{'component':<10} {'predicted ms':>13} {'measured ms':>12} "
             f"{'error':>8}"]
    for comp, r in rec["components"].items():
        pred = ("-" if r["predicted_ms"] is None
                else f"{r['predicted_ms']:.3f}")
        err = ("-" if r["prediction_error"] is None
               else f"{r['prediction_error']:+.1%}")
        lines.append(f"{comp:<10} {pred:>13} {r['measured_ms']:>12.3f} "
                     f"{err:>8}")
    return "\n".join(lines)


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="device-time attribution from jax.profiler traces")
    p.add_argument("trace", help="profiler dir / trace file / "
                   "profile_summary.json / BENCH_r*.json")
    p.add_argument("--steps", type=int, default=None,
                   help="steps captured in the window (per-step figures)")
    p.add_argument("--workload", default="32big_mixer",
                   help="workload row to read from a BENCH json source")
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--depth", type=int, default=0,
                   help="collapse scope paths to this depth (0 = full)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--flame", default="",
                   help="write flamegraph collapsed stacks to this path")
    p.add_argument("--compare", default="",
                   help="second source: print attribution drift (b - a)")
    p.add_argument("--config", default="",
                   help="config JSON: reconcile vs the graftcost estimate")
    p.add_argument("--device", default="",
                   help="device kind for --config (default: target_device "
                        "or the graftcost verdict default)")
    p.add_argument("--min-category-frac", type=float, default=None,
                   help="exit 1 when category attribution is below this")
    p.add_argument("--min-scope-frac", type=float, default=None,
                   help="exit 1 when scope attribution is below this")
    args = p.parse_args(argv)

    try:
        summary = load_source(args.trace, args.steps, args.workload)
    except Exception as e:
        print(f"graftprof: cannot load {args.trace}: {e}", file=sys.stderr)
        return 2

    if args.compare:
        try:
            other = load_source(args.compare, args.steps, args.workload)
        except Exception as e:
            print(f"graftprof: cannot load {args.compare}: {e}",
                  file=sys.stderr)
            return 2
        diff = P.diff_summaries(summary, other)
        print(json.dumps(diff, indent=1, sort_keys=True) if args.as_json
              else render_diff(diff, args.top))
        return 0

    rec = None
    if args.config:
        try:
            rec = _reconcile_for_config(summary, args.config, args.device)
        except Exception as e:
            print(f"graftprof: reconciliation failed: {e}", file=sys.stderr)
            return 2

    if args.flame:
        with open(args.flame, "w") as f:
            f.write("\n".join(P.collapsed_stacks(summary)) + "\n")
        print(f"flamegraph collapsed stacks -> {args.flame}",
              file=sys.stderr)

    if args.as_json:
        doc = summary.to_json()
        if rec is not None:
            doc["reconcile"] = rec
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(render_summary(summary, args.top, args.depth))
        if rec is not None:
            print()
            print(render_reconcile(rec))

    failed = []
    if (args.min_category_frac is not None
            and summary.attributed_category_frac < args.min_category_frac):
        failed.append(f"category attribution "
                      f"{summary.attributed_category_frac:.1%} < "
                      f"{args.min_category_frac:.1%}")
    if (args.min_scope_frac is not None
            and summary.attributed_scope_frac < args.min_scope_frac):
        failed.append(f"scope attribution "
                      f"{summary.attributed_scope_frac:.1%} < "
                      f"{args.min_scope_frac:.1%}")
    for msg in failed:
        print(f"graftprof: GATE FAILED: {msg}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
