"""Preemption babysitter: supervise a training run, restart on failure.

Port of /root/reference/scripts/run_manager.py — the reference's elastic
story (SURVEY.md §5.3): create the TPU, stream logs, poll health every few
minutes, and on unhealthiness kill the process group, recreate the TPU and
relaunch (:119-146), relying on checkpoint restore + deterministic data
resume for continuity.  Health here is two-signal: child liveness and a
training heartbeat (metrics.jsonl mtime — a hung-but-alive job is unhealthy
too, which the reference's TPU-state poll missed); TPU recreate hooks are
command templates so the gcloud recipe stays available without hardcoding
gcloud.

Usage:
  python tools/run_manager.py --cmd 'python main.py --model cfg.json --run_mode train' \
      --model-path runs/myrun [--recreate-cmd 'gcloud compute tpus ...'] \
      [--poll 300] [--max-restarts 100]
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def start(cmd: str, log_path: str) -> subprocess.Popen:
    log = open(log_path, "ab")
    return subprocess.Popen(cmd, shell=True, stdout=log, stderr=log,
                            preexec_fn=os.setsid)


def kill_group(proc: subprocess.Popen) -> None:
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except ProcessLookupError:
        pass


def heartbeat_age(model_path: str) -> float:
    metrics = os.path.join(model_path, "metrics.jsonl")
    if not os.path.exists(metrics):
        return float("inf")
    return time.time() - os.path.getmtime(metrics)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cmd", required=True, help="training command")
    ap.add_argument("--model-path", required=True,
                    help="run dir (heartbeat = metrics.jsonl mtime)")
    ap.add_argument("--log", default="", help="log file (default: "
                    "<model-path>/manager.log)")
    ap.add_argument("--log-remote", default="",
                    help="remote URL (gs://...) the log is uploaded to at "
                         "every health poll (the reference streams logs to "
                         "GCS, scripts/run_manager.py:26-56)")
    ap.add_argument("--poll", type=int, default=300, help="seconds between "
                    "health checks (reference polls every 5-10 min)")
    ap.add_argument("--stall-timeout", type=int, default=1800,
                    help="restart if no heartbeat for this many seconds")
    ap.add_argument("--startup-grace", type=int, default=1800,
                    help="allowance for compile/restore before first heartbeat")
    ap.add_argument("--recreate-cmd", default="",
                    help="run before each relaunch (e.g. gcloud tpus delete+"
                         "create recipe, reference :119-146)")
    ap.add_argument("--max-restarts", type=int, default=100)
    args = ap.parse_args()

    os.makedirs(args.model_path, exist_ok=True)
    log_path = args.log or os.path.join(args.model_path, "manager.log")
    restarts = 0
    while restarts <= args.max_restarts:
        started = time.time()
        proc = start(args.cmd, log_path)
        print(f"[manager] started pid {proc.pid} (restart {restarts})",
              flush=True)
        while True:
            time.sleep(args.poll)
            if args.log_remote:
                try:
                    sys.path.insert(0, os.path.dirname(
                        os.path.dirname(os.path.abspath(__file__))))
                    from homebrewnlp_tpu.data import fs
                    fs.put_with_retry(log_path, args.log_remote, retries=1)
                except Exception as e:  # keep supervising even if upload fails
                    print(f"[manager] log upload failed: {e!r}", flush=True)
            rc = proc.poll()
            if rc is not None:
                if rc == 0:
                    print("[manager] run completed cleanly", flush=True)
                    return
                print(f"[manager] child exited rc={rc}; restarting", flush=True)
                break
            age = heartbeat_age(args.model_path)
            elapsed = time.time() - started
            if age == float("inf"):
                # no heartbeat yet: healthy while within the compile/restore
                # startup grace window
                unhealthy = elapsed > args.startup_grace
            else:
                unhealthy = age > args.stall_timeout
            if unhealthy:
                print(f"[manager] heartbeat stale ({age:.0f}s, "
                      f"elapsed {elapsed:.0f}s); killing", flush=True)
                kill_group(proc)
                break
        restarts += 1
        if args.recreate_cmd:
            print(f"[manager] recreate: {args.recreate_cmd}", flush=True)
            subprocess.run(args.recreate_cmd, shell=True, check=False)
    print("[manager] max restarts exceeded", file=sys.stderr)
    sys.exit(1)


if __name__ == "__main__":
    main()
