"""homebrewnlp_tpu launcher (reference: /root/reference/main.py).

Usage: python3 main.py --model configs/32big_mixer.json --run_mode train
"""
from homebrewnlp_tpu.main import main

if __name__ == "__main__":
    main()
