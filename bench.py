"""Benchmark: tokens/sec/chip on the three reference workloads (BASELINE.md).

Primary metric (the driver's ``value``): the flagship 32big_mixer
architecture (full DSL/optimizer/dtype config, batch shrunk to fit one
chip), 5 timed windows of train steps, MEDIAN window.  Round 5 adds the two
other reference workload definitions (``32mixer_group`` throughput shape,
``32ctx_mixer`` long-context shape) as driver-captured rows in the same JSON
line — previously their numbers lived only in docs/perf — plus the
real-corpus numerics guard.

Prints ONE JSON line:

    {"metric": "tokens_per_sec_per_chip", "value": N, "unit": "tok/s/chip",
     "vs_baseline": R, ..., "workloads": {"32big_mixer": {...},
     "32mixer_group": {...}, "32ctx_mixer": {...}},
     "numerics_guard": {...}}

Each workload row is self-verifying: ``flops_per_step`` comes from XLA's
cost analysis of the exact compiled step (EXECUTED flops — includes the
recompute that the ``reversible_remat_blocks`` knob adds), and
``flops_per_step_algorithmic`` cost-analyzes the same step with the remat
knob off, so the line carries BOTH ``mfu`` (hardware utilization) and
``mfu_algorithmic`` (useful-work utilization) — VERDICT r4 item 3.  A
physically-possible mfu is <= 1.0; if the host<->chip transport distorts
wall-clock, ``distorted`` is set and the throughput must not be trusted.

``numerics_guard`` (VERDICT r4 item 9) replays the first N (default 300)
steps of the real-corpus 32ctx ACCEPTANCE run
(``configs/32ctx_accept_10k.json`` — the Run-B hyperparameters, LR 0.002 /
warmup 512, on the committed 84M-token corpus) through the full CLI train
path and asserts the warmup trajectory: fresh-init loss > 6.5, loss below
4.5 by step 120, final loss < 3.6 and finite (the committed 10k-run record
measured 7.71 -> 3.45@100 -> 2.76-class@300, docs/perf/32ctx_10k_run.md).
Round-5 correction: the guard originally ran ``32ctx_real_1chip.json``
(the reference's LR 0.01 at batch 8) — an operating point
docs/perf/32ctx_real_run.md already documents as UNSTABLE ("grad norms
climb and the loss regresses to 5-8"); a guard anchored there flakes
across environments (measured: the identical round-4-final code replays
at 5.67@120 today).  The stable Run-B point is what the 10k acceptance
record pins, so that is what the guard checks.

The MTF reference publishes no numbers (see BASELINE.md), so ``vs_baseline``
is computed against the first value this repo ever recorded
(bench_baseline.json, COMMITTED — 21040.8 tok/s on v5e) — i.e.
round-over-round speedup.

The async-dispatch PR adds two host-path fields (docs/performance.md): each
workload row carries ``host_blocked_s`` (median per-window wall time the
host spends blocked in the device->host loss pull that closes a timed
window, AFTER a block_until_ready excludes the window's remaining device
compute), and the flagship row carries ``compile_cache_hit``
(``warm_compile_s``: re-lower + re-compile the exact step after dropping
the in-process jit caches, with the persistent XLA cache warm — the
restart cost a user actually pays; ``hit`` flags whether it undercut half
the cold step compile, ``cold_compile_s``).

The low-precision PR (ISSUE 6) adds: per-workload ``compile_budget`` (+
top-level ``compile_ok``) evaluating ``compile_and_warmup_s`` against the
committed per-device budget in bench_compile_baseline.json (>20% over =
fail; tools/compile_ratchet.py runs the same check in CI);
``compile_cache_hit`` on every workload row (was flagship-only); a
``step``/``drain`` split inside every row's ``phases_s``; a complete
``flops_per_step``/``mfu`` under opaque pallas kernels (unfused-twin
lower bound, flagged ``flops_lower_bound``/``mfu_lower_bound`` — no more
``mfu: null``); and the ``quant`` probe on the 32mixer_group row
(docs/performance.md "Low-precision compute"): int8 step-time/MFU delta
plus the fixed-seed loss-trajectory accept gate.

The static-analysis cost-model PR (ISSUE 7) adds: ``hbm_peak_bytes`` on
EVERY workload row (max per-device ``memory_stats()`` peak, sampled right
after the timed windows so a failing telemetry/quant probe can no longer
drop it) and a ``resources`` validation hook — the graftcost prediction
(``predicted_peak_bytes`` + per-component breakdown, analysis/
cost_model.py) next to the measured peak and XLA's ``memory_analysis()``
figures, with ``prediction_error`` riding the BENCH trajectory so the
per-topology constants table (homebrewnlp_tpu/devices.py) is calibrated by
every TPU round.

The graftprof PR (ISSUE 8) adds a per-workload ``profile`` sub-dict: each
row auto-arms a ``jax.profiler`` window over ``HBNLP_BENCH_PROFILE_STEPS``
(default 5) steady-state steps — no hand-set ``profile_start`` needed —
and attributes the captured device time (obs/profile.py,
docs/observability.md "Profile attribution"): an ``ms_per_step``
decomposition into mxu + hbm + comm + idle, top-K ops, per-scope ms, the
comm fraction, and a ``reconcile`` block comparing each measured component
against graftcost's static alpha-beta / roofline estimate
(per-component ``prediction_error`` — how the constants table in
homebrewnlp_tpu/devices.py gets calibrated for *time*, the way the
``resources`` hook calibrates it for bytes).  Attribution drift is gated
by the committed per-device-kind baseline ``bench_profile_baseline.json``
(same shape + self-record semantics as the compile ratchet): any
decomposition fraction moving more than 0.15 absolute, or scope coverage
dropping more than 0.15, fails the row's ``baseline`` and the top-level
``profile_ok``.  The probe skips cleanly when the toolchain never writes
the profiler plugin directory.

The serving-SLO PR (ISSUE 9) adds a ``serving`` workload row: the REST
server comes up in-process on live (fresh-init) params, tools/graftload.py
drives it closed-loop with a fixed-seed prompt corpus, and the row records
client-measured e2e percentiles + goodput tok/s next to the server's own
TTFT / queue-wait / engine-busy histogram percentiles, the client-vs-server
reconciliation verdict, and ``serialization_overhead_s`` (client p50 e2e −
engine-busy p50 — the number the future continuous-batching PR must
shrink).  The core latency/goodput fields are recorded BEFORE the
server-scrape/reconcile sub-sections, so a probe failure cannot drop the
baseline comparison (same ordering discipline as ``hbm_peak_bytes``).
Latency/goodput drift is gated by the committed per-device-kind
``bench_serve_baseline.json`` (self-records on first contact, like the
compile budget): p50 e2e growing past 1.5x, or goodput dropping below
2/3x, fails the row's ``baseline`` and the top-level ``serve_ok``.
The token-level observability PR (ISSUE 14) extends the row with
``itl_p50/p95`` + ``decode_step_p50/p95`` (the server's per-token
histograms), ``prefill_stall_fraction`` (decode wall stalled on admission
prefill / total loop wall — the number the prefill-off-critical-path work
must shrink), and a contained streaming probe recording ``stream_ttft_s``
(client-measured first-SSE-chunk latency) plus the client-vs-server ITL
reconciliation; all three self-record into the baseline and ratchet in
``evaluate_serve_baseline``.

Env knobs (development / partial runs): ``HBNLP_BENCH_WORKLOADS`` is a
comma list or ``all`` (default); ``HBNLP_BENCH_GUARD_STEPS`` overrides the
guard length (0 disables); ``HBNLP_BENCH_QUANT=0`` skips the quant probe,
``HBNLP_BENCH_QUANT_DTYPE``/``_STEPS``/``_TOL`` tune it;
``HBNLP_BENCH_RESOURCES=0`` skips the cost-model prediction hook;
``HBNLP_BENCH_PROFILE=0`` skips the profile probe,
``HBNLP_BENCH_PROFILE_STEPS`` sizes its window; ``HBNLP_BENCH_SERVE=0``
skips the serving row, ``HBNLP_BENCH_SERVE_CONFIG``/``_REQUESTS``/
``_CONCURRENCY``/``_RESPONSE_LEN`` shape it.
"""
from __future__ import annotations

import json
import os
import time
import typing

import jax

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")
# committed per-device compile+warmup budgets (seconds per workload); the
# compile ratchet fails any row >20% above its budget — the silent
# 79 s -> 135 s slide of r04 -> r05 must not repeat (tools/compile_ratchet.py
# enforces the same file in CI over the committed BENCH_r*.json lines)
COMPILE_BASELINE_FILE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_compile_baseline.json")
#: tolerated compile_and_warmup_s ratio vs the committed budget
COMPILE_BUDGET_RATIO = 1.2
# committed per-device-kind device-time attribution baseline (graftprof):
# category fractions + scope coverage per workload; drift past the
# tolerance fails the row's profile baseline and the line's profile_ok
PROFILE_BASELINE_FILE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_profile_baseline.json")
#: steps in the per-workload profile capture window
PROFILE_PROBE_STEPS = int(os.environ.get("HBNLP_BENCH_PROFILE_STEPS", "5"))

# committed per-device-kind serving baseline (p50 e2e latency + goodput);
# self-records on first contact like the compile budget, then drift past
# the ratios below fails the serving row's baseline and the line's serve_ok
SERVE_BASELINE_FILE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_serve_baseline.json")
#: tolerated p50 e2e growth vs the committed serving baseline
SERVE_LATENCY_RATIO = 1.5
#: tolerated goodput floor vs the committed serving baseline
SERVE_GOODPUT_RATIO = 2.0 / 3.0
#: serving-row shape (env-overridable for development/smoke runs).  An
#: overridden shape never SELF-RECORDS a baseline: a smoke run on a fresh
#: device kind would otherwise commit its shape as the baseline and leave
#: every later default-shape run skipping the ratchet as "shape differs".
SERVE_SHAPE_OVERRIDDEN = any(
    os.environ.get(k) for k in
    ("HBNLP_BENCH_SERVE_CONFIG", "HBNLP_BENCH_SERVE_REQUESTS",
     "HBNLP_BENCH_SERVE_CONCURRENCY", "HBNLP_BENCH_SERVE_RESPONSE_LEN",
     "HBNLP_BENCH_SERVE_MAX_BATCH"))
SERVE_CONFIG = os.environ.get("HBNLP_BENCH_SERVE_CONFIG", "32big_mixer")
SERVE_REQUESTS = int(os.environ.get("HBNLP_BENCH_SERVE_REQUESTS", "24"))
SERVE_CONCURRENCY = int(os.environ.get("HBNLP_BENCH_SERVE_CONCURRENCY", "4"))
SERVE_RESPONSE_LEN = int(os.environ.get("HBNLP_BENCH_SERVE_RESPONSE_LEN",
                                        "16"))
#: decode lanes for the serving row's continuous-batching engine
#: (docs/observability.md "Continuous batching"); 1 = the pre-engine
#: serialized path (what the committed baselines were measured under)
SERVE_MAX_BATCH = int(os.environ.get("HBNLP_BENCH_SERVE_MAX_BATCH", "4"))
#: chunked-prefill A/B probe: when > 0, the serving row runs two extra
#: contained closed-loop drives over a mixed-length corpus — one with
#: serve_prefill_chunk_tokens=0 (monolithic admission prefill on the
#: decode thread) and one at this chunk size — and records itl_p95 +
#: prefill_stall_fraction for both arms under row["chunked_prefill"].
#: Deliberately NOT part of SERVE_SHAPE_OVERRIDDEN: the probe never
#: touches the main drive, so its presence must not skip the ratchet.
SERVE_CHUNK_TOKENS = int(os.environ.get("HBNLP_BENCH_SERVE_CHUNK", "0"))

# Peak table + MFU arithmetic shared with the LIVE utilization accounting
# (homebrewnlp_tpu/train/flops.py): bench's offline mfu and the run's
# /metrics mfu are the same math over the same cost-analyzed executable,
# so the two figures cannot drift.
from homebrewnlp_tpu.train.flops import peak_flops as _peak_flops  # noqa: E402
from homebrewnlp_tpu.train.flops import unfused_twin_flops  # noqa: E402

# The three reference workload definitions (BASELINE.md:19-21), batch shrunk
# to one chip.  slice_dtype (device-resident param copy) is forced to bf16:
# the config's f32 slices double every param transfer through the
# experimental host<->chip relay, which times out on the flagship's init
# program; rounds 1-4 recorded with bf16 residency, keeping the numbers
# comparable round-over-round.
_COMMON = dict(use_checkpointing=False, calc_accuracy=False, tpu_size=1,
               slice_dtype="bfloat16")
WORKLOADS = {
    # flagship: reference configs/32big_mixer.json:24-32, batch 1024 -> 8
    "32big_mixer": dict(train_batch_size=8),
    # throughput shape: reference configs/32mixer_group.json:26-32,
    # batch 4096 -> 64 (the round 3-4 harness shape)
    "32mixer_group": dict(train_batch_size=64),
    # long-context shape: reference configs/32ctx_mixer.json:26-32,
    # batch 256 -> 8
    "32ctx_mixer": dict(train_batch_size=8),
}


_CACHE_PREWARMED = None


def _cache_prewarmed() -> bool:
    """True when the persistent XLA cache dir already held entries BEFORE
    this process compiled anything — probed once, on the first call (the
    first workload's own init would otherwise populate the dir and make
    every later check read true)."""
    global _CACHE_PREWARMED
    if _CACHE_PREWARMED is None:
        cache_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
        _CACHE_PREWARMED = bool(
            cache_dir and os.path.isdir(os.path.expanduser(cache_dir))
            and os.listdir(os.path.expanduser(cache_dir)))
    return _CACHE_PREWARMED


def bench_workload(name: str, probe_loss: bool = False) -> dict:
    """Median-of-5 timed windows on one workload config; returns the row.

    ``probe_loss`` pins the fixed-seed 33-step comparison loss (the
    flagship's round-over-round numerics probe; schedule-rounding-sensitive,
    see BASELINE.md — the real guard is ``numerics_guard``)."""
    from homebrewnlp_tpu.obs.spans import SpanTracer
    from homebrewnlp_tpu.train import Trainer
    from homebrewnlp_tpu.utils import load_config, random_text_batch

    # local span tracer (NOT the process-ambient one): the per-phase wall
    # breakdown rides the JSON line as ``phases_s``
    tracer = SpanTracer(mirror_jax=False)
    t0_all = time.perf_counter()
    cache_prewarmed = _cache_prewarmed()  # probe BEFORE any compile
    with tracer.span("init"):
        cfg = load_config(f"configs/{name}.json", **_COMMON,
                          **WORKLOADS[name])
        trainer = Trainer(cfg)
        batch = random_text_batch(cfg)
        state = trainer.init(batch)
    rng = jax.random.key(1)

    # compile + XLA cost analysis of the exact step being timed (EXECUTED
    # flops: remat recompute included); timed separately so the
    # compile_cache_hit comparison below has an honest cold denominator.
    # On a warm-restart run the persistent cache serves THIS compile too —
    # cache_prewarmed (probed above) keeps the hit flag from reading a
    # fast "cold" compile as a cache miss
    t_cold = time.perf_counter()
    with tracer.span("compile"):
        cost = trainer.step_cost_analysis(state, batch)
    cold_compile_s = time.perf_counter() - t_cold
    flops_exec = float(cost.get("flops", 0.0))

    # algorithmic flops: the same step with the remat knob AND the fused
    # pallas kernel off — what the model's math costs as XLA-visible ops
    # (revnet's own backward replay is part of the algorithm and stays
    # counted; pallas kernels are opaque to cost analysis, so the unfused
    # chain is the only honest flop count)
    flops_algo = flops_exec
    kernel_opaque = bool(cfg.fused_mixer_block or cfg.fused_group_linear)
    if cfg.reversible_remat_blocks or kernel_opaque or cfg.blocked_causal_map:
        from homebrewnlp_tpu.optim import Optimizer
        # blocked_causal_map also resets to 0: the algorithmic count is the
        # CONVENTIONAL masked-einsum implementation, so mfu_algorithmic
        # stays comparable round-over-round while mfu (executed) shows the
        # carved-triangle saving
        cfg_algo = load_config(f"configs/{name}.json", **_COMMON,
                               **WORKLOADS[name],
                               reversible_remat_blocks=False,
                               fused_mixer_block=False,
                               fused_group_linear=False,
                               blocked_causal_map=0)
        # params/opt-state/axes are identical either way: adopt them from
        # the measured trainer instead of re-initializing on device
        tr_algo = Trainer(cfg_algo)
        tr_algo.axes = trainer.axes
        tr_algo.optimizer = Optimizer(cfg_algo, trainer.axes)
        cost_algo = tr_algo.step_cost_analysis(state, batch)
        flops_algo = float(cost_algo.get("flops", 0.0)) or flops_exec

    # complete hardware-flops figure even under opaque pallas kernels
    # (BENCH_r05 reported flops_executed_partial + mfu null for the group
    # workload): the unfused twin's executed count is an explicit LOWER
    # BOUND on the fused step's (the kernels run the same math plus
    # in-kernel backward recompute — train/flops.py::unfused_twin_flops),
    # so the row carries a usable flops_per_step and a floor mfu, flagged
    # flops_lower_bound instead of silently incomplete
    flops_lower_bound = False
    if kernel_opaque:
        if cfg.reversible_remat_blocks or cfg.blocked_causal_map:
            # the twin keeps remat/blocked-map exactly as timed; flops_algo
            # above reset them, so it is NOT the right bound here
            flops_exec = max(flops_exec,
                             unfused_twin_flops(trainer, state, batch))
        else:
            # remat and blocked-map are off: the unfused twin IS the
            # cfg_algo analysis already paid for — no third lowering
            flops_exec = max(flops_exec, flops_algo)
        flops_lower_bound = True

    # fixed seed schedule: step i always uses fold_in(rng, i), so the probe
    # loss is reproducible round over round
    step_i = 0

    def run_steps(n, state):
        nonlocal step_i
        metrics = None
        for _ in range(n):
            # per-step dispatch span: phases_s separates dispatch ("step")
            # from the host pull closing each window ("drain"), so the
            # group path's compile/feed/step split is visible per workload
            with tracer.span("step"):
                state, metrics = trainer.step(state, batch,
                                              jax.random.fold_in(rng, step_i))
            step_i += 1
        return state, metrics

    # warmup: compile + let the device path reach steady state
    with tracer.span("warmup"):
        state, metrics = run_steps(3, state)
        float(metrics["loss"])
    compile_and_warmup_s = time.perf_counter() - t0_all

    # 5 windows of 10 steps.  Each window ends with a HOST PULL of the loss
    # scalar, not block_until_ready: the experimental axon relay acks
    # readiness before execution completes (round-1 bench measured
    # 6.5 ms/step = 12x chip peak), but a device->host transfer of the final
    # step's output cannot complete until the whole dependency chain has.
    # The figure of record is the MEDIAN window (the relay's wall-clock
    # jitter between windows is several percent); best + raw windows expose
    # the spread.  The fixed-seed comparison loss stays pinned to step 33
    # (the figure rounds 1-2 recorded).
    n_steps = 10
    window_dts = []
    host_blocked = []
    loss_after = None
    pin_step = step_i + 3 * n_steps
    for _ in range(5):
        t0 = time.perf_counter()
        with tracer.span("window"):
            state, metrics = run_steps(n_steps, state)
            # host_blocked_s: wall time the host spends BLOCKED on the
            # device->host pull that ends the window — the async train loop
            # hides exactly this class of sync behind its in-flight window
            # (docs/performance.md), so the bench line makes it visible.
            # block_until_ready first: it waits for the window's remaining
            # DEVICE compute (which belongs to the window, not to host
            # blocking), so t_sync..t_end times only the transfer/sync
            jax.block_until_ready(state)
            t_sync = time.perf_counter()
            with tracer.span("drain"):
                window_loss = float(metrics["loss"])
            t_end = time.perf_counter()
        host_blocked.append(t_end - t_sync)
        window_dts.append(t_end - t0)
        if step_i == pin_step or loss_after is None and step_i >= pin_step:
            loss_after = window_loss
    dt = sorted(window_dts)[len(window_dts) // 2]
    best_dt = min(window_dts)
    tokens = cfg.train_batch_size * cfg.sequence_length * n_steps
    n_chips = max(1, len(jax.devices()))
    peak = _peak_flops(jax.devices()[0].device_kind)

    row = {
        "value": round(tokens / dt / n_chips, 2),
        "best": round(tokens / best_dt / n_chips, 2),
        "windows_tok_s": [round(tokens / w / n_chips, 1)
                          for w in window_dts],
        "ms_per_step": round(dt / n_steps * 1e3, 3),
        "flops_per_step": flops_exec,
        "flops_per_step_algorithmic": flops_algo,
        "mfu": None, "mfu_algorithmic": None,
        "compile_and_warmup_s": round(compile_and_warmup_s, 1),
        # median per-window host-blocked time (the loss pull closing each
        # window); the rest of the window is async-dispatched device work
        "host_blocked_s": round(sorted(host_blocked)[len(host_blocked) // 2],
                                4),
        # per-phase wall breakdown from the span tracer ("window" totals all
        # 5 timed windows; "init"/"compile"/"warmup" decompose the startup
        # envelope compile_and_warmup_s summarizes)
        "phases_s": {k: round(v, 3) for k, v in
                     tracer.phase_totals().items()},
    }
    # hbm_peak_bytes rides EVERY workload row, recorded immediately after
    # the timed windows and BEFORE the telemetry/quant probes below — a
    # probe failure (they donate `state` and can die on exotic toolchains)
    # previously dropped the whole prediction-vs-measured comparison row
    # (ISSUE 7 satellite).  None on backends without memory_stats (CPU).
    row["hbm_peak_bytes"] = _hbm_peak_bytes()

    _res_cache: list = []

    def static_train_resources():
        # ONE abstract re-trace (seconds) shared by the resources and
        # profile hooks below; lazy so either hook can be env-skipped
        if not _res_cache:
            from homebrewnlp_tpu.analysis import cost_model, trace_config
            traces = trace_config(cfg, name, steps=("train",))
            _res_cache.append(cost_model.config_resources(traces)
                              .get("train"))
        return _res_cache[0]

    # static cost-model validation hook (docs/static_analysis.md "Resource
    # cost model"): the predicted per-device peak next to the measured
    # memory_stats() peak and XLA's own memory analysis, so
    # prediction_error joins the BENCH trajectory and the constants table
    # in homebrewnlp_tpu/devices.py gets calibrated every TPU round
    if os.environ.get("HBNLP_BENCH_RESOURCES", "1") != "0":
        try:
            row["resources"] = _resource_prediction(
                trainer, row["hbm_peak_bytes"], static_train_resources())
        except Exception as e:  # noqa: BLE001 - must not kill the line
            row["resources"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    # graftprof device-time attribution (module docstring; ISSUE 8): a
    # short auto-armed profiler window over the live state, parsed into
    # the ms_per_step decomposition + prediction_error vs graftcost.
    # Steps through trainer.step donate-and-return `state`, so the probe
    # hands the post-window state back for the probes below
    if os.environ.get("HBNLP_BENCH_PROFILE", "1") != "0":
        try:
            row["profile"], state = _profile_probe(
                name, cfg, trainer, state, batch, static_train_resources)
        except Exception as e:  # noqa: BLE001 - must not kill the line
            row["profile"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    if kernel_opaque:
        # flops_per_step is the unfused twin's LOWER BOUND (see above) —
        # the flags describe the flop count itself, peak table or not
        row["flops_executed_partial"] = True  # r05-compatible flag
        row["flops_lower_bound"] = flops_lower_bound
    if peak and flops_exec:
        # under opaque pallas kernels mfu inherits the lower bound — a
        # floor, flagged, never null
        row["mfu"] = round(flops_exec * n_steps / dt / (peak * n_chips), 4)
        if kernel_opaque:
            row["mfu_lower_bound"] = True
        row["mfu_algorithmic"] = round(
            flops_algo * n_steps / dt / (peak * n_chips), 4)
    if probe_loss:
        row["loss_after_n_steps"] = round(loss_after, 4)
        row["n_steps_total"] = step_i
    # compile_cache_hit (EVERY workload since the compile-ratchet PR; it
    # was flagship-only before): drop the in-process jit caches and
    # re-lower + re-compile the exact step.  bench.main enables the
    # persistent XLA cache, and the cold compile above just populated it,
    # so this measures the warm-restart path: tracing/lowering re-runs, the
    # XLA compile is served from disk.  A warm second bench run shows the
    # same effect in compile_and_warmup_s itself.
    t_warm = time.perf_counter()
    if hasattr(jax, "clear_caches"):
        jax.clear_caches()
    tr_warm = Trainer(cfg)
    tr_warm.axes = trainer.axes
    tr_warm.optimizer = trainer.optimizer
    tr_warm.step_cost_analysis(state, batch)
    warm_s = time.perf_counter() - t_warm
    # hit compares against the COLD lower+compile of the same step (not
    # the whole init+warmup envelope, which would flatter a cold cache).
    # When the cache was prewarmed, cold_compile_s was ITSELF served
    # from disk (warm ~= "cold"), which is a hit, not a miss.
    row["compile_cache_hit"] = {
        "warm_compile_s": round(warm_s, 1),
        "cold_compile_s": round(cold_compile_s, 1),
        "cache_prewarmed": cache_prewarmed,
        "hit": bool(cache_prewarmed or warm_s < 0.5 * cold_compile_s),
    }
    if probe_loss:
        if os.environ.get("HBNLP_BENCH_TELEMETRY", "1") != "0":
            # device-telemetry overhead probe (docs/observability.md): the
            # same workload with in-graph numerics armed.  Acceptance:
            # tokens/s within 2% of the base row, and the telemetry graph's
            # cost-analyzed flops within 1% of flops_per_step (the norm
            # reductions are O(params), noise next to the matmuls) — both
            # ratios ride the line.  LAST probe in the row: its step calls
            # donate `state`
            try:
                row["telemetry"] = _telemetry_probe(
                    name, trainer, state, batch, flops_exec, row["value"])
            except Exception as e:  # noqa: BLE001 - must not kill the line
                row["telemetry"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    if (name == "32mixer_group"
            and os.environ.get("HBNLP_BENCH_QUANT", "1") != "0"):
        # int8 accept gate for the grouped-mixer chain (ISSUE 6): the
        # quantized step's tok/s + ms_per_step delta vs this base row, and
        # a numerics_guard-style fixed-seed loss-trajectory comparison.
        # LAST probe in the row: its step calls donate `state`
        try:
            row["quant"] = _quant_probe(name, trainer, state, batch,
                                        flops_algo, row["value"],
                                        row["ms_per_step"])
        except Exception as e:  # noqa: BLE001 - must not kill the line
            row["quant"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    return row


def _hbm_peak_bytes():
    """Max per-device ``memory_stats()`` peak, or None where the backend
    exposes none (CPU).  Never raises — the field must survive any probe."""
    try:
        peaks = []
        for d in jax.local_devices():
            stats = d.memory_stats() or {}
            peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
            if peak is not None:
                peaks.append(int(peak))
        return max(peaks) if peaks else None
    except Exception:  # noqa: BLE001
        return None


def _resource_prediction(trainer, measured_peak, res):
    """Static cost-model prediction for the workload's exact config
    (``res`` = the shared ``static_train_resources()`` StepResources) +
    the compiled step's XLA memory analysis, with ``prediction_error``
    vs the measured device peak when available."""
    out = {}
    if res is not None:
        out["predicted_peak_bytes"] = int(res.hbm["peak"])
        out["predicted_hbm"] = {k: int(v) for k, v in res.hbm.items()}
        out["verdict"] = res.verdict
    compiled = getattr(trainer, "_compiled", None)
    if compiled is not None:
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                out["xla_temp_bytes"] = int(ma.temp_size_in_bytes)
                out["xla_argument_bytes"] = int(ma.argument_size_in_bytes)
        except Exception:  # noqa: BLE001 - optional on some backends
            pass
    if measured_peak and out.get("predicted_peak_bytes"):
        out["measured_peak_bytes"] = int(measured_peak)
        out["prediction_error"] = round(
            out["predicted_peak_bytes"] / measured_peak - 1.0, 4)
    return out


def _profile_probe(name: str, cfg, trainer, state, batch, static_res):
    """One auto-armed capture window (docs/observability.md "Profile
    attribution"): profile ``PROFILE_PROBE_STEPS`` steps of the workload's
    live state, dump the AOT executable's op->scope sidecar, attribute the
    device time, and reconcile the measured mxu/hbm/comm split against
    graftcost's static estimate (``static_res`` = the shared lazy
    ``static_train_resources`` callable).  Returns ``(profile row,
    state)`` — the steps donate state buffers, so the caller must adopt
    the new state; once the window has stepped, parse/attribution
    failures are contained in the row's ``error`` field rather than
    raised, so the donated-and-returned state is never lost to the
    caller's except handler.  Skips cleanly (``skipped`` field) when the
    toolchain writes no profiler plugin directory."""
    import shutil
    import tempfile

    from homebrewnlp_tpu.obs import profile as profile_mod

    n = PROFILE_PROBE_STEPS
    rng = jax.random.key(5)
    tmp = tempfile.mkdtemp(prefix=f"bench_prof_{name}_")
    stepped = False
    try:
        try:
            jax.profiler.start_trace(tmp)
            try:
                for i in range(n):
                    state, metrics = trainer.step(state, batch,
                                                  jax.random.fold_in(rng, i))
                jax.block_until_ready(state)
                stepped = True
            finally:
                jax.profiler.stop_trace()
            profile_mod.write_op_map_for(trainer, tmp)
            summary = profile_mod.capture_summary(tmp, n_steps=n)
        except Exception as e:  # noqa: BLE001 - see docstring
            if not stepped:
                raise
            return {"error": f"{type(e).__name__}: {e}"[:300]}, state
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if summary is None:
        return {"skipped": "no profiler trace written "
                           "(plugin directory absent)"}, state
    steps = max(1, n)
    scopes_ms = {k: round(v * 1e3 / steps, 4)
                 for k, v in list(summary.scopes_s.items())[:8]}
    row = {
        "n_steps": n,
        "ms_per_step": summary.decomposition_ms_per_step,
        "fractions": summary.fractions,
        "comm_fraction": summary.fractions.get("comm", 0.0),
        "attributed_category_frac": summary.attributed_category_frac,
        "attributed_scope_frac": summary.attributed_scope_frac,
        "top_ops": summary.top_ops[:5],
        "scopes_ms": scopes_ms,
        "collectives_s": summary.collectives_s,
    }
    # measured vs graftcost static estimate — per-component
    # prediction_error; null on CPU/unknown kinds, where the constants
    # table makes no time claims
    try:
        from homebrewnlp_tpu.analysis import cost_model
        from homebrewnlp_tpu.analysis.graph_rules import intended_mesh
        res = static_res()
        pred = None
        kind = jax.devices()[0].device_kind
        if res is not None:
            pred = cost_model.step_static_times(
                res, dict(intended_mesh(cfg).shape), kind)
        row["reconcile"] = profile_mod.reconcile(summary, pred)
        row["prediction_device"] = kind if pred is not None else None
    except Exception as e:  # noqa: BLE001 - reconcile is best-effort
        row["reconcile"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    return row, state


def _telemetry_probe(name: str, trainer, state, batch, flops_base: float,
                     base_tok_s: float) -> dict:
    """Timed windows of the telemetry-enabled step (telemetry_interval=1,
    anomaly_policy=skip_step — the most expensive configuration: sentinels,
    norms AND the in-graph update mask).  Returns tokens/s, the ratio vs
    the base row, and the flops agreement with the base cost analysis."""
    from homebrewnlp_tpu.optim import Optimizer
    from homebrewnlp_tpu.train import Trainer
    from homebrewnlp_tpu.utils import load_config

    cfg_tel = load_config(f"configs/{name}.json", **_COMMON,
                          **WORKLOADS[name], telemetry_interval=1,
                          anomaly_policy="skip_step")
    tr = Trainer(cfg_tel)
    tr.axes = trainer.axes
    tr.optimizer = Optimizer(cfg_tel, trainer.axes)
    cost = tr.step_cost_analysis(state, batch)
    flops_tel = float(cost.get("flops", 0.0))
    rng = jax.random.key(2)
    for i in range(3):  # warmup the telemetry executable
        state, metrics = tr.step(state, batch, jax.random.fold_in(rng, i))
    float(metrics["loss"])
    n_steps, dts = 10, []
    for w in range(3):
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, metrics = tr.step(state, batch,
                                     jax.random.fold_in(rng, 100 + w * 16 + i))
        jax.block_until_ready(state)
        float(metrics["loss"])
        dts.append(time.perf_counter() - t0)
    dt = sorted(dts)[len(dts) // 2]
    tokens = cfg_tel.train_batch_size * cfg_tel.sequence_length * n_steps
    tok_s = tokens / dt / max(1, len(jax.devices()))
    return {
        "value": round(tok_s, 2),
        "ratio_vs_base": round(tok_s / base_tok_s, 4) if base_tok_s else None,
        "flops_per_step": flops_tel,
        "flops_ratio_vs_base": (round(flops_tel / flops_base, 4)
                                if flops_base else None),
    }


#: quant probe knobs (env-overridable for development runs)
QUANT_PROBE_BLOCKS = ("bottleneck_group_linear",)
QUANT_GATE_STEPS = int(os.environ.get("HBNLP_BENCH_QUANT_STEPS", "30"))
QUANT_GATE_REL_TOL = float(os.environ.get("HBNLP_BENCH_QUANT_TOL", "0.1"))


def evaluate_quant_gate(base_losses, quant_losses,
                        rel_tol: float = QUANT_GATE_REL_TOL) -> dict:
    """Pure accept-gate evaluation (unit-testable without a chip), in the
    numerics_guard mold: the quantized trajectory must be finite, must
    train (final < first), and must track the high-precision trajectory
    within ``rel_tol`` relative deviation at every compared step.  A False
    verdict is a measured REJECT — the knob stays default-off and the
    numbers ride the line either way (repo perf culture)."""
    if not base_losses or len(base_losses) != len(quant_losses):
        return {"pass": False, "error": "trajectory length mismatch"}
    finite = all(l == l and abs(l) != float("inf")
                 for l in base_losses + quant_losses)
    devs = [abs(q - b) / max(abs(b), 1.0)
            for b, q in zip(base_losses, quant_losses)]
    max_dev = max(devs) if devs else 0.0
    trains = quant_losses[-1] < quant_losses[0]
    return {"pass": bool(finite and trains and max_dev <= rel_tol),
            "finite": bool(finite),
            "trains": bool(trains),
            "max_rel_dev": round(max_dev, 4),
            "rel_tol": rel_tol,
            "steps": len(base_losses),
            "loss_first": round(quant_losses[0], 4),
            "loss_final": round(quant_losses[-1], 4),
            "loss_final_base": round(base_losses[-1], 4)}


def _loss_trajectory(cfg, batch, n_steps: int):
    """Fresh-init fixed-seed loss trajectory (one float per step) — the
    deterministic comparison arm of the quant accept gate.  Same init seed
    and rng schedule for both arms, so the only difference between the
    base and quant trajectories is the quantized forward itself."""
    from homebrewnlp_tpu.train import Trainer
    tr = Trainer(cfg)
    state = tr.init(batch)
    rng = jax.random.key(3)
    losses = []
    for i in range(n_steps):
        state, metrics = tr.step(state, batch, jax.random.fold_in(rng, i))
        losses.append(float(metrics["loss"]))
    return losses


def _quant_probe(name: str, trainer, state, batch, flops_algo: float,
                 base_tok_s: float, base_ms: float) -> dict:
    """The int8 (or fp8, HBNLP_BENCH_QUANT_DTYPE) grouped-mixer probe:

    1. timed windows of the quantized step against the SAME live state —
       tok/s, ms_per_step, and their delta vs the base row (the mfu delta
       follows from ms_per_step: both rows share flops_algorithmic);
    2. the accept gate: two fresh-init fixed-seed loss trajectories (quant
       off / on) compared by ``evaluate_quant_gate``.
    """
    from homebrewnlp_tpu.optim import Optimizer
    from homebrewnlp_tpu.train import Trainer
    from homebrewnlp_tpu.utils import load_config

    qdtype = os.environ.get("HBNLP_BENCH_QUANT_DTYPE", "int8")
    quant_over = dict(quant_blocks=list(QUANT_PROBE_BLOCKS),
                      quant_dtype=qdtype)
    cfg_q = load_config(f"configs/{name}.json", **_COMMON, **WORKLOADS[name],
                        **quant_over)
    tr = Trainer(cfg_q)
    tr.axes = trainer.axes
    tr.optimizer = Optimizer(cfg_q, trainer.axes)
    tr.step_cost_analysis(state, batch)  # compile (kept AOT executable)
    rng = jax.random.key(4)
    for i in range(3):  # warmup
        state, metrics = tr.step(state, batch, jax.random.fold_in(rng, i))
    float(metrics["loss"])
    n_steps, dts = 10, []
    for w in range(3):
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, metrics = tr.step(state, batch,
                                     jax.random.fold_in(rng, 100 + w * 16 + i))
        jax.block_until_ready(state)
        float(metrics["loss"])
        dts.append(time.perf_counter() - t0)
    dt = sorted(dts)[len(dts) // 2]
    tokens = cfg_q.train_batch_size * cfg_q.sequence_length * n_steps
    n_chips = max(1, len(jax.devices()))
    tok_s = tokens / dt / n_chips
    peak = _peak_flops(jax.devices()[0].device_kind)
    row = {
        "quant_dtype": qdtype,
        "quant_blocks": list(QUANT_PROBE_BLOCKS),
        "value": round(tok_s, 2),
        "ms_per_step": round(dt / n_steps * 1e3, 3),
        "ratio_vs_base": round(tok_s / base_tok_s, 4) if base_tok_s else None,
        "ms_delta_vs_base": (round(dt / n_steps * 1e3 - base_ms, 3)
                             if base_ms else None),
    }
    if peak and flops_algo:
        # same algorithmic flop count as the base row by construction, so
        # the two mfu_algorithmic figures ARE the MFU delta
        row["mfu_algorithmic"] = round(
            flops_algo * n_steps / dt / (peak * n_chips), 4)
    gate_steps = QUANT_GATE_STEPS
    if gate_steps > 0:
        cfg_base = load_config(f"configs/{name}.json", **_COMMON,
                               **WORKLOADS[name])
        row["accept"] = evaluate_quant_gate(
            _loss_trajectory(cfg_base, batch, gate_steps),
            _loss_trajectory(cfg_q, batch, gate_steps))
    return row


def _stream_delta_reconcile(client: dict, pre_text: str,
                            post_text: str) -> dict:
    """Reconcile the streaming probe's CLIENT percentiles against the
    server histograms' pre/post scrape DELTA — exactly the probe's own
    requests, even when the cumulative series is dominated by the main
    (queued, non-streamed) drive.  Same per-series tolerance as graftload:
    ``bucket_width_at(p50) + max(0.05, 0.25 * p50)``."""
    import math

    import graftload

    from homebrewnlp_tpu.obs.registry import bucket_quantile, bucket_width_at
    pre = graftload.parse_prom(pre_text)
    post = graftload.parse_prom(post_text)
    arms: dict = {}
    for key, series in (("itl", "hbnlp_serve_itl_seconds"),
                        ("ttft", "hbnlp_serve_ttft_seconds")):
        cp = (client.get(f"{key}_s") or {}).get("p50")
        snap_post = graftload.histogram_snapshot(post, series)
        if cp is None or snap_post is None:
            continue
        snap_pre = graftload.histogram_snapshot(pre, series)
        counts = list(snap_post["counts"])
        if (snap_pre is not None
                and snap_pre["buckets"] == snap_post["buckets"]):
            counts = [b - a for a, b in zip(snap_pre["counts"], counts)]
        sp = bucket_quantile(snap_post["buckets"], counts, 0.5)
        if sp is None:
            continue
        width = bucket_width_at(snap_post["buckets"], sp)
        tol = (width if width != math.inf else 0.0) + max(0.05, 0.25 * sp)
        arms[key] = {"client_p50_s": round(cp, 6),
                     "server_p50_s": round(sp, 6),
                     "abs_diff_s": round(abs(cp - sp), 6),
                     "tolerance_s": round(tol, 6),
                     "within_tolerance": bool(abs(cp - sp) <= tol)}
    return arms


def bench_serving() -> dict:
    """The ``serving`` workload row (docs/observability.md "Serving SLOs"):
    bring the REST server up in-process on live fresh-init params, drive it
    with tools/graftload.py (closed loop, fixed-seed corpus), and record
    client-side latency/goodput next to the server's own SLO histograms.

    Field-ordering contract: the core fields the baseline gate consumes
    (``e2e_p50_s``, ``goodput_tok_s``) are written into the row BEFORE the
    server-scrape/reconcile sub-sections, each of which is contained — a
    scrape failure lands in ``server.error`` without dropping the gate."""
    import shutil
    import sys
    import tempfile
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    t0 = time.perf_counter()
    # the continuous-batching engine serves the row by default
    # (serve_max_batch lanes, AOT executables cached in a fresh dir so
    # one run measures BOTH the cold compile and the warm reload); 1 =
    # the pre-engine serialized path
    aot_dir = tempfile.mkdtemp(prefix="hbnlp_aot_")
    try:
        return _bench_serving_inner(aot_dir, t0)
    finally:
        shutil.rmtree(aot_dir, ignore_errors=True)


def _serve_chunk_arm(params, chunk_tokens: int) -> dict:
    """One arm of the chunked-prefill A/B probe: fresh engine + server at
    ``serve_prefill_chunk_tokens=chunk_tokens`` driven closed-loop over a
    MIXED-length corpus (graftload --long-frac/--long-len) so long-prompt
    admissions land while short requests are mid-decode — the workload the
    decode-stall exists on.  No AOT dir: both arms pay their own compile,
    keeping donation identical to production.  Returns the figures the
    ratchet compares (goodput, itl_p95, prefill_stall_fraction)."""
    import graftload

    from homebrewnlp_tpu.obs.registry import MetricsRegistry
    from homebrewnlp_tpu.serve import RestAPI, serve
    from homebrewnlp_tpu.utils import load_config

    cfg = load_config(f"configs/{SERVE_CONFIG}.json", **_COMMON,
                      train_batch_size=1, serve_max_batch=SERVE_MAX_BATCH,
                      serve_prefill_chunk_tokens=chunk_tokens)
    reg = MetricsRegistry()
    api = RestAPI(cfg, params)
    server = serve(cfg, None, port=0, background=True, registry=reg,
                   obs_port=0, api=api)
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        murl = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
        api.wrapper.complete([1, 2, 3], 0.0, SERVE_RESPONSE_LEN)
        # long prompts fill most of the context window minus the response;
        # short ones keep decode lanes busy underneath the long admissions
        long_len = max(8, cfg.sequence_length - SERVE_RESPONSE_LEN)
        report = graftload.drive(
            url, metrics_url=murl, n_requests=SERVE_REQUESTS,
            concurrency=max(8, SERVE_CONCURRENCY), vocab=cfg.vocab_size,
            min_prompt=4,
            max_prompt=max(4, min(16, cfg.sequence_length // 4)),
            long_frac=0.25, long_len=long_len,
            response_len=SERVE_RESPONSE_LEN, seed=5)
    finally:
        server.shutdown()
        server.server_close()
        api.wrapper.close()
    c = report.get("client") or {}
    srv = report.get("server") or {}
    arm = {"goodput_tok_s": c.get("goodput_tok_s"),
           "error_rate": c.get("error_rate")}
    if isinstance(srv, dict) and "error" not in srv:
        itl = srv.get("itl_s")
        arm["itl_p95"] = itl.get("p95") if isinstance(itl, dict) else None
        arm["prefill_stall_fraction"] = srv.get("prefill_stall_fraction")
    return arm


def _bench_serving_inner(aot_dir: str, t0: float) -> dict:
    import graftload

    from homebrewnlp_tpu.models import init_params
    from homebrewnlp_tpu.obs.registry import MetricsRegistry
    from homebrewnlp_tpu.serve import RestAPI, serve
    from homebrewnlp_tpu.utils import load_config, random_text_batch

    cfg = load_config(f"configs/{SERVE_CONFIG}.json", **_COMMON,
                      train_batch_size=1, serve_max_batch=SERVE_MAX_BATCH,
                      serve_aot_cache_dir=aot_dir if SERVE_MAX_BATCH > 1
                      else "")
    params, _ = init_params(cfg, random_text_batch(cfg))
    # a dedicated registry: the serving histograms this row reconciles
    # against must contain exactly this run's requests, not the training
    # workloads' REST leftovers
    reg = MetricsRegistry()
    t_engine0 = time.perf_counter()
    api = RestAPI(cfg, params)
    server = serve(cfg, None, port=0, background=True, registry=reg,
                   obs_port=0, api=api)
    cold = {}
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        murl = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
        # prompts must leave room to generate: TTFT/decode need tokens
        max_prompt = max(4, min(64, cfg.sequence_length - SERVE_RESPONSE_LEN))
        # warmup: pay the sampler compile OUTSIDE the HTTP/SLO path (a
        # direct engine call records nothing), so the registry this row
        # scrapes holds exactly the timed requests and the steady-state
        # percentiles are honest; timed apart as compile_and_warmup_s
        api.wrapper.complete([1, 2, 3], 0.0, SERVE_RESPONSE_LEN)
        compile_and_warmup_s = time.perf_counter() - t0
        # cold start (engine build -> first token served), split into the
        # engine's own compile vs AOT-reload accounting when available
        cold["cold_start_s"] = round(time.perf_counter() - t_engine0, 3)
        for k in ("compile_s", "aot_reload_s", "aot_cache_hit"):
            v = getattr(api.engine, k, None)
            cold[k] = round(v, 3) if isinstance(v, float) else v
        report = graftload.drive(
            url, metrics_url=murl, n_requests=SERVE_REQUESTS,
            concurrency=SERVE_CONCURRENCY, vocab=cfg.vocab_size,
            min_prompt=4, max_prompt=max_prompt,
            response_len=SERVE_RESPONSE_LEN, seed=2)
        # streaming probe (contained): a short --stream pass measuring
        # client-side TTFT-to-first-SSE-chunk and reconciling client ITL
        # against the server histogram — runs AFTER the main drive so the
        # main report's scrape holds exactly the gated load.  The probe's
        # reconcile arms use a pre/post scrape DELTA: the cumulative
        # histograms are dominated by the main drive's queued load, and
        # comparing the idle probe's client clocks against those would
        # flag two healthy clocks
        stream_probe: dict = {}
        try:
            pre_text = graftload.fetch_metrics(murl)
            sreport = graftload.drive(
                url, n_requests=4, concurrency=2,
                vocab=cfg.vocab_size, min_prompt=4, max_prompt=max_prompt,
                response_len=SERVE_RESPONSE_LEN, seed=7, stream=True)
            post_text = graftload.fetch_metrics(murl)
            sc = sreport["client"]
            if sc.get("error_rate"):
                stream_probe["stream_probe_error"] = (
                    f"error_rate={sc['error_rate']}")
            else:
                stream_probe["stream_ttft_s"] = (sc.get("ttft_s")
                                                 or {}).get("p50")
                stream_probe["stream_itl_p50"] = (sc.get("itl_s")
                                                  or {}).get("p50")
                arms = _stream_delta_reconcile(sc, pre_text, post_text)
                if arms:
                    stream_probe["stream_reconcile"] = arms
        except Exception as e:  # noqa: BLE001 - probe failure, row survives
            stream_probe["stream_probe_error"] = (
                f"{type(e).__name__}: {e}"[:200])
    finally:
        server.shutdown()
        server.server_close()
        # the wrapper's daemon workers pin wrapper -> engine -> params (the
        # full serving-config weights) through every later bench section
        # unless told to exit
        api.wrapper.close()
    if SERVE_MAX_BATCH > 1 and cold.get("compile_s") is not None:
        # second server start against the populated AOT cache: the replica
        # autoscaling number — deserialization must beat compilation
        # (contained: a probe failure lands in cold["error"], the row and
        # its core figures survive)
        try:
            from homebrewnlp_tpu.serve.engine import BatchEngine
            t1 = time.perf_counter()
            e2 = BatchEngine(cfg, params)
            e2.complete_tokens([1, 2, 3], 0.0, SERVE_RESPONSE_LEN)
            cold["warm_start_s"] = round(time.perf_counter() - t1, 3)
            cold["aot_reload_s"] = (round(e2.aot_reload_s, 3)
                                    if e2.aot_reload_s is not None else None)
            cold["aot_cache_hit"] = e2.aot_cache_hit
            e2.close()
        except Exception as e:  # noqa: BLE001
            # NOT "error": that key at row top level flips the serve_ok
            # gate, and a failed warm-start probe must not sink a row whose
            # core serving figures are healthy
            cold["warm_probe_error"] = f"{type(e).__name__}: {e}"[:200]
    chunk_probe: dict = {}
    if SERVE_CHUNK_TOKENS > 0 and SERVE_MAX_BATCH > 1:
        # chunked-prefill A/B (contained): same model, same mixed-length
        # corpus, chunking off vs on.  Off measures the real decode stall
        # (the blocking admission prefill the PR-14 ruler prices); on must
        # cut the stall fraction without regressing itl_p95 — the ratchet
        # in evaluate_serve_baseline enforces exactly that once recorded
        try:
            chunk_probe["chunked_prefill"] = {
                "chunk_tokens": SERVE_CHUNK_TOKENS,
                "off": _serve_chunk_arm(params, 0),
                "on": _serve_chunk_arm(params, SERVE_CHUNK_TOKENS)}
        except Exception as e:  # noqa: BLE001 - probe failure, row survives
            chunk_probe["chunk_probe_error"] = f"{type(e).__name__}: {e}"[:200]
    c = report["client"]
    e2e = c.get("e2e_s") or {}
    row = {
        # core fields FIRST (the baseline gate and the driver's trajectory
        # read these; everything after is contained best-effort detail)
        "config": SERVE_CONFIG,
        "value": c.get("goodput_tok_s"),  # the row's figure of record
        "goodput_tok_s": c.get("goodput_tok_s"),
        "e2e_p50_s": e2e.get("p50"),
        "e2e_p95_s": e2e.get("p95"),
        "requests_per_s": c.get("requests_per_s"),
        "truncated": c.get("truncated", False),
        "error_rate": c.get("error_rate"),
        "n_requests": c.get("n_requests"),
        "n_rejected": c.get("n_rejected"),
        "concurrency": SERVE_CONCURRENCY,
        "response_len": SERVE_RESPONSE_LEN,
        "serve_max_batch": SERVE_MAX_BATCH,
        "compile_and_warmup_s": round(compile_and_warmup_s, 1),
    }
    row.update(cold)
    row.update(stream_probe)
    row.update(chunk_probe)
    # flight-recorder steady-state overhead (observability PR): time the
    # recorder's whole per-request hot path (trail build + ring append +
    # tail-sampling quantile) on realistic finished records and price it
    # against this row's measured p50 latency — the figure the ≤1%
    # acceptance bound ratchets (contained: probe failure, row survives)
    try:
        from homebrewnlp_tpu.obs.flight import FlightRecorder
        from homebrewnlp_tpu.serve.slo import RequestRecord
        fr = FlightRecorder(registry=reg)

        def _probe_rec(i: int) -> RequestRecord:
            r = RequestRecord(i, path="/token_completion")
            r.xid = f"bench-{i:04d}"
            r.mark_parsed()
            r.mark_enqueued(queue_depth=0)
            r.mark_started()
            r.mark_first_token()
            r.mark_engine_done()
            r.tokens_generated = SERVE_RESPONSE_LEN
            r.mark_finished(200)
            return r

        probe_recs = [_probe_rec(i) for i in range(256)]
        t_fl = time.perf_counter()
        for r in probe_recs:
            fr.observe_request(r)
        per_req_s = (time.perf_counter() - t_fl) / len(probe_recs)
        row["flight_observe_us"] = round(per_req_s * 1e6, 2)
        if isinstance(e2e.get("p50"), (int, float)) and e2e["p50"] > 0:
            row["flight_overhead_frac"] = round(per_req_s / e2e["p50"], 6)
    except Exception as e:  # noqa: BLE001 - probe failure, row survives
        row["flight_probe_error"] = f"{type(e).__name__}: {e}"[:200]
    # usage-meter steady-state overhead (usage metering PR): time the
    # meter's whole per-request hot path (tenant validation + sketch admit
    # + accumulate + flops pricing + rate-window append) on realistic
    # finished records and price it against this row's measured p50 — the
    # same absolute ≤1% acceptance bound as the flight recorder
    try:
        from homebrewnlp_tpu.obs.usage import UsageMeter
        from homebrewnlp_tpu.serve.slo import RequestRecord
        meter = UsageMeter(32, pricing={"prefill_flops": 1.0e9,
                                        "decode_flops_per_token": 1.0e6})

        def _usage_rec(i: int) -> RequestRecord:
            r = RequestRecord(i, path="/token_completion")
            r.xid = f"bench-u-{i:04d}"
            r.tenant = f"t{i % 8}"
            r.mark_parsed()
            r.mark_enqueued(queue_depth=0)
            r.mark_started()
            r.mark_first_token()
            r.mark_engine_done()
            r.prompt_tokens = 16
            r.tokens_generated = SERVE_RESPONSE_LEN
            r.kv_blocks = 2
            r.kv_block_seconds = 0.25
            r.lane_seconds = 0.12
            r.mark_finished(200)
            return r

        usage_recs = [_usage_rec(i) for i in range(256)]
        t_um = time.perf_counter()
        for r in usage_recs:
            meter.finalize(r, 200)
        per_req_s = (time.perf_counter() - t_um) / len(usage_recs)
        row["usage_finalize_us"] = round(per_req_s * 1e6, 2)
        if isinstance(e2e.get("p50"), (int, float)) and e2e["p50"] > 0:
            row["usage_overhead_frac"] = round(per_req_s / e2e["p50"], 6)
    except Exception as e:  # noqa: BLE001 - probe failure, row survives
        row["usage_probe_error"] = f"{type(e).__name__}: {e}"[:200]
    srv = report.get("server") or {}
    if isinstance(srv, dict) and "error" not in srv:
        for key, out_key in (("ttft_s", "ttft"), ("queue_wait_s",
                                                  "queue_wait"),
                             ("engine_s", "engine"),
                             ("decode_tokens_per_sec", "decode_rate"),
                             ("batch_size", "batch_size"),
                             ("itl_s", "itl"),
                             ("decode_step_s", "decode_step")):
            if isinstance(srv.get(key), dict):
                row[f"{out_key}_p50"] = srv[key].get("p50")
                row[f"{out_key}_p95"] = srv[key].get("p95")
        if srv.get("prefill_stall_fraction") is not None:
            row["prefill_stall_fraction"] = srv["prefill_stall_fraction"]
    if "server" in report:
        row["server"] = srv
    if "reconcile" in report:
        row["reconcile"] = report["reconcile"]
        over = report["reconcile"].get("serialization_overhead_s")
        if over is not None:
            row["serialization_overhead_s"] = over
    return row


def evaluate_serve_baseline(row: dict, baseline: dict,
                            max_latency_ratio: float = SERVE_LATENCY_RATIO,
                            min_goodput_ratio: float = SERVE_GOODPUT_RATIO):
    """Pure serving-ratchet evaluation (unit-testable without a server):
    the row's p50 e2e latency and goodput tok/s against the committed
    per-device baseline.  Returns (gate row or None, ok).  A missing
    figure or baseline is skipped — absence is not a regression (the
    baseline self-records on first contact, bench.main)."""
    if not isinstance(row, dict) or not baseline:
        return None, True
    out: dict = {}
    ok = True
    e2e, base_e2e = row.get("e2e_p50_s"), baseline.get("e2e_p50_s")
    if isinstance(e2e, (int, float)) and base_e2e:
        ratio = e2e / base_e2e
        passed = bool(ratio <= max_latency_ratio)
        out["e2e_p50"] = {"baseline_s": base_e2e, "ratio": round(ratio, 3),
                          "pass": passed}
        ok = ok and passed
    good, base_good = row.get("goodput_tok_s"), baseline.get("goodput_tok_s")
    if isinstance(good, (int, float)) and base_good:
        ratio = good / base_good
        passed = bool(ratio >= min_goodput_ratio)
        out["goodput"] = {"baseline_tok_s": base_good,
                          "ratio": round(ratio, 3), "pass": passed}
        ok = ok and passed
    # cold-start ratchet (continuous-batching PR): once a baseline has
    # recorded cold_start_s, a later round may not regress it past the
    # latency ratio — AOT reload keeps replica cold starts in seconds
    cold, base_cold = row.get("cold_start_s"), baseline.get("cold_start_s")
    if isinstance(cold, (int, float)) and base_cold:
        ratio = cold / base_cold
        passed = bool(ratio <= max_latency_ratio)
        out["cold_start"] = {"baseline_s": base_cold,
                             "ratio": round(ratio, 3), "pass": passed}
        ok = ok and passed
    # token-level ratchets (streaming/ITL PR): per-token latency and the
    # streamed first-chunk latency gate like e2e; the prefill-stall
    # fraction gets an absolute 0.05 slack on top of the ratio — at tiny
    # stall fractions a pure ratio would flag scheduler noise
    for key, base_key in (("itl_p50", "itl_p50"),
                          ("stream_ttft_s", "stream_ttft_s")):
        v, b = row.get(key), baseline.get(base_key)
        if isinstance(v, (int, float)) and b:
            ratio = v / b
            passed = bool(ratio <= max_latency_ratio)
            out[key] = {"baseline_s": b, "ratio": round(ratio, 3),
                        "pass": passed}
            ok = ok and passed
    frac = row.get("prefill_stall_fraction")
    base_frac = baseline.get("prefill_stall_fraction")
    if isinstance(frac, (int, float)) and isinstance(base_frac, (int, float)):
        limit = base_frac * max_latency_ratio + 0.05
        passed = bool(frac <= limit)
        out["prefill_stall_fraction"] = {
            "baseline": base_frac, "value": frac,
            "limit": round(limit, 4), "pass": passed}
        ok = ok and passed
    # chunked-prefill ratchet (chunked prefill PR): once a baseline has
    # recorded the A/B probe's ON arm, a later round's ON arm may not
    # regress it — the stall fraction gets the same ratio + 0.05 absolute
    # slack as the main stall gate, and itl_p95 gates like the other
    # latencies (chunk interleave must stay off the decode critical path)
    on = (row.get("chunked_prefill") or {}).get("on") or {}
    base_on = (baseline.get("chunked_prefill") or {}).get("on") or {}
    c_frac = on.get("prefill_stall_fraction")
    b_frac = base_on.get("prefill_stall_fraction")
    if isinstance(c_frac, (int, float)) and isinstance(b_frac, (int, float)):
        limit = b_frac * max_latency_ratio + 0.05
        passed = bool(c_frac <= limit)
        out["chunked_stall_fraction"] = {
            "baseline": b_frac, "value": c_frac,
            "limit": round(limit, 4), "pass": passed}
        ok = ok and passed
    c_itl, b_itl = on.get("itl_p95"), base_on.get("itl_p95")
    if isinstance(c_itl, (int, float)) and b_itl:
        ratio = c_itl / b_itl
        passed = bool(ratio <= max_latency_ratio)
        out["chunked_itl_p95"] = {"baseline_s": b_itl,
                                  "ratio": round(ratio, 3), "pass": passed}
        ok = ok and passed
    # flight-recorder overhead (observability PR): an ABSOLUTE cap, not a
    # ratio against baseline — the ≤1%-of-p50 bound IS the acceptance
    # criterion, so a baseline recorded at 0.2% must not license 0.3%
    fo = row.get("flight_overhead_frac")
    if isinstance(fo, (int, float)):
        passed = bool(fo <= 0.01)
        out["flight_overhead_frac"] = {"value": fo, "limit": 0.01,
                                       "pass": passed}
        ok = ok and passed
    # usage-meter overhead (usage metering PR): the same absolute ≤1%
    # bound — metering must stay invisible next to a model step
    uo = row.get("usage_overhead_frac")
    if isinstance(uo, (int, float)):
        passed = bool(uo <= 0.01)
        out["usage_overhead_frac"] = {"value": uo, "limit": 0.01,
                                      "pass": passed}
        ok = ok and passed
    return (out or None), ok


def evaluate_compile_budget(workloads: dict, budgets: dict,
                            max_ratio: float = COMPILE_BUDGET_RATIO):
    """Pure compile-time ratchet evaluation (unit-testable, shared with
    tools/compile_ratchet.py): each workload's ``compile_and_warmup_s``
    against its committed per-device budget.  Returns (per-workload budget
    rows, all_pass).  Workloads without a recorded figure or budget are
    skipped — absence is not a regression (e.g. a partial
    HBNLP_BENCH_WORKLOADS run)."""
    rows: dict = {}
    ok = True
    for nm, w in sorted(workloads.items()):
        s = w.get("compile_and_warmup_s") if isinstance(w, dict) else None
        base = (budgets or {}).get(nm)
        if not isinstance(s, (int, float)) or not base:
            continue
        ratio = s / base
        passed = bool(ratio <= max_ratio)
        rows[nm] = {"baseline_s": base, "ratio": round(ratio, 3),
                    "pass": passed}
        ok = ok and passed
    return rows, ok


def ensure_real_corpus(pattern: str, builder=None):
    """None when files matching ``pattern`` exist (rebuilding them
    deterministically if needed), else a structured guard-failure dict —
    the trajectory guard REFUSES the train CLI's silent synthetic fallback
    (round-5 post-mortem, docs/perf/README.md).  ``builder`` is injectable
    for tests; the default shells out to tools/build_corpus.py."""
    import glob
    import subprocess
    import sys

    def default_builder():
        subprocess.run([sys.executable, "tools/build_corpus.py",
                        "--out-dir", "datasets"], check=True)

    if not glob.glob(pattern):
        try:
            (builder or default_builder)()
        except Exception as e:  # noqa: BLE001 - report, don't crash the line
            return {"pass": False,
                    "error": f"corpus rebuild failed: {e}"[:300]}
    if not glob.glob(pattern):
        return {"pass": False,
                "error": f"no real corpus at {pattern}; refusing the "
                         "synthetic fallback"}
    return None


def numerics_guard(n_steps: int = 300) -> dict:
    """Real-corpus trajectory check, driver-visible (VERDICT r4 item 9):
    run the first ``n_steps`` of the 10k acceptance setup
    (``configs/32ctx_accept_10k.json``, committed 84M-token corpus, fixed
    data_seed) through the full CLI train path and assert the warmup
    trajectory of the committed 10k-run record (the STABLE Run-B
    hyperparameters — see the module docstring for why not the LR-0.01
    ``32ctx_real_1chip`` point)."""
    import argparse
    import tempfile

    from homebrewnlp_tpu import main as cli
    from homebrewnlp_tpu.utils import load_config

    with tempfile.TemporaryDirectory(prefix="bench_guard_") as tmp:
        cfg = load_config("configs/32ctx_accept_10k.json",
                          model_path=tmp, use_checkpointing=False)
        err = ensure_real_corpus(cfg.dataset_configs[0]["path"])
        if err is not None:
            return err
        args = argparse.Namespace(steps=n_steps, profile="", workers=None)
        t0 = time.perf_counter()
        cli.train(cfg, args)
        wall = time.perf_counter() - t0
        from homebrewnlp_tpu.train.metrics import read_metric_rows
        rows = read_metric_rows(tmp)
    result = evaluate_guard(rows, n_steps)
    result["wall_s"] = round(wall, 1)
    result["config"] = "configs/32ctx_accept_10k.json"
    return result


def evaluate_guard(rows, n_steps: int) -> dict:
    """Pure threshold evaluation over metrics rows (separated so the logic
    is unit-testable without a chip).  Thresholds follow the committed
    10k-run record (7.71 -> 3.45@100 -> 2.76-class@300 with margin,
    docs/perf/32ctx_10k_run.md); shorter development runs
    (HBNLP_BENCH_GUARD_STEPS < 120/300) only assert the checkpoints they
    actually reach, plus strict decrease."""
    # tolerate raw rows: run-start boundary markers carry no loss and only
    # metric rows participate in the trajectory check (read_metric_rows
    # already filters when the rows come from it)
    rows = [r for r in rows if "loss" in r]
    if not rows:
        return {"pass": False,
                "error": "no metric rows (marker-only metrics.jsonl — the "
                         "run died before its first metric drain)"}
    by_step = {r["step"]: r["loss"] for r in rows}
    first = rows[0]["loss"]
    final = rows[-1]["loss"]
    at_120 = min((s for s in by_step if s >= min(120, n_steps - 1)),
                 default=rows[-1]["step"])
    loss_120 = by_step[at_120]
    ok = (first > 6.5 and final == final and final < first)
    if n_steps >= 120:
        ok = ok and loss_120 < 4.5
    if n_steps >= 300:
        ok = ok and final < 3.6
    return {"pass": bool(ok), "steps": rows[-1]["step"],
            "loss_first": round(first, 4),
            "loss_step120": round(loss_120, 4),
            "loss_final": round(final, 4)}


def main() -> None:
    from homebrewnlp_tpu.utils import enable_compilation_cache, load_config

    # persistent XLA cache: a warm re-run skips the step compiles; honors
    # the config's compilation_cache_dir knob like main.py
    enable_compilation_cache(
        load_config("configs/32big_mixer.json").compilation_cache_dir)

    sel = os.environ.get("HBNLP_BENCH_WORKLOADS", "all")
    names = list(WORKLOADS) if sel == "all" else [
        s for s in sel.split(",") if s in WORKLOADS]
    workloads = {}
    for name in names:
        try:
            workloads[name] = bench_workload(
                name, probe_loss=(name == "32big_mixer"))
        except Exception as e:  # noqa: BLE001 - one workload must not kill the line
            workloads[name] = {"error": f"{type(e).__name__}: {e}"[:300]}

    # serving workload row + its ratchet, evaluated HERE — before the
    # guard and the compile/profile ratchet sections below — so a failure
    # in any later probe cannot drop the serving baseline comparison
    # (the hbm_peak_bytes ordering discipline, ISSUE 9 satellite)
    serve_ok: typing.Optional[bool] = None
    if os.environ.get("HBNLP_BENCH_SERVE", "1") != "0":
        try:
            workloads["serving"] = bench_serving()
        except Exception as e:  # noqa: BLE001
            workloads["serving"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        srow = workloads["serving"]
        # a row without usable core figures (every request failed, server
        # never came up cleanly, graftload abandoned a live worker) must
        # FAIL the gate, not skip it — serve_ok exists to catch exactly
        # that class of regression
        serve_ok = ("error" not in srow
                    and not srow.get("truncated")
                    and isinstance(srow.get("e2e_p50_s"), (int, float))
                    and isinstance(srow.get("goodput_tok_s"), (int, float)))
        if (isinstance(srow.get("e2e_p50_s"), (int, float))
                and not srow.get("truncated")):
            serve_baselines = {}
            if os.path.exists(SERVE_BASELINE_FILE):
                with open(SERVE_BASELINE_FILE) as f:
                    serve_baselines = json.load(f)
            kind = jax.devices()[0].device_kind
            dev_serve = serve_baselines.setdefault(kind, {})
            # latency/goodput only compare like against like: the baseline
            # remembers the workload shape it was recorded under, and an
            # env-overridden run (HBNLP_BENCH_SERVE_*, smoke/dev shapes)
            # skips the ratchet instead of failing it spuriously
            shape = {"config": SERVE_CONFIG, "n_requests": SERVE_REQUESTS,
                     "concurrency": SERVE_CONCURRENCY,
                     "response_len": SERVE_RESPONSE_LEN}
            if not dev_serve and not SERVE_SHAPE_OVERRIDDEN:
                # first contact at the DEFAULT shape: self-record (operator
                # commits); an overridden smoke shape must not become the
                # baseline every default run then skips against
                dev_serve.update({
                    "e2e_p50_s": srow["e2e_p50_s"],
                    "goodput_tok_s": srow.get("goodput_tok_s"),
                    # continuous-batching figures self-record so the NEXT
                    # round ratchets them (cold start + the serialization
                    # overhead the engine exists to collapse)
                    "queue_wait_p50_s": srow.get("queue_wait_p50"),
                    "serialization_overhead_s": srow.get(
                        "serialization_overhead_s"),
                    "cold_start_s": srow.get("cold_start_s"),
                    "compile_s": srow.get("compile_s"),
                    "aot_reload_s": srow.get("aot_reload_s"),
                    "serve_max_batch": srow.get("serve_max_batch"),
                    # token-level figures (streaming/ITL PR) self-record
                    # so the NEXT round ratchets them
                    "itl_p50": srow.get("itl_p50"),
                    "prefill_stall_fraction": srow.get(
                        "prefill_stall_fraction"),
                    "stream_ttft_s": srow.get("stream_ttft_s"),
                    # chunked-prefill A/B figures (chunked prefill PR),
                    # present only when HBNLP_BENCH_SERVE_CHUNK ran the probe
                    "chunked_prefill": srow.get("chunked_prefill"),
                    # flight-recorder per-request cost (observability PR) —
                    # recorded for trajectory visibility; the gate itself
                    # is the absolute ≤1% cap, not a ratio against this
                    "flight_overhead_frac": srow.get("flight_overhead_frac"),
                    # usage-meter per-request cost (usage metering PR) —
                    # same deal: trajectory visibility, absolute ≤1% gate
                    "usage_overhead_frac": srow.get("usage_overhead_frac"),
                    "shape": shape,
                    "recorded": time.time()})
                with open(SERVE_BASELINE_FILE, "w") as f:
                    json.dump(serve_baselines, f, indent=2, sort_keys=True)
                    f.write("\n")
            elif (dev_serve and not SERVE_SHAPE_OVERRIDDEN
                    and isinstance(srow.get("chunked_prefill"), dict)
                    and not dev_serve.get("chunked_prefill")
                    and dev_serve.get("shape", shape) == shape):
                # the A/B probe self-records into an EXISTING baseline the
                # first time HBNLP_BENCH_SERVE_CHUNK runs at the default
                # shape, so the next round ratchets the ON arm
                dev_serve["chunked_prefill"] = srow["chunked_prefill"]
                with open(SERVE_BASELINE_FILE, "w") as f:
                    json.dump(serve_baselines, f, indent=2, sort_keys=True)
                    f.write("\n")
            elif (dev_serve and not SERVE_SHAPE_OVERRIDDEN
                    and isinstance(srow.get("usage_overhead_frac"),
                                   (int, float))
                    and dev_serve.get("usage_overhead_frac") is None
                    and dev_serve.get("shape", shape) == shape):
                # the usage-meter probe self-records into an EXISTING
                # baseline on its first default-shape run (the gate stays
                # the absolute ≤1% cap; this is trajectory visibility)
                dev_serve["usage_overhead_frac"] = srow["usage_overhead_frac"]
                with open(SERVE_BASELINE_FILE, "w") as f:
                    json.dump(serve_baselines, f, indent=2, sort_keys=True)
                    f.write("\n")
            if dev_serve.get("shape", shape) == shape:
                gate, gate_ok = evaluate_serve_baseline(srow, dev_serve)
                if gate is not None:
                    srow["baseline"] = gate
                serve_ok = serve_ok and gate_ok
            else:
                srow["baseline"] = {"skipped": "workload shape differs "
                                               "from the recorded baseline"}

    guard_steps = int(os.environ.get("HBNLP_BENCH_GUARD_STEPS", "300"))
    guard = None
    if guard_steps:
        try:
            guard = numerics_guard(guard_steps)
        except Exception as e:  # noqa: BLE001
            guard = {"pass": False,
                     "error": f"{type(e).__name__}: {e}"[:300]}

    device_kind = jax.devices()[0].device_kind
    n_chips = max(1, len(jax.devices()))
    flag = workloads.get("32big_mixer", {})
    value = flag.get("value")

    # round-over-round comparison keyed by device kind; bench_baseline.json
    # is COMMITTED, so every round's vs_baseline shares one pinned
    # denominator (21040.8 tok/s on v5e, the round-1 figure) instead of
    # resetting per machine
    baselines = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            baselines = json.load(f)
    if value is not None and device_kind not in baselines:
        baselines[device_kind] = {"value": value, "recorded": time.time()}
        with open(BASELINE_FILE, "w") as f:
            json.dump(baselines, f)
    baseline = baselines.get(device_kind, {}).get("value")

    # compile-time ratchet: every workload's compile_and_warmup_s against
    # the committed per-device budget (bench_compile_baseline.json).  A
    # first run on an unknown device kind records its own budget (committed
    # by the operator like bench_baseline.json); after that, >20% over
    # budget fails the line's compile_ok and the CI ratchet
    # (tools/compile_ratchet.py).
    comp_baselines = {}
    if os.path.exists(COMPILE_BASELINE_FILE):
        with open(COMPILE_BASELINE_FILE) as f:
            comp_baselines = json.load(f)
    # self-record per WORKLOAD, not just per device kind: a workload added
    # after the device's budget was first recorded (or missing from a
    # partial first run) must gain a budget on its first successful
    # measurement, or it would pass the ratchet unguarded forever
    dev_budget = comp_baselines.setdefault(device_kind, {})
    new_rows = {n: w["compile_and_warmup_s"] for n, w in workloads.items()
                if isinstance(w, dict) and n not in dev_budget
                and isinstance(w.get("compile_and_warmup_s"), (int, float))}
    if new_rows:
        dev_budget.update(new_rows)
        with open(COMPILE_BASELINE_FILE, "w") as f:
            json.dump(comp_baselines, f, indent=2, sort_keys=True)
            f.write("\n")
    budget_rows, compile_ok = evaluate_compile_budget(
        workloads, comp_baselines.get(device_kind, {}))
    for n, b in budget_rows.items():
        workloads[n]["compile_budget"] = b

    # attribution-drift ratchet (graftprof): per-device-kind committed
    # baseline of decomposition fractions + scope coverage, self-recorded
    # on a workload's first successful capture (operator commits it, like
    # the compile budget); after that, drift past the tolerance fails the
    # row and the line's profile_ok
    from homebrewnlp_tpu.obs.profile import (baseline_entry,
                                             evaluate_profile_baseline)
    prof_baselines = {}
    if os.path.exists(PROFILE_BASELINE_FILE):
        with open(PROFILE_BASELINE_FILE) as f:
            prof_baselines = json.load(f)
    dev_prof = prof_baselines.setdefault(device_kind, {})
    new_prof = {n: baseline_entry(w["profile"]) for n, w in workloads.items()
                if isinstance(w, dict) and isinstance(w.get("profile"), dict)
                and "fractions" in w["profile"] and n not in dev_prof}
    if new_prof:
        dev_prof.update(new_prof)
        with open(PROFILE_BASELINE_FILE, "w") as f:
            json.dump(prof_baselines, f, indent=2, sort_keys=True)
            f.write("\n")
    prof_rows, profile_ok = evaluate_profile_baseline(workloads, dev_prof)
    for n, b in prof_rows.items():
        workloads[n]["profile"]["baseline"] = b

    record = {
        "metric": "tokens_per_sec_per_chip",
        # figure of record = the flagship's median-of-5 windows (continuity
        # with rounds 1-4); the two other reference workloads ride in
        # "workloads"
        "value": value,
        "unit": "tok/s/chip",
        "vs_baseline": (round(value / baseline, 4)
                        if value and baseline else None),
        "best": flag.get("best"),
        "windows_tok_s": flag.get("windows_tok_s"),
        "ms_per_step": flag.get("ms_per_step"),
        "flops_per_step": flag.get("flops_per_step"),
        "flops_per_step_algorithmic": flag.get("flops_per_step_algorithmic"),
        "mfu": flag.get("mfu"),
        "mfu_algorithmic": flag.get("mfu_algorithmic"),
        "loss_after_n_steps": flag.get("loss_after_n_steps"),
        "n_steps_total": flag.get("n_steps_total"),
        "compile_and_warmup_s": flag.get("compile_and_warmup_s"),
        "host_blocked_s": flag.get("host_blocked_s"),
        "phases_s": flag.get("phases_s"),
        "compile_cache_hit": flag.get("compile_cache_hit"),
        "device": device_kind,
        "n_chips": n_chips,
        "compile_ok": compile_ok,
        "profile_ok": profile_ok,
        # serving ratchet verdict (None = row skipped via HBNLP_BENCH_SERVE)
        "serve_ok": serve_ok,
        "workloads": workloads,
        "numerics_guard": guard,
    }
    if any(isinstance(w.get("mfu"), float) and w["mfu"] > 1.0
           for w in workloads.values()):
        # physically impossible: the host<->chip transport is distorting
        # wall-clock (e.g. an experimental relay acking before execution
        # completes); the throughput figures must not be trusted.
        record["distorted"] = True
    print(json.dumps(record))


if __name__ == "__main__":
    main()
