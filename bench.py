"""Benchmark: tokens/sec/chip on the 32big_mixer architecture (BASELINE.md).

Runs the flagship mixer LM (full 32big_mixer DSL/optimizer/dtype config,
batch shrunk to fit one chip) for 5 timed windows of train steps on whatever
accelerator JAX selects, and prints ONE JSON line whose ``value`` is the
MEDIAN window (``best`` and the raw ``windows_tok_s`` list expose the
spread):

    {"metric": "tokens_per_sec_per_chip", "value": N, "unit": "tok/s/chip",
     "vs_baseline": R, ...}

The line is self-verifying: it carries ``flops_per_step`` from XLA's cost
analysis of the compiled step, the derived ``mfu`` against the device's peak
(a physically-possible number is <= 1.0 — if the transport between host and
chip distorts wall-clock timing, ``distorted`` is set and the throughput
figure must not be trusted), ``ms_per_step``, and ``loss_after_n_steps`` on a
fixed seed so rounds are comparable for both speed and numerics.

The MTF reference publishes no numbers (see BASELINE.md), so ``vs_baseline``
is computed against the first value this repo ever recorded
(bench_baseline.json, written on first run) — i.e. round-over-round speedup.
"""
from __future__ import annotations

import json
import os
import time

import jax

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")

# Peak dense bf16 FLOP/s per chip, by device_kind substring (public specs).
_PEAK_BF16 = (
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None  # CPU / unknown: no MFU claim


def main() -> None:
    from homebrewnlp_tpu.train import Trainer
    from homebrewnlp_tpu.utils import (enable_compilation_cache, load_config,
                                       random_text_batch)

    t_compile0 = time.perf_counter()

    # full 32big_mixer architecture (d_model 4096, depth 32x2 blocks, seq 512,
    # bf16, revnet, AGC+SM3+momentum); batch shrunk from the pod-scale 1024 to
    # fit a single chip — tokens/sec/chip is per-chip throughput either way.
    # slice_dtype (device-resident param copy) is forced to bf16 here: the
    # config's f32 slices double every param transfer through the
    # experimental host<->chip relay, which times out / drops the response on
    # the flagship's init program.  Round-1 recorded with bf16 residency, so
    # this also keeps the number comparable round-over-round.
    cfg = load_config("configs/32big_mixer.json", train_batch_size=8,
                      use_checkpointing=False, calc_accuracy=False, tpu_size=1,
                      slice_dtype="bfloat16")
    # persistent XLA cache: a warm re-run of this script skips the flagship
    # step compile (the cache key covers program + compile options + backend);
    # honors the config's compilation_cache_dir knob like main.py
    enable_compilation_cache(cfg.compilation_cache_dir)
    trainer = Trainer(cfg)
    batch = random_text_batch(cfg)

    state = trainer.init(batch)
    rng = jax.random.key(1)

    # compile + XLA cost analysis of the exact step being timed
    cost = trainer.step_cost_analysis(state, batch)
    flops_per_step = float(cost.get("flops", 0.0))

    # fixed seed schedule: step i always uses fold_in(rng, i), so
    # loss_after_n_steps is reproducible round over round
    step_i = 0

    def run_steps(n, state):
        nonlocal step_i
        metrics = None
        for _ in range(n):
            state, metrics = trainer.step(state, batch,
                                          jax.random.fold_in(rng, step_i))
            step_i += 1
        return state, metrics

    # warmup: compile + let the device path reach steady state
    state, metrics = run_steps(3, state)
    float(metrics["loss"])
    compile_and_warmup_s = time.perf_counter() - t_compile0

    # 5 windows of 10 steps.  Each window ends with a HOST PULL of the loss
    # scalar, not block_until_ready: the experimental axon relay acks
    # readiness before execution completes (round-1 bench measured 6.5 ms/step
    # = 12x chip peak), but a device->host transfer of the final step's output
    # cannot complete until the whole dependency chain has — measured 193
    # ms/step, a physically sane 41% MFU on v5e.
    #
    # The relay's wall-clock jitter between windows is several percent, so
    # the figure of record is the MEDIAN window (robust to one slow/fast
    # outlier); the best window and the raw per-window list are reported
    # alongside so the spread is visible (VERDICT r3 "what's weak" #2).  The
    # fixed-seed comparison loss stays pinned to the end of window 3 (step
    # 33 under the 3-warmup/10-step constants — the figure rounds 1-2
    # recorded) regardless of how many timing windows run.
    n_steps = 10
    window_dts = []
    loss_after = None
    pin_step = step_i + 3 * n_steps
    for _ in range(5):
        t0 = time.perf_counter()
        state, metrics = run_steps(n_steps, state)
        window_loss = float(metrics["loss"])
        window_dts.append(time.perf_counter() - t0)
        if step_i == pin_step or loss_after is None and step_i >= pin_step:
            loss_after = window_loss
    dt = sorted(window_dts)[len(window_dts) // 2]
    best_dt = min(window_dts)
    tokens = cfg.train_batch_size * cfg.sequence_length * n_steps
    n_chips = max(1, len(jax.devices()))
    value = tokens / dt / n_chips
    best_value = tokens / best_dt / n_chips
    ms_per_step = dt / n_steps * 1e3

    device_kind = jax.devices()[0].device_kind
    peak = _peak_flops(device_kind)
    mfu = None
    if peak and flops_per_step:
        mfu = flops_per_step * n_steps / dt / (peak * n_chips)

    # round-over-round comparison keyed by device kind; bench_baseline.json
    # is COMMITTED, so every round's vs_baseline shares one pinned
    # denominator (21040.8 tok/s on v5e, the round-1 figure) instead of
    # resetting per machine
    baselines = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            baselines = json.load(f)
    if device_kind not in baselines:
        baselines[device_kind] = {"value": value, "recorded": time.time()}
        with open(BASELINE_FILE, "w") as f:
            json.dump(baselines, f)
    baseline = baselines[device_kind]["value"]

    record = {
        "metric": "tokens_per_sec_per_chip",
        # figure of record = median-of-5 windows; best + raw windows shown so
        # the run-to-run spread is part of the record, not a narrative claim
        "value": round(value, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(value / baseline, 4),
        "best": round(best_value, 2),
        "windows_tok_s": [round(tokens / w / n_chips, 1) for w in window_dts],
        "ms_per_step": round(ms_per_step, 3),
        "flops_per_step": flops_per_step,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "loss_after_n_steps": round(loss_after, 4),
        "n_steps_total": step_i,
        "compile_and_warmup_s": round(compile_and_warmup_s, 1),
        "device": device_kind,
        "n_chips": n_chips,
    }
    if mfu is not None and mfu > 1.0:
        # physically impossible: the host<->chip transport is distorting
        # wall-clock (e.g. an experimental relay acking before execution
        # completes); the throughput figure must not be trusted.
        record["distorted"] = True
    print(json.dumps(record))


if __name__ == "__main__":
    main()
