"""Benchmark: tokens/sec/chip on the 32big_mixer architecture (BASELINE.md).

Runs the flagship mixer LM (full 32big_mixer DSL/optimizer/dtype config,
batch shrunk to fit one chip) for a timed window of train steps on whatever
accelerator JAX selects, and prints ONE JSON line:

    {"metric": "tokens_per_sec_per_chip", "value": N, "unit": "tok/s/chip",
     "vs_baseline": R}

The MTF reference publishes no numbers (see BASELINE.md), so ``vs_baseline``
is computed against the first value this repo ever recorded
(bench_baseline.json, written on first run) — i.e. round-over-round speedup.
"""
from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")


def main() -> None:
    from homebrewnlp_tpu.train import Trainer
    from homebrewnlp_tpu.utils import load_config, random_text_batch

    # full 32big_mixer architecture (d_model 4096, depth 32x2 blocks, seq 512,
    # bf16, revnet, AGC+SM3+momentum); batch shrunk from the pod-scale 1024 to
    # fit a single chip — tokens/sec/chip is per-chip throughput either way.
    cfg = load_config("configs/32big_mixer.json", train_batch_size=8,
                      use_checkpointing=False, calc_accuracy=False, tpu_size=1)
    trainer = Trainer(cfg)
    batch = random_text_batch(cfg)

    state = trainer.init(batch)
    rng = jax.random.key(1)

    # warmup: compile + let the device path reach steady state
    for i in range(3):
        state, metrics = trainer.step(state, batch, jax.random.fold_in(rng, 90 + i))
    jax.block_until_ready(metrics["loss"])

    # best-of-3 windows of 10 steps: robust against transient host/tunnel
    # stalls that would otherwise understate device throughput
    n_steps = 10
    best_dt = float("inf")
    for w in range(3):
        t0 = time.perf_counter()
        for i in range(n_steps):
            state, metrics = trainer.step(state, batch,
                                          jax.random.fold_in(rng, w * n_steps + i))
        jax.block_until_ready(metrics["loss"])
        best_dt = min(best_dt, time.perf_counter() - t0)
    dt = best_dt

    tokens = cfg.train_batch_size * cfg.sequence_length * n_steps
    n_chips = max(1, len(jax.devices()))
    value = tokens / dt / n_chips

    # round-over-round comparison keyed by device kind (the baseline file is
    # machine-local state, .gitignored)
    device_kind = jax.devices()[0].device_kind
    baselines = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            baselines = json.load(f)
    if device_kind not in baselines:
        baselines[device_kind] = {"value": value, "recorded": time.time()}
        with open(BASELINE_FILE, "w") as f:
            json.dump(baselines, f)
    baseline = baselines[device_kind]["value"]

    print(json.dumps({
        "metric": "tokens_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(value / baseline, 4),
    }))


if __name__ == "__main__":
    main()
