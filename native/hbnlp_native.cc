// Native hot paths for the data tooling.
//
// TPU-native replacement for the reference's two Cython components
// (/root/reference/scripts/train_tokenizer.pyx, local_text2tfrecord.pyx,
// compiled with gcc -Ofast by compile_*.sh): the compute-heavy inner loops —
// TFRecord framing + CRC32C, streaming text cleaning, and BPE pair
// counting/merging — live here; Python (homebrewnlp_tpu/native) binds via
// ctypes with a pure-Python fallback.
//
// Build: make -C native   (g++ -O3 -shared -fPIC, no deps)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- crc32c --
// Castagnoli CRC, slicing-by-8.
static uint32_t kCrcTable[8][256];
static bool crc_init_done = false;

static void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = kCrcTable[0][i];
    for (int t = 1; t < 8; ++t) {
      c = kCrcTable[0][c & 0xFF] ^ (c >> 8);
      kCrcTable[t][i] = c;
    }
  }
  crc_init_done = true;
}

uint32_t hb_crc32c(const uint8_t* data, size_t n) {
  crc_init();
  uint32_t crc = 0xFFFFFFFFu;
  while (n >= 8) {
    uint32_t lo, hi;
    memcpy(&lo, data, 4);
    memcpy(&hi, data + 4, 4);
    lo ^= crc;
    crc = kCrcTable[7][lo & 0xFF] ^ kCrcTable[6][(lo >> 8) & 0xFF] ^
          kCrcTable[5][(lo >> 16) & 0xFF] ^ kCrcTable[4][lo >> 24] ^
          kCrcTable[3][hi & 0xFF] ^ kCrcTable[2][(hi >> 8) & 0xFF] ^
          kCrcTable[1][(hi >> 16) & 0xFF] ^ kCrcTable[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n--) crc = kCrcTable[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

uint32_t hb_masked_crc(const uint8_t* data, size_t n) {
  uint32_t crc = hb_crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// --------------------------------------------------------- tfrecord write --
// Append framed records to a file: [u64 len][crc(len)][payload][crc(payload)]
int hb_write_records(const char* path, const uint8_t* payloads,
                     const uint64_t* lengths, uint64_t count, int append) {
  FILE* f = fopen(path, append ? "ab" : "wb");
  if (!f) return -1;
  const uint8_t* p = payloads;
  for (uint64_t i = 0; i < count; ++i) {
    uint64_t len = lengths[i];
    uint8_t header[8];
    memcpy(header, &len, 8);  // little-endian hosts only (x86/ARM)
    uint32_t hcrc = hb_masked_crc(header, 8);
    uint32_t pcrc = hb_masked_crc(p, len);
    if (fwrite(header, 1, 8, f) != 8 || fwrite(&hcrc, 4, 1, f) != 1 ||
        fwrite(p, 1, len, f) != len || fwrite(&pcrc, 4, 1, f) != 1) {
      fclose(f);
      return -2;
    }
    p += len;
  }
  fclose(f);
  return 0;
}

// ------------------------------------------------------------ text clean --
// Streaming cleaner (the ftfy-ish hot loop of train_tokenizer.pyx:98-106):
// drop control bytes except \n and \t, collapse \r\n -> \n, collapse runs of
// >2 blank lines, NFC is left to Python (rare path). Returns output length.
size_t hb_clean_text(const uint8_t* in, size_t n, uint8_t* out) {
  size_t o = 0;
  int newlines = 0;
  for (size_t i = 0; i < n; ++i) {
    uint8_t c = in[i];
    if (c == '\r') {
      if (i + 1 < n && in[i + 1] == '\n') continue;  // \r\n -> \n
      c = '\n';
    }
    if (c == '\n') {
      if (++newlines > 2) continue;  // at most one blank line
    } else {
      newlines = 0;
      if (c < 0x20 && c != '\t') continue;  // strip control bytes
    }
    out[o++] = c;
  }
  return o;
}

// ------------------------------------------------------------------- BPE --
// Greedy byte-pair training over a deduplicated word-frequency table — the
// same structure HuggingFace's BpeTrainer (which the reference calls,
// train_tokenizer.pyx:180-187) uses, so full-corpus scale is feasible:
//   * pair counts are maintained incrementally (only words containing the
//     merged pair are touched, found via pair -> word-id postings)
//   * the argmax pair comes from a lazy max-heap (stale entries validated
//     against the live count on pop)
// Tie-break: larger count first, then smaller packed (left<<32|right) key.
//
// words_flat / word_offsets: CSR of n_words token sequences (int32 ids);
// word_counts: corpus frequency per word.  out_pairs: n_merges*2 (left,
// right); merge i creates id first_new_id + i.  Returns merges performed.

struct HeapEntry {
  int64_t count;
  uint64_t key;
  bool operator<(const HeapEntry& o) const {
    if (count != o.count) return count < o.count;  // max-heap by count
    return key > o.key;                            // then smallest key
  }
};

static inline uint64_t pack(int32_t a, int32_t b) {
  return ((uint64_t)(uint32_t)a << 32) | (uint32_t)b;
}

int hb_bpe_train_words(const int32_t* words_flat, const int64_t* word_offsets,
                       const int64_t* word_counts, int64_t n_words,
                       int32_t n_merges, int32_t first_new_id,
                       int32_t* out_pairs) {
  std::vector<std::vector<int32_t>> words(n_words);
  std::unordered_map<uint64_t, int64_t> counts;
  std::unordered_map<uint64_t, std::unordered_set<int32_t>> postings;
  counts.reserve(1 << 18);
  postings.reserve(1 << 18);
  for (int64_t w = 0; w < n_words; ++w) {
    words[w].assign(words_flat + word_offsets[w],
                    words_flat + word_offsets[w + 1]);
    for (size_t i = 0; i + 1 < words[w].size(); ++i) {
      uint64_t key = pack(words[w][i], words[w][i + 1]);
      counts[key] += word_counts[w];
      postings[key].insert((int32_t)w);
    }
  }
  std::priority_queue<HeapEntry> heap;
  for (const auto& kv : counts) heap.push({kv.second, kv.first});

  int merges_done = 0;
  while (merges_done < n_merges && !heap.empty()) {
    HeapEntry top = heap.top();
    heap.pop();
    auto it = counts.find(top.key);
    if (it == counts.end() || it->second != top.count) continue;  // stale
    if (top.count < 2) break;
    int32_t left = (int32_t)(top.key >> 32);
    int32_t right = (int32_t)(top.key & 0xFFFFFFFFu);
    int32_t new_id = first_new_id + merges_done;
    out_pairs[2 * merges_done] = left;
    out_pairs[2 * merges_done + 1] = right;
    ++merges_done;

    auto post_it = postings.find(top.key);
    std::vector<int32_t> touched;
    if (post_it != postings.end())
      touched.assign(post_it->second.begin(), post_it->second.end());
    std::unordered_set<uint64_t> dirty;
    for (int32_t w : touched) {
      std::vector<int32_t>& word = words[w];
      int64_t c = word_counts[w];
      // remove this word's old pair contributions
      for (size_t i = 0; i + 1 < word.size(); ++i) {
        uint64_t key = pack(word[i], word[i + 1]);
        counts[key] -= c;
        dirty.insert(key);
      }
      // apply the merge in place
      size_t o = 0;
      for (size_t r = 0; r < word.size();) {
        if (r + 1 < word.size() && word[r] == left && word[r + 1] == right) {
          word[o++] = new_id;
          r += 2;
        } else {
          word[o++] = word[r++];
        }
      }
      word.resize(o);
      // add back the new contributions
      for (size_t i = 0; i + 1 < word.size(); ++i) {
        uint64_t key = pack(word[i], word[i + 1]);
        counts[key] += c;
        postings[key].insert(w);
        dirty.insert(key);
      }
    }
    counts.erase(top.key);
    postings.erase(top.key);
    dirty.erase(top.key);
    for (uint64_t key : dirty) {
      auto cit = counts.find(key);
      if (cit != counts.end() && cit->second > 0)
        heap.push({cit->second, key});
    }
  }
  return merges_done;
}

// Apply learned merges to encode a byte/token stream (local_text2tfrecord's
// encode loop). pairs: n_merges*2; merge i -> id first_new_id+i.
// Returns encoded length (<= n). In-place on `tokens`.
int64_t hb_bpe_encode(int32_t* tokens, int64_t n, const int32_t* pairs,
                      int32_t n_merges, int32_t first_new_id) {
  // Heap-driven greedy BPE: always merge the globally lowest-(rank, pos)
  // occurrence, O(n log n).  (The previous per-rank global-rescan was
  // O(n * applied_ranks) — 0.01 MB/s at a 65k-merge vocab; this form is
  // the standard tokenizer encode order and runs ~three orders faster.)
  std::unordered_map<uint64_t, int32_t> merge_rank;
  merge_rank.reserve(n_merges * 2);
  for (int32_t i = 0; i < n_merges; ++i) {
    uint64_t key = ((uint64_t)(uint32_t)pairs[2 * i] << 32) |
                   (uint32_t)pairs[2 * i + 1];
    merge_rank.emplace(key, i);
  }
  auto rank_of = [&](int32_t a, int32_t b) -> int32_t {
    uint64_t key = ((uint64_t)(uint32_t)a << 32) | (uint32_t)b;
    auto it = merge_rank.find(key);
    return it == merge_rank.end() ? n_merges : it->second;
  };
  std::vector<int64_t> nxt(n), prv(n);
  // negative INPUT tokens (word-boundary sentinels in the train-corpus
  // format) are preserved in the output and never pair (their rank lookup
  // always misses); consumption is tracked separately so the sentinel
  // contract of the previous implementation holds
  std::vector<char> dead(n, 0);
  for (int64_t i = 0; i < n; ++i) {
    nxt[i] = i + 1;
    prv[i] = i - 1;
  }
  using Entry = std::pair<int32_t, int64_t>;  // (rank, left position)
  std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> heap;
  for (int64_t i = 0; i + 1 < n; ++i) {
    int32_t r = rank_of(tokens[i], tokens[i + 1]);
    if (r < n_merges) heap.emplace(r, i);
  }
  while (!heap.empty()) {
    auto [r, i] = heap.top();
    heap.pop();
    if (dead[i]) continue;  // left token already consumed
    int64_t j = nxt[i];
    // stale entry: the pair at i changed since this entry was pushed
    if (j >= n || dead[j] || rank_of(tokens[i], tokens[j]) != r)
      continue;
    tokens[i] = first_new_id + r;
    dead[j] = 1;
    nxt[i] = nxt[j];
    if (nxt[j] < n) prv[nxt[j]] = i;
    if (prv[i] >= 0) {
      int32_t pr = rank_of(tokens[prv[i]], tokens[i]);
      if (pr < n_merges) heap.emplace(pr, prv[i]);
    }
    if (nxt[i] < n) {
      int32_t nr = rank_of(tokens[i], tokens[nxt[i]]);
      if (nr < n_merges) heap.emplace(nr, i);
    }
  }
  int64_t w = 0;
  for (int64_t i = 0; i < n; ++i)
    if (!dead[i]) tokens[w++] = tokens[i];
  return w;
}

}  // extern "C"
