// Native hot paths for the data tooling.
//
// TPU-native replacement for the reference's two Cython components
// (/root/reference/scripts/train_tokenizer.pyx, local_text2tfrecord.pyx,
// compiled with gcc -Ofast by compile_*.sh): the compute-heavy inner loops —
// TFRecord framing + CRC32C, streaming text cleaning, and BPE pair
// counting/merging — live here; Python (homebrewnlp_tpu/native) binds via
// ctypes with a pure-Python fallback.
//
// Build: make -C native   (g++ -O3 -shared -fPIC, no deps)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- crc32c --
// Castagnoli CRC, slicing-by-8.
static uint32_t kCrcTable[8][256];
static bool crc_init_done = false;

static void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = kCrcTable[0][i];
    for (int t = 1; t < 8; ++t) {
      c = kCrcTable[0][c & 0xFF] ^ (c >> 8);
      kCrcTable[t][i] = c;
    }
  }
  crc_init_done = true;
}

uint32_t hb_crc32c(const uint8_t* data, size_t n) {
  crc_init();
  uint32_t crc = 0xFFFFFFFFu;
  while (n >= 8) {
    uint32_t lo, hi;
    memcpy(&lo, data, 4);
    memcpy(&hi, data + 4, 4);
    lo ^= crc;
    crc = kCrcTable[7][lo & 0xFF] ^ kCrcTable[6][(lo >> 8) & 0xFF] ^
          kCrcTable[5][(lo >> 16) & 0xFF] ^ kCrcTable[4][lo >> 24] ^
          kCrcTable[3][hi & 0xFF] ^ kCrcTable[2][(hi >> 8) & 0xFF] ^
          kCrcTable[1][(hi >> 16) & 0xFF] ^ kCrcTable[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n--) crc = kCrcTable[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

uint32_t hb_masked_crc(const uint8_t* data, size_t n) {
  uint32_t crc = hb_crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// --------------------------------------------------------- tfrecord write --
// Append framed records to a file: [u64 len][crc(len)][payload][crc(payload)]
int hb_write_records(const char* path, const uint8_t* payloads,
                     const uint64_t* lengths, uint64_t count, int append) {
  FILE* f = fopen(path, append ? "ab" : "wb");
  if (!f) return -1;
  const uint8_t* p = payloads;
  for (uint64_t i = 0; i < count; ++i) {
    uint64_t len = lengths[i];
    uint8_t header[8];
    memcpy(header, &len, 8);  // little-endian hosts only (x86/ARM)
    uint32_t hcrc = hb_masked_crc(header, 8);
    uint32_t pcrc = hb_masked_crc(p, len);
    if (fwrite(header, 1, 8, f) != 8 || fwrite(&hcrc, 4, 1, f) != 1 ||
        fwrite(p, 1, len, f) != len || fwrite(&pcrc, 4, 1, f) != 1) {
      fclose(f);
      return -2;
    }
    p += len;
  }
  fclose(f);
  return 0;
}

// ------------------------------------------------------------ text clean --
// Streaming cleaner (the ftfy-ish hot loop of train_tokenizer.pyx:98-106):
// drop control bytes except \n and \t, collapse \r\n -> \n, collapse runs of
// >2 blank lines, NFC is left to Python (rare path). Returns output length.
size_t hb_clean_text(const uint8_t* in, size_t n, uint8_t* out) {
  size_t o = 0;
  int newlines = 0;
  for (size_t i = 0; i < n; ++i) {
    uint8_t c = in[i];
    if (c == '\r') {
      if (i + 1 < n && in[i + 1] == '\n') continue;  // \r\n -> \n
      c = '\n';
    }
    if (c == '\n') {
      if (++newlines > 2) continue;  // at most one blank line
    } else {
      newlines = 0;
      if (c < 0x20 && c != '\t') continue;  // strip control bytes
    }
    out[o++] = c;
  }
  return o;
}

// ------------------------------------------------------------------- BPE --
// Greedy byte-pair training over a token stream (the compute core of
// train_tokenizer.pyx's BpeTrainer call): repeatedly count adjacent pairs,
// merge the most frequent into a fresh id.  O(n_merges * n) rescan — simple,
// cache-friendly, and orders of magnitude faster than a Python loop.
//
// corpus: int32 tokens, -1 marks an unmergeable boundary (word split).
// out_pairs: n_merges * 2 ints (left id, right id), merge i creates id
// first_new_id + i.  Returns number of merges actually performed.
int hb_bpe_train(int32_t* corpus, int64_t n, int32_t n_merges,
                 int32_t first_new_id, int32_t* out_pairs) {
  std::vector<int32_t> buf(corpus, corpus + n);
  int merges_done = 0;
  for (int m = 0; m < n_merges; ++m) {
    std::unordered_map<uint64_t, int64_t> counts;
    counts.reserve(1 << 16);
    for (int64_t i = 0; i + 1 < (int64_t)buf.size(); ++i) {
      if (buf[i] < 0 || buf[i + 1] < 0) continue;
      uint64_t key = ((uint64_t)(uint32_t)buf[i] << 32) |
                     (uint32_t)buf[i + 1];
      ++counts[key];
    }
    uint64_t best_key = 0;
    int64_t best_count = 0;
    for (const auto& kv : counts) {
      if (kv.second > best_count ||
          (kv.second == best_count && kv.first < best_key)) {
        best_count = kv.second;
        best_key = kv.first;
      }
    }
    if (best_count < 2) break;  // nothing worth merging
    int32_t left = (int32_t)(best_key >> 32);
    int32_t right = (int32_t)(best_key & 0xFFFFFFFFu);
    int32_t new_id = first_new_id + m;
    out_pairs[2 * m] = left;
    out_pairs[2 * m + 1] = right;
    // in-place merge pass
    int64_t w = 0;
    for (int64_t r = 0; r < (int64_t)buf.size();) {
      if (r + 1 < (int64_t)buf.size() && buf[r] == left &&
          buf[r + 1] == right) {
        buf[w++] = new_id;
        r += 2;
      } else {
        buf[w++] = buf[r++];
      }
    }
    buf.resize(w);
    ++merges_done;
  }
  return merges_done;
}

// Apply learned merges to encode a byte/token stream (local_text2tfrecord's
// encode loop). pairs: n_merges*2; merge i -> id first_new_id+i.
// Returns encoded length (<= n). In-place on `tokens`.
int64_t hb_bpe_encode(int32_t* tokens, int64_t n, const int32_t* pairs,
                      int32_t n_merges, int32_t first_new_id) {
  std::unordered_map<uint64_t, int32_t> merge_rank;
  merge_rank.reserve(n_merges * 2);
  for (int32_t i = 0; i < n_merges; ++i) {
    uint64_t key = ((uint64_t)(uint32_t)pairs[2 * i] << 32) |
                   (uint32_t)pairs[2 * i + 1];
    merge_rank.emplace(key, i);
  }
  int64_t len = n;
  bool changed = true;
  while (changed) {
    changed = false;
    // find lowest-rank applicable merge, apply globally (BPE order matters)
    int32_t best_rank = n_merges;
    for (int64_t i = 0; i + 1 < len; ++i) {
      if (tokens[i] < 0 || tokens[i + 1] < 0) continue;
      uint64_t key = ((uint64_t)(uint32_t)tokens[i] << 32) |
                     (uint32_t)tokens[i + 1];
      auto it = merge_rank.find(key);
      if (it != merge_rank.end() && it->second < best_rank)
        best_rank = it->second;
    }
    if (best_rank == n_merges) break;
    int32_t left = pairs[2 * best_rank];
    int32_t right = pairs[2 * best_rank + 1];
    int32_t new_id = first_new_id + best_rank;
    int64_t w = 0;
    for (int64_t r = 0; r < len;) {
      if (r + 1 < len && tokens[r] == left && tokens[r + 1] == right) {
        tokens[w++] = new_id;
        r += 2;
        changed = true;
      } else {
        tokens[w++] = tokens[r++];
      }
    }
    len = w;
  }
  return len;
}

}  // extern "C"
