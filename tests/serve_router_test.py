"""Fleet-resilient serving tests (docs/reliability.md "Serving
resilience"): the health-aware replica router (``serve/router.py``) —
tiering, shedding, transparent pre-commit failover preserving
``X-Request-Id``, at-most-once past the first relayed byte, deadline-
bounded drain — plus the engine-side liveness stack it health-gates on:
``EngineHealth``/``ServeWatchdog`` stall detection, SSE client-disconnect
lane/KV reclamation, replica drain, and the graftserve chaos drill
(``replica:die`` behind a 2-replica router, ``@slow`` — the CI
``chaos-serve`` job runs it explicitly)."""
from __future__ import annotations

import http.client
import json
import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time
import typing
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from backend import mixer_config  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import graftload  # noqa: E402

from homebrewnlp_tpu.models import init_params  # noqa: E402
from homebrewnlp_tpu.obs.registry import MetricsRegistry  # noqa: E402
from homebrewnlp_tpu.reliability import faults  # noqa: E402
from homebrewnlp_tpu.serve import RestAPI, serve  # noqa: E402
from homebrewnlp_tpu.serve.interface import RequestCancelled  # noqa: E402
from homebrewnlp_tpu.serve.router import (Replica, Router,  # noqa: E402
                                          classify_health, serve_router)
from homebrewnlp_tpu.serve.slo import (EngineHealth,  # noqa: E402
                                       ServeWatchdog)
from homebrewnlp_tpu.utils import random_text_batch  # noqa: E402


# -- fake replicas (stdlib HTTP, no engine) -----------------------------------


class _FakeHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        srv = self.server
        if self.path.split("?", 1)[0].strip("/") != "healthz":
            self.send_error(404)
            return
        code, doc = srv.health
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        srv = self.server
        path = self.path.split("?", 1)[0].strip("/")
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n) or b"{}")
        xid = self.headers.get("X-Request-Id", "")
        with srv.lock:
            srv.seen.append((path, xid))
        mode = srv.mode
        if mode == "die":        # death BEFORE any response byte
            self.connection.close()
            return
        if mode == "http500":
            self.send_error(500, "injected")
            return
        if mode == "sse_mid":    # commit the first SSE event, then die
            first = b'data: {"tokens": [1]}\n\n'
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Content-Length", "4096")  # never delivered
            if xid:
                self.send_header("X-Request-Id", xid)
            self.end_headers()
            self.wfile.write(first)
            self.wfile.flush()
            return               # handler returns -> connection closes
        if srv.delay_s:
            time.sleep(srv.delay_s)
        out = {"completion": list(body.get("prompt") or []) + [7, 7]}
        payload = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        if xid:
            self.send_header("X-Request-Id", xid)
        self.end_headers()
        self.wfile.write(payload)


class FakeReplica:
    """A canned backend: POST /token_completion per ``mode``, GET /healthz
    per the mutable ``health`` (code, payload) pair."""

    def __init__(self, mode: str = "ok",
                 health: tuple = (200, {"status": "ok"})):
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), _FakeHandler)
        self.server.daemon_threads = True
        self.server.mode = mode
        self.server.health = health
        self.server.delay_s = 0.0
        self.server.seen = []
        self.server.lock = threading.Lock()
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.server.server_address[1]}"

    @property
    def seen(self):
        with self.server.lock:
            return list(self.server.seen)

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _post(url: str, body: dict, xid: typing.Optional[str] = None,
          timeout: float = 30.0):
    data = json.dumps(body).encode()
    hdr = {"Content-Type": "application/json"}
    if xid:
        hdr["X-Request-Id"] = xid
    req = urllib.request.Request(url + "/token_completion", data=data,
                                 headers=hdr)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read() or b"{}"), r.headers


def _router_over(replicas, registry=None, **kw) -> Router:
    reg = registry if registry is not None else MetricsRegistry()
    kw.setdefault("health_interval_s", 30.0)  # no background re-polls
    return Router(replicas, registry=reg, **kw)


# -- health tiering (pure) ----------------------------------------------------


def test_classify_health_tiers():
    assert classify_health(200, {"status": "ok"}) == ("ok", "ok")
    assert classify_health(200, {"status": "stalled"})[0] == "down"
    assert classify_health(503, {"status": "stalled"})[0] == "down"
    assert classify_health(200, {"status": "draining"})[0] == "down"
    assert classify_health(404, {"status": "ok"})[0] == "down"
    assert classify_health(200, None)[0] == "down"
    tier, reason = classify_health(
        200, {"status": "ok", "alerts": {"firing": ["ttft_p95_s"]}})
    assert tier == "degraded" and "ttft_p95_s" in reason
    tier, reason = classify_health(
        200, {"status": "ok", "slo": {"kv_blocks_free": 0}})
    assert tier == "degraded" and "kv" in reason
    # a free pool keeps the replica fully routable
    assert classify_health(
        200, {"status": "ok", "slo": {"kv_blocks_free": 3}})[0] == "ok"


def test_fault_plan_accepts_serve_sites_and_req_trigger():
    rules = faults.parse_plan(
        "replica:die@req5;serve_step:stall@3;replica:wedge_healthz@2;"
        "serve_step:fail@1")
    assert [(r.site, r.action, r.at) for r in rules] == [
        ("replica", "die", 5), ("serve_step", "stall", 3),
        ("replica", "wedge_healthz", 2), ("serve_step", "fail", 1)]


# -- selection ----------------------------------------------------------------


def test_pick_prefers_healthy_least_inflight_then_degraded():
    router = _router_over([Replica("http://127.0.0.1:1", name="a"),
                           Replica("http://127.0.0.1:2", name="b"),
                           Replica("http://127.0.0.1:3", name="c")])
    a, b, c = router.replicas
    router.observe_poll(a, "ok", "ok", {})
    router.observe_poll(b, "ok", "ok", {})
    router.observe_poll(c, "degraded", "kv pool exhausted", {})
    first = router.pick()
    assert first in (a, b) and first.inflight == 1
    second = router.pick()          # least-inflight: the OTHER healthy one
    assert second in (a, b) and second is not first
    third = router.pick()           # both healthy busy 1, still preferred
    assert third in (a, b)
    # healthy ones exhausted by `tried` -> degraded fallback
    assert router.pick(tried=[a, b]) is c
    # nothing left at all
    assert router.pick(tried=[a, b, c]) is None
    assert router.m_healthy.value() == 2.0


def test_mark_down_demotes_until_next_good_poll():
    router = _router_over([Replica("http://127.0.0.1:1", name="a")])
    (a,) = router.replicas
    router.observe_poll(a, "ok", "ok", {})
    assert router.pick() is a
    router.release(a)
    router.mark_down(a, "request failed: connect/send")
    assert router.pick() is None
    assert router.m_healthy.value() == 0.0
    router.observe_poll(a, "ok", "ok", {})  # the next successful poll
    assert router.pick() is a


# -- proxying / failover ------------------------------------------------------


def _run_router(router: Router):
    server = serve_router(router, port=0, background=True)
    return server, f"http://127.0.0.1:{server.server_address[1]}"


def test_router_routes_and_preserves_request_id():
    rep = FakeReplica()
    router = _router_over([Replica(rep.url, name="r0")])
    server, url = _run_router(router)
    try:
        time.sleep(0.2)  # initial health poll
        status, out, hdrs = _post(url, {"prompt": [1, 2]}, xid="keep-me")
        assert status == 200 and out["completion"] == [1, 2, 7, 7]
        assert hdrs.get("X-Request-Id") == "keep-me"
        assert hdrs.get("X-Replica") == "r0"
        assert rep.seen == [("token_completion", "keep-me")]
        # a router-minted id when the client sends none
        status, _, hdrs = _post(url, {"prompt": [3]})
        assert status == 200 and rep.seen[-1][1] == hdrs.get("X-Request-Id")
    finally:
        router.stop()
        server.shutdown()
        server.server_close()
        rep.close()


@pytest.mark.parametrize("failure", ["refused", "http500", "die"])
def test_router_failover_preserves_xid_and_counts(failure):
    """Replica death the router can see — connection refused, a 5xx, a
    connection dropped before any response byte — fails over transparently
    under the SAME X-Request-Id, and the merged trace shows both attempts
    under that one id."""
    if failure == "refused":
        bad_url, bad = f"http://127.0.0.1:{_free_port()}", None
    else:
        bad = FakeReplica(mode=failure)
        bad_url = bad.url
    good = FakeReplica()
    reg = MetricsRegistry()
    router = _router_over([Replica(bad_url, name="bad"),
                           Replica(good.url, name="good")], registry=reg)
    server, url = _run_router(router)
    try:
        time.sleep(0.2)
        bad_state, good_state = router.replicas
        # pin the pick order: only `bad` reads healthy, `good` is the
        # degraded fallback the failover retry reaches
        router.observe_poll(bad_state, "ok", "ok", {})
        router.observe_poll(good_state, "degraded", "kv pool exhausted", {})
        status, out, hdrs = _post(url, {"prompt": [9]}, xid="xid-fo")
        assert status == 200 and out["completion"] == [9, 7, 7]
        assert hdrs.get("X-Request-Id") == "xid-fo"
        assert hdrs.get("X-Replica") == "good"
        assert good.seen == [("token_completion", "xid-fo")]
        # the handler notes the terminal outcome AFTER relaying the last
        # body byte, so the client can get here first: poll briefly
        deadline = time.monotonic() + 5.0
        while (router.m_requests.value(replica="good", outcome="ok") < 1.0
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert router.m_failovers.value() == 1.0
        assert router.m_requests.value(replica="bad",
                                       outcome="failover") == 1.0
        assert router.m_requests.value(replica="good", outcome="ok") == 1.0
        # the failed replica was demoted on the spot
        assert not bad_state.healthy
        # merged trace: both attempts, one id, distinct pids for replicas
        doc = router.merged_trace(timeout_s=1.0)
        attempts = [e for e in doc["traceEvents"]
                    if e.get("pid") == 0 and e.get("ph") == "X"]
        assert [a["args"]["outcome"] for a in attempts] == ["failover", "ok"]
        assert {a["args"]["xid"] for a in attempts} == {"xid-fo"}
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("name") == "process_name"}
        assert {"router", "bad", "good"} <= names
    finally:
        router.stop()
        server.shutdown()
        server.server_close()
        good.close()
        if bad is not None:
            bad.close()


def test_router_sheds_stalled_and_draining_replicas():
    stalled = FakeReplica(health=(503, {"status": "stalled"}))
    draining = FakeReplica(health=(200, {"status": "draining"}))
    good = FakeReplica()
    router = _router_over(
        [Replica(stalled.url, name="stalled"),
         Replica(draining.url, name="draining"),
         Replica(good.url, name="good")],
        health_interval_s=0.1)
    server, url = _run_router(router)
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            s, d, g = router.replicas
            if g.healthy and not s.healthy and not d.healthy:
                break
            time.sleep(0.05)
        assert [r.healthy for r in router.replicas] == [False, False, True]
        assert router.replicas[0].reason == "stalled"
        assert router.replicas[1].reason == "draining"
        for i in range(4):
            status, _, hdrs = _post(url, {"prompt": [i]})
            assert status == 200 and hdrs.get("X-Replica") == "good"
        assert stalled.seen == [] and draining.seen == []
        assert len(good.seen) == 4
    finally:
        router.stop()
        server.shutdown()
        server.server_close()
        for r in (stalled, draining, good):
            r.close()


def test_router_at_most_once_past_first_sse_byte():
    """A replica that dies AFTER the first relayed SSE byte must not be
    retried — the client already holds a prefix; the router truncates."""
    dying = FakeReplica(mode="sse_mid")
    spare = FakeReplica()
    reg = MetricsRegistry()
    router = _router_over([Replica(dying.url, name="dying"),
                           Replica(spare.url, name="spare")], registry=reg)
    server, url = _run_router(router)
    try:
        time.sleep(0.2)
        dying_state, spare_state = router.replicas
        router.observe_poll(dying_state, "ok", "ok", {})
        router.observe_poll(spare_state, "ok", "ok", {})
        # pin the rr cursor so the dying replica takes this request
        router._rr = 0 if router.replicas[0] is dying_state else 1
        conn = http.client.HTTPConnection("127.0.0.1",
                                          server.server_address[1],
                                          timeout=10)
        conn.request("POST", "/token_completion",
                     body=json.dumps({"prompt": [1], "stream": True}),
                     headers={"Content-Type": "application/json",
                              "X-Request-Id": "amo-1"})
        resp = conn.getresponse()
        assert resp.status == 200
        first = resp.read1(8192)
        assert first.startswith(b"data: ")    # the committed prefix
        with pytest.raises((http.client.HTTPException, OSError)):
            while resp.read1(8192):           # stream dies mid-flight
                pass
            raise http.client.IncompleteRead(b"")  # clean-EOF short read
        conn.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:     # handler finishes async
            if reg.render().count("truncated"):
                break
            time.sleep(0.05)
        assert router.m_requests.value(replica="dying",
                                       outcome="truncated") == 1.0
        assert router.m_failovers.value() == 0.0
        assert spare.seen == []                # NEVER retried past commit
    finally:
        router.stop()
        server.shutdown()
        server.server_close()
        dying.close()
        spare.close()


def test_router_503_when_no_replica_is_routable():
    router = _router_over([Replica(f"http://127.0.0.1:{_free_port()}",
                                   name="gone")])
    server, url = _run_router(router)
    try:
        time.sleep(0.3)  # initial poll marks it down
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url, {"prompt": [1]}, xid="nope")
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert "no healthy replica" in body["error"]
        assert ei.value.headers.get("Retry-After") is not None
        assert ei.value.headers.get("X-Request-Id") == "nope"
    finally:
        router.stop()
        server.shutdown()
        server.server_close()


def test_router_drain_finishes_inflight_sheds_new_and_bounds_deadline():
    slow = FakeReplica()
    slow.server.delay_s = 0.8
    router = _router_over([Replica(slow.url, name="slow")],
                          health_interval_s=0.1)
    server, url = _run_router(router)
    try:
        time.sleep(0.3)
        results: dict = {}

        def go():
            results["inflight"] = _post(url, {"prompt": [1]}, xid="in-fl")

        t = threading.Thread(target=go, daemon=True)
        t.start()
        deadline = time.monotonic() + 5.0
        while server.inflight() == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server.inflight() == 1
        out: dict = {}
        dt = threading.Thread(
            target=lambda: out.setdefault("clean", server.drain(10.0)),
            daemon=True)
        t0 = time.monotonic()
        dt.start()
        while not router.draining and time.monotonic() < t0 + 5.0:
            time.sleep(0.005)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url, {"prompt": [2]})     # new admission: shed
        assert ei.value.code == 503
        assert "draining" in json.loads(ei.value.read())["error"]
        t.join(timeout=10.0)
        dt.join(timeout=10.0)
        assert results["inflight"][0] == 200   # in-flight finished
        assert out["clean"] is True
        assert time.monotonic() - t0 < 10.0    # bounded, not open-ended
    finally:
        server.server_close()
        slow.close()


def test_router_drain_gives_up_at_the_deadline():
    stuck = FakeReplica()
    stuck.server.delay_s = 8.0
    router = _router_over([Replica(stuck.url, name="stuck")],
                          health_interval_s=0.1)
    server, url = _run_router(router)
    try:
        time.sleep(0.3)
        t = threading.Thread(
            target=lambda: _post(url, {"prompt": [1]}, timeout=20.0),
            daemon=True)
        t.start()
        deadline = time.monotonic() + 5.0
        while server.inflight() == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        t0 = time.monotonic()
        assert server.drain(grace_deadline_s=0.2) is False
        assert time.monotonic() - t0 < 5.0
    finally:
        server.server_close()
        stuck.close()


# -- EngineHealth / ServeWatchdog ---------------------------------------------


def test_engine_health_idle_engine_never_stalls():
    h = EngineHealth(factor=2.0, min_stall_s=0.05)
    h.iteration_completed(0.01)
    time.sleep(0.12)             # idle: nothing in flight, however long
    assert h.stalled() is None
    assert h.snapshot()["status"] == "ok"


def test_engine_health_flags_overdue_iteration_and_recovers():
    h = EngineHealth(factor=1.0, min_stall_s=0.05)
    h.iteration_completed(0.01)
    h.iteration_started()
    time.sleep(0.12)
    late = h.stalled()
    assert late is not None and late > 0.05
    snap = h.snapshot()
    assert snap["status"] == "stalled" and snap["overdue_s"] > 0.05
    h.iteration_completed(0.12)  # the books close: healthy again
    assert h.stalled() is None and h.snapshot()["status"] == "ok"


def test_engine_health_draining_and_unarmed_watchdog():
    h = EngineHealth(factor=0.0)          # watchdog unarmed
    h.iteration_started()
    time.sleep(0.05)
    assert h.stalled() is None            # no factor -> no stall verdict
    h.set_draining(True)
    assert h.snapshot()["status"] == "draining"
    h.set_draining(False)
    assert h.snapshot()["status"] == "ok"


def test_engine_health_wedge_hangs_snapshot(monkeypatch):
    monkeypatch.setattr(EngineHealth, "WEDGE_S", 0.3)
    h = EngineHealth()
    h.wedge()
    t0 = time.monotonic()
    assert h.snapshot()["status"] == "ok"
    assert time.monotonic() - t0 >= 0.3   # the router's poll TIMEOUT trips


def test_serve_watchdog_fires_once_per_stall():
    reg = MetricsRegistry()
    dumps: list = []

    class Flight:
        def wants(self, reason):
            return True

        def dump(self, reason, extra=None):
            dumps.append((reason, extra))

    h = EngineHealth(factor=1.0, min_stall_s=0.05)
    h.iteration_completed(0.01)
    wd = ServeWatchdog(h, flight=Flight(), registry=reg, poll_s=0.02)
    wd.start()
    try:
        h.iteration_started()
        time.sleep(0.3)           # well past the threshold: one stall
        count = reg.counter("hbnlp_serve_watchdog_stalls_total", "").value()
        assert count == 1.0       # one per stall, not one per poll
        assert len(dumps) == 1 and dumps[0][0] == "watchdog"
        assert dumps[0][1]["overdue_s"] > 0.05
        h.iteration_completed(0.3)
        time.sleep(0.1)           # recovery re-arms
        h.iteration_started()
        time.sleep(0.3)
        assert reg.counter("hbnlp_serve_watchdog_stalls_total",
                           "").value() == 2.0
    finally:
        wd.stop()
        wd.join(timeout=2.0)


# -- engine-backed: cancel reclamation, stall e2e, replica drain --------------


def _engine_cfg(**over):
    base = dict(depth=1, sequence_length=32, heads=2, features_per_head=16,
                vocab_size=32, train_batch_size=1, sampling_temperature=0.0,
                use_autoregressive_sampling=True, serve_max_batch=2,
                watchdog_factor=1.5, serve_watchdog_min_stall_s=0.3)
    base.update(over)
    return mixer_config(**base)


@pytest.fixture(scope="module")
def engine_setup():
    cfg = _engine_cfg()
    params, _ = init_params(cfg, random_text_batch(cfg))
    return cfg, params


@pytest.fixture(scope="module")
def live_batch_server(engine_setup):
    cfg, params = engine_setup
    reg = MetricsRegistry()
    api = RestAPI(cfg, params)
    server = serve(cfg, None, port=0, background=True, registry=reg,
                   obs_port=0, api=api)
    yield server, cfg, reg
    server.shutdown()
    server.server_close()


def _wait_engine_idle(wrapper, free0: int, timeout_s: float = 30.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if (wrapper.kv_blocks_free() == free0
                and wrapper.active_lanes() == 0):
            return
        time.sleep(0.05)
    raise AssertionError(
        f"engine never reclaimed: free={wrapper.kv_blocks_free()} "
        f"(want {free0}), lanes={wrapper.active_lanes()}")


def test_cancel_raises_request_cancelled_and_reclaims(live_batch_server):
    """Satellite bugfix: a cancelled request's lane + KV blocks come back
    promptly — the scheduler's reap pass, not lane exhaustion, ends it."""
    server, cfg, reg = live_batch_server
    wrapper = server._batch_wrapper
    free0 = wrapper.kv_blocks_free()
    sink: "queue.Queue" = queue.Queue()
    fetch = wrapper.complete([1, 2, 3, 4], temperature=0.0, response_len=24,
                             asynchronous=True, token_sink=sink)
    assert sink.get(timeout=120.0) is not None   # generation is live
    fetch.cancel()
    with pytest.raises(RequestCancelled):
        fetch()
    _wait_engine_idle(wrapper, free0)
    # the token sink was closed (None sentinel), not left hanging
    items = []
    while True:
        item = sink.get(timeout=10.0)
        if item is None:
            break
        items.append(item)


def test_sse_client_disconnect_frees_lane_and_blocks(live_batch_server):
    server, cfg, reg = live_batch_server
    wrapper = server._batch_wrapper
    free0 = wrapper.kv_blocks_free()
    port = server.server_address[1]
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/token_completion",
                 body=json.dumps({"prompt": [1, 2, 3, 4],
                                  "temperature": 0.0, "response_len": 24,
                                  "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.read1(8192)       # at least the first SSE event arrived
    resp.close()                  # client vanishes mid-stream (owns the
    conn.close()                  # socket once Connection: close is up)
    _wait_engine_idle(wrapper, free0)
    # the replica still serves after the abandonment
    url = f"http://127.0.0.1:{port}"
    status, out, _ = _post(url, {"prompt": [5, 6], "temperature": 0.0,
                                 "response_len": 4}, timeout=120.0)
    assert status == 200 and len(out["completion"]) == 6


def test_stall_flips_healthz_and_router_routes_around(live_batch_server,
                                                      monkeypatch):
    """The e2e chain: ``serve_step:stall`` chaos wedges the decode loop ->
    EngineHealth flags the overdue iteration -> /healthz answers 503
    stalled -> the router's poll sheds the replica -> pick() routes to the
    healthy peer -> the loop recovers -> the next poll restores it."""
    server, cfg, reg = live_batch_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    obs_url = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
    # warm request: the jit compile must not be the EMA
    _post(url, {"prompt": [1, 2, 3], "temperature": 0.0, "response_len": 4},
          timeout=300.0)
    health = server.health
    assert health is not None and health.factor > 0
    for _ in range(60):            # wash the compile out of the cadence
        health.iteration_completed(0.02)
    peer = FakeReplica()
    router = _router_over([Replica(url, obs_url, name="real"),
                           Replica(peer.url, name="peer")],
                          health_timeout_s=2.0)
    real, peer_state = router.replicas
    router.poll_replica(real)
    router.poll_replica(peer_state)
    assert real.healthy and peer_state.healthy
    monkeypatch.setenv("HBNLP_SERVE_STALL_S", "2.5")
    faults.install("serve_step:stall@1")
    try:
        t = threading.Thread(
            target=lambda: _post(url, {"prompt": [5, 6, 7],
                                       "temperature": 0.0,
                                       "response_len": 4}, timeout=300.0),
            daemon=True)
        t.start()
        saw_stall = False
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            router.poll_replica(real)
            if not real.healthy and real.reason == "stalled":
                saw_stall = True
                break
            time.sleep(0.05)
        assert saw_stall, f"healthz never flipped (last: {real.reason!r})"
        picked = router.pick()     # routed AROUND the stalled replica
        assert picked is peer_state
        router.release(picked)
        t.join(timeout=300.0)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            router.poll_replica(real)
            if real.healthy:
                break
            time.sleep(0.1)
        assert real.healthy        # recovered once the stall passed
        assert reg.counter("hbnlp_serve_watchdog_stalls_total",
                           "").value() >= 1.0
    finally:
        faults.reset()
        peer.close()


def test_replica_drain_finishes_inflight_and_sheds_new(engine_setup):
    cfg, params = engine_setup
    reg = MetricsRegistry()
    api = RestAPI(cfg, params)
    server = serve(cfg, None, port=0, background=True, registry=reg,
                   obs_port=0, api=api)
    url = f"http://127.0.0.1:{server.server_address[1]}"
    obs_url = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
    try:
        results: dict = {}

        def go():
            results["inflight"] = _post(
                url, {"prompt": [1, 2, 3], "temperature": 0.0,
                      "response_len": 24}, timeout=300.0)

        t = threading.Thread(target=go, daemon=True)
        t.start()
        deadline = time.monotonic() + 120.0
        while server.slo.inflight() == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server.slo.inflight() >= 1
        out: dict = {}
        dt = threading.Thread(
            target=lambda: out.setdefault("clean", server.drain(120.0)),
            daemon=True)
        dt.start()
        t0 = time.monotonic()
        while not server.draining and time.monotonic() < t0 + 10.0:
            time.sleep(0.005)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url, {"prompt": [9], "temperature": 0.0,
                        "response_len": 4})
        assert ei.value.code == 503
        assert "draining" in json.loads(ei.value.read())["error"]
        # the health snapshot the router polls flips to draining too
        snap = json.loads(urllib.request.urlopen(
            obs_url + "/healthz", timeout=10).read())
        assert snap["status"] == "draining"
        t.join(timeout=300.0)
        dt.join(timeout=300.0)
        assert results["inflight"][0] == 200    # zero-5xx drain
        assert out["clean"] is True
    finally:
        server.server_close()


# -- chaos drill: replica:die behind a live 2-replica fleet (@slow) ----------


def _drill_cfg(tmp_path) -> str:
    raw = dict(
        model_mode="gpt", use_video=False, use_language=True,
        sequence_length=12, features_per_head=16, heads=2, depth=1,
        vocab_size=32, train_batch_size=1, calc_accuracy=False,
        memory_reduction_strategy="revnet", group_linear_factor=2,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[
            {"layer": ["norm-shift-scale-features-group",
                       "bottleneck_group_linear-in:relu-mid:relu-mid:norm-"
                       "mid:shift-mid:scale-mid:features"]},
        ],
        sampling_temperature=0.0, use_autoregressive_sampling=True,
        serve_max_batch=3, use_checkpointing=False,
        watchdog_factor=3.0, serve_watchdog_min_stall_s=1.0,
        model_path=str(tmp_path / "model"),
        compilation_cache_dir=str(tmp_path / "jitcache"),
    )
    path = tmp_path / "cfg.json"
    path.write_text(json.dumps(raw))
    return str(path)


def _healthy_replicas(router_url: str) -> int:
    try:
        req = urllib.request.Request(router_url + "/healthz")
        with urllib.request.urlopen(req, timeout=5) as r:
            return int(json.loads(r.read()).get("healthy", 0))
    except urllib.error.HTTPError as e:
        try:
            return int(json.loads(e.read()).get("healthy", 0))
        except (ValueError, OSError):
            return 0
    except OSError:
        return 0


@pytest.mark.slow
def test_chaos_drill_replica_die_behind_router(tmp_path):
    """The CI ``chaos-serve`` drill: 2 real replicas (graftserve), a
    closed-loop graftload at concurrency 16, ``replica:die`` hard-killing
    replica 0 mid-run.  Goodput must recover (>= 80% of requests OK, the
    chaos-tolerant verdict), the merged trace must hold zero request-id
    collisions, the router must have counted the failovers, and the
    supervisor must relaunch the dead replica back to a 2-healthy fleet
    with every surviving obs surface green (graftwatch --check)."""
    cfg_path = _drill_cfg(tmp_path)
    base_port, obs_port = _free_port(), _free_port()
    router_port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "graftserve.py"),
         "--model", cfg_path, "--replicas", "2",
         "--base-port", str(base_port), "--base-obs-port", str(obs_port),
         "--router-port", str(router_port),
         "--health-interval-s", "0.25", "--backoff-base", "0.25",
         "--grace-deadline-s", "15",
         "--fault-plan", "0:replica:die@req5"],
        env=env, cwd=REPO)
    router_url = f"http://127.0.0.1:{router_port}"
    try:
        deadline = time.monotonic() + 600.0
        while time.monotonic() < deadline:
            if _healthy_replicas(router_url) >= 2:
                break
            assert proc.poll() is None, "graftserve died during startup"
            time.sleep(1.0)
        assert _healthy_replicas(router_url) >= 2, "fleet never came up"
        trace_path = str(tmp_path / "merged.json")
        report = graftload.drive(
            router_url, n_requests=48, concurrency=16, response_len=4,
            temperature=0.0, seed=11, vocab=32, min_prompt=2, max_prompt=4,
            timeout_s=300.0, targets=[router_url],
            router_metrics_url=router_url, trace_out=trace_path)
        c = report["client"]
        assert not c["truncated"]
        # goodput recovery: the chaos-tolerant verdict (error count
        # bounded by peak in-flight at the kill) AND the 80% floor
        assert graftload.check_ok(report, chaos_tolerant=True), c
        assert c["n_ok"] >= 0.8 * c["n_requests"], c
        # the kill actually happened and the router absorbed it
        rr = report.get("router") or {}
        assert rr.get("failovers", 0) >= 1, rr
        assert rr.get("failover_column_consistent", False), rr
        assert rr.get("client_ok_matches_router", False), rr
        # zero id collisions in the merged trace
        doc = json.load(open(trace_path))
        xids = [e["args"]["xid"] for e in doc["traceEvents"]
                if e.get("pid") == 0 and e.get("name") == "client/request"]
        assert len(xids) == len(set(xids)) == 48
        # the supervisor relaunched replica 0: fleet back to 2-healthy
        deadline = time.monotonic() + 600.0
        while time.monotonic() < deadline:
            if _healthy_replicas(router_url) >= 2:
                break
            time.sleep(1.0)
        assert _healthy_replicas(router_url) >= 2, "fleet never recovered"
        # every replica's obs surface is green again
        for i in range(2):
            rc = subprocess.run(
                [sys.executable, os.path.join(REPO, "tools",
                                              "graftwatch.py"),
                 "--metrics-url", f"http://127.0.0.1:{obs_port + i}",
                 "--check"], env=env, cwd=REPO, timeout=60).returncode
            assert rc == 0, f"graftwatch --check failed for replica {i}"
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
