"""Fleet/ops tooling tests: sweep config generation, babysitter restart
logic, video pipeline (synthetic avi -> tfrecords -> VideoPipeline), subtitle
parsing, duration balancing."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_run_experiments_grid(tmp_path):
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"learning_rate": 1.0, "depth": 1}))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/run_experiments.py"),
         "--base", str(base), "--grid", "learning_rate=0.01,0.003",
         "--grid", "depth=8,16", "--out-dir", str(tmp_path / "sweep")],
        check=True, capture_output=True, text=True)
    cfgs = sorted((tmp_path / "sweep").glob("*.json"))
    assert len(cfgs) == 4
    one = json.loads(cfgs[0].read_text())
    assert one["learning_rate"] in (0.01, 0.003) and one["depth"] in (8, 16)
    assert str(tmp_path / "sweep") in one["model_path"]
    assert out.stdout.count("would launch") == 4


def test_run_manager_restarts_and_completes(tmp_path):
    """Child fails twice then succeeds; manager must restart and exit 0."""
    model = tmp_path / "run"
    model.mkdir()
    script = tmp_path / "child.sh"
    marker = tmp_path / "attempts"
    script.write_text(
        "#!/bin/bash\n"
        f"echo x >> {marker}\n"
        f"touch {model}/metrics.jsonl\n"
        f"if [ $(wc -l < {marker}) -lt 3 ]; then exit 1; fi\n")
    script.chmod(0o755)
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/run_manager.py"),
         "--cmd", str(script), "--model-path", str(model), "--poll", "1",
         "--max-restarts", "5"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert marker.read_text().count("x") == 3
    assert "restarting" in proc.stdout


def test_split_equal_balances():
    from video2tfrecord import split_equal
    buckets = split_equal([10, 1, 1, 1, 1, 1, 1, 1, 1, 2], 3)
    loads = [sum([10, 1, 1, 1, 1, 1, 1, 1, 1, 2][i] for i in b)
             for b in buckets]
    assert max(loads) <= 10  # the giant item sits alone-ish
    assert sum(len(b) for b in buckets) == 10


def test_parse_subs_cue_spans():
    from vtt_align import parse_timed_words
    words = parse_timed_words(
        "WEBVTT\n\n00:00:01.000 --> 00:00:03.500\nhello <i>world</i>\n"
        "\n00:00:04.000 --> 00:00:05.000\nsecond line\nmore\n")
    assert [w.word for w in words] == ["hello", "world", "second", "line",
                                       "more"]
    assert words[0].time == 1.0 and words[1].time == 2.25
    np.testing.assert_allclose([w.time for w in words[2:]],
                               [4.0, 4.0 + 1 / 3, 4.0 + 2 / 3])


KARAOKE_VTT = """WEBVTT
Kind: captions
Language: en

00:00:00.000 --> 00:00:02.100
hello<00:00:00.700><c> brave</c><00:00:01.400><c> new</c>

00:00:02.100 --> 00:00:04.000
new
world<00:00:02.800><c> of</c><00:00:03.400><c> <i>captions</i></c>
"""


def test_vtt_karaoke_word_timing():
    from vtt_align import parse_timed_words
    words = parse_timed_words(KARAOKE_VTT)
    assert [w.word for w in words] == ["hello", "brave", "new", "world",
                                       "of", "captions"]
    times = [w.time for w in words]
    assert times == [0.0, 0.7, 1.4, 2.1, 2.8, 3.4]
    # rolling repeat line ("new" alone) must NOT duplicate the word;
    # HTML tags inside <c> are stripped ("captions")


def test_vtt_karaoke_duplicate_lead_discrimination():
    """A rolling restated lead across a CONTIGUOUS cue boundary collapses;
    a genuine duplicate word after a silence gap is kept."""
    from vtt_align import parse_timed_words
    rolling = ("WEBVTT\n\n00:00:00.000 --> 00:00:02.100\n"
               "hello<00:00:00.700><c> new</c>\n\n"
               "00:00:02.100 --> 00:00:04.000\n"
               "new\nnew<00:00:02.800><c> world</c>\n")
    assert [w.word for w in parse_timed_words(rolling)] == [
        "hello", "new", "world"]
    gapped = ("WEBVTT\n\n00:00:00.000 --> 00:00:02.000\n"
              "hello<00:00:00.700><c> yeah</c>\n\n"
              "00:00:05.000 --> 00:00:07.000\n"
              "yeah\nyeah<00:00:05.800><c> right</c>\n")
    words = parse_timed_words(gapped)
    assert [w.word for w in words] == ["hello", "yeah", "yeah", "right"]
    assert words[2].time == 5.0  # the kept duplicate starts at its cue


def test_vtt_cue_interpolation():
    from vtt_align import parse_timed_words
    content = ("WEBVTT\n\n00:00:01.000 --> 00:00:03.000\n"
               "four words in here\n\n"
               "00:00:05.000 --> 00:00:06.000\nlast <b>cue</b>\n")
    words = parse_timed_words(content)
    assert [w.word for w in words] == ["four", "words", "in", "here",
                                       "last", "cue"]
    np.testing.assert_allclose([w.time for w in words],
                               [1.0, 1.5, 2.0, 2.5, 5.0, 5.5])


def test_align_tokens_byte_offsets():
    from vtt_align import align_tokens, byte_decode, byte_encode
    words = ["aa", "b", "aa"]  # repeated word: substring matching would slip
    lists = align_tokens(byte_encode, words)
    assert [byte_decode(t) for t in lists] == [" aa", " b", " aa"]
    # non-ASCII: multi-byte chars must not desynchronize the walk
    words = ["café", "au", "lait"]
    lists = align_tokens(byte_encode, words)
    assert [byte_decode(t) for t in lists] == [" café", " au", " lait"]
    # a multi-byte-merging tokenizer: pairs of bytes as single tokens
    def enc2(text):
        bs = text.encode()
        return [int.from_bytes(bs[i:i + 2].ljust(2, b"\0"), "big")
                for i in range(0, len(bs), 2)]
    lists = align_tokens(enc2, words, token_bytes=lambda t: 2)
    # every token lands on exactly one word, stream order preserved
    flat = [t for ts in lists for t in ts]
    assert flat == enc2(" café au lait")
    assert all(ts for ts in lists)


def test_align_tokens_bpe_vocab():
    """align_tokens over a real trained-BPE vocabulary: byte lengths from
    the merges table keep the walk synchronized."""
    from vtt_align import align_tokens, bpe_token_bytes
    from homebrewnlp_tpu.native import bpe_encode, bpe_train_words
    words = ["the", "theme", "of", "the", "day"]
    text = "".join(" " + w for w in words)
    corpus = {np.frombuffer(w.encode(), np.uint8).astype(np.int32).tobytes(): 5
              for w in set(words)}
    merges = bpe_train_words(corpus, 10, first_new_id=256)
    assert len(merges)  # multi-byte tokens exist, so lengths really vary

    def enc(t):
        toks = np.frombuffer(t.encode(), np.uint8).astype(np.int32)
        return bpe_encode(toks, merges).tolist()

    # expand a token id back to its bytes via the merge table
    expand = {i: bytes([i]) for i in range(256)}
    for i, (l, r) in enumerate(merges.tolist()):
        expand[256 + i] = expand[int(l)] + expand[int(r)]

    tb = bpe_token_bytes(merges.tolist())
    lists = align_tokens(enc, words, token_bytes=tb)
    # every word's token sublist must decode to exactly that word's span —
    # the real alignment property (a wrong token_bytes breaks this)
    for w, l in zip(words, lists):
        assert b"".join(expand[t] for t in l) == (" " + w).encode(), (w, l)
    assert sum(tb(t) for t in enc(text)) == len(text.encode())


def test_tokens_per_frame_window():
    from vtt_align import (TimedWord, align_tokens, byte_decode, byte_encode,
                           tokens_per_frame)
    timed = [TimedWord(0.0, "hi"), TimedWord(0.9, "mid"), TimedWord(2.5, "far")]
    lists = align_tokens(byte_encode, [w.word for w in timed])
    assert byte_decode(tokens_per_frame(timed, lists, 0.0, 1.0)) == " hi mid"
    assert tokens_per_frame(timed, lists, 1.0, 1.0) == []
    assert byte_decode(tokens_per_frame(timed, lists, 2.0, 1.0)) == " far"


def test_video2tfrecord_end_to_end(tmp_path):
    cv2 = pytest.importorskip("cv2")
    # synthetic avi
    vid_path = str(tmp_path / "in.avi")
    w = cv2.VideoWriter(vid_path, cv2.VideoWriter_fourcc(*"MJPG"), 10, (64, 32))
    rng = np.random.default_rng(0)
    for _ in range(30):
        w.write(rng.integers(0, 255, (32, 64, 3), np.uint8))
    w.release()
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(dict(
        model_mode="jannet", use_language=False, frame_height=32,
        frame_width=64, patch_size=16, sequence_length=4, experts=1,
        features_per_head=16, heads=2, depth=1)))
    out_dir = tmp_path / "shards"
    subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/video2tfrecord.py"),
         "--input", vid_path, "--model", str(cfg_path),
         "--output-dir", str(out_dir), "--fps", "10", "--procs", "1"],
        check=True, capture_output=True)
    shards = list(out_dir.glob("*.tfrecord"))
    assert len(shards) == 1

    # and the training pipeline can consume them
    from homebrewnlp_tpu.config import Config
    from homebrewnlp_tpu.data.video import VideoPipeline
    cfg = Config(json.loads(cfg_path.read_text()))
    pipe = VideoPipeline(cfg, sub_batch_size=2, paths=[str(shards[0])])
    batch = next(iter(pipe))
    assert batch["frame"].shape == (2, 5, 2, 4, 16 * 16 * 3)
    assert not batch["cat_mask_x"].all()  # first frame concat flag present


def test_manifest_chunk_and_split(tmp_path):
    import json as jsonlib
    from tools.manifest import chunk, load_manifests, main, split

    manifest = {"id": [f"v{i}" for i in range(20)],
                "duration": [float(10 + i * 3) for i in range(20)]}
    src = tmp_path / "manifest.json"
    src.write_text(jsonlib.dumps(manifest))

    # chunk: every chunk but possibly the last reaches min duration; nothing
    # is lost
    cids, cdur = chunk(manifest["id"], manifest["duration"], 60.0, seed=1)
    assert sorted(i for c in cids for i in c) == sorted(manifest["id"])
    assert all(sum(d) >= 60.0 for d in cdur[:-1])

    # split: balanced by duration, everything kept
    parts = split(manifest["id"], manifest["duration"], 4)
    totals = [sum(p["duration"]) for p in parts]
    assert sum(len(p["id"]) for p in parts) == 20
    assert max(totals) - min(totals) <= max(manifest["duration"])

    # CLI end-to-end: chunk then split the chunks across 3 workers
    main(["chunk", str(src), "--min-duration", "60", "--seed", "2",
          "--prefix", str(tmp_path) + "/"])
    chunks_path = tmp_path / "work_chunks.json"
    assert chunks_path.exists()
    main(["split", str(chunks_path), "--splits", "3",
          "--prefix", str(tmp_path) + "/"])
    outs = sorted(tmp_path.glob("work_split_*.json"))
    assert len(outs) == 3
    seen = []
    for p in outs:
        data = jsonlib.loads(p.read_text())
        for c in data["id"]:
            seen.extend(c)
    assert sorted(seen) == sorted(manifest["id"])


def test_text2tfrecord_jsonl_zst(tmp_path):
    """Pile-style streaming ingestion: .jsonl.zst shards -> TFRecords, one
    record per document, token count in the filename."""
    import json as jsonlib
    import subprocess
    zstandard = pytest.importorskip("zstandard")
    docs = ["hello world", "the quick brown fox", "pile document three"]
    src = tmp_path / "shard0.jsonl.zst"
    raw = "\n".join(jsonlib.dumps({"text": d, "meta": {}}) for d in docs)
    src.write_bytes(zstandard.ZstdCompressor().compress(raw.encode()))

    out = tmp_path / "out"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/text2tfrecord.py"),
         "--input", str(src), "--output-dir", str(out), "--jsonl-zst",
         "--procs", "1"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    from homebrewnlp_tpu.data.tfrecord import decode_example, read_records
    shards = sorted(out.glob("*.tfrecord"))
    assert len(shards) == 1
    total = int(shards[0].stem.split("_")[-1])
    payloads = list(read_records(str(shards[0]), verify=True))
    assert len(payloads) == 3
    texts = [decode_example(p)["text"][0].decode() for p in payloads]
    assert texts == docs
    assert total == sum(len(d) for d in docs)


# -- download front end (tools/fetch.py): the reference's proxied fleet ------
# (reference scripts/video2tfrecord.py:57-129,373-760) with every network
# call mocked — no egress needed to execute the logic.

def _chunked(data: bytes, n: int = 7):
    return [data[i:i + n] for i in range(0, len(data), n)]


def test_rate_limiter_spacing():
    from tools.fetch import RateLimiter
    t = [0.0]
    slept = []

    def clock():
        return t[0]

    def sleep(s):
        slept.append(s)
        t[0] += s

    rl = RateLimiter(1.0, clock=clock, sleep=sleep)
    rl.wait()            # first call never sleeps
    t[0] += 0.25
    rl.wait()            # 0.75s early
    rl.wait()            # immediately again: full interval
    assert slept == [0.75, 1.0]


def test_proxy_rotator_paginates_filters_and_rotates():
    import random

    from tools.fetch import ProxyRotator
    pages = {
        "/api/proxy/list/?page=1": {
            "next": "/api/proxy/list/?page=2",
            "results": [{"valid": False, "username": "x", "password": "x",
                         "proxy_address": "bad", "ports": {"http": 1}}]},
        "/api/proxy/list/?page=2": {
            "next": None,
            "results": [{"valid": True, "username": "u", "password": "p",
                         "proxy_address": "1.2.3.4", "ports": {"http": 80}}]},
    }
    calls = []

    def fetch_json(url, headers):
        assert headers == {"Authorization": "Token KEY"}
        path = url[len("https://proxy.webshare.io"):]
        calls.append(path)
        return pages[path]

    rot = ProxyRotator(fetch_json, "KEY", rng=random.Random(0))
    assert rot.proxies == {"http": "http://u:p@1.2.3.4:80",
                           "https": "http://u:p@1.2.3.4:80"}
    assert calls == ["/api/proxy/list/?page=1", "/api/proxy/list/?page=2"]
    rot.rotate()
    assert len(calls) == 4  # rotate() re-fetches the pool

    # no API key => no-proxy stub (reference webshare_io_key=None)
    assert ProxyRotator(fetch_json, None).proxies is None


def test_downloader_retries_rotates_and_cleans_partial(tmp_path):
    import random

    from tools.fetch import Downloader, ProxyRotator
    page = {"next": None, "results": [
        {"valid": True, "username": "u", "password": "p",
         "proxy_address": "1.2.3.4", "ports": {"http": 80}}]}
    rotations = []

    def fetch_json(url, headers):
        rotations.append(url)
        return page

    rot = ProxyRotator(fetch_json, "KEY", rng=random.Random(0))
    attempts = []

    def flaky(url, proxies):
        attempts.append(proxies)
        if len(attempts) < 3:
            yield b"partial"
            raise IOError("mid-stream drop")
        yield from _chunked(b"final payload")

    d = Downloader(flaky, rot, max_try=3)
    out = tmp_path / "a.bin"
    assert d.download("http://v", str(out), use_proxy=True)
    assert out.read_bytes() == b"final payload"
    assert len(attempts) == 3
    # proxied failures rotate the proxy before the next try (reference :84-87)
    assert len(rotations) == 3  # 1 init + 2 failure rotations

    def always_fail(url, proxies):
        yield b"junk"
        raise IOError("down")

    d2 = Downloader(always_fail, rot, max_try=2)
    out2 = tmp_path / "b.bin"
    assert not d2.download("http://v", str(out2), use_proxy=False)
    assert not out2.exists()  # partial file removed (reference :90-92)


def test_select_video_format_resolution_and_webm_demotion():
    from tools.fetch import select_video_format
    formats = [
        {"format_note": "tiny", "width": 9999, "height": 9999,
         "ext": "mp4", "url": "audio"},          # audio-only: skipped
        {"width": 256, "height": 144, "ext": "mp4", "url": "too-small"},
        {"width": 1920, "height": 1080, "ext": "mp4", "url": "too-big"},
        {"width": 640, "height": 360, "ext": "webm", "url": "w-webm"},
        {"width": 640, "height": 360, "ext": "mp4", "url": "w-mp4"},
        {"width": 640, "height": None, "ext": "mp4", "url": "no-h"},
    ]
    out = select_video_format(formats, (320, 176))
    # smallest resolution strictly above target wins; mp4 before webm
    assert [f["url"] for f in out] == ["w-mp4", "w-webm"]


def test_select_caption_track_en_vtt():
    from tools.fetch import select_caption_track
    info = {"automatic_captions": {"en": [
        {"ext": "srv1", "url": "no"},
        {"ext": "vtt", "url": "http://caps/en.vtt"},
        {"ext": "vtt", "url": "later"},
    ], "de": [{"ext": "vtt", "url": "wrong-lang"}]}}
    assert select_caption_track(info) == "http://caps/en.vtt"
    assert select_caption_track({}) is None


def test_fetch_video_mocked_transport(tmp_path):
    from tools.fetch import Downloader, fetch_video
    info = {
        "formats": [
            {"width": 640, "height": 360, "ext": "webm", "url": "u-webm"},
            {"width": 640, "height": 360, "ext": "mp4", "url": "u-mp4"},
        ],
        "automatic_captions": {"en": [{"ext": "vtt", "url": "u-vtt"}]},
    }
    served = {"u-mp4": b"mp4 bytes", "u-webm": b"webm bytes",
              "u-vtt": b"WEBVTT\n"}
    proxy_log = []

    def transport(url, proxies):
        proxy_log.append((url, proxies))
        yield from _chunked(served[url])

    d = Downloader(transport, None)
    video, vtt = fetch_video(
        "abc123", str(tmp_path), lambda url: info, d,
        target_resolution=(320, 176), want_subtitles=True)
    assert video == str(tmp_path / "abc123.mp4")
    assert vtt == str(tmp_path / "abc123.vtt")
    assert open(video, "rb").read() == b"mp4 bytes"
    # mp4 preferred over webm; vtt fetched after the video
    assert [u for u, _ in proxy_log] == ["u-mp4", "u-vtt"]

    # failed info extraction never raises (reference :525-527)
    def boom(url):
        raise RuntimeError("scrape blocked")

    assert fetch_video("zzz", str(tmp_path), boom, d, (320, 176)) == (None,
                                                                      None)


def test_fetch_video_falls_through_invalid_candidates(tmp_path):
    from tools.fetch import Downloader, fetch_video
    info = {"formats": [
        {"width": 640, "height": 360, "ext": "mp4", "url": "u-corrupt"},
        {"width": 640, "height": 360, "ext": "webm", "url": "u-good"},
    ]}
    served = {"u-corrupt": b"garbage", "u-good": b"webm bytes"}
    converted = []

    def transport(url, proxies):
        yield served[url]

    def convert(src, dst):
        converted.append((src, dst))
        os.rename(src, dst)

    d = Downloader(transport, None)
    video, _ = fetch_video(
        "vid", str(tmp_path), lambda url: info, d, (320, 176),
        convert=convert, validate=lambda p: b"webm" in open(p, "rb").read())
    # corrupt mp4 rejected by the validator and removed; webm converted
    assert video == str(tmp_path / "vid.mp4")
    assert converted and not os.path.exists(str(tmp_path / "vid.webm"))
    assert not os.path.exists(str(tmp_path / "vid.garbage"))


def test_plan_worker_shards_balances_and_filters():
    from tools.fetch import plan_worker_shards
    ids = [[f"v{i}"] for i in range(10)]
    durations = [100.0, 2000.0, 300.0, 400.0, 1500.0, 50.0, 600.0, 700.0,
                 800.0, 900.0]
    shards, loads = plan_worker_shards(ids, durations, 3, min_duration=256.0)
    kept = sorted(v for s in shards for c in s for v in c)
    # chunks at or below min_duration dropped (v0=100, v5=50)
    assert kept == sorted(f"v{i}" for i in range(10) if i not in (0, 5))
    assert max(loads) - min(loads) <= max(durations)


def test_stream_pile_documents_mocked_http(tmp_path):
    import json as jsonlib
    zstandard = pytest.importorskip("zstandard")
    from tools.fetch import pile_worker_shards, stream_pile_documents
    shard_docs = {
        0: [{"text": "doc zero"}, {"text": ["part a", "part b"]}],
        2: [{"text": "doc two"}],
    }
    blobs = {}
    for shard, docs in shard_docs.items():
        raw = "\n".join(jsonlib.dumps(d) for d in docs)
        blobs[f"http://pile/{shard:02d}.jsonl.zst"] = (
            zstandard.ZstdCompressor().compress(raw.encode()))
    requested = []

    def transport(url, proxies):
        requested.append(url)
        yield from _chunked(blobs[url], 11)

    shards = pile_worker_shards(0, 2, 4)   # worker 0 of 2 over 4 splits
    assert shards == [0, 2]
    docs = list(stream_pile_documents(
        shards, transport, url_template="http://pile/{shard:02d}.jsonl.zst",
        separator=4))
    assert docs == ["doc zero", "part a\x04part b", "doc two"]
    assert requested == ["http://pile/00.jsonl.zst",
                         "http://pile/02.jsonl.zst"]


def test_download_and_encode_fleet_mocked(tmp_path):
    """Full fleet worker against mocked transports: manifest -> shards ->
    fetch (synthetic avi served as 'download') -> one tfrecord per chunk."""
    cv2 = pytest.importorskip("cv2")
    import json as jsonlib

    from tools.fetch import Downloader, load_manifest, plan_worker_shards
    from tools.video2tfrecord import download_and_encode

    vid_path = str(tmp_path / "served.avi")
    w = cv2.VideoWriter(vid_path, cv2.VideoWriter_fourcc(*"MJPG"), 10,
                        (64, 32))
    rng = np.random.default_rng(0)
    for _ in range(20):
        w.write(rng.integers(0, 255, (32, 64, 3), np.uint8))
    w.release()
    video_bytes = open(vid_path, "rb").read()
    vtt = ("WEBVTT\n\n00:00:00.000 --> 00:00:01.000\nhello there\n\n"
           "00:00:01.000 --> 00:00:02.000\nfleet worker\n")

    manifest = tmp_path / "manifest.json"
    manifest.write_text(jsonlib.dumps(
        {"id": ["vidA", "vidB", "missing"],
         "duration": [300.0, 400.0, 500.0]}))
    ids, durations = load_manifest([str(manifest)])
    shards, _ = plan_worker_shards(ids, durations, 1, min_duration=256.0)

    def info_extractor(url):
        vid = url.rsplit("=", 1)[1]
        if vid == "missing":
            raise RuntimeError("unavailable")
        return {"formats": [{"width": 640, "height": 360, "ext": "avi",
                             "url": f"http://v/{vid}.avi"}],
                "automatic_captions": {"en": [
                    {"ext": "vtt", "url": f"http://v/{vid}.vtt"}]}}

    def transport(url, proxies):
        yield video_bytes if url.endswith(".avi") else vtt.encode()

    def convert(src, dst):  # "ffmpeg": the avi is already cv2-readable
        os.rename(src, dst)

    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(jsonlib.dumps(dict(
        model_mode="jannet", use_language=True, frame_height=32,
        frame_width=64, patch_size=16, sequence_length=4, experts=1,
        features_per_head=16, heads=2, depth=1,
        language_token_per_frame=8)))
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    buffer_dir = tmp_path / "buffer"

    outs = download_and_encode(
        [[c for c in chunk] for chunk in shards[0]], 0, str(out_dir),
        str(buffer_dir), str(cfg_path), 10.0, info_extractor,
        Downloader(transport, None), convert=convert,
        validate=lambda p: True, want_subtitles=True,
        skip_if_no_subtitles=True, keep_buffer=False)
    assert len(outs) == 2  # vidA + vidB chunks; "missing" skipped
    from homebrewnlp_tpu.data.tfrecord import decode_example, read_records
    recs = list(read_records(outs[0], verify=True))
    assert recs
    ex = decode_example(recs[0])
    assert "frame" in ex and "tokens" in ex and ex["concat"][0] == 1
    # download buffer cleaned (keep_buffer=False)
    assert not list(buffer_dir.glob("*"))
