"""Jannet (joint video+text) end-to-end: real VideoPipeline batches through
the full model, both losses, gradients, training step on the 8-device mesh —
the reference's primary mode (model_mode='jannet'), which its own test suite
never exercised end-to-end (SURVEY.md §4)."""
import json

import jax
import numpy as np
import pytest

from homebrewnlp_tpu.config import Config
from homebrewnlp_tpu.data import to_global, write_video_tfrecords
from homebrewnlp_tpu.data.video import VideoPipeline
from homebrewnlp_tpu.parallel import make_mesh
from homebrewnlp_tpu.train import Trainer


def jannet_config(**over):
    base = dict(
        model_mode="jannet", use_video=True, use_language=True,
        frame_height=32, frame_width=32, patch_size=16, experts=1,
        sequence_length=4, language_token_per_frame=8, token_patch_size=1,
        heads=2, features_per_head=16, depth=1, vocab_size=256,
        train_batch_size=2, memory_reduction_strategy="none",
        optimizer="adam-learning_rate", learning_rate=3e-3,
        calc_accuracy=True,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
    )
    base.update(over)
    return Config(base)


@pytest.fixture(scope="module")
def video_batch(tmp_path_factory):
    pytest.importorskip("cv2")
    cfg = jannet_config()
    d = tmp_path_factory.mktemp("vids")
    paths = write_video_tfrecords(str(d), 2, 16, cfg, seed=5)
    pipe = VideoPipeline(cfg, sub_batch_size=cfg.train_batch_size, paths=paths)
    return cfg, next(iter(pipe))


def test_jannet_batch_shapes(video_batch):
    cfg, batch = video_batch
    t = cfg.time_patch_size
    assert batch["frame"].shape[:2] == (2, t + 1)
    assert batch["token_x"].shape == (2, t, cfg.language_token_patch,
                                      cfg.token_patch_size)
    assert batch["txt_msk"].shape == batch["token_y"].shape


def test_jannet_trains_both_losses(eight_devices, video_batch):
    cfg, np_batch = video_batch
    mesh = make_mesh(cfg)
    trainer = Trainer(cfg, mesh)
    gb = to_global(np_batch, cfg, mesh)
    state = trainer.init(gb)
    first = None
    for i in range(8):
        state, m = trainer.step(state, gb, jax.random.key(i))
        if first is None:
            first = m
    assert "token_loss" in first and "video_loss" in first
    assert np.isfinite(float(first["token_loss"]))
    assert np.isfinite(float(first["video_loss"]))
    assert float(m["loss"]) < float(first["loss"])


def test_jannet_multiloss_pcgrad(eight_devices, video_batch):
    cfg, np_batch = video_batch
    cfg = jannet_config(multi_loss_strategy="pcgrad")
    mesh = make_mesh(cfg)
    trainer = Trainer(cfg, mesh)
    gb = to_global(np_batch, cfg, mesh)
    state = trainer.init(gb)
    state, m = trainer.step(state, gb, jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


def test_jannet_video_only(eight_devices, tmp_path):
    pytest.importorskip("cv2")
    cfg = jannet_config(use_language=False, language_token_per_frame=0)
    paths = write_video_tfrecords(str(tmp_path), 1, 16, cfg, seed=7)
    pipe = VideoPipeline(cfg, sub_batch_size=2, paths=paths)
    np_batch = next(iter(pipe))
    assert "token_x" not in np_batch
    mesh = make_mesh(cfg)
    trainer = Trainer(cfg, mesh)
    gb = to_global(np_batch, cfg, mesh)
    state = trainer.init(gb)
    state, m = trainer.step(state, gb, jax.random.key(0))
    assert np.isfinite(float(m["video_loss"]))
