"""Flight-recorder / SLO-alerting / request-tracing tests
(docs/observability.md "Flight recorder" / "SLO alerting" / "Request
tracing"): objective parsing, burn-rate properties (monotone in breach
fraction, window independence), exemplar cap + render byte-identity,
bundle schema round-trips, dump rate limiting, tail sampling, the
client/server clock-offset estimator + merged Chrome traces, graftwatch's
check verdict, and a live end-to-end SLO-breach incident."""
import json
import os
import sys
import urllib.request

import pytest

from homebrewnlp_tpu.models import init_params
from homebrewnlp_tpu.obs.flight import (BUNDLE_SCHEMA, FlightRecorder,
                                        request_trail, validate_bundle)
from homebrewnlp_tpu.obs.registry import EXEMPLAR_CAP, MetricsRegistry
from homebrewnlp_tpu.obs.slo_alerts import (ALERT_THRESHOLD, SLOAlerts,
                                            parse_objective,
                                            validate_objectives)
from homebrewnlp_tpu.obs.spans import SpanTracer
from homebrewnlp_tpu.serve import serve
from homebrewnlp_tpu.serve.slo import RequestRecord
from homebrewnlp_tpu.utils import random_text_batch

from .backend import mixer_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import graftload  # noqa: E402
import graftwatch  # noqa: E402


def _small_cfg(**over):
    base = dict(depth=1, sequence_length=12, heads=2, features_per_head=16,
                vocab_size=32, train_batch_size=1,
                initial_autoregressive_position=4, sampling_temperature=0.0,
                use_autoregressive_sampling=True)
    base.update(over)
    return mixer_config(**base)


# -- objective parsing --------------------------------------------------------

def test_parse_objective_latency_and_error_rate():
    ob = parse_objective("ttft_p95_s", 2.0)
    assert (ob.kind, ob.metric, ob.threshold) == ("latency", "ttft", 2.0)
    assert ob.budget == pytest.approx(0.05)
    ob = parse_objective("error_rate", 0.01)
    assert (ob.kind, ob.budget) == ("error_rate", 0.01)


@pytest.mark.parametrize("key,value", [
    ("ttft_p95_s", 0.0),          # non-positive bound
    ("ttft_p95_s", "fast"),       # not a number
    ("error_rate", 1.5),          # budget is a fraction
    ("ttft_p0_s", 1.0),           # percentile out of (0, 100)
    ("loss_p95_s", 1.0),          # unknown metric
    ("ttft_p95", 1.0),            # missing the _s unit suffix
])
def test_parse_objective_rejects(key, value):
    with pytest.raises(ValueError):
        parse_objective(key, value)


def test_validate_objectives_normalizes():
    out = validate_objectives({"e2e_p99_s": "3", "error_rate": 0.05})
    assert out == {"e2e_p99_s": 3.0, "error_rate": 0.05}


def test_config_rejects_bad_objectives_and_triggers():
    with pytest.raises(ValueError):
        _small_cfg(slo_objectives={"bogus_key": 1.0})
    with pytest.raises(ValueError):
        _small_cfg(flight_dump_triggers="slo")  # bare string, not a list
    with pytest.raises(ValueError):
        _small_cfg(flight_dump_triggers=["slo", "nonsense"])


# -- burn-rate properties -----------------------------------------------------

def _burn(n_total, n_breach, now=1000.0, window="fast"):
    al = SLOAlerts({"ttft_p95_s": 1.0})
    for i in range(n_total):
        ttft = 2.0 if i < n_breach else 0.1
        al.observe(status=200, ttft_s=ttft, now=now)
    return al.burn_rates(now=now)["ttft_p95_s"][window]


def test_burn_rate_monotone_in_breach_fraction():
    # property: more breaches in the same window can only raise the burn
    n = 20
    rates = [_burn(n, k) for k in range(n + 1)]
    assert rates == sorted(rates)
    assert rates[0] == 0.0
    # all-breach: fraction 1.0 over budget 0.05 -> burn 20x
    assert rates[-1] == pytest.approx(1.0 / 0.05)


def test_burn_rate_window_independence():
    # an old breach burst sits inside the slow window but OUTSIDE the
    # fast one: the fast rate must not see it
    al = SLOAlerts({"ttft_p95_s": 1.0})
    now = 10_000.0
    for _ in range(10):
        al.observe(status=200, ttft_s=9.0, now=now - 300.0)  # slow only
    for _ in range(10):
        al.observe(status=200, ttft_s=0.1, now=now)          # both windows
    rates = al.burn_rates(now=now)["ttft_p95_s"]
    assert rates["fast"] == 0.0
    assert rates["slow"] == pytest.approx((10 / 20) / 0.05)


def test_alert_fires_only_when_both_windows_burn():
    al = SLOAlerts({"ttft_p95_s": 1.0})
    now = 10_000.0
    # breaches only in the slow window: no alert (fast window is clean)
    for _ in range(5):
        al.observe(status=200, ttft_s=9.0, now=now - 300.0)
    al.observe(status=200, ttft_s=0.1, now=now)
    assert al.summary(now=now)["firing"] == []
    # breach NOW too: both windows hot -> rising edge fires
    for _ in range(5):
        al.observe(status=200, ttft_s=9.0, now=now)
    assert al.summary(now=now)["firing"] == ["ttft_p95_s"]
    # windows drain -> the alert clears without new traffic
    assert al.summary(now=now + 3600.0)["firing"] == []


def test_error_rate_objective_counts_5xx_and_missing_milestones():
    al = SLOAlerts({"error_rate": 0.5, "ttft_p95_s": 1.0})
    now = 1000.0
    al.observe(status=500, now=now)          # 5xx, no TTFT stamp
    al.observe(status=200, now=now)          # 2xx, never reached TTFT
    al.observe(status=200, ttft_s=0.1, now=now)
    rates = al.burn_rates(now=now)
    # error_rate: 1 of 3 breached over budget .5
    assert rates["error_rate"]["fast"] == pytest.approx((1 / 3) / 0.5)
    # latency: the 5xx-without-stamp is a breach, the stampless 2xx is
    # NOT a sample -> 1 of 2
    assert rates["ttft_p95_s"]["fast"] == pytest.approx((1 / 2) / 0.05)


def test_on_alert_rising_edge_only():
    fired = []
    al = SLOAlerts({"ttft_p95_s": 1.0},
                   on_alert=lambda k, info: fired.append(k))
    now = 1000.0
    for _ in range(3):
        al.observe(status=200, ttft_s=9.0, now=now)
    assert fired == ["ttft_p95_s"]  # one edge, not one per observe
    assert al.burn_rates(now=now)["ttft_p95_s"]["fast"] > ALERT_THRESHOLD


def test_burn_rate_gauge_registered():
    reg = MetricsRegistry()
    SLOAlerts({"ttft_p95_s": 1.0}, registry=reg).observe(
        status=200, ttft_s=9.0, now=1000.0)
    text = reg.render()
    assert 'hbnlp_slo_burn_rate{objective="ttft_p95_s",window="fast"}' in text


# -- exemplars ----------------------------------------------------------------

def test_exemplar_cap_and_render_byte_identity():
    reg = MetricsRegistry()
    h = reg.histogram("t_ex_seconds", "x", buckets=(1.0, 2.0))
    for v in (0.5, 1.5, 3.0):
        h.observe(v)
    before = reg.render()
    for i in range(EXEMPLAR_CAP + 40):
        h.attach_exemplar(0.5 + (i % 3), {"request_id": f"r{i}"})
    assert len(h.exemplars()) <= EXEMPLAR_CAP
    # the default Prometheus 0.0.4 render must not change by a byte
    assert reg.render() == before
    om = reg.render_openmetrics()
    assert om.rstrip().endswith("# EOF")
    assert 'request_id="r' in om  # exemplar suffix made it out


# -- bundles + recorder -------------------------------------------------------

def _finished_record(rid=1, xid="t-0001", status=200):
    rec = RequestRecord(rid, path="/token_completion")
    rec.xid = xid
    rec.mark_parsed()
    rec.mark_enqueued(queue_depth=0)
    rec.mark_started()
    rec.mark_first_token()
    rec.mark_engine_done()
    rec.tokens_generated = 3
    rec.mark_finished(status)
    return rec


def test_request_trail_carries_xid_and_latencies():
    trail = request_trail(_finished_record())
    assert trail["xid"] == "t-0001"
    assert trail["status"] == 200
    assert trail["e2e_s"] >= 0.0
    assert trail["ttft_s"] is not None


def test_validate_bundle_catches_damage():
    fr = FlightRecorder(max_spans=8, model_path="")
    doc = fr.bundle("manual")
    assert doc["schema"] == BUNDLE_SCHEMA
    assert validate_bundle(doc) == []
    bad = dict(doc)
    del bad["spans"]
    bad["schema"] = "nope"
    problems = validate_bundle(bad)
    assert any("spans" in p for p in problems)
    assert any("schema" in p for p in problems)
    assert validate_bundle([]) == ["bundle is not a JSON object"]


def test_recorder_ring_is_bounded():
    fr = FlightRecorder(max_records=4)
    for i in range(10):
        fr.observe_request(_finished_record(rid=i, xid=f"t-{i:04d}"))
    doc = fr.bundle("manual")
    assert len(doc["requests"]) == 4
    assert doc["requests"][-1]["xid"] == "t-0009"  # newest kept


def test_dump_rate_limit_and_trigger_gate(tmp_path):
    fr = FlightRecorder(model_path=str(tmp_path),
                        triggers=("error",), min_dump_interval_s=3600.0)
    assert fr.dump("slo") is None            # not an armed trigger
    p1 = fr.dump("error")
    assert p1 and os.path.exists(p1)
    assert fr.dump("error") is None          # rate-limited
    p2 = fr.dump("error", force=True)        # manual endpoint bypasses
    assert p2 and p2 != p1
    assert validate_bundle(json.load(open(p1))) == []
    assert fr.dumps == [p1, p2]


def test_tail_sampling_attaches_exemplar():
    reg = MetricsRegistry()
    from homebrewnlp_tpu.serve.slo import ServeSLO
    ServeSLO(reg)  # registers the serve histograms exemplars land on
    fr = FlightRecorder(registry=reg, tail_min_samples=4)
    for i in range(8):
        fr.observe_request(_finished_record(rid=i))
    slow = _finished_record(rid=99, xid="t-slow")
    slow.t_finished = slow.t_arrival + 100.0  # way past rolling p99
    trail = fr.observe_request(slow)
    assert trail["tail"] is True
    h = reg.get("hbnlp_serve_request_seconds")
    assert any(lbl.get("request_id") == "t-slow"
               for _, lbl, _ in h.exemplars().values())


def test_engine_trace_rotation_writes_segments(tmp_path):
    # a tiny span ring under real traffic: the serve_trace_path export
    # must roll to numbered segments instead of silently dropping spans,
    # and close() still writes the base path with the final partial ring
    base = str(tmp_path / "serve.trace.json")
    cfg = _small_cfg(serve_max_batch=2, serve_trace_path=base,
                     flight_buffer_spans=32)
    params, _ = init_params(cfg, random_text_batch(cfg))
    from homebrewnlp_tpu.serve.engine import BatchEngine
    eng = BatchEngine(cfg, params)
    try:
        for _ in range(4):
            eng.complete_tokens([1, 2, 3], 0.0, 4)
    finally:
        eng.close()
    assert eng.trace_segments, "span ring filled but never rotated"
    assert eng.trace_segments[0].endswith(".001.json")
    for seg_path in eng.trace_segments:
        assert json.load(open(seg_path))["traceEvents"]
    assert os.path.exists(base)


def test_span_tracer_rotate_clears_ring(tmp_path):
    tr = SpanTracer(max_events=16)
    with tr.span("x"):
        pass
    assert tr.event_count() == 1
    out = str(tmp_path / "seg.json")
    assert tr.rotate(out) == out
    assert tr.event_count() == 0
    doc = json.load(open(out))
    assert any(e.get("name") == "x" for e in doc["traceEvents"])


# -- clock offset + merged traces ---------------------------------------------

def _stamp_rec(i, c0, off, up_s, down_s):
    # client sends at c0; server clock = client + off; legs up_s/down_s
    s0 = c0 + up_s + off
    s1 = s0 + 0.01
    c1 = s1 - off + down_s
    return {"id": i, "xid": f"x-{i:04d}", "status": 200,
            "c_send_wall_s": c0, "s_recv_wall_s": s0, "s_send_wall_s": s1,
            "c_hdr_wall_s": c1, "c_done_wall_s": c1 + 0.001, "e2e_s": 0.02}


def test_estimate_offset_recovers_symmetric_offset():
    recs = [_stamp_rec(i, 100.0 + i, off=5.0, up_s=0.004, down_s=0.004)
            for i in range(6)]
    est = graftload.estimate_offset(recs)
    assert est["n_pairs"] == 6
    # symmetric legs: the NTP estimator is exact
    assert est["offset_s"] == pytest.approx(5.0, abs=1e-6)
    assert est["bound_s"] >= 0.0


def test_estimate_offset_bound_covers_asymmetry():
    # one-sided delay: the estimate is off by (down-up)/2, which the
    # half-round-trip term in the bound must cover
    recs = [_stamp_rec(i, 100.0 + i, off=5.0, up_s=0.0, down_s=0.02)
            for i in range(4)]
    est = graftload.estimate_offset(recs)
    assert abs(est["offset_s"] - 5.0) <= est["bound_s"]
    assert graftload.estimate_offset([{"id": 0}]) is None  # no stamp quad


def test_merge_traces_rebases_server_onto_client_clock():
    recs = [_stamp_rec(i, 100.0 + i, off=5.0, up_s=0.002, down_s=0.002)
            for i in range(3)]
    server_doc = {"otherData": {"wall_epoch": 104.9},  # == client 99.9
                  "traceEvents": [
                      {"name": "serve/request", "ph": "X", "pid": 9,
                       "tid": 1, "ts": 150_000.0, "dur": 1000.0,
                       "args": {"xid": "x-0000"}}]}
    doc = graftload.merge_traces(recs, server_doc)
    other = doc["otherData"]
    assert other["n_client_requests"] == 3
    assert other["n_server_events"] == 1
    client0 = next(e for e in doc["traceEvents"]
                   if e["name"] == "client/request"
                   and e["args"]["xid"] == "x-0000")
    server0 = next(e for e in doc["traceEvents"] if e["pid"] == 1)
    # server epoch 104.9 is client 99.9; +0.15s puts the span at client
    # 100.05 — 50ms after the client span opened at origin 100.0
    assert client0["ts"] == pytest.approx(0.0, abs=1.0)
    assert server0["ts"] == pytest.approx(50_000.0, abs=1e4)
    assert other["clock_offset"]["offset_s"] == pytest.approx(5.0, abs=1e-3)


# -- bench ratchet ------------------------------------------------------------

def test_serve_baseline_flight_overhead_gate():
    import bench
    base = {"e2e_p50_s": 0.1}
    # the cap is absolute: a fat baseline does not license a fat row
    gate, ok = bench.evaluate_serve_baseline(
        {"e2e_p50_s": 0.1, "flight_overhead_frac": 0.002}, base)
    assert ok and gate["flight_overhead_frac"]["pass"]
    gate, ok = bench.evaluate_serve_baseline(
        {"e2e_p50_s": 0.1, "flight_overhead_frac": 0.02}, base)
    assert not ok and not gate["flight_overhead_frac"]["pass"]
    # a row without the figure is not gated on it
    gate, ok = bench.evaluate_serve_baseline({"e2e_p50_s": 0.1}, base)
    assert ok and "flight_overhead_frac" not in gate


# -- graftwatch ---------------------------------------------------------------

def test_graftwatch_verdict():
    ok, reasons = graftwatch.verdict({"healthz": {"status": "ok"}})
    assert ok and reasons == []
    ok, reasons = graftwatch.verdict(
        {"healthz": {"status": "stalled",
                     "alerts": {"firing": ["ttft_p95_s"]}}})
    assert not ok
    assert len(reasons) == 2


# -- live end-to-end: a deliberate SLO breach ---------------------------------

def test_e2e_breach_fires_alert_and_dumps_flight_bundle(tmp_path):
    cfg = _small_cfg(model_path=str(tmp_path / "m"),
                     slo_objectives={"ttft_p95_s": 1e-6},  # unmeetable
                     flight_buffer_spans=512)
    params, _ = init_params(cfg, random_text_batch(cfg))
    reg = MetricsRegistry()
    server = serve(cfg, params, port=0, background=True, registry=reg,
                   obs_port=0)
    try:
        port = server.server_address[1]
        url = f"http://127.0.0.1:{port}"
        murl = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
        trace_path = str(tmp_path / "merged.json")
        report = graftload.drive(url, metrics_url=murl, n_requests=4,
                                 concurrency=2, response_len=4,
                                 temperature=0.0, seed=7,
                                 trace_out=trace_path)
        assert report["client"]["n_ok"] == 4
        # merged trace: both arms of one request id on one timebase
        doc = json.load(open(trace_path))
        xids = {e["args"]["xid"] for e in doc["traceEvents"]
                if e.get("pid") == 0 and e["name"] == "client/request"}
        assert xids and all(x.startswith("gl7-") for x in xids)
        assert any(e.get("pid") == 1
                   and e.get("args", {}).get("xid") in xids
                   for e in doc["traceEvents"])
        assert doc["otherData"]["clock_offset"]["bound_s"] < 5.0
        # the unmeetable objective fires on /healthz ...
        with urllib.request.urlopen(murl + "/healthz", timeout=10) as r:
            hz = json.loads(r.read())
        assert hz["alerts"]["firing"] == ["ttft_p95_s"]
        # ... flips graftwatch --check nonzero ...
        assert graftwatch.main(["--metrics-url", murl, "--check"]) == 1
        # ... and auto-wrote an slo-trigger bundle holding a breaching
        # request's full trail under the propagated request id
        diag = os.path.join(cfg.model_path, "diagnostics")
        bundles = [json.load(open(os.path.join(diag, f)))
                   for f in sorted(os.listdir(diag))]
        slo_bundles = [b for b in bundles if b["reason"] == "slo"]
        assert slo_bundles
        for b in slo_bundles:
            assert validate_bundle(b) == []
        assert any(r.get("xid", "").startswith("gl7-")
                   for b in slo_bundles for r in b["requests"])
        # manual dump via graftwatch: fetch, validate, write locally
        out = str(tmp_path / "incident.json")
        assert graftwatch.main(["--metrics-url", murl, "--url", url,
                                "--dump", out]) == 0
        local = json.load(open(out))
        assert validate_bundle(local) == []
    finally:
        server.shutdown()
        server.server_close()
