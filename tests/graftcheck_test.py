"""graftcheck static-analysis subsystem: trace harness, graph rules (census
goldens, donation, sharding specs, constant bloat), AST lint (axis literals,
f64 requests, RNG/time, PartitionSpec axes, .x ratchet), NT scope-named
errors, and the CLI."""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from homebrewnlp_tpu import nd
from homebrewnlp_tpu.analysis import (ast_rules, graph_rules, trace as
                                      atrace)
from homebrewnlp_tpu.analysis.findings import Finding, worst_severity
from homebrewnlp_tpu.config import Config

from .backend import tiny_config


def _load_config(name):
    raw = json.load(open(os.path.join(REPO, "configs", name)))
    raw.pop("_comment", None)
    return Config(raw)


# -- NT scope-path errors (ISSUE satellite) ---------------------------------

def test_nt_rank_mismatch_names_scope():
    nd.push_scope("gpt")
    nd.push_scope("body")
    try:
        with pytest.raises(ValueError, match=r"gpt/body"):
            nd.NT(jnp.zeros((2, 3)), ("batch",))
    finally:
        nd.pop_scope()
        nd.pop_scope()
    # outside any scope the message stays shape-only
    with pytest.raises(ValueError) as e:
        nd.NT(jnp.zeros((2, 3)), ("batch",))
    assert "scope" not in str(e.value)


def test_model_build_error_names_layer_scope():
    """A rank mismatch raised while building a real model names the
    enclosing block scope, making analyzer findings actionable."""
    from homebrewnlp_tpu.models import build
    from homebrewnlp_tpu.models.ctx import Ctx
    from homebrewnlp_tpu.models.registry import LAYER_FUNCTIONS
    from .backend import text_batch
    cfg = tiny_config()
    batch = text_batch(cfg)
    orig = LAYER_FUNCTIONS["feed_forward"]

    def broken(args):
        out = orig(args)
        return nd.NT(out.x, out.names[:-1])  # drop a name -> rank mismatch

    LAYER_FUNCTIONS["feed_forward"] = broken
    try:
        with pytest.raises(ValueError, match=r"scope '.*body.*'"):
            build(Ctx(cfg, params=None, seed=0, train=False), batch)
    finally:
        LAYER_FUNCTIONS["feed_forward"] = orig


def test_axis_registry_has_canonical_names():
    known = nd.known_axes()
    for name in ("batch", "sequence", "heads", "features_per_head", "vocab",
                 "pipe_stage"):
        assert name in known, name


# -- trace harness ----------------------------------------------------------

def test_trace_tiny_config_train_and_decode(eight_devices):
    cfg = tiny_config()
    traces = atrace.trace_config(cfg, "tiny", steps=("train", "eval",
                                                     "decode"))
    assert not traces.errors, traces.errors
    assert set(traces.steps) == {"train", "eval", "decode"}
    assert traces.param_shapes and traces.param_axes
    # abstract params: no leaf is a concrete array
    for v in traces.param_shapes.values():
        assert isinstance(v, jax.ShapeDtypeStruct)
    census = graph_rules.census_of(traces.steps["train"])
    assert census["n_eqns"] > 0
    # clean tree: donation + dtype + sharding + const rules all quiet
    # (golden-backed rules excluded: the ad-hoc "tiny" config has none)
    findings = [f for f in graph_rules.run_graph_rules(traces)
                if f.rule not in ("collective-census", "resource-budget",
                                  "implicit-collective", "mesh-rank")]
    errors = [f for f in findings if f.severity == "error"]
    assert not errors, [f.render() for f in errors]


def test_composed_dryrun_census_matches_golden(eight_devices):
    """The DP/SP/PP/TP composed config (ring attention nested in 1F1B
    pipeline stages) traces and its collective census matches the committed
    golden — the ppermute budget only changes deliberately."""
    cfg = _load_config("8dev_composed_dryrun.json")
    traces = atrace.trace_config(cfg, "8dev_composed_dryrun",
                                 steps=("train", "decode"))
    assert not traces.errors, traces.errors
    findings = graph_rules.check_collective_census(traces)
    assert not findings, [f.render() for f in findings]
    census = graph_rules.census_of(traces.steps["train"])
    # the composed graph must actually move data around the rings: pipeline
    # hops + ring attention rotations
    assert census["collectives"].get("ppermute", 0) >= 8, census


def test_census_diff_detected(eight_devices, monkeypatch, tmp_path):
    """An unplanned collective (census drift vs golden) is an error."""
    cfg = tiny_config()
    traces = atrace.trace_config(cfg, "tinycensus", steps=("train",))
    monkeypatch.setattr(graph_rules, "GOLDENS_DIR", str(tmp_path))
    # record, verify clean, then tamper the golden budget
    graph_rules.check_collective_census(traces, update_goldens=True)
    assert graph_rules.check_collective_census(traces) == []
    path = graph_rules.golden_path("tinycensus")
    golden = json.load(open(path))
    train = golden["steps"]["train"]
    train["collectives"]["all_gather"] = \
        train["collectives"].get("all_gather", 0) + 2
    json.dump(golden, open(path, "w"))
    findings = graph_rules.check_collective_census(traces)
    assert any(f.severity == "error" and "all_gather" in f.message
               for f in findings), [f.render() for f in findings]


# -- graph rules: seeded defects --------------------------------------------

def test_injected_bad_partitionspec_rule_is_caught(eight_devices,
                                                   monkeypatch):
    """Regression (ISSUE acceptance): a mesh-unknown axis in the sharding
    rule table — which spec_for silently replicates — fails the validator."""
    from homebrewnlp_tpu.parallel import sharding as shmod
    cfg = tiny_config()
    traces = atrace.trace_config(cfg, "tiny", steps=())
    bad = dict(shmod.RULES)
    bad["batch"] = "dataa"  # graftcheck: disable=partitionspec-axis
    monkeypatch.setattr(graph_rules, "RULES", bad)
    findings = graph_rules.check_sharding_specs(traces)
    assert any(f.severity == "error" and "dataa" in f.message
               for f in findings), [f.render() for f in findings]
    # clean table passes
    monkeypatch.setattr(graph_rules, "RULES", dict(shmod.RULES))
    assert not [f for f in graph_rules.check_sharding_specs(traces)
                if f.severity == "error"]


def test_dropped_donation_is_caught(eight_devices):
    """A train step jitted WITHOUT donate_argnums fails the donation audit;
    the real step (donating) passes."""
    from homebrewnlp_tpu.train.state import TrainState
    cfg = tiny_config()
    traces = atrace.trace_config(cfg, "tiny", steps=("train",))
    assert graph_rules.check_donation(traces) == []

    params = traces.param_shapes
    state = TrainState(params, {}, jax.ShapeDtypeStruct((), jnp.int32))

    def fake_step(state, rng):
        return state

    traced = jax.jit(fake_step).trace(state, jax.random.key(0))
    st = atrace.StepTrace("train", traced.jaxpr, traces.mesh,
                          traced.args_info, traced.args_info[0][0])
    bad = atrace.ConfigTraces("tiny", cfg, traces.mesh, {"train": st},
                              traces.param_axes, params, {})
    findings = graph_rules.check_donation(bad)
    assert findings and all(f.severity == "error" for f in findings)
    assert "donate" in findings[0].message


def test_serve_donation_audit_passes_on_batch_engine_config(eight_devices):
    """The donation rule's serving extension: a KV-cache-eligible config
    running the continuous-batching engine traces the engine's EXACT
    jitted decode/prefill executables and finds the pooled state donated
    (the ROADMAP cache-donation residual, now ratcheted)."""
    from .backend import mixer_config
    cfg = mixer_config(depth=1, sequence_length=12, heads=2,
                       features_per_head=16, vocab_size=32,
                       train_batch_size=1, serve_max_batch=2)
    traces = atrace.trace_config(cfg, "engine_tiny", steps=("train",))
    assert graph_rules.check_donation(traces) == []


def test_serve_donation_dropped_is_caught(eight_devices, monkeypatch):
    """Seeded regression: stripping donate_argnums from the engine's
    executables must fail the donation audit naming the pooled buffers."""
    from homebrewnlp_tpu.serve import engine
    from .backend import mixer_config
    cfg = mixer_config(depth=1, sequence_length=12, heads=2,
                       features_per_head=16, vocab_size=32,
                       train_batch_size=1, serve_max_batch=2)
    traces = atrace.trace_config(cfg, "engine_tiny", steps=("train",))
    orig = engine.jit_executables

    def undonated(cfg, rows, n_lanes, first_token_cb=None):
        import functools
        dec = functools.partial(engine.decode_body, cfg, rows, n_lanes,
                                first_token_cb)
        pre = functools.partial(engine.prefill_body, cfg, rows)
        return jax.jit(dec), jax.jit(pre), None

    monkeypatch.setattr(engine, "jit_executables", undonated)
    findings = graph_rules.check_donation(traces)
    assert findings and all(f.severity == "error" for f in findings)
    assert any("pooled KV caches" in f.message for f in findings)
    assert any("serve_decode" in f.location for f in findings)
    assert any("serve_prefill" in f.location for f in findings)
    monkeypatch.setattr(engine, "jit_executables", orig)
    # serialized-path configs (serve_max_batch=1) skip the engine audit
    cfg1 = mixer_config(depth=1, sequence_length=12, heads=2,
                        features_per_head=16, vocab_size=32,
                        train_batch_size=1, serve_max_batch=1)
    t1 = atrace.trace_config(cfg1, "serialized_tiny", steps=("train",))
    assert graph_rules._check_serve_donation(t1) == []


def test_serve_donation_warns_on_aot_no_donate_tradeoff(eight_devices,
                                                       tmp_path):
    """serve_aot_cache_dir engines compile undonated (the serialization
    tradeoff) — the audit must surface that as a warning, never a silent
    green."""
    from .backend import mixer_config
    cfg = mixer_config(depth=1, sequence_length=12, heads=2,
                       features_per_head=16, vocab_size=32,
                       train_batch_size=1, serve_max_batch=2,
                       serve_aot_cache_dir=str(tmp_path))
    traces = atrace.trace_config(cfg, "engine_aot", steps=("train",))
    findings = graph_rules.check_donation(traces)
    warns = [f for f in findings if f.severity == "warning"]
    assert any("WITHOUT pool donation" in f.message for f in warns)
    assert not [f for f in findings if f.severity == "error"]


def test_constant_bloat_detected(eight_devices):
    big = jnp.asarray(np.ones((512, 1024), np.float32))  # 2 MB closure

    def f(x):
        return x @ big

    jaxpr = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((4, 512), jnp.float32))
    cfg = tiny_config()
    mesh = traces_mesh = None
    st = atrace.StepTrace("train", jaxpr, traces_mesh)
    traces = atrace.ConfigTraces("tiny", cfg, mesh, {"train": st}, {}, {}, {})
    findings = graph_rules.check_constant_bloat(traces)
    assert any(f.severity == "error" for f in findings), findings


def test_f64_in_graph_detected(eight_devices):
    """The jaxpr-level dtype audit flags real f64 avals (as produced when
    x64 is enabled)."""
    import dataclasses

    def f(x):
        return x + 1

    jaxpr = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((4,), jnp.float32))
    # forge an f64 aval on the output eqn (x64 cannot be toggled in-process)
    eqn = jaxpr.jaxpr.eqns[-1]
    var = eqn.outvars[0]
    var.aval = var.aval.update(dtype=jnp.dtype("float64"))
    cfg = tiny_config()
    st = atrace.StepTrace("train", jaxpr, None)
    traces = atrace.ConfigTraces("tiny", cfg, None, {"train": st}, {}, {}, {})
    findings = graph_rules.check_dtype_promotion(traces)
    assert findings and findings[0].severity == "error"
    assert "f64" in findings[0].message


# -- quant-dtype allowlist (ISSUE 6) ----------------------------------------

def _quant_mixer_traces(**overrides):
    from .backend import mixer_config
    cfg = mixer_config(quant_blocks=["bottleneck_group_linear"], **overrides)
    traces = atrace.trace_config(cfg, "tinyquant", steps=("train",))
    assert not traces.errors, traces.errors
    return traces


def test_quant_census_counts_and_rule_clean(eight_devices):
    """A declared quant scope shows int8 dots + casts in the census and the
    quant-dtype rule passes; an undeclared config's census carries NO quant
    key (goldens stay byte-stable)."""
    traces = _quant_mixer_traces()
    census = graph_rules.census_of(traces.steps["train"])
    assert census["quant"]["int8_dot"] > 0
    assert census["quant"]["int8_cast"] > 0
    assert graph_rules.check_quant_dtype(traces) == []
    from .backend import mixer_config
    plain = atrace.trace_config(mixer_config(), "tinyplain",
                                steps=("train",))
    assert "quant" not in graph_rules.census_of(plain.steps["train"])
    assert graph_rules.check_quant_dtype(plain) == []


def test_quant_outside_declared_scope_is_error(eight_devices):
    """Seeded regression: int8 ops in a graph whose config declares NO
    quant scope fail the ratchet (the allowlist direction)."""
    import dataclasses
    traces = _quant_mixer_traces()
    undeclared = dataclasses.replace(traces, cfg=tiny_config())
    findings = graph_rules.check_quant_dtype(undeclared)
    assert findings and all(f.severity == "error" for f in findings)
    assert "quant_blocks is empty" in findings[0].message


def test_quant_silent_fallback_is_error(eight_devices):
    """Seeded regression: a declared scope that matches no layer (typo /
    fused-kernel bypass) compiles zero quantized dots — an error, not a
    silently-unquantized 'success'."""
    from .backend import mixer_config
    cfg = mixer_config(quant_blocks=["bottleneck_gruop_linear"])  # typo
    traces = atrace.trace_config(cfg, "tinytypo", steps=("train",))
    assert not traces.errors, traces.errors
    findings = graph_rules.check_quant_dtype(traces)
    assert findings and findings[0].severity == "error"
    assert "silently fell back" in findings[0].message


def test_quant_census_drift_detected(eight_devices, monkeypatch, tmp_path):
    """The quant counts are ratcheted through the census golden: a pinned
    int8_dot figure that stops matching the trace is an error."""
    traces = _quant_mixer_traces()
    monkeypatch.setattr(graph_rules, "GOLDENS_DIR", str(tmp_path))
    graph_rules.check_collective_census(traces, update_goldens=True)
    assert graph_rules.check_collective_census(traces) == []
    path = graph_rules.golden_path("tinyquant")
    golden = json.load(open(path))
    golden["steps"]["train"]["quant"]["int8_dot"] += 2
    json.dump(golden, open(path, "w"))
    findings = graph_rules.check_collective_census(traces)
    assert any(f.severity == "error" and "int8_dot" in f.message
               for f in findings), [f.render() for f in findings]


def test_quant_committed_config_golden_matches(eight_devices):
    """The bundled 32mixer_group_int8 config: census golden (incl. the
    pinned quant counts) matches and the quant-dtype rule is green, on a
    shrunk twin of the real trace path."""
    cfg = _load_config("32mixer_group_int8.json")
    assert cfg.quant_blocks == ["bottleneck_group_linear"]
    traces = atrace.trace_config(cfg, "32mixer_group_int8",
                                 steps=("train",))
    assert not traces.errors, traces.errors
    assert graph_rules.check_quant_dtype(traces) == []
    census = graph_rules.census_of(traces.steps["train"])
    golden = json.load(open(graph_rules.golden_path("32mixer_group_int8")))
    assert census["quant"] == golden["steps"]["train"]["quant"]


# -- AST rules --------------------------------------------------------------

def _mini_tree(tmp_path, models_src="", ops_src=""):
    for rel, src in (("homebrewnlp_tpu/models/m.py", models_src),
                     ("homebrewnlp_tpu/ops/o.py", ops_src),
                     ("homebrewnlp_tpu/infer/__init__.py", ""),
                     ("homebrewnlp_tpu/data/__init__.py", ""),
                     ("homebrewnlp_tpu/optim/__init__.py", ""),
                     ("homebrewnlp_tpu/train/__init__.py", ""),
                     ("tools/__init__.py", "")):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return str(tmp_path)


def test_ast_axis_literal_typo_caught(tmp_path):
    root = _mini_tree(tmp_path, models_src=(
        "from homebrewnlp_tpu.nd import NT\n"
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    a = NT(x, ('batch', 'sequnce'))\n"          # typo -> error
        "    b = a.rename('sequence', '_sequence')\n"    # anonymized ok
        "    return b\n"))
    findings = ast_rules.check_axis_literals(root)
    assert len(findings) == 1 and "sequnce" in findings[0].message
    assert findings[0].location.endswith("m.py:4")


def test_ast_axis_literal_suppression(tmp_path):
    root = _mini_tree(tmp_path, models_src=(
        "from homebrewnlp_tpu.nd import NT\n"
        "def f(x):\n"
        "    return NT(x, ('totally_custom',))"
        "  # graftcheck: disable=axis-literal\n"))
    assert ast_rules.check_axis_literals(root) == []


def test_ast_f64_literal_caught(tmp_path):
    root = _mini_tree(tmp_path, models_src=(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    return x.astype(jnp.float64)\n"))
    findings = ast_rules.check_f64_literals(root)
    assert len(findings) == 1 and findings[0].severity == "error"


def test_ast_traced_rng_caught(tmp_path):
    root = _mini_tree(tmp_path, ops_src=(
        "import time\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    t = time.time()\n"
        "    r = np.random.normal()\n"
        "    return x + r + t\n"))
    findings = ast_rules.check_traced_rng(root)
    msgs = " ".join(f.message for f in findings)
    assert len(findings) == 2 and "time.time" in msgs and "np.random" in msgs


def test_ast_partitionspec_unknown_axis_caught(tmp_path):
    root = _mini_tree(tmp_path, models_src=(
        "from jax.sharding import PartitionSpec\n"
        "SPEC = PartitionSpec('data', 'modell')\n"))
    findings = ast_rules.check_partitionspec_literals(root)
    assert len(findings) == 1 and "modell" in findings[0].message


def test_ast_x_escape_ratchet(tmp_path, monkeypatch):
    root = _mini_tree(tmp_path, models_src=(
        "def f(t):\n    return t.x + t.x\n"))
    golden = tmp_path / "goldens" / "ast_x_escapes.json"
    monkeypatch.setattr(ast_rules, "x_escape_golden_path",
                        lambda: str(golden))
    ast_rules.check_x_escapes(root, update_goldens=True)
    assert ast_rules.check_x_escapes(root) == []
    # a NEW escape beyond the ratchet is an error
    p = tmp_path / "homebrewnlp_tpu/models/m.py"
    p.write_text(p.read_text() + "\ndef g(t):\n    return t.x\n")
    findings = ast_rules.check_x_escapes(root)
    assert len(findings) == 1 and findings[0].severity == "error"


_HOST_SYNC_TRAIN = (
    "def train(cfg, args):\n"
    "    total = float(cfg.learning_rate)\n"      # outside the loop: free
    "    for u in range(10):\n"
    "        state, metrics = step(state, u)\n"
    "        print(float(metrics['loss']))\n"     # seeded regression
    "        s = int(state.step)\n"
    "        metrics['loss'].block_until_ready()\n"
    "    return total\n")


def test_ast_host_sync_seeded_regression_caught(tmp_path, monkeypatch):
    """ISSUE acceptance: a seeded float(loss) (plus int(step) and
    block_until_ready) inside train()'s step loop fails the host-sync
    ratchet; host code outside the loop does not count."""
    root = _mini_tree(tmp_path)
    (tmp_path / "homebrewnlp_tpu/main.py").write_text(_HOST_SYNC_TRAIN)
    golden = tmp_path / "goldens" / "ast_host_sync.json"
    golden.parent.mkdir(parents=True, exist_ok=True)
    golden.write_text("{}")
    monkeypatch.setattr(ast_rules, "host_sync_golden_path",
                        lambda: str(golden))
    assert ast_rules.host_sync_counts(root) == {"homebrewnlp_tpu/main.py": 3}
    findings = ast_rules.check_host_sync(root)
    assert len(findings) == 1 and findings[0].severity == "error"
    assert "device->host" in findings[0].message
    # deliberate syncs ratchet: re-record, then clean; removing one is info
    ast_rules.check_host_sync(root, update_goldens=True)
    assert ast_rules.check_host_sync(root) == []
    (tmp_path / "homebrewnlp_tpu/main.py").write_text(
        _HOST_SYNC_TRAIN.replace("        s = int(state.step)\n", ""))
    improved = ast_rules.check_host_sync(root)
    assert len(improved) == 1 and improved[0].severity == "info"


def test_ast_host_sync_suppression_and_scope(tmp_path, monkeypatch):
    root = _mini_tree(tmp_path)
    (tmp_path / "homebrewnlp_tpu/main.py").write_text(
        "def train(cfg, args):\n"
        "    for u in range(10):\n"
        "        s = int(u)  # graftcheck: disable=host-sync\n"
        "    return s\n"
        "def sample(cfg, args):\n"
        "    for i in range(3):\n"
        "        print(float(i))\n")  # not train(): out of scope
    golden = tmp_path / "goldens" / "ast_host_sync.json"
    golden.parent.mkdir(parents=True, exist_ok=True)
    golden.write_text("{}")
    monkeypatch.setattr(ast_rules, "host_sync_golden_path",
                        lambda: str(golden))
    assert ast_rules.host_sync_counts(root) == {}
    assert ast_rules.check_host_sync(root) == []


def test_ast_host_sync_repo_loop_is_clean():
    """The shipped async train loop carries ZERO host syncs — the ratchet
    golden pins the empty count, so any reintroduced device read fails."""
    assert ast_rules.host_sync_counts(REPO) == {}
    assert json.load(open(ast_rules.host_sync_golden_path())) == {}


def test_ast_obs_in_trace_seeded_regression_caught(tmp_path, monkeypatch):
    """ISSUE satellite: a span/registry call inside jit-traced code (models/,
    ops/) fails the obs-in-trace ratchet — every obs import style roots."""
    root = _mini_tree(tmp_path, models_src=(
        "from ..obs.spans import span\n"
        "from homebrewnlp_tpu.obs import REGISTRY as reg\n"
        "def layer(x):\n"
        "    with span('layer'):\n"                  # rooted call 1
        "        reg.counter('bad_total').inc()\n"   # 2 rooted calls:
        "    return x\n"), ops_src=(                 #  .counter() and .inc()
        "import homebrewnlp_tpu.obs.spans as spans\n"
        "def kernel(x):\n"
        "    with spans.span('k'):\n"                # rooted call
        "        return x\n"))
    golden = tmp_path / "goldens" / "ast_obs_in_trace.json"
    golden.parent.mkdir(parents=True, exist_ok=True)
    golden.write_text("{}")
    monkeypatch.setattr(ast_rules, "obs_in_trace_golden_path",
                        lambda: str(golden))
    counts = ast_rules.obs_in_trace_counts(root)
    assert counts == {"homebrewnlp_tpu/models/m.py": 3,
                      "homebrewnlp_tpu/ops/o.py": 1}, counts
    findings = ast_rules.check_obs_in_trace(root)
    assert len(findings) == 2
    assert all(f.severity == "error" for f in findings)
    assert "jit-traced" in findings[0].message
    # the ratchet can pin deliberate exceptions, then only go down
    ast_rules.check_obs_in_trace(root, update_goldens=True)
    assert ast_rules.check_obs_in_trace(root) == []


def test_ast_obs_in_trace_package_import_form(tmp_path, monkeypatch):
    """`from .. import obs` (module=None carries no 'obs' component) must
    still root: it is the most natural way to smuggle a registry call in."""
    root = _mini_tree(tmp_path, models_src=(
        "from .. import obs\n"
        "def layer(x):\n"
        "    obs.REGISTRY.counter('bad_total').inc()\n"
        "    return x\n"), ops_src=(
        "from homebrewnlp_tpu import obs as o\n"
        "def kernel(x):\n"
        "    with o.span('k'):\n"
        "        return x\n"))
    counts = ast_rules.obs_in_trace_counts(root)
    assert counts == {"homebrewnlp_tpu/models/m.py": 2,
                      "homebrewnlp_tpu/ops/o.py": 1}, counts


def test_ast_obs_in_trace_bare_dotted_import_precise(tmp_path):
    """A bare `import homebrewnlp_tpu.obs.spans` binds only the top-level
    name: calls through it count ONLY when the chain passes through obs —
    an unrelated `homebrewnlp_tpu.nd.*` call in the same file must not."""
    root = _mini_tree(tmp_path, models_src=(
        "import homebrewnlp_tpu.obs.spans\n"
        "import homebrewnlp_tpu.nd\n"
        "def layer(x):\n"
        "    homebrewnlp_tpu.nd.register_axis('rows')\n"   # NOT obs: clean
        "    with homebrewnlp_tpu.obs.spans.span('bad'):\n"  # obs: counts
        "        return x\n"))
    counts = ast_rules.obs_in_trace_counts(root)
    assert counts == {"homebrewnlp_tpu/models/m.py": 1}, counts


def test_ast_obs_in_trace_suppression_and_host_code_free(tmp_path,
                                                         monkeypatch):
    root = _mini_tree(tmp_path, models_src=(
        "from ..obs.spans import span\n"
        "def layer(x):\n"
        "    with span('ok'):  # graftcheck: disable=obs-in-trace\n"
        "        return x\n"))
    # host-layer code (data/, train/, serve/, main) is OUT of scope: the
    # same import + call in data/ must not count
    p = tmp_path / "homebrewnlp_tpu/data/feedish.py"
    p.write_text("from ..obs.spans import span\n"
                 "def feed(x):\n"
                 "    with span('feed'):\n"
                 "        return x\n")
    golden = tmp_path / "goldens" / "ast_obs_in_trace.json"
    golden.parent.mkdir(parents=True, exist_ok=True)
    golden.write_text("{}")
    monkeypatch.setattr(ast_rules, "obs_in_trace_golden_path",
                        lambda: str(golden))
    assert ast_rules.obs_in_trace_counts(root) == {}
    assert ast_rules.check_obs_in_trace(root) == []


def test_ast_obs_in_trace_repo_is_clean():
    """The shipped traced code (models/ops/infer/optim/train-step) carries
    ZERO forbidden obs calls; the committed golden pins the empty count.
    train/state.py is IN scope and imports the allowlisted device_telemetry
    — proof the allowlist admits exactly that module and nothing else."""
    assert ast_rules.obs_in_trace_counts(REPO) == {}
    assert json.load(open(ast_rules.obs_in_trace_golden_path())) == {}
    state_src = open(os.path.join(
        REPO, "homebrewnlp_tpu", "train", "state.py")).read()
    assert "device_telemetry" in state_src  # the allowlist is exercised


def test_ast_obs_in_trace_device_telemetry_allowlist(tmp_path):
    """ISSUE satellite: device_telemetry is the ONE obs module legal in
    traced code — every import style of it passes, while spans/registry use
    in the same files still fires."""
    root = _mini_tree(tmp_path, models_src=(
        "from ..obs import device_telemetry\n"
        "from ..obs.device_telemetry import collect\n"
        "import homebrewnlp_tpu.obs.device_telemetry as dt\n"
        "def layer(g):\n"
        "    ok, nf = device_telemetry.grads_finite(g)\n"   # allowed
        "    c = collect(g, g, {}, 1.0, nf, ok, None)\n"    # allowed
        "    return dt.thin(c, 0, 1)\n"), ops_src=(         # allowed
        "import homebrewnlp_tpu.obs.device_telemetry\n"
        "def kernel(g):\n"
        "    return homebrewnlp_tpu.obs.device_telemetry.grads_finite(g)\n"))
    assert ast_rules.obs_in_trace_counts(root) == {}
    # the allowlist must not leak: spans use NEXT TO a device_telemetry
    # import in the same file still counts
    root = _mini_tree(tmp_path / "mixed", models_src=(
        "from ..obs import device_telemetry\n"
        "from ..obs import spans\n"
        "def layer(g):\n"
        "    with spans.span('bad'):\n"                      # forbidden
        "        return device_telemetry.grads_finite(g)\n"))  # allowed
    counts = ast_rules.obs_in_trace_counts(root)
    assert counts == {"homebrewnlp_tpu/models/m.py": 1}, counts


def test_ast_obs_in_trace_allowlist_cannot_shield_siblings(tmp_path):
    """Review regression: a bare dotted import of the ALLOWLISTED module
    must not whitelist a sibling obs call through the same root — the
    chain filter decides per call site."""
    root = _mini_tree(tmp_path, models_src=(
        "import homebrewnlp_tpu.obs.device_telemetry\n"
        "def layer(g):\n"
        "    homebrewnlp_tpu.obs.spans.span('bad')\n"              # counts
        "    return homebrewnlp_tpu.obs.device_telemetry.thin(g, 0, 1)\n"))
    counts = ast_rules.obs_in_trace_counts(root)
    assert counts == {"homebrewnlp_tpu/models/m.py": 1}, counts


def test_ast_obs_in_trace_train_state_in_scope(tmp_path):
    """train/state.py joined the traced scope: a registry call seeded there
    fails the ratchet (the step function it builds IS traced code)."""
    root = _mini_tree(tmp_path)
    p = tmp_path / "homebrewnlp_tpu/train/state.py"
    p.write_text("from ..obs.registry import REGISTRY\n"
                 "def step_fn(s):\n"
                 "    REGISTRY.counter('bad_total').inc()\n"
                 "    return s\n")
    counts = ast_rules.obs_in_trace_counts(root)
    assert counts == {"homebrewnlp_tpu/train/state.py": 2}, counts


def test_ast_rules_clean_on_repo():
    """The committed tree carries no AST-lint errors (ratchet is current)."""
    findings = ast_rules.run_ast_rules(REPO)
    errors = [f for f in findings if f.severity == "error"]
    assert not errors, "\n".join(f.render() for f in errors)


# -- findings / CLI ---------------------------------------------------------

def test_worst_severity_ordering():
    mk = lambda s: Finding("r", s, "loc", "m")
    assert worst_severity([]) is None
    assert worst_severity([mk("info"), mk("warning")]) == "warning"
    assert worst_severity([mk("warning"), mk("error"), mk("info")]) == "error"


def test_cli_ast_only_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftcheck.py"),
         "--ast-only"], capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no findings" in proc.stdout


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftcheck.py"),
         "--list-rules"], capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0
    for rule in ("collective-census", "donation", "axis-literal"):
        assert rule in proc.stdout


@pytest.mark.slow
def test_cli_all_configs_clean():
    """The full CI gate: every bundled config audits clean in one process
    (the ISSUE acceptance bound is 120 s on CPU)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftcheck.py"),
         "--all-configs"], capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no findings" in proc.stdout


# -- bare-io ratchet (ISSUE 4) ------------------------------------------------

def test_ast_bare_io_seeded_regression_caught(tmp_path, monkeypatch):
    """ISSUE satellite: unwrapped open()/orbax calls in the train/data hot
    paths fail the bare-io ratchet (golden committed at zero)."""
    root = _mini_tree(tmp_path)
    (tmp_path / "homebrewnlp_tpu/train/ckpt.py").write_text(
        "import orbax.checkpoint as ocp\n"
        "from orbax.checkpoint import CheckpointManager as CM\n"
        "def save(self, step, tree):\n"
        "    mgr = ocp.CheckpointManager('/ckpt')\n"     # bare construction
        "    mgr2 = CM('/ckpt2')\n"                      # aliased ctor
        "    self.manager.save(step, tree)\n"            # bare save
        "    self.manager.wait_until_finished()\n"       # bare barrier
        "    with open('sidecar.json', 'w') as f:\n"     # bare open
        "        f.write('{}')\n")
    (tmp_path / "homebrewnlp_tpu/data/reader.py").write_text(
        "def read(path):\n"
        "    return open(path, 'rb').read()\n")          # bare open
    golden = tmp_path / "goldens" / "ast_bare_io.json"
    golden.parent.mkdir(parents=True, exist_ok=True)
    golden.write_text("{}")
    monkeypatch.setattr(ast_rules, "bare_io_golden_path",
                        lambda: str(golden))
    counts = ast_rules.bare_io_counts(root)
    assert counts == {"homebrewnlp_tpu/train/ckpt.py": 5,
                      "homebrewnlp_tpu/data/reader.py": 1}, counts
    findings = ast_rules.check_bare_io(root)
    assert len(findings) == 2
    assert all(f.severity == "error" for f in findings)
    assert "reliability.retry" in findings[0].message


def test_ast_bare_io_suppression_and_exemptions(tmp_path, monkeypatch):
    """Retry-wrapped sites carry the disable comment; fs.py/synthetic.py
    (the I/O layer and fixture generation) are exempt; unrelated .save()
    calls (no manager in the chain) and non-orbax constructors are clean."""
    root = _mini_tree(tmp_path)
    (tmp_path / "homebrewnlp_tpu/train/ckpt.py").write_text(
        "def save(self, step, tree):\n"
        "    self.manager.save(step, tree)  # graftcheck: disable=bare-io\n"
        "    self.writer.save(step)\n"            # not a manager chain
        "    CheckpointManager('/x')\n")          # not an orbax alias
    (tmp_path / "homebrewnlp_tpu/data/fs.py").write_text(
        "def open_stream(path, mode='rb'):\n"
        "    return open(path, mode)\n")
    (tmp_path / "homebrewnlp_tpu/data/synthetic.py").write_text(
        "def write(path):\n"
        "    open(path, 'w').write('x')\n")
    golden = tmp_path / "goldens" / "ast_bare_io.json"
    golden.parent.mkdir(parents=True, exist_ok=True)
    golden.write_text("{}")
    monkeypatch.setattr(ast_rules, "bare_io_golden_path",
                        lambda: str(golden))
    assert ast_rules.bare_io_counts(root) == {}
    assert ast_rules.check_bare_io(root) == []


def test_ast_bare_io_repo_is_clean():
    """The committed golden is ZERO and the tree satisfies it: every hot-
    path I/O call routes through reliability.retry or data/fs.py."""
    assert ast_rules.bare_io_counts(REPO) == {}
    assert json.load(open(ast_rules.bare_io_golden_path())) == {}


# -- trace_compat shims (ISSUE 7 satellite) ----------------------------------

def test_trace_compat_uninstalls_after_midcontext_raise():
    """The trace-only jax API shims must be gone after an exception inside
    the context — a half-traced config must never leave patched jax state
    behind for the rest of the process."""
    before = {name: (hasattr(obj, name), getattr(obj, name, None))
              for obj, name in ((jax, "shard_map"), (jax.lax, "pcast"),
                                (jax, "typeof"),
                                (jax.sharding, "get_abstract_mesh"))}
    with pytest.raises(RuntimeError, match="boom"):
        with atrace.trace_compat():
            # inside the context every shimmed surface exists
            assert hasattr(jax, "shard_map")
            assert hasattr(jax.lax, "pcast")
            assert hasattr(jax, "typeof")
            assert hasattr(jax.sharding, "get_abstract_mesh")
            raise RuntimeError("boom")
    for (obj, name), (had, val) in zip(
            ((jax, "shard_map"), (jax.lax, "pcast"), (jax, "typeof"),
             (jax.sharding, "get_abstract_mesh")), before.values()):
        assert hasattr(obj, name) == had, name
        if had:
            assert getattr(obj, name) is val, name


def test_collective_prims_cover_both_toolchain_spellings():
    """Census normalization: the typed-shard_map toolchain spellings and the
    legacy ones both land on one census family."""
    P = atrace.COLLECTIVE_PRIMS
    assert P["psum"] == P["psum2"] == P["psum_invariant"] == "psum"
    assert P["all_gather"] == P["all_gather_invariant"] == "all_gather"
    assert P["reduce_scatter"] == P["psum_scatter"] == "reduce_scatter"


# -- golden-coverage gate (ISSUE 7 satellite) --------------------------------

def test_golden_coverage_gate_detects_missing_and_orphans():
    import glob as _glob
    from homebrewnlp_tpu.analysis import check_golden_coverage
    names = [os.path.splitext(os.path.basename(p))[0] for p in
             _glob.glob(os.path.join(REPO, "configs", "*.json"))]
    # the committed tree is fully covered
    assert check_golden_coverage(names) == []
    # a brand-new config without goldens is an ERROR for census, resources
    # AND the spmd (implicit-collective) census
    findings = check_golden_coverage(names + ["brand_new_config"])
    errs = [f for f in findings if f.severity == "error"]
    assert len(errs) == 3 and all("brand_new_config" in f.location
                                  for f in errs)
    kinds = {("census" in f.message and "spmd" not in f.message,
              "resources" in f.message, "spmd" in f.message)
             for f in errs}
    assert kinds == {(True, False, False), (False, True, False),
                     (False, False, True)}
    # a golden whose config was deleted is an orphan warning (census +
    # resources + spmd, plus the mesh golden when the dropped config is
    # multi-device — mesh goldens exist only for tpu_size > 1)
    findings = check_golden_coverage(names[1:])
    orphans = [f for f in findings if f.severity == "warning"]
    raw = json.load(open(os.path.join(REPO, "configs",
                                      names[0] + ".json")))
    want = 4 if raw.get("tpu_size", 32) > 1 else 3
    assert len(orphans) == want and all(names[0] in f.location
                                        for f in orphans)


# -- CLI exit status (ISSUE 7 satellite) -------------------------------------

def test_cli_exit_codes_and_severity_summary(tmp_path):
    """Warnings-only runs exit 0 (1 only under --strict), error runs exit 1,
    and the findings-by-severity summary line prints in every mode."""
    cfg = dict(model_mode="gpt", use_video=False, sequence_length=16,
               features_per_head=16, heads=2, depth=1, vocab_size=64,
               train_batch_size=2, tpu_size=1,
               memory_reduction_strategy="none",
               intermediate_feed_forward_multiplier_multiplier=0.5,
               block_config=[{"layer": ["norm-shift-scale",
                                        "feed_forward-in:relu"]}])
    path = tmp_path / "tmpnew.json"
    path.write_text(json.dumps(cfg))
    base = [sys.executable, os.path.join(REPO, "tools/graftcheck.py"),
            "--config", str(path), "--graph-only"]
    # no goldens for a brand-new config -> census error -> exit 1
    proc = subprocess.run(base + ["--rules", "collective-census"],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "1 error(s)" in proc.stderr and "exit 1" in proc.stderr
    # an eval-only trace is unpinned by the golden -> warnings only -> 0
    warn = [sys.executable, os.path.join(REPO, "tools/graftcheck.py"),
            "--config", os.path.join(REPO, "configs", "bpe65k_1chip.json"),
            "--graph-only", "--steps", "eval",
            "--rules", "collective-census"]
    proc = subprocess.run(warn, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 error(s)" in proc.stderr and "exit 0" in proc.stderr
    assert "warning(s)" in proc.stderr
    # --strict promotes those warnings to a failing exit
    proc = subprocess.run(warn + ["--strict"], capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "--strict promotes warnings" in proc.stderr


# -- resource-budget through the CLI (ISSUE 7) -------------------------------

def test_cli_golden_coverage_requires_all_configs():
    """Explicitly requesting the tree-wide rule on a single config must
    refuse (exit 2), not silently skip it and report a clean pass."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftcheck.py"),
         "--config", os.path.join(REPO, "configs", "bpe65k_1chip.json"),
         "--rules", "golden-coverage"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "requires --all-configs" in proc.stderr


def test_cli_resource_budget_rule_selectable():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftcheck.py"),
         "--list-rules"], capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0
    assert "resource-budget" in proc.stdout
    assert "golden-coverage" in proc.stdout
