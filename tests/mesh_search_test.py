"""graftmesh: factorization enumeration, cost-model monotonicity, search
determinism, propagation-priced implicit collectives in the objective,
the mesh-rank ratchet, mesh-golden coverage, the degraded-resume
suggestion, and the CLI.
"""
import json
import os
import subprocess
import sys
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from homebrewnlp_tpu.analysis import cost_model, mesh_search
from homebrewnlp_tpu.analysis import trace as atrace
from homebrewnlp_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, PIPE_AXIS,
                                           SEQ_AXIS, axis_sizes,
                                           mesh_factorizations)

from .backend import tiny_config


@pytest.fixture(scope="module")
def pod_traces():
    """A tiny 8-device config (heads 4, batch 8) with its train trace —
    the anchor every in-process search test prices."""
    cfg = tiny_config(tpu_size=8, train_batch_size=8)
    traces = atrace.trace_config(cfg, "tinymesh", steps=("train",))
    assert "train" in traces.steps, traces.errors
    return cfg, traces


# -- factorization enumeration (parallel/mesh.py) ----------------------------

def test_factorizations_product_and_constraints():
    cfg = tiny_config(tpu_size=8, train_batch_size=8)  # heads=4
    factors = mesh_factorizations(cfg, 8)
    assert factors, "no factorizations of 8 devices"
    for f in factors:
        n = 1
        for v in f.values():
            n *= v
        assert n == 8, f
        assert cfg.heads % f[MODEL_AXIS] == 0
        assert cfg.train_batch_size % f[DATA_AXIS] == 0
        # default: structural axes pinned to the declared values
        assert f[SEQ_AXIS] == cfg.sequence_parallel
        assert f[PIPE_AXIS] == cfg.pipeline_parallel
    # heads=4 bounds the model axis; batch=8 admits every data size
    assert {f[MODEL_AXIS] for f in factors} == {1, 2, 4}
    # the hand-written axis_sizes mesh is always in the space
    assert axis_sizes(cfg, 8, quiet=True) in factors


def test_factorizations_free_axes_unlock_structure():
    cfg = tiny_config(tpu_size=8, train_batch_size=8)  # seq_len=16, depth=2
    seqs = {f[SEQ_AXIS] for f in mesh_factorizations(
        cfg, 8, free_axes=(SEQ_AXIS,))}
    assert seqs == {1, 2, 4, 8}  # divisors of 8 that divide seq_len 16
    pipes = {f[PIPE_AXIS] for f in mesh_factorizations(
        cfg, 8, free_axes=(PIPE_AXIS,))}
    assert pipes == {1, 2}  # pipe must divide depth=2
    with pytest.raises(ValueError, match="free_axes"):
        mesh_factorizations(cfg, 8, free_axes=("data",))


def test_factorizations_deterministic_order():
    cfg = tiny_config(tpu_size=8, train_batch_size=8)
    a = mesh_factorizations(cfg, 8, free_axes=(SEQ_AXIS, PIPE_AXIS))
    b = mesh_factorizations(cfg, 8, free_axes=(SEQ_AXIS, PIPE_AXIS))
    assert a == b and a == sorted(
        a, key=lambda s: (s[DATA_AXIS], s[SEQ_AXIS], s[PIPE_AXIS],
                          s[MODEL_AXIS]))


# -- cost-model monotonicity (ISSUE satellite) -------------------------------

def test_static_step_times_monotone_in_inputs():
    """static_step_times must be monotone in flops, HBM traffic, and
    collective bytes — the searcher's ordering is meaningless otherwise."""
    shape = {DATA_AXIS: 4, SEQ_AXIS: 1, PIPE_AXIS: 1, MODEL_AXIS: 2}
    comm = cost_model.CommModel({DATA_AXIS: 1 << 20}, {DATA_AXIS: 2})

    def t(flops=1e12, traffic=1e9, c=comm):
        out = cost_model.static_step_times(flops, traffic, c, shape, "v4")
        assert out is not None
        return out

    assert t(flops=2e12)["mxu"] > t()["mxu"]
    assert t(traffic=2e9)["hbm"] > t()["hbm"]
    fatter = cost_model.CommModel({DATA_AXIS: 1 << 22}, {DATA_AXIS: 2})
    assert t(c=fatter)["ici"] > t()["ici"]
    chattier = cost_model.CommModel({DATA_AXIS: 1 << 20}, {DATA_AXIS: 64})
    assert t(c=chattier)["ici"] > t()["ici"]
    # unknown device kinds make no bandwidth claims
    assert cost_model.static_step_times(1e12, 1e9, comm, shape, "cpu") is None


def test_implicit_dp_grad_allreduce_priced(pod_traces):
    """The hand-patched analytic DP term is gone: the SPMD propagation
    (analysis/spmd.py) now supplies the implicit gradient all-reduce —
    a pure-DP candidate prices a nonzero data-axis ici term, a pure-TP
    candidate prices none of it, and every candidate's implicit split is
    recorded in the golden (``implicit_ici_s``)."""
    cfg, traces = pod_traces
    assert not hasattr(mesh_search, "_with_implicit_grad_allreduce")
    result = mesh_search.search(cfg, "tinymesh", traces=traces,
                                device_kind="v4")
    by_model = {c.axes[MODEL_AXIS]: c for c in result.candidates}
    dp = by_model[1]  # data8
    assert dp.predicted["implicit_ici_s"] > 0
    assert dp.predicted["ici_s"] >= dp.predicted["implicit_ici_s"]
    assert all("implicit_ici_s" in c.as_golden() for c in result.candidates)
    assert all(c.spmd_error == "" for c in result.candidates)


def test_unseeded_trace_degrades_mesh_rank_loudly(pod_traces):
    """A trace whose sharding seeds are gone prices implicit collectives
    as zero — the search must carry that on every candidate and the
    mesh-rank rule must WARN instead of silently comparing under-charged
    ranks against the golden (the pure-DP-looks-free regression guard)."""
    import dataclasses
    cfg, traces = pod_traces
    bad_st = dataclasses.replace(traces.steps["train"], in_axes=None)
    bad = dataclasses.replace(traces, steps={"train": bad_st})
    result = mesh_search.search(cfg, "tinymesh", traces=bad,
                                device_kind="v4")
    assert all(c.spmd_error for c in result.candidates)
    assert all(c.predicted["implicit_ici_s"] == 0.0
               for c in result.candidates)
    findings = mesh_search.check_mesh_rank(bad)
    assert any(f.severity == "warning" and "could not be priced"
               in f.message for f in findings)


# -- the search --------------------------------------------------------------

def test_search_ranks_hand_mesh_first_and_is_deterministic(pod_traces):
    """ROADMAP acceptance shape + the determinism satellite: the committed
    axis_sizes mesh ranks at or above the searcher's own pick, and two
    searches over the same topology produce byte-identical sheets."""
    cfg, traces = pod_traces
    a = mesh_search.search(cfg, "tinymesh", traces=traces)
    b = mesh_search.search(cfg, "tinymesh", traces=traces)
    assert a.as_json() == b.as_json()
    assert len(a.candidates) == 3  # model in {1,2,4} x matching data
    assert a.hand_axes == axis_sizes(cfg, 8, quiet=True)
    assert a.hand.is_hand and a.hand_rank == a.hand.rank
    assert a.hand_rank <= a.top.rank, (a.hand_rank, a.top.rank)
    # ranked best-first, every candidate priced and gated
    steps = [c.step_s for c in a.candidates]
    assert steps == sorted(steps)
    assert all(c.predicted and c.fits for c in a.candidates)
    # deeper model sharding means fewer implicit DP grad bytes: the
    # sheet's ici must strictly decrease with the model axis
    by_model = {c.axes[MODEL_AXIS]: c.predicted["ici_s"]
                for c in a.candidates}
    assert by_model[4] < by_model[2] < by_model[1]


def test_search_scores_on_target_device(pod_traces):
    cfg, traces = pod_traces
    default = mesh_search.search(cfg, "tinymesh", traces=traces)
    assert default.device_kind == cost_model.DEFAULT_VERDICT_DEVICE
    v4 = mesh_search.search(cfg, "tinymesh", traces=traces,
                            device_kind="v4")
    assert v4.device_kind == "v4"
    with pytest.raises(ValueError, match="unknown device kind"):
        mesh_search.search(cfg, "tinymesh", traces=traces,
                           device_kind="not_a_tpu")


def test_rank_assignment_ties_and_oom_ordering():
    def cand(step_s, fits=True, peak=0):
        return mesh_search.MeshCandidate(
            axes={DATA_AXIS: 1}, predicted={"step_s": step_s},
            hbm_peak=peak, fits=fits)

    a, b, c, d = cand(1.00), cand(1.05), cand(2.0), cand(0.5, fits=False,
                                                         peak=9)
    ranked = mesh_search._assign_ranks([d, c, b, a])
    # OOM candidates rank strictly after every fitting one, however fast
    assert ranked[-1] is d and d.rank == 4
    # 1.05 is within RANK_RTOL of 1.00 -> tied at rank 1; 2.0 is not
    assert a.rank == 1 and b.rank == 1 and c.rank == 3


def test_free_axes_candidates_retrace_or_skip(pod_traces):
    """Structural candidates need the raw config dict; without it they are
    skipped loudly, with it they re-trace and join the sheet."""
    cfg, traces = pod_traces
    no_raw = mesh_search.search(cfg, "tinymesh", traces=traces,
                                free_axes=(SEQ_AXIS,))
    assert no_raw.skipped and all("raw config" in c.error
                                  for c in no_raw.skipped)
    raw = dict(model_mode="gpt", use_video=False, use_language=True,
               sequence_length=16, features_per_head=32, heads=4, depth=2,
               vocab_size=64, train_batch_size=8, tpu_size=8,
               memory_reduction_strategy="none",
               intermediate_feed_forward_multiplier_multiplier=0.5,
               block_config=[{"layer": ["norm-shift-scale",
                                        "feed_forward-in:relu"]}])
    wide = mesh_search.search(cfg, "tinymesh", traces=traces, raw=raw,
                              free_axes=(SEQ_AXIS,))
    retraced = [c for c in wide.candidates if c.retraced]
    assert retraced, "no structural candidate joined the sheet"
    assert {c.axes[SEQ_AXIS] for c in retraced} >= {2}
    assert len(wide.candidates) > len(no_raw.candidates)


# -- the mesh-rank graph rule ------------------------------------------------

def test_mesh_rank_rule_skips_single_device():
    cfg = tiny_config(tpu_size=1)
    traces = atrace.trace_config(cfg, "tiny1chip", steps=("train",))
    assert mesh_search.check_mesh_rank(traces) == []


def test_mesh_rank_rule_golden_roundtrip(pod_traces, tmp_path, monkeypatch):
    _, traces = pod_traces
    monkeypatch.setattr(mesh_search, "GOLDENS_DIR", str(tmp_path))
    # no golden yet -> error naming the update command
    fs = mesh_search.check_mesh_rank(traces)
    assert any(f.severity == "error" and "no mesh golden" in f.message
               for f in fs)
    fs = mesh_search.check_mesh_rank(traces, update_goldens=True)
    assert [f.severity for f in fs] == ["info"]
    assert mesh_search.check_mesh_rank(traces) == []
    path = mesh_search.mesh_golden_path(traces.config_name)
    golden = json.load(open(path))
    assert golden["objective"] == mesh_search.OBJECTIVE
    assert golden["hand_rank"] == 1 and golden["top_k"] == 3
    # ratchet: the golden claims the hand mesh used to rank better
    golden["hand_rank"] = 0
    json.dump(golden, open(path, "w"))
    fs = mesh_search.check_mesh_rank(traces)
    assert any(f.severity == "error" and "regressed" in f.message
               for f in fs), [f.render() for f in fs]
    # a moved top pick is a warning
    golden["hand_rank"] = 1
    golden["candidates"][0]["axes"] = {DATA_AXIS: 8, SEQ_AXIS: 1,
                                       PIPE_AXIS: 1, MODEL_AXIS: 1}
    json.dump(golden, open(path, "w"))
    fs = mesh_search.check_mesh_rank(traces)
    assert any(f.severity == "warning" and "top pick moved" in f.message
               for f in fs)
    # an improved recorded rank asks for a re-record
    mesh_search.check_mesh_rank(traces, update_goldens=True)
    golden = json.load(open(path))
    golden["hand_rank"] = 2
    json.dump(golden, open(path, "w"))
    fs = mesh_search.check_mesh_rank(traces)
    assert any(f.severity == "info" and "improved" in f.message for f in fs)


def test_mesh_rank_rule_fails_outside_top_k(pod_traces, tmp_path,
                                            monkeypatch):
    """Force the bar to 0 effective headroom by shrinking top_k via a
    doctored config twin: a hand rank above top_k is an error even with a
    fresh golden."""
    cfg, traces = pod_traces
    monkeypatch.setattr(mesh_search, "GOLDENS_DIR", str(tmp_path))
    mesh_search.check_mesh_rank(traces, update_goldens=True)
    # doctor the search result: pretend the hand mesh ranked 5th
    real_search = mesh_search.search

    def doctored(cfg_, name, **kw):
        r = real_search(cfg_, name, **kw)
        r.hand_rank = 5
        return r

    monkeypatch.setattr(mesh_search, "search", doctored)
    fs = mesh_search.check_mesh_rank(traces)
    sev = {f.severity for f in fs}
    assert "error" in sev, [f.render() for f in fs]
    assert any("mesh_search_top_k" in f.message for f in fs
               if f.severity == "error")


def test_committed_mesh_goldens_cover_multi_device_configs():
    """Every bundled multi-device config carries a mesh golden recording
    hand rank 1 — the acceptance invariant, pinned in-tree."""
    import glob
    for p in sorted(glob.glob(os.path.join(REPO, "configs", "*.json"))):
        name = os.path.splitext(os.path.basename(p))[0]
        raw = json.load(open(p))
        gp = mesh_search.mesh_golden_path(name)
        if int(raw.get("tpu_size", 32)) > 1:
            assert os.path.exists(gp), name
            golden = json.load(open(gp))
            assert golden["hand_rank"] == 1, name
            assert golden["candidates"][0]["rank"] == 1, name
        else:
            assert not os.path.exists(gp), f"orphan mesh golden: {name}"


def test_golden_coverage_requires_mesh_goldens(tmp_path, monkeypatch):
    import glob
    from homebrewnlp_tpu.analysis import check_golden_coverage
    names = [os.path.splitext(os.path.basename(p))[0] for p in
             glob.glob(os.path.join(REPO, "configs", "*.json"))]
    multi = [n for n in names if json.load(open(os.path.join(
        REPO, "configs", n + ".json"))).get("tpu_size", 32) > 1]
    assert multi
    # committed tree fully covered
    assert check_golden_coverage(names) == []
    # an empty mesh-golden dir -> one missing-mesh error per multi-device
    # config, none for the single-chip ones
    monkeypatch.setattr(mesh_search, "GOLDENS_DIR", str(tmp_path))
    findings = check_golden_coverage(names)
    mesh_errs = [f for f in findings if "mesh golden" in f.message]
    assert {f.location for f in mesh_errs} == {
        f"configs/{n}.json" for n in multi}
    # an orphan mesh golden is a warning
    os.makedirs(tmp_path / "mesh")
    (tmp_path / "mesh" / "ghost_config.json").write_text("{}")
    findings = check_golden_coverage(names)
    assert any(f.severity == "warning" and "ghost_config" in f.location
               and "mesh" in f.message for f in findings)


# -- resource-budget target_device warning (ISSUE satellite) -----------------

def test_resource_budget_warns_on_multidev_without_target(tmp_path,
                                                          monkeypatch):
    monkeypatch.setattr(cost_model, "GOLDENS_DIR", str(tmp_path))
    cfg = tiny_config(tpu_size=8)
    traces = atrace.trace_config(cfg, "tinypod", steps=("train",))
    fs = cost_model.check_resource_budget(traces, update_goldens=True)
    warn = [f for f in fs if f.severity == "warning"]
    assert warn and "target_device is empty" in warn[0].message
    # setting the knob silences it
    cfg2 = tiny_config(tpu_size=8, target_device="v5e")
    traces2 = atrace.trace_config(cfg2, "tinypod", steps=("train",))
    fs2 = cost_model.check_resource_budget(traces2, update_goldens=True)
    assert not [f for f in fs2 if f.severity == "warning"]
    # and single-device configs are exempt
    cfg1 = tiny_config(tpu_size=1)
    traces1 = atrace.trace_config(cfg1, "tiny1", steps=("train",))
    fs1 = cost_model.check_resource_budget(traces1, update_goldens=True)
    assert not [f for f in fs1 if f.severity == "warning"]


def test_mesh_search_top_k_knob_validated():
    assert tiny_config().mesh_search_top_k == 3
    assert tiny_config(mesh_search_top_k=1).mesh_search_top_k == 1
    with pytest.raises(ValueError, match="mesh_search_top_k"):
        tiny_config(mesh_search_top_k=0)


# -- degraded-resume suggestion (reliability/dist.py) ------------------------

def test_suggest_mesh_for_degraded_world(pod_traces):
    cfg, traces = pod_traces
    s = mesh_search.suggest(cfg, 4, traces=traces)
    assert s.world_size == 4
    n = 1
    for v in s.best.axes.values():
        n *= v
    assert n == 4
    assert s.fallback.axes == axis_sizes(cfg, 4, quiet=True)
    assert s.delta_frac <= 0.0  # the suggestion is never predicted slower
    assert "world_size=4" in s.describe() and "ms/step" in s.describe()


def test_dist_suggest_mesh_guards(monkeypatch, caplog):
    from homebrewnlp_tpu.reliability import dist
    cfg = tiny_config(tpu_size=8, train_batch_size=8)
    s = dist.suggest_mesh(cfg, 4)
    assert s is not None and s.world_size == 4
    # env kill-switch
    monkeypatch.setenv(dist.ENV_MESH_SUGGEST, "0")
    assert dist.suggest_mesh(cfg, 4) is None
    monkeypatch.delenv(dist.ENV_MESH_SUGGEST)
    # a world the declared structure cannot factor degrades to None with a
    # warning, never an exception (the resume must go on)
    cfg2 = tiny_config(tpu_size=8, train_batch_size=8, sequence_parallel=2)
    with caplog.at_level("WARNING"):
        assert dist.suggest_mesh(cfg2, 3) is None
    assert any("mesh search" in r.getMessage() for r in caplog.records)


def test_dist_log_mesh_suggestion(caplog):
    from homebrewnlp_tpu.reliability import dist
    cfg = tiny_config(tpu_size=8, train_batch_size=8)
    mesh = types.SimpleNamespace(size=4, shape={DATA_AXIS: 1, SEQ_AXIS: 1,
                                                PIPE_AXIS: 1, MODEL_AXIS: 4})
    with caplog.at_level("WARNING"):
        s = dist.log_mesh_suggestion(cfg, mesh)
    assert s is not None
    text = " ".join(r.getMessage() for r in caplog.records)
    assert "resuming degraded" in text and "suggest" in text
    # a data-axis fold that dropped devices out of the mesh: the searcher
    # factors the AVAILABLE world and the log names the unused devices
    caplog.clear()
    small = types.SimpleNamespace(size=4, shape={DATA_AXIS: 4, SEQ_AXIS: 1,
                                                 PIPE_AXIS: 1,
                                                 MODEL_AXIS: 1})
    with caplog.at_level("WARNING"):
        s = dist.log_mesh_suggestion(cfg, small, n_devices=8)
    assert s is not None and s.world_size == 8
    text = " ".join(r.getMessage() for r in caplog.records)
    assert "left out of the built mesh" in text


# -- supervisor wiring -------------------------------------------------------

def test_supervisor_mesh_suggestion_subprocess_stub():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import supervise

    sheet = [{"config": "x", "device": "v4", "hand_rank": 1,
              "candidates": [{"axes": {"data": 2, "model": 2},
                              "step_time_s": 0.001, "rank": 1}]}]

    def fake_run(cmd, **kw):
        assert "--world" in cmd and cmd[cmd.index("--world") + 1] == "4"
        return types.SimpleNamespace(returncode=0,
                                     stdout=json.dumps(sheet), stderr="")

    doc = supervise.mesh_suggestion("configs/x.json", 4, run=fake_run)
    assert doc == sheet[0]

    def failing_run(cmd, **kw):
        return types.SimpleNamespace(returncode=1, stdout="", stderr="boom")

    assert supervise.mesh_suggestion("configs/x.json", 4,
                                     run=failing_run) is None


def test_supervise_cli_accepts_suggest_mesh_flags():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import supervise
    args = supervise.parse_args(
        ["--model-path", "runs/x", "--suggest-mesh-config",
         "configs/8dev_composed_dryrun.json", "--devices-per-host", "4",
         "--", "true"])
    assert args.suggest_mesh_config.endswith("8dev_composed_dryrun.json")
    assert args.devices_per_host == 4


# -- CLI ---------------------------------------------------------------------

MINI_POD_CONFIG = dict(
    model_mode="gpt", use_video=False, use_language=True,
    sequence_length=32, features_per_head=16, heads=4, depth=2,
    vocab_size=64, train_batch_size=8, tpu_size=8, target_device="v5e",
    memory_reduction_strategy="none",
    intermediate_feed_forward_multiplier_multiplier=0.5,
    optimizer="adam-learning_rate",
    block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
)


def test_graftmesh_cli_check_json(tmp_path):
    cfg_path = tmp_path / "minipod.json"
    cfg_path.write_text(json.dumps(MINI_POD_CONFIG))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftmesh.py"),
         "--config", str(cfg_path), "--check", "--json",
         "--emit", str(tmp_path / "out")],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)[0]
    assert doc["device"] == "v5e" and doc["hand_rank"] == 1
    assert doc["objective"] == mesh_search.OBJECTIVE
    assert len(doc["candidates"]) == 3
    # --emit wrote the ranked sheet + the winner's golden-style files
    emitted = sorted(os.listdir(tmp_path / "out"))
    assert emitted == ["minipod_census.json", "minipod_mesh.json",
                       "minipod_resources.json"]
    win = json.load(open(tmp_path / "out" / "minipod_resources.json"))
    assert win["mesh"] == doc["candidates"][0]["axes"]
    assert win["steps"]["train"]["hbm"]["peak"] > 0


def test_graftmesh_cli_rejects_unknown_free_axes():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftmesh.py"),
         "--config", os.path.join(REPO, "configs",
                                  "8dev_composed_dryrun.json"),
         "--free-axes", "bogus"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 2, (proc.stdout, proc.stderr)
    assert "unknown --free-axes" in proc.stderr


@pytest.mark.slow
def test_graftmesh_cli_composed_acceptance():
    """THE acceptance bar: the committed composed dryrun's hand-written
    mesh ranks at or above the searcher's own top pick, in one CLI run
    (CI wraps the same command in `timeout 60`)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftmesh.py"),
         "--config", os.path.join(REPO, "configs",
                                  "8dev_composed_dryrun.json"),
         "--check", "--strict-check", "--json"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)[0]
    assert doc["hand_rank"] == 1
    assert doc["hand_mesh"] == {"data": 1, "model": 2, "pipeline": 2,
                                "sequence_parallel": 2}
