"""Training child for the fleet-lockstep chaos drill (elastic_test.py and
the CI ``chaos-multihost`` job).

Runs as ``python tests/elastic_child.py --model-path DIR --steps N
[--fault-plan PLAN]``: a tiny synthetic-data training under checkpointing,
exactly what ``tools/supervise.py`` launches per host.  A
``peer:die@stepK`` plan makes the child observe a (simulated) peer death at
global step K — checkpoint cut, exit ``EXIT_PEER_LOST`` (87) — and the
resumed relaunch disarms the rule behind its restore point, so the fleet
generation after the lockstep relaunch completes with a loss sequence
bit-identical to an uninterrupted run."""
import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model-path", required=True)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--fault-plan", default="")
    p.add_argument("--obs-spans", action="store_true",
                   help="record host spans (the fleet-obs drill merges the "
                        "per-rank traces; fleet postings themselves key off "
                        "the supervisor-injected HBNLP_FLEET_DIR)")
    args = p.parse_args()
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tests.backend import tiny_config
    from homebrewnlp_tpu import main as cli
    # compilation_cache_dir="": fresh-process checkpoint resume can
    # segfault on some jax builds when deserializing a persistently-cached
    # executable (docs/reliability.md "Troubleshooting") — the drill tests
    # the fleet protocol, not the XLA cache
    cfg = tiny_config(model_path=args.model_path, use_checkpointing=True,
                      steps_per_checkpoint=2, fault_plan=args.fault_plan,
                      grace_deadline_s=60.0, compilation_cache_dir="",
                      obs_spans=args.obs_spans)
    cli.train(cfg, argparse.Namespace(steps=args.steps, profile="",
                                      workers=None))


if __name__ == "__main__":
    main()
