"""REAL multi-process SPMD: two OS processes form a jax.distributed cluster
(gloo collectives between them) and run the framework's sharded train step on
a mesh spanning both — the strongest local stand-in for multi-host TPU
(SURVEY.md §2.12 comm-backend row; round-1 VERDICT called multi-host feeding
unexercised)."""
import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_train_step():
    port = _free_port()
    worker = os.path.join(HERE, "multiprocess_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    p0 = subprocess.Popen([sys.executable, worker, "0", str(port)],
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, env=env)
    p1 = subprocess.Popen([sys.executable, worker, "1", str(port)],
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, env=env)
    try:
        try:
            out0, _ = p0.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            # rank0 hung — usually because rank1 died and left it blocked in
            # a collective; surface rank1's traceback instead of a bare
            # timeout
            p1.kill()
            out1 = p1.communicate()[0] if p1.stdout else ""
            p0.kill()
            raise AssertionError(f"rank0 timed out; rank1 output:\n"
                                 f"{out1[-2000:]}")
        if p0.returncode != 0:
            # a dead rank leaves the peer blocked in a collective — kill it
            # so the failure surfaces rank0's traceback, not a timeout
            p1.kill()
            raise AssertionError(out0[-2000:])
        out1, _ = p1.communicate(timeout=60)
        assert p1.returncode == 0, out1[-2000:]
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
    assert "MULTIPROC_OK" in out0 and "MULTIPROC_OK" in out1
    # both processes observed the SAME global loss sequences for every case
    for case in ("dp_tp", "dp_sp_tp"):
        line0 = [l for l in out0.splitlines() if f" {case} " in l][0]
        line1 = [l for l in out1.splitlines() if f" {case} " in l][0]
        assert line0.split("rank0: ")[1] == line1.split("rank1: ")[1]


def test_four_process_pipeline_and_checkpoint(tmp_path):
    """4 OS processes x 2 devices: the pipe axis spans process boundaries
    (GPipe and 1F1B activation hops + gradient transposes over gloo), and
    orbax save/restore works under jax.distributed with per-process data
    cursors (VERDICT r3 item 7)."""
    port = _free_port()
    worker = os.path.join(HERE, "multiprocess_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    ckpt_dir = str(tmp_path / "mp_ckpt")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(r), str(port), "4", ckpt_dir],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for r in range(4)]
    outs = [""] * 4
    try:
        try:
            outs[0], _ = procs[0].communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for p in procs[1:]:
                p.kill()
            tails = "\n---\n".join(
                (p.communicate()[0] or "")[-1200:] for p in procs[1:])
            procs[0].kill()
            raise AssertionError(f"rank0 timed out; peers:\n{tails}")
        if procs[0].returncode != 0:
            for p in procs[1:]:
                p.kill()
            raise AssertionError(outs[0][-3000:])
        for r in (1, 2, 3):
            outs[r], _ = procs[r].communicate(timeout=120)
            assert procs[r].returncode == 0, outs[r][-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r in range(4):
        assert "MULTIPROC_OK" in outs[r], outs[r][-2000:]
        assert f"ckpt restored step=5 cursor={1000 + r}" in outs[r]
    # every rank observed the SAME global loss sequence for each schedule
    # case — incl. seq x pipe composed across process boundaries
    for case in ("dp_pp", "dp_pp_1f1b", "sp_pp_1f1b"):
        lines = [[l for l in outs[r].splitlines() if f" {case} " in l][0]
                 for r in range(4)]
        payloads = {l.split(": ", 1)[1] for l in lines}
        assert len(payloads) == 1, (case, lines)
    # every rank observed the SAME global loss sequence for each case
    for case in ("dp_pp", "dp_pp_1f1b", "dp_tp_ckpt"):
        lines = [[l for l in outs[r].splitlines() if f" {case} " in l][0]
                 for r in range(4)]
        vals = {l.split(": ", 1)[1].split("losses=")[1] for l in lines}
        assert len(vals) == 1, (case, lines)
