"""Optimizer layer tests: DSL transforms, schedules, decay heuristic,
multi-loss strategies, end-to-end training with the reference's 32big_mixer
optimizer chain."""
import jax
import jax.numpy as jnp
import pytest

from homebrewnlp_tpu.optim import Optimizer, is_large_tensor, learning_rate_fn
from homebrewnlp_tpu.optim.multiloss import mgda_gamma, pcgrad
from homebrewnlp_tpu.optim.transforms import (VarCtx, apply_chain,
                                              chain_slot_shapes)

from .backend import init_and_loss, mixer_config, tiny_config


def _ctx(grad, value=None, lr=0.1, step=1.0):
    return VarCtx(grad=jnp.asarray(grad, jnp.float32),
                  value=jnp.asarray(value if value is not None else grad,
                                    jnp.float32),
                  lr=jnp.float32(lr), beta1=0.9, beta2=0.999,
                  step_count=jnp.float32(step), global_norm_reciprocal=None)


def _slots(spec, shape):
    return {k: jnp.zeros(s, jnp.float32)
            for k, s in chain_slot_shapes(spec, shape).items()}


def test_adam_first_step_is_sign():
    """With debiasing, adam's first update is ~sign(g) (|g|/sqrt(g^2))."""
    g = jnp.array([0.5, -2.0, 1e-3])
    out, _ = apply_chain("adam", _ctx(g), _slots("adam", (3,)))
    assert jnp.allclose(out, jnp.sign(g), atol=1e-3), out


def test_sm3_slot_shapes_and_accumulator():
    shapes = chain_slot_shapes("sm3", (4, 6))
    assert shapes == {"0/sm3/dim0": (4,), "0/sm3/dim1": (6,)}
    g = jax.random.normal(jax.random.key(0), (4, 6))
    out, slots = apply_chain("sm3", _ctx(g), _slots("sm3", (4, 6)))
    # first step: accumulator == g^2, so update == g / max(|g|, 1e-5) == sign
    assert jnp.allclose(out, jnp.sign(g), atol=1e-4)
    assert jnp.allclose(slots["0/sm3/dim0"], jnp.max(g * g, axis=1))
    assert jnp.allclose(slots["0/sm3/dim1"], jnp.max(g * g, axis=0))


def test_sm3_min_of_maxes_second_step():
    g1 = jnp.ones((3, 3))
    g2 = jnp.full((3, 3), 2.0)
    c = _ctx(g1)
    _, slots = apply_chain("sm3", c, _slots("sm3", (3, 3)))
    out, _ = apply_chain("sm3", _ctx(g2, step=2.0), slots)
    # accumulator = min(dim0,dim1) + g2^2 = 1 + 4 = 5
    assert jnp.allclose(out, 2.0 / jnp.sqrt(5.0), atol=1e-5)


def test_novograd_scalar_second_moment():
    shapes = chain_slot_shapes("novograd", (8,))
    assert shapes["0/novograd/exp_avg_p2"] == ()
    g = jax.random.normal(jax.random.key(1), (8,))
    out, slots = apply_chain("novograd", _ctx(g), _slots("novograd", (8,)))
    assert out.shape == (8,)
    assert jnp.isfinite(out).all()


def test_adaptive_clip_bounds_update_norm():
    """AGC: ||out|| <= clip * ||w|| and out == g when g is already small."""
    w = jnp.full((10,), 1.0)
    g_big = jnp.full((10,), 100.0)
    out, _ = apply_chain("adaptive_clip:0.01", _ctx(g_big, w), {})
    gnorm = float(jnp.linalg.norm(out))
    wnorm = float(jnp.linalg.norm(w))
    assert gnorm <= 0.01 * wnorm * 1.01
    g_small = jnp.full((10,), 1e-5)
    out2, _ = apply_chain("adaptive_clip:0.01", _ctx(g_small, w), {})
    assert jnp.allclose(out2, g_small)


def test_l2norm_and_value_clip():
    g = jnp.array([3.0, 4.0])  # norm 5
    out, _ = apply_chain("l2norm_clip:1.0", _ctx(g), {})
    assert jnp.allclose(jnp.linalg.norm(out), 1.0, atol=1e-5)
    out, _ = apply_chain("value_clip:0.5", _ctx(g), {})
    assert jnp.allclose(out, jnp.array([0.5, 0.5]))


def test_graft_norm_property():
    """graft:adam carries adam's magnitude along g's direction."""
    g = jax.random.normal(jax.random.key(2), (16,))
    spec = "graft:adam"
    out, _ = apply_chain(spec, _ctx(g), _slots(spec, (16,)))
    adam_out, _ = apply_chain("adam", _ctx(g), _slots("adam", (16,)))
    assert jnp.allclose(jnp.linalg.norm(out), jnp.linalg.norm(adam_out), rtol=1e-4)
    cos = jnp.sum(out * g) / (jnp.linalg.norm(out) * jnp.linalg.norm(g))
    assert cos > 0.999


def test_momentum_nesterov():
    g = jnp.ones((4,))
    out, slots = apply_chain("momentum:0.9:1:0", _ctx(g),
                             _slots("momentum:0.9:1:0", (4,)))
    assert jnp.allclose(out, g)  # state = 0.9*0 + g
    out2, _ = apply_chain("momentum:0.9:1:1", _ctx(g),
                          _slots("momentum:0.9:1:1", (4,)))
    assert jnp.allclose(out2, g + 0.9 * g)  # nesterov: g + mul*state


def test_centralisation():
    g = jnp.array([1.0, 2.0, 3.0])
    out, _ = apply_chain("gradient_centralisation", _ctx(g), {})
    assert abs(float(jnp.mean(out))) < 1e-6


def test_schedule_composition():
    cfg = tiny_config(learning_rate=1.0, learning_rate_config={
        "linear_warmup": {"final_step": 100},
        "linear_decay": {"start_step": 100, "final_step": 200},
        "lower_bound": {"factor": 0.1},
    })
    assert abs(float(learning_rate_fn(cfg, jnp.int32(50))) - 0.5) < 1e-6
    assert abs(float(learning_rate_fn(cfg, jnp.int32(100))) - 1.0) < 1e-6
    assert abs(float(learning_rate_fn(cfg, jnp.int32(150))) - 0.5) < 1e-6
    assert abs(float(learning_rate_fn(cfg, jnp.int32(300))) - 0.1) < 1e-6


def test_weight_decay_heuristic():
    cfg = tiny_config()
    feat = ("heads", "features_per_head")
    # body linear: features + extra dim -> large
    assert is_large_tensor("gpt/body/@d0_0/feed_forward_/orthogonal_var",
                           ("intermediate",) + feat, 4096, cfg)
    # norm scale: not large
    assert not is_large_tensor("gpt/body/@d0_0/norm_/scale", feat, 128, cfg)
    # rezero scalar: not large
    assert not is_large_tensor("gpt/body/@d0_0/rezero_var", (), 1, cfg)
    # embedding: not large
    assert not is_large_tensor("gpt/input/embed/embed_var",
                               ("vocab", "intermediate"), 8192, cfg)


def test_pcgrad_removes_conflict():
    g1 = {"body/w": jnp.array([1.0, 0.0])}
    g2 = {"body/w": jnp.array([-1.0, 1.0])}
    out = pcgrad([g1, g2])["body/w"]
    # combined gradient should not point against either loss gradient
    assert float(jnp.dot(out, g2["body/w"])) >= -1e-5


def test_mgda_gamma_bounds():
    g1 = {"body/w": jnp.array([1.0, 0.0])}
    g2 = {"body/w": jnp.array([0.0, 1.0])}
    gamma = float(mgda_gamma([g1, g2]))
    assert 0.0 <= gamma <= 1.0
    assert abs(gamma - 0.5) < 1e-5  # symmetric case


@pytest.mark.parametrize("spec", [
    "adam-learning_rate",
    "adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",  # 32big_mixer
    # novograd's zero-initialized scalar second moment makes its first steps
    # huge (opt_rsqrt(0)=1e5, faithful to the reference formula), so bound it
    # with a post-chain clip like the reference configs do with AGC.
    "global_l2norm_clip:1.0-novograd-l2norm_clip:1.0-learning_rate",
    "graft:adam-momentum:0.9:1:0-learning_rate",
])
def test_end_to_end_training_decreases_loss(spec):
    cfg = mixer_config(depth=1, optimizer=spec, learning_rate=3e-3,
                       weight_decay=0.001)
    params, axes, batch, loss_fn = init_and_loss(cfg)
    opt = Optimizer(cfg, axes)
    state = opt.init(params)

    @jax.jit
    def step(p, s, i):
        loss, g = jax.value_and_grad(loss_fn)(p, jax.random.key(0))
        new_p, new_s, lr = opt.update(p, g, s, i)
        return loss, new_p, new_s

    first = None
    loss = None
    for i in range(15):
        loss, params, state = step(params, state, jnp.int32(i))
        if first is None:
            first = float(loss)
    assert float(loss) < first, (spec, first, float(loss))


def test_optimizer_state_dtype_policy():
    cfg = mixer_config(depth=1, optimizer="adam-learning_rate",
                       optimizer_slice_dtype="bfloat16")
    params, axes, batch, loss_fn = init_and_loss(cfg)
    opt = Optimizer(cfg, axes)
    state = opt.init(params)
    for slots in state.values():
        for v in slots.values():
            assert v.dtype == jnp.bfloat16
