"""Shutdown-ordering coverage under the HBNLP_SYNC_RECORD shim (ISSUE 16
satellite): engine close, exporter teardown, feeder close and supervisor
SIGTERM each run with every declared lock wrapped in the recording proxy,
and must produce (a) no held-while-joining event — joining a thread while
holding a lock it may need is the classic shutdown deadlock — and (b) no
lock-order edge outside the static graph pinned in
``analysis/goldens/sync/lock_order.json``."""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from backend import mixer_config  # noqa: E402

from homebrewnlp_tpu import sync  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def static_edges():
    from homebrewnlp_tpu.analysis import concurrency as cc
    model = cc.build_model(REPO)
    return ({f"{a} -> {b}" for a, b in model.edges}, set(model.locks))


@pytest.fixture
def recorder():
    """Arm the recording shim for locks created inside the test; always
    disarm (and unpatch ``Thread.join``) afterwards."""
    sync.set_recording(True)
    sync.reset()
    try:
        yield sync
    finally:
        sync.set_recording(False)
        sync.reset()


def _assert_clean(snap, static_edges):
    static, known = static_edges
    assert snap["joins"] == [], (
        f"Thread.join with declared lock(s) held during shutdown: "
        f"{snap['joins']}")
    for src, dst in snap["edges"]:
        assert src in known and dst in known, (src, dst)
        assert f"{src} -> {dst}" in static, (
            f"recorded lock-order edge {src} -> {dst} missing from the "
            f"static graph — run `python tools/graftsync.py` and extend "
            f"the analyzer (never the golden) if the order is intended")


def test_engine_close_clean_shutdown(recorder, static_edges):
    """close() must notify the scheduler out of its wait and join it with
    no declared lock held; the admit path's nested _cv -> allocator
    acquisition must match the pinned order."""
    from homebrewnlp_tpu.models import init_params
    from homebrewnlp_tpu.serve.engine import BatchEngine
    from homebrewnlp_tpu.utils import random_text_batch
    cfg = mixer_config(depth=1, sequence_length=12, heads=2,
                       features_per_head=16, vocab_size=32,
                       train_batch_size=1, sampling_temperature=0.0,
                       use_autoregressive_sampling=True, serve_max_batch=2)
    params, _ = init_params(cfg, random_text_batch(cfg))
    eng = BatchEngine(cfg, params)
    out = eng.complete_tokens([1, 2, 3], 0.0, 4)
    assert len(out) >= 1
    eng.close()
    _assert_clean(recorder.snapshot(), static_edges)


def test_feeder_close_clean_shutdown(recorder, static_edges, tmp_path,
                                     eight_devices):
    from homebrewnlp_tpu.data import GptPipeline, write_text_tfrecords
    from homebrewnlp_tpu.data.feed import DeviceFeeder
    from homebrewnlp_tpu.parallel import make_mesh
    cfg = mixer_config(interleaved_datasets=1)
    paths = write_text_tfrecords(str(tmp_path), 2, 2, 100, seed=7)
    mesh = make_mesh(cfg)
    feeder = DeviceFeeder(iter(GptPipeline(cfg, 2, paths=paths)), cfg, mesh,
                          depth=2)
    next(feeder)
    feeder.close()  # joins the producer: must hold nothing while waiting
    _assert_clean(recorder.snapshot(), static_edges)


def test_exporter_teardown_clean_shutdown(recorder, static_edges, tmp_path):
    """stop_server joins the serving thread and Watchdog.stop joins the
    poller — both while the freshly recorded Health/registry locks are
    live."""
    import socket

    from homebrewnlp_tpu.obs import (Health, MetricsRegistry, Watchdog,
                                     start_server, stop_server)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    reg = MetricsRegistry()
    health = Health()
    health.step_completed(1)
    server = start_server(port, registry=reg, health=health)
    wd = Watchdog(health, str(tmp_path), poll_s=0.02)
    wd.start()
    time.sleep(0.1)
    wd.stop()
    stop_server(server)
    _assert_clean(recorder.snapshot(), static_edges)


def test_supervisor_sigterm_clean_shutdown(recorder, static_edges):
    """The fleet watcher's terminate() crosses threads into the launcher:
    the Popen-handle lock must be released before any signalling/waiting,
    and the launch thread join happens lock-free."""
    from tools.supervise import SubprocessLauncher
    launcher = SubprocessLauncher(
        [sys.executable, "-c", "import time; time.sleep(30)"])
    rc = []
    t = threading.Thread(target=lambda: rc.append(launcher()))
    t.start()
    deadline = time.time() + 10.0
    while time.time() < deadline and not launcher.terminate():
        time.sleep(0.02)
    t.join(timeout=15.0)
    assert not t.is_alive()
    assert rc and rc[0] == -signal.SIGTERM
    _assert_clean(recorder.snapshot(), static_edges)


def test_record_file_dump_round_trip(recorder, tmp_path):
    """The subprocess contract graftsync --validate relies on: events dump
    as appendable JSONL and load back losslessly."""
    a = recorder.make_lock("x.A._lock")
    b = recorder.make_lock("x.B._lock")
    with a:
        with b:
            pass
    path = str(tmp_path / "rec.jsonl")
    recorder.dump(path)
    recorder.dump(path)  # append-mode: a second process would land too
    recs = sync.load_records(path)
    assert {"kind": "edge", "src": "x.A._lock", "dst": "x.B._lock"} in recs
    assert len([r for r in recs if r["kind"] == "edge"]) == 2
