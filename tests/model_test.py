"""End-to-end model tests: forward, gradients, memory-reduction strategy
parity, shared-weight identity.  Covers what the reference never tested
(SURVEY.md §4: no train-step tests exist upstream)."""
import jax
import jax.numpy as jnp
import pytest

from homebrewnlp_tpu.models import build, init_params
from homebrewnlp_tpu.models.ctx import Ctx

from .backend import init_and_loss, mixer_config, text_batch, tiny_config


def test_forward_loss_reasonable():
    cfg = mixer_config()
    params, axes, batch, loss_fn = init_and_loss(cfg)
    loss = jax.jit(loss_fn)(params, jax.random.key(0))
    # z-loss regularized CE near ln(vocab) at init
    assert 2.0 < float(loss) < 6.0


@pytest.mark.parametrize("strategy", ["none", "checkpoint", "revnet", "momentum"])
def test_memory_strategies_train(strategy):
    cfg = mixer_config(memory_reduction_strategy=strategy)
    params, axes, batch, loss_fn = init_and_loss(cfg)
    g = jax.jit(jax.grad(loss_fn))(params, jax.random.key(0))
    for k, v in g.items():
        assert jnp.all(jnp.isfinite(v.astype(jnp.float32))), k
    total = sum(float(jnp.sum(jnp.abs(v.astype(jnp.float32)))) for v in g.values())
    assert total > 0


def test_revnet_grads_match_numeric():
    """Reversible custom_vjp backward (input reconstruction) must agree with
    a numeric directional derivative of the same loss."""
    cfg_rev = mixer_config(memory_reduction_strategy="revnet")
    p_rev, _, batch, loss_rev = init_and_loss(cfg_rev)
    g_rev = jax.jit(jax.grad(loss_rev))(p_rev, jax.random.key(0))
    key = jax.random.key(42)
    vec = {k: jax.random.normal(jax.random.fold_in(key, i), v.shape, jnp.float32)
           for i, (k, v) in enumerate(sorted(p_rev.items()))}
    eps = 1e-3

    def lf(p):
        return loss_rev(p, jax.random.key(0))

    lp = float(jax.jit(lf)({k: v + eps * vec[k] for k, v in p_rev.items()}))
    lm = float(jax.jit(lf)({k: v - eps * vec[k] for k, v in p_rev.items()}))
    numeric = (lp - lm) / (2 * eps)
    analytic = sum(float(jnp.sum(g_rev[k].astype(jnp.float32) * vec[k]))
                   for k in vec)
    assert abs(numeric - analytic) < 5e-2 * max(1.0, abs(numeric)), \
        (numeric, analytic)


def test_shared_weights_identity():
    """'shared' DSL flag: depth iterations reuse one tensor per call slot."""
    cfg = mixer_config(depth=3)
    batch = text_batch(cfg)
    params, axes = init_params(cfg, batch)
    shared = [k for k in params if "/shared_" in k]
    # two shared attention bias maps (one per call slot in block config 1)
    assert len(shared) == 2, shared
    # no per-depth copies of the attention embedding exist
    assert not any("attention" in k and "@d" in k and "embed" in k for k in params)


def test_sgd_loss_decreases():
    cfg = mixer_config(depth=1)
    params, axes, batch, loss_fn = init_and_loss(cfg)

    @jax.jit
    def step(p, rng):
        l, g = jax.value_and_grad(loss_fn)(p, rng)
        return l, {k: v - 0.03 * g[k].astype(v.dtype) for k, v in p.items()}

    rng = jax.random.key(0)
    first = None
    loss = None
    for i in range(20):
        loss, params = step(params, jax.random.fold_in(rng, i))
        if first is None:
            first = float(loss)
    assert float(loss) < first, (first, float(loss))


def test_relative_embedding_finite_large_features():
    """Regression: the reference's relative-embedding formula overflows f32
    for feature counts > ~89 (exp of the raw flat feature index); our
    geometric-frequency form must stay finite at any width."""
    import numpy as np
    from homebrewnlp_tpu.models.ctx import Args
    from homebrewnlp_tpu.models.embedding import relative_embedding
    cfg = mixer_config(heads=8, features_per_head=64)  # 512 features
    ctx = Ctx(cfg, params={})
    args = Args(ctx, None, ["relative"])
    out = relative_embedding(
        args, [("sequence", 128)], [("heads", 8), ("features_per_head", 64)],
        [("sequence", 128), ("heads", 8), ("features_per_head", 64)])
    x = np.asarray(out.x, np.float32)
    assert np.isfinite(x).all()
    assert 0 < np.abs(x).max() <= cfg.embedding_stddev + 1e-6


def test_dtype_policy_bf16():
    """Device-resident params live in slice_dtype (MTF's per-device slice
    copy); storage_dtype only affects the checkpoint master (see
    test_checkpoint_master_dtype_roundtrip)."""
    cfg = mixer_config(calculation_dtype="bfloat16", storage_dtype="bfloat16",
                       slice_dtype="float32")
    params, axes, batch, loss_fn = init_and_loss(cfg)
    assert all(v.dtype == jnp.float32 for v in params.values())
    loss = jax.jit(loss_fn)(params, jax.random.key(0))
    assert jnp.isfinite(loss)
    assert loss.dtype == jnp.float32  # losses accumulate in f32

    cfg2 = mixer_config(calculation_dtype="bfloat16",
                        storage_dtype="bfloat16", slice_dtype="bfloat16")
    params2, _, _, loss_fn2 = init_and_loss(cfg2)
    assert all(v.dtype == jnp.bfloat16 for v in params2.values())
    assert jnp.isfinite(jax.jit(loss_fn2)(params2, jax.random.key(0)))


def test_einsum_f32_accumulation():
    """bf16 einsum must accumulate in f32 (preferred_element_type) and cast
    back — output dtype bf16, but dot_general runs with an f32 accumulator."""
    from homebrewnlp_tpu import nd
    from homebrewnlp_tpu.nd import NT

    a = NT(jnp.ones((4, 8), jnp.bfloat16), ("row", "inner"))
    b = NT(jnp.ones((8, 3), jnp.bfloat16), ("inner", "col"))

    out = nd.einsum([a, b], ("row", "col"))
    assert out.dtype == jnp.bfloat16  # storage stays half-precision

    jaxpr = jax.make_jaxpr(
        lambda x, y: nd.einsum([NT(x, a.names), NT(y, b.names)],
                               ("row", "col")).x)(a.x, b.x)
    dots = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "dot_general"]
    assert dots, "einsum should lower to dot_general"
    for e in dots:
        assert e.params["preferred_element_type"] == jnp.float32

    # f32 inputs keep an f32 accumulator and f32 output
    af = NT(jnp.ones((4, 8), jnp.float32), ("row", "inner"))
    bf = NT(jnp.ones((8, 3), jnp.float32), ("inner", "col"))
    assert nd.einsum([af, bf], ("row", "col")).dtype == jnp.float32


def test_pallas_causal_map_attention_parity():
    """Interpret-mode parity of the (measured-and-rejected) pallas mixer
    kernel against the production masked einsum (docs/perf/README.md)."""
    import numpy as np

    from homebrewnlp_tpu.ops.pallas_attn import (_fwd_einsum, _fwd_pallas,
                                                 causal_map_attention)
    k1, k2 = jax.random.split(jax.random.key(0))
    bias = jax.random.normal(k1, (2, 256, 256), jnp.float32)
    val = jax.random.normal(k2, (2, 256, 2, 128), jnp.float32)
    a = np.asarray(_fwd_einsum(bias, val))
    b = np.asarray(_fwd_pallas(bias, val, interpret=True))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)

    # custom_vjp grads match autodiff through the einsum form
    def loss_k(bias, val):
        return jnp.sum(jnp.square(causal_map_attention(bias, val, False)))

    def loss_e(bias, val):
        return jnp.sum(jnp.square(_fwd_einsum(bias, val)))

    ga = jax.grad(loss_k, argnums=(0, 1))(bias, val)
    ge = jax.grad(loss_e, argnums=(0, 1))(bias, val)
    for x, y in zip(ga, ge):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-4, atol=2e-4)


def test_pallas_tri_map_attention_parity():
    """Interpret-mode parity of the (measured-and-rejected) large-S
    triangular map-attention kernels — fwd AND both backward kernels —
    against the masked einsum (docs/perf/README.md round 5c)."""
    import numpy as np

    from homebrewnlp_tpu.ops.pallas_tri_attn import (tri_map_attention,
                                                     tri_reference)
    k1, k2 = jax.random.split(jax.random.key(0))
    # S=512 -> 2 row tiles (the fori + diagonal paths both execute);
    # K=256 -> the key axis splits into 2 half-panels
    bias = jax.random.normal(k1, (2, 512, 512), jnp.float32) * 0.02
    val = jax.random.normal(k2, (2, 512, 2, 256), jnp.float32)
    with jax.default_matmul_precision("highest"):
        a = np.asarray(tri_reference(bias, val))
        b = np.asarray(tri_map_attention(bias, val, True))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
        gr = jax.grad(lambda t: jnp.sum(tri_reference(*t) ** 2))((bias, val))
        gf = jax.grad(
            lambda t: jnp.sum(tri_map_attention(*t, True) ** 2))((bias, val))
    for name, x, y in zip(("dbias", "dval"), gr, gf):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


def test_blocked_causal_map_matches_masked_einsum():
    """models/layers.py::_blocked_map_rows: the block decomposition of the
    causal triangle must reproduce the masked einsum inside the REAL model
    (identical params — the embed scope walk is unchanged) and at the
    helper level for every depth, including depths past the 256-row leaf
    cutoff."""
    import numpy as np

    from homebrewnlp_tpu.models.layers import _blocked_map_rows
    k1, k2 = jax.random.split(jax.random.key(1))
    bias = jax.random.normal(k1, (2, 512, 512), jnp.float32) * 0.02
    val = jax.random.normal(k2, (2, 512, 2, 64), jnp.float32)
    row = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (512, 512), 1)
    ref = jnp.einsum("hst,bthk->bshk", bias * (row >= col), val,
                     preferred_element_type=jnp.float32)
    with jax.default_matmul_precision("highest"):
        for depth in (0, 1, 2, 5):
            out = _blocked_map_rows(bias, val, depth)
            np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                       rtol=1e-5, atol=1e-5,
                                       err_msg=f"depth {depth}")

    # model level: same params, same loss/grads
    dt = dict(calculation_dtype="float32", storage_dtype="float32",
              slice_dtype="float32", optimizer_slice_dtype="float32")
    shape = dict(sequence_length=512, features_per_head=64, heads=2,
                 depth=2, train_batch_size=2,
                 memory_reduction_strategy="none")
    cfg0 = mixer_config(**shape, **dt)
    cfg1 = mixer_config(**shape, **dt, blocked_causal_map=3)
    p0, _, _, l0 = init_and_loss(cfg0)
    p1, _, _, l1 = init_and_loss(cfg1)
    assert set(p0) == set(p1)
    with jax.default_matmul_precision("highest"):
        a = float(jax.jit(l0)(p0, jax.random.key(0)))
        b = float(jax.jit(l1)(p0, jax.random.key(0)))
        assert abs(a - b) < 1e-5 * max(1.0, abs(a)), (a, b)
        g0 = jax.jit(jax.grad(l0))(p0, jax.random.key(0))
        g1 = jax.jit(jax.grad(l1))(p0, jax.random.key(0))
    for k in g0:
        x = np.asarray(g0[k], np.float32)
        y = np.asarray(g1[k], np.float32)
        scale = max(1e-3, float(np.abs(x).max()))
        assert np.abs(x - y).max() < 1e-4 * scale, (
            k, float(np.abs(x - y).max()))


def test_blocked_causal_map_composes_with_sharding(eight_devices):
    """blocked_causal_map on a data x model mesh: the decomposition slices
    only the (unsharded) sequence axis, so GSPMD composition must hold."""
    import numpy as np

    from homebrewnlp_tpu.parallel import make_mesh
    from homebrewnlp_tpu.train import Trainer
    cfg = mixer_config(sequence_length=512, features_per_head=64, heads=2,
                       depth=2, train_batch_size=8, tpu_size=8,
                       blocked_causal_map=3)
    mesh = make_mesh(cfg)
    assert mesh.size == 8
    trainer = Trainer(cfg, mesh)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    state, m = trainer.step(state, batch, jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


def test_reversible_cotangent_dtype_is_noop_under_bf16():
    import numpy as np
    """Round-4 measured finding pinned as a test: under bf16 calculation
    dtype the inter-block cotangent streams are already bf16, so the
    reversible_cotangent_dtype barrier must be a numeric NO-OP (bit-identical
    grads).  If this ever fails, the backward started carrying f32 streams
    and the barrier became a real lever again (docs/perf/README.md)."""
    base = dict(memory_reduction_strategy="revnet",
                calculation_dtype="bfloat16", storage_dtype="bfloat16",
                slice_dtype="bfloat16")
    cfg_a = mixer_config(**base)
    cfg_b = mixer_config(**base, reversible_cotangent_dtype="bfloat16")
    p, _, batch, loss_a = init_and_loss(cfg_a)
    _, _, _, loss_b = init_and_loss(cfg_b)
    ga = jax.jit(jax.grad(loss_a))(p, jax.random.key(0))
    gb = jax.jit(jax.grad(loss_b))(p, jax.random.key(0))
    for k in ga:
        np.testing.assert_array_equal(np.asarray(ga[k]).view(np.uint16),
                                      np.asarray(gb[k]).view(np.uint16),
                                      err_msg=k)


def test_reversible_cotangent_squash_f32_runs():
    import numpy as np
    """f32-calculation configs with the bf16 cotangent squash must train (the
    squash rounds through bf16 and casts back, so block vjps still see f32
    cotangents) and produce grads close to the exact ones."""
    base = dict(memory_reduction_strategy="revnet",
                calculation_dtype="float32", storage_dtype="float32",
                slice_dtype="float32")
    cfg_a = mixer_config(**base)
    cfg_b = mixer_config(**base, reversible_cotangent_dtype="bfloat16")
    p, _, batch, loss_a = init_and_loss(cfg_a)
    _, _, _, loss_b = init_and_loss(cfg_b)
    ga = jax.jit(jax.grad(loss_a))(p, jax.random.key(0))
    gb = jax.jit(jax.grad(loss_b))(p, jax.random.key(0))
    for k in ga:
        a, b = np.asarray(ga[k], np.float32), np.asarray(gb[k], np.float32)
        assert np.all(np.isfinite(b)), k
        # bf16 rounding on the streams: close but not exact
        np.testing.assert_allclose(a, b, rtol=0.1, atol=1e-3, err_msg=k)


def test_vocab_weight_factorization_shapes_and_grads():
    """Factorized vocab embedding (reference src/model/__init__.py:76-82):
    the token embedding table gathers into a SMALL intermediate
    (intermediate_size * vocab_weight_factorization) and a linear lifts it
    to features, so the table is (vocab, small) instead of
    (vocab, intermediate) — the memory lever that makes vocab 65536
    affordable.  Grads must flow through both factors."""
    import numpy as np
    factor = 0.25
    cfg = tiny_config(vocab_size=512, vocab_weight_factorization=factor)
    params, axes, batch, loss_fn = init_and_loss(cfg)
    small = int(cfg.intermediate_size * factor)
    assert small < cfg.intermediate_size
    # exactly one parameter carries the vocab axis on the input side: the
    # factorized gather table
    emb = [(k, v) for k, v in params.items()
           if "input" in k and cfg.vocab_size in v.shape]
    assert len(emb) == 1, [k for k, _ in emb]
    k_emb, table = emb[0]
    assert sorted(table.shape) == sorted((cfg.vocab_size, small)), (
        k_emb, table.shape)
    # the lift linear maps (token_patch, small) -> features
    g = jax.jit(jax.grad(loss_fn))(params, jax.random.key(0))
    gt = np.asarray(g[k_emb], np.float32)
    assert np.isfinite(gt).all()
    # only gathered rows receive grads; at least one row must be nonzero
    assert np.abs(gt).sum() > 0
    # unfactorized control: table widens to the full intermediate
    cfg1 = tiny_config(vocab_size=512, vocab_weight_factorization=1.0)
    params1, _, _, _ = init_and_loss(cfg1)
    t1 = params1[k_emb]
    assert sorted(t1.shape) == sorted((cfg1.vocab_size,
                                       cfg1.intermediate_size))


def test_fused_mixer_block_matches_unfused():
    """ops/pallas_mixer.py (interpret mode on CPU): the fused
    [norm, map-attn, norm, gelu, map-attn] kernel must reproduce the
    unfused layer chain inside the REAL model — identical parameter names
    (checkpoints interchange) and matching loss/grads in f32."""
    import numpy as np
    dt = dict(calculation_dtype="float32", storage_dtype="float32",
              slice_dtype="float32", optimizer_slice_dtype="float32")
    shape = dict(sequence_length=128, features_per_head=128, heads=2,
                 depth=2, train_batch_size=2)
    cfg_u = mixer_config(**shape, **dt)
    cfg_f = mixer_config(**shape, **dt, fused_mixer_block=True)
    pu, axu, batch, loss_u = init_and_loss(cfg_u)
    pf, axf, _, loss_f = init_and_loss(cfg_f)
    # identical scope walk => identical parameter census
    assert set(pu) == set(pf)
    for k in pu:
        np.testing.assert_array_equal(np.asarray(pu[k]), np.asarray(pf[k]))

    lu = float(jax.jit(loss_u)(pu, jax.random.key(0)))
    lf = float(jax.jit(loss_f)(pu, jax.random.key(0)))
    assert abs(lu - lf) < 1e-4 * max(1.0, abs(lu)), (lu, lf)

    gu = jax.jit(jax.grad(loss_u))(pu, jax.random.key(0))
    gf = jax.jit(jax.grad(loss_f))(pu, jax.random.key(0))
    for k in gu:
        a = np.asarray(gu[k], np.float32)
        b = np.asarray(gf[k], np.float32)
        scale = max(1e-3, float(np.abs(a).max()))
        assert np.abs(a - b).max() < 5e-3 * scale, (
            k, float(np.abs(a - b).max()), scale)


def test_fused_mixer_kernel_batch_accumulation():
    """Kernel-level: the backward's cross-grid-cell parameter-grad
    accumulation (the pl.when(b != 0) path) must run — batch large enough
    that the batch grid axis has multiple steps — and match the unfused
    reference in f32."""
    import numpy as np

    from homebrewnlp_tpu.ops.pallas_mixer import (_block_rows,
                                                  fused_mixer_block,
                                                  mixer_chain_reference)
    B, S, H, K = 16, 128, 2, 128
    assert B > _block_rows(B, S, K)  # multiple batch grid steps
    ks = jax.random.split(jax.random.key(3), 7)
    f32 = jnp.float32
    x = jax.random.normal(ks[0], (B, S, H, K), f32)
    b1 = jax.random.normal(ks[1], (H, S, S), f32) * 0.02
    b2 = jax.random.normal(ks[2], (H, S, S), f32) * 0.02
    s1 = 1 + jax.random.normal(ks[3], (H, K), f32) * 0.02
    sh1 = jax.random.normal(ks[4], (H, K), f32) * 0.02
    s2 = 1 + jax.random.normal(ks[5], (H, K), f32) * 0.02
    sh2 = jax.random.normal(ks[6], (H, K), f32) * 0.02
    args = (x, b1, b2, s1, sh1, s2, sh2)
    gr = jax.grad(lambda a: jnp.sum(mixer_chain_reference(*a) ** 2))(args)
    gf = jax.grad(lambda a: jnp.sum(fused_mixer_block(*a, True) ** 2))(args)
    for name, a, b_ in zip(("dx", "db1", "db2", "ds1", "dsh1", "ds2",
                            "dsh2"), gr, gf):
        a = np.asarray(a, np.float32)
        b_ = np.asarray(b_, np.float32)
        scale = max(1e-3, float(np.abs(a).max()))
        assert np.abs(a - b_).max() < 2e-4 * scale, (
            name, float(np.abs(a - b_).max()), scale)


def test_fused_group_block_matches_unfused():
    """ops/pallas_group.py (interpret mode on CPU): the fused two-kernel
    [group norm, bottleneck_group_linear] pair must reproduce the unfused
    layer chain inside the REAL model — identical parameter names
    (checkpoints interchange) and matching loss/grads in f32."""
    import numpy as np
    dt = dict(calculation_dtype="float32", storage_dtype="float32",
              slice_dtype="float32", optimizer_slice_dtype="float32")
    # memory_reduction_strategy="none" for the tight grad assertion: revnet's
    # stream reconstruction (x1 = y1 - f(y2)) chaotically amplifies the
    # fusion's benign summation-order differences (measured: 6e-7 rel grads
    # under "none" vs 1.6e-2 under revnet for the SAME kernels — the same
    # caveat docs/perf/README.md records for every remat/fusion change)
    shape = dict(sequence_length=128, features_per_head=128, heads=2,
                 depth=2, train_batch_size=2,
                 memory_reduction_strategy="none")
    cfg_u = mixer_config(**shape, **dt)
    cfg_f = mixer_config(**shape, **dt, fused_group_linear=True)
    # lane-aligned widths: K=128, mid=256, bottleneck I=128, N=256
    assert cfg_f.intermediate_size % 128 == 0
    pu, axu, batch, loss_u = init_and_loss(cfg_u)
    pf, axf, _, loss_f = init_and_loss(cfg_f)
    # identical scope walk => identical parameter census
    assert set(pu) == set(pf)
    for k in pu:
        np.testing.assert_array_equal(np.asarray(pu[k]), np.asarray(pf[k]))

    # XLA:CPU's DEFAULT f32 dot is split-bf16 (~1e-3 wobble, shape-
    # dependent); pin exact-f32 dots on both paths so parity is tight
    with jax.default_matmul_precision("highest"):
        lu = float(jax.jit(loss_u)(pu, jax.random.key(0)))
        lf = float(jax.jit(loss_f)(pu, jax.random.key(0)))
        assert abs(lu - lf) < 1e-5 * max(1.0, abs(lu)), (lu, lf)

        gu = jax.jit(jax.grad(loss_u))(pu, jax.random.key(0))
        gf = jax.jit(jax.grad(loss_f))(pu, jax.random.key(0))
    for k in gu:
        a = np.asarray(gu[k], np.float32)
        b = np.asarray(gf[k], np.float32)
        scale = max(1e-3, float(np.abs(a).max()))
        assert np.abs(a - b).max() < 1e-4 * scale, (
            k, float(np.abs(a - b).max()), scale)

    # under revnet the kernels still train the same model: loss parity holds
    # (grads deviate only through the reconstruction's rounding chaos)
    cfg_ur = mixer_config(**{**shape, "memory_reduction_strategy": "revnet"},
                          **dt)
    cfg_fr = mixer_config(**{**shape, "memory_reduction_strategy": "revnet"},
                          **dt, fused_group_linear=True)
    pur, _, _, loss_ur = init_and_loss(cfg_ur)
    _, _, _, loss_fr = init_and_loss(cfg_fr)
    with jax.default_matmul_precision("highest"):
        lur = float(jax.jit(loss_ur)(pur, jax.random.key(0)))
        lfr = float(jax.jit(loss_fr)(pur, jax.random.key(0)))
    assert abs(lur - lfr) < 1e-4 * max(1.0, abs(lur)), (lur, lfr)


def test_fused_group_kernel_row_accumulation():
    """Kernel-level: the backward's cross-grid-cell parameter-grad
    accumulation (the pl.when(r != 0) path) must run — rows beyond one
    grid cell of BOTH kernels — and match the unfused reference in f32."""
    import numpy as np

    from homebrewnlp_tpu.ops.pallas_group import (fused_group_linear_block,
                                                  group_chain_reference)
    B, S, H, K, I, J = 8, 128, 2, 128, 128, 256
    assert B * S > 512  # > kernel IN's row budget => multiple grid cells
    ks = jax.random.split(jax.random.key(3), 8)
    f32 = jnp.float32
    x = jax.random.normal(ks[0], (B, S, H, K), f32)
    w1 = jax.random.normal(ks[1], (H, K, I), f32) * 0.05
    w2 = jax.random.normal(ks[2], (I, H, J), f32) * 0.05
    w3 = jax.random.normal(ks[3], (H, J, K), f32) * 0.05
    s0 = 1 + jax.random.normal(ks[4], (H, K), f32) * 0.02
    h0 = jax.random.normal(ks[5], (H, K), f32) * 0.02
    s1 = 1 + jax.random.normal(ks[6], (H, J), f32) * 0.02
    h1 = jax.random.normal(ks[7], (H, J), f32) * 0.02
    args = (x, w1, w2, w3, s0, h0, s1, h1)
    # XLA:CPU's DEFAULT f32 dot is split-bf16 (~1e-3 wobble, shape-
    # dependent); pin exact-f32 dots on both paths so parity is tight
    with jax.default_matmul_precision("highest"):
        gr = jax.grad(
            lambda a: jnp.sum(group_chain_reference(*a) ** 2))(args)
        gf = jax.grad(
            lambda a: jnp.sum(fused_group_linear_block(*a, True) ** 2))(args)
    for name, a, b_ in zip(("dx", "dw1", "dw2", "dw3", "ds0", "dh0",
                            "ds1", "dh1"), gr, gf):
        a = np.asarray(a, np.float32)
        b_ = np.asarray(b_, np.float32)
        scale = max(1e-3, float(np.abs(a).max()))
        assert np.abs(a - b_).max() < 2e-4 * scale, (
            name, float(np.abs(a - b_).max()), scale)


def test_fused_group_falls_back_under_sharded_mesh(eight_devices):
    """fused_group_linear=true on a multi-device mesh must silently take
    the unfused GSPMD chain (pallas custom calls cannot be partitioned) —
    the knob is safe to leave on in a config that also runs sharded."""
    import numpy as np

    from homebrewnlp_tpu.parallel import make_mesh
    from homebrewnlp_tpu.train import Trainer
    cfg = mixer_config(sequence_length=128, features_per_head=128, heads=2,
                       depth=2, train_batch_size=8, tpu_size=8,
                       fused_group_linear=True)
    mesh = make_mesh(cfg)
    assert mesh.size == 8
    trainer = Trainer(cfg, mesh)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    state, m = trainer.step(state, batch, jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


def test_fused_mixer_falls_back_under_sharded_mesh(eight_devices):
    """fused_mixer_block=true on a multi-device mesh must silently take the
    unfused GSPMD chain (pallas custom calls cannot be partitioned) — the
    knob is safe to leave on in a config that also runs sharded."""
    import numpy as np

    from homebrewnlp_tpu.parallel import make_mesh
    from homebrewnlp_tpu.train import Trainer
    cfg = mixer_config(sequence_length=128, features_per_head=128, heads=2,
                       depth=2, train_batch_size=8, tpu_size=8,
                       fused_mixer_block=True)
    mesh = make_mesh(cfg)
    assert mesh.size == 8
    trainer = Trainer(cfg, mesh)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    state, m = trainer.step(state, batch, jax.random.key(0))
    assert np.isfinite(float(m["loss"]))
