"""Sequence-parallel ring attention: kernel numerics vs dense softmax,
end-to-end parity of sp=2 vs sp=1 training, multi-axis mesh train step."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from homebrewnlp_tpu.ops.ring import ring_attention
from homebrewnlp_tpu.parallel import make_mesh
from homebrewnlp_tpu.parallel.mesh import SEQ_AXIS
from homebrewnlp_tpu.train import Trainer
from homebrewnlp_tpu.utils import random_text_batch

from .backend import mixer_config

ATTN_BLOCK = [{"layer": ["norm-shift-scale",
                         "attention-in:relu-dot_product-embedded-relative"]}]


def _dense_reference(q, k, v, causal):
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if causal:
        s = q.shape[1]
        mask = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        logits = jnp.where(mask[None, None], logits, -2e38)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(eight_devices, causal):
    cfg = mixer_config(heads=2, sequence_parallel=4, train_batch_size=2)
    mesh = make_mesh(cfg)
    assert mesh.shape[SEQ_AXIS] == 4
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)
               for _ in range(3))
    from homebrewnlp_tpu.parallel.sharding import spec_for
    spec = spec_for(("batch", "sequence", "heads", "features_per_head"), mesh)
    with mesh:
        out = jax.jit(functools.partial(
            ring_attention, mesh=mesh, seq_axis=SEQ_AXIS, spec=spec,
            causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_dense_reference(q, k, v, causal)),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_dense(eight_devices):
    cfg = mixer_config(heads=2, sequence_parallel=4, train_batch_size=2)
    mesh = make_mesh(cfg)
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)
               for _ in range(3))
    from homebrewnlp_tpu.parallel.sharding import spec_for
    spec = spec_for(("batch", "sequence", "heads", "features_per_head"), mesh)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.sin(ring_attention(q, k, v, mesh, SEQ_AXIS, spec)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(_dense_reference(q, k, v, True)))

    with mesh:
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-5)


def test_sequence_parallel_training_matches_sp1(eight_devices):
    """sp=2 training must produce the same loss trajectory as sp=1 (exact
    attention, just distributed)."""
    base = dict(depth=1, heads=2, train_batch_size=4, sequence_length=32,
                optimizer="adam-learning_rate", learning_rate=1e-2,
                block_config=ATTN_BLOCK, use_initial_position_embedding=False)
    cfg1 = mixer_config(sequence_parallel=1, **base)
    cfg2 = mixer_config(sequence_parallel=2, **base)
    losses = {}
    for name, cfg in (("sp1", cfg1), ("sp2", cfg2)):
        trainer = Trainer(cfg)
        batch = random_text_batch(cfg, seed=3)
        state = trainer.init(batch)
        ls = []
        for i in range(5):
            state, m = trainer.step(state, batch, jax.random.key(9))
            ls.append(float(m["loss"]))
        losses[name] = ls
    np.testing.assert_allclose(losses["sp1"], losses["sp2"], rtol=2e-4)
    assert losses["sp2"][-1] < losses["sp2"][0]


def test_biased_map_mixer_under_sequence_parallel(eight_devices):
    """The flagship's bias-map mixer attention is NOT ring-eligible
    (_ring_eligible routes it to the GSPMD path: its seq x seq bias
    parameters live row-sharded over the sequence axis).  On a
    data x seq x model mesh it must train with the exact sp=1 trajectory
    and finite per-variable grads — the flagship architecture's SP story,
    proven rather than assumed (VERDICT r2 item 9)."""
    base = dict(depth=2, heads=2, train_batch_size=4, sequence_length=32,
                optimizer="adam-learning_rate", learning_rate=1e-2,
                memory_reduction_strategy="none", weight_decay=0.0,
                use_initial_position_embedding=False)
    cfg1 = mixer_config(sequence_parallel=1, **base)
    cfg2 = mixer_config(sequence_parallel=2, **base)
    losses = {}
    for name, cfg in (("sp1", cfg1), ("sp2", cfg2)):
        mesh = make_mesh(cfg)
        if name == "sp2":
            assert dict(mesh.shape) == {"data": 2, "sequence_parallel": 2,
                                        "pipeline": 1, "model": 2}
        trainer = Trainer(cfg, mesh)
        batch = random_text_batch(cfg, seed=3)
        state = trainer.init(batch)
        # the seq x seq bias maps must actually be sharded over the seq axis
        bias_keys = [k for k, ax in trainer.axes.items()
                     if ax.count("sequence") + ax.count("_sequence") == 2]
        assert bias_keys, sorted(trainer.axes)
        if name == "sp2":
            assert any(SEQ_AXIS in tuple(state.params[k].sharding.spec)
                       for k in bias_keys), [
                (k, state.params[k].sharding.spec) for k in bias_keys]
        ls = []
        for i in range(5):
            state, m = trainer.step(state, batch, jax.random.key(9))
            ls.append(float(m["loss"]))
            assert np.isfinite(float(m["grad_norm"]))
        losses[name] = ls
    np.testing.assert_allclose(losses["sp1"], losses["sp2"], rtol=2e-4)
    assert losses["sp2"][-1] < losses["sp2"][0]


def test_ring_composes_with_pipeline(eight_devices, monkeypatch):
    """Sequence parallelism composes with pipeline parallelism: the ring
    attention nests a seq-manual shard_map inside the pipe-manual stage
    region (ops/ring.py).  A seq2 x pipe2 x model2 mesh under the 1F1B
    schedule must reproduce the sp1/pp1 loss trajectory exactly, with the
    ring path actually taken inside the stages (counted via monkeypatch,
    not assumed), and the forward/eval walk (gpipe body, no grad) must
    report the same loss as the sequential model."""
    import homebrewnlp_tpu.ops.ring as ring_mod
    base = dict(depth=2, heads=2, train_batch_size=16, sequence_length=32,
                optimizer="adam-learning_rate", learning_rate=1e-2,
                memory_reduction_strategy="none", weight_decay=0.0,
                block_config=ATTN_BLOCK, use_initial_position_embedding=False)
    cfg1 = mixer_config(sequence_parallel=1, **base)
    cfgp = mixer_config(sequence_parallel=2, pipeline_parallel=2,
                        pipeline_schedule="1f1b", **base)
    calls = {"ring": 0}
    real_ring = ring_mod.ring_attention

    def counting_ring(*a, **kw):
        calls["ring"] += 1
        return real_ring(*a, **kw)

    monkeypatch.setattr(ring_mod, "ring_attention", counting_ring)
    losses = {}
    eval_loss = {}
    for name, cfg in (("sp1", cfg1), ("seq_pipe", cfgp)):
        mesh = make_mesh(cfg)
        if name == "seq_pipe":
            assert dict(mesh.shape) == {"data": 1, "sequence_parallel": 2,
                                        "pipeline": 2, "model": 2}
            calls["ring"] = 0
        trainer = Trainer(cfg, mesh)
        batch = random_text_batch(cfg, seed=3)
        state = trainer.init(batch)
        # forward/eval walk on the fresh init (the gpipe body with the
        # nested ring, no gradients)
        with mesh:
            eval_loss[name] = float(jax.jit(
                lambda p, b: trainer._losses(p, b, jax.random.key(9)).loss
            )(state.params, batch))
        ls = []
        for i in range(5):
            state, m = trainer.step(state, batch, jax.random.key(9))
            ls.append(float(m["loss"]))
            assert np.isfinite(float(m["grad_norm"]))
        losses[name] = ls
        if name == "seq_pipe":
            # one ring call traced per attention layer per stage walk
            assert calls["ring"] > 0, "ring attention never engaged"
    np.testing.assert_allclose(eval_loss["sp1"], eval_loss["seq_pipe"],
                               rtol=1e-5)
    np.testing.assert_allclose(losses["sp1"], losses["seq_pipe"], rtol=2e-4)
    assert losses["seq_pipe"][-1] < losses["seq_pipe"][0]
    # the gpipe TRAINING schedule cannot host the nested ring's backward
    # (jax.grad through the scan delays it across the scan boundary);
    # config validation rejects the combination up front
    with pytest.raises(ValueError, match="1f1b"):
        mixer_config(sequence_parallel=2, pipeline_parallel=2,
                     pipeline_schedule="gpipe", **base)


def test_dp_tp_sp_mesh_step(eight_devices):
    """2x2x2 data x sequence x model mesh runs a full train step."""
    cfg = mixer_config(depth=1, heads=2, train_batch_size=4,
                       sequence_length=32, sequence_parallel=2,
                       block_config=ATTN_BLOCK)
    mesh = make_mesh(cfg)
    assert dict(mesh.shape) == {"data": 2, "sequence_parallel": 2,
                                "pipeline": 1, "model": 2}
    trainer = Trainer(cfg, mesh)
    batch = random_text_batch(cfg)
    state = trainer.init(batch)
    state, metrics = trainer.step(state, batch, jax.random.key(0))
    assert np.isfinite(float(metrics["loss"]))
