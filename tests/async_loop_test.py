"""Async-dispatch training loop (ISSUE 2): the CPU smoke test the CI step
runs (5 synthetic updates through the real CLI train path, guarding thread
shutdown and exit paths), sync-vs-async loss-sequence parity, the deferred
metric drain's window semantics, the profiler's drain-before-stop_trace
interaction, and checkpoint resume under device prefetch."""
import argparse
import json
import os
import threading

import numpy as np
import pytest

from homebrewnlp_tpu import main as cli
from homebrewnlp_tpu.data.synthetic import write_text_tfrecords

from .backend import tiny_config


def _args(steps, profile=""):
    return argparse.Namespace(steps=steps, profile=profile, workers=None)


def _metric_rows(model_path):
    """Metric rows only — the shared reader skips run-start markers."""
    from homebrewnlp_tpu.train.metrics import read_metric_rows
    return read_metric_rows(model_path)


def _feeder_threads():
    return [t for t in threading.enumerate()
            if t.name == "device-feeder" and t.is_alive()]


def test_async_train_smoke_synthetic(tmp_path, eight_devices):
    """The CI smoke: 5 synthetic-data updates through the async loop —
    host-computed step indices land in metrics.jsonl, losses are finite,
    and the feeder thread is joined on exit."""
    cfg = tiny_config(model_path=str(tmp_path), async_inflight_steps=2,
                      device_prefetch_depth=1)
    cli.train(cfg, _args(5))
    rows = _metric_rows(str(tmp_path))
    assert [r["step"] for r in rows] == [0, 1, 2, 3, 4]
    assert all(np.isfinite(r["loss"]) for r in rows)
    assert not _feeder_threads()


def test_async_loss_sequence_matches_sync(tmp_path, eight_devices):
    """Acceptance: prefetch + async dispatch must produce the IDENTICAL loss
    sequence (same values, same order) as the synchronous path."""
    sync_cfg = tiny_config(model_path=str(tmp_path / "sync"),
                           async_inflight_steps=0, device_prefetch_depth=0)
    cli.train(sync_cfg, _args(8))
    async_cfg = tiny_config(model_path=str(tmp_path / "async"),
                            async_inflight_steps=3, device_prefetch_depth=2)
    cli.train(async_cfg, _args(8))
    sync_rows = _metric_rows(str(tmp_path / "sync"))
    async_rows = _metric_rows(str(tmp_path / "async"))
    assert [r["step"] for r in sync_rows] == [r["step"] for r in async_rows]
    assert [r["loss"] for r in sync_rows] == [r["loss"] for r in async_rows]


@pytest.mark.slow
def test_async_loss_parity_300_steps(tmp_path, eight_devices):
    """Full acceptance length: 300 synthetic updates, identical loss
    sequence with prefetch + async enabled vs. the synchronous path."""
    sync_cfg = tiny_config(model_path=str(tmp_path / "sync"),
                           async_inflight_steps=0, device_prefetch_depth=0)
    cli.train(sync_cfg, _args(300))
    async_cfg = tiny_config(model_path=str(tmp_path / "async"),
                            async_inflight_steps=2, device_prefetch_depth=1)
    cli.train(async_cfg, _args(300))
    sync_rows = _metric_rows(str(tmp_path / "sync"))
    async_rows = _metric_rows(str(tmp_path / "async"))
    assert len(sync_rows) == len(async_rows) == 300
    assert [r["loss"] for r in sync_rows] == [r["loss"] for r in async_rows]


def test_profile_drains_inflight_window(tmp_path, eight_devices):
    """--profile under async dispatch: the in-flight window drains before
    stop_trace (whole steps in the trace) and the run completes with every
    step's metrics written."""
    trace_dir = str(tmp_path / "trace")
    cfg = tiny_config(model_path=str(tmp_path / "run"),
                      async_inflight_steps=4, device_prefetch_depth=1)
    cli.train(cfg, _args(8, profile=trace_dir))
    assert os.path.isdir(trace_dir)
    assert any(files for _, _, files in os.walk(trace_dir))
    assert [r["step"] for r in _metric_rows(str(tmp_path / "run"))] == \
        list(range(8))
    assert not _feeder_threads()


def test_dataset_exhaustion_stops_cleanly(tmp_path, eight_devices, capsys):
    """StopIteration propagates through feeder + loop: the exhaustion
    message fires, metrics cover exactly the completed updates, no feeder
    thread survives."""
    paths_dir = tmp_path / "data"
    # 1 file x 1 record x 70 tokens, window 17/shift 16 -> 4 windows -> two
    # 2-row batches before exhaustion
    write_text_tfrecords(str(paths_dir), n_files=1, records_per_file=1,
                         tokens_per_record=70, seed=3)
    cfg = tiny_config(model_path=str(tmp_path / "run"), vocab_size=256,
                      interleaved_datasets=1, async_inflight_steps=2,
                      device_prefetch_depth=2, dataset_configs=[
                          {"type": "text",
                           "path": str(paths_dir / "*.tfrecord")}])
    cli.train(cfg, _args(10))
    out = capsys.readouterr().out
    assert "dataset exhausted" in out
    assert [r["step"] for r in _metric_rows(str(tmp_path / "run"))] == [0, 1]
    assert not _feeder_threads()


def test_checkpoint_resume_under_prefetch(tmp_path, eight_devices):
    """Save/restore round-trip under device prefetch depth 2: the cursor
    records CONSUMED batches only, so the resumed run's losses equal the
    uninterrupted run's — model state AND data stream both land exactly."""
    paths_dir = tmp_path / "data"
    write_text_tfrecords(str(paths_dir), n_files=2, records_per_file=2,
                         tokens_per_record=200, seed=7)
    dsets = [{"type": "text", "path": str(paths_dir / "*.tfrecord")}]

    def run(model_path, steps):
        cfg = tiny_config(model_path=model_path, dataset_configs=dsets,
                          vocab_size=256, interleaved_datasets=2,
                          use_checkpointing=True, steps_per_checkpoint=3,
                          async_inflight_steps=2, device_prefetch_depth=2)
        cli.train(cfg, _args(steps))

    run(str(tmp_path / "a"), 6)          # uninterrupted reference
    run(str(tmp_path / "b"), 3)          # train 3, checkpoint
    run(str(tmp_path / "b"), 6)          # resume at step 3, finish
    ref = {r["step"]: r["loss"] for r in _metric_rows(str(tmp_path / "a"))}
    resumed = {r["step"]: r["loss"]
               for r in _metric_rows(str(tmp_path / "b"))}
    assert set(ref) == set(resumed) == set(range(6))
    assert all(np.isfinite(v) for v in ref.values())
    for s in range(6):
        assert ref[s] == resumed[s], f"loss diverged at step {s}"


def test_deferred_writer_window_flush_and_blocked_time(tmp_path):
    from homebrewnlp_tpu.train.metrics import AsyncMetricWriter, MetricWriter
    w = AsyncMetricWriter(MetricWriter(str(tmp_path)), window=2)
    w.write(0, {"loss": np.float32(1.0)})
    w.write(1, {"loss": np.float32(2.0)})
    assert w.last_loss is None          # both still inside the window
    assert _metric_rows(str(tmp_path)) == []
    w.write(2, {"loss": np.float32(3.0)})
    assert w.last_loss == 1.0           # oldest fell out and drained
    w.flush()
    assert w.last_loss == 3.0
    rows = _metric_rows(str(tmp_path))
    assert [r["step"] for r in rows] == [0, 1, 2]
    assert [r["loss"] for r in rows] == [1.0, 2.0, 3.0]
    assert w.host_blocked_s >= 0.0
    w.close()
    # window=0: every write drains immediately (the synchronous parity path)
    w0 = AsyncMetricWriter(MetricWriter(str(tmp_path / "sync")), window=0)
    w0.write(0, {"loss": np.float32(5.0)})
    assert w0.last_loss == 5.0
    w0.close()


def test_deferred_writer_step_seconds_reflect_dispatch(tmp_path):
    """step_seconds must come from dispatch wall times, not drain times —
    a flush() draining 3 entries at once still reports per-step gaps."""
    import time
    from homebrewnlp_tpu.train.metrics import AsyncMetricWriter, MetricWriter
    w = AsyncMetricWriter(MetricWriter(str(tmp_path)), window=8)
    for i in range(3):
        w.write(i, {"loss": np.float32(i)})
        time.sleep(0.02)
    w.flush()
    rows = _metric_rows(str(tmp_path))
    # the gap between writes (>= 20ms) survives the batched drain
    assert rows[1]["wall_time"] - rows[0]["wall_time"] >= 0.01
    assert rows[1]["step_seconds"] >= 0.01
    w.close()
