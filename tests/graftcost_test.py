"""graftcost static cost model: liveness scan, exact param/slot accounting,
KV-cache shape accessors, per-axis collective payloads, resources-golden
ratchet, the OOM-before-compile gate, the sweep scaling model, and the CLI.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from homebrewnlp_tpu.analysis import cost_model, memory, trace as atrace
from homebrewnlp_tpu.devices import DEVICE_TABLE, resolve_device
from homebrewnlp_tpu.train.flops import jaxpr_flops, peak_flops

from .backend import mixer_config, tiny_config


@pytest.fixture(scope="module")
def mixer_traces():
    cfg = mixer_config(tpu_size=1)
    traces = atrace.trace_config(cfg, "mixer1chip",
                                 steps=("train", "decode", "prefill"))
    assert not traces.errors, traces.errors
    return traces


# -- liveness linear scan ----------------------------------------------------

def test_liveness_peak_releases_dead_buffers():
    """a -> b -> c chain of matmuls: at most two 64 KiB products are ever
    live at once (a dies once b exists)."""
    x = jnp.zeros((128, 128), jnp.float32)

    def f(x):
        a = jnp.dot(x, x)
        b = jnp.dot(a, a)
        return jnp.dot(b, b)

    r = memory.liveness_peak(jax.make_jaxpr(f)(x))
    assert r.peak_bytes == 2 * 128 * 128 * 4, r.peak_bytes
    assert all(getattr(a, "shape", None) == (128, 128) for a in r.peak_live)


def test_liveness_fuses_elementwise_chains():
    """tanh/mul/add between two dots alias the dot's buffer (XLA fuses
    them); the chain must NOT count one buffer per elementwise op."""
    x = jnp.zeros((128, 128), jnp.float32)

    def f(x):
        a = jnp.dot(x, x)
        b = jnp.tanh(a) * 2.0 + 1.0
        return jnp.dot(b, b)

    r = memory.liveness_peak(jax.make_jaxpr(f)(x))
    assert r.peak_bytes == 2 * 128 * 128 * 4, r.peak_bytes


def test_liveness_donated_outputs_excluded():
    """exclude_outputs models donation: the returned buffer stops counting
    once its last in-graph reader is done."""
    x = jnp.zeros((128, 128), jnp.float32)

    def f(x):
        return jnp.dot(x, x)

    j = jax.make_jaxpr(f)(x)
    assert memory.liveness_peak(j).peak_bytes == 128 * 128 * 4
    assert memory.liveness_peak(j, exclude_outputs=True).peak_bytes == 0


def test_liveness_exclude_output_indices():
    """Selected outvar positions stop counting past their last in-graph
    use — how prefill's cache outputs (priced separately as kv_cache) are
    kept out of the transient term."""
    x = jnp.zeros((64, 64), jnp.float32)

    def f(x):
        big = jnp.dot(x, x)
        return jnp.sum(x), big

    j = jax.make_jaxpr(f)(x)
    full = memory.liveness_peak(j).peak_bytes
    excl = memory.liveness_peak(j, exclude_output_indices={1}).peak_bytes
    assert excl < full, (excl, full)


def test_prefill_caches_not_double_counted(mixer_traces):
    """Prefill's written caches are priced ONCE (the kv_cache term), not
    again as liveness outputs — double-counting halved the sweep's
    predicted max prompt length."""
    res = cost_model.config_resources(mixer_traces)["prefill"]
    st = mixer_traces.steps["prefill"]
    assert res.hbm["kv_cache"] > 0
    # on the 1-chip anchor (divisor 1) a reverted exclusion makes the
    # activation term equal the all-outputs liveness peak
    full = memory.liveness_peak(st.jaxpr).peak_bytes
    assert res.hbm["activation_peak"] < full, (res.hbm, full)


def test_liveness_charges_scan_bodies_once():
    """A scan body's internal peak is charged at the scan site, not
    multiplied by trip count (iterations run one at a time)."""
    x = jnp.zeros((64, 64), jnp.float32)

    def body(c, _):
        return jnp.tanh(jnp.dot(c, c)), None

    def f(x):
        out, _ = jax.lax.scan(body, x, None, length=8)
        return out

    r = memory.liveness_peak(jax.make_jaxpr(f)(x))
    # one body-internal dot product (16 KiB) + the scan's carry output
    assert r.peak_bytes <= 3 * 64 * 64 * 4, r.peak_bytes


# -- exact param/slot accounting (ISSUE acceptance) --------------------------

def _exact_bytes(shapes):
    return sum(int(np.prod(s.shape or (1,))) * np.dtype(s.dtype).itemsize
               for s in shapes)


def test_param_slot_bytes_exact_on_one_chip():
    """1-chip config: predicted param+slot bytes == the analytic count."""
    cfg = tiny_config(tpu_size=1, optimizer="adam-learning_rate")
    traces = atrace.trace_config(cfg, "tiny1chip", steps=("train",))
    res = cost_model.config_resources(traces)["train"]
    exact_p = _exact_bytes(traces.param_shapes.values())
    exact_s = _exact_bytes(s for slots in traces.opt_state_shapes.values()
                           for s in slots.values())
    assert exact_s > 0  # adam carries real moment slots
    assert res.hbm["params"] == exact_p
    assert res.hbm["opt_slots"] == exact_s


def test_param_bytes_sharded_on_intended_mesh():
    """tpu_size 8 with 4 heads: head-sharded params divide by the model
    axis; per-device bytes strictly below the global count."""
    cfg = tiny_config(tpu_size=8)
    traces = atrace.trace_config(cfg, "tinypod", steps=("train",))
    res = cost_model.config_resources(traces)["train"]
    assert res.hbm["params"] < _exact_bytes(traces.param_shapes.values())


# -- XLA cross-check (ISSUE acceptance: within the recorded tolerance) -------

def test_predicted_peak_within_xla_tolerance(eight_devices):
    """Predicted peak vs the compiled step's XLA memory analysis
    (temp + argument buffers), on the same mesh the trace used."""
    from homebrewnlp_tpu.train.state import Trainer
    from .backend import text_batch
    for cfg in (tiny_config(tpu_size=1), mixer_config(tpu_size=1)):
        traces = atrace.trace_config(cfg, "xlacheck", steps=("train",))
        res = cost_model.step_resources(traces, "train",
                                        traces.steps["train"], traces.mesh)
        trainer = Trainer(cfg)
        batch = text_batch(cfg)
        state = trainer.init(batch)
        compiled = trainer._make_step().lower(
            state, batch, jax.random.key(0)).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            pytest.skip("backend exposes no memory_analysis")
        xla = int(ma.temp_size_in_bytes) + int(ma.argument_size_in_bytes)
        ratio = res.hbm["peak"] / xla
        assert 1 / cost_model.XLA_RATIO <= ratio <= cost_model.XLA_RATIO, (
            f"{cfg}: predicted {res.hbm} vs XLA {xla} (ratio {ratio:.2f})")


# -- KV-cache shape accessors ------------------------------------------------

def test_cache_shapes_accessor_scales_and_counts(mixer_traces):
    from homebrewnlp_tpu.infer.kv_cache import cache_nbytes, cache_shapes
    cfg = mixer_traces.cfg
    s1 = cache_shapes(cfg, mixer_traces.param_shapes, 1)
    s2 = cache_shapes(cfg, mixer_traces.param_shapes, 2)
    assert s1 and all(isinstance(s, jax.ShapeDtypeStruct)
                      for kv in s1.values() for s in kv)
    b1, b2 = cache_nbytes(s1), cache_nbytes(s2)
    assert b1 > 0 and b2 == 2 * b1  # linear in batch
    # every cached row is per-position: bytes divide exactly by
    # batch x seq x a whole itemsize (context scaling itself is exercised
    # through the sweep model — the learned-map mixer pins its map length
    # to sequence_length, so cache_shapes only accepts the model's seq)
    seq = cfg.sequence_length // cfg.token_patch_size
    assert b1 % seq == 0


def test_decode_resources_price_the_kv_cache(mixer_traces):
    res = cost_model.config_resources(mixer_traces)
    assert res["decode"].hbm["kv_cache"] > 0
    assert res["train"].hbm["kv_cache"] == 0
    assert res["decode"].hbm["peak"] < res["train"].hbm["peak"]


# -- collective payload attribution ------------------------------------------

def test_collective_bytes_attributed_to_mesh_axes(eight_devices):
    """The composed DP/SP/PP/TP config moves real bytes over the ring and
    pipeline axes; the cost model sizes them (census only counts them)."""
    raw = json.load(open(os.path.join(REPO, "configs",
                                      "8dev_composed_dryrun.json")))
    raw.pop("_comment", None)
    from homebrewnlp_tpu.config import Config
    traces = atrace.trace_config(Config(raw), "8dev", steps=("train",))
    res = cost_model.config_resources(traces)["train"]
    assert res.comm.bytes_per_axis.get("sequence_parallel", 0) > 0
    assert res.comm.bytes_per_axis.get("pipeline", 0) > 0
    spec = resolve_device("v5e")
    times = res.comm.times(cost_model._imesh_shape(traces), spec)
    assert all(t > 0 for t in times.values())


# -- resources golden ratchet + OOM gate -------------------------------------

def test_resource_budget_ratchet_roundtrip(mixer_traces, tmp_path,
                                           monkeypatch):
    monkeypatch.setattr(cost_model, "GOLDENS_DIR", str(tmp_path))
    fs = cost_model.check_resource_budget(mixer_traces, update_goldens=True)
    assert all(f.severity == "info" for f in fs)
    # clean against the freshly recorded budget
    assert cost_model.check_resource_budget(mixer_traces) == []
    path = cost_model.resources_golden_path(mixer_traces.config_name)
    golden = json.load(open(path))
    # regression: the recorded budget says the step used to be 2x smaller
    golden["steps"]["train"]["hbm"]["peak"] //= 2
    json.dump(golden, open(path, "w"))
    fs = cost_model.check_resource_budget(mixer_traces)
    assert any(f.severity == "error" and "regressed" in f.message
               for f in fs), [f.render() for f in fs]
    # improvement: budget far above the prediction -> info asking to ratchet
    golden["steps"]["train"]["hbm"]["peak"] *= 64
    json.dump(golden, open(path, "w"))
    fs = cost_model.check_resource_budget(mixer_traces)
    assert any(f.severity == "info" and "improved" in f.message for f in fs)
    assert not any(f.severity == "error" for f in fs)


def test_resource_budget_missing_golden_is_error(mixer_traces, tmp_path,
                                                 monkeypatch):
    monkeypatch.setattr(cost_model, "GOLDENS_DIR", str(tmp_path))
    fs = cost_model.check_resource_budget(mixer_traces)
    assert any(f.severity == "error" and "no resources golden" in f.message
               for f in fs)


def test_oom_before_compile_fires_on_inflated_context(tmp_path, monkeypatch):
    """ISSUE acceptance: inflate a config's context/batch so the predicted
    peak exceeds the target device's HBM — the rule errors even when the
    ratcheted golden matches (the gate is independent of the ratchet)."""
    cfg = tiny_config(tpu_size=1, target_device="v5e",
                      sequence_length=32768, train_batch_size=32,
                      features_per_head=256, heads=4)
    traces = atrace.trace_config(cfg, "inflated", steps=("train",))
    monkeypatch.setattr(cost_model, "GOLDENS_DIR", str(tmp_path))
    cost_model.check_resource_budget(traces, update_goldens=True)
    fs = cost_model.check_resource_budget(traces)
    oom = [f for f in fs if f.severity == "error"
           and "OOM before compile" in f.message]
    assert oom, [f.render() for f in fs]
    assert "v5e" in oom[0].message


def test_committed_resources_goldens_cover_all_configs():
    """Every bundled config carries a resources golden and the committed
    budgets pass (the graftcheck CI gate runs the same check; this pins it
    in-tree)."""
    import glob
    names = sorted(os.path.splitext(os.path.basename(p))[0] for p in
                   glob.glob(os.path.join(REPO, "configs", "*.json")))
    for name in names:
        assert os.path.exists(cost_model.resources_golden_path(name)), name
        golden = json.load(open(cost_model.resources_golden_path(name)))
        assert golden["steps"], name
        assert golden["tolerance"]["xla"] == cost_model.XLA_RATIO


# -- sweep scaling model -----------------------------------------------------

def test_sweep_model_scales_context_and_batch(mixer_traces):
    m = cost_model.build_sweep_model(mixer_traces)
    anchor = m.peak_at("decode")
    doubled = m.peak_at("decode", context=2 * m.anchor_seq)
    assert doubled["kv_cache"] == 2 * anchor["kv_cache"]
    assert doubled["peak"] > anchor["peak"]
    # serving batch scaling anchors at the decode trace's batch of 1
    b4 = m.peak_at("decode", batch=4)
    assert b4["kv_cache"] == 4 * anchor["kv_cache"]
    assert b4["activation_peak"] == 4 * anchor["activation_peak"]
    # params don't scale with batch
    assert b4["params"] == anchor["params"]
    # train peaks grow monotonically in context
    peaks = [m.peak_at("train", context=c)["peak"]
             for c in (16, 64, 256, 1024)]
    assert peaks == sorted(peaks) and peaks[0] < peaks[-1]


def test_first_context_exceeding(mixer_traces):
    import dataclasses
    m = cost_model.build_sweep_model(mixer_traces)
    contexts = [16, 64, 256, 1024]
    tight = dataclasses.replace(resolve_device("v5e"), hbm_bytes=int(
        m.peak_at("train", context=64)["peak"]) + 1)
    first = cost_model.first_context_exceeding(m, "train", tight, contexts)
    assert first == 256
    roomy = dataclasses.replace(tight, hbm_bytes=1 << 50)
    assert cost_model.first_context_exceeding(
        m, "train", roomy, contexts) is None


# -- static flop counter -----------------------------------------------------

def test_jaxpr_flops_exact_on_dot_and_scan():
    a = jnp.zeros((8, 16), jnp.float32)
    b = jnp.zeros((16, 32), jnp.float32)
    assert jaxpr_flops(jax.make_jaxpr(jnp.dot)(a, b)) == 2 * 8 * 16 * 32

    sq = jnp.zeros((16, 16), jnp.float32)

    def f(x):
        out, _ = jax.lax.scan(lambda c, _: (jnp.dot(c, c), None), x, None,
                              length=5)
        return out

    assert jaxpr_flops(jax.make_jaxpr(f)(sq)) == 5 * 2 * 16 * 16 * 16


# -- device constants table --------------------------------------------------

def test_device_table_agrees_with_peak_flops_table():
    """Every device kind the cost model prices must resolve in the live-MFU
    peak table too (one verdict arithmetic, two tables kept honest)."""
    for spec in DEVICE_TABLE:
        assert peak_flops(spec.kind), spec.kind
        assert spec.hbm_bytes > 0 and spec.hbm_bw > 0 and spec.ici_bw > 0
    assert resolve_device("TPU v5 lite") is not None
    assert resolve_device("cpu") is None


def test_target_device_knob_validated():
    with pytest.raises(ValueError, match="target_device"):
        tiny_config(target_device="v99")
    assert tiny_config(target_device="v5e").target_device == "v5e"
    assert tiny_config().target_device == ""


# -- CLI ---------------------------------------------------------------------

MINI_CONFIG = dict(
    model_mode="gpt", use_video=False, use_language=True,
    sequence_length=32, features_per_head=16, heads=2, depth=2,
    vocab_size=64, train_batch_size=4, tpu_size=1,
    memory_reduction_strategy="none",
    intermediate_feed_forward_multiplier_multiplier=0.5,
    optimizer="adam-learning_rate",
    block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
)


def test_graftcost_cli_sweep_json(tmp_path):
    """The planning CLI end to end: sweep a tmp config's context, parse the
    JSON, check monotone peaks and the per-device first-exceeding report."""
    cfg_path = tmp_path / "mini.json"
    cfg_path.write_text(json.dumps(MINI_CONFIG))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/graftcost.py"),
         "--config", str(cfg_path), "--sweep", "context=32..128",
         "--devices", "v5e,v4", "--steps", "train,decode", "--json"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)[0]
    assert out["sweep"] == "context" and out["points"] == [32, 64, 128]
    train = out["steps"]["train"]
    peaks = [train["peaks"][str(p)] if str(p) in train["peaks"]
             else train["peaks"][p] for p in out["points"]]
    assert peaks == sorted(peaks)
    assert set(train["first_exceeding"]) == {"v5e", "v4"}


def test_graftcost_cli_rejects_unknown_steps():
    """A typoed step must exit 2, not print an empty sheet with exit 0."""
    for extra in (["--steps", "trian"], ["--sweep", "context=32..64",
                                         "--sweep-step", "prefil"]):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools/graftcost.py"),
             "--config", os.path.join(REPO, "configs", "32ctx_mixer.json")]
            + extra, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 2, (extra, proc.stdout, proc.stderr)
        assert "unknown step" in proc.stderr
