"""Test configuration: force an 8-device virtual CPU mesh so SPMD code paths
get genuine multi-device coverage without hardware (the reference's tests run
single-device PlacementMeshImpl on CPU — see SURVEY.md §4; this is strictly
stronger)."""
import os

# Force CPU even when the ambient env selects a TPU platform (e.g.
# JAX_PLATFORMS=axon registered by a sitecustomize PJRT plugin, which wins
# over the env var): the suite needs the 8-device virtual mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs
