"""Data layer tests: TFRecord codec (with tf.train.Example as oracle when
available), windowed pipelines, interleave determinism + resume, mixture
weighting, run-log replay parity against actual consumption, video decode,
host->device feeding."""
import numpy as np
import pytest

from homebrewnlp_tpu.data import (GptPipeline, MixturePipeline, RecordWriter,
                                  count_records, decode_example,
                                  encode_example, read_records,
                                  skips_for_restart, synthetic_text_batch,
                                  to_global, write_text_tfrecords)
from homebrewnlp_tpu.data.pipeline import _FileWindows, _Interleave
from homebrewnlp_tpu.data.resume import RunLog, simulate_consumption

from .backend import mixer_config


def test_example_roundtrip():
    ex = {"text": b"hello world", "ids": [1, 5, 70000, 0], "w": [0.5, -1.25]}
    decoded = decode_example(encode_example(ex))
    assert decoded["text"] == [b"hello world"]
    assert decoded["ids"] == [1, 5, 70000, 0]
    assert decoded["w"] == [0.5, -1.25]


def test_example_matches_tensorflow_oracle():
    tf = pytest.importorskip("tensorflow")
    ours = encode_example({"text": b"abc", "ids": [3, 9, 127, 128, 300]})
    theirs = decode_example(
        tf.train.Example(features=tf.train.Features(feature={
            "text": tf.train.Feature(bytes_list=tf.train.BytesList(value=[b"abc"])),
            "ids": tf.train.Feature(int64_list=tf.train.Int64List(value=[3, 9, 127, 128, 300])),
        })).SerializeToString())
    assert theirs["text"] == [b"abc"] and theirs["ids"] == [3, 9, 127, 128, 300]
    # and tf can parse ours
    parsed = tf.io.parse_single_example(ours, {
        "text": tf.io.FixedLenFeature([], tf.string),
        "ids": tf.io.VarLenFeature(tf.int64)})
    assert parsed["text"].numpy() == b"abc"
    assert list(tf.sparse.to_dense(parsed["ids"]).numpy()) == [3, 9, 127, 128, 300]


def test_record_framing_roundtrip(tmp_path):
    p = str(tmp_path / "x.tfrecord")
    payloads = [b"a" * 3, b"b" * 1000, b""]
    with RecordWriter(p) as w:
        for x in payloads:
            w.write(x)
    assert list(read_records(p, verify=True)) == payloads
    assert count_records(p) == 3
    assert list(read_records(p, skip=2)) == [b""]


def test_tfrecord_readable_by_tensorflow(tmp_path):
    tf = pytest.importorskip("tensorflow")
    p = str(tmp_path / "x.tfrecord")
    with RecordWriter(p) as w:
        w.write(b"payload-1")
        w.write(b"payload-2")
    got = [r.numpy() for r in tf.data.TFRecordDataset(p)]
    assert got == [b"payload-1", b"payload-2"]


def test_file_windows_per_record(tmp_path):
    (path,) = write_text_tfrecords(str(tmp_path), 1, records_per_file=2,
                                   tokens_per_record=25, seed=1)
    # window 10+1, shift 10 -> per 25-token record: starts 0,10 => 2 windows
    wins = list(_FileWindows(path, window=11, shift=10))
    assert len(wins) == 4
    assert all(len(w) == 11 for w in wins)
    # consecutive windows overlap by 1 token (x/y offset)
    assert wins[0][10] == wins[1][0]


def test_gpt_pipeline_shapes_and_xy_offset(tmp_path):
    cfg = mixer_config(sequence_length=16)
    paths = write_text_tfrecords(str(tmp_path), 4, 4, 70, seed=3)
    pipe = GptPipeline(cfg, sub_batch_size=2, paths=paths)
    batch = next(iter(pipe))
    assert batch["token_x"].shape == (2, 16, 1)
    assert batch["token_y"].shape == (2, 16, 1)
    np.testing.assert_array_equal(batch["token_x"][:, 1:], batch["token_y"][:, :-1])


def test_interleave_deterministic_and_resumable(tmp_path):
    paths = write_text_tfrecords(str(tmp_path), 6, 3, 40, seed=5)
    def make():
        return _Interleave(sorted(paths), [0] * 6, window=17, shift=16,
                           cycle=3, repeat=False)
    full = [w.tobytes() for w in make()]
    assert len(full) > 10
    # same stream twice
    assert [w.tobytes() for w in make()] == full
    # stop after k, save state, resume
    k = 7
    inter = make()
    it = iter(inter)
    got = [next(it).tobytes() for _ in range(k)]
    state = inter.state_dict()
    resumed = make()
    resumed.load_state_dict(state)
    got += [w.tobytes() for w in resumed]
    assert got == full


def test_shuffled_pipeline_resume(tmp_path):
    """Resume with shuffling must reproduce the exact continuation (buffer
    contents rebuilt by replay)."""
    cfg = mixer_config(sequence_length=16, use_random_dataloader=True,
                       shuffle_buffer=8, interleaved_datasets=2)
    paths = write_text_tfrecords(str(tmp_path), 3, 2, 100, seed=13)

    def make():
        return GptPipeline(cfg, sub_batch_size=2, paths=paths)

    it = iter(make_pipe := make())
    consumed = [next(it) for _ in range(4)]
    state = make_pipe.state_dict()
    expected = [next(it)["token_x"].tobytes() for _ in range(3)]
    fresh = make()
    fresh.load_state_dict(state)
    got = []
    it2 = iter(fresh)
    got = [next(it2)["token_x"].tobytes() for _ in range(3)]
    assert got == expected
    assert consumed


def test_mixture_continues_after_child_exhausts():
    a = [{"x": np.full(1, 0)}] * 5
    b = [{"x": np.full(1, 1)}] * 50
    out = [int(m["x"][0]) for m in MixturePipeline([a, b], [1, 1], seed=3)]
    # all 55 elements are yielded; the mixture doesn't stop when `a` drains
    assert len(out) == 55
    assert out.count(0) == 5 and out.count(1) == 50


def test_mixture_weights_and_determinism():
    a = [{"x": np.full(1, 0)}] * 300
    b = [{"x": np.full(1, 1)}] * 300
    mix1 = list(MixturePipeline([a, b], [3, 1], seed=7))
    mix2 = list(MixturePipeline([a, b], [3, 1], seed=7))
    assert [m["x"][0] for m in mix1] == [m["x"][0] for m in mix2]
    frac = np.mean([m["x"][0] for m in mix1][:200])
    assert 0.1 < frac < 0.4  # ~0.25


def test_runlog_replay_matches_actual_consumption(tmp_path):
    """Property test (SURVEY.md §7 hard part): replay arithmetic must equal
    real pipeline consumption for a single-record-per-file dataset."""
    cfg = mixer_config(sequence_length=16, interleaved_datasets=2)
    paths = write_text_tfrecords(str(tmp_path), 5, 1, 130, seed=9)
    pipe = GptPipeline(cfg, sub_batch_size=2, paths=paths)
    # consume 3 batches = 6 windows
    it = iter(pipe)
    consumed_windows = [next(it) for _ in range(3)]
    log = RunLog(str(tmp_path))
    log.append(steps=3, batch_size=2, slice_count=1, ctx=16,
               interleave_size=2, token_patch_size=1)

    # actual continuation from the live iterator
    rest_actual = [b["token_x"].tobytes() for b in it]
    # continuation reconstructed purely from the run log
    pipe_replay = GptPipeline(cfg, sub_batch_size=2, paths=paths,
                              runs_log=log.runs)
    rest_replay = [b["token_x"].tobytes() for b in pipe_replay]
    assert rest_replay == rest_actual
    assert consumed_windows  # silence unused warning; 3 batches were drawn


def test_simulate_consumption_full_depletion():
    # 2 files, 100 tokens each, ctx 10 + patch 1 -> 9 windows per file
    depleted, consumed = simulate_consumption(
        [100, 100], [dict(steps=18, batch_size=1, slice_count=1, ctx=10,
                          grad_accumulation=1, interleave_size=2,
                          token_patch_size=1)])
    assert depleted == [True, True]
    assert consumed == [90, 90]


def test_to_global_feeds_mesh(eight_devices):
    import jax
    from homebrewnlp_tpu.parallel import make_mesh
    cfg = mixer_config(train_batch_size=8)
    mesh = make_mesh(cfg)
    batch = synthetic_text_batch(cfg)
    global_batch = to_global(batch, cfg, mesh)
    x = global_batch["token_x"]
    assert x.x.shape == (8, 16, 1)
    assert len(x.x.addressable_shards) == 8
    np.testing.assert_array_equal(np.asarray(x.x), batch["token_x"])


def test_video_pipeline(tmp_path):
    cv2 = pytest.importorskip("cv2")
    from homebrewnlp_tpu.data import write_video_tfrecords
    from homebrewnlp_tpu.data.video import VideoPipeline
    cfg = mixer_config(model_mode="jannet", use_video=True, use_language=False,
                       frame_height=32, frame_width=32, patch_size=16,
                       sequence_length=4, experts=1)
    paths = write_video_tfrecords(str(tmp_path), 2, 12, cfg, seed=11)
    pipe = VideoPipeline(cfg, sub_batch_size=2, paths=paths)
    batch = next(iter(pipe))
    # 3 axes: [B, t+1, hp, wp, color*patch^2]
    assert batch["frame"].shape == (2, 5, 2, 2, 16 * 16 * 3)
    assert batch["vid_msk_src"].shape == (2, 4)
    assert batch["cat_mask_x"].dtype == bool
    # first frame of each file is concat -> mask False somewhere
    assert not batch["cat_mask_x"].all() or not batch["cat_mask_y"].all()


def test_video_pipeline_exact_resume(tmp_path):
    """Resume mid-file reproduces the uninterrupted stream (window-level
    cursor, round-1 only kept the file index)."""
    cv2 = pytest.importorskip("cv2")
    from homebrewnlp_tpu.data import write_video_tfrecords
    from homebrewnlp_tpu.data.video import VideoPipeline
    cfg = mixer_config(model_mode="jannet", use_video=True, use_language=False,
                       frame_height=32, frame_width=32, patch_size=16,
                       sequence_length=4, experts=1)
    paths = write_video_tfrecords(str(tmp_path), 2, 30, cfg, seed=3)

    pipe = VideoPipeline(cfg, sub_batch_size=2, paths=paths)
    it = iter(pipe)
    batches = [next(it) for _ in range(5)]
    state = pipe.state_dict()
    assert state["windows_done"] > 0 or state["file_idx"] > 0
    expected = [next(it) for _ in range(3)]

    pipe2 = VideoPipeline(cfg, sub_batch_size=2, paths=paths)
    pipe2.load_state_dict(state)
    it2 = iter(pipe2)
    for want in expected:
        got = next(it2)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k], err_msg=k)


def _write_video_shard_with_bad_frame(tmp_path, cfg, n_frames, bad_index):
    """One video shard where frame ``bad_index`` carries undecodable JPEG
    bytes (valid Example framing, garbage payload)."""
    import cv2
    rng = np.random.default_rng(5)
    path = str(tmp_path / "video0000.tfrecord")
    with RecordWriter(path) as w:
        for j in range(n_frames):
            if j == bad_index:
                frame_bytes = b"\xff\xd8 definitely not a jpeg"
            else:
                img = rng.integers(0, 256, (cfg.frame_height, cfg.frame_width,
                                            cfg.color_channels), np.uint8)
                ok, enc = cv2.imencode(".jpg", img)
                assert ok
                frame_bytes = enc.tobytes()
            w.write(encode_example({"frame": frame_bytes,
                                    "concat": [int(j == 0)],
                                    "skip_frame": [0]}))
    return [path]


def test_video_corrupt_budget_skips_frame_and_counts(tmp_path):
    """ISSUE satellite (ROADMAP reliability item): a per-frame decode error
    under corrupt_record_budget becomes a SKIPPED frame (zero payload,
    vid masks False — the shape the model already handles), counted on
    hbnlp_corrupt_records_total{pipeline="video"}; alignment and batch
    count are unaffected."""
    pytest.importorskip("cv2")
    from homebrewnlp_tpu.data.video import VideoPipeline
    from homebrewnlp_tpu.obs.registry import REGISTRY
    cfg = mixer_config(model_mode="jannet", use_video=True, use_language=False,
                       frame_height=32, frame_width=32, patch_size=16,
                       sequence_length=4, experts=1, corrupt_record_budget=3)
    paths = _write_video_shard_with_bad_frame(tmp_path, cfg, 12, bad_index=6)
    counter = REGISTRY.counter("hbnlp_corrupt_records_total",
                               labelnames=("pipeline",))
    before = counter.value(pipeline="video")
    pipe = VideoPipeline(cfg, sub_batch_size=2, paths=paths)
    it = iter(pipe)
    batch = next(it)
    assert counter.value(pipeline="video") == before + 1
    assert pipe.budget is not None and pipe.budget.spent == 1
    # windows 0 and 1 cover frames 0..4 and 4..8: the bad frame (6) lands in
    # window 1 at position 2, masked exactly like a real skip-frame
    assert batch["frame"].shape[0] == 2
    assert not batch["vid_msk_src"][1].all()
    assert batch["vid_msk_src"][0].all()
    # the substituted frame is all-zero payload
    assert (batch["frame"][1][2] == 0).all()


def test_video_strict_without_budget_raises(tmp_path):
    pytest.importorskip("cv2")
    from homebrewnlp_tpu.data.video import VideoPipeline
    cfg = mixer_config(model_mode="jannet", use_video=True, use_language=False,
                       frame_height=32, frame_width=32, patch_size=16,
                       sequence_length=4, experts=1, corrupt_record_budget=0)
    paths = _write_video_shard_with_bad_frame(tmp_path, cfg, 12, bad_index=2)
    with pytest.raises(ValueError, match="undecodable"):
        next(iter(VideoPipeline(cfg, sub_batch_size=2, paths=paths)))


def test_video_budget_exhaustion_raises(tmp_path):
    """A rotting shard (more bad frames than budget) must surface, not be
    papered over."""
    pytest.importorskip("cv2")
    import cv2
    from homebrewnlp_tpu.data.video import VideoPipeline
    cfg = mixer_config(model_mode="jannet", use_video=True, use_language=False,
                       frame_height=32, frame_width=32, patch_size=16,
                       sequence_length=4, experts=1, corrupt_record_budget=1)
    rng = np.random.default_rng(5)
    path = str(tmp_path / "video0000.tfrecord")
    with RecordWriter(path) as w:
        for j in range(12):
            if j in (3, 4):
                frame_bytes = b"garbage"
            else:
                ok, enc = cv2.imencode(".jpg", rng.integers(
                    0, 256, (cfg.frame_height, cfg.frame_width,
                             cfg.color_channels), np.uint8))
                frame_bytes = enc.tobytes()
            w.write(encode_example({"frame": frame_bytes,
                                    "concat": [int(j == 0)],
                                    "skip_frame": [0]}))
    with pytest.raises(OSError, match="budget exhausted"):
        list(VideoPipeline(cfg, sub_batch_size=2, paths=[path]))


def test_video_parallel_decode_matches_serial(tmp_path):
    cv2 = pytest.importorskip("cv2")
    from homebrewnlp_tpu.data import write_video_tfrecords
    from homebrewnlp_tpu.data.video import VideoPipeline
    cfg_s = mixer_config(model_mode="jannet", use_video=True,
                         use_language=False, frame_height=32, frame_width=32,
                         patch_size=16, sequence_length=4, experts=1)
    cfg_p = mixer_config(model_mode="jannet", use_video=True,
                         use_language=False, frame_height=32, frame_width=32,
                         patch_size=16, sequence_length=4, experts=1,
                         parallel_interleave=4)
    paths = write_video_tfrecords(str(tmp_path), 1, 25, cfg_s, seed=7)
    serial = []
    it_s = iter(VideoPipeline(cfg_s, sub_batch_size=2, paths=paths))
    for _ in range(3):
        serial.append(next(it_s))
    par_pipe = VideoPipeline(cfg_p, sub_batch_size=2, paths=paths)
    assert par_pipe._workers == 4
    parallel = []
    it = iter(par_pipe)
    for _ in range(len(serial)):
        parallel.append(next(it))
    for a, b in zip(serial, parallel):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_prefetcher_passthrough_and_resume(tmp_path):
    from homebrewnlp_tpu.data.pipeline import Prefetcher
    paths = write_text_tfrecords(str(tmp_path), 3, 4, 64, seed=5)
    cfg = mixer_config(sequence_length=16)

    plain = GptPipeline(cfg, sub_batch_size=2, paths=paths)
    want = [dict(b) for _, b in zip(range(6), plain)]

    pre = Prefetcher(GptPipeline(cfg, sub_batch_size=2, paths=paths), depth=3)
    it = iter(pre)
    got = [next(it) for _ in range(4)]
    state = pre.state_dict()
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a["token_x"], b["token_x"])

    # resume: state reflects the last *delivered* batch, not queue contents
    pre2 = Prefetcher(GptPipeline(cfg, sub_batch_size=2, paths=paths), depth=3)
    pre2.load_state_dict(state)
    it2 = iter(pre2)
    np.testing.assert_array_equal(next(it2)["token_x"], want[4]["token_x"])
    np.testing.assert_array_equal(next(it2)["token_x"], want[5]["token_x"])


def test_device_feeder_matches_sync_order(tmp_path, eight_devices):
    """Background-thread device prefetch delivers the exact batch sequence
    of the synchronous (depth=0) path — ordering is a correctness invariant
    (ISSUE 2 prefetcher coverage)."""
    from homebrewnlp_tpu.data.feed import DeviceFeeder
    from homebrewnlp_tpu.parallel import make_mesh
    cfg = mixer_config(interleaved_datasets=2)
    paths = write_text_tfrecords(str(tmp_path), 3, 2, 100, seed=5)
    mesh = make_mesh(cfg)
    sync = DeviceFeeder(iter(GptPipeline(cfg, 2, paths=paths)), cfg, mesh,
                        depth=0)
    want = [np.asarray(next(sync)["token_x"].x).copy() for _ in range(5)]
    feeder = DeviceFeeder(iter(GptPipeline(cfg, 2, paths=paths)), cfg, mesh,
                          depth=2)
    got = [np.asarray(next(feeder)["token_x"].x).copy() for _ in range(5)]
    feeder.close()
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


def test_device_feeder_stopiteration_and_shutdown(tmp_path, eight_devices):
    """Exhaustion propagates as StopIteration (after every real batch was
    delivered) and close() leaves no live producer thread."""
    import threading
    from homebrewnlp_tpu.data.feed import DeviceFeeder
    from homebrewnlp_tpu.parallel import make_mesh
    cfg = mixer_config(interleaved_datasets=1)
    # 1 file x 1 record x 70 tokens -> 4 windows -> two 2-row batches
    paths = write_text_tfrecords(str(tmp_path), 1, 1, 70, seed=3)
    mesh = make_mesh(cfg)
    feeder = DeviceFeeder(iter(GptPipeline(cfg, 2, paths=paths)), cfg, mesh,
                          depth=2)
    batches = []
    with pytest.raises(StopIteration):
        for _ in range(10):
            batches.append(next(feeder))
    assert len(batches) == 2
    # iterator contract: exhaustion re-raises on EVERY later next() — the
    # one-shot DONE sentinel must not leave a second call deadlocked on an
    # empty queue with a dead producer
    with pytest.raises(StopIteration):
        next(feeder)
    feeder.close()
    assert not any(t.name == "device-feeder" and t.is_alive()
                   for t in threading.enumerate())
    # a producer-side error (not exhaustion) surfaces to the consumer too
    def boom():
        yield {"token_x": np.zeros((2, 16, 1), np.int32),
               "token_y": np.zeros((2, 16, 1), np.int32)}
        raise RuntimeError("decode failed")
    f2 = DeviceFeeder(boom(), cfg, mesh, depth=1)
    next(f2)
    with pytest.raises(RuntimeError, match="decode failed"):
        next(f2)
    with pytest.raises(RuntimeError, match="decode failed"):
        next(f2)  # errors also re-raise instead of deadlocking
    f2.close()


def test_device_feeder_resume_cursor_consumed_only(tmp_path, eight_devices):
    """state_dict under prefetch depth 2 reflects CONSUMED batches only:
    resuming from it continues with exactly the next undelivered batch,
    even though the producer ran ahead."""
    from homebrewnlp_tpu.data.feed import DeviceFeeder
    from homebrewnlp_tpu.parallel import make_mesh
    cfg = mixer_config(interleaved_datasets=2)
    paths = write_text_tfrecords(str(tmp_path), 3, 2, 120, seed=9)
    mesh = make_mesh(cfg)
    want = [b["token_x"].copy()
            for _, b in zip(range(6), GptPipeline(cfg, 2, paths=paths))]

    pipe = GptPipeline(cfg, 2, paths=paths)
    feeder = DeviceFeeder(iter(pipe), cfg, mesh, depth=2,
                          state_fn=pipe.state_dict)
    for i in range(3):
        np.testing.assert_array_equal(
            np.asarray(next(feeder)["token_x"].x), want[i])
    state = feeder.state_dict()
    feeder.close()

    pipe2 = GptPipeline(cfg, 2, paths=paths)
    pipe2.load_state_dict(state)
    feeder2 = DeviceFeeder(iter(pipe2), cfg, mesh, depth=2,
                           state_fn=pipe2.state_dict)
    for i in (3, 4, 5):
        np.testing.assert_array_equal(
            np.asarray(next(feeder2)["token_x"].x), want[i])
    feeder2.close()


def test_prefetcher_close_joins_blocked_producer(tmp_path):
    """Prefetcher.close() unjams a producer parked on a full queue and
    wakes a consumer parked on an empty one (the async loop's shutdown
    path)."""
    import threading
    from homebrewnlp_tpu.data.pipeline import Prefetcher
    paths = write_text_tfrecords(str(tmp_path), 3, 4, 64, seed=5)
    cfg = mixer_config(sequence_length=16)
    before = {id(t) for t in threading.enumerate()}
    pre = Prefetcher(GptPipeline(cfg, sub_batch_size=2, paths=paths), depth=1)
    it = iter(pre)
    next(it)  # starts the producer; queue depth 1 fills, producer parks
    pre.close()
    leaked = [t for t in threading.enumerate()
              if id(t) not in before and t.is_alive()]
    assert not leaked


def test_remote_fs_tfrecord_roundtrip():
    """TFRecord write/read/glob through a remote (memory://) filesystem —
    the gs:// path type-checks through the same fsspec route."""
    fsspec = pytest.importorskip("fsspec")
    from homebrewnlp_tpu.data import fs
    from homebrewnlp_tpu.data.tfrecord import RecordWriter

    base = "memory://bucket/shards"
    for i in range(2):
        with RecordWriter(f"{base}/part{i}_128.tfrecord") as w:
            w.write(encode_example({"text": bytes(range(10))}))
            w.write(encode_example({"text": bytes(range(10, 20))}))

    found = sorted(fs.glob(f"{base}/part*_128.tfrecord"))
    assert len(found) == 2 and all(p.startswith("memory://") for p in found)
    payloads = list(read_records(found[0], verify=True))
    assert len(payloads) == 2
    ex = decode_example(payloads[1])
    assert ex["text"][0] == bytes(range(10, 20))
    assert count_records(found[1]) == 2


def test_remote_fs_pipeline_reads_remote_glob():
    fsspec = pytest.importorskip("fsspec")
    from homebrewnlp_tpu.data.tfrecord import RecordWriter
    rng = np.random.default_rng(0)
    for i in range(2):
        with RecordWriter(f"memory://data/sh{i}_256.tfrecord") as w:
            w.write(encode_example(
                {"text": bytes(rng.integers(0, 255, 256, np.uint8).tolist())}))
    cfg = mixer_config(sequence_length=16, dataset_configs=[
        {"type": "text", "path": "memory://data/sh*_256.tfrecord"}])
    pipe = GptPipeline(cfg, sub_batch_size=2)
    batch = next(iter(pipe))
    assert batch["token_x"].shape == (2, 16, 1)


def test_put_with_retry_memory():
    fsspec = pytest.importorskip("fsspec")
    import tempfile, os
    from homebrewnlp_tpu.data import fs
    with tempfile.NamedTemporaryFile(delete=False) as f:
        f.write(b"payload")
        local = f.name
    try:
        fs.put_with_retry(local, "memory://up/loads/x.bin", retries=2)
        with fs.open_stream("memory://up/loads/x.bin") as r:
            assert r.read() == b"payload"
        fs.write_with_retry("memory://up/loads/y.txt", b"hi")
        with fs.open_stream("memory://up/loads/y.txt") as r:
            assert r.read() == b"hi"
    finally:
        os.unlink(local)


def test_local_row_slice_two_process_layout():
    """Property test of the multi-host feed arithmetic against a simulated
    2-process x 4-device layout: reassembling every device's slice from the
    per-process local batches must reproduce the global batch exactly."""
    from homebrewnlp_tpu.data.feed import local_row_slice

    global_rows, n_proc = 8, 2
    local = global_rows // n_proc  # 4 rows per process
    data = np.arange(global_rows * 3).reshape(global_rows, 3)
    host_batches = [data[p * local:(p + 1) * local] for p in range(n_proc)]

    # 8 devices, data axis 8: each device requests one global row; devices
    # 0-3 live on process 0, 4-7 on process 1.  The caller passes each
    # process's span start (data/feed.py::to_global derives it from the
    # process's data-axis coordinates)
    for dev in range(8):
        index = (slice(dev, dev + 1), slice(None))
        proc = dev // 4
        rows = local_row_slice(index, local, global_rows, proc * local)
        np.testing.assert_array_equal(host_batches[proc][rows],
                                      data[dev:dev + 1])

    # data axis 4 (2 rows per device), 2 devices per process
    for dev in range(4):
        index = (slice(dev * 2, dev * 2 + 2), slice(None))
        proc = dev // 2
        rows = local_row_slice(index, local, global_rows, proc * local)
        np.testing.assert_array_equal(host_batches[proc][rows],
                                      data[dev * 2:dev * 2 + 2])

    # a request outside the process's span is rejected, not silently wrong
    with pytest.raises(ValueError):
        local_row_slice((slice(2, 6), slice(None)), local, global_rows, 4)

    # replicated batch (no data sharding): every device asks for everything —
    # only valid single-process; the span guard fires for 2 procs
    with pytest.raises(ValueError):
        local_row_slice((slice(0, 8), slice(None)), local, global_rows, 0)
    assert local_row_slice((slice(0, 8), slice(None)), 8, 8) == slice(0, 8)


def test_metric_writer_scalars_and_histograms(tmp_path):
    import json as jsonlib
    from homebrewnlp_tpu.train.metrics import MetricWriter
    w = MetricWriter(str(tmp_path))
    w.write(0, {"loss": 1.5, "grad_hist/x": np.array([0, 3, 5, 1]),
                "grad_norm/x": np.float32(2.0)})
    w.close()
    line = jsonlib.loads((tmp_path / "metrics.jsonl").read_text().splitlines()[0])
    assert line["loss"] == 1.5 and line["grad_norm/x"] == 2.0
    assert "grad_hist/x" not in line  # vectors go to TB only


def test_bench_guard_threshold_logic():
    """bench.evaluate_guard: 10k-acceptance-record thresholds at full
    length (docs/perf/32ctx_10k_run.md: 7.71 -> 3.45@100 -> 2.76@300),
    reach-what-you-ran semantics for short development runs."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import evaluate_guard

    def rows(pairs):
        return [{"step": s, "loss": l} for s, l in pairs]

    healthy = rows([(1, 7.71), (60, 3.9), (120, 3.3), (300, 2.8)])
    assert evaluate_guard(healthy, 300)["pass"]
    # short dev run: only the reached checkpoints are asserted
    assert evaluate_guard(rows([(1, 7.77), (50, 5.9)]), 50)["pass"]
    # not decreasing -> fail even short
    assert not evaluate_guard(rows([(1, 7.77), (50, 7.9)]), 50)["pass"]
    # bad init (loaded checkpoint instead of fresh) -> fail
    assert not evaluate_guard(rows([(1, 3.0), (300, 2.5)]), 300)["pass"]
    # the LR-0.01 instability signature (docs/perf/32ctx_real_run.md:
    # regression toward 5-8 after warmup) -> fail at full length
    stalled = rows([(1, 7.77), (120, 5.7), (300, 5.7)])
    assert not evaluate_guard(stalled, 300)["pass"]
    # stalls above the 300-step bar -> fail
    assert not evaluate_guard(rows([(1, 7.71), (120, 4.2), (300, 4.0)]),
                              300)["pass"]


def test_bench_guard_refuses_synthetic_fallback(tmp_path):
    """bench.ensure_real_corpus: missing corpus triggers the injectable
    builder; a builder that fails (or produces nothing) yields a structured
    refusal instead of letting the guard train on synthetic noise (the
    round-5 post-mortem, docs/perf/README.md round 5d)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import ensure_real_corpus

    pattern = str(tmp_path / "corpus" / "*.tfrecord")
    # builder that fails outright -> structured error, no exception
    res = ensure_real_corpus(pattern,
                             builder=lambda: (_ for _ in ()).throw(
                                 RuntimeError("roots missing")))
    assert res is not None and not res["pass"] and "rebuild failed" in res["error"]
    # builder that "succeeds" but produces nothing -> refusal
    res = ensure_real_corpus(pattern, builder=lambda: None)
    assert res is not None and not res["pass"] and "synthetic" in res["error"]
    # builder that creates the files -> None (guard proceeds on real data)
    def build():
        os.makedirs(tmp_path / "corpus", exist_ok=True)
        (tmp_path / "corpus" / "a.tfrecord").write_bytes(b"x")
    res = ensure_real_corpus(pattern, builder=build)
    assert res is None
    # files already present -> builder not invoked
    res = ensure_real_corpus(pattern, builder=lambda: (_ for _ in ()).throw(
        AssertionError("must not be called")))
    assert res is None


def test_repeat_dataset_epoch_wraparound(tmp_path):
    """repeat_dataset=true: the sequential reader wraps deterministically at
    the epoch boundary (same window order every epoch), and the resume
    cursor keeps working across it — the reference's sequential path dies
    on exhaustion here (inputs.py:540-541)."""
    from homebrewnlp_tpu.data.synthetic import write_text_tfrecords

    cfg = mixer_config(sequence_length=8, token_patch_size=1,
                       use_random_dataloader=False, repeat_dataset=True,
                       interleaved_datasets=2)
    paths = write_text_tfrecords(str(tmp_path), n_files=2,
                                 records_per_file=1, tokens_per_record=64,
                                 seed=3)
    pipe = GptPipeline(cfg, sub_batch_size=2, paths=paths)
    it = iter(pipe)
    # one epoch = 2 files x 64 tokens -> 14 windows of 9 -> 7 batches of 2
    epoch1 = [next(it)["token_x"].copy() for _ in range(7)]
    epoch2 = [next(it)["token_x"].copy() for _ in range(7)]
    for a, b in zip(epoch1, epoch2):
        np.testing.assert_array_equal(a, b)
    # single-epoch default (reference rule): same config without the knob
    cfg1 = mixer_config(sequence_length=8, token_patch_size=1,
                        use_random_dataloader=False,
                        interleaved_datasets=2)
    it1 = iter(GptPipeline(cfg1, sub_batch_size=2, paths=paths))
    n = sum(1 for _ in it1)
    assert n == 7
