"""Serving SLO tests (docs/observability.md "Serving SLOs"): the shared
quantile estimators, per-request phase records, the sampler TTFT hook
(rebuild + KV-cache paths), the engine-queue deadline 503, graftload's
client-vs-server reconciliation, and the bench serving ratchet."""
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from homebrewnlp_tpu.infer.kv_cache import cache_eligible, \
    make_cached_text_sampler
from homebrewnlp_tpu.infer.sampler import make_text_sampler
from homebrewnlp_tpu.models import init_params
from homebrewnlp_tpu.nd import NT
from homebrewnlp_tpu.obs import exporter as obs_exporter
from homebrewnlp_tpu.obs.registry import (DEFAULT_BUCKETS, MetricsRegistry,
                                          bucket_quantile, bucket_width_at,
                                          sample_quantile)
from homebrewnlp_tpu.obs.spans import SpanTracer
from homebrewnlp_tpu.serve import QueueDeadlineExceeded, serve
from homebrewnlp_tpu.serve import slo as slo_mod
from homebrewnlp_tpu.serve.interface import (CompletionEngine,
                                             InterfaceWrapper, TEXT_AXES)
from homebrewnlp_tpu.serve.slo import RequestRecord, ServeSLO
from homebrewnlp_tpu.utils import random_text_batch

from .backend import mixer_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import graftload  # noqa: E402


def _small_cfg(**over):
    base = dict(depth=1, sequence_length=12, heads=2, features_per_head=16,
                vocab_size=32, train_batch_size=1,
                initial_autoregressive_position=4, sampling_temperature=0.0,
                use_autoregressive_sampling=True)
    base.update(over)
    return mixer_config(**base)


@pytest.fixture(scope="module")
def cfg_params():
    cfg = _small_cfg()
    params, _ = init_params(cfg, random_text_batch(cfg))
    return cfg, params


# -- shared quantile estimators ----------------------------------------------

def test_bucket_quantile_empty_is_none():
    assert bucket_quantile((1.0, 2.0), [0, 0, 0], 0.5) is None


def test_bucket_quantile_interpolates_inside_bucket():
    # 10 observations all in (1, 2]: the median interpolates to 1.5
    assert bucket_quantile((1.0, 2.0, 4.0), [0, 10, 0, 0], 0.5) == \
        pytest.approx(1.5)
    # first bucket's lower edge is 0
    assert bucket_quantile((1.0, 2.0), [10, 0, 0], 0.5) == pytest.approx(0.5)


def test_bucket_quantile_inf_bucket_clamps_to_last_edge():
    # every observation beyond the finite buckets: the estimator cannot
    # invent values it has no resolution for
    assert bucket_quantile((1.0, 2.0), [0, 0, 7], 0.99) == 2.0


def test_bucket_quantile_spanning_buckets():
    # 4 in (0,1], 4 in (1,2]: p75 ranks 6 of 8 -> middle of second bucket
    assert bucket_quantile((1.0, 2.0), [4, 4, 0], 0.75) == pytest.approx(1.5)


def test_sample_quantile_matches_numpy():
    rng = np.random.RandomState(0)
    xs = rng.exponential(size=101).tolist()
    for q in (0.0, 0.25, 0.5, 0.95, 1.0):
        assert sample_quantile(xs, q) == pytest.approx(
            float(np.quantile(xs, q)))
    assert sample_quantile([], 0.5) is None


def test_bucket_width_at():
    buckets = (1.0, 2.0, 4.0)
    assert bucket_width_at(buckets, 0.5) == 1.0
    assert bucket_width_at(buckets, 1.5) == 1.0
    assert bucket_width_at(buckets, 3.0) == 2.0
    assert bucket_width_at(buckets, 10.0) == float("inf")


def test_histogram_quantile_and_label_aggregation():
    reg = MetricsRegistry()
    hist = reg.histogram("t_q_seconds", "x", labelnames=("path",),
                         buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5):
        hist.labels(path="/a").observe(v)
    hist.labels(path="/b").observe(3.0)
    # per-child quantile vs the aggregate-across-children view
    assert hist.quantile(0.5, path="/a") == pytest.approx(
        bucket_quantile((1.0, 2.0, 4.0), [1, 2, 0, 0], 0.5))
    agg = hist.quantile(0.99)
    assert agg is not None and agg > hist.quantile(0.99, path="/a")
    assert hist.quantile(0.5, path="/missing") is None
    plain = reg.histogram("t_q2_seconds", "x", buckets=(1.0,))
    assert plain.quantile(0.5) is None  # never observed
    plain.observe(0.25)
    assert plain.quantile(0.5) == pytest.approx(0.5)


# -- retroactive spans --------------------------------------------------------

def test_span_add_records_retroactively_and_swaps_reversed_stamps():
    tracer = SpanTracer(mirror_jax=False)
    t0 = time.perf_counter()
    tracer.add("serve/queue_wait", t0, t0 + 0.25, id=3)
    tracer.add("serve/decode", t0 + 0.5, t0 + 0.3)  # reversed -> swapped
    totals = tracer.phase_totals()
    assert totals["serve/queue_wait"] == pytest.approx(0.25, abs=1e-6)
    assert totals["serve/decode"] == pytest.approx(0.2, abs=1e-6)
    names = [e["name"] for e in tracer.chrome_events()
             if e.get("ph") == "X"]
    assert "serve/queue_wait" in names and "serve/decode" in names


# -- per-request records ------------------------------------------------------

def test_request_record_phase_math():
    rec = RequestRecord(1, "/token_completion")
    rec.mark_parsed()
    rec.mark_enqueued(queue_depth=2)
    rec.mark_started()
    rec.mark_first_token(7)
    rec.tokens_generated = 5
    rec.mark_engine_done()
    rec.mark_finished(200)
    for phase in (rec.e2e_s(), rec.parse_s(), rec.queue_wait_s(),
                  rec.ttft_s(), rec.prefill_s(), rec.decode_s(),
                  rec.engine_s()):
        assert phase is not None and phase >= 0.0
    assert rec.ttft_s() >= rec.prefill_s()  # TTFT is arrival-anchored
    assert rec.queue_depth == 2 and rec.status == 200
    assert rec.decode_tokens_per_sec() is not None


def test_request_record_first_token_first_stamp_wins():
    rec = RequestRecord(2)
    rec.mark_first_token()
    first = rec.t_first_token
    time.sleep(0.001)
    rec.mark_first_token()
    assert rec.t_first_token == first


def test_request_record_missing_stamps_yield_none():
    rec = RequestRecord(3)
    assert rec.ttft_s() is None and rec.queue_wait_s() is None
    rec.tokens_generated = 1
    rec.mark_started()
    rec.mark_first_token()
    rec.mark_engine_done()
    # one generated token belongs to prefill: no decode rate
    assert rec.decode_tokens_per_sec() is None


def test_serve_slo_finish_observes_phases_and_summary():
    reg = MetricsRegistry()
    s = ServeSLO(reg)
    rec = s.begin("/token_completion")
    assert s.inflight() == 1
    rec.mark_parsed()
    rec.mark_enqueued(queue_depth=0)
    rec.mark_started()
    rec.mark_first_token()
    rec.tokens_generated = 4
    rec.mark_engine_done()
    s.requests.labels(method="POST", path="/token_completion",
                      status="200").inc()
    s.finish(rec, 200)
    assert s.inflight() == 0
    assert s.ttft.count() == 1 and s.queue_wait.count() == 1
    assert s.engine.count() == 1 and s.decode_rate.count() == 1
    summary = s.summary()
    assert summary["requests_total"] == 1
    assert summary["error_rate"] == 0.0
    for key in ("ttft_s", "queue_wait_s", "engine_s"):
        assert set(summary[key]) == {"p50", "p95", "p99"}
    # a 5xx moves the error rate
    s.requests.labels(method="POST", path="/token_completion",
                      status="503").inc()
    assert s.summary()["error_rate"] == pytest.approx(0.5)


def test_serve_slo_rejected_request_feeds_queue_wait():
    """A deadline-503'd request spent real time in the queue; that wait
    must reach the queue-wait histogram or the SLO reads healthy exactly
    under overload."""
    reg = MetricsRegistry()
    s = ServeSLO(reg)
    rec = s.begin("/token_completion")
    rec.mark_parsed()
    rec.mark_enqueued(queue_depth=4)
    time.sleep(0.02)  # queued, never claimed
    s.finish(rec, 503)
    assert s.queue_wait.count() == 1
    assert s.queue_wait.quantile(0.5) > 0
    # shed at admission (never enqueued): nothing to observe
    rec2 = s.begin("/token_completion")
    rec2.mark_parsed()
    s.finish(rec2, 503)
    assert s.queue_wait.count() == 1


def test_serve_slo_retry_after_prices_backlog():
    reg = MetricsRegistry()
    s = ServeSLO(reg)
    # no engine history: the deadline is the only hint
    assert s.retry_after_s(4.2) == 5
    assert s.retry_after_s(0.0) == 1
    s.engine.observe(2.0)
    s.set_queue_probe(lambda: 3)
    # backlog (3 queued) x ~2s median engine time
    assert s.retry_after_s(1.0) >= 6
    # queued handlers are ALSO in-flight: backlog takes the larger view,
    # never the sum — 3 queued + 1 executing + me = 5 in-flight, and the
    # true drain is max(3, 5-1) = 4 engine turns, not 8
    recs = [s.begin("/token_completion") for _ in range(5)]
    import math as _math
    assert s.retry_after_s(1.0) == _math.ceil(4 * s.engine.quantile(0.5))
    for r in recs:
        s.finish(r, 200)


def test_summary_e2e_covers_completion_paths_only():
    """Fast /encode/probe/404 requests share the e2e histogram (path
    label) but carry no phases; folding them into the slo block's e2e_s
    would drag it below engine_s and make e2e − engine meaningless."""
    reg = MetricsRegistry()
    s = ServeSLO(reg)
    for _ in range(50):  # sub-ms noise on a non-completion path
        s.e2e.labels(path="/encode").observe(0.001)
    s.e2e.labels(path="/token_completion").observe(2.0)
    s.e2e.labels(path="/completion").observe(2.0)
    p50 = s.summary()["e2e_s"]["p50"]
    assert p50 > 1.0  # completion-only, not dominated by the /encode swarm
    # no completion traffic at all -> no e2e block, not a misleading one
    s2 = ServeSLO(MetricsRegistry())
    s2.e2e.labels(path="/encode").observe(0.001)
    assert s2.summary()["e2e_s"] is None


def test_serve_slo_registration_is_idempotent():
    reg = MetricsRegistry()
    a, b = ServeSLO(reg), ServeSLO(reg)
    assert a.ttft is b.ttft  # same series, not a duplicate


def test_slo_latency_buckets_cover_slow_hosts():
    """The committed CPU bench operating point sits past 60 s; every
    latency histogram needs finite buckets beyond it or server percentiles
    clamp to 60 and serialization overhead becomes clamp error."""
    s = ServeSLO(MetricsRegistry())
    for hist in (s.ttft, s.queue_wait, s.engine, s.e2e):
        assert max(b for b in hist.buckets if b != float("inf")) >= 600.0
    for _ in range(10):
        s.engine.observe(90.0)
    assert 60.0 < s.engine.quantile(0.5) <= 120.0


def test_clear_queue_probe_is_ownership_checked():
    s = ServeSLO(MetricsRegistry())
    mine, theirs = (lambda: 3), (lambda: 7)
    s.set_queue_probe(mine)
    s.clear_queue_probe(theirs)  # someone else's probe: no-op
    assert s.queue_depth() == 3
    s.clear_queue_probe(mine)
    assert s.queue_depth() == 0


def test_server_close_detaches_queue_probe(cfg_params):
    """The registry outlives the server; a still-bound probe would pin
    wrapper -> engine -> params for the process lifetime."""
    cfg, params = cfg_params
    reg = MetricsRegistry()
    server = serve(cfg, params, port=0, background=True, registry=reg)
    assert server.slo.queue_depth() == 0 and server._slo_probe is not None
    server.shutdown()
    server.server_close()
    assert server._slo_probe is None
    assert server.slo._queue_probe is None


# -- sampler TTFT hook --------------------------------------------------------

def test_rebuild_sampler_first_token_fires_exactly_once(cfg_params):
    cfg, params = cfg_params
    fires = []
    sampler = make_text_sampler(
        cfg, params, first_token_callback=lambda tag, tok:
        fires.append((int(tag), int(tok))))
    toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
    toks[0, :4, 0] = [5, 9, 3, 7]
    out = np.asarray(sampler(NT(jax.numpy.asarray(toks), TEXT_AXES),
                             np.int32(4), np.float32(0.0), jax.random.key(0),
                             np.int32(cfg.sequence_length), np.int32(17)))
    jax.effects_barrier()
    assert len(fires) == 1
    tag, tok = fires[0]
    assert tag == 17
    assert tok == int(out[0, 4, 0])  # the FIRST generated position


def test_rebuild_sampler_full_prompt_never_fires(cfg_params):
    cfg, params = cfg_params
    fires = []
    sampler = make_text_sampler(
        cfg, params, first_token_callback=lambda tag, tok:
        fires.append(int(tag)))
    toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
    # end == initial_pos: nothing to generate, so no first token exists
    np.asarray(sampler(NT(jax.numpy.asarray(toks), TEXT_AXES), np.int32(6),
                       np.float32(0.0), jax.random.key(0), np.int32(6),
                       np.int32(9)))
    jax.effects_barrier()
    assert fires == []


def test_kv_sampler_first_token_fires_once_on_cached_prefill(cfg_params):
    cfg, params = cfg_params
    assert cache_eligible(cfg)
    fires = []
    sampler = make_cached_text_sampler(
        cfg, params, first_token_callback=lambda tag, tok:
        fires.append((int(tag), int(tok))))
    toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
    toks[0, :4, 0] = [5, 9, 3, 7]
    out = np.asarray(sampler(NT(jax.numpy.asarray(toks), TEXT_AXES),
                             np.int32(4), np.float32(0.0), jax.random.key(0),
                             np.int32(cfg.sequence_length), np.int32(23)))
    jax.effects_barrier()
    assert len(fires) == 1
    tag, tok = fires[0]
    assert tag == 23
    assert tok == int(out[0, 4, 0])


def test_kv_sampler_empty_prompt_fires_once(cfg_params):
    cfg, params = cfg_params
    fires = []
    sampler = make_cached_text_sampler(
        cfg, params, first_token_callback=lambda tag, tok:
        fires.append(int(tag)))
    toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
    np.asarray(sampler(NT(jax.numpy.asarray(toks), TEXT_AXES), np.int32(0),
                       np.float32(0.0), jax.random.key(1),
                       np.int32(cfg.sequence_length), np.int32(4)))
    jax.effects_barrier()
    assert fires == [4]


def test_engine_resolves_ambient_record_to_ttft(cfg_params):
    cfg, params = cfg_params
    engine = CompletionEngine(
        cfg, params, first_token_callback=slo_mod.dispatch_first_token)
    rec = RequestRecord(991)
    slo_mod.register_first_token(rec.rid, rec.mark_first_token)
    prev = slo_mod.set_current(rec)
    try:
        rec.mark_started()
        out = engine.complete_tokens([1, 2, 3], temperature=0.0,
                                     max_tokens=4)
    finally:
        slo_mod.set_current(prev)
        slo_mod.unregister_first_token(rec.rid)
    assert rec.t_first_token is not None
    assert rec.tokens_generated == 4
    assert list(out[:3]) == [1, 2, 3]


def test_dispatch_unknown_tag_is_noop():
    slo_mod.dispatch_first_token(999983, 5)  # must not raise


# -- engine-queue deadline ----------------------------------------------------

class _StubCfg:
    web_workers = 1
    default_sleep_duration = 0.02
    serve_queue_deadline_s = 0.0
    serve_queue_limit = 0


class _StubEngine:
    def __init__(self, sleep_s=0.0):
        self.cfg = _StubCfg()
        self.sleep_s = sleep_s

    def complete_tokens(self, prompt, *a):
        time.sleep(self.sleep_s)
        return list(prompt)


def test_queue_deadline_rejects_instead_of_hanging():
    wrapper = InterfaceWrapper(_StubEngine(sleep_s=1.0), workers=1,
                               sleep_duration=0.02, queue_deadline_s=0.15)
    first = wrapper.complete([1], asynchronous=True)  # occupies the worker
    t0 = time.monotonic()
    with pytest.raises(QueueDeadlineExceeded) as ei:
        wrapper.complete([2])  # queued behind a 1s request, deadline 0.15s
    waited = time.monotonic() - t0
    assert waited < 0.9  # rejected well before the head request finished
    assert ei.value.waited_s >= 0.15 and not ei.value.shed
    assert first() == [1]  # the running request is unaffected
    wrapper.close()


def test_queue_limit_sheds_at_admission():
    wrapper = InterfaceWrapper(_StubEngine(sleep_s=0.5), workers=1,
                               sleep_duration=0.02, queue_limit=1)
    handles = [wrapper.complete([1], asynchronous=True)]
    time.sleep(0.05)  # let the worker claim the first request
    handles.append(wrapper.complete([2], asynchronous=True))  # 1 queued
    with pytest.raises(QueueDeadlineExceeded) as ei:
        wrapper.complete([3])
    assert ei.value.shed
    assert [h() for h in handles] == [[1], [2]]
    wrapper.close()


def test_engine_done_stamped_before_result_is_published():
    """finish() runs the instant fetch() wakes; the worker must stamp
    engine-done before putting the result or the record intermittently
    loses its engine/decode observations."""
    from homebrewnlp_tpu.serve import slo as smod
    wrapper = InterfaceWrapper(_StubEngine(sleep_s=0.01), workers=1,
                               sleep_duration=0.005)
    rec = RequestRecord(1, "/token_completion")
    prev = smod.set_current(rec)
    try:
        assert wrapper.complete([5]) == [5]
    finally:
        smod.set_current(prev)
    assert rec.t_engine_done is not None
    assert rec.engine_s() is not None and rec.engine_s() > 0
    wrapper.close()


def test_queue_depth_excludes_cancelled_jobs():
    """A deadline-cancelled job sits in the internal queue until the busy
    worker pops it; counting those corpses would shed healthy arrivals and
    report phantom backlog for as long as the engine call runs."""
    wrapper = InterfaceWrapper(_StubEngine(sleep_s=0.6), workers=1,
                               sleep_duration=0.02, queue_deadline_s=0.1,
                               queue_limit=1)
    first = wrapper.complete([1], asynchronous=True)  # occupies the worker
    time.sleep(0.05)
    with pytest.raises(QueueDeadlineExceeded):
        wrapper.complete([2])  # queued, then deadline-cancelled
    # the corpse is still in _q (the worker is busy) but no longer pending
    assert wrapper.queue_depth() == 0
    # admission therefore accepts a fresh request instead of shedding it
    second = wrapper.complete([3], asynchronous=True)
    assert first() == [1] and second() == [3]
    wrapper.close()


def test_rest_maps_queue_deadline_to_503_with_retry_after():
    class ShedAPI:
        ENDPOINTS = ("token_completion",)

        def token_completion(self, body):
            raise QueueDeadlineExceeded(0.5, 0.2, 3)

    reg = MetricsRegistry()
    server = serve(None, None, port=0, background=True, api=ShedAPI(),
                   registry=reg)
    try:
        port = server.server_address[1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/token_completion", data=b"{}",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        err = ei.value
        assert err.code == 503
        retry = err.headers.get("Retry-After")
        assert retry is not None and int(retry) >= 1
        body = json.loads(err.read())
        assert body["retry_after_s"] == int(retry)
        # the rejection is a counted, phase-attributed request like any
        # other — but the record lands in the handler's `finally`, AFTER the
        # 503 is on the wire, so wait for it before asserting
        deadline = time.time() + 5.0
        while (time.time() < deadline
               and server.slo.summary()["error_rate"] is None):
            time.sleep(0.01)
        assert server.slo.summary()["error_rate"] == 1.0
    finally:
        server.shutdown()
        server.server_close()


# -- exporter /healthz slo block ----------------------------------------------

def test_exporter_healthz_carries_slo_block():
    reg = MetricsRegistry()
    srv = obs_exporter.start_server(0, registry=reg,
                                    slo_probe=lambda: {"requests_total": 7})
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["slo"] == {"requests_total": 7}
    finally:
        obs_exporter.stop_server(srv)


def test_exporter_healthz_survives_broken_slo_probe():
    reg = MetricsRegistry()

    def boom():
        raise RuntimeError("probe died")

    srv = obs_exporter.start_server(0, registry=reg, slo_probe=boom)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["slo"] is None
    finally:
        obs_exporter.stop_server(srv)


# -- graftload ----------------------------------------------------------------

def test_graftload_corpus_is_deterministic_and_bounded():
    a = graftload.make_corpus(7, 16, vocab=32, min_len=3, max_len=9)
    b = graftload.make_corpus(7, 16, vocab=32, min_len=3, max_len=9)
    assert a == b
    assert graftload.make_corpus(8, 16, vocab=32) != a
    assert all(3 <= len(p) <= 9 for p in a)
    assert all(1 <= t < 32 for p in a for t in p)


def test_graftload_prom_roundtrip_matches_registry_quantile():
    reg = MetricsRegistry()
    hist = reg.histogram("hbnlp_serve_request_seconds", "x",
                         labelnames=("path",))
    for v in (0.004, 0.02, 0.02, 0.3, 1.2):
        hist.labels(path="/token_completion").observe(v)
    hist.labels(path="/other").observe(9.0)
    metrics = graftload.parse_prom(reg.render())
    snap = graftload.histogram_snapshot(
        metrics, "hbnlp_serve_request_seconds",
        {"path": "/token_completion"})
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(1.544)
    for q in (0.5, 0.95):
        assert bucket_quantile(snap["buckets"], snap["counts"], q) == \
            pytest.approx(hist.quantile(q, path="/token_completion"))


def test_graftload_client_report_fields():
    records = [{"id": i, "status": 200, "e2e_s": 0.1 * (i + 1),
                "tokens_generated": 4} for i in range(4)]
    records.append({"id": 4, "status": 503, "e2e_s": 0.01,
                    "tokens_generated": 0})
    rep = graftload.client_report(records, [[0.0, 1], [0.05, 2]], 2.0)
    assert rep["n_requests"] == 5 and rep["n_ok"] == 4
    assert rep["n_rejected"] == 1
    assert rep["error_rate"] == pytest.approx(0.2)
    assert rep["goodput_tok_s"] == pytest.approx(8.0)
    assert rep["e2e_s"]["p50"] == pytest.approx(0.25)
    assert rep["inflight_trace"] == [[0.0, 1], [0.05, 2]]


def test_graftload_write_log_jsonl_and_csv(tmp_path):
    records = [{"id": 0, "t_send_s": 0.0, "e2e_s": 0.5, "status": 200,
                "prompt_len": 3, "tokens_generated": 2}]
    jp = graftload.write_log(records, str(tmp_path / "log.jsonl"))
    assert json.loads(open(jp).read())["status"] == 200
    cp = graftload.write_log(records, str(tmp_path / "log.csv"))
    lines = open(cp).read().splitlines()
    assert lines[0].startswith("id,") and len(lines) == 2


def test_graftload_reconcile_report_tolerance():
    reg = MetricsRegistry()
    hist = reg.histogram("hbnlp_serve_request_seconds", "x",
                         labelnames=("path",))
    eng = reg.histogram("hbnlp_serve_engine_seconds", "x")
    for v in (0.08, 0.09, 0.11):
        hist.labels(path="/token_completion").observe(v)
        eng.observe(v / 2)
    client = {"e2e_s": {"p50": 0.09}}
    rec = graftload.reconcile_report(client, reg.render())
    assert rec["within_tolerance"]
    assert rec["serialization_overhead_s"] >= 0.0
    # a client p50 far outside one bucket + margin must fail
    rec2 = graftload.reconcile_report({"e2e_s": {"p50": 5.0}}, reg.render())
    assert not rec2["within_tolerance"]
    assert graftload.reconcile_report({"e2e_s": None}, reg.render()) \
        .get("skipped")
    # non-200s share the unlabelled server histogram: reconciliation is
    # defined over clean runs only, never flagged under shedding
    dirty = {"e2e_s": {"p50": 0.09}, "error_rate": 0.25}
    assert "skipped" in graftload.reconcile_report(dirty, reg.render())


# -- end to end: REST server + graftload + reconciliation --------------------

@pytest.fixture(scope="module")
def live_server(cfg_params):
    cfg, params = cfg_params
    reg = MetricsRegistry()
    server = serve(cfg, params, port=0, background=True, registry=reg,
                   obs_port=0)
    yield server, cfg
    server.shutdown()
    server.server_close()


def test_graftload_end_to_end_reconciles(live_server, tmp_path):
    server, cfg = live_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    murl = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
    report = graftload.drive(
        url, metrics_url=murl, n_requests=8, concurrency=2,
        vocab=cfg.vocab_size, min_prompt=2, max_prompt=6, response_len=3,
        temperature=0.0, seed=3, log_path=str(tmp_path / "load.jsonl"))
    c = report["client"]
    assert c["n_ok"] == 8 and c["error_rate"] == 0.0
    assert c["e2e_s"]["p50"] > 0
    assert sum(1 for _ in open(report["log_path"])) == 8
    # TTFT and queue wait are reported SEPARATELY (the issue's acceptance)
    assert report["server"]["ttft_s"]["p50"] > 0
    assert "queue_wait_s" in report["server"]
    assert report["reconcile"]["within_tolerance"]
    assert report["reconcile"]["serialization_overhead_s"] >= 0.0


def test_live_healthz_slo_block_and_metrics_series(live_server):
    server, _ = live_server
    obs_port = server._obs_server.server_address[1]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{obs_port}/healthz", timeout=10) as r:
        hz = json.loads(r.read())
    slo = hz["slo"]
    assert slo["requests_total"] >= 8
    assert slo["ttft_s"] is not None and slo["queue_wait_s"] is not None
    with urllib.request.urlopen(
            f"http://127.0.0.1:{obs_port}/metrics", timeout=10) as r:
        text = r.read().decode()
    for name in ("hbnlp_serve_ttft_seconds", "hbnlp_serve_queue_wait_seconds",
                 "hbnlp_serve_engine_seconds",
                 "hbnlp_serve_decode_tokens_per_sec", "hbnlp_serve_inflight",
                 "hbnlp_serve_queue_depth", "hbnlp_serve_request_seconds"):
        assert f"# TYPE {name}" in text


def test_graftload_open_loop_mode(live_server):
    server, cfg = live_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    report = graftload.drive(url, n_requests=4, mode="open", rate=20.0,
                             vocab=cfg.vocab_size, min_prompt=2,
                             max_prompt=4, response_len=2, seed=5)
    assert report["client"]["n_ok"] == 4
    with pytest.raises(ValueError):
        graftload.run_load(url, [[1]], 1, mode="open", rate=0)
    with pytest.raises(ValueError):
        graftload.run_load(url, [[1]], 1, mode="nope")


# -- bench serving ratchet ----------------------------------------------------

def test_graftload_check_ok_tolerates_error_rate_skip():
    """--max-error-rate must be honorable: reconciliation skips itself on
    any non-zero error rate (defined over clean runs), and --check passes
    that skip exactly when the error rate is within the allowed maximum."""
    agree = {"client": {"error_rate": 0.0},
             "reconcile": {"within_tolerance": True}}
    assert graftload.check_ok(agree)
    disagree = {"client": {"error_rate": 0.0},
                "reconcile": {"within_tolerance": False}}
    assert not graftload.check_ok(disagree)
    shed = {"client": {"error_rate": 0.05},
            "reconcile": {"skipped": "client error_rate=0.05: ..."}}
    assert graftload.check_ok(shed, max_error_rate=0.1)
    assert not graftload.check_ok(shed)  # default tolerates no errors
    # a clean run whose reconciliation was skipped for any OTHER reason
    # (no metrics URL, missing p50) still fails
    unmeasured = {"client": {"error_rate": 0.0},
                  "reconcile": {"skipped": "client or server p50 unavailable"}}
    assert not graftload.check_ok(unmeasured, max_error_rate=0.1)
    assert not graftload.check_ok({"client": {"error_rate": 0.0}})
    # a truncated run (run_load abandoned a live worker) never passes:
    # its records are partial however good its numbers look
    cut = {"client": {"error_rate": 0.0, "truncated": True},
           "reconcile": {"within_tolerance": True}}
    assert not graftload.check_ok(cut, max_error_rate=0.5)


def test_client_report_carries_truncation():
    rec = {"id": 0, "status": 200, "e2e_s": 0.1, "tokens_generated": 4}
    full = graftload.client_report([rec], [], 1.0)
    assert full["truncated"] is False
    cut = graftload.client_report([rec], [], 1.0, truncated=True)
    assert cut["truncated"] is True


def test_evaluate_serve_baseline():
    import bench
    row = {"e2e_p50_s": 0.1, "goodput_tok_s": 100.0}
    # no baseline: self-record semantics, absence is not a regression
    assert bench.evaluate_serve_baseline(row, {}) == (None, True)
    gate, ok = bench.evaluate_serve_baseline(
        row, {"e2e_p50_s": 0.09, "goodput_tok_s": 90.0})
    assert ok and gate["e2e_p50"]["pass"] and gate["goodput"]["pass"]
    gate, ok = bench.evaluate_serve_baseline(
        row, {"e2e_p50_s": 0.05, "goodput_tok_s": 90.0})
    assert not ok and not gate["e2e_p50"]["pass"]
    gate, ok = bench.evaluate_serve_baseline(
        row, {"e2e_p50_s": 0.09, "goodput_tok_s": 300.0})
    assert not ok and not gate["goodput"]["pass"]
    # partial rows gate only what they carry
    gate, ok = bench.evaluate_serve_baseline(
        {"e2e_p50_s": 0.1}, {"e2e_p50_s": 0.09})
    assert ok and "goodput" not in gate
