"""Concurrency-audit tests (analysis/concurrency.py + tools/graftsync.py,
ISSUE 16): seeded regressions — an injected unguarded multi-thread write
must fail ``sync-shared-state`` and an injected lock inversion must fail
``sync-lock-order`` — plus recorder semantics, golden wiring through
graftcheck, and the repo-clean assertions the CI gate relies on."""
from __future__ import annotations

import json
import os
import textwrap
import threading

import pytest

from homebrewnlp_tpu import sync
from homebrewnlp_tpu.analysis import concurrency as cc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _seed_tree(tmp_path, files):
    """Materialize a minimal scoped tree the analyzer will walk."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


GUARDED = """\
    import threading
    from homebrewnlp_tpu.sync import make_lock


    class Worker:
        def __init__(self):
            self._lock = make_lock("serve.victim.Worker._lock")
            self.counter = 0
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            with self._lock:
                self.counter += 1

        def read(self):
            with self._lock:
                return self.counter
"""

#: same class, with the thread-side write outside the lock — THE seeded bug
UNGUARDED = GUARDED.replace(
    """        def _run(self):
            with self._lock:
                self.counter += 1
""",
    """        def _run(self):
            self.counter += 1
""")
assert UNGUARDED != GUARDED


def test_seeded_unguarded_write_fails_shared_state(tmp_path, monkeypatch):
    root = _seed_tree(tmp_path, {"homebrewnlp_tpu/serve/victim.py": UNGUARDED})
    golden = tmp_path / "shared_state.json"
    golden.write_text("{}\n")
    monkeypatch.setattr(cc, "sync_shared_state_golden_path",
                        lambda: str(golden))
    findings = cc.check_shared_state(root)
    errs = [f for f in findings if f.severity == "error"]
    assert errs, "injected unguarded multi-thread write not flagged"
    assert any("Worker" in f.location and f.rule == "sync-shared-state"
               for f in errs)


def test_seeded_guarded_write_passes_shared_state(tmp_path, monkeypatch):
    root = _seed_tree(tmp_path, {"homebrewnlp_tpu/serve/victim.py": GUARDED})
    golden = tmp_path / "shared_state.json"
    golden.write_text("{}\n")
    monkeypatch.setattr(cc, "sync_shared_state_golden_path",
                        lambda: str(golden))
    assert [f for f in cc.check_shared_state(root)
            if f.severity == "error"] == []


INVERSION = """\
    from homebrewnlp_tpu.sync import make_lock


    class Pair:
        def __init__(self):
            self._a = make_lock("serve.inv.Pair._a")
            self._b = make_lock("serve.inv.Pair._b")

        def ab(self):
            with self._a:
                with self._b:
                    pass

        def ba(self):
            with self._b:
                with self._a:
                    pass
"""


def test_seeded_lock_inversion_fails_lock_order(tmp_path, monkeypatch):
    root = _seed_tree(tmp_path, {"homebrewnlp_tpu/serve/inv.py": INVERSION})
    golden = tmp_path / "lock_order.json"
    monkeypatch.setattr(cc, "sync_lock_order_golden_path",
                        lambda: str(golden))
    # cycle detection fires even on a fresh (just-recorded) golden: an
    # inversion is a deadlock, not a new-edge formality
    findings = cc.check_lock_order(root, update_goldens=True)
    errs = [f for f in findings if f.severity == "error"]
    assert any("cycle" in f.message for f in errs), findings


def test_new_lock_order_edge_fails_against_pinned_golden(tmp_path,
                                                         monkeypatch):
    one_way = INVERSION.replace(
        """        def ba(self):
            with self._b:
                with self._a:
                    pass
""", "")
    assert one_way != INVERSION
    root = _seed_tree(tmp_path, {"homebrewnlp_tpu/serve/inv.py": one_way})
    golden = tmp_path / "lock_order.json"
    golden.write_text(json.dumps({"edges": []}) + "\n")
    monkeypatch.setattr(cc, "sync_lock_order_golden_path",
                        lambda: str(golden))
    errs = [f for f in cc.check_lock_order(root) if f.severity == "error"]
    assert any("new lock-order edge" in f.message for f in errs)
    # ... and re-recording then re-checking is clean
    cc.check_lock_order(root, update_goldens=True)
    assert [f for f in cc.check_lock_order(root)
            if f.severity == "error"] == []


def test_raw_threading_lock_draws_warning(tmp_path, monkeypatch):
    root = _seed_tree(tmp_path, {"homebrewnlp_tpu/serve/raw.py": """\
        import threading


        class C:
            def __init__(self):
                self._lock = threading.Lock()
    """})
    model = cc.build_model(root)
    assert any("make_lock" in f.message for f in model.warnings)


def test_repo_is_clean():
    """The committed tree passes both rules against its committed goldens —
    the exact CI gate (`graftsync --check`)."""
    findings = cc.run_sync_rules(REPO)
    assert [f for f in findings if f.severity == "error"] == []


def test_repo_shared_state_golden_is_empty():
    """ISSUE 16 satellite: every true finding was FIXED, not allowlisted —
    the ratchet golden must pin zero."""
    with open(cc.sync_shared_state_golden_path()) as f:
        assert json.load(f) == {}


def test_validate_recorded_matches_static():
    model = cc.build_model(REPO)
    a, b = sorted(model.locks)[:2]
    static_pairs = [(x, y) for (x, y) in model.edges]
    # a recorded edge present in the static graph: no error
    if static_pairs:
        src, dst = static_pairs[0]
        recs = [{"kind": "edge", "src": src, "dst": dst}]
        assert [f for f in cc.validate_recorded(REPO, recs)
                if f.severity == "error"] == []
    # a recorded edge absent from it: error
    recs = [{"kind": "edge", "src": a, "dst": b}]
    if (a, b) not in model.edges:
        assert any(f.severity == "error"
                   for f in cc.validate_recorded(REPO, recs))
    # an unknown lock name: error
    recs = [{"kind": "edge", "src": "nowhere.X._lock", "dst": a}]
    assert any("does not know" in f.message
               for f in cc.validate_recorded(REPO, recs)
               if f.severity == "error")
    # held-while-joining: warning, not error
    recs = [{"kind": "join", "held": [a], "thread": "t"}]
    fs = cc.validate_recorded(REPO, recs)
    assert any(f.severity == "warning" and "join" in f.message.lower()
               for f in fs)
    assert [f for f in fs if f.severity == "error"] == []


# -- recorder unit tests ------------------------------------------------------

@pytest.fixture
def recorder():
    sync.set_recording(True)
    sync.reset()
    try:
        yield sync
    finally:
        sync.set_recording(False)
        sync.reset()


def test_recorder_edges_and_reentrancy(recorder):
    a = recorder.make_lock("t.A._lock")
    r = recorder.make_rlock("t.B._lock")
    with a:
        with r:
            with r:  # reentrant: no self-edge
                pass
    snap = recorder.snapshot()
    assert snap["edges"] == [["t.A._lock", "t.B._lock"]]


def test_recorder_same_name_instances_merge(recorder):
    """Two instances sharing a declared name (per-request locks) are one
    graph node: nesting them records no self-edge."""
    a1 = recorder.make_lock("t.R._lock")
    a2 = recorder.make_lock("t.R._lock")
    with a1:
        with a2:
            pass
    assert recorder.snapshot()["edges"] == []


def test_recorder_held_while_blocking(recorder):
    outer = recorder.make_lock("t.Outer._lock")
    inner = recorder.make_lock("t.Inner._lock")
    started = threading.Event()
    release = threading.Event()

    def holder():
        with inner:
            started.set()
            release.wait(5.0)

    t = threading.Thread(target=holder)
    t.start()
    assert started.wait(5.0)
    with outer:
        got = inner.acquire(False)  # contended probe path, non-blocking
        if got:
            inner.release()
    release.set()
    t.join(5.0)
    # the edge is recorded either way; the blocked event only on contention
    assert ["t.Outer._lock", "t.Inner._lock"] in recorder.snapshot()["edges"]


def test_recorder_join_with_held_lock(recorder):
    lk = recorder.make_lock("t.J._lock")
    t = threading.Thread(target=lambda: None)
    t.start()
    with lk:
        t.join(5.0)
    joins = recorder.snapshot()["joins"]
    assert joins and joins[0]["held"] == ["t.J._lock"]


def test_recorder_off_returns_plain_primitives():
    assert sync.recording() is False
    lk = sync.make_lock("t.off._lock")
    assert type(lk) is type(threading.Lock())


def test_factories_registered_in_graftcheck():
    from homebrewnlp_tpu import analysis
    assert "sync-shared-state" in analysis.AST_RULES
    assert "sync-lock-order" in analysis.AST_RULES
    fs = analysis.run_ast_rules(
        REPO, rules=["sync-shared-state", "sync-lock-order"])
    assert [f for f in fs if f.severity == "error"] == []
