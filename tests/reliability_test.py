"""Fault-tolerance suite (ISSUE 4): retry/backoff policy, fault-plan grammar
and injection, preemption-safe checkpoint manifests with fallback-to-verified
restore, corrupt-record budgets, SIGTERM grace shutdown with bit-identical
resume, and the auto-resume supervisor — the CI ``chaos`` job runs this file
end to end on CPU."""
import argparse
import json
import logging
import os
import sys
import time

import numpy as np
import pytest

from homebrewnlp_tpu import main as cli
from homebrewnlp_tpu.data.synthetic import write_text_tfrecords
from homebrewnlp_tpu.obs.registry import REGISTRY, MetricsRegistry
from homebrewnlp_tpu.reliability import (EXIT_CRASH_LOOP, EXIT_PREEMPTED,
                                         CorruptRecordBudget,
                                         GraceController, RetryPolicy,
                                         faults, retry_call, retrying)
from homebrewnlp_tpu.reliability.faults import (FaultInjectedCrash,
                                                FaultInjectedIOError,
                                                FaultPlan, parse_plan)

from .backend import tiny_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import supervise  # noqa: E402  (tools/supervise.py)


def _args(steps):
    return argparse.Namespace(steps=steps, profile="", workers=None)


def _rows(model_path):
    from homebrewnlp_tpu.train.metrics import read_metric_rows
    return read_metric_rows(model_path)


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    faults.reset()
    yield
    faults.reset()


# -- retry policy -------------------------------------------------------------

def test_retry_succeeds_after_transient_failures():
    reg = MetricsRegistry()
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    policy = RetryPolicy(max_attempts=4, base_delay_s=0.1, jitter=0.0)
    out = retry_call(flaky, site="t", policy=policy, registry=reg,
                     sleep=sleeps.append)
    assert out == "ok" and len(calls) == 3
    assert sleeps == [0.1, 0.2]  # exponential, no jitter
    assert reg.counter("hbnlp_io_retries_total",
                       labelnames=("site",)).value(site="t") == 2
    assert reg.counter("hbnlp_io_giveups_total",
                       labelnames=("site",)).value(site="t") == 0


def test_retry_gives_up_and_reraises():
    reg = MetricsRegistry()
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)
    with pytest.raises(OSError, match="always"):
        retry_call(lambda: (_ for _ in ()).throw(OSError("always")),
                   site="t", policy=policy, registry=reg, sleep=lambda s: None)
    assert reg.counter("hbnlp_io_giveups_total",
                       labelnames=("site",)).value(site="t") == 1


def test_retry_non_retryable_passes_through():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        retry_call(broken, site="t", registry=MetricsRegistry(),
                   sleep=lambda s: None)
    assert len(calls) == 1  # no retry on non-transport errors


def test_retry_deadline_bounds_attempts():
    policy = RetryPolicy(max_attempts=100, base_delay_s=0.0, jitter=0.0,
                         deadline_s=0.05)
    calls = []

    def flaky():
        calls.append(1)
        time.sleep(0.03)
        raise OSError("slow transient")

    with pytest.raises(OSError):
        retry_call(flaky, site="t", policy=policy,
                   registry=MetricsRegistry(), sleep=lambda s: None)
    assert len(calls) < 10  # the wall deadline cut the 100-attempt budget


def test_retrying_decorator():
    calls = []

    @retrying("deco", RetryPolicy(max_attempts=2, base_delay_s=0.0,
                                  jitter=0.0), registry=MetricsRegistry())
    def sometimes(x):
        calls.append(x)
        if len(calls) == 1:
            raise TimeoutError("first")
        return x * 2

    assert sometimes(21) == 42 and calls == [21, 21]


# -- fault plan ---------------------------------------------------------------

def test_fault_plan_grammar():
    rules = parse_plan("ckpt_write:fail@2;feeder:die@step10;sigterm@step25")
    assert [(r.site, r.action, r.at) for r in rules] == [
        ("ckpt_write", "fail", 2), ("feeder", "die", 10),
        ("step", "sigterm", 25)]
    assert parse_plan("") == [] and parse_plan(None) == []
    for bad in ("nonsense", "x:y@z", "ckpt_write:explode@1", ":fail@1"):
        with pytest.raises(ValueError):
            parse_plan(bad)


def test_fault_plan_config_validation():
    with pytest.raises(ValueError):
        tiny_config(fault_plan="ckpt_write:explode@1")
    assert tiny_config(fault_plan="sigterm@step5").fault_plan


def test_fault_rules_fire_once_at_trigger():
    plan = FaultPlan.from_spec("io:fail@2")
    plan.hit("io")  # 1st: no fire
    with pytest.raises(FaultInjectedIOError):
        plan.hit("io")  # 2nd: fires
    plan.hit("io")  # 3rd: one-shot, spent
    plan = FaultPlan.from_spec("step:die@7")
    plan.hit("step", value=6)
    with pytest.raises(FaultInjectedCrash):
        plan.hit("step", value=7)  # value-pinned trigger


# -- corrupt-record budget ----------------------------------------------------

def test_corrupt_budget_skips_then_raises():
    b = CorruptRecordBudget(2, registry=MetricsRegistry())
    b.spend("a.tfrecord", OSError("x"))
    b.spend("b.tfrecord", OSError("y"))
    with pytest.raises(OSError, match="budget exhausted"):
        b.spend("c.tfrecord", OSError("z"))


def test_pipeline_survives_injected_read_failure_within_budget(
        tmp_path, caplog):
    """data_read:fail under a budget: the bad shard is skipped and logged,
    the stream keeps producing from the remaining files."""
    from homebrewnlp_tpu.data.pipeline import GptPipeline
    write_text_tfrecords(str(tmp_path), n_files=3, records_per_file=1,
                         tokens_per_record=120, seed=5)
    cfg = tiny_config(vocab_size=256, interleaved_datasets=1,
                      corrupt_record_budget=3,
                      dataset_configs=[{"type": "text",
                                        "path": str(tmp_path / "*.tfrecord")}])
    faults.install("data_read:fail@1")  # first shard dies at its first read
    pipe = GptPipeline(cfg, 2)
    with caplog.at_level(logging.WARNING, "homebrewnlp_tpu.reliability"):
        batches = []
        for batch in pipe:
            batches.append(batch)
            if len(batches) >= 3:
                break
    assert len(batches) >= 2  # stream survived the injected failure
    assert any("corrupt-record budget" in r.message for r in caplog.records)


def test_pipeline_strict_without_budget(tmp_path):
    from homebrewnlp_tpu.data.pipeline import GptPipeline
    write_text_tfrecords(str(tmp_path), n_files=2, records_per_file=1,
                         tokens_per_record=120, seed=5)
    cfg = tiny_config(vocab_size=256, interleaved_datasets=1,
                      corrupt_record_budget=0,
                      dataset_configs=[{"type": "text",
                                        "path": str(tmp_path / "*.tfrecord")}])
    faults.install("data_read:fail@1")
    with pytest.raises(OSError):
        list(GptPipeline(cfg, 2))


# -- grace controller ---------------------------------------------------------

def test_grace_controller_deadline_forces_exit():
    exits = []
    g = GraceController(deadline_s=0.05, exit_fn=exits.append)
    g.install()
    try:
        os.kill(os.getpid(), __import__("signal").SIGTERM)
        assert g.triggered and g.signame == "SIGTERM"
        time.sleep(0.2)  # deadline timer fires: a wedged drain forces exit
        assert exits == [84]
    finally:
        g.uninstall()


# -- checkpoint manifests + verified restore ---------------------------------

def _ckpt_run(model_path, steps, **over):
    cfg = tiny_config(model_path=model_path, use_checkpointing=True,
                      steps_per_checkpoint=2, max_checkpoints_keep=5, **over)
    cli.train(cfg, _args(steps))
    return cfg


def _restore_step(model_path, **over):
    """Build a fresh template and restore whatever the Checkpointer deems
    the newest VERIFIED checkpoint; returns the restored step."""
    from homebrewnlp_tpu.data.synthetic import synthetic_text_batch
    from homebrewnlp_tpu.data import to_global
    from homebrewnlp_tpu.parallel import make_mesh
    from homebrewnlp_tpu.train import Checkpointer, Trainer
    cfg = tiny_config(model_path=model_path, use_checkpointing=True, **over)
    mesh = make_mesh(cfg)
    trainer = Trainer(cfg, mesh)
    state = trainer.init(to_global(synthetic_text_batch(cfg, 0), cfg, mesh))
    ckpt = Checkpointer(os.path.join(model_path, "ckpt"))
    state, data_state = ckpt.restore(state, cfg)
    return int(state.step), data_state


def test_save_writes_manifest_after_commit(tmp_path, eight_devices):
    _ckpt_run(str(tmp_path), 4)
    ck = tmp_path / "ckpt"
    m = json.loads((ck / "manifest_4.json").read_text())
    assert m["step"] == 4 and m["structure"] and m["config_hash"]
    assert all("crc32" in e for e in m["leaves"].values())
    assert (ck / "4").is_dir()  # manifest never precedes the step dir


def test_restore_falls_back_on_corrupt_leaf(tmp_path, eight_devices, caplog):
    """Seeded regression for the manifest code: bit-flip an orbax leaf of
    the NEWEST checkpoint; restore must land on the previous verified one
    with a clear log line, not crash and not trust the corrupt data."""
    from homebrewnlp_tpu.reliability.faults import corrupt_largest_file
    _ckpt_run(str(tmp_path), 4)  # checkpoints at steps 2 and 4
    corrupt_largest_file(str(tmp_path / "ckpt" / "4"))
    with caplog.at_level(logging.ERROR, "homebrewnlp_tpu.train.checkpoint"):
        step, _ = _restore_step(str(tmp_path))
    assert step == 2
    assert any("falling back" in r.message for r in caplog.records)


def test_restore_falls_back_on_missing_manifest(tmp_path, eight_devices,
                                                caplog):
    """A step dir without its manifest is a torn write (the manifest is the
    commit marker): restore skips it."""
    _ckpt_run(str(tmp_path), 4)
    os.remove(tmp_path / "ckpt" / "manifest_4.json")
    with caplog.at_level(logging.ERROR, "homebrewnlp_tpu.train.checkpoint"):
        step, _ = _restore_step(str(tmp_path))
    assert step == 2
    assert any("torn write" in r.message for r in caplog.records)


def test_restore_falls_back_on_corrupt_sidecar(tmp_path, eight_devices,
                                               caplog):
    """A data-state sidecar failing its manifest crc (torn cursor write)
    rejects the whole checkpoint — resuming the model without its data
    cursor would silently replay data."""
    paths_dir = tmp_path / "data"
    write_text_tfrecords(str(paths_dir), n_files=2, records_per_file=2,
                         tokens_per_record=200, seed=7)
    _ckpt_run(str(tmp_path / "run"), 4, vocab_size=256,
              interleaved_datasets=2,
              dataset_configs=[{"type": "text",
                                "path": str(paths_dir / "*.tfrecord")}])
    side = tmp_path / "run" / "ckpt" / "data_state_4.json"
    assert side.exists()
    side.write_text(side.read_text()[:-7] + "GARBAGE")
    with caplog.at_level(logging.ERROR, "homebrewnlp_tpu.train.checkpoint"):
        step, data_state = _restore_step(
            str(tmp_path / "run"), vocab_size=256, interleaved_datasets=2,
            dataset_configs=[{"type": "text",
                              "path": str(paths_dir / "*.tfrecord")}])
    assert step == 2 and data_state is not None
    assert any("falling back" in r.message for r in caplog.records)


def test_stale_sidecar_step_refused(tmp_path, eight_devices):
    """Satellite: a sidecar whose recorded step disagrees with the restored
    checkpoint step must refuse loudly (here: sole checkpoint -> restore
    raises) instead of silently resuming from a stale cursor."""
    paths_dir = tmp_path / "data"
    write_text_tfrecords(str(paths_dir), n_files=2, records_per_file=2,
                         tokens_per_record=200, seed=7)
    dsets = [{"type": "text", "path": str(paths_dir / "*.tfrecord")}]
    cfg = tiny_config(model_path=str(tmp_path / "run"),
                      use_checkpointing=True, steps_per_checkpoint=4,
                      vocab_size=256, interleaved_datasets=2,
                      dataset_configs=dsets)
    cli.train(cfg, _args(4))  # one checkpoint, at step 4
    ck = tmp_path / "run" / "ckpt"
    side = json.loads((ck / "data_state_4.json").read_text())
    side["step"] = 2  # a stale cursor from some other step
    (ck / "data_state_4.json").write_text(json.dumps(side))
    # legacy mode (no manifests): the stale cursor is the only defense
    for fn in os.listdir(ck):
        if fn.startswith("manifest_"):
            os.remove(ck / fn)
    with pytest.raises(RuntimeError, match="stale data cursor|failed"):
        _restore_step(str(tmp_path / "run"), vocab_size=256,
                      interleaved_datasets=2, dataset_configs=dsets)


def test_legacy_checkpoint_without_manifest_still_restores(tmp_path,
                                                           eight_devices):
    """Pre-manifest checkpoints (no manifest anywhere) keep restoring —
    verification only gates when manifests exist."""
    _ckpt_run(str(tmp_path), 4)
    ck = tmp_path / "ckpt"
    for fn in os.listdir(ck):
        if fn.startswith("manifest_"):
            os.remove(ck / fn)
    step, _ = _restore_step(str(tmp_path))
    assert step == 4


def test_ckpt_write_failure_retried(tmp_path, eight_devices):
    """ckpt_write:fail@1 + ckpt_retries: the injected storage failure is
    retried and training completes with a valid checkpoint."""
    c = REGISTRY.counter("hbnlp_io_retries_total", labelnames=("site",))
    before = c.value(site="ckpt_write")
    _ckpt_run(str(tmp_path), 4, fault_plan="ckpt_write:fail@1",
              ckpt_retries=2)
    assert c.value(site="ckpt_write") >= before + 1
    assert (tmp_path / "ckpt" / "manifest_4.json").exists()


def test_fault_corrupts_freshest_checkpoint_then_restore_falls_back(
        tmp_path, eight_devices):
    """The corrupt action end to end: ckpt_commit:corrupt@2 tears the step-4
    checkpoint as it lands; a later restore transparently lands on step 2."""
    _ckpt_run(str(tmp_path), 4, fault_plan="ckpt_commit:corrupt@2")
    step, _ = _restore_step(str(tmp_path))
    assert step == 2


# -- SIGTERM grace shutdown + resume ------------------------------------------

def _data_cfg(tmp_path, model, **over):
    paths_dir = tmp_path / "data"
    if not paths_dir.exists():
        write_text_tfrecords(str(paths_dir), n_files=2, records_per_file=2,
                             tokens_per_record=400, seed=7)
    return tiny_config(
        model_path=str(tmp_path / model), use_checkpointing=True,
        steps_per_checkpoint=3, vocab_size=256, interleaved_datasets=2,
        dataset_configs=[{"type": "text",
                          "path": str(paths_dir / "*.tfrecord")}], **over)


def test_sigterm_grace_resume_bit_identical(tmp_path, eight_devices):
    """Acceptance drill core: SIGTERM mid-run -> EXIT_PREEMPTED after a
    grace checkpoint; the relaunched run's loss sequence is bit-identical
    to an uninterrupted run of the same length (model AND data cursor)."""
    cli.train(_data_cfg(tmp_path, "ref"), _args(6))  # uninterrupted
    with pytest.raises(SystemExit) as e:
        cli.train(_data_cfg(tmp_path, "pre", fault_plan="sigterm@step4"),
                  _args(6))
    assert e.value.code == EXIT_PREEMPTED
    # the grace checkpoint landed at the interruption point, manifest-valid
    assert (tmp_path / "pre" / "ckpt" / "manifest_4.json").exists()
    cli.train(_data_cfg(tmp_path, "pre"), _args(6))  # the relaunch
    ref = {r["step"]: r["loss"] for r in _rows(str(tmp_path / "ref"))}
    got = {r["step"]: r["loss"] for r in _rows(str(tmp_path / "pre"))}
    assert set(ref) == set(got) == set(range(6))
    assert all(np.isfinite(v) for v in ref.values())
    for s in range(6):
        assert ref[s] == got[s], f"loss diverged at step {s} after resume"


@pytest.mark.slow
def test_sigterm_grace_resume_300_steps(tmp_path, eight_devices):
    """Extends the 300-step sync-parity acceptance: preempt at step 150,
    resume, and require the full 300-loss sequence bit-identical to the
    uninterrupted run."""
    sync_cfg = tiny_config(model_path=str(tmp_path / "ref"),
                           async_inflight_steps=0, device_prefetch_depth=0)
    cli.train(sync_cfg, _args(300))
    pre = tiny_config(model_path=str(tmp_path / "pre"),
                      use_checkpointing=True, steps_per_checkpoint=50,
                      fault_plan="sigterm@step150")
    with pytest.raises(SystemExit) as e:
        cli.train(pre, _args(300))
    assert e.value.code == EXIT_PREEMPTED
    cli.train(tiny_config(model_path=str(tmp_path / "pre"),
                          use_checkpointing=True, steps_per_checkpoint=50),
              _args(300))
    ref = {r["step"]: r["loss"] for r in _rows(str(tmp_path / "ref"))}
    got = {r["step"]: r["loss"] for r in _rows(str(tmp_path / "pre"))}
    assert set(got) == set(range(300))
    assert [ref[s] for s in range(300)] == [got[s] for s in range(300)]


# -- supervisor ---------------------------------------------------------------

def test_supervisor_preemption_relaunches_without_backoff():
    sleeps = []
    outcomes = iter([EXIT_PREEMPTED, EXIT_PREEMPTED, 0])
    progress = iter([-1, 3, 6, 9])
    sup = supervise.Supervisor(
        lambda: next(outcomes), lambda: next(progress),
        sleep=sleeps.append, registry=MetricsRegistry())
    assert sup.run() == 0
    assert sleeps == []  # preemption never backs off
    assert sup.restarts == 2


def test_supervisor_crash_backs_off_and_recovers():
    sleeps = []
    outcomes = iter([1, 1, 0])
    progress = iter([-1, 5, 10, 15])  # every run makes progress
    sup = supervise.Supervisor(
        lambda: next(outcomes), lambda: next(progress),
        backoff_base_s=1.0, backoff_max_s=8.0, backoff_jitter=0.0,
        sleep=sleeps.append, registry=MetricsRegistry())
    assert sup.run() == 0
    # progress resets the backoff, so both crashes wait the base delay
    assert sleeps == [1.0, 1.0]


def test_supervisor_aborts_crash_loop_without_progress():
    sleeps = []
    sup = supervise.Supervisor(
        lambda: 1, lambda: 7,  # always crashes, progress frozen
        max_failures_no_progress=3, backoff_base_s=1.0, backoff_jitter=0.0,
        sleep=sleeps.append, registry=MetricsRegistry())
    assert sup.run() == EXIT_CRASH_LOOP
    assert len(sleeps) == 2  # two relaunches, third failure aborts
    assert sleeps == [1.0, 2.0]  # no progress: backoff keeps growing


def test_supervisor_progress_probe_reads_disk(tmp_path):
    assert supervise.last_step_progress(str(tmp_path)) == -1
    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"run_start": True, "resume_step": 0}) + "\n"
        + json.dumps({"step": 4, "loss": 1.0}) + "\n"
        + '{"torn line')
    ck = tmp_path / "ckpt"
    ck.mkdir()
    (ck / "manifest_6.json").write_text("{}")
    assert supervise.last_step_progress(str(tmp_path)) == 6


def test_supervisor_end_to_end_drill(tmp_path, eight_devices):
    """THE acceptance drill: feeder death (crash) -> supervisor relaunch
    with backoff; SIGTERM (preemption + grace checkpoint) -> immediate
    relaunch; final run completes; the assembled loss sequence is
    bit-identical to an uninterrupted run."""
    cli.train(_data_cfg(tmp_path, "ref"), _args(6))
    plans = ["feeder:die@2", "sigterm@step4", ""]

    def launch():
        cfg = _data_cfg(tmp_path, "drill", fault_plan=plans.pop(0))
        try:
            cli.train(cfg, _args(6))
        except SystemExit as e:
            return int(e.code or 0)
        except Exception:
            return 1
        return 0

    sleeps = []
    sup = supervise.Supervisor(
        launch, lambda: supervise.last_step_progress(str(tmp_path / "drill")),
        sleep=sleeps.append, registry=MetricsRegistry())
    assert sup.run() == 0
    assert sup.restarts == 2 and len(sleeps) == 1  # 1 crash, 1 preemption
    ref = {r["step"]: r["loss"] for r in _rows(str(tmp_path / "ref"))}
    got = {r["step"]: r["loss"] for r in _rows(str(tmp_path / "drill"))}
    assert set(got) == set(range(6))
    for s in range(6):
        assert ref[s] == got[s], f"loss diverged at step {s} after drill"


# -- watchdog stall counter (satellite) ---------------------------------------

def test_watchdog_stall_increments_registry_counter(tmp_path):
    from homebrewnlp_tpu.obs import Health, Watchdog
    reg = MetricsRegistry()
    health = Health(stall_factor=2.0)
    health.step_completed(0)
    time.sleep(0.02)
    health.step_completed(1)
    wd = Watchdog(health, str(tmp_path), factor=2.0, poll_s=0.02,
                  min_stall_s=0.05, registry=reg)
    wd.start()
    time.sleep(0.4)  # stall
    wd.stop()
    assert reg.counter("hbnlp_watchdog_stalls_total").value() == 1
    assert "hbnlp_watchdog_stalls_total 1" in reg.render()


# -- feeder death surfaces as a crash ----------------------------------------

def test_feeder_death_crashes_run_with_flushed_metrics(tmp_path,
                                                       eight_devices):
    """feeder:die kills the producer thread; the consumer re-raises, the
    run exits nonzero (a crash, not a hang), and already-completed steps
    are flushed for the post-mortem."""
    cfg = tiny_config(model_path=str(tmp_path),
                      fault_plan="feeder:die@3", device_prefetch_depth=1)
    with pytest.raises(FaultInjectedCrash):
        cli.train(cfg, _args(10))
    steps = [r["step"] for r in _rows(str(tmp_path))]
    assert steps == [0, 1]  # two batches fed before the injected death


# -- code-review hardening regressions ----------------------------------------

def test_supervisor_exit_code_contract_and_no_jax():
    """tools/supervise.py pins the exit codes locally (it must not import
    the package, whose __init__ pulls jax); the two definitions cannot
    drift, and the supervise module must be loadable without jax."""
    import homebrewnlp_tpu.reliability as rel
    assert supervise.EXIT_PREEMPTED == rel.EXIT_PREEMPTED
    assert supervise.EXIT_GRACE_TIMEOUT == rel.EXIT_GRACE_TIMEOUT
    assert supervise.EXIT_CRASH_LOOP == rel.EXIT_CRASH_LOOP
    assert supervise.EXIT_ANOMALY_HALT == rel.EXIT_ANOMALY_HALT
    assert supervise.EXIT_PEER_LOST == rel.EXIT_PEER_LOST
    import subprocess
    out = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.modules['jax'] = None\n"  # poison jax import
         "import importlib.util\n"
         "spec = importlib.util.spec_from_file_location('supervise', "
         f"{os.path.join(REPO, 'tools', 'supervise.py')!r})\n"
         "m = importlib.util.module_from_spec(spec)\n"
         "spec.loader.exec_module(m)\n"
         "print(m.EXIT_PREEMPTED)"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "83"


def test_save_after_fallback_restore_persists(tmp_path, eight_devices):
    """Rejected (corrupt) newer checkpoints are scrubbed on fallback, so a
    later save at a LOWER step is not silently swallowed by orbax's
    should_save — without the scrub, no checkpoint would persist until
    training re-passed the corrupt step."""
    from homebrewnlp_tpu.reliability.faults import corrupt_largest_file
    _ckpt_run(str(tmp_path), 4)  # checkpoints at 2 and 4
    corrupt_largest_file(str(tmp_path / "ckpt" / "4"))
    # fallback restore (lands on 2) scrubs the corrupt step 4 ...
    step, _ = _restore_step(str(tmp_path))
    assert step == 2
    assert not (tmp_path / "ckpt" / "4").exists()
    # ... so resuming training persists its step-3/4 checkpoints again
    cli.train(tiny_config(model_path=str(tmp_path), use_checkpointing=True,
                          steps_per_checkpoint=1, max_checkpoints_keep=5),
              _args(3))
    assert (tmp_path / "ckpt" / "3").is_dir()
    assert (tmp_path / "ckpt" / "manifest_3.json").exists()


def test_step_fault_rules_disarm_on_resume(tmp_path, eight_devices):
    """A sigterm@stepN plan inherited by the relaunched child (config/env)
    must not refire at the resume step: run 1 preempts at N, run 2 with the
    SAME plan resumes from N and completes."""
    cfg = dict(model_path=str(tmp_path), use_checkpointing=True,
               steps_per_checkpoint=10, fault_plan="sigterm@step2")
    with pytest.raises(SystemExit) as e:
        cli.train(tiny_config(**cfg), _args(5))
    assert e.value.code == EXIT_PREEMPTED
    cli.train(tiny_config(**cfg), _args(5))  # same plan: must complete
    assert sorted({r["step"] for r in _rows(str(tmp_path))}) == list(range(5))


def test_restore_propagates_exhausted_transient_errors(tmp_path,
                                                       eight_devices,
                                                       monkeypatch):
    """A storage outage that survives the retry budget must surface as the
    real error, NOT masquerade as corruption and silently fall back to an
    older checkpoint."""
    from homebrewnlp_tpu.train import checkpoint as ckpt_mod
    _ckpt_run(str(tmp_path), 4)
    real = ckpt_mod.ocp.CheckpointManager.restore

    def outage(self, step, *a, **kw):
        raise OSError("storage unreachable")

    monkeypatch.setattr(ckpt_mod.ocp.CheckpointManager, "restore", outage)
    with pytest.raises(OSError, match="storage unreachable"):
        _restore_step(str(tmp_path))
    monkeypatch.setattr(ckpt_mod.ocp.CheckpointManager, "restore", real)
    step, _ = _restore_step(str(tmp_path))  # outage over: newest restores
    assert step == 4
