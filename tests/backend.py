"""Shared test harness: tiny configs + forward/grad helpers.

Plays the role of the reference's tests/backend.py (BaseTest/OperationTest
over a CPU PlacementMeshImpl) for the JAX framework.
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

from homebrewnlp_tpu.config import Config
from homebrewnlp_tpu.models import build, init_params
from homebrewnlp_tpu.models.ctx import Args, Ctx
from homebrewnlp_tpu.nd import NT

RELU_STD = 1 / 1.42


def tiny_config(**overrides) -> Config:
    base = dict(
        model_mode="gpt", use_video=False, use_language=True,
        sequence_length=16, features_per_head=32, heads=4, depth=2,
        vocab_size=64, train_batch_size=2,
        memory_reduction_strategy="none",
        embedding_stddev=0.04,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
    )
    base.update(overrides)
    return Config(base)


def mixer_config(**overrides) -> Config:
    """Shrunk 32big_mixer.json architecture (same DSL strings)."""
    base = dict(
        model_mode="gpt", use_video=False, use_language=True,
        sequence_length=16, features_per_head=32, heads=4, depth=2,
        vocab_size=64, train_batch_size=2, calc_accuracy=True,
        memory_reduction_strategy="revnet",
        group_linear_factor=2,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[
            {"layer": ["norm-shift-scale-features-group",
                       "bottleneck_group_linear-in:relu-mid:relu-mid:norm-mid:shift-mid:scale-mid:features"]},
            {"layer": ["norm-shift-scale-features-group",
                       "attention-biased_attention_map-absolute-input_as_value-shared",
                       "norm-shift-scale-features-group",
                       "activation-gelu",
                       "attention-biased_attention_map-absolute-input_as_value-shared"]},
        ],
    )
    base.update(overrides)
    return Config(base)


def text_batch(cfg: Config, seed: int = 0) -> typing.Dict[str, NT]:
    key = jax.random.key(seed)
    shape = (cfg.train_batch_size * cfg.macro_batching, cfg.sequence_length,
             cfg.token_patch_size)
    names = ("batch", "sequence", "language_token_patch")
    kx, ky = jax.random.split(key)
    return {
        "token_x": NT(jax.random.randint(kx, shape, 0, cfg.vocab_size), names),
        "token_y": NT(jax.random.randint(ky, shape, 0, cfg.vocab_size), names),
    }


def init_and_loss(cfg: Config, seed: int = 0):
    batch = text_batch(cfg, seed)
    params, axes = init_params(cfg, batch, seed=seed)

    def loss_fn(p, rng):
        ctx = Ctx(cfg, params=p, train=True, rng=rng)
        return build(ctx, batch).loss

    return params, axes, batch, loss_fn


def feature_tensor(cfg: Config, seed: int = 0, std: float = 1.0) -> NT:
    shape = (cfg.train_batch_size, cfg.sequence_length, cfg.heads,
             cfg.features_per_head)
    x = jax.random.normal(jax.random.key(seed), shape, jnp.float32) * std
    return NT(x, ("batch", "sequence", "heads", "features_per_head"))


def run_layer(cfg: Config, layer_spec: str, x: NT, seed: int = 0,
              train: bool = False) -> NT:
    """Init + apply a single DSL layer on tensor x."""
    from homebrewnlp_tpu.models.registry import LAYER_FUNCTIONS

    name, *extras = layer_spec.split("-")

    def _run(ctx):
        args = Args(ctx, x, extras, is_last=False)
        return LAYER_FUNCTIONS[name](args)

    ctx = Ctx(cfg, params=None, seed=seed, train=train)
    _run(ctx)
    ctx2 = Ctx(cfg, params=dict(ctx.collected), train=train,
               rng=jax.random.key(seed))
    return _run(ctx2)
