"""graftprof: device-time attribution from profiler traces (ISSUE 8).

Three layers of coverage:

- pure parser/attribution math over the committed miniature Chrome-trace
  fixture (``tests/data/mini_trace.json`` + op-map sidecar) — category
  bucketing, nested-thunk self time, scope attribution through transform
  wrappers, malformed-event tolerance, the flamegraph golden, the
  ``--compare`` diff, and the predicted-vs-measured reconciliation;
- the live capture path: 5 CPU train steps through the real CLI with the
  profiler armed must produce a summary attributing >=90% of device time
  with named model scopes present (the CI ``profile-smoke`` contract);
- the observability surfaces: ``record_profile`` gauges on /metrics, the
  comm fraction mirrored under /healthz ``utilization``, and the watchdog
  diagnostics dump inlining the latest summary.
"""
from __future__ import annotations

import argparse
import json
import os

import pytest

from homebrewnlp_tpu.obs import profile as P

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
FIXTURE = os.path.join(DATA, "mini_trace.json")


def fixture_summary(n_steps=2, **kw):
    return P.summarize_trace(FIXTURE, op_map=P.sidecar_op_map(FIXTURE),
                             n_steps=n_steps, **kw)


# -- category bucketing -------------------------------------------------------

@pytest.mark.parametrize("op,cat", [
    ("dot.4", "mxu"),
    ("convolution.2", "mxu"),
    ("input_reduce_dot_fusion.1", "mxu"),
    ("custom-call.3", "mxu"),
    ("all-reduce.12.clone", "collective"),
    ("reduce-scatter", "collective"),
    ("collective-permute.1", "collective"),
    ("all-gather.7", "collective"),
    # async halves (the form modern XLA emits on TPU) are still comm
    ("all-reduce-start.1", "collective"),
    ("all-gather-start", "collective"),
    ("reduce-scatter-done.3", "collective"),
    ("collective-permute-start.2", "collective"),
    # dtype casts are vector work, not MXU ("conv" must not eat "convert")
    ("convert.5", "vector"),
    ("convert_fusion.2", "vector"),
    ("copy.9", "copy"),
    ("dynamic-update-slice.2", "copy"),
    ("infeed", "infeed"),
    ("outfeed.1", "infeed"),
    ("tanh.5.clone", "vector"),
    ("broadcast_multiply_fusion", "vector"),
    ("reduce-window", "vector"),
    ("call.1", "vector"),
    ("while", "vector"),
    ("frobnicate.3", "unknown"),
])
def test_categorize(op, cat):
    assert P.categorize(op) == cat


def test_collective_kind():
    assert P.collective_kind("all-reduce.3.clone") == "all-reduce"
    assert P.collective_kind("all-to-all.1") == "all-to-all"
    assert P.collective_kind("all-reduce-start.2") == "all-reduce"
    assert P.collective_kind("all-gather-done") == "all-gather"
    assert P.collective_kind("dot.4") is None
    assert P.collective_kind("copy-start.1") is None


# -- scope extraction ---------------------------------------------------------

def test_scope_of_op_name_unwraps_transforms():
    assert P.scope_of_op_name(
        "jit(step)/jit(main)/transpose(jvp(body))/layer0/ffn/dot_general"
    ) == ("body", "layer0", "ffn")
    assert P.scope_of_op_name(
        "jit(step)/jit(main)/jvp(gpt)/loss/exp") == ("gpt", "loss")
    # bare step-level glue: no scope components at all
    assert P.scope_of_op_name("jit(step)/jit(main)/add") == ()
    assert P.scope_of_op_name("jit(f)/jit(main)/") == ()


def test_scope_collapses_doubled_preset_prefix():
    # per-block sub-builds re-enter their preset path while the outer
    # build's name-stack entries are still open (models/ctx.py)
    assert P.scope_of_op_name(
        "jit(step_fn)/jit(main)/jvp(gpt)/body/gpt/body/d0_0/block_/mul"
    ) == ("gpt", "body", "d0_0", "block_")


def test_collapse_repeat_pure():
    assert P._collapse_repeat(("a", "b", "a", "b", "c")) == ("a", "b", "c")
    assert P._collapse_repeat(("a", "a")) == ("a",)
    assert P._collapse_repeat(("a", "b", "c")) == ("a", "b", "c")
    assert P._collapse_repeat(()) == ()


# -- HLO op map ---------------------------------------------------------------

HLO_SNIPPET = """\
HloModule jit_step_fn, is_scheduled=true

%fused_computation (p: f32[8]) -> f32[8] {
  ROOT %mul.3 = f32[8] multiply(%p, %p), metadata={op_name="jit(step_fn)/jit(main)/body/mul" source_file="x.py" source_line=3}
}

ENTRY %main {
  %Arg_0.1 = f32[8] parameter(0), metadata={op_name="x"}
  %dot.7 = f32[8,8] dot(%Arg_0.1, %Arg_0.1), metadata={op_name="jit(step_fn)/jit(main)/body/attn/dot_general"}
  ROOT %out_fusion = f32[8] fusion(%Arg_0.1), calls=%fused_computation, metadata={op_name="jit(step_fn)/jit(main)/body/mul"}
}
"""


def test_op_map_from_hlo_text():
    assert P.hlo_module_name(HLO_SNIPPET) == "jit_step_fn"
    ops = P.op_map_from_hlo_text(HLO_SNIPPET)
    # entry ops, fused-computation internals, and args all carried
    assert ops["dot.7"].endswith("body/attn/dot_general")
    assert ops["mul.3"].endswith("body/mul")
    assert ops["out_fusion"].endswith("body/mul")
    assert ops["Arg_0.1"] == "x"


def test_op_map_lookup_clone_fallback(tmp_path):
    om = P.OpMap.from_hlo_text(HLO_SNIPPET)
    assert om.lookup("jit_step_fn", "dot.7.clone") is not None
    assert om.lookup("jit_step_fn", "dot.7.clone.clone") is not None
    assert om.lookup("jit_step_fn", "nope.1") is None
    assert om.lookup("other_module", "dot.7") is None
    path = om.save(str(tmp_path / "map.json"))
    assert P.OpMap.load(path).lookup("jit_step_fn", "dot.7") \
        == om.lookup("jit_step_fn", "dot.7")


# -- the committed fixture ----------------------------------------------------

def test_fixture_category_seconds():
    s = fixture_summary()
    # hand-computed from the fixture (us): dot 60 mxu; tanh 40 + fusion 20
    # + call self 0 vector; all-reduce 50; copy 30; weird_thing 10 unknown
    assert s.categories_s == {"collective": 5e-05, "copy": 3e-05,
                              "mxu": 6e-05, "unknown": 1e-05,
                              "vector": 6e-05}
    assert s.collectives_s == {"all-reduce": 5e-05}
    assert s.attributed_category_frac == pytest.approx(200 / 210, abs=1e-5)


def test_fixture_self_time_nesting():
    # the call.1 thunk (100us) encloses dot.1 (60) + tanh (40) on its lane:
    # its SELF time must be zero, or the window double-counts
    s = fixture_summary()
    call_rows = [r for r in s.op_rows if r["op"] == "call"]
    assert call_rows and call_rows[0]["self_s"] == 0.0


def test_fixture_scope_attribution():
    s = fixture_summary()
    # transform wrappers unwrap (jvp/transpose -> model), clone suffix
    # falls back, arg-label metadata goes to (toplevel), map misses and
    # the TPU-pid fusion go to (unattributed)
    assert s.scopes_s == {"(toplevel)": 3e-05, "(unattributed)": 3e-05,
                          "model/body": 0.0, "model/body/attn": 0.00011,
                          "model/body/ffn": 4e-05}
    assert s.attributed_scope_frac == pytest.approx(180 / 210, abs=1e-5)


def test_fixture_decomposition_and_idle():
    s = fixture_summary(n_steps=2)
    # wall 210us, busy union 160us (lanes overlap), idle 50us; decomposition
    # splits busy across buckets by self-time share and sums to the wall
    assert s.wall_s == pytest.approx(210e-6)
    assert s.busy_s == pytest.approx(160e-6)
    d = s.decomposition_ms_per_step
    assert d["total"] == pytest.approx(0.105)
    assert d["idle"] == pytest.approx(0.025)
    assert d["mxu"] == pytest.approx(160 * 60 / 210 / 2 * 1e-3, rel=1e-3)
    assert d["comm"] == pytest.approx(160 * 50 / 210 / 2 * 1e-3, rel=1e-3)
    assert (d["mxu"] + d["hbm"] + d["comm"] + d["idle"]
            == pytest.approx(d["total"], rel=1e-4))
    assert sum(s.fractions.values()) == pytest.approx(1.0, abs=1e-4)


def test_fixture_garbage_events_counted_not_fatal():
    s = fixture_summary()
    # missing dur, negative dur, non-numeric ts -> counted; the host-side
    # python event and ph=B marker are silently ignored
    assert s.n_malformed == 3
    assert s.n_events == 7


def test_fixture_tpu_device_pid_detected():
    # fusion.7 carries no hlo_op arg; it counts because pid 9 is a
    # /device: process — the TPU-side trace shape
    s = fixture_summary()
    assert any(r["op"] == "fusion" for r in s.op_rows)
    assert s.n_lanes == 3


def test_summary_json_roundtrip(tmp_path):
    s = fixture_summary()
    path = s.save(str(tmp_path / "summary.json"))
    back = P.ProfileSummary.load(path)
    assert back.to_json() == s.to_json()


def test_no_trace_skips_cleanly(tmp_path):
    assert P.capture_summary(str(tmp_path)) is None
    assert P.find_trace_file(str(tmp_path / "missing")) is None


def test_empty_trace_summary():
    s = P.summarize_events([])
    assert s.n_events == 0 and s.wall_s == 0.0
    assert s.decomposition_ms_per_step["total"] == 0.0


# -- flamegraph + compare + CLI -----------------------------------------------

def test_flamegraph_golden():
    s = fixture_summary()
    golden = open(os.path.join(DATA, "mini_trace_flame.txt")).read()
    assert "\n".join(P.collapsed_stacks(s)) + "\n" == golden


def test_diff_summaries_self_is_zero():
    s = fixture_summary()
    d = P.diff_summaries(s, s)
    assert d["ms_per_step"]["delta"] == 0.0
    assert all(v == 0.0 for v in d["fractions_delta"].values())
    assert all(r["delta_ms"] == 0.0 for r in d["scopes_ms"].values())


def test_diff_summaries_detects_growth():
    import dataclasses
    a = fixture_summary()
    b = dataclasses.replace(
        a, scopes_s=dict(a.scopes_s, **{"model/body/attn": 0.00022}),
        decomposition_ms_per_step=dict(a.decomposition_ms_per_step,
                                       total=0.2))
    d = P.diff_summaries(a, b)
    assert d["scopes_ms"]["model/body/attn"]["delta_ms"] > 0
    assert d["ms_per_step"]["delta"] == pytest.approx(0.095)


def _run_cli(*argv):
    from tools import graftprof as cli
    return cli.main(list(argv))


def test_cli_table_and_gates(capsys):
    rc = _run_cli(FIXTURE, "--steps", "2")
    out = capsys.readouterr().out
    assert rc == 0
    assert "model/body/attn" in out
    assert "ms/step" in out and "all-reduce" in out
    # gates: fixture attributes 95.2% by category, 85.7% by scope
    assert _run_cli(FIXTURE, "--min-category-frac", "0.9") == 0
    capsys.readouterr()
    assert _run_cli(FIXTURE, "--min-scope-frac", "0.9") == 1


def test_cli_json_and_depth(capsys):
    rc = _run_cli(FIXTURE, "--steps", "2", "--json")
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_steps"] == 2
    assert doc["scopes_s"]["model/body/attn"] == 0.00011
    rc = _run_cli(FIXTURE, "--depth", "1")
    out = capsys.readouterr().out
    assert rc == 0 and "model " in out  # collapsed to depth 1


def test_cli_flame_export(tmp_path, capsys):
    out_path = str(tmp_path / "flame.txt")
    assert _run_cli(FIXTURE, "--flame", out_path) == 0
    golden = open(os.path.join(DATA, "mini_trace_flame.txt")).read()
    assert open(out_path).read() == golden


def test_cli_compare_self(tmp_path, capsys):
    assert _run_cli(FIXTURE, "--compare", FIXTURE, "--json") == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ms_per_step"]["delta"] == 0.0


def test_cli_bench_round_source_and_compare(tmp_path, capsys):
    """--compare between two BENCH_r*.json lines diffs the profile rows."""
    s = fixture_summary()
    prof_row = {
        "n_steps": 2,
        "ms_per_step": s.decomposition_ms_per_step,
        "fractions": s.fractions,
        "attributed_category_frac": s.attributed_category_frac,
        "attributed_scope_frac": s.attributed_scope_frac,
        "scopes_ms": {k: v * 1e3 / 2 for k, v in s.scopes_s.items()},
        "top_ops": s.top_ops[:3],
    }
    a = {"metric": "x", "workloads": {"32big_mixer": {"profile": prof_row}}}
    b = json.loads(json.dumps(a))
    b["workloads"]["32big_mixer"]["profile"]["ms_per_step"] = dict(
        prof_row["ms_per_step"], total=prof_row["ms_per_step"]["total"] + 1.0)
    pa, pb = str(tmp_path / "BENCH_rA.json"), str(tmp_path / "BENCH_rB.json")
    json.dump(a, open(pa, "w"))
    json.dump(b, open(pb, "w"))
    assert _run_cli(pa, "--compare", pb, "--json") == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ms_per_step"]["delta"] == pytest.approx(1.0)


def test_cli_unreadable_source_exits_2(tmp_path, capsys):
    bad = tmp_path / "trunc.trace.json"
    bad.write_text('{"traceEvents": [ {"ph": "X", "na')  # truncated
    assert _run_cli(str(bad)) == 2
    assert _run_cli(str(tmp_path / "missing.json")) == 2


# -- reconciliation math ------------------------------------------------------

def test_reconcile_math():
    s = fixture_summary()
    rec = P.reconcile(s, {"mxu": 1e-3, "hbm": 2e-3, "ici": 5e-4})
    # predicted 1ms vs measured mxu ms
    m = s.decomposition_ms_per_step
    assert rec["mxu"]["predicted_ms"] == 1.0
    assert rec["mxu"]["prediction_error"] == pytest.approx(
        1.0 / m["mxu"] - 1.0, rel=1e-3)
    assert rec["comm"]["predicted_ms"] == 0.5
    assert rec["hbm"]["measured_ms"] == m["hbm"]


def test_reconcile_null_prediction_keeps_shape():
    rec = P.reconcile(fixture_summary(), None)
    assert set(rec) == {"mxu", "hbm", "comm"}
    for r in rec.values():
        assert r["predicted_ms"] is None
        assert r["prediction_error"] is None
        assert r["measured_ms"] >= 0


def test_static_step_times_known_and_unknown_device():
    from homebrewnlp_tpu.analysis.cost_model import (CommModel,
                                                     static_step_times)
    comm = CommModel(bytes_per_axis={"data": 1 << 20},
                     count_per_axis={"data": 4})
    t = static_step_times(1e12, 1e9, comm, {"data": 8}, "v5e")
    assert t is not None
    assert t["mxu"] == pytest.approx(1e12 / 197e12)
    assert t["hbm"] == pytest.approx(1e9 / 819e9)
    assert t["ici"] == pytest.approx(sum(t["ici_per_axis"].values()))
    assert t["ici_per_axis"]["data"] > 0
    assert static_step_times(1e12, 1e9, comm, {"data": 8}, "cpu") is None


def test_roofline_verdict_consistent_with_static_times():
    """_roofline and static_step_times must rank identically — they are
    documented as the same time model."""
    from homebrewnlp_tpu.analysis import cost_model as cm
    comm = cm.CommModel(bytes_per_axis={}, count_per_axis={})

    class _IMesh:
        shape = {"data": 1}
    verdict, kind = cm._roofline(None, 1e15, 1e3, comm, _IMesh(), "v5e")
    t = cm.static_step_times(1e15, 1e3, comm, {"data": 1}, "v5e")
    assert kind == "v5e"
    assert verdict == max(("mxu", "hbm", "ici"), key=lambda k: t[k])


# -- attribution-drift baseline (bench ratchet) -------------------------------

def _profile_row(mxu=0.25, hbm=0.35, comm=0.2, idle=0.2, cov=0.95):
    return {"profile": {"fractions": {"mxu": mxu, "hbm": hbm, "comm": comm,
                                      "idle": idle},
                        "attributed_scope_frac": cov}}


def test_evaluate_profile_baseline_pass_and_drift():
    base = {"w": P.baseline_entry(_profile_row()["profile"])}
    rows, ok = P.evaluate_profile_baseline({"w": _profile_row()}, base)
    assert ok and rows["w"]["pass"]
    # a fraction moving past the tolerance fails
    rows, ok = P.evaluate_profile_baseline(
        {"w": _profile_row(mxu=0.45, hbm=0.15)}, base)
    assert not ok and not rows["w"]["pass"]
    assert rows["w"]["fraction_drift"]["mxu"] == pytest.approx(0.2)
    # coverage dropping past the tolerance fails
    rows, ok = P.evaluate_profile_baseline({"w": _profile_row(cov=0.5)}, base)
    assert not ok and rows["w"]["coverage_drop"] == pytest.approx(0.45)


def test_evaluate_profile_baseline_skips_absent():
    base = {"w": P.baseline_entry(_profile_row()["profile"])}
    # no profile row / error rows / missing baseline: skipped, not failed
    rows, ok = P.evaluate_profile_baseline(
        {"w": {"profile": {"error": "x"}}, "v": _profile_row(),
         "u": {"no_profile": 1}}, base)
    assert ok and rows == {}


def test_baseline_entry_shape():
    e = P.baseline_entry(_profile_row()["profile"])
    assert set(e) == {"fractions", "attributed_scope_frac"}
    assert json.dumps(e)  # committed-file serializable


# -- nd named-scope emission --------------------------------------------------

def test_nd_scope_stacks_stay_balanced():
    from homebrewnlp_tpu import nd
    depth0 = len(nd._SCOPE_STACK)
    for _ in range(3):
        nd.push_scope("a")
        nd.push_scope("@d0_b")  # '@' must not break emission
        assert nd.current_scope() == "a/@d0_b"
        nd.pop_scope()
        nd.pop_scope()
    assert len(nd._SCOPE_STACK) == depth0
    assert len(nd._NAMED_SCOPE_CMS) == depth0
    nd.pop_scope()  # over-pop stays a no-op
    assert len(nd._SCOPE_STACK) == depth0


def test_named_scopes_reach_compiled_hlo_metadata():
    """End to end through the real model build: the compiled train step's
    HLO metadata must carry nd scope paths (this is what graftprof joins
    against)."""
    from tests.backend import text_batch, tiny_config
    from homebrewnlp_tpu.train import Trainer
    cfg = tiny_config()
    tr = Trainer(cfg)
    batch = text_batch(cfg)
    state = tr.init(batch)
    tr.step_cost_analysis(state, batch)
    text = tr._compiled.as_text()
    ops = P.op_map_from_hlo_text(text)
    scopes = {"/".join(P.scope_of_op_name(v)) for v in ops.values()
              if "jit(" in v}
    assert any(s.startswith("gpt/body") for s in scopes), sorted(scopes)[:20]
    assert "optimizer" in scopes, sorted(scopes)[:20]
    # the depth token's '@' was stripped, never silently dropped wholesale
    assert any("d0_" in s for s in scopes), sorted(scopes)[:20]


# -- live capture end to end (the CI profile-smoke contract) ------------------

def test_train_profile_capture_end_to_end(tmp_path):
    from tests.backend import tiny_config
    from homebrewnlp_tpu import main as cli
    cfg = tiny_config(model_path=str(tmp_path / "run"),
                      profile_start=1, profile_steps=3)
    cli.train(cfg, argparse.Namespace(steps=5,
                                      profile=str(tmp_path / "prof"),
                                      workers=None))
    # op-map sidecar written next to the trace session
    trace = P.find_trace_file(str(tmp_path / "prof"))
    assert trace is not None
    assert os.path.exists(os.path.join(os.path.dirname(trace),
                                       P.OP_MAP_FILENAME))
    # persisted summary: named scopes present, >=90% attributed
    doc = json.load(open(tmp_path / "run" / "profile_summary.json"))
    assert doc["n_steps"] == 3
    assert doc["attributed_category_frac"] >= 0.9
    assert doc["attributed_scope_frac"] >= 0.9
    assert any(k.startswith("gpt/") for k in doc["scopes_s"])
    assert "optimizer" in doc["scopes_s"]
    d = doc["decomposition_ms_per_step"]
    assert (d["mxu"] + d["hbm"] + d["comm"] + d["idle"]
            == pytest.approx(d["total"], rel=1e-3))
    # the CLI renders it and passes the CI attribution gate
    from tools import graftprof as cli_mod
    assert cli_mod.main([str(tmp_path / "prof"), "--steps", "3",
                         "--min-category-frac", "0.9"]) == 0


# -- observability surfaces ---------------------------------------------------

def test_record_profile_gauges_and_healthz():
    from homebrewnlp_tpu.obs import Obs
    from homebrewnlp_tpu.obs.registry import MetricsRegistry
    reg = MetricsRegistry()
    obs = Obs(model_path="/tmp/x", watchdog_factor=100.0, registry=reg)
    obs.health.step_completed(1)
    s = fixture_summary()
    obs.record_profile(s)
    text = reg.render()
    assert 'hbnlp_step_time_ms{stat="total"} 0.105' in text
    assert 'hbnlp_step_time_ms{stat="comm"}' in text
    assert 'hbnlp_profile_time_fraction{category="idle"}' in text
    assert 'hbnlp_profile_attributed_fraction{kind="scope"}' in text
    # no telemetry this run: /healthz utilization carries the comm fraction
    snap = obs.health.snapshot()
    assert snap["utilization"]["comm_fraction"] == pytest.approx(
        s.fractions["comm"], abs=1e-5)


def test_record_profile_merges_into_telemetry_utilization():
    from homebrewnlp_tpu.obs import Obs
    from homebrewnlp_tpu.obs.registry import MetricsRegistry

    class _Writer:
        last_rates = {"mfu": 0.5, "tokens_per_sec": 10.0}

        def goodput(self):
            return 0.9

    class _Util:
        flops_per_step = 1e9
    reg = MetricsRegistry()
    obs = Obs(model_path="/tmp/x", watchdog_factor=100.0, registry=reg)
    obs.watch_utilization(_Writer(), _Util())
    obs.record_profile(fixture_summary())
    util = obs.health.snapshot()["utilization"]
    assert util["mfu"] == 0.5
    assert "comm_fraction" in util


def test_dump_diagnostics_inlines_profile_summary(tmp_path):
    from homebrewnlp_tpu.obs.exporter import dump_diagnostics
    fixture_summary().save(str(tmp_path / "profile_summary.json"))
    path = dump_diagnostics(str(tmp_path), reason="test")
    content = open(path).read()
    assert "profile_summary: " in content
    assert '"attributed_scope_frac"' in content
    # and absent file stays absent, not an error
    path2 = dump_diagnostics(str(tmp_path / "other"), reason="test")
    assert not any(l.startswith("profile_summary: ")
                   for l in open(path2).read().splitlines())
