"""Fleet observability suite (ISSUE 11): exact cross-rank histogram
merging, Prometheus text federation with rank labels + fleet aggregates,
clock-offset estimation from dist/barrier span pairs + the merged
multi-lane trace, step-dispatch posting and straggler/barrier-wait
attribution, the self-describing /healthz identity block and run-start
markers, rank-labeled supervisor series, the graftfleet CLI, and THE
two-supervisor composed drill — the CI ``fleet-obs`` job runs this file
on CPU."""
import argparse
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from homebrewnlp_tpu import main as cli
from homebrewnlp_tpu.obs import Obs, SpanTracer, fleet, start_server, \
    stop_server
from homebrewnlp_tpu.obs.registry import (MetricsRegistry, bucket_quantile,
                                          merge_histogram_counts)

from .backend import tiny_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import graftfleet  # noqa: E402  (tools/graftfleet.py)
import supervise  # noqa: E402  (tools/supervise.py)


def _args(steps):
    return argparse.Namespace(steps=steps, profile="", workers=None)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


# -- exact histogram merging (satellite) --------------------------------------

BUCKETS = (0.1, 0.5, 1.0, 5.0)


def _observed(values):
    reg = MetricsRegistry()
    h = reg.histogram("h", "t", buckets=BUCKETS)
    for v in values:
        h.observe(v)
    return h.snapshot()


def test_histogram_merge_same_edges_is_lossless():
    """The federation contract: merging per-rank snapshots with the SHARED
    bucket edges equals one histogram that observed every rank's samples —
    counts, sum, count, and therefore any bucket_quantile, exactly."""
    a_vals, b_vals = [0.05, 0.3, 0.7, 2.0], [0.2, 0.2, 4.0, 9.0]
    a, b = _observed(a_vals), _observed(b_vals)
    edges, merged = merge_histogram_counts(
        [(BUCKETS, a["counts"]), (BUCKETS, b["counts"])])
    want = _observed(a_vals + b_vals)
    assert edges == BUCKETS
    assert merged == [float(c) for c in want["counts"]]
    for q in (0.1, 0.5, 0.9, 0.99):
        assert bucket_quantile(edges, merged, q) == \
            bucket_quantile(BUCKETS, want["counts"], q)


def test_histogram_merge_rejects_mismatched_edges_loudly():
    a = _observed([0.3])
    with pytest.raises(ValueError, match="edges differ"):
        merge_histogram_counts(
            [(BUCKETS, a["counts"]), ((0.1, 0.5, 2.0, 5.0), a["counts"])])
    with pytest.raises(ValueError, match="nothing to merge"):
        merge_histogram_counts([])
    with pytest.raises(ValueError, match="counts"):
        merge_histogram_counts([(BUCKETS, [1, 2])])


def test_bucket_quantile_over_merged_snapshots():
    """The fleet p95 story end to end: two ranks' latency histograms merge
    exactly, and the quantile of the merge sits where the combined
    distribution puts it (inside the bucket holding the target rank)."""
    a = _observed([0.05] * 90)   # fast rank
    b = _observed([3.0] * 10)    # slow rank
    edges, merged = merge_histogram_counts(
        [(BUCKETS, a["counts"]), (BUCKETS, b["counts"])])
    p50 = bucket_quantile(edges, merged, 0.5)
    p95 = bucket_quantile(edges, merged, 0.95)
    assert p50 <= 0.1           # median in the fast bucket
    assert 1.0 < p95 <= 5.0     # p95 lands in the slow rank's bucket


# -- prometheus text parse + federate -----------------------------------------

def _rank_registry(steps, latency):
    reg = MetricsRegistry()
    reg.counter("hbnlp_train_steps_total", "steps").inc(steps)
    reg.gauge("hbnlp_mfu", "mfu").set(steps / 100.0)
    h = reg.histogram("hbnlp_metric_drain_seconds", "drain",
                      buckets=BUCKETS)
    h.observe(latency)
    return reg


def test_parse_prom_text_roundtrip_with_labels_and_escapes():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "help text", labelnames=("path",))
    c.labels(path='we"ird\npath\\x').inc(3)
    fams = fleet.parse_prom_text(reg.render())
    (labels, value), = fams["c_total"].samples
    assert labels == {"path": 'we"ird\npath\\x'} and value == 3.0
    assert fams["c_total"].kind == "counter"
    assert fams["c_total"].help == "help text"


def test_parse_prom_text_unescape_is_single_pass():
    """Code-review regression: a literal backslash followed by 'n' (e.g. a
    Windows-ish path label) must round-trip — sequential .replace-based
    unescaping would turn the escaped pair into a real newline."""
    reg = MetricsRegistry()
    c = reg.counter("c_total", "h", labelnames=("p",))
    c.labels(p="a\\nb").inc(1)  # backslash + 'n', NOT a newline
    (labels, _), = fleet.parse_prom_text(reg.render())["c_total"].samples
    assert labels == {"p": "a\\nb"}


def test_federate_tolerates_nan_samples():
    """Code-review regression: a rank whose callback gauge failed renders
    'NaN' — one bad sample must not crash the whole federation render."""
    errors = []
    out = fleet.federate(
        {0: "# TYPE g gauge\ng 5\n", 1: "# TYPE g gauge\ng NaN\n"},
        errors=errors)
    assert not errors
    assert 'g{rank="0"} 5' in out and 'g{rank="1"} NaN' in out
    # the NaN renders per-rank but is excluded from the aggregates
    assert 'g{agg="max",rank="fleet"} 5' in out
    assert 'g{agg="mean",rank="fleet"} 5' in out


def test_parse_prom_text_reconstructs_histograms():
    reg = _rank_registry(5, 0.3)
    fams = fleet.parse_prom_text(reg.render())
    (labels, edges, counts, hsum, hcount), = \
        fams["hbnlp_metric_drain_seconds"].snapshots()
    assert labels == {} and edges == BUCKETS
    assert counts == [0.0, 1.0, 0.0, 0.0, 0.0]  # 0.3 in the (0.1, 0.5] bin
    assert hsum == pytest.approx(0.3) and hcount == 1


def test_federate_rank_labels_and_aggregates():
    texts = {0: _rank_registry(10, 0.05).render(),
             1: _rank_registry(30, 3.0).render()}
    errors = []
    out = fleet.federate(texts, errors=errors)
    assert not errors
    # per-rank series, rank-labeled
    assert 'hbnlp_train_steps_total{rank="0"} 10' in out
    assert 'hbnlp_train_steps_total{rank="1"} 30' in out
    # counters sum into the fleet aggregate
    assert 'hbnlp_train_steps_total{rank="fleet"} 40' in out
    # gauges aggregate min/mean/max
    assert 'hbnlp_mfu{agg="min",rank="fleet"} 0.1' in out
    assert 'hbnlp_mfu{agg="mean",rank="fleet"} 0.2' in out
    assert 'hbnlp_mfu{agg="max",rank="fleet"} 0.3' in out
    # histograms merge exactly: fleet count = 2, both observations binned
    assert ('hbnlp_metric_drain_seconds_count{rank="fleet"} 2' in out)
    fams = fleet.parse_prom_text(out)
    snaps = {tuple(sorted(lab.items())): counts for lab, _, counts, _, _
             in fams["hbnlp_metric_drain_seconds"].snapshots()}
    assert snaps[(("rank", "fleet"),)] == [1.0, 0.0, 0.0, 1.0, 0.0]


def test_federate_rejects_mismatched_bucket_edges_loudly():
    reg_a = _rank_registry(1, 0.2)
    reg_b = MetricsRegistry()
    reg_b.histogram("hbnlp_metric_drain_seconds", "drain",
                    buckets=(1.0, 2.0)).observe(0.5)
    errors = []
    out = fleet.federate({0: reg_a.render(), 1: reg_b.render()},
                         errors=errors)
    assert errors and "edges differ" in errors[0]
    # per-rank series survive; the aggregate is refused and counted
    assert 'hbnlp_metric_drain_seconds_count{rank="0"} 1' in out
    assert 'hbnlp_metric_drain_seconds_count{rank="1"} 1' in out
    assert 'rank="fleet"' not in \
        [l for l in out.splitlines()
         if l.startswith("hbnlp_metric_drain_seconds")][-1]
    assert "hbnlp_fleet_merge_errors 1" in out


def test_federate_excludes_serve_gauge_sentinels_from_aggregates():
    """ISSUE-14 satellite: -1 on hbnlp_serve_kv_blocks_free (and the new
    lane-occupancy gauge) is a documented "no pool / no scheduler"
    sentinel, not a measurement — a mixed fleet (one serialized rank, one
    batching) must not report fleet-min -1 or a mean dragged below every
    real pool level."""
    mixed = {0: ("# TYPE hbnlp_serve_kv_blocks_free gauge\n"
                 "hbnlp_serve_kv_blocks_free -1\n"),
             1: ("# TYPE hbnlp_serve_kv_blocks_free gauge\n"
                 "hbnlp_serve_kv_blocks_free 6\n"),
             2: ("# TYPE hbnlp_serve_kv_blocks_free gauge\n"
                 "hbnlp_serve_kv_blocks_free 4\n")}
    out = fleet.federate(mixed)
    # per-rank samples keep the sentinel (the serialized rank is visible)
    assert 'hbnlp_serve_kv_blocks_free{rank="0"} -1' in out
    assert ('hbnlp_serve_kv_blocks_free{agg="min",rank="fleet"} 4'
            in out), out
    assert ('hbnlp_serve_kv_blocks_free{agg="mean",rank="fleet"} 5'
            in out), out
    assert ('hbnlp_serve_kv_blocks_free{agg="max",rank="fleet"} 6'
            in out), out
    # an all-sentinel fleet keeps the sentinel as its honest aggregate
    all_sent = {r: ("# TYPE hbnlp_serve_lane_occupancy gauge\n"
                    "hbnlp_serve_lane_occupancy -1\n") for r in (0, 1)}
    out = fleet.federate(all_sent)
    assert 'hbnlp_serve_lane_occupancy{agg="min",rank="fleet"} -1' in out


def test_federate_merge_errors_gauge_always_present():
    """Code-review regression: the merge-error figure is recomputed per
    render, so it must be a gauge and present even at 0 — a vanishing
    'counter' would read as a counter reset and an absent-when-clean
    series can never arm an alert from baseline."""
    out = fleet.federate({0: _rank_registry(1, 0.2).render()})
    assert "# TYPE hbnlp_fleet_merge_errors gauge" in out
    assert "hbnlp_fleet_merge_errors 0" in out


def test_federate_kind_conflict_refuses_aggregate():
    reg_a = MetricsRegistry()
    reg_a.counter("x_total", "a").inc(2)
    reg_b = MetricsRegistry()
    reg_b.gauge("x_total", "b").set(5)
    errors = []
    out = fleet.federate({0: reg_a.render(), 1: reg_b.render()},
                         errors=errors)
    assert errors and "TYPE differs" in errors[0]
    assert 'x_total{rank="0"} 2' in out and 'x_total{rank="1"} 5' in out
    assert 'rank="fleet"' not in out.split("hbnlp_fleet", 1)[0]


def test_federate_passes_through_pre_rank_labeled_series():
    """The supervisor's own series already carry rank labels (satellite
    fix): federation must not double-label or duplicate them, and the
    aggregate sees each rank once."""
    reg0 = MetricsRegistry()
    reg0.counter("s_total", "s", labelnames=("rank",)).labels(rank=0).inc(1)
    reg1 = MetricsRegistry()
    reg1.counter("s_total", "s", labelnames=("rank",)).labels(rank=1).inc(2)
    out = fleet.federate({0: reg0.render(), 1: reg1.render()})
    lines = [l for l in out.splitlines() if l.startswith("s_total{")]
    assert lines == ['s_total{rank="0"} 1', 's_total{rank="1"} 2',
                     's_total{rank="fleet"} 3']


# -- step posts + straggler attribution ---------------------------------------

def _post(fleet_dir, rank, rows, gen=None):
    d = fleet.obs_dir(fleet_dir)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"steps_r{rank}.jsonl"), "a") as f:
        for step, wall in rows:
            doc = {"step": step, "wall": wall}
            if gen is not None:
                doc["gen"] = gen
            f.write(json.dumps(doc) + "\n")


def test_read_step_posts_dedups_and_tolerates_torn_lines(tmp_path):
    _post(str(tmp_path), 0, [(0, 10.0), (1, 11.0)])
    # relaunch re-dispatches step 1 (restore point): newest post wins
    _post(str(tmp_path), 0, [(1, 99.0)], gen=1)
    with open(os.path.join(fleet.obs_dir(str(tmp_path)),
                           "steps_r0.jsonl"), "a") as f:
        f.write('{"step": 2, "wa')  # torn tail of a live writer
    posts = fleet.read_step_posts(str(tmp_path))
    assert posts == {0: {0: {"wall": 10.0, "gen": None},
                         1: {"wall": 99.0, "gen": 1}}}


def test_straggler_report_attribution(tmp_path):
    """Rank 1 dispatches 100ms late every step: skew ~100ms, rank 1 is the
    straggler, and rank 0 carries the barrier-wait (the seconds it would
    idle at a per-step barrier waiting for rank 1)."""
    base = 1000.0
    _post(str(tmp_path), 0, [(s, base + s) for s in range(5)])
    _post(str(tmp_path), 1, [(s, base + s + 0.1) for s in range(5)])
    rep = fleet.straggler_report(fleet.read_step_posts(str(tmp_path)))
    assert rep["n_common_steps"] == 5
    assert rep["straggler_rank"] == 1
    assert rep["skew_ms"]["mean"] == pytest.approx(100.0, abs=1e-6)
    assert rep["skew_ms"]["max"] == pytest.approx(100.0, abs=1e-6)
    r0, r1 = rep["ranks"]["0"], rep["ranks"]["1"]
    assert r0["barrier_wait_s"] == pytest.approx(0.5, abs=1e-6)
    assert r1["barrier_wait_s"] == 0.0
    assert r1["straggler_score_ms"] > r0["straggler_score_ms"]
    assert r0["mean_step_s"] == pytest.approx(1.0)
    # the EMA converges toward the true 100ms lag
    assert 60.0 < r1["straggler_score_ms"] <= 100.0


def test_straggler_report_refuses_cross_generation_walls(tmp_path):
    """Code-review regression: after an elastic relaunch, rank 0
    re-dispatches steps 2-3 (post-outage walls, generation 1) that rank 1
    only ran before the crash (generation 0).  Comparing those walls would
    report the whole outage as skew — they must be excluded, while the
    generation-matched steps still attribute."""
    base = 1000.0
    outage = 300.0  # seconds between crash and relaunch
    _post(str(tmp_path), 1, [(s, base + s) for s in range(4)], gen=0)
    _post(str(tmp_path), 0, [(s, base + s) for s in range(2)], gen=0)
    # rank 0 restored to step 2 and re-posts 2..3 after the outage
    _post(str(tmp_path), 0, [(s, base + outage + s) for s in (2, 3)],
          gen=1)
    rep = fleet.straggler_report(fleet.read_step_posts(str(tmp_path)))
    assert rep["n_common_steps"] == 2      # steps 0-1 (both gen 0)
    assert rep["n_generation_skipped"] == 2  # steps 2-3 (gen 1 vs gen 0)
    # the outage never shows up as skew
    assert rep["skew_ms"]["max"] < 1.0, rep["skew_ms"]


def test_straggler_report_single_rank_and_disjoint_steps(tmp_path):
    _post(str(tmp_path), 0, [(0, 1.0)])
    rep = fleet.straggler_report(fleet.read_step_posts(str(tmp_path)))
    assert rep["skew_ms"] is None and rep["straggler_rank"] is None
    _post(str(tmp_path), 1, [(7, 2.0)])  # no step in common
    rep = fleet.straggler_report(fleet.read_step_posts(str(tmp_path)))
    assert rep["n_common_steps"] == 0 and rep["skew_ms"] is None


# -- clock offsets + merged trace ---------------------------------------------

def _trace_with_barriers(wall_epoch, barrier_ends, extra_span=None):
    """A minimal Chrome trace: dist/barrier spans ending (relative to
    wall_epoch) at the given seconds, each 10ms long."""
    events = []
    for i, end in enumerate(barrier_ends):
        events.append({"ph": "X", "name": fleet.BARRIER_SPAN,
                       "cat": "host", "ts": (end - 0.010) * 1e6,
                       "dur": 0.010 * 1e6, "pid": 1, "tid": 1,
                       "args": {"barrier": f"b{i}"}})
    if extra_span:
        events.append(extra_span)
    return {"traceEvents": events,
            "otherData": {"wall_epoch": wall_epoch}}


def test_estimate_offsets_recovers_known_clock_shift():
    """Rank 1's wall clock runs 2.5s AHEAD: at the same true barrier-exit
    instant its wall reads 2.5s more, so the estimated offset (seconds to
    ADD to rank 1 to land on rank 0's timebase) must recover -2.5s within
    the documented residual bound."""
    true_ends = [1.0, 2.0, 3.0]
    shift = 2.5
    jitter = [0.0, 0.0004, -0.0004]  # barrier release skew
    t0 = _trace_with_barriers(100.0, true_ends)
    # same relative ends, epoch shifted: every wall timestamp reads +2.5s
    t1 = _trace_with_barriers(
        100.0 + shift, [e + j for e, j in zip(true_ends, jitter)])
    off = fleet.estimate_offsets({0: t0, 1: t1})
    assert off["base_rank"] == 0 and off["n_pairs"] == 3
    assert off["offsets_s"]["1"] == pytest.approx(-shift, abs=1e-3)
    assert off["bound_s"] <= 0.001  # residual = the injected jitter
    # merged: barrier ends align across lanes within the bound
    merged = fleet.merge_traces({0: t0, 1: t1}, off)
    ends = {}
    for e in merged["traceEvents"]:
        if e.get("ph") == "X" and e["name"] == fleet.BARRIER_SPAN:
            ends.setdefault(e["args"]["barrier"], {})[e["pid"]] = \
                (e["ts"] + e["dur"]) / 1e6
    for b, per_rank in ends.items():
        assert abs(per_rank[0] - per_rank[1]) <= off["bound_s"] + 1e-6, \
            (b, per_rank)


def test_estimate_offsets_nulls_bound_when_a_lane_has_no_pairs():
    """Code-review regression: rank 2's trace lost its barrier spans —
    its lane aligns on raw wall clock, so the merge must NOT advertise
    the other ranks' tight residual as the whole-trace bound."""
    t0 = _trace_with_barriers(100.0, [1.0, 2.0])
    t1 = _trace_with_barriers(100.2, [1.0, 2.0])
    t2 = _trace_with_barriers(107.0, [])  # no barrier spans survived
    off = fleet.estimate_offsets({0: t0, 1: t1, 2: t2})
    assert off["n_pairs"] == 2 and off["ranks_without_pairs"] == [2]
    assert off["bound_s"] is None  # no alignment promise for lane 2
    assert off["offsets_s"]["1"] == pytest.approx(-0.2, abs=1e-6)


def test_estimate_offsets_skips_base_candidate_without_spans():
    """Code-review regression: rank 0's trace lost its barrier spans while
    ranks 1 and 2 both have them — the base must move to rank 1 (so the
    1<->2 pairing still happens and the mixed fleet is visible), not
    silently zero every pairing against a span-less rank 0."""
    t0 = _trace_with_barriers(100.0, [])
    t1 = _trace_with_barriers(200.0, [1.0, 2.0])
    t2 = _trace_with_barriers(200.3, [1.0, 2.0])
    off = fleet.estimate_offsets({0: t0, 1: t1, 2: t2})
    assert off["base_rank"] == 1
    assert off["n_pairs"] == 2 and off["ranks_without_pairs"] == [0]
    assert off["offsets_s"]["2"] == pytest.approx(-0.3, abs=1e-6)
    assert off["bound_s"] is None  # lane 0 is unaligned: no promise
    # ...and graftfleet --check treats it as a mixed fleet
    s = {"metrics_ranks": [0, 1, 2], "merge_errors": [],
         "straggler": {"n_common_steps": 3}, "trace_ranks": [0, 1, 2],
         "clock_offsets": off}
    failed = graftfleet.run_check(s)
    assert failed and "NOT aligned" in failed[0]


def test_merge_traces_without_barriers_falls_back_to_wall_clock():
    t0 = _trace_with_barriers(50.0, [], extra_span={
        "ph": "X", "name": "step", "ts": 0.0, "dur": 1e4, "pid": 9,
        "tid": 1})
    t1 = _trace_with_barriers(51.0, [], extra_span={
        "ph": "X", "name": "step", "ts": 0.0, "dur": 1e4, "pid": 9,
        "tid": 1})
    off = fleet.estimate_offsets({0: t0, 1: t1})
    assert off["n_pairs"] == 0 and off["bound_s"] is None
    merged = fleet.merge_traces({0: t0, 1: t1}, off)
    lanes = {e["pid"] for e in merged["traceEvents"] if e.get("ph") == "X"}
    assert lanes == {0, 1}  # one lane per rank, pids rewritten
    # rank 1's identical relative span sits 1s later on the merged axis
    spans = {e["pid"]: e["ts"] for e in merged["traceEvents"]
             if e.get("ph") == "X" and e["name"] == "step"}
    assert spans[1] - spans[0] == pytest.approx(1e6, rel=1e-6)


def test_span_tracer_traces_merge_with_real_exports(tmp_path):
    """End to end over REAL SpanTracer exports: two tracers with barrier
    spans recorded at matching true instants merge into two aligned
    lanes."""
    tracers = {}
    for r in (0, 1):
        t = SpanTracer(mirror_jax=False)
        with t.span(fleet.BARRIER_SPAN, barrier="sync0"):
            time.sleep(0.002)
        with t.span("step", update=0):
            time.sleep(0.001)
        t.export(str(tmp_path / f"trace_r{r}.json"))
        tracers[r] = t
    d = fleet.obs_dir(str(tmp_path / "fleet"))
    os.makedirs(d)
    for r in (0, 1):
        os.replace(str(tmp_path / f"trace_r{r}.json"),
                   os.path.join(d, f"trace_r{r}.json"))
    traces = fleet.read_traces(str(tmp_path / "fleet"))
    assert sorted(traces) == [0, 1]
    merged = fleet.merge_traces(traces)
    names = {e["name"] for e in merged["traceEvents"]
             if e.get("ph") == "X"}
    assert {fleet.BARRIER_SPAN, "step"} <= names


# -- FleetReporter ------------------------------------------------------------

def test_fleet_reporter_posts_steps_and_throttles_prom(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c_total", "c").inc(1)
    clock = [100.0]
    rep = fleet.FleetReporter(str(tmp_path), rank=3, world_size=4,
                              registry=reg, min_render_s=2.0,
                              clock=lambda: clock[0])
    prom = os.path.join(fleet.obs_dir(str(tmp_path)), "metrics_r3.prom")
    rep.step_completed(0, 100.0)
    assert os.path.exists(prom)  # first render
    first_mtime = os.path.getmtime(prom)
    reg.counter("c_total", "c").inc(1)
    clock[0] += 0.5
    rep.step_completed(1, 100.5)  # inside the throttle window: no render
    assert "c_total 1" in open(prom).read()
    clock[0] += 2.0
    rep.step_completed(2, 102.5)  # past the window: re-rendered
    assert "c_total 2" in open(prom).read()
    rep.close()
    posts = fleet.read_step_posts(str(tmp_path))
    assert {s: row["wall"] for s, row in posts[3].items()} == \
        {0: 100.0, 1: 100.5, 2: 102.5}
    assert first_mtime <= os.path.getmtime(prom)


def test_fleet_reporter_survives_unwritable_dir(tmp_path, caplog):
    """Posting is weather, not structure: a reporter pointed at an
    unwritable fleet dir degrades to a logged miss, never an exception."""
    bad = tmp_path / "nodir"
    bad.write_text("a file where a directory should be")
    rep = fleet.FleetReporter(str(bad), rank=0, world_size=2,
                              registry=MetricsRegistry())
    rep.step_completed(0, 1.0)  # must not raise
    rep.render_prom()
    rep.close()
    assert rep.skew_summary()["ranks"] == {}


# -- identity: /healthz block + run-start markers -----------------------------

def test_identity_resolution_env_first(monkeypatch):
    assert fleet.identity() == {"rank": 0, "world_size": 1,
                                "coordinator": ""}
    monkeypatch.setenv(fleet.ENV_FLEET_RANK, "2")
    monkeypatch.setenv(fleet.ENV_FLEET_WORLD, "4")
    monkeypatch.setenv(fleet.ENV_FLEET_GENERATION, "7")
    ident = fleet.identity()
    assert ident["rank"] == 2 and ident["world_size"] == 4
    assert ident["generation"] == 7


def test_healthz_carries_identity_block():
    reg = MetricsRegistry()
    server = start_server(0, registry=reg,
                          identity={"rank": 1, "world_size": 2,
                                    "coordinator": "h:1", "generation": 3})
    try:
        port = server.server_address[1]
        _, body = _get(f"http://127.0.0.1:{port}/healthz")
        snap = json.loads(body)
        assert snap["identity"] == {"rank": 1, "world_size": 2,
                                    "coordinator": "h:1", "generation": 3}
    finally:
        stop_server(server)


def test_run_start_marker_carries_identity(tmp_path, monkeypatch,
                                           eight_devices):
    monkeypatch.setenv(fleet.ENV_FLEET_RANK, "1")
    monkeypatch.setenv(fleet.ENV_FLEET_WORLD, "2")
    monkeypatch.setenv(fleet.ENV_FLEET_GENERATION, "4")
    cli.train(tiny_config(model_path=str(tmp_path)), _args(2))
    rows = [json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    marker = rows[0]
    assert marker["run_start"] is True
    assert marker["rank"] == 1 and marker["world_size"] == 2
    assert marker["generation"] == 4
    # metric-row readers still skip the marker
    from homebrewnlp_tpu.train.metrics import read_metric_rows
    assert [r["step"] for r in read_metric_rows(str(tmp_path))] == [0, 1]


# -- Obs wiring: the production posting path ----------------------------------

def test_train_posts_fleet_obs_and_stays_parity(tmp_path, monkeypatch,
                                                eight_devices):
    """A single-rank training run with cfg.fleet_dir set posts steps, a
    /metrics snapshot, and its span trace under <fleet_dir>/obs — through
    the production Obs + AsyncMetricWriter wiring — while the loss
    sequence stays bit-identical to fleet obs off."""
    ref = tiny_config(model_path=str(tmp_path / "ref"))
    cli.train(ref, _args(4))
    fleet_dir = str(tmp_path / "fleet")
    cfg = tiny_config(model_path=str(tmp_path / "run"), obs_spans=True,
                      fleet_dir=fleet_dir)
    cli.train(cfg, _args(4))
    d = fleet.obs_dir(fleet_dir)
    assert sorted(os.listdir(d)) == ["metrics_r0.prom", "steps_r0.jsonl",
                                     "trace_r0.json"]
    posts = fleet.read_step_posts(fleet_dir)
    assert sorted(posts[0]) == [0, 1, 2, 3]
    assert "hbnlp_train_steps_total" in \
        open(os.path.join(d, "metrics_r0.prom")).read()
    trace = json.load(open(os.path.join(d, "trace_r0.json")))
    assert {e["name"] for e in trace["traceEvents"]
            if e.get("ph") == "X"} >= {"step", "feed"}
    from homebrewnlp_tpu.train.metrics import read_metric_rows
    ref_losses = [r["loss"] for r in read_metric_rows(str(tmp_path / "ref"))]
    got_losses = [r["loss"] for r in read_metric_rows(str(tmp_path / "run"))]
    assert ref_losses == got_losses


# -- supervisor: rank labels + fleet posting + federation serving -------------

def test_supervisor_series_carry_rank_label(tmp_path):
    prom = tmp_path / "sup.prom"
    sup = supervise.Supervisor(
        lambda: 0, lambda: 1, registry=supervise.MetricsRegistry(),
        metrics_path=str(prom), rank=2)
    assert sup.run() == 0
    text = prom.read_text()
    assert 'hbnlp_supervisor_exits_total{outcome="clean",rank="2"} 1' in text
    assert 'hbnlp_supervisor_goodput{rank="2"}' in text
    assert 'hbnlp_supervisor_wall_seconds{rank="2"}' in text


def test_supervisor_posts_rank_prom_to_fleet_dir(tmp_path):
    """Satellite: supervisors sharing a fleet dir render per-rank files
    whose series are rank-labeled — no more collisions."""
    fdir = str(tmp_path / "fleet")
    outcomes = {0: iter([supervise.EXIT_PEER_LOST, 0]), 1: iter([0])}
    sups = {}
    for r in (0, 1):
        f = supervise.FleetCoordinator(fdir, r, 2, peer_timeout_s=5,
                                       poll_s=0.02)
        sups[r] = supervise.Supervisor(
            lambda r=r: next(outcomes[r]), lambda: 1,
            registry=supervise.MetricsRegistry(),
            metrics_path=str(tmp_path / f"host{r}" / "sup.prom"),
            fleet=f, rank=r, backoff_jitter=0.0, sleep=lambda s: None)
    import threading
    ts = [threading.Thread(target=sups[r].run) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    d = fleet.obs_dir(fdir)
    assert {"supervisor_r0.prom", "supervisor_r1.prom"} <= \
        set(os.listdir(d))
    t0 = open(os.path.join(d, "supervisor_r0.prom")).read()
    t1 = open(os.path.join(d, "supervisor_r1.prom")).read()
    assert ('hbnlp_supervisor_exits_total{outcome="peer_lost",rank="0"} 1'
            in t0)
    assert 'rank="1"' in t1 and 'rank="0"' not in t1
    # and the two files federate into distinct + aggregate series
    out = fleet.federate({0: t0, 1: t1})
    assert 'hbnlp_supervisor_exits_total{outcome="clean",rank="fleet"} 2' \
        in out


def test_federation_server_endpoints(tmp_path):
    fdir = str(tmp_path)
    t0 = time.time()  # fresh walls: ancient posts now read as stale
    _post(fdir, 0, [(0, t0), (1, t0 + 1.0)])
    _post(fdir, 1, [(0, t0 + 0.05), (1, t0 + 1.08)])
    d = fleet.obs_dir(fdir)
    for r in (0, 1):
        with open(os.path.join(d, f"metrics_r{r}.prom"), "w") as f:
            f.write(_rank_registry(5 * (r + 1), 0.2).render())
    fed = fleet.FleetFederation(fdir, world_size=2,
                                identity_doc={"rank": 0, "world_size": 2})
    server = fleet.serve_federation(0, fed)
    try:
        port = server.server_address[1]
        _, body = _get(f"http://127.0.0.1:{port}/metrics")
        text = body.decode()
        assert 'hbnlp_train_steps_total{rank="fleet"} 15' in text
        assert "hbnlp_fleet_step_skew_ms" in text
        assert 'hbnlp_fleet_barrier_wait_seconds{rank="0"}' in text
        assert "hbnlp_fleet_straggler_rank 1" in text
        status, body = _get(f"http://127.0.0.1:{port}/healthz")
        snap = json.loads(body)
        assert status == 200 and snap["status"] == "ok"
        assert snap["identity"]["world_size"] == 2
        assert snap["straggler"]["n_common_steps"] == 2
        assert snap["ranks"]["1"]["last_step"] == 1
    finally:
        fleet.stop_federation(server)


def test_federation_healthz_flags_silently_dead_rank_stale(tmp_path):
    """Code-review regression: a host that died WITHOUT any exit posting
    leaves its files behind — file existence alone must not read as a
    healthy fleet forever.  A rank whose newest step post exceeds
    stale_after_s flags stale and degrades the status."""
    now = time.time()
    _post(str(tmp_path), 0, [(0, now - 1.0)])          # fresh
    _post(str(tmp_path), 1, [(0, now - 3600.0)])       # died an hour ago
    fed = fleet.FleetFederation(str(tmp_path), world_size=2,
                                stale_after_s=600.0)
    snap = fed.snapshot()
    assert snap["status"] == "degraded"
    assert snap["ranks"]["1"]["stale"] is True
    assert snap["ranks"]["0"]["stale"] is False
    # both fresh: ok again
    _post(str(tmp_path), 1, [(1, now)])
    assert fed.snapshot()["status"] == "ok"


def test_two_rank_mixed_barrier_spans_fails_check():
    """Code-review regression: with exactly two ranks, one lane carrying
    barrier spans and the other having lost them yields zero PAIRS — pair
    counts alone cannot distinguish this mixed merge from the legitimate
    no-barriers supervision-only fleet, so the span census must."""
    t0 = _trace_with_barriers(100.0, [1.0, 2.0])
    t1 = _trace_with_barriers(100.5, [])
    off = fleet.estimate_offsets({0: t0, 1: t1})
    assert off["ranks_with_spans"] == [0] and off["n_pairs"] == 0
    s = {"metrics_ranks": [0, 1], "merge_errors": [],
         "straggler": {"n_common_steps": 2}, "trace_ranks": [0, 1],
         "clock_offsets": off}
    failed = graftfleet.run_check(s)
    assert failed and "NOT aligned" in failed[0]
    # both span-less (supervision-only drill): legitimately green
    off2 = fleet.estimate_offsets({0: _trace_with_barriers(1.0, []),
                                   1: _trace_with_barriers(2.0, [])})
    assert off2["ranks_with_spans"] == []
    s["clock_offsets"] = off2
    assert graftfleet.run_check(s) == []


def test_launcher_extra_env_is_per_launch(tmp_path):
    """Code-review regression: the fleet generation reaches the child via
    an explicit per-launch parameter, not by mutating the dict instance
    the launcher captured at construction."""
    marker = tmp_path / "gen.txt"
    launcher = supervise.SubprocessLauncher(
        [sys.executable, "-c",
         "import os;open(r'%s','a').write("
         "os.environ.get('HBNLP_FLEET_GENERATION','unset')+'\\n')"
         % marker],
        env=dict(os.environ))
    assert launcher(extra_env={"HBNLP_FLEET_GENERATION": "5"}) == 0
    assert launcher() == 0  # no extra env: the base env is untouched
    assert marker.read_text().splitlines() == ["5", "unset"]


def test_federation_healthz_dark_fleet_is_503(tmp_path):
    fed = fleet.FleetFederation(str(tmp_path), world_size=2)
    server = fleet.serve_federation(0, fed)
    try:
        port = server.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"http://127.0.0.1:{port}/healthz")
        assert e.value.code == 503
        assert json.loads(e.value.read())["status"] == "empty"
    finally:
        fleet.stop_federation(server)


# -- graftfleet CLI -----------------------------------------------------------

def _fake_fleet_dir(tmp_path):
    fdir = str(tmp_path / "fleet")
    _post(fdir, 0, [(s, 100.0 + s) for s in range(4)])
    _post(fdir, 1, [(s, 100.02 + s) for s in range(4)])
    d = fleet.obs_dir(fdir)
    for r in (0, 1):
        with open(os.path.join(d, f"metrics_r{r}.prom"), "w") as f:
            f.write(_rank_registry(4, 0.1).render())
        # rank 1's wall clock runs 0.5s ahead: same true barrier exits,
        # epoch shifted
        t = _trace_with_barriers(100.0 + r * 0.5, [1.0, 2.0])
        with open(os.path.join(d, f"trace_r{r}.json"), "w") as f:
            json.dump(t, f)
    return fdir


def test_graftfleet_report_check_and_merged_trace(tmp_path, capsys):
    fdir = _fake_fleet_dir(tmp_path)
    merged_path = str(tmp_path / "merged.json")
    rc = graftfleet.main([fdir, "--check", "--merged-trace", merged_path])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "straggler rank: 1" in out
    assert "clock offsets vs rank 0" in out
    merged = json.load(open(merged_path))
    assert {e["pid"] for e in merged["traceEvents"]
            if e.get("ph") == "X"} == {0, 1}
    # rank 1's clock runs 0.5s ahead: the offset recovers -0.5s exactly
    off = merged["otherData"]["clock_offsets"]
    assert off["offsets_s"]["1"] == pytest.approx(-0.5, abs=1e-6)


def test_graftfleet_check_fails_on_empty_or_single_rank(tmp_path, capsys):
    empty = tmp_path / "empty"
    empty.mkdir()
    assert graftfleet.main([str(empty), "--check"]) == 1
    err = capsys.readouterr().err
    assert "CHECK FAILED" in err and "need >= 2" in err
    assert graftfleet.main([str(tmp_path / "missing")]) == 2


def test_graftfleet_json_output(tmp_path, capsys):
    fdir = _fake_fleet_dir(tmp_path)
    assert graftfleet.main([fdir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["metrics_ranks"] == [0, 1]
    assert doc["straggler"]["skew_ms"]["mean"] == pytest.approx(20.0,
                                                               abs=0.5)


def _multichip_round(tmp_path, name, row):
    doc = {"n_devices": 8, "rc": 0, "ok": True,
           "tail": "dryrun_multichip(8): mesh=... loss=5.5\n"
                   f"dryrun_multichip(8) fleet_obs: {json.dumps(row)}\n"}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_graftfleet_compare_multichip_fleet_rows(tmp_path, capsys):
    """Satellite: two MULTICHIP rounds' fleet rows diff in the same shape
    as graftprof --compare (a -> b with deltas)."""
    row_a = {"skew_ms": {"mean": 50.0, "p95": 51.0, "max": 52.0},
             "barrier_wait_total_s": 0.30, "straggler_rank": 1,
             "ranks": {"0": {"mean_step_s": 0.119, "barrier_wait_s": 0.30},
                       "1": {"mean_step_s": 0.119, "barrier_wait_s": 0.0}}}
    row_b = {"skew_ms": {"mean": 20.0, "p95": 21.0, "max": 22.0},
             "barrier_wait_total_s": 0.12, "straggler_rank": 0,
             "ranks": {"0": {"mean_step_s": 0.100, "barrier_wait_s": 0.0},
                       "1": {"mean_step_s": 0.095, "barrier_wait_s": 0.12}}}
    a = _multichip_round(tmp_path, "MULTICHIP_rA.json", row_a)
    b = _multichip_round(tmp_path, "MULTICHIP_rB.json", row_b)
    assert graftfleet.main(["--compare", a, b]) == 0
    out = capsys.readouterr().out
    assert "skew mean ms: 50.000 -> 20.000 (-30.000)" in out
    assert "barrier-wait total s: 0.300 -> 0.120 (-0.180)" in out
    assert "straggler rank: 1 -> 0" in out
    assert "-19.000" in out  # per-rank step-time delta (0.119 -> 0.100)
    # a round without the row is a usage error, not a crash
    legacy = tmp_path / "MULTICHIP_r00.json"
    legacy.write_text(json.dumps({"n_devices": 8, "tail": "no row"}))
    assert graftfleet.main(["--compare", a, str(legacy)]) == 2


# -- watchdog diagnostics carry the fleet report ------------------------------

def test_watchdog_dump_includes_fleet_straggler_report(tmp_path):
    from homebrewnlp_tpu.obs import Health, Watchdog
    fdir = str(tmp_path / "fleet")
    _post(fdir, 0, [(0, 1.0), (1, 2.0)])
    _post(fdir, 1, [(0, 1.3), (1, 2.3)])
    rep = fleet.FleetReporter(fdir, rank=0, world_size=2)
    health = Health(stall_factor=2.0, min_stall_s=0.05)
    health.step_completed(0)
    health.step_completed(1)
    wd = Watchdog(health, str(tmp_path / "run"), factor=2.0, poll_s=0.05,
                  min_stall_s=0.05, registry=MetricsRegistry(),
                  extra_fn=rep.skew_summary)
    wd.start()
    try:
        deadline = time.time() + 10
        while not wd.dumps and time.time() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
        rep.close()
    assert wd.dumps, "watchdog never fired"
    text = open(wd.dumps[0]).read()
    assert '"straggler_rank": 1' in text.replace("'", '"') or \
        '"straggler_rank": 1' in text
    assert "fleet:" in text


# -- THE composed drill: two supervised processes with fleet obs --------------

@pytest.mark.slow  # ~90s: two supervisors x two generations of children;
# the CI fleet-obs job runs it explicitly
def test_fleet_obs_two_supervised_processes(tmp_path, eight_devices):
    """Acceptance drill (CI ``fleet-obs``): the PR-10 lockstep drill
    (peer:die@step4 under two real per-host supervisors) now produces the
    full fleet-observability surface — a federated /metrics with both
    ranks labeled plus fleet aggregates, per-rank supervisor proms, a
    populated skew report over the common steps, a two-lane merged trace,
    and a green ``graftfleet --check``."""
    steps = 10
    fleet_dir = str(tmp_path / "fleet")
    child = os.path.join(REPO, "tests", "elastic_child.py")
    sup_py = os.path.join(REPO, "tools", "supervise.py")
    procs = []
    for r in range(2):
        model = str(tmp_path / f"host{r}")
        cmd = [sys.executable, sup_py, "--model-path", model,
               "--rank", str(r), "--world-size", "2",
               "--fleet-dir", fleet_dir, "--peer-timeout", "120",
               "--backoff-jitter", "0", "--backoff-base", "0.1", "--",
               sys.executable, child, "--model-path", model,
               "--steps", str(steps), "--fault-plan", "peer:die@step4",
               "--obs-spans"]
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, p in enumerate(procs):
        assert p.returncode == 0, f"rank{r} supervisor rc={p.returncode}:\n" \
                                  f"{outs[r][-3000:]}"
    d = fleet.obs_dir(fleet_dir)
    files = set(os.listdir(d))
    assert {"steps_r0.jsonl", "steps_r1.jsonl", "metrics_r0.prom",
            "metrics_r1.prom", "trace_r0.json", "trace_r1.json",
            "supervisor_r0.prom", "supervisor_r1.prom"} <= files, files
    # federated /metrics: both ranks labeled + fleet aggregates
    fed = fleet.FleetFederation(fleet_dir, world_size=2)
    errors = []
    text = fleet.federate(fed.rank_texts(), errors=errors)
    assert not errors, errors
    for series in ('hbnlp_train_steps_total{rank="0"}',
                   'hbnlp_train_steps_total{rank="1"}',
                   'hbnlp_train_steps_total{rank="fleet"}'):
        assert series in text, series
    # run-start markers carry per-rank identity + the relaunch generation
    for r in range(2):
        markers = [json.loads(l) for l in
                   (tmp_path / f"host{r}" / "metrics.jsonl")
                   .read_text().splitlines() if '"run_start"' in l]
        assert markers and all(m["rank"] == r and m["world_size"] == 2
                               for m in markers), markers
        assert markers[-1]["generation"] >= 1  # the lockstep relaunch
    # skew report populated over the generation-matched steps (a rank
    # SIGTERMed a step later than its peer re-posts one step fewer in
    # generation 1, so a small generation-skipped tail is legitimate)
    report = fleet.straggler_report(fleet.read_step_posts(fleet_dir))
    assert (report["n_common_steps"]
            + report["n_generation_skipped"]) == steps, report
    assert report["n_common_steps"] >= steps - 4, report
    assert report["skew_ms"] is not None
    # merged trace: two lanes (no cross-rank barriers in this drill — the
    # offset bound comes from the fleet_obs dryrun, which has them)
    merged = fleet.merge_traces(fleet.read_traces(fleet_dir))
    lanes = {e["pid"] for e in merged["traceEvents"] if e.get("ph") == "X"}
    assert lanes == {0, 1}, lanes
    # graftfleet --check gates green on this dir
    assert graftfleet.main([fleet_dir, "--check"]) == 0
    # fleet healthz sees both ranks
    snap = fleet.FleetFederation(fleet_dir, world_size=2).snapshot()
    assert snap["status"] == "ok" and set(snap["ranks"]) == {"0", "1"}
