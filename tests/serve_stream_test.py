"""Token-level serving observability tests (docs/observability.md
"Streaming and inter-token latency"): SSE streaming on both engines
(chunks concatenate byte-identically to the buffered completion), the
inter-token-latency / decode-step SLO surfaces, the decode-loop phase
decomposition (contiguous segments summing to the loop wall), prefill
stall attribution, lane-occupancy tracing, the AOT host-side TTFT
resolution pin, and the graftload ``--stream`` client arm."""
from __future__ import annotations

import io
import json
import os
import queue
import sys
import threading
import time
import typing
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from backend import mixer_config  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import graftload  # noqa: E402

from homebrewnlp_tpu.models import init_params  # noqa: E402
from homebrewnlp_tpu.obs.registry import MetricsRegistry  # noqa: E402
from homebrewnlp_tpu.obs.spans import SpanTracer  # noqa: E402
from homebrewnlp_tpu.serve import RestAPI, serve  # noqa: E402
from homebrewnlp_tpu.serve import slo as slo_mod  # noqa: E402
from homebrewnlp_tpu.serve.interface import (CompletionEngine,  # noqa: E402
                                             _RowStream)
from homebrewnlp_tpu.serve.slo import (RequestRecord, ServeSLO,  # noqa: E402
                                       STEP_PHASES)
from homebrewnlp_tpu.utils import random_text_batch  # noqa: E402


def _engine_cfg(**over):
    base = dict(depth=1, sequence_length=12, heads=2, features_per_head=16,
                vocab_size=32, train_batch_size=1, sampling_temperature=0.0,
                use_autoregressive_sampling=True, serve_max_batch=3)
    base.update(over)
    return mixer_config(**base)


@pytest.fixture(scope="module")
def engine_setup():
    cfg = _engine_cfg()
    params, _ = init_params(cfg, random_text_batch(cfg))
    return cfg, params


def _drain(sink: "queue.Queue", timeout: float = 30.0
           ) -> typing.List[typing.List[int]]:
    chunks = []
    while True:
        item = sink.get(timeout=timeout)
        if item is None:
            return chunks
        chunks.append(item)


# -- _RowStream (ordered emission) --------------------------------------------

def test_row_stream_reorders_rows_and_clips_prompt_and_end():
    sink: "queue.Queue" = queue.Queue()
    rec = RequestRecord(1)
    # patch 4, prompt 5 tokens (rows 0 + part of 1), budget ends at 11
    rs = _RowStream(sink, prompt_len=5, end=11, patch=4, first_row=1,
                    rec=rec)
    rs.on_row(2, [80, 81, 82, 83])  # out of order: buffered
    assert sink.qsize() == 0
    rs.on_row(1, [40, 41, 42, 43])  # releases row 1 THEN row 2
    assert sink.get_nowait() == [41, 42, 43]  # token 4 is prompt: clipped
    assert sink.get_nowait() == [80, 81, 82]  # token 11 past end: clipped
    rs.flush_final([0] * 11)  # nothing left
    rs.close()
    assert sink.get_nowait() is None
    # every emission stamped the record; gaps need >= 2 emissions
    assert len(rec.token_times) == 2
    assert len(rec.itl_gaps()) == 1


def test_row_stream_initial_gap_is_emitted_unstamped():
    """Positions the decode loop never rewrites (the seed row of an empty
    prompt under the KV sampler) come from the host-built layout, emitted
    up front WITHOUT a cadence stamp."""
    sink: "queue.Queue" = queue.Queue()
    rec = RequestRecord(2)
    rs = _RowStream(sink, prompt_len=0, end=6, patch=4, first_row=1,
                    initial_tokens=[9, 8, 7, 6, 5, 4, 3, 2], rec=rec)
    assert sink.get_nowait() == [9, 8, 7, 6]  # the seed row, unstamped
    assert rec.token_times == []
    rs.on_row(1, [50, 51, 52, 53])
    assert sink.get_nowait() == [50, 51]  # clipped at end=6
    assert len(rec.token_times) == 1


def test_row_stream_flush_final_covers_unfired_rows():
    sink: "queue.Queue" = queue.Queue()
    rs = _RowStream(sink, prompt_len=2, end=6, patch=2, first_row=1)
    rs.on_row(1, [10, 11])  # row 1 tokens 2..3
    rs.flush_final([0, 1, 10, 11, 20, 21])  # rows 2.. never fired
    rs.close()
    assert _drain(sink, timeout=1) == [[10, 11], [20, 21]]


# -- RequestRecord token stamps ----------------------------------------------

def test_request_record_mark_token_sets_first_token_and_gaps():
    rec = RequestRecord(3)
    rec.mark_token(10.0)
    rec.mark_token(10.5)
    rec.mark_token(10.6)
    assert rec.t_first_token == 10.0
    assert rec.itl_gaps() == pytest.approx([0.5, 0.1])


def test_request_record_mark_token_respects_prior_first_token():
    rec = RequestRecord(4)
    rec.mark_first_token()
    t0 = rec.t_first_token
    rec.mark_token()
    assert rec.t_first_token == t0


# -- ServeSLO token-level surfaces --------------------------------------------

def test_observe_step_feeds_histogram_counters_and_stall():
    reg = MetricsRegistry()
    s = ServeSLO(reg)
    phases = {"admit": 0.001, "prefill": 0.004, "dispatch": 0.002,
              "sync": 0.002, "sample": 0.0005, "emit": 0.0005}
    s.observe_step(0.01, phases, n_active=2, prefill_stall_s=0.004)
    s.observe_step(0.005, {"admit": 0.005}, n_active=0, stepped=False)
    assert s.decode_step.count() == 1  # stepped=False skips the histogram
    assert s.decode_loop.value() == pytest.approx(0.015)
    total = sum(s.step_phase.value(phase=p) for p in STEP_PHASES)
    assert total == pytest.approx(0.015)
    assert s.prefill_stall.value() == pytest.approx(0.004)
    summary = s.summary()
    assert summary["decode_step_s"] is not None
    assert summary["prefill_stall_fraction"] == pytest.approx(0.004 / 0.015,
                                                              abs=1e-6)


def test_finish_observes_itl_gaps():
    reg = MetricsRegistry()
    s = ServeSLO(reg)
    rec = s.begin("/token_completion")
    now = time.perf_counter()
    for dt in (0.0, 0.01, 0.02, 0.04):
        rec.mark_token(now + dt)
    s.finish(rec, 200)
    assert s.itl.count() == 3  # 4 emissions -> 3 gaps
    assert s.summary()["itl_s"] is not None


def test_retry_after_divides_by_lane_count():
    """ISSUE-14 satellite: a batched server drains `lane_count` requests
    concurrently — Retry-After must divide the backlog by it instead of
    overstating by ~the batch factor."""
    import math
    reg = MetricsRegistry()
    s = ServeSLO(reg)
    s.engine.observe(2.0)
    s.set_queue_probe(lambda: 8)
    serialized = s.retry_after_s(1.0)
    assert serialized == math.ceil(8 * s.engine.quantile(0.5))
    s.set_lane_count(4)
    batched = s.retry_after_s(1.0)
    assert batched == math.ceil(8 * s.engine.quantile(0.5) / 4)
    assert batched < serialized


def test_lane_occupancy_gauge_sentinel_and_probe():
    reg = MetricsRegistry()
    s = ServeSLO(reg)
    assert s.lane_occupancy() == -1  # no scheduler: documented sentinel
    probe = lambda: 3  # noqa: E731
    s.set_lane_probe(probe)
    assert s.lane_occupancy() == 3
    assert "hbnlp_serve_lane_occupancy 3" in reg.render()
    s.clear_lane_probe(lambda: 9)  # not the installed probe: keeps it
    assert s.lane_occupancy() == 3
    s.clear_lane_probe(probe)
    assert s.lane_occupancy() == -1


# -- batch engine: streaming + attribution ------------------------------------

def test_batch_engine_stream_concatenates_to_completion(engine_setup):
    from homebrewnlp_tpu.serve.engine import BatchEngine
    cfg, params = engine_setup
    eng = BatchEngine(cfg, params)
    try:
        for prompt in ([1, 2, 3], [], [7, 8, 9, 10, 11]):
            sink: "queue.Queue" = queue.Queue()
            out = np.asarray(eng.complete_tokens(
                prompt, 0.0, 5, token_sink=sink)).tolist()
            chunks = _drain(sink)
            flat = [t for c in chunks for t in c]
            assert flat == out[len(prompt):], (prompt, chunks, out)
            assert len(chunks) >= 2  # token-by-token, not one blob
            if prompt:  # greedy + a prompt: deterministic across calls
                ref = np.asarray(
                    eng.complete_tokens(prompt, 0.0, 5)).tolist()
                assert out == ref, (prompt, out, ref)
    finally:
        eng.close()


def test_batch_engine_phase_decomposition_sums_to_wall(engine_setup):
    """The acceptance bound: per-iteration phase segments are contiguous,
    so their sum matches the decode-loop wall within 5% (here: exactly,
    by construction)."""
    from homebrewnlp_tpu.serve.engine import BatchEngine, BatchInterface
    cfg, params = engine_setup
    eng = BatchEngine(cfg, params)
    iface = BatchInterface(eng)
    steps: typing.List[tuple] = []
    eng.set_step_observer(
        lambda wall, ph, n, stall, stepped: steps.append(
            (wall, dict(ph), n, stall, stepped)))
    try:
        results = [None] * 4

        def go(i):
            results[i] = iface.complete([1 + i, 2, 3], 0.0, 6)

        threads = [threading.Thread(target=go, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert all(r is not None for r in results)
    finally:
        iface.close()
    assert steps
    for wall, phases, _, _, _ in steps:
        assert set(phases) == set(STEP_PHASES)
        assert sum(phases.values()) == pytest.approx(wall, rel=0.05)
    # 4 requests over 3 lanes: at least one admission prefilled while
    # other lanes were active -> stall attributed
    assert sum(stall for _, _, _, stall, _ in steps) > 0
    # prefill wall was actually attributed somewhere
    assert sum(ph["prefill"] for _, ph, _, _, _ in steps) > 0


def test_batch_engine_stamps_itl_without_a_sink(engine_setup):
    """ITL is the engine's token cadence — stamped for every batch-engine
    request, streamed or not (what a streaming client WOULD have seen)."""
    from homebrewnlp_tpu.serve.engine import BatchEngine
    cfg, params = engine_setup
    eng = BatchEngine(cfg, params)
    rec = RequestRecord(77)
    prev = slo_mod.set_current(rec)
    try:
        eng.complete_tokens([1, 2, 3], 0.0, 5)
    finally:
        slo_mod.set_current(prev)
        eng.close()
    assert len(rec.token_times) >= 2
    assert len(rec.itl_gaps()) == len(rec.token_times) - 1


def test_serving_trace_has_lane_tracks_and_phase_spans(engine_setup,
                                                       tmp_path):
    from homebrewnlp_tpu.serve.engine import BatchEngine
    _, params = engine_setup
    cfg2 = _engine_cfg(serve_trace_path=str(tmp_path / "serve_trace.json"))
    eng = BatchEngine(cfg2, params)
    try:
        eng.complete_tokens([1, 2, 3], 0.0, 5)
        eng.complete_tokens([4, 5], 0.0, 4)
    finally:
        eng.close()
    with open(cfg2.serve_trace_path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    names = {e["name"] for e in events if e.get("ph") == "X"}
    # decode-loop phase spans on the scheduler thread's track
    for phase in ("engine/step", "engine/admit", "engine/prefill",
                  "engine/dispatch", "engine/sync", "engine/sample",
                  "engine/emit"):
        assert phase in names, (phase, sorted(names))
    # per-lane virtual tracks: occupied spans carrying request ids
    tracks = {e["args"]["name"] for e in events
              if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert any(t.startswith("lane") for t in tracks), tracks
    occupied = [e for e in events if e["name"] == "occupied"]
    assert occupied and all("rid" in e["args"] for e in occupied)


def test_aot_engine_host_ttft_respects_step_resolution(tmp_path):
    """ISSUE-14 satellite: the AOT-cached engine stamps TTFT host-side at
    the step-boundary sync — the stamp can never precede the first decode
    step's completion (the documented one-step resolution)."""
    from homebrewnlp_tpu.serve.engine import BatchEngine
    cfg = _engine_cfg(serve_aot_cache_dir=str(tmp_path))
    params, _ = init_params(cfg, random_text_batch(cfg))
    eng = BatchEngine(
        cfg, params, first_token_callback=slo_mod.dispatch_first_token)
    assert eng._graph_ttft is False  # AOT executables carry no callback
    decode_returns: typing.List[float] = []
    real_decode = eng._decode

    def timed_decode(*a, **k):
        out = real_decode(*a, **k)
        decode_returns.append(time.perf_counter())
        return out

    eng._decode = timed_decode
    rec = RequestRecord(88)
    prev = slo_mod.set_current(rec)
    try:
        eng.complete_tokens([1, 2, 3], 0.0, 5)
    finally:
        slo_mod.set_current(prev)
        eng.close()
    assert rec.t_first_token is not None and decode_returns
    # never before the first decode step returned to the host
    assert rec.t_first_token >= decode_returns[0]
    # and exactly once (first stamp wins across repeated step hits)
    assert rec.t_first_token <= rec.t_engine_done


# -- serialized engine streaming ----------------------------------------------

@pytest.mark.parametrize("force_rebuild", (False, True),
                         ids=("kv", "rebuild"))
def test_serialized_engine_streams_on_both_paths(engine_setup,
                                                 force_rebuild):
    cfg, params = engine_setup
    eng = CompletionEngine(cfg, params, force_rebuild=force_rebuild,
                           token_callback=slo_mod.dispatch_token_row)
    for prompt in ([1, 2, 3], []):
        sink: "queue.Queue" = queue.Queue()
        out = np.asarray(eng.complete_tokens(
            prompt, 0.0, 5, token_sink=sink)).tolist()
        chunks = _drain(sink)
        assert [t for c in chunks for t in c] == out[len(prompt):], (
            prompt, chunks, out)
        if prompt:  # greedy + a prompt: deterministic across calls —
            # streaming must not perturb the sampled tokens
            ref = np.asarray(eng.complete_tokens(prompt, 0.0, 5)).tolist()
            assert out == ref


def test_serialized_engine_unarmed_hook_degrades_to_final_chunk(
        engine_setup):
    """token_sink without a token_callback (non-serving construction):
    the sentinel contract still holds — one final chunk, then None."""
    cfg, params = engine_setup
    eng = CompletionEngine(cfg, params)  # no token hook armed
    sink: "queue.Queue" = queue.Queue()
    out = np.asarray(eng.complete_tokens([1, 2, 3], 0.0, 4,
                                         token_sink=sink)).tolist()
    chunks = _drain(sink)
    assert [t for c in chunks for t in c] == out[3:]


# -- REST SSE end to end ------------------------------------------------------

def _post_json(url: str, body: dict, timeout: float = 60.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


@pytest.fixture(scope="module")
def live_batch_server(engine_setup):
    cfg, params = engine_setup
    reg = MetricsRegistry()
    api = RestAPI(cfg, params)
    server = serve(cfg, None, port=0, background=True, registry=reg,
                   obs_port=0, api=api)
    yield server, cfg, api
    server.shutdown()
    server.server_close()
    api.wrapper.close()


def test_rest_sse_stream_matches_buffered_payload(live_batch_server):
    server, cfg, _ = live_batch_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    body = {"prompt": [1, 2, 3], "temperature": 0.0, "response_len": 6}
    with _post_json(url + "/token_completion", body) as r:
        buffered = json.loads(r.read())
        assert r.headers.get("Content-Type") == "application/json"
    events = []
    with _post_json(url + "/token_completion",
                    dict(body, stream=True)) as r:
        assert r.headers.get("Content-Type") == "text/event-stream"
        events = [e for _, e in graftload.read_sse(r)]
    assert len(events) >= 3  # token-by-token, not one blob
    assert events[-1].get("done") is True
    # final event == the buffered response payload (+ done)
    assert events[-1]["completion"] == buffered["completion"]
    assert events[-1]["top_k"] == buffered["top_k"]
    streamed = [t for e in events[:-1] for t in e["tokens"]]
    assert streamed == buffered["completion"][3:]


def test_rest_sse_first_chunk_arrives_before_completion(engine_setup):
    """The headline acceptance: while the client holds the FIRST chunk,
    the server is provably still serving the request.  Decode steps are
    slowed so the remaining-generation window dwarfs the scrape —
    deterministic, unlike comparing client-side arrival timestamps
    (which increase monotonically even for a terminal burst)."""
    cfg, params = engine_setup
    reg = MetricsRegistry()
    api = RestAPI(cfg, params)
    real_decode = api.engine._decode

    def slow_decode(*a, **k):
        time.sleep(0.05)
        return real_decode(*a, **k)

    api.engine._decode = slow_decode
    server = serve(cfg, None, port=0, background=True, registry=reg,
                   obs_port=0, api=api)
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        murl = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
        body = {"prompt": [1, 2, 3], "temperature": 0.0,
                "response_len": 8, "stream": True}
        with _post_json(url + "/token_completion", body, timeout=120) as r:
            it = graftload.read_sse(r)
            _, first = next(it)
            assert "tokens" in first
            # ~6 more slowed steps (>=300ms) remain: the in-flight gauge
            # must still count this request
            with urllib.request.urlopen(murl + "/healthz", timeout=10) as h:
                slo_block = json.loads(h.read())["slo"]
            assert slo_block["inflight"] >= 1, slo_block
            events = [first] + [e for _, e in it]
        assert events[-1].get("done") is True
    finally:
        server.shutdown()
        server.server_close()
        api.engine._decode = real_decode
        api.wrapper.close()


def test_rest_stream_keeps_flowing_past_queue_deadline(engine_setup):
    """Code-review regression: the queue-deadline check must never block
    the SSE drain of an ADMITTED request — fetch() blocks until
    completion once admitted, so running it inline would buffer every
    remaining chunk into one terminal burst.  With 50ms decode steps and
    a 50ms deadline, inter-chunk gaps must stay step-sized."""
    cfg, params = engine_setup
    cfg_dl = _engine_cfg(serve_queue_deadline_s=0.05,
                         default_sleep_duration=0.02)
    reg = MetricsRegistry()
    api = RestAPI(cfg_dl, params)
    real_decode = api.engine._decode

    def slow_decode(*a, **k):
        time.sleep(0.05)
        return real_decode(*a, **k)

    api.engine._decode = slow_decode
    server = serve(cfg_dl, None, port=0, background=True, registry=reg,
                   api=api)
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        body = {"prompt": [1, 2, 3], "temperature": 0.0,
                "response_len": 8, "stream": True}
        times, events = [], []
        with _post_json(url + "/token_completion", body, timeout=120) as r:
            for t, ev in graftload.read_sse(r):
                times.append(t)
                events.append(ev)
        assert events[-1].get("done") is True
        assert "error" not in events[-1]
        chunk_gaps = [times[i] - times[i - 1]
                      for i in range(1, len(times) - 1)]  # token chunks
        # a terminal burst collapses every post-deadline gap to ~0; the
        # fixed drain keeps them at decode-step scale
        assert len(chunk_gaps) >= 3
        assert sorted(chunk_gaps)[len(chunk_gaps) // 2] > 0.02, chunk_gaps
    finally:
        server.shutdown()
        server.server_close()
        api.engine._decode = real_decode
        api.wrapper.close()


def test_rest_completion_text_stream(live_batch_server):
    server, cfg, api = live_batch_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    body = {"prompt": "ab", "temperature": 0.0, "response_len": 4,
            "stream": True}
    events = [e for _, e in graftload.read_sse(
        _post_json(url + "/completion", body))]
    assert events[-1].get("done") is True
    assert "".join(e["text"] for e in events[:-1]) == \
        events[-1]["completion"]


def test_rest_buffered_path_untouched_by_streaming(live_batch_server):
    """Streaming off: the response is exactly the pre-streaming shape —
    no new keys, standard JSON framing (the PR-13 parity contract)."""
    server, cfg, _ = live_batch_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    body = {"prompt": [1, 2, 3], "temperature": 0.0, "response_len": 4}
    with _post_json(url + "/token_completion", body) as r:
        out = json.loads(r.read())
        assert r.headers.get("Content-Type") == "application/json"
        assert r.headers.get("Content-Length") is not None
    assert set(out) == {"completion", "top_k", "top_p"}


def test_rest_stream_request_ignored_when_knob_off(engine_setup):
    cfg, params = engine_setup
    cfg_off = _engine_cfg(serve_stream=False)
    reg = MetricsRegistry()
    api = RestAPI(cfg_off, params)
    server = serve(cfg_off, None, port=0, background=True, registry=reg,
                   api=api)
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        body = {"prompt": [1, 2, 3], "temperature": 0.0,
                "response_len": 4, "stream": True}
        with _post_json(url + "/token_completion", body) as r:
            assert r.headers.get("Content-Type") == "application/json"
            out = json.loads(r.read())
        assert set(out) == {"completion", "top_k", "top_p"}
    finally:
        server.shutdown()
        server.server_close()
        api.wrapper.close()


def test_rest_streamed_shed_still_answers_503():
    """The generator is primed before headers: admission shedding on a
    streamed request maps to the same clean 503 + Retry-After."""
    from homebrewnlp_tpu.serve.interface import QueueDeadlineExceeded

    class ShedAPI:
        ENDPOINTS = ("token_completion",)
        STREAM_ENDPOINTS = ("token_completion",)
        streaming = True

        def token_completion_stream(self, body):
            raise QueueDeadlineExceeded(0.0, 0.2, 3, shed=True)

    reg = MetricsRegistry()
    server = serve(None, None, port=0, background=True, api=ShedAPI(),
                   registry=reg)
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_json(url + "/token_completion", {"stream": True},
                       timeout=10)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") is not None
    finally:
        server.shutdown()
        server.server_close()


def test_live_metrics_and_healthz_token_level(live_batch_server):
    server, cfg, _ = live_batch_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    # a little load so every token-level series is populated
    for i in range(2):
        _post_json(url + "/token_completion",
                   {"prompt": [1 + i, 2], "temperature": 0.0,
                    "response_len": 5}).read()
    murl = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
    with urllib.request.urlopen(murl + "/metrics", timeout=10) as r:
        text = r.read().decode()
    for series in ("hbnlp_serve_itl_seconds", "hbnlp_serve_decode_step_seconds",
                   "hbnlp_serve_step_phase_seconds",
                   "hbnlp_serve_decode_loop_seconds",
                   "hbnlp_serve_prefill_stall_seconds",
                   "hbnlp_serve_lane_occupancy"):
        assert series in text, series
    # the scraped phase decomposition sums to the loop wall within 5%
    metrics = graftload.parse_prom(text)
    loop = sum(v for _, v in metrics["hbnlp_serve_decode_loop_seconds"])
    phases = sum(v for _, v in metrics["hbnlp_serve_step_phase_seconds"])
    assert loop > 0 and phases == pytest.approx(loop, rel=0.05)
    with urllib.request.urlopen(murl + "/healthz", timeout=10) as r:
        slo_block = json.loads(r.read())["slo"]
    assert slo_block["itl_s"] is not None
    assert slo_block["decode_step_s"] is not None
    assert slo_block["prefill_stall_fraction"] is not None
    assert slo_block["lane_occupancy"] is not None


def test_graftload_stream_reconciles_itl_and_ttft(live_batch_server):
    server, cfg, _ = live_batch_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    murl = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
    report = graftload.drive(url, metrics_url=murl, n_requests=6,
                             concurrency=2, vocab=cfg.vocab_size,
                             min_prompt=2, max_prompt=6, response_len=5,
                             temperature=0.0, seed=5, stream=True)
    c = report["client"]
    assert c["error_rate"] == 0.0
    assert c["ttft_s"]["p50"] > 0
    assert c["itl_s"]["p50"] > 0
    rec = report["reconcile"]
    assert rec["itl"]["within_tolerance"], rec
    assert rec["ttft"]["within_tolerance"], rec
    assert graftload.check_ok(report)


# -- graftload units ----------------------------------------------------------

def test_read_sse_parses_data_lines():
    fp = io.BytesIO(b"data: {\"tokens\": [1, 2]}\n\n"
                    b": comment\n"
                    b"data: {\"done\": true}\n\n")
    events = [e for _, e in graftload.read_sse(fp)]
    assert events == [{"tokens": [1, 2]}, {"done": True}]


def test_client_report_stream_fields_absent_without_streaming():
    records = [{"id": 0, "status": 200, "e2e_s": 0.5,
                "tokens_generated": 4}]
    rep = graftload.client_report(records, [], 1.0)
    assert "ttft_s" not in rep and "itl_s" not in rep


def test_check_ok_requires_token_arms_within_tolerance():
    base = {"client": {"error_rate": 0.0, "truncated": False},
            "reconcile": {"within_tolerance": True,
                          "itl": {"within_tolerance": False}}}
    assert not graftload.check_ok(base)
    base["reconcile"]["itl"]["within_tolerance"] = True
    assert graftload.check_ok(base)


def test_post_stream_rejects_buffered_response(engine_setup):
    """Code-review regression: --stream against a serve_stream=false (or
    pre-streaming) server must fail loudly, not pass as an empty stream
    that measured nothing."""
    cfg, params = engine_setup
    cfg_off = _engine_cfg(serve_stream=False)
    reg = MetricsRegistry()
    api = RestAPI(cfg_off, params)
    server = serve(cfg_off, None, port=0, background=True, registry=reg,
                   api=api)
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        with pytest.raises(RuntimeError, match="did not stream"):
            graftload._post_stream(
                url + "/token_completion",
                {"prompt": [1, 2, 3], "temperature": 0.0,
                 "response_len": 4}, 30.0)
        # and through the drive: every record errors, the check fails
        report = graftload.drive(url, n_requests=2, concurrency=1,
                                 vocab=cfg_off.vocab_size, min_prompt=2,
                                 max_prompt=4, response_len=3,
                                 temperature=0.0, stream=True)
        assert report["client"]["error_rate"] == 1.0
        assert not graftload.check_ok(report)
    finally:
        server.shutdown()
        server.server_close()
        api.wrapper.close()


def test_bench_stream_delta_reconcile_ignores_prior_load():
    """Code-review regression: the bench streaming probe reconciles over
    the pre/post scrape DELTA — a cumulative histogram dominated by the
    main drive's queued TTFTs must not flag the idle probe's clocks."""
    import bench
    reg = MetricsRegistry()
    s = ServeSLO(reg)
    for _ in range(20):  # "main drive": queued TTFTs far above the probe
        s.ttft.observe(40.0)
        s.itl.observe(2.0)
    pre = reg.render()
    for _ in range(8):  # the probe's own requests
        s.ttft.observe(0.02)
        s.itl.observe(0.004)
    post = reg.render()
    client = {"ttft_s": {"p50": 0.02}, "itl_s": {"p50": 0.004}}
    arms = bench._stream_delta_reconcile(client, pre, post)
    assert arms["ttft"]["within_tolerance"], arms
    assert arms["itl"]["within_tolerance"], arms
    # the delta isolates the probe's own requests: server p50 reflects
    # the 0.02s probe, not the 40s main-drive TTFTs the cumulative
    # histogram is dominated by
    assert arms["ttft"]["server_p50_s"] < 0.1, arms
    cum = bench._stream_delta_reconcile(client, "", post)
    assert cum["ttft"]["server_p50_s"] > 1.0, cum  # the polluted view


def test_evaluate_serve_baseline_token_ratchets():
    import bench
    row = {"e2e_p50_s": 1.0, "goodput_tok_s": 10.0, "itl_p50": 0.010,
           "stream_ttft_s": 0.2, "prefill_stall_fraction": 0.30}
    base = {"e2e_p50_s": 1.0, "goodput_tok_s": 10.0, "itl_p50": 0.010,
            "stream_ttft_s": 0.2, "prefill_stall_fraction": 0.10}
    out, ok = bench.evaluate_serve_baseline(row, base)
    # stall fraction 0.30 > 0.10 * 1.5 + 0.05 = 0.20 -> fail
    assert not ok and not out["prefill_stall_fraction"]["pass"]
    assert out["itl_p50"]["pass"] and out["stream_ttft_s"]["pass"]
    row["prefill_stall_fraction"] = 0.15  # inside the slack
    row["itl_p50"] = 0.020  # 2x the baseline -> fail the ITL ratchet
    out, ok = bench.evaluate_serve_baseline(row, base)
    assert not ok and not out["itl_p50"]["pass"]
    assert out["prefill_stall_fraction"]["pass"]
    row["itl_p50"] = 0.011
    out, ok = bench.evaluate_serve_baseline(row, base)
    assert ok


# -- span tracer virtual tracks -----------------------------------------------

def test_span_tracer_virtual_tracks_get_named_lanes():
    tr = SpanTracer(mirror_jax=False)
    tr.add("occupied", 1.0, 2.0, track="lane0", rid=7)
    tr.add("occupied", 1.5, 2.5, track="lane1", rid=8)
    tr.add("host_span", 1.0, 1.1)  # thread track, unaffected
    events = tr.chrome_events()
    meta = {e["args"]["name"]: e["tid"] for e in events
            if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert "lane0" in meta and "lane1" in meta
    assert meta["lane0"] != meta["lane1"]
    lane0 = [e for e in events if e.get("tid") == meta["lane0"]
             and e.get("ph") == "X"]
    assert lane0 and lane0[0]["args"]["rid"] == "7"
