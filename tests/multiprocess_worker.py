"""Worker for the real multi-process SPMD test (multiprocess_test.py).

Runs as ``python multiprocess_worker.py <rank> <port>``: joins a 2-process
jax.distributed cluster (4 virtual CPU devices per process -> 8 global),
builds the framework's data x model mesh spanning both processes, feeds its
local half of the batch through data/feed.py, and runs 5 train steps.  The
cross-process gradient all-reduce and head-sharded matmul collectives ride
the gloo backend — the CPU stand-in for the reference's multi-host story
(SURVEY.md §5.8: TF distributed session over DCN)."""
import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax.extend  # noqa: E402

# the sitecustomize-registered accelerator plugin initializes backends at
# interpreter start; clear them so the distributed CPU cluster forms
jax.extend.backend.clear_backends()
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=2,
                           process_id=rank)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.config import Config  # noqa: E402
from homebrewnlp_tpu.data import synthetic_text_batch, to_global  # noqa: E402
from homebrewnlp_tpu.parallel import make_mesh  # noqa: E402
from homebrewnlp_tpu.train import Trainer  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8


def run_case(name, **over):
    base = dict(
        model_mode="gpt", use_video=False, sequence_length=16, heads=4,
        features_per_head=32, vocab_size=64, depth=1, train_batch_size=8,
        memory_reduction_strategy="none", optimizer="adam-learning_rate",
        learning_rate=1e-2, weight_decay=0.0,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}])
    base.update(over)
    cfg = Config(base)
    mesh = make_mesh(cfg)
    trainer = Trainer(cfg, mesh)
    full = synthetic_text_batch(cfg, 0)
    rows = full["token_x"].shape[0] // 2
    local = {k: v[rank * rows:(rank + 1) * rows] for k, v in full.items()}
    state = trainer.init(to_global(local, cfg, mesh))
    losses = []
    for i in range(5):
        gb = to_global(local, cfg, mesh)
        state, m = trainer.step(state, gb, jax.random.key(i))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], (name, losses)
    # full-precision full sequence: the harness compares this line across
    # ranks to catch any cross-process divergence, not just the endpoints
    print(f"rank{rank}: {name} mesh={dict(mesh.shape)} "
          f"losses={[x.hex() for x in losses]}", flush=True)


# 1) data x model parallel: cross-process gradient all-reduce + head-sharded
#    matmul collectives
run_case("dp_tp")
# 2) data x sequence x model: ring attention's ppermute ring crosses the
#    process boundary (long-context sequence parallelism over "DCN")
run_case("dp_sp_tp", heads=2, sequence_parallel=2, sequence_length=32,
         block_config=[
             {"layer": ["norm-shift-scale",
                        "attention-in:relu-dot_product-embedded-relative"]},
             {"layer": ["norm-shift-scale", "feed_forward-in:relu"]}])
print(f"rank{rank}: MULTIPROC_OK", flush=True)
