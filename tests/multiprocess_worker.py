"""Worker for the real multi-process SPMD test (multiprocess_test.py).

Runs as ``python multiprocess_worker.py <rank> <port>``: joins a 2-process
jax.distributed cluster (4 virtual CPU devices per process -> 8 global),
builds the framework's data x model mesh spanning both processes, feeds its
local half of the batch through data/feed.py, and runs 5 train steps.  The
cross-process gradient all-reduce and head-sharded matmul collectives ride
the gloo backend — the CPU stand-in for the reference's multi-host story
(SURVEY.md §5.8: TF distributed session over DCN)."""
import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]
NPROCS = int(sys.argv[3]) if len(sys.argv) > 3 else 2
CKPT_DIR = sys.argv[4] if len(sys.argv) > 4 else ""
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax.extend  # noqa: E402

# the sitecustomize-registered accelerator plugin initializes backends at
# interpreter start; clear them so the distributed CPU cluster forms
jax.extend.backend.clear_backends()
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8 // NPROCS)
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=NPROCS,
                           process_id=rank)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from homebrewnlp_tpu.config import Config  # noqa: E402
from homebrewnlp_tpu.data import synthetic_text_batch, to_global  # noqa: E402
from homebrewnlp_tpu.parallel import make_mesh  # noqa: E402
from homebrewnlp_tpu.train import Trainer  # noqa: E402

assert jax.process_count() == NPROCS, jax.process_count()
assert len(jax.devices()) == 8


def run_case(name, **over):
    base = dict(
        model_mode="gpt", use_video=False, sequence_length=16, heads=4,
        features_per_head=32, vocab_size=64, depth=1, train_batch_size=8,
        memory_reduction_strategy="none", optimizer="adam-learning_rate",
        learning_rate=1e-2, weight_decay=0.0,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}])
    base.update(over)
    cfg = Config(base)
    mesh = make_mesh(cfg)
    trainer = Trainer(cfg, mesh)
    full = synthetic_text_batch(cfg, 0)
    # processes sharing a data coordinate (pipe spanning hosts) load the
    # SAME rows — the data_slice_for_process contract
    from homebrewnlp_tpu.data.feed import data_slice_for_process
    si, sc = data_slice_for_process(mesh)
    rows = full["token_x"].shape[0] // sc
    local = {k: v[si * rows:(si + 1) * rows] for k, v in full.items()}
    state = trainer.init(to_global(local, cfg, mesh))
    losses = []
    for i in range(5):
        gb = to_global(local, cfg, mesh)
        state, m = trainer.step(state, gb, jax.random.key(i))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], (name, losses)
    # full-precision full sequence: the harness compares this line across
    # ranks to catch any cross-process divergence, not just the endpoints
    print(f"rank{rank}: {name} mesh={dict(mesh.shape)} "
          f"losses={[x.hex() for x in losses]}", flush=True)
    return cfg, mesh, trainer, state, local


if NPROCS == 2:
    # 1) data x model parallel: cross-process gradient all-reduce +
    #    head-sharded matmul collectives
    run_case("dp_tp")
    # 2) data x sequence x model: ring attention's ppermute ring crosses the
    #    process boundary (long-context sequence parallelism over "DCN")
    run_case("dp_sp_tp", heads=2, sequence_parallel=2, sequence_length=32,
             block_config=[
                 {"layer": ["norm-shift-scale",
                            "attention-in:relu-dot_product-embedded-relative"]},
                 {"layer": ["norm-shift-scale", "feed_forward-in:relu"]}])
else:
    # 4 processes x 2 devices (VERDICT r3 item 7):
    # a) pipe axis ACROSS process boundaries: pipeline_parallel=4 with
    #    data=2 makes each pipe ring span two processes — the GPipe
    #    activation hops and their gradient transposes ride the gloo "DCN"
    run_case("dp_pp", heads=1, pipeline_parallel=4, depth=4,
             memory_reduction_strategy="none")
    # ...and the 1F1B interleaved schedule over the same cross-process ring
    run_case("dp_pp_1f1b", heads=1, pipeline_parallel=4, depth=4,
             pipeline_schedule="1f1b", memory_reduction_strategy="none")
    # c) seq x pipe COMPOSED across processes: the nested seq-manual ring
    #    (ops/ring.py) rotates K/V blocks over one process boundary while
    #    the pipe ring hops activations over another — both collectives
    #    ride the gloo "DCN" inside one 1F1B step
    run_case("sp_pp_1f1b", heads=2, sequence_parallel=2, pipeline_parallel=2,
             depth=2, sequence_length=32, train_batch_size=16,
             pipeline_schedule="1f1b", memory_reduction_strategy="none",
             block_config=[
                 {"layer": ["norm-shift-scale",
                            "attention-in:relu-dot_product-embedded-relative"]},
                 {"layer": ["norm-shift-scale", "feed_forward-in:relu"]}])
    # b) orbax save/restore under jax.distributed with PER-PROCESS data
    #    cursors (each host's reader position differs; the sidecar is
    #    per-process like the reference's per-host DataLog)
    from homebrewnlp_tpu.train import Checkpointer
    cfg, mesh, trainer, state, local = run_case("dp_tp_ckpt")
    assert CKPT_DIR, "4-process mode needs a shared checkpoint dir argv[4]"
    ckpt = Checkpointer(CKPT_DIR)
    ckpt.save(state, data_state={"cursor": 1000 + rank})
    ckpt.wait()
    trainer2 = Trainer(cfg, make_mesh(cfg))
    template = trainer2.init(to_global(local, cfg, mesh))
    restored, ds = Checkpointer(CKPT_DIR).restore(template)
    assert int(restored.step) == 5, int(restored.step)
    assert ds == {"cursor": 1000 + rank}, ds
    import numpy as np
    for k in state.params:
        for sa, sb in zip(state.params[k].addressable_shards,
                          restored.params[k].addressable_shards):
            np.testing.assert_array_equal(np.asarray(sa.data),
                                          np.asarray(sb.data), err_msg=k)
    print(f"rank{rank}: ckpt restored step=5 cursor={ds['cursor']}",
          flush=True)
print(f"rank{rank}: MULTIPROC_OK", flush=True)
