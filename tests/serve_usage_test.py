"""Per-tenant usage metering tests (``obs/usage.py``; docs/observability.md
"Usage metering & capacity"): tenant validation, the Misra-Gries top-K
sketch (exactness, tail fold, bounded memory under a 10k-distinct-tenant
drill), the at-most-once finalize guard, billing rules (tokens/flops on
200s only), KV block-second settlement against hand-built lane timelines,
flops pricing against the cost model's jaxpr anchor, the router's exact
cross-replica federation, and the LIVE loop: graftload ``--tenants``
client counts reconciling EXACTLY with the server's metered totals under
buffered, streamed, chunked-prefill, SSE-disconnect and ``replica:die``
failover traffic (the ``@slow`` drill — the CI ``meter-smoke`` job runs
the live arms explicitly)."""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import types
import typing
import urllib.error
import urllib.request

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from backend import mixer_config  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import graftload  # noqa: E402
import graftmeter  # noqa: E402

from homebrewnlp_tpu.models import init_params  # noqa: E402
from homebrewnlp_tpu.obs import usage as usage_mod  # noqa: E402
from homebrewnlp_tpu.obs.flight import (FlightRecorder,  # noqa: E402
                                        request_trail)
from homebrewnlp_tpu.obs.registry import MetricsRegistry  # noqa: E402
from homebrewnlp_tpu.obs.usage import (ANON, OTHER,  # noqa: E402
                                       HeavyHitters, UsageMeter,
                                       clean_tenant, merge_usage,
                                       price_serve_executables)
from homebrewnlp_tpu.serve import RestAPI, serve  # noqa: E402
from homebrewnlp_tpu.utils import random_text_batch  # noqa: E402


class _Rec:
    """A finished-request stand-in carrying exactly the fields
    UsageMeter.finalize reads off a RequestRecord."""

    def __init__(self, tenant: str, prompt: int = 3, gen: int = 4,
                 qw: float = 0.01, kv: float = 0.5, lane: float = 0.2):
        self.tenant = tenant
        self.prompt_tokens = prompt
        self.tokens_generated = gen
        self.kv_block_seconds = kv
        self.lane_seconds = lane
        self.usage_done = False
        self._qw = qw

    def queue_wait_s(self):
        return self._qw


# -- tenant identity ----------------------------------------------------------


def test_clean_tenant_validation():
    assert clean_tenant("acme-prod") == "acme-prod"
    assert clean_tenant("a.b:c_d-9") == "a.b:c_d-9"
    # missing / empty / whitespace-only -> anon
    for bad in (None, "", "   "):
        assert clean_tenant(bad) == ANON
    # bad charset, over-long -> anon (never a 400: identity is advisory)
    assert clean_tenant('evil"label') == ANON
    assert clean_tenant("x" * 65) == ANON
    assert clean_tenant("has space") == ANON
    # reserved rows cannot be claimed or spoofed into distinct series
    assert clean_tenant(OTHER) == ANON
    assert clean_tenant(ANON) == ANON


def test_config_usage_knobs_validate():
    cfg = mixer_config(depth=1, sequence_length=12, heads=2,
                       features_per_head=16, vocab_size=32,
                       train_batch_size=1)
    assert cfg.usage_top_k == 32
    assert cfg.usage_tenant_header == "X-Tenant"
    with pytest.raises(ValueError, match="usage_top_k"):
        mixer_config(depth=1, sequence_length=12, heads=2,
                     features_per_head=16, vocab_size=32,
                     train_batch_size=1, usage_top_k=-1)


# -- the sketch ---------------------------------------------------------------


def test_heavy_hitters_topk_exact_and_bounded():
    hh = HeavyHitters(3)
    for _ in range(10):
        hh.admit("big")
    for i in range(5):
        hh.admit(f"small{i}")
    # the bound: never more than k slots, ever
    assert len(hh.weight) <= 3
    # the Frequent guarantee: frequency > n/(k+1) stays tracked
    assert "big" in hh.weight


def test_heavy_hitters_eviction_reports_freed_slots():
    hh = HeavyHitters(2)
    assert hh.admit("a") == (True, [])
    assert hh.admit("b") == (True, [])
    # full table, miss: every weight decrements, both zero out, newcomer
    # takes a freed slot — the evicted names come back for the fold
    tracked, evicted = hh.admit("c")
    assert tracked and sorted(evicted) == ["a", "b"]
    assert "c" in hh.weight and len(hh.weight) <= 2


def test_10k_tenant_drill_bounded_memory_and_metrics():
    top_k = 32
    meter = UsageMeter(top_k)
    reg = MetricsRegistry()
    reg.register_collector(meter.prom_lines)
    for i in range(10_000):
        meter.finalize(_Rec(f"tenant{i}"), 200)
    # memory bound: at most K exact rows + the fold row, no matter how
    # many distinct tenants hit the server
    assert len(meter._tenants) <= top_k
    assert len(meter._sketch.weight) <= top_k
    # /metrics stays bounded: 5 families x (K+1) children + HELP/TYPE
    text = reg.render()
    tenant_lines = [ln for ln in text.splitlines()
                    if ln.startswith("hbnlp_serve_") and "tenant=" in ln]
    assert 0 < len(tenant_lines) <= (top_k + 1) * 6
    s = meter.summary()
    assert s["tracked_tenants"] <= top_k
    assert s["folds"] > 0
    # exact-totals invariant: every one of the 10k records landed in
    # exactly one row; the rows sum back to the overall totals TO THE TOKEN
    assert s["totals"]["requests"] == 10_000
    for field in ("requests", "prompt_tokens", "generated_tokens"):
        assert sum(r[field] for r in s["per_tenant"].values()) \
            == s["totals"][field]
    assert not graftmeter.row_sum_problems(s)


def test_fold_moves_exact_accumulators_into_other():
    meter = UsageMeter(1)
    meter.finalize(_Rec("a", prompt=10, gen=20), 200)
    meter.finalize(_Rec("b", prompt=1, gen=2), 200)   # evicts a -> other
    s = meter.summary()
    per = s["per_tenant"]
    assert OTHER in per
    # a's exact accumulators moved whole into other (series restart on
    # re-admission is the consumer's clamp problem, not a token leak)
    assert per[OTHER]["prompt_tokens"] == 10
    assert per[OTHER]["generated_tokens"] == 20
    assert s["totals"]["prompt_tokens"] == 11
    assert s["totals"]["generated_tokens"] == 22


# -- finalize semantics -------------------------------------------------------


def test_finalize_at_most_once():
    meter = UsageMeter(4)
    rec = _Rec("t0")
    assert meter.finalize(rec, 200) is True
    assert meter.finalize(rec, 200) is False   # SSE-disconnect double call
    assert meter.summary()["totals"]["requests"] == 1


def test_billing_rules_tokens_on_200_only():
    meter = UsageMeter(4, pricing={"prefill_flops": 100.0,
                                   "decode_flops_per_token": 10.0})
    meter.finalize(_Rec("t0", prompt=5, gen=7), 200)
    meter.finalize(_Rec("t0", prompt=5, gen=7, kv=0.25, lane=0.1), 503)
    row = meter.summary()["per_tenant"]["t0"]
    assert row["requests"] == 2 and row["errors"] == 1
    # tokens + flops billed for the 200 only (the client-verifiable
    # counts); capacity (block/lane seconds) accrues for BOTH
    assert row["prompt_tokens"] == 5 and row["generated_tokens"] == 7
    assert row["flops"] == pytest.approx(100.0 + 10.0 * 7)
    assert row["kv_block_seconds"] == pytest.approx(0.75)
    assert row["lane_seconds"] == pytest.approx(0.3)


def test_price_formula_and_missing_pricing():
    meter = UsageMeter(4, pricing={"prefill_flops": 7.0,
                                   "decode_flops_per_token": 3.0})
    assert meter.price(100, 5) == pytest.approx(7.0 + 15.0)
    assert meter.price(100, 0) == pytest.approx(7.0)
    assert UsageMeter(4).price(100, 5) is None


# -- KV block-seconds against a hand-built lane timeline ----------------------


def test_settle_kv_block_seconds_timeline():
    from homebrewnlp_tpu.serve.engine import BatchEngine
    now = time.perf_counter()
    rec = types.SimpleNamespace(kv_blocks=None, kv_block_seconds=None,
                                lane_seconds=None)
    req = types.SimpleNamespace(rec=rec, n_blocks=3, t_alloc=now - 2.0,
                                t_admitted=now - 1.5)
    BatchEngine._settle_kv(None, req)
    # 3 blocks held for ~2s of wall -> ~6 block-seconds; lane time runs
    # from admission (decode occupancy), not allocation
    assert rec.kv_blocks == 3
    assert rec.kv_block_seconds == pytest.approx(6.0, abs=0.5)
    assert rec.lane_seconds == pytest.approx(1.5, abs=0.5)
    # allocation-only (admission failed before t_admitted): falls back to
    # the alloc stamp so capacity consumed pre-failure still accrues
    rec2 = types.SimpleNamespace(kv_blocks=None, kv_block_seconds=None,
                                 lane_seconds=None)
    req2 = types.SimpleNamespace(rec=rec2, n_blocks=2,
                                 t_alloc=time.perf_counter() - 1.0,
                                 t_admitted=None)
    BatchEngine._settle_kv(None, req2)
    assert rec2.kv_block_seconds == pytest.approx(2.0, abs=0.5)
    assert rec2.lane_seconds == pytest.approx(1.0, abs=0.5)
    # no record attached: settlement is a no-op, not a crash
    BatchEngine._settle_kv(None, types.SimpleNamespace(rec=None))


# -- flops pricing vs the cost-model anchor -----------------------------------


def test_price_serve_executables_matches_jaxpr_anchor():
    import functools

    import jax

    from homebrewnlp_tpu.serve import engine as serve_engine
    from homebrewnlp_tpu.train.flops import jaxpr_flops
    cfg = mixer_config(depth=1, sequence_length=12, heads=2,
                       features_per_head=16, vocab_size=32,
                       train_batch_size=1, sampling_temperature=0.0,
                       use_autoregressive_sampling=True, serve_max_batch=2)
    params, _ = init_params(cfg, random_text_batch(cfg))
    sheet = price_serve_executables(cfg, params)
    assert sheet is not None
    patch = sheet["patch"]
    rows, n_lanes = sheet["rows"], sheet["n_lanes"]
    assert rows == int(cfg.sequence_length) // patch and n_lanes == 2
    # the anchor: the SAME analytic counter (train/flops.py::jaxpr_flops)
    # over the SAME executables the scheduler compiles must agree exactly
    decode_abs, prefill_abs, _ = serve_engine.abstract_exec_args(
        cfg, params, rows, n_lanes)
    dec = functools.partial(serve_engine.decode_body, cfg, rows, n_lanes,
                            None)
    anchor = float(jaxpr_flops(jax.make_jaxpr(dec)(*decode_abs)))
    assert sheet["decode_step_flops"] == pytest.approx(anchor, rel=1e-9)
    assert anchor > 0 and sheet["prefill_flops"] > 0
    # the marginal per-token price spreads one step over lanes x patch
    assert sheet["decode_flops_per_token"] * n_lanes * patch \
        == pytest.approx(sheet["decode_step_flops"])
    # a non-traceable config prices to None, never raises
    assert price_serve_executables(object(), params) is None


# -- registry collector hook --------------------------------------------------


def test_registry_collector_hook_render_and_unregister():
    reg = MetricsRegistry()
    lines = ["# HELP x_total t", "# TYPE x_total counter",
             'x_total{tenant="a"} 1']
    fn = lambda: list(lines)  # noqa: E731
    reg.register_collector(fn)
    reg.register_collector(fn)      # idempotent
    assert reg.render().count('x_total{tenant="a"} 1') == 1
    om = reg.render_openmetrics()
    # collector lines render BEFORE the EOF terminator
    assert om.index('x_total{tenant="a"} 1') < om.index("# EOF")
    reg.unregister_collector(fn)
    assert "x_total" not in reg.render()
    reg.unregister_collector(fn)    # no-op, no raise


def test_registry_collector_failure_is_contained():
    reg = MetricsRegistry()
    reg.counter("ok_total", "t").inc()

    def bad():
        raise RuntimeError("collector died")

    reg.register_collector(bad)
    assert "ok_total" in reg.render()   # scrape survives the bad collector


# -- capacity + rates ---------------------------------------------------------


def test_capacity_utilization_and_saturation():
    cap = {"device_kind": "TPU v4", "n_devices": 4,
           "peak_flops_per_s": 100.0}
    rates = {"window_s": 10.0, "flops_per_s": 25.0, "tokens_per_s": 50.0,
             "mean_inflight": 2.0}
    out = usage_mod._capacity_block(cap, rates)
    assert out["capacity_utilization"] == pytest.approx(0.25)
    # mean in-flight 2 at 25% utilization projects saturation at depth 8
    assert out["projected_saturation_concurrency"] == pytest.approx(8.0)
    # CPU hosts price no peak: utilization honestly None, never 0
    out = usage_mod._capacity_block({"device_kind": "cpu", "n_devices": 1,
                                     "peak_flops_per_s": None}, rates)
    assert out["capacity_utilization"] is None
    assert out["projected_saturation_concurrency"] is None
    assert usage_mod._capacity_block(None, rates) is None


def test_serve_capacity_ceiling_shape():
    from homebrewnlp_tpu.analysis.cost_model import serve_capacity_ceiling
    cap = serve_capacity_ceiling()
    assert set(cap) == {"device_kind", "n_devices", "peak_flops_per_s"}
    assert cap["n_devices"] >= 1
    if cap["device_kind"] == "cpu":     # the tier-1 environment
        assert cap["peak_flops_per_s"] is None


def test_summary_rates_from_window():
    meter = UsageMeter(4)
    meter.finalize(_Rec("t0"), 200)
    time.sleep(0.02)
    meter.finalize(_Rec("t0", prompt=7, gen=9), 200)
    rates = meter.summary()["rates"]
    assert rates is not None and rates["window_s"] > 0
    # the window spans finalize #1 -> #2, so it carries request #2's tokens
    assert rates["tokens_per_s"] > 0


# -- federation ---------------------------------------------------------------


def _metered(top_k: int, tenants: typing.Dict[str, int]) -> dict:
    m = UsageMeter(top_k)
    for name, n in tenants.items():
        for _ in range(n):
            m.finalize(_Rec(name), 200)
    return m.summary()


def test_merge_usage_sums_exactly_and_refolds():
    a = _metered(8, {"t0": 3, "t1": 2})
    b = _metered(8, {"t1": 4, "t2": 1})
    merged = merge_usage([a, b, None, {"bogus": True}], top_k=8)
    assert merged["replicas"] == 2
    per = merged["per_tenant"]
    # disjoint accounts of disjoint requests: counters SUM exactly
    assert per["t0"]["requests"] == 3
    assert per["t1"]["requests"] == 6
    assert per["t2"]["requests"] == 1
    assert merged["totals"]["requests"] == 10
    assert merged["totals"]["prompt_tokens"] == sum(
        r["prompt_tokens"] for r in per.values())
    # re-fold: a tighter fleet top-K folds the tail into other but loses
    # nothing — the totals still balance to the token
    refolded = merge_usage([a, b], top_k=1)
    rper = refolded["per_tenant"]
    assert set(rper) == {"t1", OTHER}   # t1 has the token volume
    assert sum(r["requests"] for r in rper.values()) == 10
    assert not graftmeter.row_sum_problems(refolded)
    assert merge_usage([None, {}], top_k=4) is None


def test_router_status_federates_usage():
    from homebrewnlp_tpu.serve.router import Replica, Router
    router = Router([Replica("http://a", "http://a", name="r0"),
                     Replica("http://b", "http://b", name="r1")],
                    health_interval_s=3600.0)
    try:
        for state, block in zip(router.replicas,
                                (_metered(8, {"t0": 2}),
                                 _metered(8, {"t0": 1, "t1": 5}))):
            state.healthy = True
            state.snapshot = {"status": "ok", "usage": block}
        doc = router.status()
        usage = doc.get("usage")
        assert usage is not None and usage["replicas"] == 2
        assert usage["per_tenant"]["t0"]["requests"] == 3
        assert usage["per_tenant"]["t1"]["requests"] == 5
        # a replica set with no usage blocks federates to no usage key
        for state in router.replicas:
            state.snapshot = {"status": "ok"}
        assert "usage" not in router.status()
    finally:
        router.stop()


# -- flight recorder carries the tenant + the usage snapshot ------------------


def test_request_trail_and_bundle_carry_usage():
    from homebrewnlp_tpu.serve.slo import RequestRecord
    rec = RequestRecord(7, path="/token_completion")
    rec.xid, rec.tenant, rec.status = "x-7", "acme", 200
    trail = request_trail(rec)
    assert trail["tenant"] == "acme"
    fr = FlightRecorder()
    fr.set_usage_probe(lambda: {"totals": {"requests": 9}})
    doc = fr.bundle("manual")
    assert doc["usage"] == {"totals": {"requests": 9}}
    fr.set_usage_probe(None)
    assert FlightRecorder().bundle("manual")["usage"] is None


# -- graftload / graftmeter pure arms -----------------------------------------


_PROM = """# HELP hbnlp_serve_tokens_total t
# TYPE hbnlp_serve_tokens_total counter
hbnlp_serve_tokens_total{{tenant="t0",kind="prompt"}} {p0}
hbnlp_serve_tokens_total{{tenant="t0",kind="generated"}} {g0}
hbnlp_serve_tokens_total{{tenant="t1",kind="prompt"}} {p1}
hbnlp_serve_tokens_total{{tenant="t1",kind="generated"}} {g1}
"""


def test_graftload_usage_reconcile_exact_and_mismatch():
    before = _PROM.format(p0=10, g0=5, p1=0, g1=0)
    after = _PROM.format(p0=16, g0=13, p1=4, g1=8)
    deltas = graftload.tenant_token_deltas(before, after)
    assert deltas[("t0", "prompt")] == 6
    client = {"t0": {"requests": 2, "ok": 2, "prompt_tokens": 6,
                     "generated_tokens": 8},
              "t1": {"requests": 1, "ok": 1, "prompt_tokens": 4,
                     "generated_tokens": 8}}
    rep = graftload.usage_reconcile_report(client, deltas)
    assert rep["tokens_match"] is True
    assert rep["client_tokens_total"] == rep["server_tokens_total"] == 26
    # one server-side token short: EXACT means a one-token miss fails
    short = graftload.tenant_token_deltas(
        before, _PROM.format(p0=16, g0=12, p1=4, g1=8))
    rep = graftload.usage_reconcile_report(client, short)
    assert rep["tokens_match"] is False
    assert "t0" in rep["mismatches"]
    # foreign traffic in the window fails rather than being absorbed
    foreign = dict(deltas)
    foreign[("anon", "prompt")] = 3.0
    rep = graftload.usage_reconcile_report(client, foreign)
    assert rep["tokens_match"] is False
    assert rep["server_extra_rows"] == {"anon/prompt": 3}
    assert "skipped" in graftload.usage_reconcile_report(None, deltas)


def test_graftload_check_ok_gates_on_usage_arm():
    base = {"client": {"truncated": False, "n_requests": 4, "n_ok": 4,
                       "error_rate": 0.0, "peak_inflight": 2},
            "reconcile": {"within_tolerance": True}}
    good = dict(base, usage_reconcile={"tokens_match": True})
    bad = dict(base, usage_reconcile={"tokens_match": False,
                                      "mismatches": {"t0": {}}})
    assert graftload.check_ok(good)
    assert not graftload.check_ok(bad)
    # the usage arm binds chaos drills too: failover must not double-bill
    assert not graftload.check_ok(bad, chaos_tolerant=True)
    assert graftload.check_ok(base)   # no arm -> prior behavior unchanged


def test_graftmeter_row_sum_and_reconcile():
    s = _metered(4, {"t0": 2, "t1": 1})
    assert graftmeter.row_sum_problems(s) == []
    broken = json.loads(json.dumps(s))
    broken["per_tenant"]["t0"]["prompt_tokens"] += 1
    assert any("prompt_tokens" in p
               for p in graftmeter.row_sum_problems(broken))
    assert graftmeter.row_sum_problems(None)
    ok, _ = graftmeter.reconcile(
        {"usage_reconcile": {"tokens_match": True}}, s)
    assert ok
    ok, reasons = graftmeter.reconcile(
        {"usage_reconcile": {"tokens_match": False,
                             "client_tokens_total": 9,
                             "server_tokens_total": 8}}, s)
    assert not ok and any("mismatch" in r for r in reasons)
    # absolute fallback: client counts vs the meter's lifetime totals
    client = {"t0": {"prompt_tokens": 6, "generated_tokens": 8},
              "t1": {"prompt_tokens": 3, "generated_tokens": 4}}
    ok, _ = graftmeter.reconcile({"client": {"per_tenant": client}}, s)
    assert ok
    client["t0"]["prompt_tokens"] = 7
    ok, _ = graftmeter.reconcile({"client": {"per_tenant": client}}, s)
    assert not ok


def test_graftmeter_deltas_clamp_fold_restarts():
    prev = {"wall_time_s": 0.0, "tokens": {"t0": {"prompt": 100.0}}}
    cur = {"wall_time_s": 2.0, "tokens": {"t0": {"prompt": 10.0},
                                          "t1": {"prompt": 8.0}}}
    out = graftmeter.deltas(prev, cur)
    # t0 was folded + re-admitted (series restarted): live rate clamps to
    # 0 instead of going negative
    assert out["per_tenant"]["t0"]["tokens_per_s"] == 0.0
    assert out["per_tenant"]["t1"]["tokens_per_s"] == pytest.approx(4.0)


# -- live server: exact reconciliation under real traffic ---------------------


def _engine_cfg(**over):
    base = dict(depth=1, sequence_length=32, heads=2, features_per_head=16,
                vocab_size=32, train_batch_size=1, sampling_temperature=0.0,
                use_autoregressive_sampling=True, serve_max_batch=2,
                # chunked admission prefill ON: reconciliation must stay
                # exact when prompts land chunk by chunk
                serve_prefill_chunk_tokens=8)
    base.update(over)
    return mixer_config(**base)


@pytest.fixture(scope="module")
def live_server():
    cfg = _engine_cfg()
    params, _ = init_params(cfg, random_text_batch(cfg))
    reg = MetricsRegistry()
    api = RestAPI(cfg, params)
    server = serve(cfg, None, port=0, background=True, registry=reg,
                   obs_port=0, api=api)
    yield server, cfg, reg
    server.shutdown()
    server.server_close()


def _obs_url(server) -> str:
    return f"http://127.0.0.1:{server._obs_server.server_address[1]}"


def test_live_tenant_reconciliation_buffered(live_server, tmp_path):
    server, cfg, reg = live_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    report = graftload.drive(
        url, metrics_url=_obs_url(server), n_requests=9, concurrency=3,
        response_len=4, temperature=0.0, seed=5, vocab=32, min_prompt=2,
        max_prompt=4, timeout_s=300.0, tenants=3)
    arm = report.get("usage_reconcile")
    assert arm is not None, report
    assert arm.get("tokens_match") is True, arm
    assert set((report["client"]["per_tenant"] or {})) == {"t0", "t1", "t2"}
    # /healthz carries the capacity accounting
    with urllib.request.urlopen(_obs_url(server) + "/healthz",
                                timeout=10) as r:
        hz = json.loads(r.read())
    usage = hz.get("usage")
    assert usage is not None
    assert usage["totals"]["requests"] >= 9
    assert usage["capacity"] is not None        # ceiling block present
    assert "capacity_utilization" in usage["capacity"]
    # graftmeter --check: the books balance on the live surface, and the
    # graftload report reconciles through the CLI gate
    rpt = tmp_path / "load_report.json"
    rpt.write_text(json.dumps(report))
    rc = graftmeter.main(["--metrics-url", _obs_url(server), "--check",
                          "--load-report", str(rpt)])
    assert rc == 0


def test_live_tenant_reconciliation_streaming(live_server):
    server, cfg, reg = live_server
    url = f"http://127.0.0.1:{server.server_address[1]}"
    report = graftload.drive(
        url, metrics_url=_obs_url(server), n_requests=6, concurrency=2,
        response_len=4, temperature=0.0, seed=6, vocab=32, min_prompt=2,
        max_prompt=4, timeout_s=300.0, stream=True, tenants=2)
    arm = report.get("usage_reconcile")
    assert arm is not None and arm.get("tokens_match") is True, arm


def test_sse_disconnect_finalizes_exactly_once(live_server):
    import http.client
    server, cfg, reg = live_server
    wrapper = server._batch_wrapper
    free0 = wrapper.kv_blocks_free()
    before = graftload.parse_prom(reg.render())

    def count(name, **labels):
        metrics = graftload.parse_prom(reg.render())
        return sum(v for lab, v in metrics.get(name, [])
                   if all(lab.get(k) == s for k, s in labels.items()))

    req_before = count("hbnlp_serve_tenant_requests_total", tenant="drop")
    conn = http.client.HTTPConnection(
        "127.0.0.1", server.server_address[1], timeout=120)
    conn.request("POST", "/token_completion",
                 body=json.dumps({"prompt": [1, 2, 3, 4],
                                  "temperature": 0.0, "response_len": 24,
                                  "stream": True}),
                 headers={"Content-Type": "application/json",
                          "X-Tenant": "drop"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.read1(8192)
    resp.close()        # client vanishes mid-stream
    conn.close()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if (wrapper.kv_blocks_free() == free0
                and wrapper.active_lanes() == 0):
            break
        time.sleep(0.05)
    # the abandoned request finalized EXACTLY once...
    assert count("hbnlp_serve_tenant_requests_total", tenant="drop") \
        == req_before + 1
    # ...and billed at most the plan: whether the engine finished before
    # noticing the drop or reaped the lane mid-stream, tokens_generated
    # is capped at actuals and block-seconds settle on the exit path
    gen = count("hbnlp_serve_tokens_total", tenant="drop",
                kind="generated")
    gen -= sum(v for lab, v in
               before.get("hbnlp_serve_tokens_total", [])
               if lab.get("tenant") == "drop"
               and lab.get("kind") == "generated")
    assert 0 <= gen <= 24
    assert count("hbnlp_serve_kv_block_seconds_total", tenant="drop") > 0


# -- the failover drill: exact metering across a replica kill (@slow) ---------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _fetch_or_empty(url: str) -> str:
    try:
        return graftload.fetch_metrics(url, timeout_s=5.0)
    except Exception:  # noqa: BLE001 - dead/mid-relaunch replica scrapes as 0
        return ""


@pytest.mark.slow
def test_usage_drill_replica_die_exact_reconciliation(tmp_path):
    """A 2-replica fleet, ``replica:die`` killing replica 0 on its FIRST
    proxied request (pre-commit, so every request fails over and is
    metered exactly once on the survivor): graftload's client-side token
    counts must equal the fleet-summed server deltas TO THE TOKEN, and
    the router's ``/healthz`` must carry the federated usage block."""
    raw = dict(
        model_mode="gpt", use_video=False, use_language=True,
        sequence_length=12, features_per_head=16, heads=2, depth=1,
        vocab_size=32, train_batch_size=1, calc_accuracy=False,
        memory_reduction_strategy="revnet", group_linear_factor=2,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[
            {"layer": ["norm-shift-scale-features-group",
                       "bottleneck_group_linear-in:relu-mid:relu-mid:norm-"
                       "mid:shift-mid:scale-mid:features"]},
        ],
        sampling_temperature=0.0, use_autoregressive_sampling=True,
        serve_max_batch=3, use_checkpointing=False,
        watchdog_factor=3.0, serve_watchdog_min_stall_s=1.0,
        model_path=str(tmp_path / "model"),
        compilation_cache_dir=str(tmp_path / "jitcache"),
    )
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(raw))
    base_port, obs_port, router_port = (_free_port(), _free_port(),
                                        _free_port())
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "graftserve.py"),
         "--model", str(cfg_path), "--replicas", "2",
         "--base-port", str(base_port), "--base-obs-port", str(obs_port),
         "--router-port", str(router_port),
         "--health-interval-s", "0.25", "--backoff-base", "0.25",
         "--grace-deadline-s", "15",
         "--fault-plan", "0:replica:die@req1"],
        env=env, cwd=REPO)
    router_url = f"http://127.0.0.1:{router_port}"
    obs_urls = [f"http://127.0.0.1:{obs_port + i}" for i in range(2)]

    def healthy() -> int:
        try:
            with urllib.request.urlopen(router_url + "/healthz",
                                        timeout=5) as r:
                return int(json.loads(r.read()).get("healthy", 0))
        except Exception:  # noqa: BLE001
            return 0

    try:
        deadline = time.monotonic() + 600.0
        while time.monotonic() < deadline and healthy() < 2:
            assert proc.poll() is None, "graftserve died during startup"
            time.sleep(1.0)
        assert healthy() >= 2, "fleet never came up"
        befores = [_fetch_or_empty(u) for u in obs_urls]
        report = graftload.drive(
            router_url, n_requests=24, concurrency=8, response_len=4,
            temperature=0.0, seed=12, vocab=32, min_prompt=2,
            max_prompt=4, timeout_s=300.0, targets=[router_url],
            router_metrics_url=router_url, tenants=3)
        c = report["client"]
        assert not c["truncated"]
        assert graftload.check_ok(report, chaos_tolerant=True), c
        # fleet-summed run deltas: one account per request, no double or
        # zero billing across the kill + failover + relaunch
        deltas: dict = {}
        for b, u in zip(befores, obs_urls):
            for key, v in graftload.tenant_token_deltas(
                    b, _fetch_or_empty(u)).items():
                deltas[key] = deltas.get(key, 0.0) + v
        arm = graftload.usage_reconcile_report(c.get("per_tenant"), deltas)
        assert arm["tokens_match"] is True, arm
        # the router federates the replicas' usage blocks on /healthz —
        # even while degraded (503 with the status doc as its body).  The
        # block is rebuilt from each replica's latest health poll, so give
        # the poll loop a few beats to observe the final finalizes
        def router_usage():
            try:
                with urllib.request.urlopen(router_url + "/healthz",
                                            timeout=5) as r:
                    return json.loads(r.read()).get("usage")
            except urllib.error.HTTPError as e:
                return json.loads(e.read()).get("usage")
            except Exception:  # noqa: BLE001
                return None

        fed = router_usage()
        deadline = time.monotonic() + 30.0
        while (time.monotonic() < deadline
               and not (fed and fed["totals"]["requests"] >= c["n_ok"])):
            time.sleep(0.5)
            fed = router_usage()
        assert fed is not None and fed.get("replicas", 0) >= 1
        assert fed["totals"]["requests"] >= c["n_ok"]
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
