"""Device-side training telemetry (ISSUE 5): in-graph numerics riding the
deferred metric drain, anomaly policies (log / skip_step / halt) against an
injected NaN gradient scale, live MFU / tokens-per-sec / goodput from the
HLO cost-analysis path (reconciled against bench.py's figure), the
telemetry_interval thinning, config validation, and the supervisor's
cross-relaunch goodput accounting."""
import argparse
import json
import os

import numpy as np
import pytest

from homebrewnlp_tpu import main as cli
from homebrewnlp_tpu.obs import device_telemetry
from homebrewnlp_tpu.obs.registry import REGISTRY, MetricsRegistry
from homebrewnlp_tpu.reliability import EXIT_ANOMALY_HALT
from homebrewnlp_tpu.train.metrics import read_metric_rows

from .backend import tiny_config


def _args(steps, profile=""):
    return argparse.Namespace(steps=steps, profile=profile, workers=None)


def _losses(path):
    return [r["loss"] for r in read_metric_rows(str(path))]


# -- parity: telemetry must not perturb training -----------------------------

def test_telemetry_off_and_log_policy_keep_loss_sequence(tmp_path,
                                                         eight_devices):
    """Acceptance: telemetry off compiles the pre-telemetry graph, and
    telemetry on with anomaly_policy="log" is observe-only — all three loss
    sequences are bit-identical (grad*1.0 is exact in IEEE)."""
    cli.train(tiny_config(model_path=str(tmp_path / "off")), _args(8))
    cli.train(tiny_config(model_path=str(tmp_path / "log"),
                          telemetry_interval=1, anomaly_policy="log",
                          telemetry_groups=["embed"]), _args(8))
    cli.train(tiny_config(model_path=str(tmp_path / "skip"),
                          telemetry_interval=1, anomaly_policy="skip_step"),
              _args(8))
    off = _losses(tmp_path / "off")
    assert off == _losses(tmp_path / "log")
    # skip_step adds the in-graph mask, but with finite grads the selected
    # branch is the identical update
    assert off == _losses(tmp_path / "skip")


@pytest.mark.slow
def test_telemetry_parity_300_steps(tmp_path, eight_devices):
    """Satellite: 300 synthetic updates — telemetry off matches the PR-2
    sync-parity configuration, telemetry on (log) changes nothing."""
    base = dict(async_inflight_steps=0, device_prefetch_depth=0)
    cli.train(tiny_config(model_path=str(tmp_path / "off"), **base),
              _args(300))
    cli.train(tiny_config(model_path=str(tmp_path / "on"),
                          telemetry_interval=1, anomaly_policy="log", **base),
              _args(300))
    off, on = _losses(tmp_path / "off"), _losses(tmp_path / "on")
    assert len(off) == len(on) == 300
    assert off == on


# -- telemetry content -------------------------------------------------------

def test_telemetry_metrics_present_and_sane(tmp_path, eight_devices):
    cfg = tiny_config(model_path=str(tmp_path), telemetry_interval=1,
                      telemetry_groups=["embed", "body"])
    cli.train(cfg, _args(4))
    rows = read_metric_rows(str(tmp_path))
    assert len(rows) == 4
    for r in rows:
        assert r["telemetry/nonfinite_grads"] == 0.0
        assert r["telemetry/applied"] == 1.0
        assert r["telemetry/grad_scale"] == 1.0
        assert r["telemetry/param_norm"] > 0
        assert r["telemetry/update_norm"] > 0
        assert r["telemetry/update_ratio"] == pytest.approx(
            r["telemetry/update_norm"] / r["telemetry/param_norm"], rel=1e-4)
        assert r["telemetry/grad_norm/embed"] >= 0
        assert r["telemetry/grad_norm/body"] >= 0
        assert np.isfinite(r["loss"])


def test_telemetry_interval_thins_norms_keeps_sentinels(tmp_path,
                                                        eight_devices):
    cfg = tiny_config(model_path=str(tmp_path), telemetry_interval=3)
    cli.train(cfg, _args(7))
    rows = read_metric_rows(str(tmp_path))
    for i, r in enumerate(rows):
        # sentinels drain every step — anomaly detection is never thinned
        assert "telemetry/nonfinite_grads" in r
        assert "telemetry/applied" in r
        assert ("telemetry/param_norm" in r) == (i % 3 == 0), i


def test_thin_is_pure_and_keeps_sentinels():
    metrics = {"loss": 1.0, "telemetry/param_norm": 2.0,
               "telemetry/nonfinite_grads": 0, "telemetry/applied": 1.0,
               "telemetry/grad_scale": 1.0, "telemetry/grad_norm/x": 3.0}
    on_grid = device_telemetry.thin(dict(metrics), 6, 3)
    assert on_grid == metrics
    off_grid = device_telemetry.thin(dict(metrics), 7, 3)
    assert "telemetry/param_norm" not in off_grid
    assert "telemetry/grad_norm/x" not in off_grid
    assert off_grid["telemetry/nonfinite_grads"] == 0
    assert off_grid["loss"] == 1.0
    # interval <= 1: no thinning at all
    assert device_telemetry.thin(dict(metrics), 7, 1) == metrics


# -- anomaly policies --------------------------------------------------------

def test_skip_step_masks_one_update_and_training_continues(tmp_path,
                                                           eight_devices):
    """Acceptance: an injected non-finite gradient under skip_step skips
    exactly one update (a bit-exact no-op for params AND slots), increments
    hbnlp_anomaly_skips_total, and the run finishes with finite losses."""
    before = REGISTRY.counter("hbnlp_anomaly_skips_total").value()
    cfg = tiny_config(model_path=str(tmp_path / "inj"), telemetry_interval=1,
                      anomaly_policy="skip_step",
                      fault_plan="grads:nan@step3")
    cli.train(cfg, _args(6))
    rows = read_metric_rows(str(tmp_path / "inj"))
    assert [r["step"] for r in rows] == list(range(6))
    assert [r["telemetry/applied"] for r in rows] == [1, 1, 1, 0, 1, 1]
    assert rows[3]["telemetry/nonfinite_grads"] > 0
    assert rows[3]["telemetry/update_norm"] == 0.0  # true no-op
    assert all(np.isfinite(r["loss"]) for r in rows)
    assert REGISTRY.counter("hbnlp_anomaly_skips_total").value() == before + 1
    # the skipped update left params at their step-3 values: step 4's loss
    # differs from the uninjected run's, but training keeps descending
    ref = tiny_config(model_path=str(tmp_path / "ref"), telemetry_interval=1,
                      anomaly_policy="skip_step")
    cli.train(ref, _args(6))
    ref_rows = read_metric_rows(str(tmp_path / "ref"))
    # identical before the injection point
    assert [r["loss"] for r in rows[:4]] == [r["loss"] for r in ref_rows[:4]]


def test_log_policy_keeps_updates_applied(tmp_path, eight_devices):
    cfg = tiny_config(model_path=str(tmp_path), telemetry_interval=1,
                      anomaly_policy="log", fault_plan="grads:nan@step2")
    cli.train(cfg, _args(4))
    rows = read_metric_rows(str(tmp_path))
    assert rows[2]["telemetry/nonfinite_grads"] > 0
    # observe-only: the (poisoned) update applied, the run was not stopped
    assert [r["telemetry/applied"] for r in rows] == [1, 1, 1, 1]
    assert len(rows) == 4


def test_halt_policy_exits_with_distinct_code(tmp_path, eight_devices):
    cfg = tiny_config(model_path=str(tmp_path), telemetry_interval=1,
                      anomaly_policy="halt", fault_plan="grads:nan@step3")
    with pytest.raises(SystemExit) as e:
        cli.train(cfg, _args(12))
    assert e.value.code == EXIT_ANOMALY_HALT
    # the anomalous step's row IS in metrics.jsonl (written before the halt)
    rows = read_metric_rows(str(tmp_path))
    anomalous = [r for r in rows if r["telemetry/nonfinite_grads"] > 0]
    assert anomalous and anomalous[0]["step"] == 3


def test_halt_does_not_checkpoint_poisoned_params(tmp_path, eight_devices):
    """A halt exits BEFORE the end-of-run checkpoint: the newest saved state
    predates the anomaly, so the supervisor's relaunch resumes clean."""
    cfg = tiny_config(model_path=str(tmp_path), telemetry_interval=1,
                      anomaly_policy="halt", fault_plan="grads:nan@step3",
                      use_checkpointing=True, steps_per_checkpoint=2)
    with pytest.raises(SystemExit):
        cli.train(cfg, _args(12))
    manifests = [f for f in os.listdir(tmp_path / "ckpt")
                 if f.startswith("manifest_")]
    steps = sorted(int(f[len("manifest_"):-len(".json")]) for f in manifests)
    assert steps and steps[-1] <= 3  # nothing saved past the anomaly


def test_anomaly_monitor_rejects_unknown_policy():
    with pytest.raises(ValueError, match="anomaly_policy"):
        device_telemetry.AnomalyMonitor("explode", registry=MetricsRegistry())


def test_config_validation():
    with pytest.raises(ValueError, match="telemetry_interval"):
        tiny_config(telemetry_interval=-1)
    with pytest.raises(ValueError, match="anomaly_policy"):
        tiny_config(anomaly_policy="explode")
    cfg = tiny_config()
    assert cfg.telemetry_interval == 0 and cfg.anomaly_policy == "log"
    cfg = tiny_config(telemetry_groups=("embed",))
    assert cfg.telemetry_groups == ["embed"]
    # a grads-site fault plan with telemetry off would be silently inert:
    # rejected at config load instead
    with pytest.raises(ValueError, match="grads"):
        tiny_config(fault_plan="grads:nan@step3")
    tiny_config(fault_plan="grads:nan@step3", telemetry_interval=1)


def test_grad_scale_requires_telemetry(eight_devices):
    from homebrewnlp_tpu.train import Trainer
    tr = Trainer(tiny_config())
    with pytest.raises(ValueError, match="telemetry_interval"):
        tr.step_extra_args(grad_scale=1.0)
    assert tr.step_extra_args() == ()
    tr2 = Trainer(tiny_config(telemetry_interval=1))
    (gs,) = tr2.step_extra_args(grad_scale=np.nan)
    assert isinstance(gs, np.float32) and not np.isfinite(gs)


# -- utilization accounting (train/flops.py) ---------------------------------

def test_flops_reconcile_with_bench_cost_analysis(eight_devices):
    """Acceptance: the live MFU path's flops figure and bench.py's
    flops_per_step are the same HLO cost analysis — within 1% (they are in
    fact the identical call)."""
    import jax
    from homebrewnlp_tpu.train import Trainer, flops
    from homebrewnlp_tpu.utils import random_text_batch
    cfg = tiny_config(telemetry_interval=1)
    trainer = Trainer(cfg)
    batch = random_text_batch(cfg)
    state = trainer.init(batch)
    live = flops.step_flops(trainer, state, batch)
    bench_style = float(trainer.step_cost_analysis(state, batch).get(
        "flops", 0.0))
    assert live > 0
    assert abs(live - bench_style) <= 0.01 * bench_style
    # the AOT executable survives for the step loop (no second compile)
    assert trainer._compiled is not None
    state2, m = trainer.step(state, batch, jax.random.key(0))
    assert np.isfinite(float(m["loss"]))


def test_peak_flops_table():
    from homebrewnlp_tpu.train.flops import peak_flops
    assert peak_flops("TPU v5e") == 197e12
    assert peak_flops("TPU v5p") == 459e12
    assert peak_flops("TPU v5 lite") == 197e12  # specific beats generic
    assert peak_flops("cpu") is None


def test_utilization_rates():
    from homebrewnlp_tpu.train.flops import Utilization
    u = Utilization(flops_per_step=1e12, tokens_per_step=1000, n_chips=2,
                    peak_flops_per_chip=1e12)
    r = u.rates(0.5)
    assert r["tokens_per_sec"] == pytest.approx(2000.0)
    assert r["tokens_per_sec_per_chip"] == pytest.approx(1000.0)
    assert r["mfu"] == pytest.approx(1e12 / 0.5 / 2e12)
    assert u.rates(0.0) == {}
    # CPU/unknown device: throughput only, no MFU claim
    assert "mfu" not in Utilization(1e12, 1000, 1, None).rates(0.5)


def test_metrics_rows_carry_rates_and_goodput(tmp_path, eight_devices):
    cfg = tiny_config(model_path=str(tmp_path), telemetry_interval=1)
    cli.train(cfg, _args(5))
    rows = read_metric_rows(str(tmp_path))
    # row 0's step_seconds spans compile/init: no rate claim there
    assert "tokens_per_sec" not in rows[0]
    for r in rows[1:]:
        assert r["tokens_per_sec"] > 0
        assert 0.0 <= r["goodput"] <= 1.0


def test_live_metrics_and_healthz_carry_utilization(tmp_path, eight_devices):
    """With obs_port set and telemetry on, /metrics exposes the utilization
    gauges and /healthz mirrors them; Obs.close freezes the gauges (no
    dead-run callbacks leak into later scrapes)."""
    import socket
    import threading
    import urllib.request

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cfg = tiny_config(model_path=str(tmp_path), obs_port=port,
                      telemetry_interval=1)
    done = threading.Event()
    errs = []
    seen = {}

    def run():
        try:
            cli.train(cfg, _args(80))
        except BaseException as e:
            errs.append(e)
        finally:
            done.set()

    t = threading.Thread(target=run)
    t.start()
    import time
    deadline = time.time() + 300
    while time.time() < deadline and not done.is_set():
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
            h = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5).read())
        except OSError:
            time.sleep(0.02)
            continue
        if "hbnlp_tokens_per_sec" in body and not done.is_set() \
                and h.get("utilization"):
            seen["metrics"], seen["health"] = body, h
            break
        time.sleep(0.02)
    t.join(600)
    assert not errs, errs
    assert "metrics" in seen, "never scraped utilization while live"
    for name in ("hbnlp_tokens_per_sec", "hbnlp_goodput",
                 "hbnlp_flops_per_step", "hbnlp_mfu"):
        assert name in seen["metrics"], name
    assert "goodput" in seen["health"]["utilization"]
    # frozen after close: callback gauges report plain finals
    assert REGISTRY.get("hbnlp_flops_per_step").value() > 0


# -- supervisor goodput (tools/supervise.py satellite) ------------------------

def _load_supervise():
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "supervise_under_test", os.path.join(repo, "tools", "supervise.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_supervisor_goodput_accounting(tmp_path):
    """Two productive launch segments and one dead one: goodput =
    productive / wall, rendered to supervisor_metrics.prom after every
    exit."""
    supervise = _load_supervise()
    clock = [0.0]
    progress = [0]
    prom = tmp_path / "supervisor_metrics.prom"

    def launch():
        # each launch takes 10s; the second one makes no progress
        clock[0] += 10.0
        n = launch.calls = getattr(launch, "calls", 0) + 1
        if n == 1:
            progress[0] = 5
            return supervise.EXIT_PREEMPTED
        if n == 2:
            return 1  # crash, no progress
        progress[0] = 9
        return 0

    sleeps = []

    def sleep(s):
        sleeps.append(s)
        clock[0] += s

    sup = supervise.Supervisor(
        launch, lambda: progress[0], registry=supervise.MetricsRegistry(),
        metrics_path=str(prom), sleep=sleep, clock=lambda: clock[0],
        backoff_base_s=2.0, backoff_jitter=0.0)
    assert sup.run() == 0
    # wall 32s (3 launches + 2s backoff), productive 20s (launches 1 and 3)
    assert sup.goodput() == pytest.approx(20.0 / 32.0)
    text = prom.read_text()
    # every supervisor series carries the host's rank label (fleet-obs
    # satellite: N supervisors sharing a fleet dir must not collide)
    assert "hbnlp_supervisor_goodput" in text
    assert 'hbnlp_supervisor_productive_seconds{rank="0"} 20' in text
    assert ('hbnlp_supervisor_exits_total{outcome="preemption",rank="0"} 1'
            in text)
    assert 'hbnlp_supervisor_exits_total{outcome="crash",rank="0"} 1' in text
    assert 'hbnlp_supervisor_exits_total{outcome="clean",rank="0"} 1' in text


def test_supervisor_anomaly_halt_outcome_and_backoff(tmp_path):
    supervise = _load_supervise()
    rcs = iter([supervise.EXIT_ANOMALY_HALT, 0])
    progress = [0]

    def launch():
        progress[0] += 1  # the halt run made progress before halting
        return next(rcs)

    sleeps = []
    sup = supervise.Supervisor(
        launch, lambda: progress[0], registry=supervise.MetricsRegistry(),
        sleep=sleeps.append, backoff_base_s=3.0, backoff_jitter=0.0)
    assert sup.run() == 0
    assert sleeps == [3.0]  # halt backs off like a crash
    assert sup._exits.value(outcome="anomaly_halt", rank="0") == 1


def test_exit_code_contract_includes_anomaly_halt():
    import homebrewnlp_tpu.reliability as rel
    supervise = _load_supervise()
    assert supervise.EXIT_ANOMALY_HALT == rel.EXIT_ANOMALY_HALT == 86
