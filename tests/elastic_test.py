"""Elastic multi-host suite (ISSUE 10): retried jax.distributed init with
fault injection, barrier-with-timeout, peer-loss detection -> checkpoint ->
EXIT_PEER_LOST, sharding-aware checkpoint manifests with verified
reshard-on-restore (composed 8-device mesh -> smaller mesh -> 1 device),
stale sharding metadata refused with fallback, the supervisor fleet's
lockstep relaunch protocol, backoff jitter, and the reshard-restore
progress probe — the CI ``chaos-multihost`` job runs this file on CPU."""
import argparse
import itertools
import json
import logging
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from homebrewnlp_tpu import main as cli
from homebrewnlp_tpu.config import Config
from homebrewnlp_tpu.obs.registry import MetricsRegistry
from homebrewnlp_tpu.reliability import EXIT_PEER_LOST, dist, faults
from homebrewnlp_tpu.reliability.faults import parse_plan

from .backend import tiny_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import supervise  # noqa: E402  (tools/supervise.py)


def _args(steps):
    return argparse.Namespace(steps=steps, profile="", workers=None)


def _rows(model_path):
    from homebrewnlp_tpu.train.metrics import read_metric_rows
    return read_metric_rows(model_path)


@pytest.fixture(autouse=True)
def _clean_dist_state():
    faults.reset()
    dist._reset_for_tests()
    yield
    faults.reset()
    dist._reset_for_tests()


class _Cfg:
    """Bare attribute bag standing in for Config in dist-settings tests."""

    def __init__(self, **kw):
        self.dist_coordinator = ""
        self.dist_num_processes = 0
        self.dist_process_id = 0
        self.dist_init_timeout_s = 60.0
        self.dist_init_retries = 3
        self.dist_barrier_timeout_s = 60.0
        self.__dict__.update(kw)


# -- dist settings resolution -------------------------------------------------

def test_settings_single_host_is_none():
    assert dist.settings(None) is None
    assert dist.settings(_Cfg(dist_num_processes=1)) is None


def test_settings_env_overrides_config(monkeypatch):
    cfg = _Cfg(dist_coordinator="cfghost:1", dist_num_processes=4,
               dist_process_id=1)
    s = dist.settings(cfg)
    assert (s.coordinator, s.num_processes, s.process_id) == ("cfghost:1", 4, 1)
    monkeypatch.setenv(dist.ENV_COORDINATOR, "envhost:2")
    monkeypatch.setenv(dist.ENV_NUM_PROCESSES, "2")
    monkeypatch.setenv(dist.ENV_PROCESS_ID, "0")
    s = dist.settings(cfg)
    assert (s.coordinator, s.num_processes, s.process_id) == ("envhost:2", 2, 0)


def test_settings_single_process_with_explicit_coordinator():
    """The legacy ``--tpu addr,0,1`` single-process pod slice: an explicit
    coordinator with num_processes=1 still initializes the distributed
    runtime (regression: the env-stash refactor must not silently drop it)."""
    s = dist.settings(_Cfg(dist_coordinator="h:1", dist_num_processes=1))
    assert s is not None and s.num_processes == 1 and s.process_id == 0


def test_settings_requires_coordinator_and_valid_rank(monkeypatch):
    with pytest.raises(ValueError, match="coordinator"):
        dist.settings(_Cfg(dist_num_processes=2))
    with pytest.raises(ValueError, match="out of range"):
        dist.settings(_Cfg(dist_coordinator="h:1", dist_num_processes=2,
                           dist_process_id=2))


def test_attempt_timeout_slices_overall_deadline():
    """Each initialize attempt gets deadline/(retries+1) as its jax
    initialization_timeout — a slow coordinator consuming the whole budget
    on attempt 1 would otherwise make dist_init_retries unreachable."""
    s = dist.DistSettings("h:1", 2, 0, init_timeout_s=300.0, init_retries=3)
    assert s.attempt_timeout_s == 75
    assert dist.DistSettings("h:1", 2, 0,
                             init_timeout_s=0.0).attempt_timeout_s == 300
    assert dist.DistSettings("h:1", 2, 0,
                             init_timeout_s=5.0).attempt_timeout_s == 10


def test_config_validates_dist_knobs():
    cfg = tiny_config(dist_coordinator="h:1", dist_num_processes=2,
                      dist_process_id=1)
    assert cfg.dist_num_processes == 2
    for bad in (dict(dist_num_processes=-1),
                dict(dist_num_processes=2, dist_process_id=2),
                dict(dist_coordinator="h:1"),  # coordinator without a world
                dict(dist_init_timeout_s=-1),
                dict(dist_init_retries=-1),
                dict(dist_barrier_timeout_s=-1)):
        with pytest.raises(ValueError):
            tiny_config(**bad)


# -- retried distributed init -------------------------------------------------

def test_initialize_retries_then_succeeds():
    reg = MetricsRegistry()
    calls = []

    def flaky(s):
        calls.append(s.process_id)
        if len(calls) == 1:
            # real jax.distributed failures are jaxlib XlaRuntimeError — a
            # RuntimeError, NOT an OSError; the policy must retry it
            raise RuntimeError("DEADLINE_EXCEEDED: barrier timed out")
        if len(calls) == 2:
            raise OSError("coordinator unreachable")

    cfg = _Cfg(dist_coordinator="h:1", dist_num_processes=2)
    elapsed = dist.initialize(cfg, registry=reg, init_fn=flaky,
                              sleep=lambda d: None)
    assert elapsed is not None and len(calls) == 3
    assert reg.counter("hbnlp_dist_init_retries_total").value() == 2
    assert dist.active() and dist.init_seconds() == elapsed
    # the gauge rides the registry for the bench/MULTICHIP hook
    assert "hbnlp_dist_init_seconds" in reg.render()


def test_initialize_exhaustion_raises_coordinator_lost():
    cfg = _Cfg(dist_coordinator="h:1", dist_num_processes=2,
               dist_init_retries=1)

    def dead(s):
        raise OSError("nope")

    with pytest.raises(dist.CoordinatorLost, match="failed after 2"):
        dist.initialize(cfg, registry=MetricsRegistry(), init_fn=dead,
                        sleep=lambda d: None)
    assert not dist.active()


def test_initialize_fault_site_drills_retry_path():
    """dist_init:fail@1 injects the first attempt's failure through exactly
    the retry path a real coordinator outage takes."""
    faults.install("dist_init:fail@1")
    reg = MetricsRegistry()
    calls = []
    cfg = _Cfg(dist_coordinator="h:1", dist_num_processes=2)
    dist.initialize(cfg, registry=reg, init_fn=lambda s: calls.append(1),
                    sleep=lambda d: None)
    # attempt 1 died inside faults.hit BEFORE reaching init_fn; attempt 2
    # reached it — the retry counter shows the injected failure
    assert len(calls) == 1
    assert reg.counter("hbnlp_dist_init_retries_total").value() == 1


def test_initialize_die_fault_not_swallowed_by_retry():
    """dist_init:die@1 is documented non-retryable: it must kill the init
    like a real bug, not be absorbed by the RuntimeError retry path."""
    from homebrewnlp_tpu.reliability.faults import FaultInjectedCrash
    faults.install("dist_init:die@1")
    cfg = _Cfg(dist_coordinator="h:1", dist_num_processes=2)
    calls = []
    with pytest.raises(FaultInjectedCrash):
        dist.initialize(cfg, registry=MetricsRegistry(),
                        init_fn=lambda s: calls.append(1),
                        sleep=lambda d: None)
    assert calls == [] and not dist.active()


def test_initialize_single_host_noop():
    assert dist.initialize(_Cfg()) is None
    assert not dist.active()


# -- barrier ------------------------------------------------------------------

def test_barrier_single_process_noop():
    dist.barrier("anything", timeout_s=0.001)  # must not raise or hang


def test_barrier_timeout_raises_peer_lost(monkeypatch):
    import jax
    from jax._src import distributed as jdist

    class FakeClient:
        def wait_at_barrier(self, name, timeout_ms):
            raise RuntimeError(f"barrier {name} deadline exceeded "
                               f"({timeout_ms}ms)")

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jdist.global_state, "client", FakeClient(),
                        raising=False)
    with pytest.raises(dist.BarrierTimeout, match="never arrived"):
        dist.barrier("sync", timeout_s=0.05)
    assert issubclass(dist.BarrierTimeout, dist.PeerLost)


def test_barrier_passes_name_and_timeout(monkeypatch):
    import jax
    from jax._src import distributed as jdist
    seen = []

    class FakeClient:
        def wait_at_barrier(self, name, timeout_ms):
            seen.append((name, timeout_ms))

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jdist.global_state, "client", FakeClient(),
                        raising=False)
    dist.barrier("ckpt", timeout_s=2.5)
    assert seen == [("ckpt", 2500)]


# -- peer/coordinator fault sites (seeded regressions) ------------------------

def test_check_peers_fault_sites():
    faults.install("peer:die@step3;coordinator:drop@5")
    dist.check_peers(2)  # not due
    with pytest.raises(dist.PeerLost):
        dist.check_peers(3)
    dist.check_peers(3)  # one-shot
    with pytest.raises(dist.CoordinatorLost):
        dist.check_peers(5)


def test_new_fault_sites_parse_and_validate():
    rules = parse_plan("dist_init:fail@1;peer:die@step10;coordinator:drop@5")
    assert [(r.site, r.action, r.at) for r in rules] == [
        ("dist_init", "fail", 1), ("peer", "die", 10),
        ("coordinator", "drop", 5)]
    # config load validates the whole plan (chaos drills fail fast on typos)
    assert tiny_config(
        fault_plan="dist_init:fail@1;peer:die@step10").fault_plan
    with pytest.raises(ValueError):
        tiny_config(fault_plan="peer:explode@1")


def test_drop_action_at_hit_site_ignored_with_error(caplog):
    """Seeded regression: 'drop' is caller-implemented — reaching it through
    hit() (a site that executes actions itself) logs and does nothing."""
    faults.install("ckpt_write:drop@1")
    with caplog.at_level(logging.ERROR,
                         "homebrewnlp_tpu.reliability.faults"):
        faults.hit("ckpt_write")  # must not raise
    assert any("caller-implemented" in r.message for r in caplog.records)


def test_unknown_action_at_peer_site_logged_not_raised(caplog):
    faults.install("peer:nan@step1")
    with caplog.at_level(logging.ERROR,
                         "homebrewnlp_tpu.reliability.dist"):
        dist.check_peers(1)  # nan is not a peer action: log, don't raise
    assert any("unsupported action" in r.message for r in caplog.records)


# -- peer loss end to end: checkpoint + exit 87 + bit-identical resume --------

def test_peer_loss_checkpoints_and_exits_87(tmp_path, eight_devices):
    cli.train(tiny_config(model_path=str(tmp_path / "ref")), _args(6))
    over = dict(model_path=str(tmp_path / "pl"), use_checkpointing=True,
                steps_per_checkpoint=10, fault_plan="peer:die@step3")
    with pytest.raises(SystemExit) as e:
        cli.train(tiny_config(**over), _args(6))
    assert e.value.code == EXIT_PEER_LOST
    # this host's healthy state was checkpointed BEFORE the exit
    m = json.loads((tmp_path / "pl" / "ckpt" / "manifest_3.json").read_text())
    assert m["version"] >= 2 and m["mesh"]["axes"]
    # the relaunch inherits the SAME plan (supervisor env/config): the rule
    # behind the restore point is disarmed, the run completes
    cli.train(tiny_config(**over), _args(6))
    ref = {r["step"]: r["loss"] for r in _rows(str(tmp_path / "ref"))}
    got = {r["step"]: r["loss"] for r in _rows(str(tmp_path / "pl"))}
    assert set(got) == set(range(6))
    for s in range(6):
        assert ref[s] == got[s], f"loss diverged at step {s} after peer loss"


def test_coordinator_drop_exits_87(tmp_path, eight_devices):
    cfg = tiny_config(model_path=str(tmp_path), use_checkpointing=True,
                      steps_per_checkpoint=10,
                      fault_plan="coordinator:drop@2")
    with pytest.raises(SystemExit) as e:
        cli.train(cfg, _args(5))
    assert e.value.code == EXIT_PEER_LOST
    assert (tmp_path / "ckpt" / "manifest_2.json").exists()


# -- sharding-aware checkpoints + reshard-on-restore --------------------------

def _elastic_cfg(**over):
    """Tiny gpt on the composed parallelism knobs (DP/SP/[PP/]TP)."""
    base = dict(model_mode="gpt", use_video=False, sequence_length=16,
                heads=2, features_per_head=16, vocab_size=64, depth=2,
                train_batch_size=4, memory_reduction_strategy="none",
                tpu_size=8, sequence_parallel=2,
                intermediate_feed_forward_multiplier_multiplier=0.5,
                block_config=[{"layer": ["norm-shift-scale",
                                         "feed_forward-in:relu"]}])
    base.update(over)
    return Config(base)


def _state_on(cfg, devices, steps=0):
    import jax
    from homebrewnlp_tpu.data import synthetic_text_batch, to_global
    from homebrewnlp_tpu.parallel import make_mesh
    from homebrewnlp_tpu.train import Trainer
    mesh = make_mesh(cfg, devices)
    trainer = Trainer(cfg, mesh)
    gb = to_global(synthetic_text_batch(cfg, 0), cfg, mesh)
    state = trainer.init(gb)
    for i in range(steps):
        state, _ = trainer.step(state, gb, jax.random.key(i))
    return mesh, state


def _np_tree(tree):
    import jax
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _assert_trees_equal(a, b):
    import jax
    jax.tree_util.tree_map(np.testing.assert_array_equal, a, b)


def test_reshard_roundtrip_mesh_b_and_one_device(tmp_path, eight_devices):
    """THE reshard acceptance: a checkpoint saved on the composed 8-device
    DP/SP/TP mesh restores bit-identically (params AND optimizer slots,
    re-verified by the manifest CRCs after placement) onto a
    differently-shaped mesh and onto a single device."""
    from homebrewnlp_tpu.train import Checkpointer
    cfg = _elastic_cfg()
    meshA, state = _state_on(cfg, eight_devices, steps=2)
    assert dict(meshA.shape)["data"] > 1  # genuinely composed
    Checkpointer(str(tmp_path)).save(state, config_hash="x")
    want_p = _np_tree(dict(state.params))
    want_o = _np_tree({k: dict(v) for k, v in state.opt_state.items()})

    # mesh B: 4 devices, model axis shrunk — different shape, same values
    meshB, template = _state_on(cfg, eight_devices[:4])
    assert dict(meshB.shape) != dict(meshA.shape)
    restored, _ = Checkpointer(str(tmp_path)).restore(template, cfg)
    assert int(restored.step) == 2
    _assert_trees_equal(want_p, _np_tree(dict(restored.params)))
    _assert_trees_equal(want_o, _np_tree(
        {k: dict(v) for k, v in restored.opt_state.items()}))

    # 1 device: graceful degradation floor (sequence_parallel folds to 1 —
    # activation sharding only, the tree structure is mesh-independent)
    cfg1 = _elastic_cfg(sequence_parallel=1)
    _, template1 = _state_on(cfg1, eight_devices[:1])
    restored1, _ = Checkpointer(str(tmp_path)).restore(template1, cfg1)
    _assert_trees_equal(want_p, _np_tree(dict(restored1.params)))
    _assert_trees_equal(want_o, _np_tree(
        {k: dict(v) for k, v in restored1.opt_state.items()}))

    # every reshard was counted and persisted for the progress probe, and
    # the byte-verification honestly recorded (single-process: CRCs ran)
    marker = json.loads((tmp_path / "restore_marker.json").read_text())
    assert marker["count"] == 2 and marker["step"] == 2
    assert marker["from_mesh"] != marker["to_mesh"]
    assert marker["crc_verified"] is True


def test_reshard_roundtrip_composed_pipeline_mesh(tmp_path, eight_devices):
    """DP/SP/PP/TP composed mesh: stage-stacked pipeline leaves (leading
    PIPE_STAGE axis sharded over the pipe mesh axis) reshard onto a
    smaller mesh bit-identically.  Init-state save/restore — stepping the
    1F1B schedule needs jax.shard_map, absent from this toolchain (the
    known tier-1 gap)."""
    from homebrewnlp_tpu.train import Checkpointer
    cfg = _elastic_cfg(pipeline_parallel=2, pipeline_schedule="1f1b")
    meshP, state = _state_on(cfg, eight_devices)
    assert dict(meshP.shape)["pipeline"] == 2
    Checkpointer(str(tmp_path)).save(state, config_hash="p")
    want = _np_tree(dict(state.params))
    meshP4, template = _state_on(cfg, eight_devices[:4])
    assert dict(meshP4.shape) != dict(meshP.shape)
    restored, _ = Checkpointer(str(tmp_path)).restore(template, cfg)
    _assert_trees_equal(want, _np_tree(dict(restored.params)))


def test_resumed_training_after_reshard_stays_deterministic(
        tmp_path, eight_devices):
    """A 2-steps-on-mesh-A checkpoint restored onto mesh B trains on: the
    restored state is a valid training state, not just matching bytes."""
    import jax
    from homebrewnlp_tpu.data import synthetic_text_batch, to_global
    from homebrewnlp_tpu.train import Checkpointer, Trainer
    from homebrewnlp_tpu.parallel import make_mesh
    cfg = _elastic_cfg()
    _, state = _state_on(cfg, eight_devices, steps=2)
    Checkpointer(str(tmp_path)).save(state, config_hash="x")
    meshB = make_mesh(cfg, eight_devices[:4])
    trB = Trainer(cfg, meshB)
    gbB = to_global(synthetic_text_batch(cfg, 0), cfg, meshB)
    template = trB.init(gbB)
    restored, _ = Checkpointer(str(tmp_path)).restore(template, cfg)
    stepped, m = trB.step(restored, gbB, jax.random.key(2))
    assert int(stepped.step) == 3 and np.isfinite(float(m["loss"]))


def test_stale_sharding_metadata_refused_with_fallback(tmp_path,
                                                       eight_devices,
                                                       caplog):
    """Mismatched sharding metadata (spec naming an axis the recorded mesh
    lacks / unknown mesh axes) is refused loudly; restore falls back to the
    newest VERIFIED checkpoint."""
    import jax
    from homebrewnlp_tpu.data import synthetic_text_batch, to_global
    from homebrewnlp_tpu.parallel import make_mesh
    from homebrewnlp_tpu.train import Checkpointer, Trainer
    cfg = _elastic_cfg()
    mesh = make_mesh(cfg, eight_devices)
    trainer = Trainer(cfg, mesh)
    gb = to_global(synthetic_text_batch(cfg, 0), cfg, mesh)
    state = trainer.init(gb)
    ck = Checkpointer(str(tmp_path), max_to_keep=5)
    state, _ = trainer.step(state, gb, jax.random.key(0))
    ck.save(state, config_hash="x")  # step 1: stays clean
    good = _np_tree(dict(state.params))
    state, _ = trainer.step(state, gb, jax.random.key(1))
    ck.save(state, config_hash="x")  # step 2: metadata gets corrupted

    mpath = tmp_path / "manifest_2.json"
    doc = json.loads(mpath.read_text())
    key = next(k for k, e in doc["leaves"].items() if e.get("spec"))
    doc["leaves"][key]["spec"] = [["bogus_axis"]]
    mpath.write_text(json.dumps(doc))

    template = Trainer(cfg, mesh).init(gb)
    with caplog.at_level(logging.ERROR, "homebrewnlp_tpu.train.checkpoint"):
        restored, _ = Checkpointer(str(tmp_path), max_to_keep=5).restore(
            template, cfg)
    assert int(restored.step) == 1  # fell back past the poisoned step 2
    _assert_trees_equal(good, _np_tree(dict(restored.params)))
    assert any("sharding" in r.message and "falling back" in r.message
               for r in caplog.records)


def test_repeat_reshard_not_counted_as_new_progress(tmp_path,
                                                    eight_devices):
    """A child that reshard-restores the SAME checkpoint onto the SAME
    mesh every generation (restores, then dies before saving) must not
    reset the supervisor's crash-loop probe forever: only the first
    reshard bumps the marker count."""
    from homebrewnlp_tpu.train import Checkpointer
    cfg = _elastic_cfg()
    _, state = _state_on(cfg, eight_devices, steps=1)
    Checkpointer(str(tmp_path)).save(state, config_hash="x")
    for _ in range(3):
        _, template = _state_on(cfg, eight_devices[:4])
        Checkpointer(str(tmp_path)).restore(template, cfg)
    marker = json.loads((tmp_path / "restore_marker.json").read_text())
    assert marker["count"] == 1


def test_rejected_restore_never_counts_as_reshard_progress(
        tmp_path, eight_devices):
    """The marker is written only after the WHOLE restore (including the
    data-state sidecar validation) succeeds — a rejected restore must not
    feed the supervisor false progress."""
    from homebrewnlp_tpu.train import Checkpointer
    cfg = _elastic_cfg()
    _, state = _state_on(cfg, eight_devices, steps=1)
    Checkpointer(str(tmp_path)).save(state, data_state={"cursor": 7},
                                     config_hash="x")
    side = tmp_path / "data_state_1.json"
    side.write_text(side.read_text()[:-4] + "GAR}")  # torn cursor
    _, template = _state_on(cfg, eight_devices[:4])
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        Checkpointer(str(tmp_path)).restore(template, cfg)
    assert not (tmp_path / "restore_marker.json").exists()


def test_unknown_mesh_axes_refused(tmp_path, eight_devices, caplog):
    from homebrewnlp_tpu.train import Checkpointer
    cfg = _elastic_cfg()
    _, state = _state_on(cfg, eight_devices)
    ck = Checkpointer(str(tmp_path), max_to_keep=5)
    ck.save(state, config_hash="x")
    mpath = tmp_path / "manifest_0.json"
    doc = json.loads(mpath.read_text())
    doc["mesh"]["axes"] = {"foreign_axis": 8}
    mpath.write_text(json.dumps(doc))
    _, template = _state_on(cfg, eight_devices)
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        Checkpointer(str(tmp_path), max_to_keep=5).restore(template, cfg)


def test_pre_elastic_manifest_still_restores(tmp_path, eight_devices):
    """Version-1 manifests (no mesh key) keep restoring — reshard detection
    simply skips."""
    from homebrewnlp_tpu.train import Checkpointer
    cfg = _elastic_cfg()
    _, state = _state_on(cfg, eight_devices, steps=1)
    Checkpointer(str(tmp_path)).save(state, config_hash="x")
    mpath = tmp_path / "manifest_1.json"
    doc = json.loads(mpath.read_text())
    doc.pop("mesh")
    doc["version"] = 1
    for e in doc["leaves"].values():
        e.pop("spec", None)
    mpath.write_text(json.dumps(doc))
    _, template = _state_on(cfg, eight_devices[:4])
    restored, _ = Checkpointer(str(tmp_path)).restore(template, cfg)
    assert int(restored.step) == 1
    assert not (tmp_path / "restore_marker.json").exists()


# -- supervisor: reshard-restore progress + jitter ----------------------------

def test_progress_signature_reads_restore_marker(tmp_path):
    assert supervise.progress_signature(str(tmp_path)) == (-1, 0)
    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 4, "loss": 1.0}) + "\n")
    ck = tmp_path / "ckpt"
    ck.mkdir()
    (ck / "restore_marker.json").write_text(json.dumps({"count": 2}))
    assert supervise.progress_signature(str(tmp_path)) == (4, 2)
    # ordering: a reshard restore at a FROZEN step still compares as newer
    assert (4, 2) > (4, 1) and (5, 0) > (4, 2)


def test_reshard_restore_counts_as_crash_loop_progress(tmp_path):
    """Satellite regression: relaunches whose only on-disk evidence is a
    successful reshard restore (step counter frozen) must NOT be
    misclassified as a crash loop."""
    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"step": 4, "loss": 1.0}) + "\n")
    ck = tmp_path / "ckpt"
    ck.mkdir()
    launches = {"n": 0}

    def launch():
        launches["n"] += 1
        # every relaunch reshard-restores (marker count grows) but crashes
        # before advancing the step counter; the 4th completes
        (ck / "restore_marker.json").write_text(
            json.dumps({"count": launches["n"]}))
        return 0 if launches["n"] >= 4 else 1

    sup = supervise.Supervisor(
        launch, lambda: supervise.progress_signature(str(tmp_path)),
        max_failures_no_progress=2, backoff_base_s=0.0, backoff_jitter=0.0,
        sleep=lambda s: None, registry=MetricsRegistry())
    # without the marker component this aborts EXIT_CRASH_LOOP after 2
    assert sup.run() == 0
    assert launches["n"] == 4


def test_backoff_jitter_spreads_fleet_relaunches():
    sleeps = []
    outcomes = iter([1, 1, 0])
    progress = itertools.count()  # always advances: backoff stays at base
    sup = supervise.Supervisor(
        lambda: next(outcomes), lambda: next(progress),
        backoff_base_s=1.0, backoff_jitter=0.5, rng=lambda: 1.0,
        sleep=sleeps.append, registry=MetricsRegistry())
    assert sup.run() == 0
    assert sleeps == [1.5, 1.5]  # base * (1 + 0.5 * (2*1.0 - 1))
    sleeps2 = []
    outcomes = iter([1, 0])
    sup = supervise.Supervisor(
        lambda: next(outcomes), lambda: next(progress),
        backoff_base_s=1.0, backoff_jitter=0.5, rng=lambda: 0.0,
        sleep=sleeps2.append, registry=MetricsRegistry())
    assert sup.run() == 0
    assert sleeps2 == [0.5]  # the jitter really is two-sided


# -- fleet coordinator --------------------------------------------------------

def test_fleet_generation_resumes_from_newest_posting(tmp_path):
    f = supervise.FleetCoordinator(str(tmp_path), 0, 2)
    assert f.generation == 0
    f.post_exit(87)
    f.advance()
    f.post_exit(0)
    # a restarted supervisor rejoins PAST every posting in the directory —
    # its own or a peer's — so stale files can never read as live failures
    assert supervise.FleetCoordinator(str(tmp_path), 0, 2).generation == 2
    assert supervise.FleetCoordinator(str(tmp_path), 1, 2).generation == 2


def test_fresh_run_over_stale_fleet_dir_never_kills_children(tmp_path):
    """Code-review regression: a new run reusing last run's --fleet-dir
    must not interpret the old run's final crash postings as a live peer
    failure, and a returning supervisor clears its own stale tombstone so
    barriers wait for it again."""
    old = supervise.FleetCoordinator(str(tmp_path), 1, 2)
    old.post_exit(1)  # last run's rank 1 crashed...
    old.post_final(supervise.EXIT_CRASH_LOOP)  # ...and aborted for good
    fresh = supervise.FleetCoordinator(str(tmp_path), 0, 2,
                                       peer_timeout_s=0.2, poll_s=0.02)
    assert fresh.generation == 1  # past the stale posting
    assert fresh.peer_down() is None  # no spurious SIGTERM
    # until rank 1's supervisor is back, its standing tombstone exempts it
    # from barriers (degraded relaunch, no stall)
    fresh.post_exit(87)
    fresh.post_ready(87)
    t0 = time.monotonic()
    assert fresh.await_peers() == {0: 87}
    assert time.monotonic() - t0 < 0.2
    # rank 1's supervisor restarts: its coordinator clears the tombstone
    # (it is alive), so later barriers hold for it again
    back = supervise.FleetCoordinator(str(tmp_path), 1, 2)
    assert back.generation == 2  # joined past every posting
    assert fresh._final_ranks() == {}


def test_fleet_peer_down_ignores_clean_exits(tmp_path):
    f0 = supervise.FleetCoordinator(str(tmp_path), 0, 2, poll_s=0.01)
    f1 = supervise.FleetCoordinator(str(tmp_path), 1, 2, poll_s=0.01)
    assert f0.peer_down() is None
    f1.post_exit(0)  # peer finished cleanly: not a failure
    assert f0.peer_down() is None
    f1.advance()
    f1.post_exit(87)
    assert f0.peer_down() == 1


def test_fleet_barrier_times_out_degraded(tmp_path):
    f0 = supervise.FleetCoordinator(str(tmp_path), 0, 2,
                                    peer_timeout_s=0.3, poll_s=0.02)
    f0.post_exit(87)
    f0.post_ready(87)
    t0 = time.monotonic()
    seen = f0.await_peers()
    assert time.monotonic() - t0 >= 0.3
    assert seen == {0: 87}  # rank 1 never posted: relaunch degraded
    # the miss is remembered: the NEXT barrier does not re-pay the timeout
    f0.advance()
    f0.post_exit(1)
    f0.post_ready(1)
    t0 = time.monotonic()
    assert f0.await_peers() == {0: 1}
    assert time.monotonic() - t0 < 0.25
    # ...until the vanished rank posts again (rejoining PAST the newest
    # posting; the min-gen scan still credits it to the current barrier)
    f1 = supervise.FleetCoordinator(str(tmp_path), 1, 2)
    assert f1.generation >= f0.generation
    f1.post_ready(0)
    assert set(f0.await_peers()) == {0, 1}


def test_fleet_barrier_skips_tombstoned_rank(tmp_path):
    """A rank that left for good (crash-loop abort, budget exhaustion,
    clean completion) tombstones itself; later generations' barriers must
    not pay the peer timeout for it on EVERY relaunch."""
    f0 = supervise.FleetCoordinator(str(tmp_path), 0, 2,
                                    peer_timeout_s=10.0, poll_s=0.02)
    f1 = supervise.FleetCoordinator(str(tmp_path), 1, 2)
    f1.post_exit(supervise.EXIT_CRASH_LOOP)
    f1.post_final(supervise.EXIT_CRASH_LOOP)  # rank 1 aborts forever
    f0.advance()
    f0.advance()  # rank 0 is generations ahead, relaunching degraded
    f0.post_exit(1)
    f0.post_ready(1)
    t0 = time.monotonic()
    seen = f0.await_peers()
    assert time.monotonic() - t0 < 2.0  # no full-timeout stall
    assert seen == {0: 1}
    # the nonzero final is still a peer-down signal for the CURRENT child
    # generation where it was posted, not for later ones
    assert f0.peer_down() is None


def test_fleet_watcher_signals_live_child_exactly_once(tmp_path):
    """The watcher retries while the launcher has no live child yet (the
    Popen race), but stops the moment one SIGTERM is delivered — repeated
    signals would trip the child GraceController's second-signal
    escalation (forced exit 84, no grace checkpoint)."""
    f0 = supervise.FleetCoordinator(str(tmp_path), 0, 2, poll_s=0.02)
    f1 = supervise.FleetCoordinator(str(tmp_path), 1, 2)
    f1.post_exit(1)  # failed peer posting for the current generation
    calls = []

    def on_down(rank):
        calls.append(rank)
        return len(calls) >= 3  # first two polls: child not started yet

    w = f0.watch_peers(on_down)
    time.sleep(0.4)
    w.stop()
    assert calls == [1, 1, 1]  # retried through the race, then stopped


def test_fleet_lockstep_relaunch_in_process(tmp_path):
    """The full protocol with two in-process supervisors: rank 0's child
    crashes with EXIT_PEER_LOST; rank 1's watcher terminates its (still
    running) child; both hold the barrier, then relaunch together and
    complete."""
    events = []
    lock = threading.Lock()

    def log(e):
        with lock:
            events.append(e)

    f0 = supervise.FleetCoordinator(str(tmp_path), 0, 2,
                                    peer_timeout_s=20, poll_s=0.02)
    f1 = supervise.FleetCoordinator(str(tmp_path), 1, 2,
                                    peer_timeout_s=20, poll_s=0.02)
    term1 = threading.Event()

    def launch0():
        if f0.generation == 0:
            time.sleep(0.1)  # rank 1's child is definitely running
            log("r0 peer-lost")
            return supervise.EXIT_PEER_LOST
        log("r0 done")
        return 0

    def launch1():
        if f1.generation == 0:
            terminated = term1.wait(15)  # runs until the watcher kills it
            log("r1 terminated" if terminated else "r1 wait-timeout")
            return supervise.EXIT_PREEMPTED if terminated else 1
        log("r1 done")
        return 0

    p0, p1 = itertools.count(), itertools.count()
    sup0 = supervise.Supervisor(
        launch0, lambda: next(p0), backoff_jitter=0.0, sleep=lambda s: None,
        registry=MetricsRegistry(), fleet=f0)
    sup1 = supervise.Supervisor(
        launch1, lambda: next(p1), backoff_jitter=0.0, sleep=lambda s: None,
        registry=MetricsRegistry(), fleet=f1, terminate=term1.set)
    rcs = {}
    t0 = threading.Thread(target=lambda: rcs.update(r0=sup0.run()))
    t1 = threading.Thread(target=lambda: rcs.update(r1=sup1.run()))
    t0.start()
    t1.start()
    t0.join(30)
    t1.join(30)
    assert rcs == {"r0": 0, "r1": 0}
    assert "r1 terminated" in events  # the watcher really SIGTERMed it
    # lockstep: both relaunched exactly once, generations in sync
    assert sup0.restarts == 1 and sup1.restarts == 1
    assert f0.generation == f1.generation == 1
    # both generation-0 exits are on disk (87 + the graceful 83)
    g0 = {json.loads((tmp_path / f"exit_r{r}_g0.json").read_text())["rc"]
          for r in (0, 1)}
    assert g0 == {supervise.EXIT_PEER_LOST, supervise.EXIT_PREEMPTED}


def test_cli_inits_distributed_and_drills_dist_init_fault(tmp_path):
    """The production CLI path end to end: `main.py --run_mode train` with
    an explicit coordinator and num_processes=1 (the legacy --tpu pod
    slice) really initializes jax.distributed, and the fault plan is armed
    BEFORE the init so dist_init:fail@1 exercises the retry path."""
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = dict(model_mode="gpt", use_video=False, sequence_length=16,
               heads=4, features_per_head=32, depth=1, vocab_size=64,
               train_batch_size=2, memory_reduction_strategy="none",
               intermediate_feed_forward_multiplier_multiplier=0.5,
               block_config=[{"layer": ["norm-shift-scale",
                                        "feed_forward-in:relu"]}],
               model_path=str(tmp_path / "run"),
               dist_coordinator=f"127.0.0.1:{port}", dist_num_processes=1,
               fault_plan="dist_init:fail@1", compilation_cache_dir="")
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "main.py"), "--model",
         str(cfg_path), "--run_mode", "train", "--steps", "2"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-3000:]
    # the injected first-attempt failure went through the retry path —
    # which also proves initialize() really engaged (a silently-skipped
    # init would never reach the dist_init fault site), and rc 0 proves
    # the second attempt's real jax.distributed.initialize succeeded
    assert "dist_init failed (attempt 1" in out.stderr, out.stderr[-3000:]
    rows = _rows(str(tmp_path / "run"))
    assert [r["step"] for r in rows] == [0, 1]


# -- THE chaos-multihost drill: two supervised OS processes -------------------

@pytest.mark.slow  # ~60s: two supervisors x two generations of children;
# the CI chaos-multihost job runs it explicitly
def test_fleet_drill_two_supervised_processes(tmp_path, eight_devices):
    """Acceptance drill (CI ``chaos-multihost``): injected host death
    (peer:die@step4) under two real per-host supervisor processes ends in a
    lockstep fleet relaunch, and every host's resumed loss sequence is
    bit-identical to an uninterrupted run."""
    steps = 10
    ref = tiny_config(model_path=str(tmp_path / "ref"),
                      use_checkpointing=True, steps_per_checkpoint=2)
    cli.train(ref, _args(steps))
    fleet_dir = str(tmp_path / "fleet")
    child = os.path.join(REPO, "tests", "elastic_child.py")
    sup_py = os.path.join(REPO, "tools", "supervise.py")
    procs = []
    for r in range(2):
        model = str(tmp_path / f"host{r}")
        cmd = [sys.executable, sup_py, "--model-path", model,
               "--rank", str(r), "--world-size", "2",
               "--fleet-dir", fleet_dir, "--peer-timeout", "120",
               "--backoff-jitter", "0", "--backoff-base", "0.1", "--",
               sys.executable, child, "--model-path", model,
               "--steps", str(steps), "--fault-plan", "peer:die@step4"]
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, p in enumerate(procs):
        assert p.returncode == 0, f"rank{r} supervisor rc={p.returncode}:\n" \
                                  f"{outs[r][-3000:]}"
    ref_rows = {r["step"]: r["loss"] for r in _rows(str(tmp_path / "ref"))}
    for r in range(2):
        got = {row["step"]: row["loss"]
               for row in _rows(str(tmp_path / f"host{r}"))}
        assert set(got) == set(range(steps)), (r, sorted(got))
        for s in range(steps):
            assert ref_rows[s] == got[s], \
                f"host{r} loss diverged at step {s} after the fleet relaunch"
    # lockstep: every rank relaunched at least once — its newest exit
    # posting (never pruned) is for a generation past 0, and both ranks
    # tombstoned a clean completion
    fleet_files = os.listdir(fleet_dir)
    for r in range(2):
        assert any(f.startswith(f"exit_r{r}_g") and not f.endswith("_g0.json")
                   for f in fleet_files), (r, fleet_files)
        assert f"final_r{r}.json" in fleet_files, fleet_files
    # at least one host actually took the peer-lost path (the injected
    # death); the race where the watcher SIGTERMs a child mid-87-exit can
    # turn ONE of them into a plain crash, never both
    proms = "".join(
        (tmp_path / f"host{r}" / "supervisor_metrics.prom").read_text()
        for r in range(2))
    assert 'outcome="peer_lost"' in proms
