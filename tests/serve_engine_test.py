"""Continuous-batching engine tests (serve/engine.py + the KV-pool block
allocator, docs/observability.md "Continuous batching"): allocator
round-trips and fragmentation invariants, per-lane decode parity against
the serialized KV-cache engine, slot recycling bit-identicality, admission
shedding (pool exhaustion behaves like ``serve_queue_limit``), AOT
executable save/reload, and the REST-level batching smoke."""
from __future__ import annotations

import json
import os
import random
import threading
import typing
import urllib.error
import urllib.request

import numpy as np
import pytest

import sys
sys.path.insert(0, os.path.dirname(__file__))
from backend import mixer_config  # noqa: E402

from homebrewnlp_tpu.config import Config  # noqa: E402
from homebrewnlp_tpu.infer.kv_cache import (BlockAllocator,  # noqa: E402
                                            block_rows, blocks_per_sequence,
                                            cache_nbytes, cache_shapes,
                                            pool_blocks, pool_nbytes)
from homebrewnlp_tpu.models import init_params  # noqa: E402
from homebrewnlp_tpu.utils import random_text_batch  # noqa: E402


def _engine_cfg(**over) -> Config:
    base = dict(depth=1, sequence_length=12, heads=2, features_per_head=16,
                vocab_size=32, train_batch_size=1, sampling_temperature=0.0,
                use_autoregressive_sampling=True, serve_max_batch=3)
    base.update(over)
    return mixer_config(**base)


@pytest.fixture(scope="module")
def engine_setup():
    cfg = _engine_cfg()
    params, _ = init_params(cfg, random_text_batch(cfg))
    return cfg, params


# -- block allocator ----------------------------------------------------------

def test_allocator_round_trip():
    a = BlockAllocator(8, 4)
    assert a.free_blocks == 8
    ids = a.alloc("r1", 10)  # ceil(10/4) = 3 blocks
    assert len(ids) == 3 and a.free_blocks == 5
    assert a.held("r1") == ids
    ids2 = a.alloc("r2", 4)
    assert len(ids2) == 1 and not set(ids) & set(ids2)
    assert a.free("r1") == 3
    assert a.free_blocks == 7
    assert a.free("r1") == 0  # double free is a no-op
    # LIFO recycle: the freshly freed blocks serve the next admission
    ids3 = a.alloc("r3", 12)
    assert a.free_blocks == 4
    assert set(ids).issubset(set(ids3) | {ids2[0]})


def test_allocator_zero_and_owner_errors():
    a = BlockAllocator(2, 4)
    assert a.blocks_needed(0) == 1  # a request always holds >= 1 block
    assert a.alloc("r", 1) is not None
    with pytest.raises(ValueError):
        a.alloc("r", 1)  # one live allocation per owner
    with pytest.raises(ValueError):
        BlockAllocator(0, 4)
    with pytest.raises(ValueError):
        BlockAllocator(4, 0)


def test_allocator_fragmentation_under_random_lengths():
    """Blocks are fungible: after ANY alloc/free history, an allocation
    succeeds iff enough blocks are free (no fragmentation failure mode),
    and no block is ever lost or double-held."""
    rng = random.Random(7)
    a = BlockAllocator(16, 4)
    live: typing.Dict[int, int] = {}
    for i in range(300):
        if live and rng.random() < 0.45:
            owner = rng.choice(list(live))
            assert a.free(owner) == live.pop(owner)
        else:
            tokens = rng.randint(1, 40)
            need = a.blocks_needed(tokens)
            got = a.alloc(i, tokens)
            if need <= 16 - sum(live.values()):
                assert got is not None and len(got) == need
                live[i] = need
            else:
                assert got is None
        held = [b for o in live for b in a.held(o)]
        assert len(held) == len(set(held)) == sum(live.values())
        assert a.free_blocks + len(held) == 16
    for owner in list(live):
        a.free(owner)
    assert a.free_blocks == 16


def test_pool_geometry_defaults_match_monolithic(engine_setup):
    """Default knobs (whole-sequence blocks): pool bytes == the monolithic
    batch-1 cache x serve_max_batch; explicit blocks round up."""
    cfg, params = engine_setup
    rows = cfg.sequence_length // cfg.token_patch_size
    assert block_rows(cfg) == rows and blocks_per_sequence(cfg) == 1
    assert pool_blocks(cfg) == cfg.serve_max_batch
    mono = cache_nbytes(cache_shapes(cfg, params, 1))
    assert pool_nbytes(cfg, params) == mono * cfg.serve_max_batch
    cfg4 = _engine_cfg(serve_block_tokens=5 * cfg.token_patch_size)
    # 12 rows in blocks of 5 -> 3 blocks/sequence, pool rounds up past seq
    assert blocks_per_sequence(cfg4) == 3
    assert pool_nbytes(cfg4, params) >= mono * cfg4.serve_max_batch


def test_serve_knob_validation():
    with pytest.raises(ValueError):
        _engine_cfg(serve_max_batch=0)
    with pytest.raises(ValueError):
        _engine_cfg(serve_block_tokens=-1)
    with pytest.raises(ValueError):
        _engine_cfg(serve_kv_blocks=-2)
    # a pool that cannot hold one full-length sequence is dead at admission
    with pytest.raises(ValueError):
        _engine_cfg(serve_block_tokens=4, serve_kv_blocks=2)
    cfg = _engine_cfg(serve_block_tokens=4, serve_kv_blocks=3)
    assert pool_blocks(cfg) == 3


# -- engine semantics ---------------------------------------------------------

def test_batch_engine_greedy_parity_with_serialized(engine_setup):
    """The continuous-batching engine's greedy completions match the
    serialized KV-cache sampler token for token — same math, the lanes
    only add a batch axis."""
    from homebrewnlp_tpu.serve.engine import BatchEngine, BatchInterface
    from homebrewnlp_tpu.serve.interface import CompletionEngine
    cfg, params = engine_setup
    eng = BatchEngine(cfg, params)
    iface = BatchInterface(eng)
    ser = CompletionEngine(cfg, params)
    try:
        for prompt in ([1, 2, 3], [5], [7, 8, 9, 10, 11]):
            a = np.asarray(iface.complete(prompt, 0.0, 5))
            b = np.asarray(ser.complete_tokens(prompt, 0.0, 5))
            assert a.tolist() == b.tolist(), (prompt, a, b)
    finally:
        iface.close()


def test_lane0_stochastic_parity_with_serialized(engine_setup):
    """Per-lane RNG streams: at temperature 1.0 the engine's sampled
    completion matches the serialized KV-cache sampler called with the
    SAME key (``lane_key(seed, rid)``) and the engine's exact padded
    prompt layout, token for token — the lane's stream is a pure function
    of (seed, rid), never of lane index or step interleaving."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.data.feed import TEXT_AXES
    from homebrewnlp_tpu.infer.kv_cache import make_cached_text_sampler
    from homebrewnlp_tpu.nd import NT
    from homebrewnlp_tpu.serve.engine import BatchEngine, lane_key
    cfg, params = engine_setup
    prompt = [1, 2, 3]
    max_tokens = 5
    eng = BatchEngine(cfg, params)
    try:
        got = np.asarray(eng.complete_tokens(prompt, 1.0, max_tokens))
    finally:
        eng.close()
    rows = cfg.sequence_length // cfg.token_patch_size
    # the engine's _pad_prompt layout: a fresh engine's first request (and
    # its rid=1 lane key) is fully determined by (cfg.data_seed, prompt)
    flat = np.random.default_rng(cfg.data_seed).integers(
        0, cfg.vocab_size, size=rows * cfg.token_patch_size,
        dtype=np.int64).astype(np.int32)
    flat[:len(prompt)] = np.asarray(prompt, np.int32)
    toks = flat.reshape(1, rows, cfg.token_patch_size)
    prompt_rows = len(prompt) // cfg.token_patch_size
    end = len(prompt) + max_tokens
    end_row = min(rows, -(-end // cfg.token_patch_size))
    sampler = make_cached_text_sampler(cfg, params)
    want = np.asarray(sampler(
        NT(jnp.asarray(toks), TEXT_AXES), np.int32(prompt_rows),
        np.float32(1.0), lane_key(cfg.data_seed, 1), np.int32(end_row),
        np.int32(0), np.int32(0))).reshape(-1)[:end]
    assert got.tolist() == want.tolist()


def test_sampled_output_independent_of_admission_order(engine_setup):
    """The per-request property the per-lane streams buy: a request's
    stochastic completion depends only on (seed, rid, prompt, knobs) —
    running the same two requests concurrently (different lanes, shared
    decode steps) or back-to-back (both on lane 0) yields identical
    tokens."""
    from homebrewnlp_tpu.serve.engine import BatchEngine
    cfg, params = engine_setup
    pa, pb = [1, 2, 3], [7, 8]
    eng = BatchEngine(cfg, params)
    try:  # concurrent: B is admitted while A decodes
        ra = eng.submit(pa, 1.0, 5, None, None)
        rb = eng.submit(pb, 1.0, 5, None, None)
        conc = [np.asarray(eng.fetch(ra)), np.asarray(eng.fetch(rb))]
    finally:
        eng.close()
    eng = BatchEngine(cfg, params)
    try:  # sequential: both run alone on lane 0 with the same rids
        seq = [np.asarray(eng.complete_tokens(pa, 1.0, 5)),
               np.asarray(eng.complete_tokens(pb, 1.0, 5))]
    finally:
        eng.close()
    assert conc[0].tolist() == seq[0].tolist()
    assert conc[1].tolist() == seq[1].tolist()


def test_concurrent_requests_share_decode_steps(engine_setup):
    from homebrewnlp_tpu.serve.engine import BatchEngine, BatchInterface
    cfg, params = engine_setup
    eng = BatchEngine(cfg, params)
    iface = BatchInterface(eng)
    occupancy: typing.List[int] = []
    eng.set_batch_observer(occupancy.append)
    results: typing.List[typing.Optional[np.ndarray]] = [None] * 6
    try:
        def go(i):
            results[i] = iface.complete([1 + i, 2, 3], 0.0, 6)

        threads = [threading.Thread(target=go, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert all(r is not None and len(r) == 9 for r in results)
        # with 6 requests over 3 lanes, steps must have been shared
        assert occupancy and max(occupancy) > 1
        # and the single-request parity still holds afterwards (lanes idle)
        single = iface.complete([1, 2, 3], 0.0, 6)
        assert np.asarray(single).tolist() == np.asarray(results[0]).tolist()
    finally:
        iface.close()
    assert eng.kv_blocks_free() == eng.allocator.n_blocks  # all recycled


def test_slot_reuse_bit_identical_logits(engine_setup):
    """A lane recycled from a finished request produces bit-identical
    logits to a fresh engine's — stale K/V beyond the causal frontier is
    never visible, so recycling needs no zeroing pass."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.serve.engine import BatchEngine
    cfg, params = engine_setup
    dirty = BatchEngine(cfg, params)
    fresh = BatchEngine(cfg, params)
    try:
        # pollute every lane of `dirty` with completions, then run the SAME
        # new request through both engines and compare the decode logits
        for i in range(cfg.serve_max_batch + 1):
            dirty.complete_tokens([9 + i, 3, 1], 0.0, 6)
        probe = [4, 5, 6]
        out_d = np.asarray(dirty.complete_tokens(probe, 0.0, 6))
        lane_d = np.array(dirty._logits)
        out_f = np.asarray(fresh.complete_tokens(probe, 0.0, 6))
        lane_f = np.array(fresh._logits)
        assert out_d.tolist() == out_f.tolist()
        # the final step's logits for the probe's lane are bit-identical;
        # both engines ran it on lane 0 (all lanes idle at submit)
        assert jnp.array_equal(lane_d[0], lane_f[0])
    finally:
        dirty.close()
        fresh.close()


def test_zero_generation_and_empty_prompt(engine_setup):
    from homebrewnlp_tpu.serve.engine import BatchEngine
    cfg, params = engine_setup
    eng = BatchEngine(cfg, params)
    try:
        full = list(range(1, cfg.sequence_length + 1))
        out = eng.complete_tokens(full, 0.0, 0)  # nothing to generate
        assert np.asarray(out).tolist() == full[:cfg.sequence_length]
        empty = eng.complete_tokens([], 0.0, 4)  # decodes from scratch
        assert len(empty) == 4
    finally:
        eng.close()
    assert eng.kv_blocks_free() == eng.allocator.n_blocks


def test_pool_exhaustion_sheds_like_queue_limit(engine_setup):
    """With the pool sized to ONE full-length request, concurrent arrivals
    queue behind the admission gate; past ``serve_queue_limit`` they shed
    exactly like the serialized engine's queue (QueueDeadlineExceeded with
    ``shed=True`` -> REST 503 + Retry-After)."""
    from homebrewnlp_tpu.serve.engine import BatchEngine, BatchInterface
    from homebrewnlp_tpu.serve.interface import QueueDeadlineExceeded
    cfg = _engine_cfg(serve_max_batch=2, serve_block_tokens=4,
                      serve_kv_blocks=3, serve_queue_limit=1)
    params, _ = init_params(cfg, random_text_batch(cfg))
    eng = BatchEngine(cfg, params)
    iface = BatchInterface(eng)
    try:
        # pool-bound, not lane-bound: one full-length request holds all 3
        # blocks, so the second lane cannot admit despite being free
        hog = eng.submit(list(range(1, 9)), 0.0, None, None, None)
        assert hog.admitted.wait(60)
        starved = eng.submit(list(range(1, 9)), 0.0, None, None, None)
        with pytest.raises(QueueDeadlineExceeded) as exc:
            iface.complete([1], 0.0, None)  # 1 queued >= serve_queue_limit
        assert exc.value.shed and "shed at admission" in str(exc.value)
        assert len(eng.fetch(hog)) == cfg.sequence_length
        assert len(eng.fetch(starved)) == cfg.sequence_length
    finally:
        iface.close()
    assert eng.kv_blocks_free() == 3


def test_queue_deadline_cancels_queued_request(engine_setup):
    from homebrewnlp_tpu.serve.engine import BatchEngine
    from homebrewnlp_tpu.serve.interface import QueueDeadlineExceeded
    cfg = _engine_cfg(serve_max_batch=2, serve_block_tokens=4,
                      serve_kv_blocks=3, serve_queue_deadline_s=0.05,
                      default_sleep_duration=0.01)
    params, _ = init_params(cfg, random_text_batch(cfg))
    eng = BatchEngine(cfg, params)
    try:
        # pin the WHOLE pool through the allocator (deterministic — no
        # timing race against real requests finishing): nothing can admit,
        # so the queued request must time out and cancel
        assert eng.allocator.alloc("pin", cfg.sequence_length) is not None
        starved = eng.submit([1, 2], 0.0, 4, None, None)
        with pytest.raises(QueueDeadlineExceeded):
            eng.fetch(starved)
        assert not starved.admitted.is_set() and starved.cancelled.is_set()
        eng.allocator.free("pin")
        # the pool is back: a fresh request admits and completes, and the
        # cancelled one was pruned from the queue
        assert len(eng.complete_tokens([1, 2, 3], 0.0, 4)) == 7
        assert eng.queue_depth() == 0
    finally:
        eng.close()
    assert eng.kv_blocks_free() == 3


def test_prefill_failure_fails_request_and_recycles_blocks(engine_setup):
    """A prefill error must fail THAT request (fetch raises, blocks
    recycled) instead of orphaning it — the request is already admitted,
    so the deadline-cancel path can never rescue it."""
    from homebrewnlp_tpu.serve.engine import BatchEngine
    cfg, params = engine_setup
    eng = BatchEngine(cfg, params)
    try:
        boom = RuntimeError("injected prefill failure")

        def broken_prefill(*a, **k):
            raise boom

        eng._prefill = broken_prefill
        req = eng.submit([1, 2, 3], 0.0, 4, None, None)
        with pytest.raises(RuntimeError, match="injected prefill"):
            eng.fetch(req)
        assert eng.kv_blocks_free() == eng.allocator.n_blocks
        assert eng.active_lanes() == 0 and eng.queue_depth() == 0
    finally:
        eng.close()


def test_kv_pricing_ignores_pool_knobs_on_serialized_path(engine_setup):
    """graftcost prices the pool only where the batch engine allocates
    one: serve_max_batch=1 keeps the monolithic batch-1 kv bytes even
    with pool knobs set."""
    from homebrewnlp_tpu.analysis.cost_model import _kv_bytes
    from homebrewnlp_tpu.analysis.graph_rules import intended_mesh
    from homebrewnlp_tpu.analysis.trace import trace_config
    cfg1 = _engine_cfg(serve_max_batch=1, serve_block_tokens=4,
                       serve_kv_blocks=8)
    cfgN = _engine_cfg(serve_max_batch=3)
    t1 = trace_config(cfg1, "t1", steps=("decode",))
    tN = trace_config(cfgN, "tN", steps=("decode",))
    kv1 = _kv_bytes(t1, intended_mesh(cfg1))[0]
    kvN = _kv_bytes(tN, intended_mesh(cfgN))[0]
    assert kvN == kv1 * 3  # pool priced only for the batch engine


def test_engine_executables_donate_pooled_state(engine_setup):
    """The decode/prefill executables carry the donation contract
    (pooled caches / token pool / positions / rng) — traced abstractly,
    the exact check the graftcheck donation rule ratchets.  On-device
    this is what turns the per-step full-pool copy into an in-place
    update; CPU ignores donation, so behavior tests stay valid."""
    import jax
    from homebrewnlp_tpu.serve import engine
    cfg, params = engine_setup
    rows = cfg.sequence_length // cfg.token_patch_size
    dec_jit, pre_jit, chk_jit = engine.jit_executables(cfg, rows,
                                                       cfg.serve_max_batch)
    dec_abs, pre_abs, chk_abs = engine.abstract_exec_args(
        cfg, params, rows, cfg.serve_max_batch)
    assert chk_jit is None and chk_abs is None  # chunking off by default
    for jitted, abs_args, want in (
            (dec_jit, dec_abs, engine.DECODE_DONATE_ARGNUMS),
            (pre_jit, pre_abs, engine.PREFILL_DONATE_ARGNUMS)):
        infos = jitted.trace(*abs_args).args_info[0]
        for i, info in enumerate(infos):
            leaves = jax.tree_util.tree_leaves(info)
            donated = [bool(getattr(x, "donated", False)) for x in leaves]
            if i in want:
                assert all(donated), (i, donated)
            else:
                assert not any(donated), (i, donated)


def test_pool_reset_after_donation_consuming_failure(engine_setup):
    """A failure that consumed the donated pool (buffers deleted) must
    re-initialize the device state in _fail_all, so the engine keeps
    serving after failing the in-flight requests."""
    from homebrewnlp_tpu.serve.engine import BatchEngine
    cfg, params = engine_setup
    eng = BatchEngine(cfg, params)
    try:
        assert not eng._pool_deleted()
        next(iter(eng._caches.values()))[0].delete()
        assert eng._pool_deleted()
        eng._fail_all(RuntimeError("synthetic donation-consuming failure"))
        assert not eng._pool_deleted()
        # still serves after the reset
        out = eng.complete_tokens([1, 2, 3, 4], temperature=0.0,
                                  max_tokens=4)
        assert len(out) > 0
    finally:
        eng.close()


def test_use_batch_engine_gate():
    from homebrewnlp_tpu.serve.engine import BatchEngine, use_batch_engine
    assert not use_batch_engine(_engine_cfg(serve_max_batch=1))
    assert use_batch_engine(_engine_cfg(serve_max_batch=2))
    # a non-KV-eligible stack keeps the serialized path
    from backend import tiny_config
    cfg = tiny_config(serve_max_batch=2, block_config=[
        {"layer": ["norm-shift-scale", "cumsum"]}])
    assert not use_batch_engine(cfg)
    with pytest.raises(ValueError):
        BatchEngine(cfg, {})


# -- AOT executable serialization ---------------------------------------------

def test_aot_save_reload_same_tokens(tmp_path, engine_setup):
    from homebrewnlp_tpu.serve.engine import BatchEngine, aot_cache_key
    cfg0, params = engine_setup
    cfg = _engine_cfg(serve_aot_cache_dir=str(tmp_path))
    e1 = BatchEngine(cfg, params)
    assert e1.aot_cache_hit is False and e1.compile_s is not None
    key = aot_cache_key(cfg, e1.params, cfg.serve_max_batch)
    names = sorted(os.listdir(tmp_path))
    assert names == [f"decode-{key}.jaxexec", f"prefill-{key}.jaxexec"]
    out1 = np.asarray(e1.complete_tokens([1, 2, 3], 0.0, 5))
    e1.close()
    e2 = BatchEngine(cfg, params)
    assert e2.aot_cache_hit is True and e2.aot_reload_s is not None
    assert e2.compile_s is None
    out2 = np.asarray(e2.complete_tokens([1, 2, 3], 0.0, 5))
    assert out1.tolist() == out2.tolist()
    e2.close()


def test_aot_corrupt_entry_falls_back_to_compile(tmp_path, engine_setup):
    from homebrewnlp_tpu.serve.engine import BatchEngine
    _, params = engine_setup
    cfg = _engine_cfg(serve_aot_cache_dir=str(tmp_path))
    e1 = BatchEngine(cfg, params)
    e1.close()
    for name in os.listdir(tmp_path):
        with open(os.path.join(tmp_path, name), "wb") as f:
            f.write(b"torn write")
    e2 = BatchEngine(cfg, params)
    assert e2.aot_cache_hit is False and e2.compile_s is not None
    assert len(e2.complete_tokens([1, 2], 0.0, 3)) == 5
    e2.close()


def test_aot_key_invalidates_on_config_change(engine_setup):
    from homebrewnlp_tpu.serve.engine import aot_cache_key
    cfg, params = engine_setup
    k1 = aot_cache_key(cfg, params, 3)
    assert k1 == aot_cache_key(cfg, params, 3)  # deterministic
    assert k1 != aot_cache_key(cfg, params, 4)  # lane count
    cfg2 = _engine_cfg(sampling_top_k=4)
    assert k1 != aot_cache_key(cfg2, params, 3)  # config hash


# -- REST integration ---------------------------------------------------------

def _drive(url: str, prompt, response_len=4, n=1):
    out = []
    for _ in range(n):
        req = urllib.request.Request(
            url + "/token_completion",
            data=json.dumps({"prompt": prompt,
                             "response_len": response_len}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out.append(json.loads(r.read()))
    return out


def test_rest_serves_batch_engine_and_batch_metrics(engine_setup):
    """serve() swaps in the batching engine for serve_max_batch > 1 and
    the SLO layer exposes hbnlp_serve_batch_size (p50 > 1 under
    concurrency) + hbnlp_serve_kv_blocks_free on /metrics + /healthz."""
    import sys as _sys
    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                     "tools"))
    import graftload

    from homebrewnlp_tpu.obs.registry import MetricsRegistry
    from homebrewnlp_tpu.serve import BatchInterface, RestAPI, serve
    cfg, params = engine_setup
    reg = MetricsRegistry()
    api = RestAPI(cfg, params)
    server = serve(cfg, None, port=0, background=True, registry=reg,
                   obs_port=0, api=api)
    try:
        api_url = f"http://127.0.0.1:{server.server_address[1]}"
        murl = f"http://127.0.0.1:{server._obs_server.server_address[1]}"
        assert isinstance(server._batch_wrapper, BatchInterface)
        report = graftload.drive(api_url, metrics_url=murl, n_requests=12,
                                 concurrency=6, vocab=cfg.vocab_size,
                                 min_prompt=2, max_prompt=6, response_len=4,
                                 seed=3)
        assert report["client"]["error_rate"] == 0.0
        srv = report["server"]
        assert srv["batch_size"]["p50"] > 1, srv
        assert srv["kv_blocks_free"] == cfg.serve_max_batch
        with urllib.request.urlopen(murl + "/healthz", timeout=10) as r:
            slo = json.loads(r.read())["slo"]
        assert slo["batch_size"]["p50"] > 1
        assert slo["kv_blocks_free"] == cfg.serve_max_batch
    finally:
        server.shutdown()
        server.server_close()
        api.wrapper.close()
    # teardown detached the hooks: the registry no longer pins the engine
    assert server.slo._kv_blocks_probe is None
    assert server._batch_wrapper is None


def test_rest_pool_exhaustion_503_retry_after():
    from homebrewnlp_tpu.obs.registry import MetricsRegistry
    from homebrewnlp_tpu.serve import serve
    cfg = _engine_cfg(serve_max_batch=2, serve_block_tokens=4,
                      serve_kv_blocks=3, serve_queue_limit=1)
    params, _ = init_params(cfg, random_text_batch(cfg))
    server = serve(cfg, params, port=0, background=True,
                   registry=MetricsRegistry())
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        results: typing.List[typing.Optional[int]] = [None] * 4
        retry_after: typing.List[typing.Optional[str]] = [None]

        def go(i):
            try:
                _drive(url, list(range(1, 11)), response_len=64)
                results[i] = 200
            except urllib.error.HTTPError as e:
                results[i] = e.code
                retry_after[0] = e.headers.get("Retry-After")
                e.read()

        threads = [threading.Thread(target=go, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert results.count(503) >= 1, results
        assert retry_after[0] is not None and float(retry_after[0]) >= 1
        assert results.count(200) >= 1
    finally:
        server.shutdown()
        server.server_close()


def test_serialized_path_untouched_by_default(engine_setup):
    """serve_max_batch=1 (default) keeps the pre-engine serialized path:
    same wrapper type, no batch metrics observed."""
    from homebrewnlp_tpu.obs.registry import MetricsRegistry
    from homebrewnlp_tpu.serve import InterfaceWrapper, RestAPI, serve
    cfg = _engine_cfg(serve_max_batch=1)
    params, _ = init_params(cfg, random_text_batch(cfg))
    reg = MetricsRegistry()
    api = RestAPI(cfg, params)
    assert isinstance(api.wrapper, InterfaceWrapper)
    server = serve(cfg, None, port=0, background=True, registry=reg, api=api)
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        out = _drive(url, [1, 2, 3])[0]
        assert len(out["completion"]) == 7
        assert server.slo.batch_size.count() == 0
        assert server.slo.summary()["batch_size"] is None
        assert server.slo.summary()["kv_blocks_free"] is None
    finally:
        server.shutdown()
        server.server_close()
        api.wrapper.close()
