"""Low-precision compute (ISSUE 6): quant/dequant round-trip bounds, the
custom-vjp int8 einsum (quantized forward, exact high-precision backward),
default-off bit-identical parity, the int8 train smoke, the bench accept
gate + compile-budget evaluation, and the opaque-kernel FLOPs lower bound."""
import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from homebrewnlp_tpu import main as cli, nd
from homebrewnlp_tpu.nd import NT
from homebrewnlp_tpu.ops import quant
from homebrewnlp_tpu.train.metrics import read_metric_rows

from .backend import mixer_config, tiny_config


def _args(steps):
    return argparse.Namespace(steps=steps, profile="", workers=None)


def _losses(path):
    return [r["loss"] for r in read_metric_rows(str(path))]


# -- quantize / dequantize round-trip ----------------------------------------

def test_per_tensor_round_trip_bound():
    """Symmetric int8 round-trip error is bounded by half a quantization
    step (scale/2) everywhere inside the clip range."""
    x = jax.random.normal(jax.random.key(0), (64, 32), jnp.float32) * 3.0
    s = quant.per_tensor_scale(x, "int8")
    q = quant.quantize(x, s, "int8")
    assert q.dtype == jnp.int8
    err = jnp.max(jnp.abs(quant.dequantize(q, s) - x))
    assert float(err) <= float(s) / 2 + 1e-6, (float(err), float(s))


def test_per_channel_beats_per_tensor_on_skewed_channels():
    """Per-channel scales adapt to per-channel magnitude spread — the
    reason the weight operand quantizes per output channel."""
    key = jax.random.key(1)
    x = jax.random.normal(key, (128, 8), jnp.float32)
    x = x * (10.0 ** jnp.arange(-3, 5, dtype=jnp.float32))  # wild channels
    st = quant.per_tensor_scale(x, "int8")
    err_t = jnp.abs(quant.dequantize(quant.quantize(x, st, "int8"), st) - x)
    sc = quant.per_channel_scale(x, (0,), "int8")
    err_c = jnp.abs(
        quant.dequantize(quant.quantize(x, sc[None, :], "int8"),
                         sc[None, :]) - x)
    # compare on the small-magnitude channels where per-tensor collapses
    assert float(jnp.max(err_c[:, 0])) < float(jnp.max(err_t[:, 0])) / 100


def test_zero_tensor_quantizes_to_zero():
    x = jnp.zeros((4, 4), jnp.float32)
    s = quant.per_tensor_scale(x, "int8")
    assert float(s) > 0  # floored, no div-by-zero
    assert float(jnp.max(jnp.abs(
        quant.dequantize(quant.quantize(x, s, "int8"), s)))) == 0.0


# -- quant_einsum: forward accuracy + backward exactness ---------------------

def _rand_nt(key, shape, names, dtype):
    return NT(jax.random.normal(key, shape, jnp.float32).astype(dtype), names)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_quant_einsum_forward_close_and_backward_exact(dtype):
    """Forward: the W8A8 contraction tracks the high-precision einsum
    (int8 rounding noise only).  Backward: EXACTLY the gradients of the
    unquantized contraction (straight-through custom_vjp contract)."""
    kx, kw = jax.random.split(jax.random.key(2))
    x = _rand_nt(kx, (4, 16, 8, 32), ("batch", "sequence", "heads",
                                      "features_per_head"), dtype)
    w = _rand_nt(kw, (8, 32, 64), ("heads", "features_per_head",
                                   "intermediate"), dtype)
    out_names = ("batch", "sequence", "intermediate")
    ref = nd.einsum([x, w], out_names)
    got = quant.quant_einsum(x, w, out_names, "int8")
    assert got.names == ref.names and got.dtype == ref.dtype
    rel = (jnp.linalg.norm((got.x - ref.x).astype(jnp.float32))
           / jnp.linalg.norm(ref.x.astype(jnp.float32)))
    assert float(rel) < 0.02, float(rel)

    def loss_q(xa, wa):
        return jnp.sum(quant.quant_einsum(
            NT(xa, x.names), NT(wa, w.names), out_names, "int8"
        ).x.astype(jnp.float32))

    def loss_ref(xa, wa):
        return jnp.sum(nd.einsum(
            [NT(xa, x.names), NT(wa, w.names)], out_names
        ).x.astype(jnp.float32))

    gq = jax.grad(loss_q, argnums=(0, 1))(x.x, w.x)
    gr = jax.grad(loss_ref, argnums=(0, 1))(x.x, w.x)
    for a, b in zip(gq, gr):
        assert a.dtype == b.dtype == dtype
        assert bool(jnp.all(a == b)), "backward must be the exact " \
                                      "high-precision vjp"


def test_quant_einsum_batched_head_axis():
    """The per-head block-diagonal contraction (group linear: HEADS stays
    on both sides) — the grouped-mixer shape the tentpole targets."""
    kx, kw = jax.random.split(jax.random.key(3))
    x = _rand_nt(kx, (2, 8, 4, 16), ("batch", "sequence", "heads",
                                     "features_per_head"), jnp.float32)
    w = _rand_nt(kw, (4, 16, 32), ("heads", "features_per_head",
                                   "_features_per_head"), jnp.float32)
    out_names = ("batch", "sequence", "heads", "_features_per_head")
    ref = nd.einsum([x, w], out_names)
    got = quant.quant_einsum(x, w, out_names, "int8")
    assert got.x.shape == ref.x.shape
    rel = (jnp.linalg.norm(got.x - ref.x) / jnp.linalg.norm(ref.x))
    assert float(rel) < 0.02, float(rel)


@pytest.mark.skipif(not quant.supported("fp8"),
                    reason="toolchain lacks fp8 dtypes")
def test_quant_einsum_fp8_path():
    kx, kw = jax.random.split(jax.random.key(4))
    x = _rand_nt(kx, (4, 8), ("batch", "features_per_head"), jnp.float32)
    w = _rand_nt(kw, (8, 16), ("features_per_head", "intermediate"),
                 jnp.float32)
    out = quant.quant_einsum(x, w, ("batch", "intermediate"), "fp8")
    ref = nd.einsum([x, w], ("batch", "intermediate"))
    assert bool(jnp.all(jnp.isfinite(out.x)))
    rel = (jnp.linalg.norm(out.x - ref.x) / jnp.linalg.norm(ref.x))
    assert float(rel) < 0.1, float(rel)  # e4m3: 3 mantissa bits


# -- scope selection ---------------------------------------------------------

def test_scope_matching_and_pattern_quantized():
    assert quant.scope_matches(["bottleneck_group_linear"],
                               "gpt/block_/bottleneck_group_linear_3/x")
    assert not quant.scope_matches(["bottleneck_group_linear"],
                                   "gpt/block_/attention_/x")
    cfg = mixer_config(quant_blocks=["bottleneck_group_linear"])
    from homebrewnlp_tpu.models.layers import (GROUP_FUSED_PATTERN,
                                               MIXER_FUSED_PATTERN)
    # fusion yields to quantization on the group block; the mixer block
    # holds no quantized layer and keeps its fused kernel
    assert quant.pattern_quantized(cfg, GROUP_FUSED_PATTERN)
    assert not quant.pattern_quantized(cfg, MIXER_FUSED_PATTERN)
    assert not quant.pattern_quantized(mixer_config(), GROUP_FUSED_PATTERN)
    # seeded regression: the slash-anchored disambiguation form (and a
    # trailing-underscore scope form) must ALSO disable fusion — bare-name
    # matching here once let the fused kernel bypass a declared scope
    anchored = mixer_config(quant_blocks=["/bottleneck_group_linear"])
    assert quant.pattern_quantized(anchored, GROUP_FUSED_PATTERN)
    # "/group_linear" selects only the plain per-head linear: it matches
    # neither the bottleneck scope in linear() nor the fused pattern here
    only_plain = mixer_config(quant_blocks=["/group_linear"])
    assert not quant.pattern_quantized(only_plain, GROUP_FUSED_PATTERN)
    assert not quant.scope_matches(
        ["/group_linear"], "gpt/block_/bottleneck_group_linear_/w")


# -- parity: quant_blocks unset => bit-identical pre-quant graph -------------

def test_quant_off_parity_8_steps(tmp_path, eight_devices):
    """Acceptance: the default config and a declared-but-unmatched scope
    both compile the exact pre-quant graph — loss sequences bit-identical.
    (The committed census goldens pin the stronger structural fact: n_eqns
    of every pre-quant config is unchanged.)"""
    cli.train(tiny_config(model_path=str(tmp_path / "off")), _args(8))
    cli.train(tiny_config(model_path=str(tmp_path / "nomatch"),
                          quant_blocks=["no_such_layer_name"]), _args(8))
    off = _losses(tmp_path / "off")
    assert len(off) == 8
    assert off == _losses(tmp_path / "nomatch")


@pytest.mark.slow
def test_quant_off_parity_300_steps(tmp_path, eight_devices):
    base = dict(async_inflight_steps=0, device_prefetch_depth=0)
    cli.train(tiny_config(model_path=str(tmp_path / "off"), **base),
              _args(300))
    cli.train(tiny_config(model_path=str(tmp_path / "nomatch"),
                          quant_blocks=["no_such_layer_name"], **base),
              _args(300))
    off = _losses(tmp_path / "off")
    assert len(off) == 300
    assert off == _losses(tmp_path / "nomatch")


def test_int8_train_smoke_and_trajectory(tmp_path, eight_devices):
    """8 updates with the feed-forward linears quantized: finite losses,
    training still progresses, and the trajectory stays near the
    high-precision one (the tiny-scale twin of the bench accept gate)."""
    cli.train(tiny_config(model_path=str(tmp_path / "base")), _args(8))
    cli.train(tiny_config(model_path=str(tmp_path / "q"),
                          quant_blocks=["feed_forward"]), _args(8))
    base, q = _losses(tmp_path / "base"), _losses(tmp_path / "q")
    assert all(l == l for l in q)
    assert q[-1] < q[0]
    assert max(abs(a - b) for a, b in zip(base, q)) < 0.1, (base, q)


def test_quant_config_validation():
    with pytest.raises(ValueError):
        tiny_config(quant_dtype="int4")
    with pytest.raises(ValueError):
        tiny_config(quant_blocks=[""])
    with pytest.raises(ValueError):
        # a bare string would explode into per-character substrings and
        # silently quantize nearly everything
        tiny_config(quant_blocks="feed_forward")
    cfg = tiny_config(quant_blocks=["feed_forward"], quant_dtype="int8")
    assert cfg.quant_blocks == ["feed_forward"]


# -- bench accept gate + compile budget (pure evaluators) --------------------

def test_evaluate_quant_gate_verdicts():
    from bench import evaluate_quant_gate
    base = [7.0, 5.0, 4.0, 3.5]
    ok = evaluate_quant_gate(base, [7.05, 5.02, 4.03, 3.52], rel_tol=0.1)
    assert ok["pass"] and ok["finite"] and ok["trains"]
    # deviation beyond tolerance: measured REJECT, numbers still reported
    bad = evaluate_quant_gate(base, [7.0, 5.0, 4.0, 5.9], rel_tol=0.1)
    assert not bad["pass"] and bad["max_rel_dev"] > 0.1
    nan = evaluate_quant_gate(base, [7.0, float("nan"), 4.0, 3.5])
    assert not nan["pass"] and not nan["finite"]
    flat = evaluate_quant_gate(base, [7.0, 7.0, 7.0, 7.0], rel_tol=10.0)
    assert not flat["pass"] and not flat["trains"]
    assert not evaluate_quant_gate(base, [1.0])["pass"]  # length mismatch


def test_evaluate_compile_budget():
    from bench import evaluate_compile_budget
    budgets = {"a": 100.0, "b": 50.0}
    rows, ok = evaluate_compile_budget(
        {"a": {"compile_and_warmup_s": 110.0},
         "b": {"compile_and_warmup_s": 49.0},
         "c": {"compile_and_warmup_s": 999.0}},  # no budget: skipped
        budgets)
    assert ok and rows["a"]["pass"] and rows["b"]["pass"] and "c" not in rows
    rows, ok = evaluate_compile_budget(
        {"a": {"compile_and_warmup_s": 121.0}}, budgets)
    assert not ok and not rows["a"]["pass"] and rows["a"]["ratio"] == 1.21
    # errored workload rows (no compile figure) are not regressions
    rows, ok = evaluate_compile_budget({"a": {"error": "boom"}}, budgets)
    assert ok and not rows


def test_compile_ratchet_cli_on_committed_bench():
    """The CI entry point passes on the committed BENCH_r*.json + budget."""
    import subprocess
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "compile_ratchet.py")],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


# -- opaque-kernel FLOPs lower bound (satellite) -----------------------------

def test_utilization_lower_bound_under_opaque_kernels(eight_devices):
    """A fused-kernel config's live utilization adopts the unfused twin's
    executed flops as an explicit lower bound (BENCH_r05's mfu:null fix)."""
    from homebrewnlp_tpu.train import flops as flops_mod
    from homebrewnlp_tpu.train.state import Trainer
    from .backend import text_batch

    cfg = tiny_config()
    trainer = Trainer(cfg)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    base = flops_mod.utilization_for(trainer, state, batch, 32)
    assert base.flops_per_step > 0 and not base.flops_lower_bound

    cfg_f = tiny_config(fused_mixer_block=True)  # opaque knob on (the tiny
    # shapes keep the unfused chain, so the twin's count equals the step's)
    tr_f = Trainer(cfg_f)
    tr_f.axes = trainer.axes
    from homebrewnlp_tpu.optim import Optimizer
    tr_f.optimizer = Optimizer(cfg_f, trainer.axes)
    util = flops_mod.utilization_for(tr_f, state, batch, 32)
    assert util.flops_lower_bound
    assert util.flops_per_step == base.flops_per_step
